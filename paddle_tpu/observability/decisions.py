"""Control-plane decision ledger: every autonomous action ships its
evidence, its measured outcome, and a deterministic replay.

The forensics planes (flight recorder, reqtrace, timeseries, the cost
model's audit loop) all watch the *data plane*. But the system also
ACTS on that telemetry: the elastic supervisor evicts and regrows
slots, ``decide_scale`` spawns and drains serving replicas, the fleet
sheds and hot-swaps, ``load_at_or_before(require_healthy=True)`` walks
certified rollbacks, and ``MeshPlan.auto`` picks layouts. This module
is the black box for those actions — GC3's discipline (verify control
logic as a checkable artifact, not on a burning pod) plus TVM's
measure-don't-assume loop applied to operational decisions: every
prediction ("scaling up will recover p99") is later joined against
what actually happened.

One ``DecisionRecord`` per autonomous action::

    {decision_id, ts, actor, action, rule, evidence,
     outcome: improved|neutral|worse|unjoined, joined_ts,
     outcome_evidence}

``evidence`` is the ACTUAL inputs snapshot the pure decision function
read — the verdict dict, the queue/p99/burn signals, the candidate
costs of a layout pick, the health stamps of a rollback walk — which
is what makes ``tools/incident_replay.py`` possible: feed the evidence
back through the decision logic and assert bit-identical actions.
The replay-determinism contract this imposes on actors: NO wall-clock
reads inside decision functions (they take ``now``), no RNG, no
ambient state outside the recorded snapshot.

The **outcome joiner** re-reads the same signals after a configurable
settle window and stamps each record:

  improved / worse   the comparable signals moved (beyond a relative
                     tolerance band) in / against the metric's good
                     direction — ``judge_signals`` below
  neutral            signals re-read, nothing moved beyond the band
  unjoined           the settle window expired with NO post-signal
                     (never conflated with neutral: "we don't know"
                     is a different fact from "nothing changed")

Post-signals arrive three ways: a push (``observe(actor, signals)``
from the actor's steady-state tick — the serving fleet publishes its
queue/p99 every ``_publish``), a pull (``probe=`` callable recorded
with the decision — the layout pick reads PR 18's
``planner.prediction_error`` gauge), or immediately
(``post_signals=`` — a rollback knows its outcome the moment the
restore lands). A SECOND decision by the same actor inside the settle
window force-joins the first against the second's pre-action signals
— the first action's outcome must never be judged on state the second
action already changed.

Conventions are the flight recorder's: no jax imports (the ledger
must dump while jax is wedged), one module bool gate (``_enabled``; a
disabled ``record()`` is a function call plus a bool read, <1 µs —
but unlike the data-plane rings this gate defaults ON: decisions are
cold control-plane events, and a supervisor that healed a pod at 3am
must leave the paper trail), lock-light appends (GIL-atomic deque),
and atomic per-rank JSON dumps — ``decisions_<reason>_rank<r>_pid<p>
.json`` under the same ``$PD_FR_DIR`` directory contract tpu_doctor
globs.

Always-on registry series (ride every existing exporter, the pulse
server, and the fleet rollup): ``decision.total{actor,action}``
counters and ``decision.outcome{verdict=}`` gauges.
"""
from __future__ import annotations

import itertools
import json
import os
import socket
import sys
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from . import metrics as _obs

__all__ = [
    "DecisionRecord", "OUTCOMES", "enable", "disable", "enabled",
    "reset", "record", "observe", "join_outcomes", "judge_signals",
    "records", "get", "pending_count", "outcome_counts", "dump",
    "default_dump_path", "note_bounce", "incarnation_ts", "glob_dumps",
    "LOWER_BETTER", "HIGHER_BETTER",
]

_enabled = True       # ON by default: decisions are cold control-plane
                      # events; the gate exists for test isolation and
                      # for replay (incident_replay re-runs the actors
                      # with the ledger off so a replay never records)

_CAPACITY = 4096
OUTCOMES = ("improved", "neutral", "worse", "unjoined")

# ``judge_signals`` direction metadata: which way is "better" for the
# comparable signals actors snapshot. Anything not listed is evidence,
# not a judged signal (e.g. `live`: replica count growing is the
# mechanical effect of scale_up, not proof it helped).
LOWER_BETTER = frozenset((
    "p99_ttft_ms", "queued", "queue_depth", "failures", "episode",
    "restarts", "consecutive_failures", "prediction_error",
    "step_time_s", "shed",
))
HIGHER_BETTER = frozenset((
    "productive_fraction", "goodput", "tokens_per_s", "healthy",
    "restored", "verified", "completed",
))
_REL_BAND = 0.05      # |relative move| <= band -> no vote (neutral-ish)

# signals where a negative value is a "no data yet" sentinel, not a
# measurement (the fleet's rolling p99 is -1.0 before the first
# completion) — never judge against a sentinel
_NEGATIVE_IS_MISSING = frozenset(("p99_ttft_ms",))


def _rank() -> int:
    """Best-effort rank id without touching jax (the flight recorder's
    contract: launch env first, then an already-imported runtime)."""
    for var in ("PADDLE_TRAINER_ID", "PD_RANK", "RANK"):
        v = os.environ.get(var)
        if v is not None:
            try:
                return int(v)
            except ValueError:
                pass
    jax = sys.modules.get("jax")
    if jax is not None:
        try:
            return int(jax.process_index())
        except Exception:
            pass
    return 0


def _world() -> int:
    for var in ("PADDLE_TRAINERS_NUM", "PD_WORLD", "WORLD_SIZE"):
        v = os.environ.get(var)
        if v is not None:
            try:
                return int(v)
            except ValueError:
                pass
    jax = sys.modules.get("jax")
    if jax is not None:
        try:
            return int(jax.process_count())
        except Exception:
            pass
    return 1


@dataclass
class DecisionRecord:
    """One autonomous action and, eventually, its measured outcome."""
    decision_id: str
    ts: float                  # wall clock (timeline merge / staleness)
    actor: str
    action: str
    rule: str                  # the guard/watermark that fired, human form
    evidence: dict             # the decision function's actual inputs
    outcome: str = "unjoined"
    joined_ts: Optional[float] = None
    outcome_evidence: Optional[dict] = None
    evidence_ts: Optional[float] = None   # when the evidence was OBSERVED
                                          # (tpu_doctor's staleness check)

    def as_dict(self) -> dict:
        return {
            "decision_id": self.decision_id, "ts": self.ts,
            "actor": self.actor, "action": self.action,
            "rule": self.rule, "evidence": self.evidence,
            "outcome": self.outcome, "joined_ts": self.joined_ts,
            "outcome_evidence": self.outcome_evidence,
            "evidence_ts": self.evidence_ts,
        }


class _Pending:
    __slots__ = ("rec", "clock", "deadline", "signals", "probe", "judge")

    def __init__(self, rec, clock, deadline, signals, probe, judge):
        self.rec = rec
        self.clock = clock
        self.deadline = deadline
        self.signals = signals
        self.probe = probe
        self.judge = judge


_records: deque = deque(maxlen=_CAPACITY)
_pending: List[_Pending] = []
_observations: Dict[str, Tuple[float, dict]] = {}
_counter = itertools.count()
_outcome_counts: Dict[str, int] = {}
_born_ts = time.time()
_incarnation_ts = _born_ts     # bumped by note_bounce(): decisions made
                               # AFTER a bounce on evidence observed
                               # BEFORE it are acted-on-stale-evidence


def enable(on: bool = True) -> bool:
    global _enabled
    _enabled = bool(on)
    return _enabled


def disable() -> bool:
    return enable(False)


def enabled() -> bool:
    return _enabled


def reset():
    """Drop all ledger state (test isolation). Re-arms the gate and
    resets the incarnation clock to now."""
    global _enabled, _incarnation_ts
    _records.clear()
    _pending.clear()
    _observations.clear()
    _outcome_counts.clear()
    _enabled = True
    _incarnation_ts = time.time()


def note_bounce(ts: Optional[float] = None):
    """Mark a gang bounce / incarnation boundary. Evidence observed
    before this instant is STALE for any decision made after it —
    tpu_doctor flags those (the PR 8(i) failure class: acting on a
    previous incarnation's dumps)."""
    global _incarnation_ts
    _incarnation_ts = time.time() if ts is None else float(ts)


def incarnation_ts() -> float:
    return _incarnation_ts


# -- the judge ---------------------------------------------------------------

def judge_signals(pre: dict, post: dict) -> str:
    """Generic outcome verdict from the signals the actor snapshotted
    at decision time vs the same keys re-read after the settle window.
    Each comparable key votes by its direction metadata; moves inside
    the ±5% relative band don't vote. Net votes > 0 → improved,
    < 0 → worse, 0 → neutral. Keys with no direction metadata, missing
    on either side, non-numeric, or sitting at a no-data sentinel are
    skipped — an outcome is judged only on real, shared measurements."""
    score = 0
    for k in set(pre) & set(post):
        if k in LOWER_BETTER:
            sign = -1.0
        elif k in HIGHER_BETTER:
            sign = 1.0
        else:
            continue
        a, b = pre[k], post[k]
        if isinstance(a, bool):
            a = int(a)
        if isinstance(b, bool):
            b = int(b)
        if not isinstance(a, (int, float)) or not isinstance(
                b, (int, float)):
            continue
        if k in _NEGATIVE_IS_MISSING and (a < 0 or b < 0):
            continue
        base = max(abs(a), abs(b))
        if base == 0:
            continue
        delta = (b - a) / base
        if abs(delta) <= _REL_BAND:
            continue
        score += 1 if sign * delta > 0 else -1
    if score > 0:
        return "improved"
    if score < 0:
        return "worse"
    return "neutral"


def _publish_outcome(outcome: str):
    _outcome_counts[outcome] = _outcome_counts.get(outcome, 0) + 1
    # publish ALL taxonomy members every time so the exposition is
    # stable (byte-parity between the file export and a pulse scrape
    # must not depend on which verdicts happened to occur first)
    for v in OUTCOMES:
        _obs.gauge("decision.outcome", _always=True,
                   verdict=v).set(_outcome_counts.get(v, 0))


def _join(entry: _Pending, post: Optional[dict] = None):
    """Close one pending record: judge against `post` when provided,
    else the newest observation strictly after the decision, else the
    recorded probe; no post-signal at all stamps `unjoined` — NEVER
    neutral."""
    try:
        _pending.remove(entry)
    except ValueError:
        return
    rec = entry.rec
    if post is None:
        obs = _observations.get(rec.actor)
        if obs is not None and obs[0] > entry.clock:
            post = obs[1]
    if post is None and entry.probe is not None:
        try:
            post = entry.probe()
        except Exception:
            post = None
    if post is None:
        rec.outcome = "unjoined"
        rec.outcome_evidence = {"pre": entry.signals, "post": None}
    else:
        post = dict(post)
        judge = entry.judge or judge_signals
        try:
            verdict = judge(entry.signals, post)
        except Exception:
            verdict = "unjoined"
        rec.outcome = verdict if verdict in OUTCOMES else "unjoined"
        rec.outcome_evidence = {"pre": entry.signals, "post": post}
    rec.joined_ts = time.time()
    _publish_outcome(rec.outcome)


# -- the ledger --------------------------------------------------------------

def record(actor: str, action: str, rule: str, evidence: dict, *,
           signals: Optional[dict] = None, settle_s: float = 0.0,
           probe: Optional[Callable[[], Optional[dict]]] = None,
           judge: Optional[Callable[[dict, dict], str]] = None,
           post_signals: Optional[dict] = None,
           clock: Optional[float] = None,
           evidence_ts: Optional[float] = None) -> Optional[str]:
    """Append one DecisionRecord; returns its decision_id (None when
    the ledger is disabled — callers stamp it into their receipts
    as-is).

    `signals` is the comparable sub-snapshot of `evidence` the joiner
    will re-read (queue/p99, goodput, failure counts). `clock` is the
    decision function's OWN clock value (`now` — time.monotonic
    family); the settle deadline lives on that clock so injected-clock
    tests stay deterministic, while `ts` is always wall time for
    timeline merges. `post_signals` joins immediately (the actor knew
    the outcome at decision time, e.g. a rollback that just restored).
    """
    if not _enabled:
        return None
    clk = time.monotonic() if clock is None else float(clock)
    # a second decision by the same actor inside a pending settle
    # window closes the first AGAINST THIS DECISION'S PRE-ACTION
    # SIGNALS — never against state the new action will change
    for p in [p for p in _pending if p.rec.actor == actor]:
        _join(p, post=(dict(signals) if signals else None))
    rec = DecisionRecord(
        decision_id=f"d{_rank()}-{os.getpid()}-{next(_counter)}",
        ts=time.time(), actor=str(actor), action=str(action),
        rule=str(rule), evidence=evidence, evidence_ts=evidence_ts)
    _records.append(rec)
    _obs.counter("decision.total", _always=True, actor=rec.actor,
                 action=rec.action).add(1)
    entry = _Pending(rec, clk, clk + float(settle_s),
                     dict(signals or {}), probe, judge)
    if post_signals is not None:
        _pending.append(entry)
        _join(entry, post=dict(post_signals))
    else:
        _pending.append(entry)
    return rec.decision_id


def observe(actor: str, signals: dict, clock: Optional[float] = None):
    """Push the actor's current steady-state signals (the serving
    fleet's per-tick queue/p99, the supervisor's healthy-poll state).
    The joiner uses the newest observation strictly after a decision
    as its post-signals. No-op when disabled."""
    if not _enabled:
        return
    clk = time.monotonic() if clock is None else float(clock)
    _observations[str(actor)] = (clk, dict(signals))


def join_outcomes(now: Optional[float] = None,
                  force: bool = False) -> int:
    """Walk pending records whose settle window expired (all of them
    when `force` — drills and supervisor exit close the books) and
    stamp outcomes. Returns the number joined."""
    clk = time.monotonic() if now is None else float(now)
    joined = 0
    for entry in list(_pending):
        if force or clk >= entry.deadline:
            _join(entry)
            joined += 1
    return joined


def records(actor: Optional[str] = None) -> List[DecisionRecord]:
    out = list(_records)
    if actor is not None:
        out = [r for r in out if r.actor == actor]
    return out


def get(decision_id: str) -> Optional[DecisionRecord]:
    for r in _records:
        if r.decision_id == decision_id:
            return r
    return None


def pending_count() -> int:
    return len(_pending)


def outcome_counts() -> Dict[str, int]:
    return {v: _outcome_counts.get(v, 0) for v in OUTCOMES}


# -- dump --------------------------------------------------------------------

def default_dump_path(reason: str = "manual",
                      dump_dir: Optional[str] = None) -> str:
    """`decisions_<reason>_rank<r>_pid<p>.json` under the flight
    recorder's directory contract ($PD_FR_DIR unless overridden) — a
    later routine dump never clobbers another reason's or process's
    evidence."""
    d = dump_dir or os.environ.get("PD_FR_DIR", "/tmp/pd_flight")
    safe = "".join(c if c.isalnum() or c in "_.-" else "_"
                   for c in reason) or "manual"
    return os.path.join(
        d, f"decisions_{safe}_rank{_rank()}_pid{os.getpid()}.json")


def dump(path: Optional[str] = None, reason: str = "manual",
         out_dir: Optional[str] = None,
         extra: Optional[dict] = None) -> dict:
    """Write the ledger to JSON and return the doc. Works even when
    disabled (dumps whatever the ring holds) — the paper trail must
    never refuse to be written."""
    doc: Dict[str, Any] = {
        "version": 1,
        "reason": reason,
        "ts": time.time(),
        "host": socket.gethostname(),
        "pid": os.getpid(),
        "rank": _rank(),
        "world": _world(),
        "enabled": _enabled,
        "born_ts": _born_ts,
        "incarnation_ts": _incarnation_ts,
        "records": [r.as_dict() for r in _records],
        "pending": [p.rec.decision_id for p in _pending],
        "outcomes": outcome_counts(),
    }
    if extra:
        doc.update(extra)
    if path is None:
        path = default_dump_path(reason, dump_dir=out_dir)
    try:
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(doc, f, default=str)
        os.replace(tmp, path)
        doc["path"] = path
    except OSError:
        doc["path"] = None  # evidence still returned to the caller
    return doc


def glob_dumps(dump_dir: str) -> List[str]:
    import glob as _glob
    return sorted(_glob.glob(os.path.join(dump_dir,
                                          "decisions_*.json")))
