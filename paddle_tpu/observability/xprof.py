"""XPlane / trace.json device-time attribution: the measured tier.

``anatomy`` prices the program statically (FLOPs shares from the HLO);
this module reads what the chip actually DID: the XPlane protobuf a
``jax.profiler.trace`` capture writes (or its chrome-trace twin), maps
kernel names back to the anatomy scope taxonomy, and produces

  - per-scope device milliseconds (which component the step really
    spends time on — the in-situ counterpart of
    tools/tpu_breakdown.py's isolated numbers),
  - step-gap / idle time (device span minus the union of kernel
    intervals: dispatch stalls, host-bound gaps),
  - the **comm-overlap receipt**: of the device time spent in
    collectives (fused grad-sync buckets included — their HLO names
    carry the ``grad_sync`` scope), how much ran CONCURRENTLY with
    compute on the same device vs exposed on the critical path.
    ``overlap_fraction = hidden_ms / comm_ms`` is published as the
    ``comm.overlap_fraction`` gauge through the PR 3 exporters and
    ``fleet.aggregate()`` — the receipt ROADMAP 3(d) needs to decide
    whether bucketed grad sync actually overlaps backward.

One parser, one glob contract: ``find_xplane`` owns the
``**/*.xplane.pb`` discovery every consumer previously inlined
(tools/tpu_first_light.py's PROFILE_SNIPPET now routes here, like
PR 4 unified dump paths through ``flight_recorder.default_dump_path``).
Inputs accepted: a profiler logdir, a ``.xplane.pb`` file (parsed via
``jax.profiler.ProfileData`` when this runtime ships it), or a chrome
``trace.json``/``trace.json.gz`` — the format the recorded-trace
tier-1 fixture uses, so the whole attribution path is testable on CPU
with no hardware and no ProfileData dependency.

This module imports jax only inside the XPlane loader — the trace.json
path and the overlap math must work on a triage host (same discipline
as flight_recorder).
"""
from __future__ import annotations

import glob as _glob
import gzip
import json
import os
from typing import Dict, Iterable, List, Optional, Tuple

from . import anatomy, metrics

__all__ = [
    "find_xplane", "load_profile", "is_comm_kernel", "scope_of_event",
    "attribute_device_time", "overlap_receipt", "publish", "top_ops",
    "format_top_ops",
]

# substrings that mark a device event as collective communication
# (XLA kernel spellings + our fused grad-sync op labels)
COMM_TOKENS = (
    "all-reduce", "all_reduce", "allreduce", "all-gather", "all_gather",
    "allgather", "reduce-scatter", "reduce_scatter", "all-to-all",
    "alltoall", "collective-permute", "collective_permute", "ppermute",
    "fused_allreduce", "psum", "collective",
)

# stat/arg keys that may carry the HLO metadata path for an event
_ARG_KEYS = ("tf_op", "hlo_op", "long_name", "name", "op_name",
             "kernel_details")

_DEVICE_PLANE_TOKENS = ("/device:", "tpu", "gpu", "accelerator")

# aggregate/marker LANES inside a device plane whose events span whole
# steps or modules rather than individual kernels ("XLA Modules" holds
# one jit_step-sized event; "Steps" holds step markers). Left in, they
# sit in the compute union and saturate the overlap receipt at ~1.0 and
# zero the idle figure on every real capture — exactly the numbers this
# parser exists to measure. Matched case-insensitively on the lane name.
_AGGREGATE_LINE_TOKENS = ("xla modules", "module", "steps", "step",
                          "framework", "source", "xla traceme",
                          "scope range")


def _is_aggregate_line(line_name: str) -> bool:
    ln = (line_name or "").lower()
    return any(tok in ln for tok in _AGGREGATE_LINE_TOKENS)


def find_xplane(logdir: str) -> Optional[str]:
    """THE ``**/*.xplane.pb`` glob contract (newest capture wins), for
    every consumer that lets jax.profiler.trace pick the subdirectory."""
    hits = _glob.glob(os.path.join(logdir, "**", "*.xplane.pb"),
                      recursive=True)
    if not hits:
        return None
    return max(hits, key=os.path.getmtime)


# ---------------------------------------------------------------------------
# loading: XPlane pb / chrome trace.json -> normalized event dicts
# ---------------------------------------------------------------------------
# Event: {"device": plane/process name, "line": lane name, "name": str,
#         "ts": start µs, "dur": duration µs, "args": {str: str}}

def _load_xplane(path: str) -> List[dict]:
    try:
        from jax.profiler import ProfileData
    except ImportError as e:  # pragma: no cover — runtime-dependent
        raise RuntimeError(
            "this jax runtime has no jax.profiler.ProfileData; convert "
            "the capture to trace.json (TensorBoard writes one next to "
            "the xplane.pb) and pass that instead") from e
    pd = ProfileData.from_serialized_xspace(open(path, "rb").read())
    events: List[dict] = []
    for plane in pd.planes:
        pname = plane.name
        if not any(t in pname.lower() for t in _DEVICE_PLANE_TOKENS):
            continue
        for line in plane.lines:
            lname = getattr(line, "name", "")
            if _is_aggregate_line(lname):
                continue
            for ev in line.events:
                # event stats carry the HLO metadata (tf_op/long_name)
                # on real captures; the API has shipped both (name,
                # value) pairs and XStat-like objects — best-effort
                # either way, the kernel name alone still attributes
                args = {}
                try:
                    for stat in getattr(ev, "stats", ()) or ():
                        if isinstance(stat, (tuple, list)) \
                                and len(stat) == 2:
                            args[str(stat[0])] = str(stat[1])
                        else:
                            name = getattr(stat, "name", None)
                            if name is not None:
                                args[str(name)] = str(
                                    getattr(stat, "value", ""))
                except Exception:
                    pass
                events.append({
                    "device": pname, "line": lname, "name": ev.name,
                    "ts": ev.start_ns / 1e3,
                    "dur": ev.duration_ns / 1e3, "args": args})
    return events


def _load_trace_json(path: str) -> List[dict]:
    op = gzip.open if path.endswith(".gz") else open
    with op(path, "rt") as f:
        doc = json.load(f)
    raw = doc.get("traceEvents", doc if isinstance(doc, list) else [])
    pid_names: Dict[int, str] = {}
    tid_names: Dict[Tuple[int, int], str] = {}
    for ev in raw:
        if ev.get("ph") == "M":
            nm = (ev.get("args") or {}).get("name", "")
            if ev.get("name") == "process_name":
                pid_names[ev.get("pid")] = nm
            elif ev.get("name") == "thread_name":
                tid_names[(ev.get("pid"), ev.get("tid"))] = nm
    device_pids = {p for p, n in pid_names.items()
                   if any(t in n.lower() for t in _DEVICE_PLANE_TOKENS)}
    events: List[dict] = []
    for ev in raw:
        if ev.get("ph") != "X":
            continue
        pid = ev.get("pid")
        if device_pids and pid not in device_pids:
            continue
        lname = tid_names.get((pid, ev.get("tid")),
                              str(ev.get("tid")))
        if _is_aggregate_line(lname):
            continue
        events.append({
            "device": pid_names.get(pid, str(pid)),
            "line": lname,
            "name": ev.get("name", ""),
            "ts": float(ev.get("ts", 0.0)),
            "dur": float(ev.get("dur", 0.0)),
            "args": {k: str(v) for k, v in
                     (ev.get("args") or {}).items()}})
    return events


def load_profile(path: str) -> List[dict]:
    """Normalize a capture into device-event dicts. Accepts a profiler
    logdir (xplane.pb preferred, trace.json fallback), an .xplane.pb
    file, or a chrome trace.json(.gz)."""
    if os.path.isdir(path):
        xp = find_xplane(path)
        if xp is not None:
            return _load_xplane(xp)
        js = sorted(
            _glob.glob(os.path.join(path, "**", "*trace.json*"),
                       recursive=True), key=os.path.getmtime)
        if js:
            return _load_trace_json(js[-1])
        raise FileNotFoundError(
            f"no *.xplane.pb or *trace.json* under {path!r}")
    if path.endswith(".pb"):
        return _load_xplane(path)
    return _load_trace_json(path)


# ---------------------------------------------------------------------------
# classification
# ---------------------------------------------------------------------------

def is_comm_kernel(name: str, args: Optional[dict] = None) -> bool:
    hay = name.lower()
    if args:
        hay += " " + " ".join(str(v).lower() for v in args.values())
    return any(tok in hay for tok in COMM_TOKENS)


def scope_of_event(ev: dict,
                   scopes: Optional[Iterable[str]] = None
                   ) -> Optional[str]:
    """Map one device event to the anatomy taxonomy: HLO metadata paths
    in the event args first (tf_op/long_name carry the op_name the
    scopes lowered into), then the kernel name's own tokens."""
    args = ev.get("args") or {}
    for k in _ARG_KEYS:
        v = args.get(k)
        if v:
            sc = anatomy.scope_of_op_name(str(v), scopes)
            if sc is not None:
                return sc
    return anatomy.scope_of_op_name(
        ev.get("name", "").replace(".", "/"), scopes)


# ---------------------------------------------------------------------------
# interval math
# ---------------------------------------------------------------------------

def _merge(intervals: List[Tuple[float, float]]
           ) -> List[Tuple[float, float]]:
    out: List[Tuple[float, float]] = []
    for s, e in sorted(intervals):
        if out and s <= out[-1][1]:
            if e > out[-1][1]:
                out[-1] = (out[-1][0], e)
        else:
            out.append((s, e))
    return out


def _union_len(intervals: List[Tuple[float, float]]) -> float:
    return sum(e - s for s, e in _merge(intervals))


def _overlap_with(iv: Tuple[float, float],
                  merged: List[Tuple[float, float]]) -> float:
    s, e = iv
    got = 0.0
    for ms, me in merged:
        if me <= s:
            continue
        if ms >= e:
            break
        got += min(e, me) - max(s, ms)
    return got


# ---------------------------------------------------------------------------
# attribution + the overlap receipt
# ---------------------------------------------------------------------------

def overlap_receipt(events: List[dict]) -> dict:
    """Per-device: comm intervals vs the union of concurrent compute
    intervals on the SAME device (other lanes or async-pair gaps).
    hidden = comm time with compute in flight; exposed = the rest —
    the part of grad sync the step actually waits for."""
    comm_ms = hidden_ms = 0.0
    by_dev: Dict[str, List[dict]] = {}
    for ev in events:
        by_dev.setdefault(ev["device"], []).append(ev)
    for evs in by_dev.values():
        compute = _merge([(e["ts"], e["ts"] + e["dur"]) for e in evs
                          if not is_comm_kernel(e["name"], e["args"])])
        for e in evs:
            if not is_comm_kernel(e["name"], e["args"]):
                continue
            iv = (e["ts"], e["ts"] + e["dur"])
            comm_ms += e["dur"] / 1e3
            hidden_ms += _overlap_with(iv, compute) / 1e3
    exposed = comm_ms - hidden_ms
    return {
        "comm_ms": round(comm_ms, 6),
        "hidden_ms": round(hidden_ms, 6),
        "exposed_ms": round(exposed, 6),
        "overlap_fraction": (round(hidden_ms / comm_ms, 6)
                             if comm_ms > 0 else -1.0),
    }


def attribute_device_time(events: List[dict],
                          scopes: Optional[Iterable[str]] = None,
                          steps: int = 1) -> dict:
    """The device-time anatomy: per-scope ms (comm events land on their
    HLO scope when one is named, else the ``comm`` row), busy/idle
    split, and the comm-overlap receipt. ``steps`` divides the *_per_step
    figures for multi-step captures."""
    steps = max(int(steps), 1)
    per: Dict[str, float] = {}
    span_ms = busy_ms = 0.0
    by_dev: Dict[str, List[Tuple[float, float]]] = {}
    for ev in events:
        sc = scope_of_event(ev, scopes)
        if sc is None:
            sc = "comm" if is_comm_kernel(ev["name"], ev["args"]) \
                else "unattributed"
        per[sc] = per.get(sc, 0.0) + ev["dur"] / 1e3
        by_dev.setdefault(ev["device"], []).append(
            (ev["ts"], ev["ts"] + ev["dur"]))
    for ivs in by_dev.values():
        busy_ms += _union_len(ivs) / 1e3
        span_ms += (max(e for _, e in ivs) - min(s for s, _ in ivs)) / 1e3
    total = sum(per.values())
    comm = overlap_receipt(events)
    return {
        "per_scope_ms": {k: round(v / steps, 6) for k, v in
                         sorted(per.items(), key=lambda kv: -kv[1])},
        "per_scope_share": {k: round(v / total, 6) if total else 0.0
                            for k, v in per.items()},
        "device_busy_ms": round(busy_ms / steps, 6),
        "device_span_ms": round(span_ms / steps, 6),
        "idle_ms": round(max(span_ms - busy_ms, 0.0) / steps, 6),
        "comm": comm,
        "devices": len(by_dev),
        "events": len(events),
        "steps": steps,
    }


def publish(result: dict, prefix: str = "anatomy"):
    """Gauges for the measured tier — always-on, same contract as
    anatomy.publish: ``comm.overlap_fraction`` is THE ROADMAP 3(d)
    receipt and must ride every exporter and fleet.aggregate() even
    when the hot-path metrics gate is down."""
    comm = result.get("comm", {})
    metrics.gauge("comm.overlap_fraction", _always=True).set(
        comm.get("overlap_fraction", -1.0))
    metrics.gauge("comm.exposed_ms", _always=True).set(
        comm.get("exposed_ms", -1.0))
    metrics.gauge("comm.device_ms", _always=True).set(
        comm.get("comm_ms", -1.0))
    for name, ms in result.get("per_scope_ms", {}).items():
        metrics.gauge(f"{prefix}.device_ms", _always=True,
                      scope=name).set(ms)
    metrics.gauge(f"{prefix}.idle_ms", _always=True).set(
        result.get("idle_ms", -1.0))
    return result


# ---------------------------------------------------------------------------
# the first-light top-list (supersedes the inline one-off)
# ---------------------------------------------------------------------------

def top_ops(events: List[dict], n: int = 15,
            steps: int = 1) -> List[Tuple[str, float]]:
    """Heaviest device ops as (name, ms/step) — what
    tools/tpu_first_light.py's PROFILE_SNIPPET used to compute inline
    from raw ProfileData planes."""
    steps = max(int(steps), 1)
    tot: Dict[str, float] = {}
    for ev in events:
        tot[ev["name"]] = tot.get(ev["name"], 0.0) + ev["dur"]
    ranked = sorted(tot.items(), key=lambda kv: -kv[1])[:n]
    return [(name, us / 1e3 / steps) for name, us in ranked]


def format_top_ops(events: List[dict], n: int = 15,
                   steps: int = 1) -> str:
    lines = [f"top device ops over {steps} steps:"]
    for name, ms in top_ops(events, n=n, steps=steps):
        lines.append(f"  {ms:9.2f} ms/step  {name[:90]}")
    return "\n".join(lines)
