"""StatRegistry metrics runtime: counters / gauges / histograms.

Reference: platform/monitor.h:44 (StatValue<T> registry, the STAT_ADD /
STAT_INT macros, ExportedStatValue dump). The reference's design point —
a named registry whose hot-path increment is cheap enough to leave in
production dispatch code — is kept, with two TPU-era upgrades:

- a module-level enable gate (`_enabled`, one bool read) so a counter
  increment in a disabled build costs a function call and nothing else
  (the eager-dispatch hot path wires counters unconditionally and relies
  on this being ~sub-microsecond);
- thread-sharded counter cells (each thread increments its own cell, no
  lock, no contention; `value()` sums the shards) — the "lock-free-ish"
  promise monitor.h makes with std::atomic, delivered per-thread here
  because CPython has no cheap atomics.

Instrument kinds:
  Counter    monotonic, thread-sharded add()
  Gauge      last-write-wins set() (+ add() for monitor.h parity);
             values may be non-numeric (exporters skip those for
             Prometheus, keep them for JSONL)
  Histogram  thread-sharded count/sum/min/max plus a bounded,
             deterministically-decimated reservoir for percentiles

Naming scheme (DESIGN.md "Observability"): dot-separated
`<subsystem>.<metric>` with optional labels, e.g.
``counter("op.dispatch.total", op="matmul")``. The snapshot key renders
as ``op.dispatch.total{op=matmul}``. The one deliberately
Prometheus-flat name is ``train_recompiles_total`` (the recompile
sentinel's contract counter — grep-able across exporters unchanged).

Instruments created with ``always=True`` ignore the enable gate
(core.monitor's explicit stat() API and the recompile sentinel: both are
opted into by the caller, not blanket-wired into hot paths).
"""
from __future__ import annotations

import threading
from contextlib import contextmanager
from typing import Any, Dict, List, Optional, Tuple

__all__ = [
    "Counter", "Gauge", "Histogram", "counter", "gauge", "histogram",
    "enable", "disable", "enabled", "enabled_scope", "snapshot",
    "reset", "clear", "registry_size", "get",
]

_enabled = False          # the one-bool hot-path gate
_reg_lock = threading.Lock()
_REGISTRY: Dict[Tuple[str, Tuple[Tuple[str, str], ...]], "_Instrument"] = {}

_RESERVOIR_CAP = 2048


def enable(on: bool = True):
    """Turn the wired hot-path instruments on (off by default: the
    framework never pays for telemetry nobody reads)."""
    global _enabled
    _enabled = bool(on)
    return _enabled


def disable():
    return enable(False)


def enabled() -> bool:
    return _enabled


@contextmanager
def enabled_scope(on: bool = True):
    global _enabled
    prev = _enabled
    _enabled = bool(on)
    try:
        yield
    finally:
        _enabled = prev


def _label_key(labels: Dict[str, Any]) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class _Instrument:
    kind = "?"

    def __init__(self, name: str, labels: Tuple[Tuple[str, str], ...],
                 always: bool = False):
        self.name = name
        self.labels = labels
        self.always = always

    @property
    def full_name(self) -> str:
        if not self.labels:
            return self.name
        # label VALUES may contain the rendering's own separators (an
        # HLO op path with commas); escape them so exporters._split_key
        # can split unambiguously. Keys are python identifiers (kwarg
        # names) and '=' only separates at the FIRST occurrence per
        # pair, so ',' and '\' are the only characters needing escape.
        esc = lambda v: v.replace("\\", "\\\\").replace(",", "\\,")
        lbl = ",".join(f"{k}={esc(v)}" for k, v in self.labels)
        return f"{self.name}{{{lbl}}}"

    def _on(self) -> bool:
        return _enabled or self.always


class _Cell:
    __slots__ = ("v",)

    def __init__(self):
        self.v = 0


class Counter(_Instrument):
    """Monotonic counter (StatValue<int64_t> + STAT_ADD analogue).
    Thread-sharded: add() touches only this thread's cell."""

    kind = "counter"

    def __init__(self, name, labels=(), always=False):
        super().__init__(name, labels, always)
        self._tls = threading.local()
        self._cells: List[_Cell] = []
        self._cells_lock = threading.Lock()

    def _cell(self) -> _Cell:
        c = getattr(self._tls, "cell", None)
        if c is None:
            c = _Cell()
            self._tls.cell = c
            with self._cells_lock:
                self._cells.append(c)
        return c

    def add(self, n=1):
        if not (_enabled or self.always):
            return self
        self._cell().v += n
        return self

    inc = add

    def value(self):
        with self._cells_lock:
            return sum(c.v for c in self._cells)

    def reset(self):
        with self._cells_lock:
            for c in self._cells:
                c.v = 0

    def dump(self) -> dict:
        return {"type": "counter", "value": self.value()}


class Gauge(_Instrument):
    """Last-write-wins value. add() keeps monitor.h's `stat += v`
    surface (core.monitor routes through this)."""

    kind = "gauge"

    def __init__(self, name, labels=(), always=False):
        super().__init__(name, labels, always)
        self._value: Any = 0
        self._lock = threading.Lock()

    def set(self, v):
        if not (_enabled or self.always):
            return self
        self._value = v
        return self

    def add(self, v=1):
        if not (_enabled or self.always):
            return self
        with self._lock:
            self._value += v
        return self

    def value(self):
        return self._value

    get = value

    def reset(self):
        self._value = 0

    def dump(self) -> dict:
        return {"type": "gauge", "value": self._value}


class _HistCell:
    __slots__ = ("count", "sum", "min", "max", "res", "stride", "skip")

    def __init__(self):
        self.count = 0
        self.sum = 0.0
        self.min = float("inf")
        self.max = float("-inf")
        self.res: List[float] = []
        # deterministic decimation: when the reservoir fills, keep every
        # other sample and double the admission stride — bounded memory,
        # no RNG (reproducible percentiles for tests)
        self.stride = 1
        self.skip = 0


class Histogram(_Instrument):
    """Distribution instrument: count/sum/min/max plus a bounded
    reservoir for p50/p99 (the StepClock percentile contract, resident
    in the registry instead of a loop-local list)."""

    kind = "histogram"

    def __init__(self, name, labels=(), always=False):
        super().__init__(name, labels, always)
        self._tls = threading.local()
        self._cells: List[_HistCell] = []
        self._cells_lock = threading.Lock()

    def _cell(self) -> _HistCell:
        c = getattr(self._tls, "cell", None)
        if c is None:
            c = _HistCell()
            self._tls.cell = c
            with self._cells_lock:
                self._cells.append(c)
        return c

    def observe(self, v):
        if not (_enabled or self.always):
            return self
        c = self._cell()
        v = float(v)
        c.count += 1
        c.sum += v
        if v < c.min:
            c.min = v
        if v > c.max:
            c.max = v
        c.skip += 1
        if c.skip >= c.stride:
            c.skip = 0
            c.res.append(v)
            if len(c.res) >= _RESERVOIR_CAP:
                c.res = c.res[::2]
                c.stride *= 2
        return self

    def observe_many(self, vs):
        for v in vs:
            self.observe(v)
        return self

    def _merged(self):
        with self._cells_lock:
            cells = list(self._cells)
        count = sum(c.count for c in cells)
        total = sum(c.sum for c in cells)
        mn = min((c.min for c in cells if c.count), default=float("inf"))
        mx = max((c.max for c in cells if c.count), default=float("-inf"))
        res: List[float] = []
        for c in cells:
            res.extend(c.res)
        return count, total, mn, mx, sorted(res)

    def percentile(self, q: float) -> float:
        _, _, _, _, res = self._merged()
        if not res:
            return -1.0
        idx = min(len(res) - 1,
                  max(0, int(round(q / 100.0 * (len(res) - 1)))))
        return res[idx]

    def count(self) -> int:
        return self._merged()[0]

    def reset(self):
        with self._cells_lock:
            for c in self._cells:
                c.__init__()

    def dump(self) -> dict:
        count, total, mn, mx, res = self._merged()
        out = {"type": "histogram", "count": count,
               "sum": round(total, 6)}
        if count:
            out["min"] = round(mn, 6)
            out["max"] = round(mx, 6)
            for q, k in ((50.0, "p50"), (99.0, "p99")):
                idx = min(len(res) - 1,
                          max(0, int(round(q / 100.0 * (len(res) - 1)))))
                out[k] = round(res[idx], 6)
        return out


_KIND = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}


def _get_or_create(kind: str, name: str, labels: Dict[str, Any],
                   always: bool):
    key = (name, _label_key(labels))
    inst = _REGISTRY.get(key)
    if inst is None:
        with _reg_lock:
            inst = _REGISTRY.get(key)
            if inst is None:
                inst = _KIND[kind](name, key[1], always=always)
                _REGISTRY[key] = inst
    if inst.kind != kind:
        raise TypeError(
            f"metric '{inst.full_name}' already registered as "
            f"{inst.kind}, requested {kind}")
    if always and not inst.always:
        inst.always = True
    return inst


def counter(name: str, _always: bool = False, **labels) -> Counter:
    """Get-or-create the named counter (STAT_INT registration)."""
    return _get_or_create("counter", name, labels, _always)


def gauge(name: str, _always: bool = False, **labels) -> Gauge:
    return _get_or_create("gauge", name, labels, _always)


def histogram(name: str, _always: bool = False, **labels) -> Histogram:
    return _get_or_create("histogram", name, labels, _always)


def get(name: str, **labels) -> Optional[_Instrument]:
    return _REGISTRY.get((name, _label_key(labels)))


def snapshot(prefix: Optional[str] = None) -> Dict[str, dict]:
    """ExportedStatValue dump: full_name -> typed value dict. The
    transport format every exporter (Prometheus/JSONL/chrome-trace) and
    the fleet aggregator consume."""
    out = {}
    with _reg_lock:
        insts = list(_REGISTRY.values())
    for inst in insts:
        if prefix is not None and not inst.name.startswith(prefix):
            continue
        out[inst.full_name] = inst.dump()
    return dict(sorted(out.items()))


def reset(prefix: Optional[str] = None):
    """Zero instrument values (registry membership is kept)."""
    with _reg_lock:
        insts = list(_REGISTRY.values())
    for inst in insts:
        if prefix is None or inst.name.startswith(prefix):
            inst.reset()


def clear():
    """Drop every instrument (test isolation; production code should
    prefer reset())."""
    with _reg_lock:
        _REGISTRY.clear()


def registry_size() -> int:
    return len(_REGISTRY)
