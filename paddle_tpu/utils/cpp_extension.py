"""Custom C++ op extension: JIT-compile + register out-of-tree ops.

Reference: paddle/fluid/extension/ (ext_op_meta_info.h custom-op C++ API,
framework/custom_operator.cc registration) and
python/paddle/utils/cpp_extension/ (load(), CppExtension/CUDAExtension).

TPU design: user C++ cannot run on the TPU core — the reference's custom
CUDA kernels map to two TPU-native paths: (a) host-callback kernels (this
module: g++-compiled shared library driven through jax.pure_callback, with
forward/backward symbols wired into the op registry + autograd), which is
the analogue of the reference's custom *CPU* kernels; (b) on-chip custom
kernels, whose TPU path is Pallas (see paddle_tpu.ops.pallas_kernels) —
write those in Python, not C++.

Exported-symbol protocol (the ext_op_meta_info analogue, C ABI):
    extern "C" void pd_<op>_forward(const float* x, float* y, int64_t n);
    extern "C" void pd_<op>_backward(const float* x, const float* gy,
                                     float* gx, int64_t n);   // optional
Elementwise float32 contract keeps the ABI trivial; richer signatures
belong in Pallas.
"""
from __future__ import annotations

import ctypes
import functools
import hashlib
import os
import subprocess
import tempfile
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["load", "CppExtension", "CUDAExtension", "setup",
           "get_build_directory"]


def get_build_directory():
    d = os.environ.get("PADDLE_EXTENSION_DIR") or os.path.join(
        tempfile.gettempdir(), "paddle_tpu_extensions")
    os.makedirs(d, exist_ok=True)
    return d


def _compile(name: str, sources: Sequence[str], extra_cflags, build_dir,
             verbose: bool) -> str:
    src_key = hashlib.sha1()
    for s in sources:
        with open(s, "rb") as f:
            src_key.update(f.read())
    so_path = os.path.join(build_dir, f"{name}_{src_key.hexdigest()[:12]}.so")
    if os.path.exists(so_path):
        return so_path
    cmd = ["g++", "-O2", "-shared", "-fPIC", "-std=c++17",
           *(extra_cflags or []), *sources, "-o", so_path]
    if verbose:
        print("[cpp_extension]", " ".join(cmd))
    res = subprocess.run(cmd, capture_output=True, text=True)
    if res.returncode != 0:
        raise RuntimeError(f"g++ failed for extension '{name}':\n"
                           f"{res.stderr}")
    return so_path


class _LoadedExtension:
    """Module-like holder: each discovered op becomes an attribute."""

    def __init__(self, name):
        self._name = name
        self._ops = {}

    def __getattr__(self, item):
        try:
            return self.__dict__["_ops"][item]
        except KeyError:
            raise AttributeError(
                f"extension '{self._name}' has no op '{item}'; "
                f"available: {list(self.__dict__['_ops'])}")


def _make_op(lib, op_name: str, has_backward: bool):
    fwd_sym = getattr(lib, f"pd_{op_name}_forward")
    fwd_sym.restype = None
    fwd_sym.argtypes = [ctypes.POINTER(ctypes.c_float),
                        ctypes.POINTER(ctypes.c_float), ctypes.c_int64]
    bwd_sym = None
    if has_backward:
        bwd_sym = getattr(lib, f"pd_{op_name}_backward")
        bwd_sym.restype = None
        bwd_sym.argtypes = [ctypes.POINTER(ctypes.c_float)] * 3 + [
            ctypes.c_int64]

    def host_fwd(x: np.ndarray) -> np.ndarray:
        x = np.ascontiguousarray(x, np.float32)
        y = np.empty_like(x)
        fwd_sym(x.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                y.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), x.size)
        return y

    def host_bwd(x: np.ndarray, gy: np.ndarray) -> np.ndarray:
        x = np.ascontiguousarray(x, np.float32)
        gy = np.ascontiguousarray(gy, np.float32)
        gx = np.empty_like(x)
        bwd_sym(x.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                gy.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                gx.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), x.size)
        return gx

    def _dispatch(host_fn, out_like, *arrays):
        # concrete arrays (eager): call the C++ kernel directly — works on
        # every backend, including TPU tunnels without host-callback
        # support. Tracers (inside jit/grad): emit a pure_callback (runs
        # where the backend supports host send/recv).
        if any(isinstance(a, jax.core.Tracer) for a in arrays):
            return jax.pure_callback(
                host_fn, jax.ShapeDtypeStruct(out_like.shape, jnp.float32),
                *arrays, vmap_method="sequential")
        return jnp.asarray(host_fn(*[np.asarray(a) for a in arrays]))

    @jax.custom_vjp
    def pure(x):
        return _dispatch(host_fwd, x, x)

    def fwd_rule(x):
        return pure(x), x

    def bwd_rule(x, gy):
        if bwd_sym is None:
            raise NotImplementedError(
                f"custom op '{op_name}' has no pd_{op_name}_backward")
        return (_dispatch(host_bwd, x, x, gy),)

    pure.defvjp(fwd_rule, bwd_rule)

    from ..ops.registry import OPS, OpInfo, run_op
    reg_name = f"custom_{op_name}"
    if reg_name not in OPS:
        OPS[reg_name] = OpInfo(reg_name, pure, tags=("custom",))

    @functools.wraps(pure)
    def eager(x, **kwargs):
        return run_op(reg_name, pure, (x,), kwargs)
    eager.__op_name__ = reg_name
    eager.__pure_fn__ = pure
    return eager


def load(name: str, sources: Sequence[str], extra_cflags=None,
         extra_cuda_cflags=None, extra_ldflags=None,
         extra_include_paths=None, build_directory=None,
         verbose: bool = False):
    """JIT-compile `sources` and register every pd_<op>_forward symbol as a
    framework op (ref utils/cpp_extension/extension_utils.py load)."""
    build_dir = build_directory or get_build_directory()
    flags = list(extra_cflags or [])
    for inc in (extra_include_paths or []):
        flags.append(f"-I{inc}")
    so_path = _compile(name, sources, flags, build_dir, verbose)
    lib = ctypes.CDLL(so_path)

    # discover pd_*_forward symbols by scanning the dynamic symbol table
    syms = subprocess.run(["nm", "-D", so_path], capture_output=True,
                          text=True).stdout
    ops = []
    for line in syms.splitlines():
        parts = line.split()
        if len(parts) >= 3 and parts[1] == "T":
            s = parts[2]
            if s.startswith("pd_") and s.endswith("_forward"):
                ops.append(s[len("pd_"):-len("_forward")])
    if not ops:
        raise RuntimeError(
            f"extension '{name}' exports no pd_<op>_forward symbols")
    mod = _LoadedExtension(name)
    for op_name in ops:
        has_bwd = f"pd_{op_name}_backward" in syms
        mod._ops[op_name] = _make_op(lib, op_name, has_bwd)
    return mod


class CppExtension:
    """setuptools-style extension spec (parity with
    utils/cpp_extension.CppExtension); consumed by setup()."""

    def __init__(self, sources, *args, **kwargs):
        self.sources = list(sources)
        self.kwargs = kwargs


CUDAExtension = CppExtension  # no CUDA here; kept for import parity


def setup(name=None, ext_modules=None, **kwargs):
    """Build-and-register immediately (the setup.py path collapses to
    load() since there is no separate install step in this runtime)."""
    mods = []
    for ext in (ext_modules or []):
        mods.append(load(name or "custom_ext", ext.sources,
                         **{k: v for k, v in ext.kwargs.items()
                            if k in ("extra_cflags", "extra_include_paths",
                                     "build_directory", "verbose")}))
    return mods[0] if len(mods) == 1 else mods
