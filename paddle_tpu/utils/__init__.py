"""paddle.utils namespace (reference python/paddle/utils/)."""
from . import cpp_extension  # noqa: F401
from . import unique_name  # noqa: F401
from ..core.flags import set_flags, get_flags  # noqa: F401
from .misc import (deprecated, run_check, require_version,  # noqa: F401
                   dump_config, load_op_library,
                   get_weights_path_from_url)
from . import misc as download  # noqa: F401 — download.* helpers live
# in misc (get_weights_path_from_url); the reference exposes a module

__all__ = ["cpp_extension", "unique_name", "set_flags", "get_flags",
           "deprecated", "run_check", "require_version", "dump_config",
           "load_op_library", "download"]
