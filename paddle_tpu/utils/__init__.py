"""paddle.utils namespace (reference python/paddle/utils/)."""
from . import cpp_extension  # noqa: F401
from . import unique_name  # noqa: F401
from ..core.flags import set_flags, get_flags  # noqa: F401

__all__ = ["cpp_extension", "unique_name", "set_flags", "get_flags"]
