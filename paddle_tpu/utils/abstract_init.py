"""Meta (abstract) parameter initialization.

A 10B-class model cannot be materialized on the host just to ask "would
its sharded training step fit in HBM?". Inside `abstract_parameters()`,
every `Layer.create_parameter` call produces a Parameter whose `_data`
is a `jax.ShapeDtypeStruct` — shape and dtype only, zero bytes — so
model construction is instant at any scale. The resulting layer cannot
run eagerly; it exists to be AOT-lowered (`TrainStep.aot_lower`) for
compile-time memory receipts (tests/test_memory_receipts.py, VERDICT r4
item 3). The reference has no equivalent — its ProgramDesc is already
abstract; this restores that property for the dygraph Layer path.
"""
from __future__ import annotations

import contextlib

import jax
import numpy as np

__all__ = ["abstract_parameters"]


@contextlib.contextmanager
def abstract_parameters():
    from ..core import dtypes as _dtypes
    from ..framework import Parameter
    from ..nn.layer.layers import Layer
    from ..nn.param_attr import ParamAttr

    orig = Layer.create_parameter

    def create_abstract(self, shape, attr=None, dtype=None, is_bias=False,
                        default_initializer=None):
        if attr is False and is_bias:
            return None
        dt = _dtypes.convert_dtype(dtype) if dtype else self._dtype
        name = None
        trainable = True
        if isinstance(attr, ParamAttr):
            name = attr.name
            trainable = attr.trainable
        sds = jax.ShapeDtypeStruct(tuple(int(s) for s in shape),
                                   np.dtype(dt))
        return Parameter(sds, name=name, trainable=trainable)

    Layer.create_parameter = create_abstract
    try:
        yield
    finally:
        Layer.create_parameter = orig
