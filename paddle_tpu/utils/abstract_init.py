"""Meta (abstract) parameter initialization.

A 10B-class model cannot be materialized on the host just to ask "would
its sharded training step fit in HBM?". Inside `abstract_parameters()`,
every `Layer.create_parameter` call produces a Parameter whose `_data`
is a `jax.ShapeDtypeStruct` — shape and dtype only, zero bytes — so
model construction is instant at any scale. The resulting layer cannot
run eagerly; it exists to be AOT-lowered (`TrainStep.aot_lower`) for
compile-time memory receipts (tests/test_memory_receipts.py, VERDICT r4
item 3). The reference has no equivalent — its ProgramDesc is already
abstract; this restores that property for the dygraph Layer path.
"""
from __future__ import annotations

import contextlib

import jax
import numpy as np

__all__ = ["abstract_parameters"]


@contextlib.contextmanager
def abstract_parameters():
    from ..core import dtypes as _dtypes
    from ..framework import Parameter, Tensor
    from ..nn import initializer as init_mod
    from ..nn.layer.layers import Layer
    from ..nn.param_attr import ParamAttr

    orig_create = Layer.create_parameter

    def create_abstract(self, shape, attr=None, dtype=None, is_bias=False,
                        default_initializer=None):
        if attr is False and is_bias:
            return None
        dt = _dtypes.convert_dtype(dtype) if dtype else self._dtype
        name = None
        trainable = True
        if isinstance(attr, ParamAttr):
            name = attr.name
            trainable = attr.trainable
        sds = jax.ShapeDtypeStruct(tuple(int(s) for s in shape),
                                   np.dtype(dt))
        return Parameter(sds, name=name, trainable=trainable)

    # model code also assigns values AFTER construction
    # (`layer.weight.set_value(Normal(0, std)(shape, dtype))` — the
    # ERNIE pattern): make every Initializer return an aval and
    # set_value keep an abstract tensor abstract, otherwise a 10B model
    # would still spend minutes generating 40 GB of random numbers it
    # immediately throws away (observed: 1120 s construct time)
    def aval_init(self, shape, dtype="float32"):
        return jax.ShapeDtypeStruct(
            tuple(int(s) for s in shape),
            np.dtype(_dtypes.convert_dtype(dtype)))

    patched = []
    seen = set()
    for name in dir(init_mod):
        cls = getattr(init_mod, name)
        if isinstance(cls, type) and issubclass(cls, init_mod.Initializer) \
                and "__call__" in cls.__dict__ and id(cls) not in seen:
            # dedupe aliases (BilinearInitializer = Bilinear): visiting
            # the alias after patching would capture the PATCH as the
            # "original" and leave it active after restore
            seen.add(id(cls))
            patched.append((cls, cls.__dict__["__call__"]))
            cls.__call__ = aval_init

    orig_sv = Tensor.set_value

    def abstract_set_value(self, value):
        if isinstance(self._data, jax.ShapeDtypeStruct) or \
                isinstance(value, jax.ShapeDtypeStruct):
            return  # values are irrelevant by construction
        return orig_sv(self, value)

    Layer.create_parameter = create_abstract
    Tensor.set_value = abstract_set_value
    try:
        yield
    finally:
        Layer.create_parameter = orig_create
        Tensor.set_value = orig_sv
        for cls, fn in patched:
            cls.__call__ = fn
