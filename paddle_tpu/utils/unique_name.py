"""Unique name generator (reference python/paddle/utils/unique_name.py →
fluid/unique_name.py: generate/guard/switch over a per-context counter)."""
from __future__ import annotations

import contextlib
from collections import defaultdict

__all__ = ["generate", "switch", "guard", "guard_prefix"]


class _Generator:
    def __init__(self):
        self.ids = defaultdict(int)

    def __call__(self, key: str) -> str:
        i = self.ids[key]
        self.ids[key] += 1
        return f"{key}_{i}"


_generator = _Generator()


def generate(key: str) -> str:
    name = _generator(key)
    if _prefix_stack:  # static.name_scope prefixes
        return "/".join(_prefix_stack) + "/" + name
    return name


def switch(new_generator=None):
    global _generator
    old = _generator
    _generator = new_generator or _Generator()
    return old


@contextlib.contextmanager
def guard(new_generator=None):
    old = switch(new_generator)
    try:
        yield
    finally:
        global _generator
        _generator = old


_prefix_stack: list = []


@contextlib.contextmanager
def guard_prefix(prefix: str):
    """static.name_scope support: names generated inside get
    '<prefix>/' prepended (nestable)."""
    _prefix_stack.append(prefix)
    try:
        yield
    finally:
        _prefix_stack.pop()
