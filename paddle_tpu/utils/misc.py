"""Small paddle.utils helpers (reference python/paddle/utils/__init__.py
rows: deprecated, run_check, require_version, dump_config,
load_op_library, download)."""
from __future__ import annotations

import functools
import os
import re
import warnings

__all__ = ["deprecated", "run_check", "require_version", "dump_config",
           "load_op_library", "get_weights_path_from_url"]


def deprecated(update_to="", since="", reason="", level=1):
    """Reference utils.deprecated: decorator that warns on use."""
    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            msg = f"API '{fn.__module__}.{fn.__name__}' is deprecated"
            if since:
                msg += f" since {since}"
            if update_to:
                msg += f", use '{update_to}' instead"
            if reason:
                msg += f" ({reason})"
            warnings.warn(msg, DeprecationWarning, stacklevel=2)
            return fn(*args, **kwargs)
        return wrapper
    return deco


def run_check():
    """Reference paddle.utils.run_check: prove the install can compute.

    Runs a jitted matmul on the default backend and, when several
    devices are visible, a psum over a 1-D mesh — printing what the
    reference prints ("PaddlePaddle is installed successfully!"-style)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    devs = jax.devices()
    a = jnp.ones((128, 128), jnp.float32)
    out = jax.jit(lambda x: (x @ x).sum())(a)
    assert float(out) == 128.0 * 128.0 * 128.0
    print(f"paddle_tpu works on 1 device ({devs[0].platform}).")
    if len(devs) > 1:
        from jax.sharding import Mesh, PartitionSpec as P
        mesh = Mesh(np.array(devs), ("d",))
        x = jnp.arange(len(devs), dtype=jnp.float32).reshape(-1, 1)
        tot = jax.shard_map(
            lambda v: jax.lax.psum(v, "d"), mesh=mesh,
            in_specs=P("d"), out_specs=P("d"))(x)
        assert float(np.asarray(tot)[0, 0]) == sum(range(len(devs)))
        print(f"paddle_tpu works across {len(devs)} devices "
              f"(psum verified).")
    print("paddle_tpu is installed successfully!")


def require_version(min_version: str, max_version: str = None):
    """Reference utils.require_version: assert the installed framework
    version is within [min_version, max_version]."""
    from .. import __version__ as ver

    def parse(v):
        return [int(x) for x in re.findall(r"\d+", v)[:4]] or [0]

    cur = parse(ver)
    if parse(min_version) > cur:
        raise Exception(
            f"installed version {ver} < required {min_version}")
    if max_version is not None and parse(max_version) < cur:
        raise Exception(
            f"installed version {ver} > allowed {max_version}")
    return True


def dump_config(path=None):
    """Reference utils dump of the runtime config: the typed flag
    registry + env bridge (core/flags.py) as a dict (optionally written
    to `path`)."""
    from ..core import flags as _flags
    snap = dict(sorted(_flags.get_flags().items()))
    if path:
        import json
        with open(path, "w") as f:
            json.dump({k: repr(v) for k, v in snap.items()}, f,
                      indent=2, sort_keys=True)
    return snap


def load_op_library(lib_path: str):
    """Reference utils.load_op_library (dlopen a custom-op .so): custom
    ops here are built/loaded through utils.cpp_extension.load; a
    prebuilt shared library is attached via ctypes and its registration
    entry point (pd_register_ops) invoked when present."""
    import ctypes
    lib = ctypes.CDLL(os.path.abspath(lib_path))
    if hasattr(lib, "pd_register_ops"):
        lib.pd_register_ops()
    return lib


def get_weights_path_from_url(url: str, md5sum=None):
    """Reference utils.download.get_weights_path_from_url. This image
    has no network egress: the file must already exist in the cache dir
    (~/.cache/paddle_tpu/weights or PD_WEIGHTS_HOME); otherwise a clear
    error explains how to place it."""
    cache = os.environ.get(
        "PD_WEIGHTS_HOME",
        os.path.expanduser("~/.cache/paddle_tpu/weights"))
    fname = os.path.join(cache, url.split("/")[-1])
    if os.path.exists(fname):
        return fname
    raise RuntimeError(
        f"no network egress: place the file for {url} at {fname} "
        "(or set PD_WEIGHTS_HOME)")
