"""Vision transforms (reference python/paddle/vision/transforms parity) —
numpy/host-side, composable; heavy augment pipelines belong on the host
CPU feeding the TPU."""
from __future__ import annotations

import numbers
from typing import Sequence

import numpy as np

__all__ = ["Compose", "ToTensor", "Normalize", "Resize", "CenterCrop",
           "RandomCrop", "RandomHorizontalFlip", "RandomVerticalFlip",
           "Transpose", "BrightnessTransform", "Pad", "RandomResizedCrop"]


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, data):
        for t in self.transforms:
            data = t(data)
        return data


class ToTensor:
    """HWC uint8 [0,255] -> CHW float32 [0,1]."""

    def __init__(self, data_format="CHW"):
        self.data_format = data_format

    def __call__(self, img):
        arr = np.asarray(img)
        if arr.dtype == np.uint8:
            arr = arr.astype(np.float32) / 255.0
        if arr.ndim == 2:
            arr = arr[:, :, None]
        if self.data_format == "CHW":
            arr = arr.transpose(2, 0, 1)
        return arr.astype(np.float32)


class Normalize:
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False):
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)
        self.data_format = data_format

    def __call__(self, img):
        arr = np.asarray(img, np.float32)
        if self.data_format == "CHW":
            shape = (-1, 1, 1)
        else:
            shape = (1, 1, -1)
        return (arr - self.mean.reshape(shape)) / self.std.reshape(shape)


def _resize_np(arr, size):
    """Nearest-neighbor resize (no PIL dependency)."""
    if isinstance(size, numbers.Number):
        h, w = arr.shape[:2]
        if h < w:
            size = (int(size), int(size * w / h))
        else:
            size = (int(size * h / w), int(size))
    out_h, out_w = size
    h, w = arr.shape[:2]
    ri = (np.arange(out_h) * h / out_h).astype(np.int64)
    ci = (np.arange(out_w) * w / out_w).astype(np.int64)
    return arr[ri][:, ci]


class Resize:
    def __init__(self, size, interpolation="bilinear"):
        self.size = size

    def __call__(self, img):
        return _resize_np(np.asarray(img), self.size)


class CenterCrop:
    def __init__(self, size):
        self.size = (size, size) if isinstance(size, numbers.Number) \
            else tuple(size)

    def __call__(self, img):
        arr = np.asarray(img)
        h, w = arr.shape[:2]
        th, tw = self.size
        i = max((h - th) // 2, 0)
        j = max((w - tw) // 2, 0)
        return arr[i:i + th, j:j + tw]


class RandomCrop:
    def __init__(self, size, padding=0):
        self.size = (size, size) if isinstance(size, numbers.Number) \
            else tuple(size)
        self.padding = padding

    def __call__(self, img):
        arr = np.asarray(img)
        if self.padding:
            pad = [(self.padding, self.padding),
                   (self.padding, self.padding)] + \
                [(0, 0)] * (arr.ndim - 2)
            arr = np.pad(arr, pad)
        h, w = arr.shape[:2]
        th, tw = self.size
        i = np.random.randint(0, h - th + 1)
        j = np.random.randint(0, w - tw + 1)
        return arr[i:i + th, j:j + tw]


class RandomResizedCrop:
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation="bilinear"):
        self.size = (size, size) if isinstance(size, numbers.Number) \
            else tuple(size)
        self.scale = scale
        self.ratio = ratio

    def __call__(self, img):
        arr = np.asarray(img)
        h, w = arr.shape[:2]
        area = h * w
        for _ in range(10):
            target = area * np.random.uniform(*self.scale)
            ar = np.exp(np.random.uniform(np.log(self.ratio[0]),
                                          np.log(self.ratio[1])))
            cw = int(round(np.sqrt(target * ar)))
            ch = int(round(np.sqrt(target / ar)))
            if cw <= w and ch <= h:
                i = np.random.randint(0, h - ch + 1)
                j = np.random.randint(0, w - cw + 1)
                return _resize_np(arr[i:i + ch, j:j + cw], self.size)
        return _resize_np(arr, self.size)


class RandomHorizontalFlip:
    def __init__(self, prob=0.5):
        self.prob = prob

    def __call__(self, img):
        if np.random.rand() < self.prob:
            return np.asarray(img)[:, ::-1].copy()
        return np.asarray(img)


class RandomVerticalFlip:
    def __init__(self, prob=0.5):
        self.prob = prob

    def __call__(self, img):
        if np.random.rand() < self.prob:
            return np.asarray(img)[::-1].copy()
        return np.asarray(img)


class Transpose:
    def __init__(self, order=(2, 0, 1)):
        self.order = order

    def __call__(self, img):
        arr = np.asarray(img)
        if arr.ndim == 2:
            arr = arr[:, :, None]
        return arr.transpose(self.order)


class BrightnessTransform:
    def __init__(self, value):
        self.value = value

    def __call__(self, img):
        arr = np.asarray(img, np.float32)
        factor = np.random.uniform(max(0, 1 - self.value), 1 + self.value)
        return np.clip(arr * factor, 0, 255 if arr.max() > 1 else 1.0)


class Pad:
    def __init__(self, padding, fill=0):
        self.padding = padding
        self.fill = fill

    def __call__(self, img):
        arr = np.asarray(img)
        p = self.padding
        if isinstance(p, numbers.Number):
            p = (p, p, p, p)
        pad = [(p[1], p[3]), (p[0], p[2])] + [(0, 0)] * (arr.ndim - 2)
        return np.pad(arr, pad, constant_values=self.fill)

# reference package layout: vision.transforms.transforms (self) and
# vision.transforms.functional (the lowercase per-image functions the
# class transforms are built from — paddle/vision/transforms/functional.py)
import sys as _sys  # noqa: E402
transforms = _sys.modules[__name__]


class _Functional:
    """paddle.vision.transforms.functional over numpy images."""

    @staticmethod
    def to_tensor(pic, data_format="CHW"):
        return ToTensor(data_format)(pic)

    @staticmethod
    def normalize(img, mean, std, data_format="CHW", to_rgb=False):
        return Normalize(mean, std, data_format)(img)

    @staticmethod
    def resize(img, size, interpolation="bilinear"):
        return _resize_np(np.asarray(img), size)

    @staticmethod
    def crop(img, top, left, height, width):
        return np.asarray(img)[top:top + height, left:left + width]

    @staticmethod
    def center_crop(img, output_size):
        return CenterCrop(output_size)(img)

    @staticmethod
    def hflip(img):
        return np.asarray(img)[:, ::-1]

    @staticmethod
    def vflip(img):
        return np.asarray(img)[::-1]

    @staticmethod
    def pad(img, padding, fill=0, padding_mode="constant"):
        return Pad(padding, fill)(img)

    @staticmethod
    def adjust_brightness(img, brightness_factor):
        arr = np.asarray(img)
        return np.clip(np.asarray(arr, np.float32) * brightness_factor,
                       0, 255).astype(arr.dtype)

    @staticmethod
    def adjust_contrast(img, contrast_factor):
        arr = np.asarray(img, np.float32)
        mean = arr.mean()
        out = (arr - mean) * contrast_factor + mean
        return np.clip(out, 0, 255).astype(np.asarray(img).dtype)

    @staticmethod
    def to_grayscale(img, num_output_channels=1):
        arr = np.asarray(img, np.float32)
        gray = (arr[..., :3] @ np.asarray(
            [0.299, 0.587, 0.114], np.float32))[..., None]
        return np.repeat(gray, num_output_channels,
                         axis=-1).astype(np.asarray(img).dtype)

    @staticmethod
    def rotate(img, angle, interpolation="nearest", expand=False,
               center=None, fill=0):
        k = int(round(angle / 90.0)) % 4
        if abs(angle - 90.0 * round(angle / 90.0)) > 1e-6:
            raise NotImplementedError(
                "rotate supports multiples of 90 degrees (no PIL in "
                "this image)")
        return np.rot90(np.asarray(img), k=k).copy()


# register as a REAL submodule so reference-style imports work
# (`import paddle_tpu.vision.transforms.functional`, `from
# paddle_tpu.vision.transforms import functional`)
_fmod = type(_sys)("paddle_tpu.vision.transforms.functional")
for _n in dir(_Functional):
    if not _n.startswith("_"):
        setattr(_fmod, _n, getattr(_Functional, _n))
_fmod.__doc__ = _Functional.__doc__
_sys.modules["paddle_tpu.vision.transforms.functional"] = _fmod
functional = _fmod
