from . import models  # noqa: F401
from . import transforms  # noqa: F401
from . import datasets  # noqa: F401
from . import image  # noqa: F401
from .image import set_image_backend, get_image_backend, image_load  # noqa: F401

from . import ops  # noqa: F401,E402
