"""paddle.vision.image — image backend selection
(reference python/paddle/vision/image.py:18). The TPU build has no cv2
dependency; PIL and a pure-numpy path are the backends."""
import numpy as np

__all__ = ["set_image_backend", "get_image_backend", "image_load"]

_image_backend = "pil"


def set_image_backend(backend):
    global _image_backend
    if backend not in ("pil", "cv2", "numpy"):
        raise ValueError(
            f"Expected backend are one of ['pil', 'cv2', 'numpy'], "
            f"but got {backend}")
    _image_backend = backend


def get_image_backend():
    return _image_backend


def image_load(path, backend=None):
    """Load an image as PIL.Image or ndarray depending on the backend."""
    backend = backend or _image_backend
    if backend == "numpy":
        from PIL import Image
        return np.asarray(Image.open(path))
    if backend == "cv2":
        try:
            import cv2
            return cv2.imread(path)
        except ImportError:
            from PIL import Image
            return np.asarray(Image.open(path))[..., ::-1]
    from PIL import Image
    return Image.open(path)
