"""Vision datasets (reference python/paddle/vision/datasets parity).

Zero-egress environment: when real data files are absent, datasets fall
back to a deterministic synthetic sample set (shape/dtype-faithful) so
examples, tests, and benchmarks run anywhere. Pass `data_file`/`image_path`
pointing at real data to use it.
"""
from __future__ import annotations

import gzip
import os
import struct

import numpy as np

from ..io.dataset import Dataset

__all__ = ["MNIST", "FashionMNIST", "Cifar10", "Cifar100", "Flowers"]


class MNIST(Dataset):
    """28x28 grayscale digits; synthetic fallback generates class-dependent
    patterns so a model can actually learn (acc >> chance) without files."""

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=False, backend="cv2",
                 synthetic_size=None):
        self.mode = mode
        self.transform = transform
        if image_path and os.path.exists(image_path):
            with gzip.open(image_path, "rb") as f:
                magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
                self.images = np.frombuffer(
                    f.read(), dtype=np.uint8).reshape(n, rows, cols)
            with gzip.open(label_path, "rb") as f:
                f.read(8)
                self.labels = np.frombuffer(f.read(), dtype=np.uint8)
        else:
            n = synthetic_size or (1024 if mode == "train" else 256)
            # class patterns shared across splits; noise differs per split
            base = np.random.RandomState(1234).rand(10, 28, 28)
            rng = np.random.RandomState(42 if mode == "train" else 7)
            self.labels = rng.randint(0, 10, n).astype(np.int64)
            self.images = np.clip(
                (base[self.labels] * 128 + rng.rand(n, 28, 28) * 64),
                0, 255).astype(np.uint8)

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        else:
            img = (img.astype(np.float32) / 255.0)[None]
        return img, np.asarray(self.labels[idx], np.int64)

    def __len__(self):
        return len(self.images)


class FashionMNIST(MNIST):
    pass


class _CifarBase(Dataset):
    n_classes = 10

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=False, backend="cv2", synthetic_size=None):
        self.transform = transform
        if data_file and os.path.exists(data_file):
            import pickle
            import tarfile
            imgs, labels = [], []
            with tarfile.open(data_file) as tf:
                for member in tf.getmembers():
                    want = ("data_batch" if mode == "train" else
                            "test_batch") if self.n_classes == 10 else \
                        ("train" if mode == "train" else "test")
                    if want in member.name:
                        d = pickle.load(tf.extractfile(member),
                                        encoding="bytes")
                        imgs.append(d[b"data"])
                        labels.extend(d.get(b"labels",
                                            d.get(b"fine_labels", [])))
            self.images = np.concatenate(imgs).reshape(-1, 3, 32, 32)
            self.labels = np.asarray(labels, np.int64)
        else:
            n = synthetic_size or (1024 if mode == "train" else 256)
            base = np.random.RandomState(99).rand(self.n_classes, 3, 32, 32)
            rng = np.random.RandomState(13 if mode == "train" else 14)
            self.labels = rng.randint(0, self.n_classes, n).astype(np.int64)
            self.images = np.clip(base[self.labels] * 200
                                  + rng.rand(n, 3, 32, 32) * 55,
                                  0, 255).astype(np.uint8)

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img.transpose(1, 2, 0))
        else:
            img = img.astype(np.float32) / 255.0
        return img, np.asarray(self.labels[idx], np.int64)

    def __len__(self):
        return len(self.images)


class Cifar10(_CifarBase):
    n_classes = 10


class Cifar100(_CifarBase):
    n_classes = 100


class Flowers(_CifarBase):
    n_classes = 102
