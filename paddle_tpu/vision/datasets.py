"""Vision datasets (reference python/paddle/vision/datasets parity).

Zero-egress environment: when real data files are absent, datasets fall
back to a deterministic synthetic sample set (shape/dtype-faithful) so
examples, tests, and benchmarks run anywhere. Pass `data_file`/`image_path`
pointing at real data to use it.
"""
from __future__ import annotations

import gzip
import os
import struct

import numpy as np

from ..io.dataset import Dataset

__all__ = ["MNIST", "FashionMNIST", "Cifar10", "Cifar100", "Flowers",
           "DatasetFolder", "ImageFolder", "VOC2012"]


class MNIST(Dataset):
    """28x28 grayscale digits; synthetic fallback generates class-dependent
    patterns so a model can actually learn (acc >> chance) without files."""

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=False, backend="cv2",
                 synthetic_size=None):
        self.mode = mode
        self.transform = transform
        if image_path and os.path.exists(image_path):
            with gzip.open(image_path, "rb") as f:
                magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
                self.images = np.frombuffer(
                    f.read(), dtype=np.uint8).reshape(n, rows, cols)
            with gzip.open(label_path, "rb") as f:
                f.read(8)
                self.labels = np.frombuffer(f.read(), dtype=np.uint8)
        else:
            n = synthetic_size or (1024 if mode == "train" else 256)
            # class patterns shared across splits; noise differs per split
            base = np.random.RandomState(1234).rand(10, 28, 28)
            rng = np.random.RandomState(42 if mode == "train" else 7)
            self.labels = rng.randint(0, 10, n).astype(np.int64)
            self.images = np.clip(
                (base[self.labels] * 128 + rng.rand(n, 28, 28) * 64),
                0, 255).astype(np.uint8)

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        else:
            img = (img.astype(np.float32) / 255.0)[None]
        return img, np.asarray(self.labels[idx], np.int64)

    def __len__(self):
        return len(self.images)


class FashionMNIST(MNIST):
    pass


class _CifarBase(Dataset):
    n_classes = 10

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=False, backend="cv2", synthetic_size=None):
        self.transform = transform
        if data_file and os.path.exists(data_file):
            import pickle
            import tarfile
            imgs, labels = [], []
            with tarfile.open(data_file) as tf:
                for member in tf.getmembers():
                    want = ("data_batch" if mode == "train" else
                            "test_batch") if self.n_classes == 10 else \
                        ("train" if mode == "train" else "test")
                    if want in member.name:
                        d = pickle.load(tf.extractfile(member),
                                        encoding="bytes")
                        imgs.append(d[b"data"])
                        labels.extend(d.get(b"labels",
                                            d.get(b"fine_labels", [])))
            self.images = np.concatenate(imgs).reshape(-1, 3, 32, 32)
            self.labels = np.asarray(labels, np.int64)
        else:
            n = synthetic_size or (1024 if mode == "train" else 256)
            base = np.random.RandomState(99).rand(self.n_classes, 3, 32, 32)
            rng = np.random.RandomState(13 if mode == "train" else 14)
            self.labels = rng.randint(0, self.n_classes, n).astype(np.int64)
            self.images = np.clip(base[self.labels] * 200
                                  + rng.rand(n, 3, 32, 32) * 55,
                                  0, 255).astype(np.uint8)

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img.transpose(1, 2, 0))
        else:
            img = img.astype(np.float32) / 255.0
        return img, np.asarray(self.labels[idx], np.int64)

    def __len__(self):
        return len(self.images)


class Cifar10(_CifarBase):
    n_classes = 10


class Cifar100(_CifarBase):
    n_classes = 100


class Flowers(_CifarBase):
    n_classes = 102


_DEFAULT_IMG_EXTS = (".npy", ".npz", ".png", ".jpg", ".jpeg", ".bmp")


def _file_filter(extensions, is_valid_file):
    """One predicate per torchvision/reference semantics: extensions and
    is_valid_file are mutually exclusive."""
    if extensions is not None and is_valid_file is not None:
        raise ValueError(
            "pass either extensions or is_valid_file, not both")
    if is_valid_file is not None:
        return is_valid_file, "<is_valid_file>"
    exts = tuple(e.lower() for e in (extensions or _DEFAULT_IMG_EXTS))
    return (lambda path: path.lower().endswith(exts)), exts


class DatasetFolder(Dataset):
    """Directory-per-class image dataset (reference
    vision/datasets/folder.py:62): root/<class>/<file>. Files load via a
    pluggable `loader`; the default handles numpy formats (.npy/.npz)
    directly and other image formats through PIL when available (store
    arrays as .npy/.npz or pass loader= on PIL-less stacks)."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.transform = transform
        self.loader = loader or self._default_loader
        valid, exts = _file_filter(extensions, is_valid_file)
        classes = sorted(d for d in os.listdir(root)
                         if os.path.isdir(os.path.join(root, d)))
        if not classes:
            raise ValueError(f"no class folders under {root}")
        self.classes = classes
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        self.samples = []
        for c in classes:
            cdir = os.path.join(root, c)
            # recurse like the reference's make_dataset (folder.py):
            # class dirs may nest sessions/shards of files
            for dirpath, _, files in sorted(os.walk(cdir)):
                for fn in sorted(files):
                    path = os.path.join(dirpath, fn)
                    if valid(path):
                        self.samples.append((path, self.class_to_idx[c]))
        if not self.samples:
            raise ValueError(f"no samples matching {exts} under {root}")

    @staticmethod
    def _default_loader(path):
        low = path.lower()
        if low.endswith(".npy"):
            return np.load(path)
        if low.endswith(".npz"):
            return next(iter(np.load(path).values()))
        try:
            from PIL import Image
            return np.asarray(Image.open(path).convert("RGB"))
        except ImportError as e:
            raise ImportError(
                f"loading {path} needs PIL; store arrays as .npy/.npz "
                "or pass a custom loader=") from e

    def __getitem__(self, index):
        path, target = self.samples[index]
        img = self.loader(path)
        if self.transform is not None:
            img = self.transform(img)
        return img, target

    def __len__(self):
        return len(self.samples)


class ImageFolder(Dataset):
    """Flat/recursive unlabeled image folder (reference folder.py:219):
    yields (image,) per sample for inference sweeps."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.transform = transform
        self.loader = loader or DatasetFolder._default_loader
        valid, _ = _file_filter(extensions, is_valid_file)
        self.samples = []
        for dirpath, _, files in sorted(os.walk(root)):
            for fn in sorted(files):
                path = os.path.join(dirpath, fn)
                if valid(path):
                    self.samples.append(path)
        if not self.samples:
            raise ValueError(f"no images under {root}")

    def __getitem__(self, index):
        img = self.loader(self.samples[index])
        if self.transform is not None:
            img = self.transform(img)
        return [img]

    def __len__(self):
        return len(self.samples)


class VOC2012(Dataset):
    """Segmentation pairs (reference vision/datasets/voc2012.py:40):
    (image [H,W,3] uint8, label mask [H,W] uint8 with 21 classes).
    Synthetic fallback: deterministic blob masks + class-colored images
    so segmentation models train without files."""

    NUM_CLASSES = 21

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=False, backend=None, synthetic_size=None,
                 image_size=64):
        self.mode = mode
        self.transform = transform
        if data_file and os.path.exists(data_file):
            blob = np.load(data_file)
            self.images, self.masks = blob["images"], blob["masks"]
        else:
            n = synthetic_size or (128 if mode == "train" else 32)
            rng = np.random.RandomState(0 if mode == "train" else 1)
            h = w = image_size
            yy, xx = np.mgrid[0:h, 0:w]
            images = np.zeros((n, h, w, 3), np.uint8)
            masks = np.zeros((n, h, w), np.uint8)
            for i in range(n):
                cls = rng.randint(1, self.NUM_CLASSES)
                cy, cx = rng.randint(h // 4, 3 * h // 4, size=2)
                r = rng.randint(h // 8, h // 4)
                blob = ((yy - cy) ** 2 + (xx - cx) ** 2) < r * r
                masks[i][blob] = cls
                images[i] = rng.randint(0, 40, (h, w, 3))
                images[i][blob] = (cls * 11 % 255, cls * 37 % 255,
                                   cls * 73 % 255)
            self.images, self.masks = images, masks

    def __getitem__(self, idx):
        img, mask = self.images[idx], self.masks[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, mask

    def __len__(self):
        return len(self.images)
# reference exposes per-dataset submodules (from . import cifar ...);
# register REAL modules in sys.modules so reference-style imports work
import sys as _sys  # noqa: E402


def _submodule(name, **attrs):
    mod = type(_sys)(__name__ + "." + name)
    for k, v in attrs.items():
        setattr(mod, k, v)
    _sys.modules[__name__ + "." + name] = mod
    return mod


cifar = _submodule("cifar", Cifar10=Cifar10, Cifar100=Cifar100)
mnist = _submodule("mnist", MNIST=MNIST, FashionMNIST=FashionMNIST)
flowers = _submodule("flowers", Flowers=Flowers)
voc2012 = _submodule("voc2012", VOC2012=VOC2012)
folder = _submodule("folder", DatasetFolder=DatasetFolder,
                    ImageFolder=ImageFolder)
