"""paddle.vision.ops (reference python/paddle/vision/ops.py:
yolo_loss, yolo_box, deform_conv2d + the DeformConv2D layer) — thin
namespace over the registered detection/vision ops."""
from __future__ import annotations

from ..ops.detection import yolo_box, yolov3_loss  # noqa: F401
from ..ops.vision_extra import deformable_conv

__all__ = ["yolo_loss", "yolo_box", "deform_conv2d", "DeformConv2D"]


def yolo_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
              ignore_thresh, downsample_ratio, gt_score=None,
              use_label_smooth=True, name=None, scale_x_y=1.0):
    """paddle.vision.ops.yolo_loss → yolov3_loss op."""
    return yolov3_loss(x, gt_box, gt_label, anchors, anchor_mask,
                       class_num, ignore_thresh, downsample_ratio,
                       gt_score=gt_score,
                       use_label_smooth=use_label_smooth,
                       scale_x_y=scale_x_y)


def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None,
                  name=None):
    """paddle.vision.ops.deform_conv2d (v1 when mask is None, v2
    otherwise) → deformable_conv ops."""
    return deformable_conv(x, offset, mask, weight, bias=bias,
                           stride=stride, padding=padding,
                           dilation=dilation,
                           deformable_groups=deformable_groups,
                           groups=groups)


from ..nn.layer.layers import Layer as _Layer


class DeformConv2D(_Layer):
    """Layer form (reference vision/ops.py DeformConv2D)."""

    def __init__(self, in_channels, out_channels, kernel_size,
                 stride=1, padding=0, dilation=1,
                 deformable_groups=1, groups=1,
                 weight_attr=None, bias_attr=None):
        super().__init__()
        ks = kernel_size if isinstance(kernel_size, (list, tuple)) \
            else (kernel_size, kernel_size)
        self._stride = stride
        self._padding = padding
        self._dilation = dilation
        self._deformable_groups = deformable_groups
        self._groups = groups
        self.weight = self.create_parameter(
            (out_channels, in_channels // groups) + tuple(ks))
        self.bias = None
        if bias_attr is not False:
            self.bias = self.create_parameter((out_channels,),
                                              is_bias=True)

    def forward(self, x, offset, mask=None):
        return deform_conv2d(
            x, offset, self.weight, bias=self.bias,
            stride=self._stride, padding=self._padding,
            dilation=self._dilation,
            deformable_groups=self._deformable_groups,
            groups=self._groups, mask=mask)
