"""paddle_tpu: a TPU-native deep-learning framework.

Capability-parity with the reference (pangyoki/Paddle ~v2.0) redesigned for
TPU: JAX/XLA is the compute substrate (eager ops over jnp + tape autograd,
compiled training steps via jit/pjit over device meshes), Pallas for hot
kernels, XLA collectives over ICI for distribution. The public API mirrors
paddle 2.x so reference users can switch with minimal edits.
"""
from __future__ import annotations

__version__ = "0.1.0"

from . import jax_compat  # noqa: F401  (must precede any jax-API use)
from . import core
from .core import (CPUPlace, CUDAPinnedPlace, CUDAPlace, TPUPlace,
                   XPUPlace, get_device,
                   set_device, is_compiled_with_tpu, seed, set_flags,
                   get_flags, set_default_dtype, get_default_dtype)
from .core.dtypes import (bool_ as bool8, bfloat16, complex128, complex64,
                          float16, float32, float64, int16, int32, int64,
                          int8, uint8)
from .framework import (Tensor, to_tensor, no_grad, enable_grad,
                        is_grad_enabled, set_grad_enabled, in_dygraph_mode)
from .framework import Parameter  # noqa: F401
from .ops import *  # noqa: F401,F403
from .ops import registry as _registry  # noqa: F401

# namespace-style access: paddle_tpu.tensor.xxx mirrors paddle.tensor
from . import ops as tensor  # noqa: F401
from . import linalg  # noqa: F401
from . import nn  # noqa: F401
from . import optimizer  # noqa: F401
from . import amp  # noqa: F401
from . import jit  # noqa: F401
from . import static  # noqa: F401
from . import io  # noqa: F401
from . import metric  # noqa: F401
from . import distributed  # noqa: F401
from . import vision  # noqa: F401
from . import text  # noqa: F401
from . import inference  # noqa: F401
from . import utils  # noqa: F401
from . import models  # noqa: F401
from . import distribution  # noqa: F401
from . import compat  # noqa: F401
from . import device  # noqa: F401
from . import regularizer  # noqa: F401
from . import sysconfig  # noqa: F401
from . import incubate  # noqa: F401
from . import quant  # noqa: F401
from .batch import batch  # noqa: F401  (paddle.batch is the function)
from . import hapi  # noqa: F401
from . import observability  # noqa: F401
from . import profiler  # noqa: F401
from . import onnx  # noqa: F401
from .hapi import Model  # noqa: F401
from .distributed.parallel import DataParallel  # noqa: F401
from .nn.param_attr import ParamAttr  # noqa: F401


def is_tensor(x):
    return isinstance(x, Tensor)


def is_compiled_with_cuda() -> bool:
    return False


def is_compiled_with_xpu() -> bool:
    return False


def is_compiled_with_npu() -> bool:
    return False


def disable_static(place=None):
    return None


def enable_static():
    from .static import _enable_static_mode
    _enable_static_mode()


def grad(outputs, inputs, grad_outputs=None, retain_graph=None,
         create_graph=False, only_inputs=True, allow_unused=False,
         no_grad_vars=None):
    """paddle.grad equivalent (PartialGradEngine analogue,
    /root/reference/paddle/fluid/imperative/partial_grad_engine.cc)."""
    from .autograd_utils import partial_grad
    return partial_grad(outputs, inputs, grad_outputs, retain_graph,
                        create_graph, allow_unused, no_grad_vars)


def save(obj, path, protocol=4):
    from .serialization import save as _save
    return _save(obj, path, protocol)


def load(path, **kwargs):
    from .serialization import load as _load
    return _load(path, **kwargs)


def summary(net, input_size=None, dtypes=None, input=None):
    from .hapi.summary import summary as _summary
    return _summary(net, input_size, dtypes, input)


def flops(net, input_size, custom_ops=None, print_detail=False):
    return 0

# reference top-level re-exports: hapi callbacks namespace + platform
# introspection shims (python/paddle/__init__.py)
from .hapi import callbacks  # noqa: F401,E402


def get_cudnn_version():
    """Reference paddle.get_cudnn_version: None — no cuDNN on TPU/XLA
    (the reference returns None when CUDA is absent too)."""
    return None


def monkey_patch_math_varbase():
    """Reference internal: Tensor operator overloads. Applied at import
    here (framework.py patches Tensor); kept as an explicit no-op."""


def monkey_patch_variable():
    """Reference internal: static Variable operator overloads. Applied
    at import (static/program.py Var); kept as an explicit no-op."""
