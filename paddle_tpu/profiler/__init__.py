"""Profiler: RecordEvent spans + chrome-trace export + XPlane bridge.

Reference: platform/profiler.{h,cc} (RecordEvent RAII, push/pop per-thread
event stacks, Enable/DisableProfiler with sorted reports), device_tracer.cc
(CUPTI timeline) and tools/timeline.py (chrome://tracing export).

TPU-native: host-side spans are recorded here (framework overhead,
dataloading, dispatch); device-side kernels come from jax.profiler
(XPlane → TensorBoard / Perfetto). export_chrome_tracing merges host spans
into the chrome trace format directly.
"""
from __future__ import annotations

import json
import os
import threading
import time
from contextlib import contextmanager
from typing import Dict, List, Optional

__all__ = ["RecordEvent", "Profiler", "start_profiler", "stop_profiler",
           "profiler_guard", "export_chrome_tracing", "summary",
           "SummaryDict", "start_trace", "stop_trace", "StepClock"]

_lock = threading.Lock()
_enabled = False
_events: List[dict] = []
_tls = threading.local()

# native span collector (csrc/runtime.cpp pd_prof_*): the eager op
# dispatch wraps every op in a RecordEvent, so span recording must be
# cheap — the C++ path is two clock reads + one buffer append with no
# Python dict building. Loaded lazily on the first start_profiler() so
# `import paddle_tpu` never pays the one-time C++ build; falls back to
# the pure-Python list when the toolchain is unavailable.
_native = None
_native_resolved = False


def _get_native():
    global _native, _native_resolved
    if not _native_resolved:
        from ..core.native_lib import runtime_lib
        _native = runtime_lib()
        _native_resolved = True
    return _native


class RecordEvent:
    """RAII span (reference profiler.h:127). Usable as context manager or
    decorator; nesting tracked per thread."""

    def __init__(self, name: str, event_type: str = "UserDefined"):
        self.name = name
        self.event_type = event_type
        self._t0 = None
        self._backend = None

    def begin(self):
        if not _enabled:
            return self
        # capture the backend ONCE: if start_profiler resolves the
        # native lib between this span's begin and end, end() must not
        # hand a Python-clock _t0 to pd_prof_span (different epoch) or
        # leak the Python path's _tls.depth increment
        self._backend = _native
        if self._backend is not None:
            self._t0 = self._backend.pd_prof_now()
            return self
        self._t0 = time.perf_counter_ns()
        depth = getattr(_tls, "depth", 0)
        _tls.depth = depth + 1
        self._depth = depth
        return self

    def end(self):
        if self._t0 is None:
            return
        if self._backend is not None:
            if not _enabled:
                return  # native span: nothing thread-local to unwind
            self._backend.pd_prof_span(self.name.encode(),
                                       self.event_type.encode(), self._t0,
                                       self._backend.pd_prof_now(),
                                       threading.get_ident() % (1 << 31))
            return
        # python path: begin() bumped _tls.depth — unwind it even when
        # stop_profiler() landed between begin and end (the span itself
        # is dropped, the nesting bookkeeping must not tear)
        t1 = time.perf_counter_ns()
        _tls.depth = max(getattr(_tls, "depth", 1) - 1, 0)
        if not _enabled:
            return
        with _lock:
            _events.append({
                "name": self.name, "cat": self.event_type,
                "ts": self._t0 / 1000.0, "dur": (t1 - self._t0) / 1000.0,
                "ph": "X", "pid": os.getpid(),
                "tid": threading.get_ident(),
                "args": {"depth": self._depth},
            })

    def __enter__(self):
        return self.begin()

    def __exit__(self, *exc):
        self.end()

    def __call__(self, fn):
        import functools

        @functools.wraps(fn)
        def wrapper(*a, **k):
            with RecordEvent(self.name, self.event_type):
                return fn(*a, **k)
        return wrapper


def start_profiler(state="All", tracer_option="Default"):
    """reference profiler.py start_profiler parity."""
    global _enabled
    with _lock:
        _events.clear()
    native = _get_native()
    if native is not None:
        native.pd_prof_clear()
        native.pd_prof_enable(1)
    _enabled = True


def stop_profiler(sorted_key="total", profile_path="/tmp/profile"):
    global _enabled
    _enabled = False
    if _native is not None:
        _native.pd_prof_enable(0)  # resolved by start_profiler
    if profile_path:
        export_chrome_tracing(profile_path)
    return summary(sorted_key)


@contextmanager
def profiler_guard(state="All", sorted_key="total",
                   profile_path="/tmp/profile"):
    start_profiler(state)
    try:
        yield
    finally:
        stop_profiler(sorted_key, profile_path)


def _metric_marks():
    """Metric counter events for the host trace (observability overlay;
    empty when the metrics runtime is off or holds nothing — a process
    that never enabled metrics must not pay a dump reparse just because
    always-on instruments exist)."""
    try:
        from ..observability import exporters, metrics
        if not metrics.enabled() or not metrics.registry_size():
            return []
        return exporters.chrome_trace_events()
    except Exception:
        return []


def _reqtrace_lanes():
    """Serving request lanes (observability.reqtrace): one lane per
    replica, spans colored by latency component. Empty when request
    tracing is off — a training process must not pay a ring scan."""
    try:
        from ..observability import reqtrace
        if not reqtrace.enabled():
            return []
        return reqtrace.chrome_trace_events()
    except Exception:
        return []


def export_chrome_tracing(path: str):
    """Write chrome://tracing JSON (tools/timeline.py analogue). Metric
    values from the observability registry ride along as counter
    ("ph":"C") events, and serving request lanes (reqtrace spans, one
    lane per replica) merge onto the same timeline."""
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    out = path if path.endswith(".json") else path + ".json"
    marks = _metric_marks() + _reqtrace_lanes()
    if _native is not None:
        if _native.pd_prof_dump(out.encode()) != 0:
            raise OSError(f"cannot write trace to {out}")
        if marks:  # merge marks into the native dump
            with open(out) as f:
                data = json.load(f)
            data.setdefault("traceEvents", []).extend(marks)
            with open(out, "w") as f:
                json.dump(data, f)
        return out
    with _lock:
        data = {"traceEvents": list(_events) + marks}
    with open(out, "w") as f:
        json.dump(data, f)
    return out


class SummaryDict(dict):
    """summary() result: a plain sorted dict of per-span stats plus a
    `truncated` flag (True only if the native collector held more
    distinct span names than the hard buffer ceiling — reported instead
    of silently dropped)."""
    truncated = False


_SUMMARY_CAP_MAX = 1 << 16


def summary(sorted_key="total"):
    """Aggregated per-span stats (DisableProfiler sorted report)."""
    agg: Dict[str, dict] = {}
    truncated = False
    if _native is not None:
        import ctypes
        # pd_prof_summary drops distinct names beyond cap, returning
        # n == cap as the only tell; re-call with a grown buffer until
        # every name fits (or the hard ceiling is hit, then say so)
        cap = 512
        while True:
            names = ctypes.create_string_buffer(64 * cap)
            calls = (ctypes.c_int64 * cap)()
            total = (ctypes.c_int64 * cap)()
            mx = (ctypes.c_int64 * cap)()
            n = _native.pd_prof_summary(names, calls, total, mx, cap)
            if n < cap:
                break
            if cap >= _SUMMARY_CAP_MAX:
                truncated = True
                break
            cap *= 4
        for i in range(n):
            nm = names.raw[64 * i:64 * (i + 1)].split(b"\0")[0].decode()
            agg[nm] = {"calls": int(calls[i]),
                       "total_us": total[i] / 1e3,
                       "max_us": mx[i] / 1e3}
    else:
        with _lock:
            evs = list(_events)
        for e in evs:
            s = agg.setdefault(e["name"], {"calls": 0, "total_us": 0.0,
                                           "max_us": 0.0})
            s["calls"] += 1
            s["total_us"] += e["dur"]
            s["max_us"] = max(s["max_us"], e["dur"])
    for s in agg.values():
        s["avg_us"] = s["total_us"] / max(s["calls"], 1)
    key = {"total": "total_us", "calls": "calls", "max": "max_us",
           "ave": "avg_us"}.get(sorted_key, "total_us")
    out = SummaryDict(sorted(agg.items(), key=lambda kv: -kv[1][key]))
    out.truncated = truncated
    return out


# -- orchestration-overhead budget ------------------------------------------

class StepClock:
    """Per-step host wall-clock with an orchestration-overhead budget.

    The pipeline engines' contract (reference section_worker.cc:34's
    tight loop) is that HOST orchestration — schedule bookkeeping, jit
    dispatch, transfer setup — must not steal meaningful time from the
    device. This clock measures it: wrap each train step in `step()`,
    optionally feed the engine's per-tick host times via `add_ticks`,
    then `orchestration_fraction(device_compute_s)` reports what part of
    the median step the device compute estimate cannot account for
    (host wall time minus device compute time, as a fraction).

        clock = profiler.StepClock()
        for _ in range(n):
            with clock.step():
                engine.train_batch(x, y)
            clock.add_ticks(engine.last_tick_ms)
        frac = clock.orchestration_fraction(serial_compute_seconds)
        stats = clock.stats()   # step/tick p50 + p99 in ms
    """

    def __init__(self):
        self.steps_s: List[float] = []
        self.ticks_ms: List[float] = []

    @contextmanager
    def step(self):
        t0 = time.perf_counter()
        try:
            yield self
        finally:
            self.steps_s.append(time.perf_counter() - t0)

    def add_ticks(self, ticks_ms):
        self.ticks_ms.extend(float(t) for t in ticks_ms)

    @staticmethod
    def _pct(xs, q):
        if not xs:
            return -1.0
        ys = sorted(xs)
        idx = min(len(ys) - 1, max(0, int(round(q / 100.0
                                                * (len(ys) - 1)))))
        return ys[idx]

    def step_ms(self, q: float = 50.0) -> float:
        return self._pct(self.steps_s, q) * 1e3 if self.steps_s else -1.0

    def tick_ms(self, q: float = 50.0) -> float:
        return self._pct(self.ticks_ms, q)

    def orchestration_fraction(self, device_compute_s: float) -> float:
        """(median step wall time - device compute estimate) / wall —
        the upper bound on what host orchestration can steal from an
        ideal speedup. Clamped at 0 (an estimate above the measured
        wall means measurement noise, not negative overhead)."""
        if not self.steps_s:
            return -1.0
        wall = self._pct(self.steps_s, 50.0)
        if wall <= 0.0:
            return -1.0
        return max(0.0, (wall - float(device_compute_s)) / wall)

    def stats(self, device_compute_s: Optional[float] = None) -> dict:
        out = {
            "steps": len(self.steps_s),
            "step_ms_p50": round(self.step_ms(50), 3),
            "step_ms_p99": round(self.step_ms(99), 3),
        }
        if self.ticks_ms:
            out["tick_ms_p50"] = round(self.tick_ms(50), 4)
            out["tick_ms_p99"] = round(self.tick_ms(99), 4)
        if device_compute_s is not None:
            out["orchestration_fraction"] = round(
                self.orchestration_fraction(device_compute_s), 4)
        return out

    def publish(self, prefix: str = "train",
                device_compute_s: Optional[float] = None) -> dict:
        """Push this clock's stats into the observability registry as
        `<prefix>.<stat>` gauges (the step/tick percentiles become
        scrapeable next to the engines' own histograms)."""
        from ..observability import metrics as _metrics
        stats = self.stats(device_compute_s)
        for k, v in stats.items():
            _metrics.gauge(f"{prefix}.{k}").set(v)
        return stats


# -- device-side (XPlane) bridge --------------------------------------------

def start_trace(log_dir="/tmp/jax-trace"):
    """Start a jax/XLA device trace (CUPTI/device_tracer analogue —
    XPlane on TPU, viewable in TensorBoard or Perfetto)."""
    import jax
    jax.profiler.start_trace(log_dir)
    return log_dir


def stop_trace():
    import jax
    jax.profiler.stop_trace()
