"""paddle.distribution — probability distributions.

Capability parity with the reference module
(/root/reference/python/paddle/distribution.py:41 Distribution, :168
Uniform, :390 Normal, :640 Categorical): sample / log_prob / probs /
entropy / kl_divergence with the reference's broadcasting and shape
semantics (sample(shape) -> shape + batch_shape; float-only args
collapse the batch dims).

TPU-first redesign: all math is pure jnp over broadcasted arrays (one
fused XLA computation per method, no per-op graphs); sampling draws an
explicit splittable PRNG key from the framework generator
(core/generator.py), so every method is jit-traceable — a distribution
method used inside TrainStep/to_static composes with the program key
scope instead of mutating host RNG state.

Reference quirk kept for parity: Categorical.probs/log_prob treat the
constructor argument as *unnormalized probabilities* (normalized by the
sum, distribution.py:892), while entropy/kl_divergence treat it in log
space via softmax (distribution.py:827,:773). sample() draws from the
normalized probabilities.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from .core.generator import next_key
from .framework import Tensor, _unwrap

__all__ = ["Distribution", "Uniform", "Normal", "Categorical"]


def _as_array(x, dtype=None):
    a = _unwrap(x)
    a = jnp.asarray(a)
    if jnp.issubdtype(a.dtype, jnp.integer):
        a = a.astype(jnp.float32)
    if dtype is not None and a.dtype != dtype:
        a = a.astype(dtype)
    return a


def _key(seed: int):
    return jax.random.key(seed) if seed else next_key()


class Distribution:
    """Abstract base (reference distribution.py:41)."""

    def sample(self, shape, seed=0):
        raise NotImplementedError

    def entropy(self):
        raise NotImplementedError

    def kl_divergence(self, other):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def probs(self, value):
        raise NotImplementedError

    def _value(self, value, like):
        v = _as_array(value)
        if v.dtype != like.dtype:
            v = v.astype(like.dtype)
        return v


class Uniform(Distribution):
    """U(low, high); density 1/(high-low) on [low, high)
    (reference distribution.py:168)."""

    def __init__(self, low, high, name=None):
        self.name = name or "Uniform"
        self.all_arg_is_float = isinstance(low, (int, float)) and \
            isinstance(high, (int, float))
        self.low = _as_array(low)
        self.high = _as_array(high)
        dt = jnp.result_type(self.low.dtype, self.high.dtype)
        self.low, self.high = self.low.astype(dt), self.high.astype(dt)
        self.dtype = dt

    @property
    def _batch_shape(self):
        return jnp.broadcast_shapes(self.low.shape, self.high.shape)

    def sample(self, shape, seed=0):
        shape = tuple(int(s) for s in shape)
        out_shape = shape + self._batch_shape
        u = jax.random.uniform(_key(seed), out_shape, self.dtype)
        out = self.low + u * (self.high - self.low)
        if self.all_arg_is_float:
            out = out.reshape(shape)
        return Tensor(out)

    def log_prob(self, value):
        v = self._value(value, self.low)
        inside = ((self.low < v) & (v < self.high)).astype(v.dtype)
        return Tensor(jnp.log(inside) - jnp.log(self.high - self.low))

    def probs(self, value):
        v = self._value(value, self.low)
        inside = ((self.low < v) & (v < self.high)).astype(v.dtype)
        return Tensor(inside / (self.high - self.low))

    def entropy(self):
        return Tensor(jnp.log(self.high - self.low))


class Normal(Distribution):
    """N(loc, scale^2) (reference distribution.py:390)."""

    def __init__(self, loc, scale, name=None):
        self.name = name or "Normal"
        self.all_arg_is_float = isinstance(loc, (int, float)) and \
            isinstance(scale, (int, float))
        self.loc = _as_array(loc)
        self.scale = _as_array(scale)
        dt = jnp.result_type(self.loc.dtype, self.scale.dtype)
        self.loc, self.scale = self.loc.astype(dt), self.scale.astype(dt)
        self.dtype = dt

    @property
    def _batch_shape(self):
        return jnp.broadcast_shapes(self.loc.shape, self.scale.shape)

    def sample(self, shape, seed=0):
        shape = tuple(int(s) for s in shape)
        out_shape = shape + self._batch_shape
        n = jax.random.normal(_key(seed), out_shape, self.dtype)
        out = self.loc + n * self.scale
        if self.all_arg_is_float:
            out = out.reshape(shape)
        return Tensor(out)

    def entropy(self):
        b = jnp.broadcast_to(self.scale, self._batch_shape)
        return Tensor(0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(b))

    def log_prob(self, value):
        v = self._value(value, self.loc)
        var = self.scale * self.scale
        return Tensor(-((v - self.loc) ** 2) / (2.0 * var)
                      - jnp.log(self.scale)
                      - 0.5 * math.log(2.0 * math.pi))

    def probs(self, value):
        v = self._value(value, self.loc)
        var = self.scale * self.scale
        return Tensor(jnp.exp(-((v - self.loc) ** 2) / (2.0 * var))
                      / (math.sqrt(2 * math.pi) * self.scale))

    def kl_divergence(self, other):
        """KL(self || other) for two Normals (distribution.py:595)."""
        var_ratio = (self.scale / other.scale) ** 2
        t1 = ((self.loc - other.loc) / other.scale) ** 2
        return Tensor(0.5 * (var_ratio + t1 - 1.0 - jnp.log(var_ratio)))


class Categorical(Distribution):
    """Categorical over the last axis (reference distribution.py:640)."""

    def __init__(self, logits, name=None):
        self.name = name or "Categorical"
        self.logits = _as_array(logits)
        self.dtype = self.logits.dtype

    def sample(self, shape, seed=0):
        shape = tuple(int(s) for s in shape)
        num = int(np.prod(shape)) if shape else 1
        logits = self.logits
        # sample() consumes the constructor arg as UNNORMALIZED
        # PROBABILITIES (reference quirk: entropy/kl treat the same arg
        # in log space) — a negative weight here is meaningless and the
        # reference's multinomial kernel errors on it; silently clamping
        # diverged from probs() (ADVICE r3). Only at sample time:
        # log-space construction for entropy/kl stays valid. Traced
        # logits (inside jit) can't be validated.
        try:
            if bool(jnp.any(logits < 0)):
                raise ValueError(
                    "Categorical.sample needs non-negative weights "
                    "(the constructor arg is unnormalized "
                    "probabilities for sampling, not log-probs)")
        except jax.errors.TracerBoolConversionError:
            pass
        batch = logits.shape[:-1]
        # sample indices with replacement from the normalized weights
        lg = jnp.log(jnp.maximum(logits, 1e-30))
        idx = jax.random.categorical(_key(seed), lg, axis=-1,
                                     shape=(num,) + batch)
        return Tensor(idx.reshape(shape + batch).astype(jnp.int64))

    def _softmax_logits(self, logits):
        z = logits - jnp.max(logits, axis=-1, keepdims=True)
        return z, jnp.sum(jnp.exp(z), axis=-1, keepdims=True)

    def entropy(self):
        z, denom = self._softmax_logits(self.logits)
        prob = jnp.exp(z) / denom
        neg = jnp.sum(prob * (z - jnp.log(denom)), axis=-1, keepdims=True)
        return Tensor(-neg)

    def kl_divergence(self, other):
        z, denom = self._softmax_logits(self.logits)
        oz, odenom = self._softmax_logits(other.logits)
        prob = jnp.exp(z) / denom
        kl = jnp.sum(
            prob * (z - jnp.log(denom) - oz + jnp.log(odenom)),
            axis=-1, keepdims=True)
        return Tensor(kl)

    def probs(self, value):
        # reference parity: constructor arg as unnormalized probabilities
        w = self.logits / jnp.sum(self.logits, axis=-1, keepdims=True)
        idx = jnp.asarray(_unwrap(value)).astype(jnp.int32)
        if w.ndim == 1:
            return Tensor(w[idx.reshape(-1)].reshape(idx.shape))
        batch = w.shape[:-1]
        if idx.ndim == 1:
            idx = jnp.broadcast_to(idx, batch[:-1] + (1,) + idx.shape[-1:]) \
                if len(batch) > 1 else jnp.broadcast_to(
                    idx[None], (batch[0], idx.shape[0]))
        if idx.shape[:-1] != batch:
            raise ValueError(
                f"shape of value {list(idx.shape[:-1])} must match shape "
                f"of logits {list(batch)}")
        return Tensor(jnp.take_along_axis(w, idx, axis=-1))

    def log_prob(self, value):
        return Tensor(jnp.log(self.probs(value)._data))
