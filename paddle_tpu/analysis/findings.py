"""Findings + baselines: graph_lint's structured output contract.

Every rule emits ``Finding`` records — severity, a ``path:op`` location
(the HLO metadata op_name path or jax arg path, then the opcode), and a
message naming the hazard and the bytes at stake. Findings fingerprint
deterministically so a **baseline** file can pin the currently-accepted
set: CI gates on *new* findings only (the RecompileSentinel/tpu_doctor
philosophy applied pre-launch — an auditor that cries on day-one debt
gets turned off; one that catches regressions gets trusted).

Baseline semantics (DESIGN.md "Static analysis"):
- a baseline maps fingerprint -> human-readable summary, so the file is
  reviewable in a PR diff (an opaque hash list hides what was waived);
- ``new_findings(findings, baseline)`` filters to fingerprints absent
  from the baseline — those gate (exit 1);
- re-anchor deliberately with ``--write-baseline`` after triaging, the
  same flow as tier1_budget's rebalance policy.

This module imports no jax: the source-lint pass and the repo_lint CLI
must run without paying a backend import.
"""
from __future__ import annotations

import hashlib
import json
import os
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Set

__all__ = [
    "Finding", "fingerprint", "load_baseline", "write_baseline",
    "new_findings", "format_findings", "exit_code",
]

SEVERITIES = ("error", "warning", "info")


@dataclass(frozen=True)
class Finding:
    """One rule violation at one location.

    ``location`` follows the ``path:op`` convention: the most precise
    stable path available (HLO metadata op_name, jax argument path,
    ``axis`` stream, or ``file:line`` for source findings), a colon,
    then the op (HLO opcode, ``parameter``, collective op name, or the
    lint check name)."""
    rule: str
    severity: str
    location: str
    message: str
    program: str = ""

    def fingerprint(self) -> str:
        return fingerprint(self.rule, self.program, self.location)

    def summary(self) -> str:
        prog = f"[{self.program}] " if self.program else ""
        return (f"{self.severity.upper():<7} {self.rule:<22} {prog}"
                f"{self.location}: {self.message}")


def fingerprint(rule: str, program: str, location: str) -> str:
    """Stable identity of a finding for baseline membership. Deliberately
    excludes the message: byte counts and instruction suffixes may drift
    with compiler versions while the (rule, program, location) triple
    names the same accepted hazard."""
    raw = "|".join((rule, program, location))
    return hashlib.sha1(raw.encode()).hexdigest()[:16]


def load_baseline(path: str) -> Set[str]:
    """Fingerprints accepted by a baseline file (empty set when the
    file does not exist — a missing baseline means everything is
    new)."""
    if not path or not os.path.exists(path):
        return set()
    with open(path) as f:
        data = json.load(f)
    return set(data.get("fingerprints", {}))


def write_baseline(findings: Iterable[Finding], path: str) -> dict:
    """Re-anchor: accept the current findings. The file keeps a human
    summary per fingerprint so the waiver is reviewable in diffs."""
    data = {
        "version": 1,
        "fingerprints": {
            f.fingerprint(): f.summary() for f in findings
        },
    }
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as fh:
        json.dump(data, fh, indent=2, sort_keys=True)
        fh.write("\n")
    os.replace(tmp, path)
    return data


def new_findings(findings: Iterable[Finding],
                 baseline: Optional[Set[str]] = None) -> List[Finding]:
    """The findings that gate: everything not waived by the baseline."""
    base = baseline or set()
    return [f for f in findings if f.fingerprint() not in base]


def format_findings(findings: Iterable[Finding],
                    baseline: Optional[Set[str]] = None) -> str:
    base = baseline or set()
    lines = []
    for f in findings:
        tag = "  (baselined)" if f.fingerprint() in base else ""
        lines.append(f.summary() + tag)
    return "\n".join(lines)


def exit_code(findings: Iterable[Finding],
              baseline: Optional[Set[str]] = None) -> int:
    """CI contract: exit 1 iff any NEW finding (any severity — a rule
    that should not gate belongs in the baseline or a config
    threshold, not in a severity loophole)."""
    return 1 if new_findings(findings, baseline) else 0
