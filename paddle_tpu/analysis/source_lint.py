"""Source pass: observability calls must gate on ``_obs._enabled``.

The recurring PR 4/PR 5 review lesson, now enforced instead of
re-learned: a metrics-instrument call (``_obs.counter(...)``,
``metrics.gauge(...)``, ``_obs.histogram(...)``) builds label dicts and
formats names BEFORE the registry's internal gate can reject the work —
on the eager-dispatch and collective hot paths that is real per-call
cost. Every call site in ``paddle_tpu/`` must therefore either:

- sit under an ``if <alias>._enabled`` guard (any ancestor ``if`` /
  conditional expression whose test reads an ``_enabled`` attribute or
  calls ``enabled()``, or a preceding early-return guard in the same
  function — collective._record's shape), or
- declare itself always-on at the call site with ``_always=True``
  (cold-path exporters, contract counters like
  ``train_recompiles_total`` — an explicit, reviewable opt-out), or
- appear in ``ALLOWLIST`` with a reason.

This is an AST pass, not a grep: aliases are resolved from imports, so
``from ..observability import metrics as _obs`` and
``from . import metrics`` are both covered, and a call inside a guarded
helper is distinguished from an unguarded one. Findings use the shared
``Finding`` shape (rule ``obs-gate``, location ``file:line``), so the
graph_lint CLI can run this as its "source" pass and tools/repo_lint.py
stays a thin shim. Imports no jax.
"""
from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Set, Tuple

from .findings import Finding

__all__ = ["ALLOWLIST", "lint_source", "lint_file", "lint_package"]

_INSTRUMENTS = {"counter", "gauge", "histogram"}

# "<relpath>::<qualified fn>" -> reason. The two legitimate ungated
# call sites: explicit PUBLISH surfaces, where the user's call is
# itself the opt-in and the registry's internal gate still applies —
# cold paths by contract (a rollup per report, not per step).
ALLOWLIST: Dict[str, str] = {
    "paddle_tpu/observability/mfu.py::ThroughputMeter.report":
        "explicit publish surface: one gauge rollup per report() call "
        "(bench/CLI cadence), never on the step hot path",
    "paddle_tpu/profiler/__init__.py::StepClock.publish":
        "explicit publish surface: pushes clock stats once when the "
        "caller asks; pipeline_bench cadence, not per tick",
}


def _attr_src(node: ast.AST) -> str:
    """Best-effort dotted-source rendering for guard tests."""
    try:
        return ast.unparse(node)
    except Exception:  # pragma: no cover — very old ast
        return ""


def _mentions_gate(test: ast.AST,
                   gate_vars: Optional[Set[str]] = None) -> bool:
    """Does an if/while/conditional test read an ``_enabled``
    attribute, call ``enabled()``, or read a local bool previously
    assigned from one (the ``_rec = _obs._enabled`` idiom the engines
    use to read the gate once per step)?"""
    vars_ = gate_vars or set()
    for sub in ast.walk(test):
        if isinstance(sub, ast.Attribute) and sub.attr in (
                "_enabled", "enabled"):
            return True
        if isinstance(sub, ast.Name) and (
                sub.id == "_enabled" or sub.id in vars_):
            return True
        if isinstance(sub, ast.Call):
            fn = sub.func
            if isinstance(fn, ast.Name) and fn.id == "enabled":
                return True
            if isinstance(fn, ast.Attribute) and fn.attr == "enabled":
                return True
    return False


def _gate_var_targets(stmt: ast.AST) -> Set[str]:
    """Names bound by an assignment whose value reads a gate
    (``_rec = _obs._enabled`` / ``a, b = x._enabled, y._enabled``)."""
    if not isinstance(stmt, ast.Assign) or not _mentions_gate(
            stmt.value):
        return set()
    out: Set[str] = set()
    for tgt in stmt.targets:
        for sub in ast.walk(tgt):
            if isinstance(sub, ast.Name):
                out.add(sub.id)
    return out


def _metric_aliases(tree: ast.Module) -> Set[str]:
    """Names this module binds to the observability metrics module."""
    aliases: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            mod = node.module or ""
            for a in node.names:
                if a.name == "metrics" and (
                        "observability" in mod or node.level > 0
                        or mod == ""):
                    aliases.add(a.asname or a.name)
        elif isinstance(node, ast.Import):
            for a in node.names:
                if a.name.endswith("observability.metrics"):
                    aliases.add(a.asname or a.name.split(".")[0])
    return aliases


def _qualname_of(stack: List[ast.AST]) -> str:
    parts = [n.name for n in stack
             if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef))]
    return ".".join(parts) or "<module>"


def _has_always_kw(call: ast.Call) -> bool:
    for kw in call.keywords:
        if kw.arg == "_always":
            # any non-False value counts (literal True is the idiom);
            # a computed value is an explicit decision either way
            return not (isinstance(kw.value, ast.Constant)
                        and kw.value.value is False)
    return False


def _guarded(stack: List[ast.AST], call: ast.Call) -> bool:
    """Ancestor if/ifexp/while gate, or a preceding early-return gate
    (``if not ..._enabled...: return``) in the nearest function. Local
    bools assigned from a gate read earlier in that function count as
    gates (``_rec = _obs._enabled; ... if _rec:``)."""
    # collect gate-vars bound before the call in the nearest function
    gate_vars: Set[str] = set()
    for anc in reversed(stack):
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for stmt in ast.walk(anc):
                if getattr(stmt, "lineno", call.lineno) < call.lineno:
                    gate_vars |= _gate_var_targets(stmt)
            break
    for anc in reversed(stack):
        if isinstance(anc, (ast.If, ast.IfExp, ast.While)) and \
                _mentions_gate(anc.test, gate_vars):
            return True
        if isinstance(anc, ast.BoolOp):
            if any(_mentions_gate(v, gate_vars) for v in anc.values):
                return True
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for stmt in anc.body:
                if stmt.lineno >= call.lineno:
                    break
                if isinstance(stmt, ast.If) and \
                        _mentions_gate(stmt.test, gate_vars) and any(
                            isinstance(s, (ast.Return, ast.Raise))
                            for s in ast.walk(stmt)):
                    return True
            return False  # nearest function decides
    return False


def lint_source(text: str, relpath: str,
                allowlist: Optional[Dict[str, str]] = None
                ) -> List[Finding]:
    """Lint one module's source text; ``relpath`` names it in findings
    and allowlist keys (posix-style, repo-relative)."""
    allow = ALLOWLIST if allowlist is None else allowlist
    try:
        tree = ast.parse(text)
    except SyntaxError as e:  # a broken file is its own finding
        return [Finding(
            rule="obs-gate", severity="error",
            location=f"{relpath}:{e.lineno or 0}",
            message=f"unparseable python: {e.msg}")]
    aliases = _metric_aliases(tree)
    if not aliases:
        return []
    findings: List[Finding] = []
    stack: List[ast.AST] = []

    def visit(node: ast.AST):
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr in _INSTRUMENTS and \
                isinstance(node.func.value, ast.Name) and \
                node.func.value.id in aliases:
            if not _has_always_kw(node) and not _guarded(stack, node):
                qual = _qualname_of(stack)
                key = f"{relpath}::{qual}"
                if key not in allow:
                    findings.append(Finding(
                        rule="obs-gate", severity="error",
                        location=f"{relpath}:{node.lineno}",
                        message=(
                            f"{_attr_src(node.func)}() in {qual} runs "
                            "ungated: wrap in `if "
                            f"{node.func.value.id}._enabled:` (hot "
                            "path) or pass `_always=True` (deliberate "
                            "always-on contract counter) — the PR 4/5 "
                            "telemetry-cost lesson, enforced")))
        stack.append(node)
        for child in ast.iter_child_nodes(node):
            visit(child)
        stack.pop()

    visit(tree)
    return findings


def lint_file(path: str, root: Optional[str] = None,
              allowlist: Optional[Dict[str, str]] = None
              ) -> List[Finding]:
    rel = os.path.relpath(path, root).replace(os.sep, "/") if root \
        else os.path.basename(path)
    with open(path, encoding="utf-8") as f:
        return lint_source(f.read(), rel, allowlist)


def lint_package(package_dir: Optional[str] = None,
                 allowlist: Optional[Dict[str, str]] = None
                 ) -> List[Finding]:
    """Lint every .py under paddle_tpu/ (or an explicit directory).
    Returns findings sorted by location for stable output."""
    if package_dir is None:
        package_dir = os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))
    root = os.path.dirname(os.path.abspath(package_dir))
    findings: List[Finding] = []
    for dirpath, dirnames, filenames in os.walk(package_dir):
        dirnames[:] = sorted(d for d in dirnames
                             if d != "__pycache__")
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                findings.extend(lint_file(
                    os.path.join(dirpath, fn), root, allowlist))
    findings.sort(key=lambda f: f.location)
    from .engine import publish_findings
    publish_findings(findings, rules_evaluated=("obs-gate",))
    return findings
