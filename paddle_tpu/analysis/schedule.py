"""Collective-schedule extraction + cross-rank/stage verification.

The deadliest pod failure is also the simplest: one rank's traced
program issues a different collective sequence than its peers — a
python branch on ``rank``, a stage that skips a sync, a bucket layout
that diverged — and the fleet deadlocks at runtime with every rank
blocked in a different collective. PR 4's flight recorder catches this
*post-mortem* (per-(axis, op) seq tables diffed by tpu_doctor); this
module catches it **pre-launch**: collectives are counted at TRACE
time (collective._record's documented counting — in-trace collectives
count once per trace, which IS the per-program collective inventory in
program order), so capturing during ``lower()`` yields the exact
static schedule the executable will replay, before anything is
dispatched.

Contract shared with the flight recorder (DESIGN.md "Static
analysis"): entries are stamped with the same monotonically increasing
per-(axis, op) sequence numbers the recorder emits at runtime — a lint
finding ``allreduce_sum@dp seq 3 missing on rank1`` names the same
event tpu_doctor would have named after the hang.

Capture is a context manager arming ``collective._schedule_capture``;
everything routed through ``collective._record`` lands in it —
collective.py's public ops, comm.py's fused/quantized buckets (with
algo/compress/elements meta), and the spmd_1f1b ring ppermutes.
"""
from __future__ import annotations

from contextlib import contextmanager
from typing import Any, Callable, Dict, List, Optional, Tuple

from .findings import Finding

__all__ = [
    "capture_collective_schedule", "schedule_of", "assign_seqs",
    "verify_collective_schedules",
]


@contextmanager
def capture_collective_schedule():
    """Arm the trace-time capture; yields the (live) entry list.

    Use around anything that traces/lowers a program::

        with capture_collective_schedule() as entries:
            engine.aot_lower_train(x, y)

    Nesting-safe (the previous capture list is restored); entries are
    finalized with per-(axis, op) seq numbers on exit."""
    from ..distributed import collective as _coll

    entries: List[dict] = []
    prev = _coll._schedule_capture
    _coll._schedule_capture = entries
    try:
        yield entries
    finally:
        _coll._schedule_capture = prev
        entries[:] = assign_seqs(entries)


def schedule_of(thunk: Callable[[], Any]) -> List[dict]:
    """Capture the collective schedule a thunk traces (the thunk's
    return value is discarded — lower, don't run)."""
    with capture_collective_schedule() as entries:
        thunk()
    return list(entries)


def assign_seqs(entries: List[dict]) -> List[dict]:
    """Stamp the flight recorder's seq convention: per-(axis, op)
    counters starting at 1, in capture order (idempotent)."""
    counters: Dict[Tuple[Optional[str], str], int] = {}
    out = []
    for e in entries:
        key = (e.get("axis"), e["op"])
        counters[key] = counters.get(key, 0) + 1
        e = dict(e)
        e["seq"] = counters[key]
        out.append(e)
    return out


def _sig(e: dict) -> tuple:
    """The static signature two ranks must agree on: (axis, op, shapes,
    dtypes, bytes) — plus the fused-collective meta (elements) when
    present, so a diverged bucket layout with equal wire bytes still
    mismatches."""
    meta = e.get("meta") or {}
    return (e.get("axis"), e["op"],
            tuple(tuple(s) for s in e.get("shapes", ())),
            tuple(e.get("dtypes", ())), e.get("bytes"),
            meta.get("elements"))


def _sig_str(e: dict) -> str:
    shapes = ",".join("x".join(map(str, s))
                      for s in e.get("shapes", ())) or "-"
    return (f"{e['op']}@{e.get('axis') or 'replica'} "
            f"seq={e.get('seq', '?')} shapes={shapes} "
            f"dtypes={','.join(e.get('dtypes', ())) or '-'} "
            f"bytes={e.get('bytes')}")


def verify_collective_schedules(
        schedules: Dict[str, List[dict]]) -> List[Finding]:
    """Prove all ranks/stages issue MATCHING static collective
    sequences; name the divergent program and the (axis, op, seq)
    where it splits — tpu_doctor's divergence diff, pre-launch.

    The reference sequence is the majority (programs grouped by full
    signature stream; largest group wins, first name breaks ties).
    Findings, most-specific first:

    - a program missing collectives on an (axis, op) stream (the
      deadlock: peers block in ``seq N`` it never issues);
    - extra collectives on a stream (same deadlock, other side);
    - equal counts but a signature/order mismatch at position i.
    """
    names = sorted(schedules)
    if len(names) < 2:
        return []
    streams = {n: [_sig(e) for e in assign_seqs(list(schedules[n]))]
               for n in names}
    groups: Dict[tuple, List[str]] = {}
    for n in names:
        groups.setdefault(tuple(streams[n]), []).append(n)
    if len(groups) == 1:
        return []
    ref_members = max(groups.values(),
                      key=lambda ms: (len(ms), ms[0] == names[0]))
    ref_name = ref_members[0]
    ref = schedules[ref_name]
    ref_entries = assign_seqs(list(ref))
    findings: List[Finding] = []
    for n in names:
        if n in ref_members:
            continue
        mine = assign_seqs(list(schedules[n]))
        # per-(axis, op) stream counts first: a MISSING collective is
        # the headline (that is the hang), order skew second
        ref_counts: Dict[Tuple[Optional[str], str], int] = {}
        for e in ref_entries:
            k = (e.get("axis"), e["op"])
            ref_counts[k] = ref_counts.get(k, 0) + 1
        my_counts: Dict[Tuple[Optional[str], str], int] = {}
        for e in mine:
            k = (e.get("axis"), e["op"])
            my_counts[k] = my_counts.get(k, 0) + 1
        # first position where the raw streams stop agreeing — the
        # earliest call peers and this rank no longer line up on
        # (which statically-identical calls were skipped is
        # undecidable from counts alone, so the message reports the
        # seq-table REACH per stream — the tpu_doctor diff — plus
        # this position, never a guessed tail range)
        my_stream = streams[n]
        ref_stream = [_sig(e) for e in ref_entries]
        first_div = next(
            (i for i, (a, b) in enumerate(zip(ref_stream, my_stream))
             if a != b), min(len(ref_stream), len(my_stream)))
        count_diff = False
        for k in sorted(set(ref_counts) | set(my_counts),
                        key=lambda kk: (kk[0] or "", kk[1])):
            axis, op = k
            r, m = ref_counts.get(k, 0), my_counts.get(k, 0)
            if r == m:
                continue
            count_diff = True
            loc = f"{axis or 'replica'}:{op}"
            if m < r:
                findings.append(Finding(
                    rule="collective-schedule", severity="error",
                    location=loc, program=n,
                    message=(f"{op} seq on axis {axis or 'replica'} "
                             f"reaches {m} on this rank vs {r} on "
                             f"the fleet majority "
                             f"({len(ref_members)} program(s), e.g. "
                             f"{ref_name}) — {r - m} collective(s) "
                             "missing from this rank's stream (first "
                             f"schedule divergence at position "
                             f"{first_div + 1}); peers would "
                             "deadlock waiting")))
            else:
                findings.append(Finding(
                    rule="collective-schedule", severity="error",
                    location=loc, program=n,
                    message=(f"{op} seq on axis {axis or 'replica'} "
                             f"reaches {m} on this rank vs {r} on "
                             f"the fleet majority — {m - r} "
                             "collective(s) have no peer (first "
                             f"schedule divergence at position "
                             f"{first_div + 1}); this rank would "
                             "deadlock waiting")))
        if count_diff:
            continue
        # counts agree: first position whose signature differs
        for i, (re_, me) in enumerate(zip(ref_entries, mine)):
            if _sig(re_) == _sig(me):
                continue
            findings.append(Finding(
                rule="collective-schedule", severity="error",
                location=f"{me.get('axis') or 'replica'}:{me['op']}",
                program=n,
                message=(f"collective sequence diverges from "
                         f"{ref_name} at position {i + 1}: expected "
                         f"{_sig_str(re_)}, got {_sig_str(me)} — "
                         "mismatched payloads corrupt silently when "
                         "they do not deadlock")))
            break
    # counters ride the same always-on series as the per-program rules
    from .engine import publish_findings
    publish_findings(findings, rules_evaluated=("collective-schedule",))
    return findings
