"""paddle_tpu.analysis — graph_lint, the pre-launch program auditor.

Proves the fused train/serve programs safe *before* they run: one
metadata-preserving lowering (anatomy's compile_uncached discipline),
pluggable passes over the optimized HLO and the trace-time collective
schedule, structured findings with severity + ``path:op`` locations,
and baseline files so CI gates on NEW findings only.

Surfaces: ``tools/graph_lint.py`` (CLI, exit 1 on new findings),
``tools/repo_lint.py`` (the source pass standalone), always-on
``lint.findings_total{rule=}`` counters through the PR 3 exporters.
DESIGN.md "Static analysis" documents the rules table and the
seq-extraction contract shared with the flight recorder.
"""
from .findings import (Finding, exit_code, fingerprint, format_findings,
                       load_baseline, new_findings, write_baseline)
from .engine import (GraphLintConfig, HloInstr, ProgramAudit,
                     iter_hlo_instructions, publish_findings,
                     registered_rules, rule, run_rules)
from . import hlo_rules  # noqa: F401  (registers the launch rules)
from .hlo_rules import LAUNCH_RULES
from .memory_baseline import (check_memory_baseline,
                              load_memory_baseline, peaks_of,
                              write_memory_baseline)
from .perf_ledger import (check_record, load_ledger,
                          load_ledger_baseline, record_from_artifact,
                          record_from_report, render_trend,
                          write_ledger_baseline)
from .schedule import (assign_seqs, capture_collective_schedule,
                       schedule_of, verify_collective_schedules)
from .source_lint import ALLOWLIST, lint_package, lint_source

__all__ = [
    "Finding", "GraphLintConfig", "HloInstr", "ProgramAudit",
    "LAUNCH_RULES", "ALLOWLIST",
    "iter_hlo_instructions", "rule", "registered_rules", "run_rules",
    "publish_findings", "fingerprint", "load_baseline",
    "write_baseline", "new_findings", "format_findings", "exit_code",
    "assign_seqs", "capture_collective_schedule", "schedule_of",
    "verify_collective_schedules", "lint_package", "lint_source",
    "peaks_of", "load_memory_baseline", "write_memory_baseline",
    "check_memory_baseline",
    "record_from_report", "record_from_artifact", "load_ledger",
    "load_ledger_baseline", "write_ledger_baseline", "check_record",
    "render_trend",
]
