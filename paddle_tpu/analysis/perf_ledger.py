"""Cross-run perf ledger: CI-gate performance the way graph_lint gates
new findings and memory_baseline gates peak growth.

Five rounds of checked-in ``BENCH_r0*.json``/``MULTICHIP_r0*.json``
receipts fed no trend and no gate — a PR that halved sustained
tokens/s or doubled p99 TTFT shipped as long as the suite stayed
green. TVM's lesson (PAPERS.md) is that measurement-driven
optimization only works on trustworthy LONGITUDINAL data; this module
is that data's home:

- the LEDGER is an append-only JSONL file: one record per
  ``emit_report``-shaped receipt (bench.py, tools/serving_bench.py,
  the multichip probe), carrying every numeric leaf of the report
  flattened to dotted keys, keyed by a PROGRAM/CONFIG FINGERPRINT
  (metric name + platform + model size + devices) so a CPU smoke
  never diffs against a TPU window and an ERNIE-base run never diffs
  against ERNIE-large;
- the BASELINE generalizes ``memory_baseline`` from one quantity
  (peak bytes, lower-better) to EVERY gateable receipt metric, each
  with a DIRECTION and TOLERANCE:
    higher-better  tokens/s, images/s, goodput productive fraction,
                   MFU — regress when cur < base × (1 − tol)
    lower-better   p99 TTFT, wire bytes, step ms — regress when
                   cur > base × (1 + tol)
    exact-better   compile/recompile/executable counts, rc — any
                   drift regresses (these are CONTRACTS, not
                   measurements: one extra executable is a retrace
                   bug regardless of magnitude)
  improvement never gates; re-anchor deliberately with
  ``--write-baseline`` (captures improvements, same flow as
  memory_anatomy);
- findings ride the shared ``findings.py`` machinery: rule
  ``perf_ledger``, location ``<fingerprint>:<metric>``, message
  naming the METRIC, the RUN and the DELTA — the CI log tells the
  author what regressed without opening an artifact.

Direction/tolerance resolution: ``spec_for(key)`` matches the key
against ``SPECS`` (ordered, first match wins, ``fnmatch`` patterns);
keys with no spec are LEDGERED but never GATED (context, not
contract). The baseline stores the resolved direction+tolerance per
metric so ``--check`` on a triage host is self-contained.

This module imports no jax — ingest/check/trend all run from JSON
artifacts anywhere (the memory_baseline discipline).
"""
from __future__ import annotations

import fnmatch
import hashlib
import json
import os
from typing import Any, Dict, List, Mapping, Optional

from .findings import Finding

__all__ = [
    "RULE", "DEFAULT_TOLERANCE", "SPECS", "spec_for",
    "flatten_numeric", "fingerprint_of", "record_from_report",
    "record_from_artifact", "append_record", "load_ledger",
    "latest_by_fingerprint", "check_record", "write_ledger_baseline",
    "load_ledger_baseline", "trend", "render_trend",
    "check_calibration", "CALIBRATION_LABEL",
]

RULE = "perf_ledger"
DEFAULT_TOLERANCE = 0.25

# (pattern, direction, tolerance-override[, abs-tolerance]). First
# match wins; None tolerance inherits the baseline default. Exact
# specs carry no tolerance by definition. Patterns are fnmatch over
# the dotted key. A 4th element (PR 18) switches the bound from
# relative to ABSOLUTE: prediction errors live in [0, 1) where a
# relative bar is meaningless near a perfect (≈0) baseline — a 0.002
# error tripling to 0.006 is not drift, an error growing by +0.10
# absolute is.
SPECS = (
    # contracts first — counts where ANY drift is a bug
    ("*recompile*", "exact", None),
    ("*compiles", "exact", None),
    ("*executables", "exact", None),
    ("*buckets", "exact", None),
    # cost-model truth plane contracts: the calibration identity must
    # keep matching (0 = stale table → analytic fallback: a drift
    # event, not a quiet degradation) and every audit plane must keep
    # joining (a dropped join would otherwise shrink the error SUM and
    # read as an improvement)
    ("*calibration.match", "exact", None),
    ("*calibration.used_calibrated", "exact", None),
    ("*metrics_joined", "exact", None),
    # prediction errors gate with ABSOLUTE tolerance, lower-better.
    # step-time error is wall-clock noisy on shared CPU (±30%
    # sandbox swings feed straight into |pred-meas|); hbm/wire join
    # deterministic planes so their bars are tight. Listed BEFORE the
    # traffic group: *wire_bytes* would otherwise shadow
    # prediction_error.wire_bytes with a relative bar.
    ("*prediction_error.step_time", "lower", None, 0.50),
    ("*prediction_error*", "lower", None, 0.10),
    # rc is not an ordinal measurement: 0 is the only good value.
    # lower-better @ tolerance 0 means an rc=1 baseline (a round whose
    # receipt parse failed) lets a LATER rc=0 run pass — "exact" would
    # gate the recovery as a regression
    ("rc", "lower", 0.0),
    # throughput (higher is better). Tolerances sized to the observed
    # sandbox round-to-round variance (CHANGES.md records ±25-30% CPU
    # swings on identical code); hardware rounds are steadier and an
    # operator can tighten with --tolerance
    ("*tokens_per_sec*", "higher", 0.35),
    ("*tokens_per_s", "higher", 0.35),
    ("*images_per_sec*", "higher", 0.35),
    ("*rows_per_sec*", "higher", 0.35),
    ("*examples_per_sec*", "higher", 0.35),
    ("value", "higher", 0.35),          # the headline metric line
    ("*mfu", "higher", 0.35),
    ("*goodput.productive_fraction", "higher", None),
    ("*speedup*", "higher", 0.35),
    ("*overlap_fraction", "higher", None),
    # latency / traffic (lower is better). Tail percentiles of
    # sub-ms CPU timers are the noisiest series the receipts carry
    # (r05: decode p99 18× its p50) — wide bars, still catching a
    # real order-of-magnitude regression
    ("*ttft_ms.p99", "lower", 0.75),
    ("*ttft_ms.p50", "lower", 0.50),
    ("*_ms.p99", "lower", 0.75),
    ("*_ms.p50", "lower", 0.50),
    ("*_ms_p99", "lower", 0.75),
    ("*_ms_p50", "lower", 0.50),
    ("*wire_bytes*", "lower", None),
    ("*overhead_us", "lower", 0.50),
    ("*peak_bytes", "lower", None),
)


def spec_for(key: str) -> Optional[dict]:
    """Direction/tolerance spec for a metric key, or None when the key
    is context-only (ledgered, never gated)."""
    for spec in SPECS:
        pat, direction, tol = spec[0], spec[1], spec[2]
        if fnmatch.fnmatch(key, pat):
            out = {"direction": direction}
            if tol is not None:
                out["tolerance"] = float(tol)
            if len(spec) > 3 and spec[3] is not None:
                out["abs_tolerance"] = float(spec[3])
            return out
    return None


# -- records -------------------------------------------------------------------

def flatten_numeric(doc: Any, parent: str = "") -> Dict[str, float]:
    """Numeric leaves only, dotted keys (bools excluded — `ok` flags
    are not measurements; `rc` style ints are)."""
    out: Dict[str, float] = {}
    if isinstance(doc, Mapping):
        for k, v in doc.items():
            key = f"{parent}.{k}" if parent else str(k)
            out.update(flatten_numeric(v, key))
    elif isinstance(doc, (int, float)) and not isinstance(doc, bool):
        out[parent] = float(doc)
    return out


_FP_FIELDS = ("metric", "unit", "kind")
_FP_EXTRAS = ("platform", "model_params", "n_devices", "replicas")


def fingerprint_of(report: Mapping) -> str:
    """Program/config identity: runs compare only within the same
    fingerprint. Built from the metric NAME (bench already encodes
    model size + platform class in it), unit, platform, model size and
    device count — message/value drift can't bust it (the findings.py
    fingerprint lesson)."""
    extras = report.get("extras") or {}
    ident = {f: report.get(f) for f in _FP_FIELDS
             if report.get(f) is not None}
    for f in _FP_EXTRAS:
        v = report.get(f, extras.get(f))
        if v is not None:
            ident[f] = v
    blob = json.dumps(ident, sort_keys=True)
    return hashlib.sha1(blob.encode("utf-8")).hexdigest()[:16]


def record_from_report(report: Mapping, source: str = "bench",
                       run: Optional[str] = None,
                       round_n: Optional[int] = None,
                       ts: Optional[float] = None) -> dict:
    """One ledger record from an emit_report-shaped receipt dict."""
    label = str(report.get("metric") or report.get("kind") or source)
    return {
        "version": 1,
        "run": run or (f"{source}-r{round_n:02d}"
                       if round_n is not None else source),
        "source": source,
        "round": round_n,
        "ts": ts,
        "fingerprint": fingerprint_of(report),
        "label": label,
        "metrics": flatten_numeric(report),
    }


def record_from_artifact(doc: Mapping, source: str,
                         run: Optional[str] = None,
                         ts: Optional[float] = None,
                         round_n: Optional[int] = None
                         ) -> Optional[dict]:
    """Ledger record from a checked-in artifact in any of the shapes
    the repo accumulates:

    - driver wrapper ``{"n", "rc", "parsed": {report}}`` (BENCH_r0*):
      the parsed report is the record, the wrapper's round/rc ride
      along (a round whose parse FAILED still ledgers rc — the
      failure is part of the trajectory);
    - multichip probe ``{"n_devices", "rc", "ok", ...}``
      (MULTICHIP_r0*): rc/n_devices under a 'multichip' fingerprint;
    - a raw emit_report dict (``{"metric", "value", ...}``).
    Returns None for artifacts with nothing numeric to ledger.
    ``round_n`` is the caller's fallback (e.g. parsed from the
    filename) for artifacts without an embedded round — a round-less
    record orders by ts alone, and mtime is NOT stable across
    checkouts, so the gate could pick the wrong 'latest' run."""
    if isinstance(doc.get("n"), int):
        round_n = doc["n"]
    if "parsed" in doc or "tail" in doc and "cmd" in doc:
        parsed = doc.get("parsed")
        if isinstance(parsed, dict) and parsed.get("metric"):
            rec = record_from_report(parsed, source=source, run=run,
                                     round_n=round_n, ts=ts)
            if isinstance(doc.get("rc"), int):
                rec["metrics"]["rc"] = float(doc["rc"])
            return rec
        if isinstance(doc.get("rc"), int):
            # a failed round: rc is the only signal, but a trajectory
            # with a hole labeled "rc=1" beats a silent gap
            rep = {"metric": f"{source}_rc_only", "unit": "rc",
                   "rc": doc["rc"]}
            return record_from_report(rep, source=source, run=run,
                                      round_n=round_n, ts=ts)
        return None
    if doc.get("metric") or doc.get("kind"):
        # emit_report receipts first: n_devices is a fingerprint
        # FIELD on these (planner_bench carries it at top level), not
        # the multichip-probe discriminator
        return record_from_report(doc, source=source, run=run,
                                  round_n=round_n, ts=ts)
    if "n_devices" in doc:
        rep = {"kind": "multichip", "n_devices": doc.get("n_devices"),
               "rc": doc.get("rc")}
        return record_from_report(rep, source=source, run=run,
                                  round_n=round_n, ts=ts)
    return None


def append_record(path: str, record: dict) -> dict:
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "a") as f:
        f.write(json.dumps(record, sort_keys=True) + "\n")
    return record


def load_ledger(path: str) -> List[dict]:
    if not path or not os.path.exists(path):
        return []
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                out.append(json.loads(line))
    return out


def _order_key(rec: dict):
    return (rec.get("round") if rec.get("round") is not None else 1e9,
            rec.get("ts") or 0.0)


def latest_by_fingerprint(records: List[dict]) -> Dict[str, dict]:
    out: Dict[str, dict] = {}
    for rec in sorted(records, key=_order_key):
        out[rec["fingerprint"]] = rec
    return out


# -- baseline + gate -----------------------------------------------------------

def load_ledger_baseline(path: str) -> dict:
    if not path or not os.path.exists(path):
        return {}
    with open(path) as f:
        return json.load(f)


def write_ledger_baseline(records: List[dict], path: str,
                          tolerance: float = DEFAULT_TOLERANCE) -> dict:
    """Anchor on the NEWEST record per fingerprint, storing only
    gateable metrics (spec_for != None) with their resolved direction
    and tolerance — the file is the reviewable waiver, a PR diff shows
    exactly which bars moved."""
    fps = {}
    for fp, rec in sorted(latest_by_fingerprint(records).items()):
        mets = {}
        for key, val in sorted(rec.get("metrics", {}).items()):
            spec = spec_for(key)
            if spec is None:
                continue
            if val < 0:
                # bench's "-1" convention marks a skipped/failed leg
                # (and e.g. overlap_fraction -1 = no data) — a
                # placeholder is not an anchor
                continue
            entry = {"value": val, "direction": spec["direction"]}
            if spec["direction"] != "exact":
                if "abs_tolerance" in spec:
                    entry["abs_tolerance"] = spec["abs_tolerance"]
                else:
                    entry["tolerance"] = spec.get("tolerance",
                                                  tolerance)
            mets[key] = entry
        fps[fp] = {"label": rec.get("label"), "run": rec.get("run"),
                   "metrics": mets}
    data = {"version": 1, "tolerance": float(tolerance),
            "fingerprints": fps}
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as fh:
        json.dump(data, fh, indent=1, sort_keys=True)
        fh.write("\n")
    os.replace(tmp, path)
    return data


def check_record(record: dict, baseline: dict,
                 tolerance: Optional[float] = None) -> List[Finding]:
    """The gate. Error findings name metric + run + delta; a run whose
    fingerprint has no baseline entry is a warning (same waiver flow
    as graph_lint/memory_anatomy: --write-baseline then check in the
    diff); a baselined metric MISSING from the run is a warning too —
    a silently dropped receipt field is a telemetry regression."""
    fp = record.get("fingerprint", "")
    run = record.get("run", "?")
    base = (baseline.get("fingerprints") or {}).get(fp)
    findings: List[Finding] = []
    if base is None:
        findings.append(Finding(
            rule=RULE, severity="warning", program=run,
            location=f"{fp}:no_baseline",
            message=(f"run {run} ({record.get('label')}) has no perf "
                     "baseline for its config fingerprint — run "
                     "tools/perf_ledger.py --write-baseline to pin "
                     "it")))
        return findings
    default_tol = (baseline.get("tolerance", DEFAULT_TOLERANCE)
                   if tolerance is None else float(tolerance))
    cur_metrics = record.get("metrics", {})
    for key, spec in sorted(base.get("metrics", {}).items()):
        cur = cur_metrics.get(key)
        base_v = spec["value"]
        direction = spec["direction"]
        if cur is None:
            findings.append(Finding(
                rule=RULE, severity="warning", program=run,
                location=f"{fp}:{key}",
                message=(f"metric {key} is baselined but missing from "
                         f"run {run} — a dropped receipt field hides "
                         "future regressions; re-anchor if the "
                         "receipt schema changed deliberately")))
            continue
        tol = (spec.get("tolerance", default_tol)
               if tolerance is None else float(tolerance))
        if cur < 0 or base_v < 0:
            # "-1" sentinels mean the leg was skipped or failed (a
            # PD_BENCH_ONLY-trimmed run, a no-data gauge): name it,
            # never diff it — a placeholder is not a measurement
            findings.append(Finding(
                rule=RULE, severity="warning", program=run,
                location=f"{fp}:{key}",
                message=(f"{key} = {cur:g} in run {run} is a "
                         "skipped/no-data sentinel — leg not gated "
                         "(run the full bench for a gateable "
                         "receipt)")))
            continue
        # PR 18: absolute-tolerance bounds for metrics that live in a
        # fixed range (prediction errors in [0,1)) where a relative
        # bar collapses to zero width at a perfect baseline
        abs_tol = spec.get("abs_tolerance")
        bad = None
        if direction == "exact":
            if cur != base_v:
                bad = (f"{key} = {cur:g}, baseline {base_v:g} "
                       "(exact-better contract: any drift regresses)")
        elif direction == "higher":
            if abs_tol is not None:
                if cur < base_v - abs_tol:
                    bad = (f"{key} = {cur:g} fell {base_v - cur:g} "
                           f"below baseline {base_v:g} "
                           f"(abs tolerance {abs_tol:g})")
            elif base_v > 0 and cur < base_v * (1.0 - tol):
                bad = (f"{key} = {cur:g} fell "
                       f"{(1.0 - cur / base_v) * 100:.1f}% below "
                       f"baseline {base_v:g} "
                       f"(tolerance {tol * 100:.0f}%)")
        elif direction == "lower":
            if abs_tol is not None:
                if cur > base_v + abs_tol:
                    bad = (f"{key} = {cur:g} grew {cur - base_v:g} "
                           f"over baseline {base_v:g} "
                           f"(abs tolerance {abs_tol:g})")
            elif cur > base_v * (1.0 + tol) and (base_v > 0
                                                 or cur > 0):
                grew = ((cur / base_v - 1.0) * 100
                        if base_v > 0 else float("inf"))
                bad = (f"{key} = {cur:g} grew {grew:.1f}% over "
                       f"baseline {base_v:g} "
                       f"(tolerance {tol * 100:.0f}%)")
        if bad:
            findings.append(Finding(
                rule=RULE, severity="error", program=run,
                location=f"{fp}:{key}",
                message=(f"perf regression in run {run} "
                         f"({record.get('label')}): {bad} — fix the "
                         "regression or re-anchor deliberately with "
                         "--write-baseline")))
    return findings


CALIBRATION_LABEL = "planner_prediction_error"


def check_calibration(records: List[dict],
                      table: Optional[Mapping]) -> List[Finding]:
    """Calibration-table staleness check for ``perf_ledger --check``
    (jax-free: it reads the committed table JSON and the ledger, never
    the live backend — the plan-time loud path is
    observability.calibration.load_for).

    Cross-checks the newest planner-audit record against the committed
    table: an audit that ran on analytic fallback
    (``extras.calibration.match`` = 0) or against a table committed
    for a different device count means the committed constants no
    longer describe the fleet — warn with the regeneration command.
    The hard gate on match is the exact-better baseline spec; these
    findings carry the WHY.
    """
    findings: List[Finding] = []
    cal_recs = [r for r in records
                if r.get("label") == CALIBRATION_LABEL]
    if not cal_recs:
        return findings
    newest = sorted(cal_recs, key=_order_key)[-1]
    run = newest.get("run", "?")
    mets = newest.get("metrics", {})
    if table is None:
        findings.append(Finding(
            rule=RULE, severity="warning", program=run,
            location="calibration:missing_table",
            message=("planner audit records exist but no "
                     "cost_calibration.json is committed — plans rank "
                     "on analytic constants; generate one with "
                     "tools/planner_calibrate.py --write")))
        return findings
    match = mets.get("extras.calibration.match")
    if match is not None and match < 1:
        findings.append(Finding(
            rule=RULE, severity="warning", program=run,
            location="calibration:stale_table",
            message=(f"newest planner audit ({run}) ran on ANALYTIC "
                     "fallback: the committed table "
                     f"({table.get('topology')!r}) did not match the "
                     "live (device_kind, topology) — regenerate with "
                     "tools/planner_calibrate.py --write on the "
                     "target fleet")))
    n_dev = mets.get("n_devices")
    if n_dev is not None and table.get("n_devices") is not None \
            and int(table["n_devices"]) != int(n_dev):
        findings.append(Finding(
            rule=RULE, severity="warning", program=run,
            location="calibration:n_devices_mismatch",
            message=(f"committed table is for "
                     f"{table['n_devices']} devices but the newest "
                     f"planner audit ({run}) ran on {int(n_dev)} — "
                     "per-axis bandwidth constants do not transfer "
                     "across mesh sizes; regenerate with "
                     "tools/planner_calibrate.py --write")))
    return findings


# -- trend ---------------------------------------------------------------------

_SPARK = "▁▂▃▄▅▆▇█"


def _spark(values: List[float]) -> str:
    vs = [v for v in values if v is not None]
    if not vs:
        return ""
    lo, hi = min(vs), max(vs)
    span = hi - lo
    out = []
    for v in values:
        if v is None:
            out.append(" ")
        elif span <= 0:
            out.append(_SPARK[3])
        else:
            out.append(_SPARK[min(7, int((v - lo) / span * 7.999))])
    return "".join(out)


def trend(records: List[dict], metric: Optional[str] = None
          ) -> Dict[str, dict]:
    """Per-fingerprint trajectory: runs in round/ts order with the
    requested metric (default: the headline ``value``, falling back
    to ``rc`` for receipt-less rounds)."""
    groups: Dict[str, dict] = {}
    for rec in sorted(records, key=_order_key):
        fp = rec["fingerprint"]
        g = groups.setdefault(fp, {"label": rec.get("label"),
                                   "runs": []})
        mets = rec.get("metrics", {})
        if metric is not None:
            val = mets.get(metric)
        else:
            val = mets.get("value", mets.get("rc"))
        g["runs"].append({"run": rec.get("run"),
                          "round": rec.get("round"),
                          "ts": rec.get("ts"), "value": val})
    return groups


def render_trend(records: List[dict], metric: Optional[str] = None
                 ) -> str:
    """Human trajectory table, one block per fingerprint, with a
    sparkline over the runs — ``perf_ledger --trend``'s output."""
    groups = trend(records, metric=metric)
    lines = []
    what = metric or "value"
    for fp, g in sorted(groups.items(),
                        key=lambda kv: -len(kv[1]["runs"])):
        vals = [r["value"] for r in g["runs"]]
        lines.append(f"{g['label']}  [{fp}]  metric={what}  "
                     f"runs={len(g['runs'])}  {_spark(vals)}")
        for r in g["runs"]:
            v = "-" if r["value"] is None else f"{r['value']:g}"
            lines.append(f"  {r['run']:<16} {v}")
    return "\n".join(lines)
