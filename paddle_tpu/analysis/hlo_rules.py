"""The built-in graph_lint passes over one program's optimized HLO.

Each pass proves (or refutes) one invariant the runtime forensics plane
can only observe post-mortem:

  donation              every donated buffer >= threshold actually
                        aliases in the executable (a silently dropped
                        donation doubles HBM for the largest buffers —
                        params and optimizer state)
  baked-constant        no closure-captured array >= threshold was
                        constant-folded into the executable (an
                        executable-resident copy of the table PLUS a
                        retrace every time the closure rebuilds — the
                        RecompileSentinel hazard, caught pre-launch)
  dtype-promotion       no unintended bf16/f16 -> f32 upcast >=
                        threshold inside AMP compute regions
                        (generalizes tools/hlo_copy_audit.py's single
                        hand-written check; loss_scale/optimizer/
                        grad_sync scopes are exempt — f32 master math
                        is their contract)
  implicit-replication  no all-gather materializes a full-size buffer
                        >= threshold (a shard_map out_spec or an
                        accidental replication re-assembling a sharded
                        param — the guardrail the unified sharding
                        planner (ROADMAP item 2) needs)
  f32-table-copy        no full-table f32 copy survives optimization
                        (VERDICT r4 weak #2, folded in from
                        tools/hlo_copy_audit.py — the CLI is now a shim
                        over this rule)

The cross-program collective-schedule verifier lives in
``analysis.schedule`` (it compares N rank/stage programs, not one).
Thresholds come from ``GraphLintConfig``; locations follow anatomy's
HLO-metadata op_name paths, so a finding reads
``jit(step)/.../attn/dot:convert`` — clickable back to the scope that
produced it.
"""
from __future__ import annotations

from typing import List

from .engine import ProgramAudit, _SHAPE_RE, finding, rule
from .findings import Finding

__all__ = ["LAUNCH_RULES"]

# registration order = report order (severity ties broken by rule)
LAUNCH_RULES = ("donation", "baked-constant", "dtype-promotion",
                "implicit-replication", "f32-table-copy")


def _mib(n: int) -> str:
    return f"{n / (1 << 20):.2f} MiB"


@rule("donation")
def donation_audit(audit: ProgramAudit) -> List[Finding]:
    """Prove donated params/opt-state alias in the compiled executable
    (XLA's input_output_alias header vs jax's args_info donation
    flags, mapped through kept_var_idx)."""
    if audit.lowered is None:
        return []
    cfg = audit.config
    donated = [a for a in audit.flat_args()
               if a["donated"] and a["nbytes"] >= cfg.donation_bytes]
    if not donated:
        return []
    aliased = audit.alias_param_numbers()
    out: List[Finding] = []
    for a in donated:
        loc = f"{a['path']}:parameter"
        if not a["kept"]:
            out.append(Finding(
                rule="", severity="warning", location=loc,
                message=(f"donated {a['dtype']} buffer "
                         f"({_mib(a['nbytes'])}) is never used by the "
                         "program — the donation was dropped at "
                         "lowering (dead input: stop passing it, or "
                         "stop donating it)")))
        elif a["param"] not in aliased:
            out.append(finding(
                loc,
                f"donated {a['dtype']} buffer ({_mib(a['nbytes'])}) "
                "is NOT aliased in the compiled executable — the "
                "updated value allocates a second copy, doubling HBM "
                "for this buffer (entry parameter "
                f"{a['param']} missing from input_output_alias)"))
    return out


@rule("baked-constant")
def baked_constants(audit: ProgramAudit) -> List[Finding]:
    """Closure-captured arrays >= threshold constant-folded into the
    executable (recompile + HBM hazard for serving: the table lives in
    the program, and every closure rebuild is a new executable)."""
    cfg = audit.config
    out: List[Finding] = []
    for ins in audit.instructions():
        if ins.opcode != "constant":
            continue
        if ins.nbytes < cfg.constant_bytes:
            continue
        out.append(finding(
            ins.location,
            f"{ins.dtype}{list(ins.dims)} constant "
            f"({_mib(ins.nbytes)}) baked into the executable — pass "
            "it as an argument (donated if it is state); a "
            "closure-captured array recompiles on every rebuild and "
            "holds HBM inside the program image"))
    return out


_OPERAND_DTYPE_RE = _SHAPE_RE  # first shape in the operand segment

_LOW_PRECISION = ("bf16", "f16")


@rule("dtype-promotion")
def dtype_promotion(audit: ProgramAudit) -> List[Finding]:
    """Unintended f32/f64 upcasts of >=-threshold low-precision
    tensors inside AMP compute regions (scopes whose f32 math is the
    contract — loss_scale, optimizer, grad_sync — are exempt)."""
    cfg = audit.config
    out: List[Finding] = []
    for ins in audit.instructions():
        if ins.opcode != "convert":
            continue
        if ins.dtype not in ("f32", "f64"):
            continue
        if ins.nbytes < cfg.promotion_bytes:
            continue
        m = _OPERAND_DTYPE_RE.search(ins.operands)
        if not m or m.group(1) not in _LOW_PRECISION:
            continue
        sc = ins.scope()
        if sc in cfg.amp_exempt_scopes:
            continue
        out.append(finding(
            ins.location,
            f"{m.group(1)} -> {ins.dtype} upcast materializes "
            f"{_mib(ins.nbytes)} "
            f"({ins.dtype}{list(ins.dims)}) inside "
            f"{'scope ' + sc if sc else 'an unattributed region'} — "
            "AMP compute should stay low-precision; an explicit "
            ".astype/f32 accumulation here doubles the bytes and "
            "defeats the MXU double-rate path"))
    return out


@rule("implicit-replication")
def implicit_replication(audit: ProgramAudit) -> List[Finding]:
    """shard_map outputs/intermediates that re-materialize full-size
    buffers: all-gathers whose result >= threshold (an out_spec that
    drops a mesh axis, or XLA re-assembling a sharded param)."""
    cfg = audit.config
    out: List[Finding] = []
    for ins in audit.instructions():
        if ins.opcode not in ("all-gather", "all-gather-start"):
            continue
        # async form yields (input, output) — the materialized result
        # is the LARGEST tuple member, not the first
        nbytes = ins.max_nbytes() if ins.opcode.endswith("-start") \
            else ins.nbytes
        if nbytes < cfg.replication_bytes:
            continue
        out.append(finding(
            ins.location,
            f"all-gather materializes {ins.dtype}{list(ins.dims)} "
            f"({_mib(nbytes)}) on every device — an implicit full "
            "replication (check the shard_map out_specs / sharding "
            "constraints; a planner output should stay sharded)"))
    return out


@rule("f32-table-copy")
def f32_table_copy(audit: ProgramAudit) -> List[Finding]:
    """Full-size f32 copies surviving in the optimized module (the
    hlo_copy_audit check, generalized from one hand-pinned vocab-table
    shape to a byte threshold)."""
    cfg = audit.config
    out: List[Finding] = []
    # copy-done included deliberately (the legacy hlo_copy_audit op
    # set): a start/done pair reports twice, but if a TPU layout
    # variant ever defeats the tuple parse on the -start line, the
    # plain-typed -done line still trips the rule — detection must
    # not hinge on one line parsing
    for ins in audit.instructions():
        if ins.opcode not in ("copy", "copy-start", "copy-done"):
            continue
        if ins.dtype not in ("f32", "f64"):
            continue
        if ins.nbytes < cfg.copy_bytes:
            continue
        out.append(finding(
            ins.location,
            f"{ins.dtype}{list(ins.dims)} {ins.opcode} "
            f"({_mib(ins.nbytes)}) survives in the optimized module — "
            "a full-table copy burns HBM bandwidth every step "
            "(VERDICT r4: ~6.3 ms/step on the f32 vocab table under "
            "AMP)"))
    return out
