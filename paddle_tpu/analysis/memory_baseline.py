"""Memory baselines: CI-gate program-peak growth like graph_lint gates
new findings.

A fused program's peak HBM is a contract the same way its executable
count is: a PR that quietly grows the TrainStep's peak by 20% ships a
future RESOURCE_EXHAUSTED to whoever raises the batch next. The memory
plane (observability.memory) measures peak-live-bytes per flagship
program from XLA's own buffer assignment; this module pins those
numbers in a reviewable baseline file and emits graph_lint-shaped
``Finding`` records when a program outgrows its waiver:

- the baseline maps program -> {peak_bytes, per-scope temp bytes}, so a
  regression finding can name not just the program but the SCOPE whose
  buffers grew most (the "which component grew" receipt at fault time,
  not launch time);
- growth within ``tolerance`` (default +20%) passes — buffer assignment
  jitters a few percent across compiler versions; a real regression
  (a re-materialized logits buffer, a dropped donation) clears 20%
  easily;
- shrinkage never gates; re-anchor deliberately with
  ``--write-baseline`` after triaging (the tier1_budget rebalance
  policy), which also captures improvements;
- a program with NO baseline entry is reported as a warning finding —
  fingerprint-stable, so checking the updated baseline in (the same
  flow as graph_lint's) waives it permanently.

Findings ride the shared fingerprint/baseline machinery in
``findings.py`` unchanged: ``tools/memory_anatomy.py --check`` is the
CLI gate (exit 1 on a trip, names program + scope).

This module imports no jax — the check half runs from JSON artifacts
on any triage host.
"""
from __future__ import annotations

import json
import os
from typing import Dict, List, Mapping, Optional

from .findings import Finding

__all__ = [
    "RULE", "DEFAULT_TOLERANCE", "peaks_of",
    "load_memory_baseline", "write_memory_baseline",
    "check_memory_baseline",
]

RULE = "memory_baseline"
DEFAULT_TOLERANCE = 0.20


def peaks_of(results: Mapping[str, dict]) -> Dict[str, dict]:
    """Collapse ``attribute_compiled_memory`` results (program ->
    result) into the baseline shape: peak/temp/argument totals plus the
    per-scope temp bytes the scope-growth attribution diffs."""
    out: Dict[str, dict] = {}
    for program, res in results.items():
        ma = res.get("memory") or {}
        out[str(program)] = {
            "peak_bytes": int(res.get("peak_bytes")
                              or ma.get("peak_bytes") or 0),
            # exact (runtime-reported) and reconstructed peaks are
            # different quantities — the gate must not diff across a
            # definition change (see check_memory_baseline)
            "peak_is_exact": bool(ma.get("peak_is_exact", True)),
            "temp_bytes": int(ma.get("temp_bytes", 0)),
            "argument_bytes": int(ma.get("argument_bytes", 0)),
            "scopes": {name: int(row["bytes"])
                       for name, row in res.get("scopes", {}).items()},
        }
    return out


def load_memory_baseline(path: str) -> dict:
    """The baseline doc ({} when missing — everything then reports as
    un-baselined, the graph_lint convention)."""
    if not path or not os.path.exists(path):
        return {}
    with open(path) as f:
        return json.load(f)


def write_memory_baseline(peaks: Mapping[str, dict], path: str,
                          tolerance: float = DEFAULT_TOLERANCE) -> dict:
    """Re-anchor: accept the current peaks. Bytes are stored raw (the
    file is the reviewable waiver — a PR diff shows exactly how much
    each program's peak moved)."""
    data = {
        "version": 1,
        "tolerance": float(tolerance),
        "programs": {k: dict(v) for k, v in sorted(peaks.items())},
    }
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as fh:
        json.dump(data, fh, indent=1, sort_keys=True)
        fh.write("\n")
    os.replace(tmp, path)
    return data


def _worst_scope_growth(cur: Mapping[str, int],
                        base: Mapping[str, int]) -> Optional[tuple]:
    """(scope, grown_bytes) with the largest absolute growth — the
    named culprit in a regression finding."""
    worst = None
    for name, nbytes in cur.items():
        grown = int(nbytes) - int(base.get(name, 0))
        if worst is None or grown > worst[1]:
            worst = (name, grown)
    return worst


def check_memory_baseline(peaks: Mapping[str, dict], baseline: dict,
                          tolerance: Optional[float] = None
                          ) -> List[Finding]:
    """The gate: error findings for programs whose peak grew past the
    tolerance (message names the program, the growth, and the
    top-growth scope), warning findings for programs with no baseline
    entry. Shrinkage and in-tolerance drift pass silently."""
    progs = baseline.get("programs", {})
    tol = (baseline.get("tolerance", DEFAULT_TOLERANCE)
           if tolerance is None else float(tolerance))
    findings: List[Finding] = []
    for program in sorted(peaks):
        cur = peaks[program]
        base = progs.get(program)
        if base is None:
            findings.append(Finding(
                rule=RULE, severity="warning", program=program,
                location=f"{program}:no_baseline",
                message=("no memory baseline entry — run "
                         "tools/memory_anatomy.py --write-baseline "
                         "to pin this program's peak")))
            continue
        cur_peak = int(cur.get("peak_bytes", 0))
        base_peak = int(base.get("peak_bytes", 0))
        # a baseline anchored on a runtime with an exact (XLA-reported)
        # peak is not comparable to a reconstructed peak from another
        # runtime (reconstruction adds undonated output bytes) — flag
        # the definition change instead of diffing mixed quantities;
        # baselines written before the marker existed compare as before
        if ("peak_is_exact" in base and "peak_is_exact" in cur
                and bool(base["peak_is_exact"])
                != bool(cur["peak_is_exact"])):
            findings.append(Finding(
                rule=RULE, severity="warning", program=program,
                location=f"{program}:peak_definition",
                message=(
                    "peak definition changed across runtimes "
                    f"(baseline {'exact' if base['peak_is_exact'] else 'reconstructed'}, "
                    f"current {'exact' if cur['peak_is_exact'] else 'reconstructed'}) "
                    "— re-anchor with --write-baseline on this "
                    "runtime before gating")))
            continue
        limit = base_peak * (1.0 + tol)
        if base_peak and cur_peak > limit:
            worst = _worst_scope_growth(cur.get("scopes", {}),
                                        base.get("scopes", {}))
            scope_note = (f"; top-growth scope '{worst[0]}' "
                          f"(+{worst[1] / 1e6:.2f} MB)"
                          if worst and worst[1] > 0 else "")
            findings.append(Finding(
                rule=RULE, severity="error", program=program,
                location=f"{program}:peak_bytes",
                message=(
                    f"peak {cur_peak / 1e6:.2f} MB exceeds baseline "
                    f"{base_peak / 1e6:.2f} MB by "
                    f"{(cur_peak / base_peak - 1.0) * 100:.1f}% "
                    f"(tolerance {tol * 100:.0f}%){scope_note} — "
                    "shrink it back or re-anchor deliberately with "
                    "--write-baseline")))
    return findings
