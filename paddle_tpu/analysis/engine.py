"""graph_lint rules engine: one lowering, pluggable passes, structured
findings.

The runtime forensics plane (flight recorder, tpu_doctor, step anatomy)
diagnoses a bad program *after* it hangs, retraces, or eats HBM — but
every one of those failure classes is statically visible in the jaxpr /
optimized HLO before dispatch. On a pod, a trace-time catch costs
seconds; a runtime catch costs a hung v4-32 window. This engine brings
the GC3 discipline (verify collective programs as compiler passes, not
runtime debugging) to the single-dispatch engines:

- ``ProgramAudit`` lowers/compiles a program ONCE, reusing anatomy's
  metadata-preserving discipline (``compile_uncached`` — a persistent
  compile-cache hit can hand back a pre-annotation ancestor whose
  op_names attribute nothing), and exposes the parsed HLO instruction
  stream, the donation/aliasing tables, and an optionally-captured
  trace-time collective schedule to every rule.
- Rules register via ``@rule(name)`` and emit ``Finding`` records with
  severity + ``path:op`` locations; ``run_rules`` evaluates them and
  publishes always-on ``lint.findings_total{rule=}`` counters through
  the PR 3 exporters, so a fleet dashboard sees lint debt without any
  per-host scraping.
- Baselines (findings.py) gate CI on *new* findings only.

The built-in passes live in ``hlo_rules`` (importing
``paddle_tpu.analysis`` registers them); the cross-program
collective-schedule verifier lives in ``schedule`` because it compares
N programs, not one.
"""
from __future__ import annotations

import re
from dataclasses import dataclass, replace
from typing import (Callable, Dict, Iterable, List, Optional,
                    Sequence, Set, Tuple)

from ..observability import metrics as _obs
from ..observability.anatomy import (_ITEMSIZE as ITEMSIZE, _META_RE,
                                     _SHAPE_RE, compile_uncached,
                                     scope_of_op_name)
from .findings import Finding

__all__ = [
    "GraphLintConfig", "HloInstr", "ProgramAudit", "rule",
    "registered_rules", "run_rules", "publish_findings",
    "iter_hlo_instructions",
]


@dataclass(frozen=True)
class GraphLintConfig:
    """Per-rule byte thresholds. Defaults target the hazards the rules
    exist for (MiB-scale buffers that double HBM or bloat executables);
    tests and the hlo_copy_audit shim tighten them to exact shapes."""
    donation_bytes: int = 64 << 10       # donated buffer must alias
    constant_bytes: int = 1 << 20        # baked closure constants
    promotion_bytes: int = 1 << 20       # bf16/f16 -> f32 upcasts
    replication_bytes: int = 1 << 20     # full-size all-gathers
    copy_bytes: int = 1 << 20            # f32 full-table copies
    # scopes where f32 math is the CONTRACT, not a leak: loss unscaling,
    # fp32 master-weight optimizer updates, grad-sync dequantize
    amp_exempt_scopes: Tuple[str, ...] = (
        "loss_scale", "optimizer", "grad_sync")


# ---------------------------------------------------------------------------
# HLO text parsing (anatomy's conventions: one line = one instruction)
# ---------------------------------------------------------------------------

# Unlike anatomy's instruction regex (which deliberately prices only
# single-shape results — tuple producers are data movement in its cost
# model), the lint parser MUST see multi-element tuple results: the
# async collective/copy forms a real TPU schedule emits look like
#   %copy-start.1 = (f32[V,H]{1,0:T(8,128)}, f32[V,H]{1,0:T(8,128)},
#                    u32[]{:T(128)}) copy-start(..)
# and the VERDICT r4 weakness was exactly copy-START. The type group
# therefore has a parenthesized-tuple alternative that tolerates one
# nesting level of parens INSIDE the tuple — TPU layouts carry tiling
# annotations like {1,0:T(8,128)(4,1)} that a naive [^)]* stops at.
_INSTR_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%?(?P<name>[\w.\-]+)\s*=\s*"
    r"(?P<type>\((?:[^()]|\([^)]*\))*\)"
    r"|[a-z0-9]+\[[\d,]*\][^\s]*)\s+"
    r"(?P<op>[\w\-]+)\(")
_ALIAS_BLOCK_RE = re.compile(
    r"input_output_alias=\{(.*?)\},\s*(?:entry_computation_layout|"
    r"frontend_attributes|num_partitions|alias_passthrough_params)")
_ALIAS_ENTRY_RE = re.compile(r"\{[\d,\s]*\}:\s*\((\d+),")


def _prod(dims: Sequence[int]) -> int:
    out = 1
    for d in dims:
        out *= int(d)
    return out


@dataclass(frozen=True)
class HloInstr:
    """One parsed HLO instruction line."""
    name: str            # instruction name (%-stripped)
    opcode: str
    dtype: str           # result dtype (first shape of tuple results)
    dims: Tuple[int, ...]
    nbytes: int          # result bytes (first shape)
    type_str: str        # full result type expression (incl. tuples)
    op_name: str         # metadata op_name path ("" when absent)
    operands: str        # raw text inside the opcode's parens
    line: str

    @property
    def location(self) -> str:
        """path:op — the stable scope path when metadata survives,
        the instruction name otherwise."""
        return f"{self.op_name or self.name}:{self.opcode}"

    def max_nbytes(self) -> int:
        """Largest shape in the result type — async start ops yield
        tuples whose FIRST element is the (smaller) input buffer; the
        materialized result is the biggest member."""
        return max(
            (_prod(tuple(int(d) for d in m.group(2).split(",") if d))
             * ITEMSIZE.get(m.group(1), 4)
             for m in _SHAPE_RE.finditer(self.type_str)),
            default=self.nbytes)

    def scope(self) -> Optional[str]:
        return scope_of_op_name(self.op_name) if self.op_name else None


def _operand_segment(line: str, op: str) -> str:
    i = line.find(op + "(")
    if i < 0:
        return ""
    j = line.find(")", i)
    return line[i + len(op) + 1: j if j > 0 else len(line)]


def iter_hlo_instructions(text: str):
    """Yield every instruction in an HLO module's ``as_text()`` dump
    (entry + subcomputations — fused/while bodies are where the real
    work lives)."""
    for line in text.splitlines():
        m = _INSTR_RE.match(line)
        if not m:
            continue
        sm = _SHAPE_RE.search(m.group("type"))
        if not sm:
            continue
        dims = tuple(int(d) for d in sm.group(2).split(",") if d)
        dtype = sm.group(1)
        meta = _META_RE.search(line)
        yield HloInstr(
            name=m.group("name"),
            opcode=m.group("op"),
            dtype=dtype,
            dims=dims,
            nbytes=_prod(dims) * ITEMSIZE.get(dtype, 4),
            type_str=m.group("type"),
            op_name=meta.group(1) if meta else "",
            operands=_operand_segment(line, m.group("op")),
            line=line,
        )


# ---------------------------------------------------------------------------
# the audited program
# ---------------------------------------------------------------------------

class ProgramAudit:
    """Everything the rules need about ONE program, computed lazily and
    cached: optimized HLO text (compiled cache-bypassed so op metadata
    is THIS program's), parsed instructions, donation tables from the
    jax side, the aliasing table from the XLA side, and an optional
    trace-time collective schedule."""

    def __init__(self, name: str, lowered=None, compiled=None,
                 hlo_text: Optional[str] = None,
                 config: Optional[GraphLintConfig] = None,
                 schedule: Optional[List[dict]] = None):
        if lowered is None and compiled is None and hlo_text is None:
            raise ValueError(
                "ProgramAudit needs a lowered, a compiled, or hlo_text")
        self.name = name
        self.lowered = lowered
        self._compiled = compiled
        self._hlo_text = hlo_text
        self.config = config or GraphLintConfig()
        self.schedule = schedule

    @property
    def compiled(self):
        if self._compiled is None:
            # cache-BYPASSED: jax's persistent-cache key strips op
            # metadata, so a stale hit would hand back an executable
            # whose op_names attribute nothing (the anatomy lesson)
            self._compiled = compile_uncached(self.lowered)
        return self._compiled

    @property
    def hlo_text(self) -> str:
        if self._hlo_text is None:
            self._hlo_text = self.compiled.as_text()
        return self._hlo_text

    def instructions(self) -> List[HloInstr]:
        cached = getattr(self, "_instrs", None)
        if cached is None:
            cached = self._instrs = list(
                iter_hlo_instructions(self.hlo_text))
        return cached

    # -- donation / aliasing ------------------------------------------------
    def alias_param_numbers(self) -> Set[int]:
        """Entry parameter numbers XLA aliased to an output (the
        ``input_output_alias={ {out}: (param, ...) }`` module-header
        table — the receipt that a donation actually took)."""
        header = self.hlo_text.splitlines()[0] if self.hlo_text else ""
        m = _ALIAS_BLOCK_RE.search(header)
        block = m.group(1) if m else header
        return {int(p) for p in _ALIAS_ENTRY_RE.findall(block)}

    def flat_args(self) -> List[dict]:
        """Flattened jax-side argument table: for every leaf arg its
        pytree path, aval bytes, donation flag, whether lowering KEPT
        it (unused args are pruned before XLA ever sees them), and —
        for kept args — its entry parameter number (rank within the
        kept set; jax emits kept args as entry parameters in flat
        order)."""
        if self.lowered is None:
            return []
        cached = getattr(self, "_flat_args", None)
        if cached is not None:
            return cached
        import numpy as np
        from jax.tree_util import keystr, tree_flatten_with_path

        leaves, _ = tree_flatten_with_path(self.lowered.args_info)
        kept = None
        try:  # private but load-bearing: exact flat-arg -> param map
            kept = self.lowered._lowering.compile_args.get(
                "kept_var_idx")
        except AttributeError:
            pass
        kept = set(range(len(leaves))) if kept is None else set(kept)
        param_of = {idx: rank
                    for rank, idx in enumerate(sorted(kept))}
        out = []
        for idx, (path, info) in enumerate(leaves):
            aval = getattr(info, "aval", None)
            if aval is None:
                aval = info._aval
            try:  # extended dtypes (RNG keys: key<fry>) have no
                itemsize = np.dtype(aval.dtype).itemsize  # np.dtype
                dtype_str = str(np.dtype(aval.dtype))
            except TypeError:
                itemsize = getattr(aval.dtype, "itemsize", 4)
                dtype_str = str(aval.dtype)
            out.append({
                "index": idx,
                "path": keystr(path),
                "dtype": dtype_str,
                "nbytes": _prod(aval.shape) * itemsize,
                "donated": bool(getattr(info, "donated", False)),
                "kept": idx in kept,
                "param": param_of.get(idx),
            })
        self._flat_args = out
        return out


# ---------------------------------------------------------------------------
# rule registry
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class RuleSpec:
    name: str
    severity: str
    doc: str
    fn: Callable[[ProgramAudit], List[Finding]]


_RULES: Dict[str, RuleSpec] = {}


def rule(name: str, severity: str = "error"):
    """Register a pass: ``fn(audit) -> [Finding]``. The decorator wires
    severity and rule name into every finding so passes only state
    location + message."""
    def deco(fn):
        def wrapped(audit: ProgramAudit) -> List[Finding]:
            return [
                f if f.rule else replace(
                    f, rule=name, severity=f.severity or severity,
                    program=f.program or audit.name)
                for f in fn(audit)
            ]
        _RULES[name] = RuleSpec(name=name, severity=severity,
                                doc=(fn.__doc__ or "").strip(),
                                fn=wrapped)
        return fn
    return deco


def finding(location: str, message: str) -> Finding:
    """Rule-internal shorthand: rule/severity/program are filled in by
    the ``@rule`` wrapper."""
    return Finding(rule="", severity="", location=location,
                   message=message)


def registered_rules() -> List[RuleSpec]:
    return list(_RULES.values())


def run_rules(audit: ProgramAudit,
              only: Optional[Iterable[str]] = None) -> List[Finding]:
    """Evaluate registered passes over one program; publish the
    always-on per-rule counters (zero-count series included, so a
    dashboard can tell 'rule ran clean' from 'rule never ran')."""
    names = list(only) if only is not None else list(_RULES)
    unknown = [n for n in names if n not in _RULES]
    if unknown:
        raise ValueError(
            f"unknown graph_lint rule(s) {unknown}; registered: "
            f"{sorted(_RULES)}")
    findings: List[Finding] = []
    for n in names:
        findings.extend(_RULES[n].fn(audit))
    publish_findings(findings, rules_evaluated=names)
    return findings


def publish_findings(findings: Iterable[Finding],
                     rules_evaluated: Iterable[str] = ()) -> None:
    """lint.findings_total{rule=} — ALWAYS-on (bypasses the metrics
    gate): lint debt is a fleet-health signal whether or not anyone
    armed per-host telemetry, same contract as train_recompiles_total."""
    per: Dict[str, int] = {n: 0 for n in rules_evaluated}
    for f in findings:
        per[f.rule] = per.get(f.rule, 0) + 1
    for name, count in per.items():
        _obs.counter("lint.findings_total", _always=True,
                     rule=name).add(count)
