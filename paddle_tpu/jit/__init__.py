from .api import (InputSpec, StaticFunction, functionalize, to_static,
                  not_to_static, save, load, TranslatedLayer)  # noqa: F401
from . import dy2static  # noqa: F401
from .dy2static import (convert_function, set_max_while_iters,  # noqa: F401
                        max_while_iters_guard)
from .compat import (TracedLayer, ProgramTranslator,  # noqa: F401
                     set_code_level, set_verbosity)
