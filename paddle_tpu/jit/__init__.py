from .api import (InputSpec, StaticFunction, functionalize, to_static,
                  not_to_static, save, load, TranslatedLayer)  # noqa: F401
