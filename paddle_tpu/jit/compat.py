"""jit-facade compatibility (reference python/paddle/jit/__init__.py):
TracedLayer, the ProgramTranslator singleton, and the dy2static logging
knobs — thin, real layers over StaticFunction/functionalize."""
from __future__ import annotations

from typing import List, Optional

import numpy as np

__all__ = ["TracedLayer", "ProgramTranslator", "set_code_level",
           "set_verbosity"]

# module-level (not thread-local): conversion may happen on any thread
_verbosity = 0
_code_level_value = 0


def set_verbosity(level: int = 0, also_to_stdout: bool = False):
    """Reference jit.set_verbosity: dy2static log level (0 silences)."""
    global _verbosity
    _verbosity = int(level)


def set_code_level(level: int = 100, also_to_stdout: bool = False):
    """Reference jit.set_code_level: at level>0 the AST-transformed
    source of each converted function is printed when it is converted
    (dy2static.convert_function consults this)."""
    global _code_level_value
    _code_level_value = int(level)


def _code_level() -> int:
    return _code_level_value


class ProgramTranslator:
    """Reference ProgramTranslator singleton: the global on/off switch
    for @to_static conversion. enable(False) makes every StaticFunction
    call fall through to the original eager function."""

    _instance: Optional["ProgramTranslator"] = None
    _enabled = True

    @classmethod
    def get_instance(cls) -> "ProgramTranslator":
        if cls._instance is None:
            cls._instance = cls()
        return cls._instance

    def enable(self, enable_to_static: bool):
        type(self)._enabled = bool(enable_to_static)

    @classmethod
    def enabled(cls) -> bool:
        return cls._enabled


class TracedLayer:
    """Reference jit.TracedLayer (jit.py:1052): trace a dygraph Layer
    into a static callable once, replay it, and export it as an
    inference artifact. Here the trace IS a StaticFunction jit cache;
    save_inference_model reuses static/io.py's jax.export path."""

    def __init__(self, layer, static_fn, example_inputs):
        self._layer = layer
        self._fn = static_fn
        self._example_inputs = example_inputs

    @staticmethod
    def trace(layer, inputs):
        """Returns (outputs, TracedLayer) like the reference."""
        from .api import StaticFunction
        ins = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        fn = StaticFunction(layer.forward, layer=layer)
        out = fn(*ins)
        return out, TracedLayer(layer, fn, ins)

    def __call__(self, *inputs):
        return self._fn(*inputs)

    def save_inference_model(self, path, feed=None, fetch=None,
                             **kwargs):
        from ..static.io import save_inference_model
        from .api import InputSpec
        spec: List[InputSpec] = []
        for i, t in enumerate(self._example_inputs):
            arr = np.asarray(t._data if hasattr(t, "_data") else t)
            spec.append(InputSpec(list(arr.shape), str(arr.dtype),
                                  f"x{i}"))
        was_training = self._layer.training
        try:
            self._layer.eval()
            save_inference_model(path, layer=self._layer,
                                 input_spec=spec)
        finally:
            if was_training:
                self._layer.train()
