"""paddle.jit: the compiled path.

Reference: @to_static AST transpiler + ProgramTranslator + program cache
(/root/reference/python/paddle/fluid/dygraph/dygraph_to_static/
program_translator.py, function_spec.py) and jit.save/load (jit.py:507,792).

TPU-first redesign: no AST rewriting. Eager ops are already pure jnp
functions, so "translating to static graph" is just jax tracing:
``functionalize`` runs paddle-level code (Layers, Tensors, tape disabled)
under a trace with parameters/buffers lifted to explicit inputs and RNG
keys threaded from a program key. ``to_static`` wraps that in a
shape/dtype-keyed executable cache (the CacheKey/ConcreteProgram analogue;
jax.jit owns compilation + caching). Python control flow is traced through
(unrolled) exactly like dy2static's fallback; data-dependent control flow
should use lax.cond/scan via paddle_tpu.ops.control_flow.
"""
from __future__ import annotations

import functools
import os
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core import dtypes as _dtypes
from ..core.generator import key_scope, next_key
from ..framework import Tensor, no_grad
from ..nn.layer.layers import Layer
from ..ops.registry import run_op

__all__ = ["InputSpec", "StaticFunction", "functionalize", "to_static",
           "not_to_static", "save", "load", "TranslatedLayer"]


class InputSpec:
    """Shape/dtype signature (reference static/input.py:123). A None dim
    means variable — calls are bucketed per concrete shape by the jit
    cache (framework-level padding policy lives in paddle_tpu.io)."""

    def __init__(self, shape, dtype="float32", name=None, stop_gradient=True):
        self.shape = tuple(shape)
        self.dtype = _dtypes.convert_dtype(dtype)
        self.name = name
        self.stop_gradient = stop_gradient

    @classmethod
    def from_tensor(cls, tensor, name=None):
        return cls(tensor.shape, tensor.dtype, name)

    def __repr__(self):
        return f"InputSpec(shape={self.shape}, dtype={self.dtype})"


def _unwrap_tree(obj):
    if isinstance(obj, Tensor):
        return obj._data
    if isinstance(obj, (list, tuple)):
        return type(obj)(_unwrap_tree(o) for o in obj)
    if isinstance(obj, dict):
        return {k: _unwrap_tree(v) for k, v in obj.items()}
    return obj


def _wrap_tree(obj):
    if isinstance(obj, jax.Array):
        return Tensor(obj)
    if isinstance(obj, (list, tuple)):
        return type(obj)(_wrap_tree(o) for o in obj)
    if isinstance(obj, dict):
        return {k: _wrap_tree(v) for k, v in obj.items()}
    return obj


def functionalize(fn: Callable, layer: Optional[Layer] = None):
    """Lower paddle-level code to a pure array function.

    Returns pure(state, key, *array_args) -> (out_tree_of_arrays, new_state)
    where `state` is the layer's raw state dict (params + buffers). Buffer
    mutations (BatchNorm running stats) surface in new_state. The tape is
    disabled inside — compiled gradients come from jax.grad of this pure
    function, not the eager tape.
    """
    target = layer if layer is not None else getattr(fn, "__self__", None)

    def pure(state, key, *args, **kwargs):
        own = target.state_dict() if target is not None else {}
        saved = {k: t._data for k, t in own.items()}
        training_saved = None
        try:
            for k, arr in (state or {}).items():
                if k in own:
                    own[k]._data = arr
            with no_grad(), key_scope(key):
                out = fn(*_wrap_tree(args), **_wrap_tree(kwargs))
            new_state = {k: own[k]._data for k in own}
            return _unwrap_tree(out), new_state
        finally:
            for k, a in saved.items():
                own[k]._data = a
    return pure


class StaticFunction:
    """The to_static callable (ProgramTranslator+StaticFunction analogue).

    Holds a jit cache keyed by input shapes/dtypes + training flag. On call:
    params/buffers are passed as pytree inputs (so optimizer updates don't
    retrigger compilation), a fresh program key is threaded for RNG, buffer
    mutations are written back, and when autograd is active the whole
    compiled forward is taped as ONE node (partial_program run_program-op
    analogue).
    """

    def __init__(self, function, input_spec=None, layer=None,
                 build_strategy=None, enable_ast=True):
        self._function = function
        self._input_spec = input_spec
        self._layer = layer if layer is not None else getattr(
            function, "__self__", None)
        traced_fn = function
        if enable_ast and not getattr(function, "_not_to_static", False):
            # AST conversion (ProgramTranslator transformer stack): tensor
            # if/while/for become lax-backed ops; plain python otherwise
            import inspect as _inspect
            from .dy2static import convert_function
            if _inspect.ismethod(function):
                conv = convert_function(function.__func__)
                if conv is not function.__func__:
                    self_obj = function.__self__

                    @functools.wraps(function)
                    def traced_fn(*a, **k):
                        return conv(self_obj, *a, **k)
            else:
                traced_fn = convert_function(function)
        self._pure = functionalize(traced_fn, self._layer)
        self._jitted = jax.jit(self._pure, static_argnames=())
        self._call_count = 0
        functools.update_wrapper(self, function,
                                 assigned=("__name__", "__doc__"))

    @property
    def concrete_program(self):
        return self

    def _state(self):
        return self._layer.raw_state() if self._layer is not None else {}

    def __call__(self, *args, **kwargs):
        from .compat import ProgramTranslator
        if not ProgramTranslator.enabled():
            # reference ProgramTranslator().enable(False): run eagerly
            return self._function(*args, **kwargs)
        arrays = _unwrap_tree(args)
        kw_arrays = _unwrap_tree(kwargs)
        state = self._state()
        key = next_key()
        self._call_count += 1

        params_requiring = []
        if self._layer is not None:
            from ..framework import is_grad_enabled
            if is_grad_enabled():
                params_requiring = [
                    (k, t) for k, t in self._layer.state_dict().items()
                    if not t.stop_gradient]

        if not params_requiring:
            out, new_state = self._jitted(state, key, *arrays, **kw_arrays)
            self._write_back(new_state)
            return _wrap_tree(out)

        # autograd path: tape the whole compiled program as one op.
        # trainable params become positional diff inputs.
        names = [k for k, _ in params_requiring]
        tensors = [t for _, t in params_requiring]
        rest = {k: v for k, v in state.items() if k not in set(names)}
        jitted = self._jitted
        holder = {}

        def program_op(*trainable_arrays):
            full_state = dict(rest)
            for n, a in zip(names, trainable_arrays[:len(names)]):
                full_state[n] = a
            in_arrays = trainable_arrays[len(names):]
            out, new_state = jitted(full_state, key, *in_arrays, **kw_arrays)
            holder["new_state"] = jax.tree_util.tree_map(
                jax.lax.stop_gradient, new_state)
            flat, tdef = jax.tree_util.tree_flatten(out)
            holder["tdef"] = tdef
            return tuple(flat) if len(flat) != 1 else flat[0]

        tensor_args = [a for a in _flatten_args(args) if isinstance(
            a, Tensor)]
        res = run_op("run_program", program_op,
                     tuple(tensors) + tuple(tensor_args), {})
        new_state = holder.get("new_state")
        if new_state:
            self._write_back({k: v for k, v in new_state.items()
                              if k not in set(names)})
        flat = list(res) if isinstance(res, tuple) else [res]
        return jax.tree_util.tree_unflatten(
            holder["tdef"], flat) if "tdef" in holder else res

    def _write_back(self, new_state):
        if self._layer is None or not new_state:
            return
        own = self._layer.state_dict()
        for k, arr in new_state.items():
            if k in own and own[k]._data is not arr:
                # only buffers mutate in forward; params are left alone
                if own[k].stop_gradient:
                    own[k]._data = arr


def _flatten_args(args):
    out = []
    for a in args:
        if isinstance(a, (list, tuple)):
            out.extend(_flatten_args(a))
        else:
            out.append(a)
    return out


def to_static(function=None, input_spec=None, build_strategy=None,
              backend=None, **kwargs):
    """@paddle.jit.to_static decorator / wrapper."""

    def decorate(fn):
        if isinstance(fn, Layer):
            static = StaticFunction(fn.forward, input_spec, layer=fn,
                                    build_strategy=build_strategy)
            fn.forward = static
            return fn
        return StaticFunction(fn, input_spec,
                              build_strategy=build_strategy)

    if function is not None:
        return decorate(function)
    return decorate


def not_to_static(fn):
    fn._not_to_static = True
    return fn


# ---------------------------------------------------------------------------
# save / load (reference jit.py:507 save → inference model; here: weights +
# AOT-exportable signature. Full StableHLO export via jax.export when specs
# are given.)
# ---------------------------------------------------------------------------

class TranslatedLayer(Layer):
    """Loaded inference layer (reference TranslatedLayer): weights +
    (when the artifact carries a serialized exported program) a runnable
    forward — the AnalysisPredictor "load program + params, run" path,
    with the program being portable StableHLO instead of a ProgramDesc."""

    def __init__(self, state, meta, exported=None):
        super().__init__()
        from ..framework import Parameter
        self._meta = meta
        self._exported = exported
        self._state_arrays = {k: jnp.asarray(v) for k, v in state.items()}
        for k, v in self._state_arrays.items():
            safe = k.replace(".", "__")
            self.add_parameter(safe, Parameter(v))
        self._keys = list(state.keys())

    def forward(self, *args):
        if self._exported is None:
            raise RuntimeError(
                "artifact has no serialized program (saved without "
                "input_spec); rebuild the model class and use "
                "set_state_dict")
        arrays = _unwrap_tree(tuple(args))
        out = self._exported.call(self._state_arrays, *arrays)
        return _wrap_tree(out)


def export_forward(layer, input_spec, platforms=("cpu", "tpu")):
    """AOT-export a Layer's eval-mode forward as a portable serialized
    program: fn(state_dict, *inputs) -> outputs via jax.export
    (the save_inference_model program-serialization analogue,
    ref inference/api/analysis_predictor.h:82 load path)."""
    from jax import export as jax_export
    fn = layer.forward
    if isinstance(fn, StaticFunction):
        fn = fn._function
    pure = functionalize(fn, layer)

    def infer_fn(state, *inputs):
        out, _ = pure(state, jax.random.key(0), *inputs)
        return out

    modes = [lyr.training for lyr in layer.sublayers(include_self=True)]
    layer.eval()
    try:
        # None dims stay polymorphic in the artifact (shape-polymorphic
        # export) so the loaded program runs at any batch size
        scope = jax_export.SymbolicScope()
        next_dim = iter(range(1000))

        def dims_of(shape):
            if all(d is not None for d in shape):
                return tuple(shape)
            spec_str = ", ".join(
                f"b{next(next_dim)}" if d is None else str(d)
                for d in shape)
            return jax_export.symbolic_shape(spec_str, scope=scope)

        args = [jax.ShapeDtypeStruct(dims_of(s.shape), np.dtype(s.dtype))
                for s in input_spec]
        raw = {k: v._data for k, v in layer.state_dict().items()}
        state_spec = {k: jax.ShapeDtypeStruct(v.shape, v.dtype)
                      for k, v in raw.items()}
        exported = jax_export.export(
            jax.jit(infer_fn), platforms=list(platforms))(
            state_spec, *args)
        return exported
    finally:
        for lyr, m in zip(layer.sublayers(include_self=True), modes):
            lyr.training = m


def save(layer, path, input_spec=None, **config):
    """paddle.jit.save: persist state + signature + (with input_spec) the
    serialized exported program so `load` returns a runnable layer."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    state = {}
    if isinstance(layer, Layer):
        state = {k: np.asarray(v._data)
                 for k, v in layer.state_dict().items()}
    meta = {"class": type(layer).__name__,
            "input_spec": [
                {"shape": list(s.shape), "dtype": str(np.dtype(s.dtype))}
                for s in (input_spec or [])]}
    import pickle
    with open(path + ".pdiparams", "wb") as f:
        pickle.dump({"state": state, "meta": meta}, f)
    if input_spec and isinstance(layer, Layer):
        try:
            exported = export_forward(layer, input_spec)
            with open(path + ".pdmodel", "wb") as f:
                f.write(exported.serialize())
            # human-inspectable StableHLO text alongside
            with open(path + ".stablehlo.txt", "w") as f:
                f.write(str(exported.mlir_module()))
        except Exception as e:  # export is best-effort for jit.save; the
            # weights are the contract (untraceable forwards still save).
            # static.save_inference_model raises instead — there the
            # program IS the artifact.
            import warnings
            for suffix in (".pdmodel", ".stablehlo.txt"):
                if os.path.exists(path + suffix):
                    os.remove(path + suffix)
            warnings.warn(
                f"jit.save: program export skipped ({type(e).__name__}: "
                f"{e}); weights saved, load() will be weights-only")


def load(path, **config):
    import pickle
    with open(path + ".pdiparams", "rb") as f:
        data = pickle.load(f)
    exported = None
    if os.path.exists(path + ".pdmodel"):
        from jax import export as jax_export
        with open(path + ".pdmodel", "rb") as f:
            exported = jax_export.deserialize(f.read())
    return TranslatedLayer(data["state"], data["meta"], exported)
