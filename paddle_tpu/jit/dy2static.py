"""dy2static: AST-level conversion of Python control flow to traceable ops.

Reference: python/paddle/fluid/dygraph/dygraph_to_static/ — the
ProgramTranslator's AST transformer stack (ifelse_transformer.py,
loop_transformer.py, logical_transformer.py, convert_call_func.py...)
rewrites user code so `if`/`while`/`for` over Tensors become
conditional_block/while ops.

TPU-native: the rewrite targets lax-backed ops (ops/control_flow.py
cond/while_loop/fori_loop) with RUNTIME dispatch — the generated helpers
check whether the predicate/bounds are traced; concrete values keep plain
Python semantics (zero overhead eagerly, and static `for range(3)` loops
stay unrolled under jit, which keeps them reverse-differentiable).

Supported rewrites:
  - `if`/`elif`/`else` over tensor predicates (assignment merging; both-
    branch returns)
  - `while` over tensor conditions
  - `for i in range(...)` with tensor bounds; `for x in <Tensor>` row
    iteration
  - `and`/`or`/`not` inside converted predicates (lazy logical helpers)
  - `break` / `continue` / early `return` via the guard-flag technique
    (reference break_continue_transformer.py / return_transformer.py):
    a pre-pass rewrites them into boolean flags, guards the trailing
    statements, folds the flags into loop conditions, and appends one
    final `return` — the flag-form loops then convert like any other.
Restrictions (clear errors, mirroring the reference's documented limits):
  - vars assigned under tensor control flow should exist beforehand when
    the predicate is traced (single-branch assignment of new names)
  - a traced early `return` must be matched (both if-branches return, or
    the fall-through path also returns) so the merged return value has a
    consistent structure; one-sided returns under a traced predicate
    raise the _check_defined error
  - `break` inside `for x in <iterable>` (non-range) keeps Python
    semantics (eager only)
"""
from __future__ import annotations

import ast
import functools
import inspect
import textwrap
import warnings
from typing import Any, Callable, Dict, List, Set, Tuple

import jax

from ..framework import Tensor
from ..ops import control_flow as _cf

__all__ = ["convert_function", "ConversionError", "jst"]


class ConversionError(Exception):
    pass


class _Undef:
    """Sentinel for a name unbound before tensor control flow. Any actual
    USE raises, mirroring Python's UnboundLocalError instead of letting
    the sentinel leak into downstream computation."""
    __slots__ = ()

    def __repr__(self):
        return "<pd-undefined>"

    def _raise(self, *a, **k):
        raise UnboundLocalError(
            "variable assigned only inside a conditional branch was used "
            "before assignment (dy2static)")

    __getattr__ = __bool__ = __add__ = __radd__ = __sub__ = __rsub__ = \
        __mul__ = __rmul__ = __truediv__ = __rtruediv__ = __lt__ = \
        __gt__ = __le__ = __ge__ = __call__ = __getitem__ = __iter__ = \
        __neg__ = _raise


UNDEF = _Undef()

# when set, converted tensor-dependent `while` loops compile to a
# reverse-differentiable masked scan of this length instead of
# lax.while_loop (which has no transpose rule). Mirrors the reference
# while_op's differentiability — with the XLA-imposed static bound made
# explicit.
_max_while_iters = None


def set_max_while_iters(n):
    """Enable differentiable converted `while` loops, bounded at n
    iterations (iterations past the dynamic exit are masked out; loops
    that would genuinely run longer than n are truncated). Pass None to
    restore unbounded forward-only lax.while_loop."""
    global _max_while_iters
    _max_while_iters = None if n is None else int(n)


import contextlib as _contextlib


@_contextlib.contextmanager
def max_while_iters_guard(n):
    global _max_while_iters
    old = _max_while_iters
    _max_while_iters = None if n is None else int(n)
    try:
        yield
    finally:
        _max_while_iters = old


def _is_traced(x):
    if isinstance(x, Tensor):
        x = x._data
    return isinstance(x, jax.core.Tracer)


# ---------------------------------------------------------------------------
# runtime helpers (the convert_operators.py analogue) — generated code
# calls these through the `_jst` module alias injected into globals
# ---------------------------------------------------------------------------

class jst:
    UNDEF = UNDEF

    @staticmethod
    def _check_defined(vals, names, what):
        for v, n in zip(vals, names):
            if v is UNDEF:
                raise ConversionError(
                    f"variable '{n}' is assigned inside a tensor-"
                    f"dependent {what} but not defined before it; "
                    "initialize it first (dy2static restriction)")

    @staticmethod
    def ifelse(pred, true_fn, false_fn, init_vals, names):
        if not _is_traced(pred):
            p = bool(pred.item() if isinstance(pred, Tensor) else pred)
            return tuple(true_fn(*init_vals) if p
                         else false_fn(*init_vals))
        # traced: UNDEF slots may not cross lax.cond; both branches must
        # assign them (checked by the original code's own semantics)
        defined_idx = [i for i, v in enumerate(init_vals)
                       if v is not UNDEF]

        def wrap(fn):
            def pure(*defined):
                full = list(init_vals)
                for i, v in zip(defined_idx, defined):
                    full[i] = v
                out = fn(*full)
                jst._check_defined(out, names, "if")
                return tuple(out)
            return pure
        operands = tuple(init_vals[i] for i in defined_idx)
        out = _cf.cond(pred, wrap(true_fn), wrap(false_fn),
                       operands=operands)
        return tuple(out) if isinstance(out, (list, tuple)) else (out,)

    @staticmethod
    def while_(cond_fn, body_fn, init_vals, names):
        vals = tuple(init_vals)
        cur = cond_fn(*vals)
        while not _is_traced(cur):
            if not bool(cur.item() if isinstance(cur, Tensor) else cur):
                return vals
            vals = tuple(body_fn(*vals))
            cur = cond_fn(*vals)
        # the condition is (or became — e.g. a traced break-flag merged
        # into the carry mid-unroll) tensor-dependent: hand the remainder
        # to lax from the current values
        init_vals = vals
        jst._check_defined(init_vals, names, "while")
        if _max_while_iters is not None:
            # differentiable bounded form (masked scan) — needed whenever
            # the converted loop sits under backward(); see
            # set_max_while_iters
            out = _cf.bounded_while_loop(
                cond_fn, lambda *vs: tuple(body_fn(*vs)),
                list(init_vals), _max_while_iters)
            return tuple(out)
        out = _cf.while_loop(cond_fn, lambda *vs: tuple(body_fn(*vs)),
                             list(init_vals))
        return tuple(out)

    @staticmethod
    def for_range(start, stop, step, body_fn, init_vals, names):
        traced = any(_is_traced(v) for v in (start, stop, step))
        if not traced:
            vals = tuple(init_vals)
            s = int(start.item() if isinstance(start, Tensor) else start)
            e = int(stop.item() if isinstance(stop, Tensor) else stop)
            st = int(step.item() if isinstance(step, Tensor) else step)
            for i in range(s, e, st):
                vals = tuple(body_fn(i, *vals))
            return vals
        jst._check_defined(init_vals, names, "for")
        # tensor bounds: normalized while over the index
        i0 = start if isinstance(start, Tensor) else Tensor(
            jax.numpy.asarray(start))

        def cond_fn(i, *vs):
            # direction depends on the (possibly traced) step sign
            import jax.numpy as jnp
            from ..framework import _unwrap
            s = _unwrap(step)
            lt = _unwrap(i < stop)
            gt = _unwrap(i > stop)
            return Tensor(jnp.where(s > 0, lt, gt))

        def body(i, *vs):
            out = body_fn(i, *vs)
            return (i + step,) + tuple(out)
        out = _cf.while_loop(cond_fn, body, [i0] + list(init_vals))
        return tuple(out[1:])

    @staticmethod
    def for_iter(seq, body_fn, init_vals, names):
        if isinstance(seq, Tensor) and seq.ndim > 0:
            n = seq.shape[0]
            vals = tuple(init_vals)
            for i in range(int(n)):   # static length: unrolled trace
                vals = tuple(body_fn(seq[i], *vals))
            return vals
        vals = tuple(init_vals)
        for item in seq:
            vals = tuple(body_fn(item, *vals))
        return vals

    @staticmethod
    def final_ret(rf, rv):
        """Function epilogue for flag-form returns: falls through to
        Python's implicit None when no return fired (eager); traced, the
        merged return value is authoritative (a traced function that may
        not return has no consistent output structure anyway)."""
        if _is_traced(rf):
            return rv
        fired = bool(rf.item() if isinstance(rf, Tensor) else rf)
        if not fired:
            return None
        return rv

    @staticmethod
    def and_(lhs, rhs_fn):
        if _is_traced(lhs) or isinstance(lhs, Tensor):
            from .. import ops
            return ops.logical_and(lhs, rhs_fn())
        return lhs and rhs_fn()

    @staticmethod
    def or_(lhs, rhs_fn):
        if _is_traced(lhs) or isinstance(lhs, Tensor):
            from .. import ops
            return ops.logical_or(lhs, rhs_fn())
        return lhs or rhs_fn()

    @staticmethod
    def not_(x):
        if _is_traced(x) or isinstance(x, Tensor):
            from .. import ops
            return ops.logical_not(x)
        return not x


# ---------------------------------------------------------------------------
# AST analysis helpers
# ---------------------------------------------------------------------------

def _assigned_names(stmts: List[ast.stmt]) -> Set[str]:
    """Names bound by simple assignments/aug-assigns/for-targets within
    the statement list (not descending into nested defs)."""
    names: Set[str] = set()

    class V(ast.NodeVisitor):
        def visit_FunctionDef(self, node):
            # don't descend. Helper defs generated by this transformer
            # (__pd_true_*, __pd_body_*...) are re-created inside each
            # branch/body where they're used, so they must never become
            # lax.cond/while operands; user-level conditional `def`s
            # remain merged stores (eager path rebinds them; traced path
            # errors as before — functions can't cross cond boundaries)
            if not node.name.startswith("__pd_"):
                names.add(node.name)

        def visit_Lambda(self, node):  # lambda params aren't assignments
            pass

        # comprehension targets are scoped to the comprehension in py3 —
        # they are NOT branch-assigned variables
        def visit_ListComp(self, node):
            pass

        visit_SetComp = visit_DictComp = visit_GeneratorExp = \
            visit_ListComp

        def visit_Name(self, node):
            # __pd_* names are bound by this transformer itself (init
            # captures, helper defs of already-converted inner control
            # flow); they never need to cross an outer cond/while
            if isinstance(node.ctx, (ast.Store,)) and \
                    not node.id.startswith("__pd_"):
                names.add(node.id)

    v = V()
    for s in stmts:
        v.visit(s)
    return names


def _contains(stmts, types) -> bool:
    class V(ast.NodeVisitor):
        found = False

        def visit_FunctionDef(self, node):
            pass

        def generic_visit(self, node):
            if isinstance(node, types):
                self.found = True
            super().generic_visit(node)
    v = V()
    for s in stmts:
        v.visit(s)
    return v.found


def _names_tuple(names):
    return ast.Tuple(
        elts=[ast.Name(id=n, ctx=ast.Store()) for n in names],
        ctx=ast.Store())


def _names_load_tuple(names):
    return ast.Tuple(
        elts=[ast.Name(id=n, ctx=ast.Load()) for n in names],
        ctx=ast.Load())


def _jst_attr(name):
    return ast.Attribute(value=ast.Name(id="_jst", ctx=ast.Load()),
                         attr=name, ctx=ast.Load())


def _init_stmts(names, uid):
    """try/except preamble capturing possibly-unbound initial values."""
    out = []
    for k, n in enumerate(names):
        out.append(ast.Try(
            body=[ast.Assign(
                targets=[ast.Name(id=f"__pd_i{uid}_{k}",
                                  ctx=ast.Store())],
                value=ast.Name(id=n, ctx=ast.Load()))],
            handlers=[ast.ExceptHandler(
                type=ast.Tuple(elts=[
                    ast.Name(id="NameError", ctx=ast.Load()),
                    ast.Name(id="UnboundLocalError", ctx=ast.Load())],
                    ctx=ast.Load()),
                name=None,
                body=[ast.Assign(
                    targets=[ast.Name(id=f"__pd_i{uid}_{k}",
                                      ctx=ast.Store())],
                    value=_jst_attr("UNDEF"))])],
            orelse=[], finalbody=[]))
    return out


def _init_load_tuple(names, uid):
    return ast.Tuple(
        elts=[ast.Name(id=f"__pd_i{uid}_{k}", ctx=ast.Load())
              for k in range(len(names))], ctx=ast.Load())


RET_F = "_pde_rf"
RET_V = "_pde_rv"


def _exit_kinds_at_level(stmts) -> Set[str]:
    """Which of {break, continue} occur at THIS loop level (not inside
    nested loops/functions) and whether any `return` occurs anywhere
    below (returns propagate through nested loops)."""
    found: Set[str] = set()

    class R(ast.NodeVisitor):
        def visit_FunctionDef(self, n):
            pass
        visit_AsyncFunctionDef = visit_FunctionDef

        def visit_Lambda(self, n):
            pass

        def visit_Return(self, n):
            found.add("return")

    class V(R):
        def visit_While(self, n):
            r = R()
            for s in n.body + n.orelse:
                r.visit(s)
        visit_For = visit_While

        def visit_Break(self, n):
            found.add("break")

        def visit_Continue(self, n):
            found.add("continue")

    v = V()
    for s in stmts:
        v.visit(s)
    return found


def _always_returns(stmts) -> bool:
    """Every control path through `stmts` ends in `return`."""
    for s in stmts:
        if isinstance(s, ast.Return):
            return True
        if isinstance(s, ast.If) and s.orelse \
                and _always_returns(s.body) and _always_returns(s.orelse):
            return True
    return False


def _assign_const(name, value):
    return ast.Assign(targets=[ast.Name(id=name, ctx=ast.Store())],
                      value=ast.Constant(value=value))


def _assign_expr(name, expr):
    return ast.Assign(targets=[ast.Name(id=name, ctx=ast.Store())],
                      value=expr)


def _not_or(flags: List[str]):
    """`not (f1 or f2 or ...)` — converted lazily by _BoolOpInPred when
    it lands in a tensor-if predicate."""
    if len(flags) == 1:
        inner = ast.Name(id=flags[0], ctx=ast.Load())
    else:
        inner = ast.BoolOp(op=ast.Or(), values=[
            ast.Name(id=f, ctx=ast.Load()) for f in flags])
    return ast.UnaryOp(op=ast.Not(), operand=inner)


class _EarlyExit:
    """Pre-pass: rewrite break/continue/early-return into guard flags
    (reference break_continue_transformer.py / return_transformer.py),
    producing flag-form loops/ifs the main _Transformer converts."""

    def __init__(self):
        self.uid = 0
        self.flagify_returns = False

    def run(self, fdef):
        kinds_all = _exit_kinds_at_level(fdef.body)
        nested_ret = self._has_nested_return(fdef.body)
        loops_exit = self._any_loop_needs_flags(fdef.body)
        if not nested_ret and not loops_exit:
            return
        self.flagify_returns = nested_ret
        body = self.block(fdef.body, None, None)
        if nested_ret:
            epilogue = ast.Return(value=ast.Call(
                func=_jst_attr("final_ret"),
                args=[ast.Name(id=RET_F, ctx=ast.Load()),
                      ast.Name(id=RET_V, ctx=ast.Load())],
                keywords=[]))
            body = ([_assign_const(RET_F, False),
                     _assign_expr(RET_V, _jst_attr("UNDEF"))]
                    + body + [epilogue])
        for s in body:
            # synthesized statements get the function's first line; the
            # user's own statements keep their real locations
            if getattr(s, "lineno", None) is None:
                ast.copy_location(s, fdef.body[0])
            ast.fix_missing_locations(s)
        fdef.body = body
        _ = kinds_all

    # -- analysis ------------------------------------------------------------
    @staticmethod
    def _has_nested_return(body) -> bool:
        for s in body:
            if isinstance(s, ast.Return):
                continue                      # top-level return is fine…
            if "return" in _exit_kinds_at_level([s]):
                return True                   # …nested ones need flags
        return False

    @staticmethod
    def _any_loop_needs_flags(stmts) -> bool:
        class V(ast.NodeVisitor):
            found = False

            def visit_FunctionDef(self, n):
                pass
            visit_AsyncFunctionDef = visit_FunctionDef

            def visit_While(self, n):
                kinds = _exit_kinds_at_level(n.body)
                if kinds & {"break", "continue"}:
                    self.found = True
                self.generic_visit(n)
            visit_For = visit_While
        v = V()
        for s in stmts:
            v.visit(s)
        return v.found

    def _flags_set_by(self, stmt, brk, cont) -> List[str]:
        kinds = _exit_kinds_at_level([stmt])
        flags = []
        if brk and "break" in kinds:
            flags.append(brk)
        if cont and "continue" in kinds:
            flags.append(cont)
        if self.flagify_returns and "return" in kinds:
            flags.append(RET_F)
        return flags

    # -- rewriting -----------------------------------------------------------
    def block(self, stmts, brk, cont) -> List[ast.stmt]:
        out: List[ast.stmt] = []
        for i, s in enumerate(stmts):
            if isinstance(s, ast.Break) and brk:
                out.append(_assign_const(brk, True))
                return out                      # rest is unreachable
            if isinstance(s, ast.Continue) and cont:
                out.append(_assign_const(cont, True))
                return out
            if isinstance(s, ast.Return) and self.flagify_returns:
                out.append(_assign_expr(
                    RET_V, s.value if s.value is not None
                    else ast.Constant(value=None)))
                out.append(_assign_const(RET_F, True))
                return out
            if isinstance(s, ast.If):
                # `if p: …return…` followed by more code where the body
                # always returns ≡ `if p: … else: <rest>` — the else-form
                # assigns the return flag/value on BOTH sides, so traced
                # predicates merge a consistent structure (the reference's
                # return_transformer does the same hoisting)
                if (self.flagify_returns and not s.orelse
                        and _always_returns(s.body) and i + 1 < len(stmts)):
                    folded = ast.If(test=s.test, body=s.body,
                                    orelse=list(stmts[i + 1:]))
                    ast.copy_location(folded, s)
                    out.extend(self.block([folded], brk, cont))
                    return out
                ns = ast.If(
                    test=s.test,
                    body=self.block(s.body, brk, cont) or [ast.Pass()],
                    orelse=self.block(s.orelse, brk, cont))
                ast.copy_location(ns, s)
                out.append(ns)
                flags = self._flags_set_by(s, brk, cont)
                if flags:
                    rest = self.block(stmts[i + 1:], brk, cont)
                    if rest:
                        guard = ast.If(test=_not_or(flags), body=rest,
                                       orelse=[])
                        ast.copy_location(guard, s)
                        out.append(guard)
                    return out
                continue
            if isinstance(s, (ast.While, ast.For)):
                out.extend(self.loop(s))
                if self.flagify_returns and \
                        "return" in _exit_kinds_at_level([s]):
                    rest = self.block(stmts[i + 1:], brk, cont)
                    if rest:
                        guard = ast.If(test=_not_or([RET_F]), body=rest,
                                       orelse=[])
                        ast.copy_location(guard, s)
                        out.append(guard)
                    return out
                continue
            out.append(s)
        return out

    def loop(self, node) -> List[ast.stmt]:
        if node.orelse:
            return [node]                       # loop-else: keep python
        kinds = _exit_kinds_at_level(node.body)
        has_b = "break" in kinds
        has_c = "continue" in kinds
        has_r = self.flagify_returns and "return" in kinds
        if not (has_b or has_c or has_r):
            new_body = self.block(node.body, None, None)
            repl = type(node)(**{**{f: getattr(node, f)
                                    for f in node._fields},
                                 "body": new_body})
            ast.copy_location(repl, node)
            return [repl]

        self.uid += 1
        uid = self.uid
        bf = f"_pde_b{uid}" if has_b else None
        cf = f"_pde_c{uid}" if has_c else None
        cond_flags = ([bf] if has_b else []) + ([RET_F] if has_r else [])

        if isinstance(node, ast.While):
            new_test = (ast.BoolOp(op=ast.And(),
                                   values=[_not_or(cond_flags), node.test])
                        if cond_flags else node.test)
            new_body = (([_assign_const(cf, False)] if has_c else [])
                        + self.block(node.body, bf, cf))
            repl = ast.While(test=new_test, body=new_body, orelse=[])
            ast.copy_location(repl, node)
            # flags are loop-carried stores: initialize both before the
            # loop so the converted while's captures are defined
            return ([_assign_const(bf, False)] if has_b else []) \
                + ([_assign_const(cf, False)] if has_c else []) + [repl]

        # for-loop over non-range iterables: the guard-flag form would
        # drain the whole iterator (wrong cost, non-termination on
        # infinite generators) — keep CPython semantics; a real `return`
        # inside exits the function directly, which composes with the
        # flag epilogue (flags simply never fire)
        is_range = (isinstance(node.iter, ast.Call)
                    and isinstance(node.iter.func, ast.Name)
                    and node.iter.func.id == "range"
                    and isinstance(node.target, ast.Name))
        if not is_range:
            return [node]

        # range-for: keep the `for` shape (stays unrolled under trace —
        # reverse-differentiable) and guard the whole body with the
        # break/return flags; iterations after the exit are no-ops.
        # Deviation from CPython: the loop variable keeps iterating to
        # the end of the range after a `break` (its post-loop value
        # differs) — all *guarded* state matches exactly.
        guard_flags = ([bf] if has_b else []) + ([RET_F] if has_r else [])
        inner = (([_assign_const(cf, False)] if has_c else [])
                 + self.block(node.body, bf, cf))
        new_body = ([ast.If(test=_not_or(guard_flags),
                            body=inner or [ast.Pass()], orelse=[])]
                    if guard_flags else inner)
        repl = ast.For(target=node.target, iter=node.iter,
                       body=new_body, orelse=[])
        ast.copy_location(repl, node)
        return ([_assign_const(bf, False)] if has_b else []) \
            + ([_assign_const(cf, False)] if has_c else []) + [repl]


class _BoolOpInPred(ast.NodeTransformer):
    """Rewrite and/or/not inside a (potentially tensor) predicate into
    lazy _jst helpers (logical_transformer.py analogue)."""

    def visit_BoolOp(self, node):
        self.generic_visit(node)
        fn = "and_" if isinstance(node.op, ast.And) else "or_"
        out = node.values[0]
        for rhs in node.values[1:]:
            out = ast.Call(
                func=_jst_attr(fn),
                args=[out, ast.Lambda(
                    args=ast.arguments(posonlyargs=[], args=[],
                                       vararg=None, kwonlyargs=[],
                                       kw_defaults=[], kwarg=None,
                                       defaults=[]),
                    body=rhs)],
                keywords=[])
        return out

    def visit_UnaryOp(self, node):
        self.generic_visit(node)
        if isinstance(node.op, ast.Not):
            return ast.Call(func=_jst_attr("not_"), args=[node.operand],
                            keywords=[])
        return node


class _Transformer(ast.NodeTransformer):
    def __init__(self):
        self.uid = 0

    def _next(self):
        self.uid += 1
        return self.uid

    # -- if/else -------------------------------------------------------------
    def visit_If(self, node: ast.If):
        self.generic_visit(node)
        uid = self._next()
        test = _BoolOpInPred().visit(node.test)

        body_ret = _contains(node.body, ast.Return)
        else_ret = _contains(node.orelse, ast.Return)
        if body_ret or else_ret:
            # supported shape: both branches are single `return expr`
            if (body_ret and else_ret
                    and len(node.body) == 1 and len(node.orelse) == 1
                    and isinstance(node.body[0], ast.Return)
                    and isinstance(node.orelse[0], ast.Return)):
                # ifelse expects branch fns returning the merged-vars
                # tuple; here that tuple is just (return-value,)
                def one_tuple_lambda(expr):
                    return ast.Lambda(args=_no_args(), body=ast.Tuple(
                        elts=[expr], ctx=ast.Load()))
                call = ast.Call(
                    func=_jst_attr("ifelse"),
                    args=[test,
                          one_tuple_lambda(node.body[0].value),
                          one_tuple_lambda(node.orelse[0].value),
                          ast.Tuple(elts=[], ctx=ast.Load()),
                          ast.Tuple(elts=[], ctx=ast.Load())],
                    keywords=[])
                ret = ast.Return(value=ast.Subscript(
                    value=call,
                    slice=ast.Constant(value=0), ctx=ast.Load()))
                return ast.copy_location(ret, node)
            return node  # leave python `if` (eager ok; traced will error)

        stores = sorted(_assigned_names(node.body)
                        | _assigned_names(node.orelse))
        if not stores:
            # side-effect-only branch (e.g. list.append): keep python
            return node
        args = _fn_args(stores)
        t_name, f_name = f"__pd_true_{uid}", f"__pd_false_{uid}"
        true_def = ast.FunctionDef(
            name=t_name, args=args,
            body=list(node.body) + [ast.Return(
                value=_names_load_tuple(stores))],
            decorator_list=[], returns=None)
        false_def = ast.FunctionDef(
            name=f_name, args=_fn_args(stores),
            body=(list(node.orelse) or [ast.Pass()]) + [ast.Return(
                value=_names_load_tuple(stores))],
            decorator_list=[], returns=None)
        call = ast.Call(
            func=_jst_attr("ifelse"),
            args=[test,
                  ast.Name(id=t_name, ctx=ast.Load()),
                  ast.Name(id=f_name, ctx=ast.Load()),
                  _init_load_tuple(stores, uid),
                  ast.Constant(value=tuple(stores))],
            keywords=[])
        assign = ast.Assign(targets=[_names_tuple(stores)], value=call)
        out = _init_stmts(stores, uid) + [true_def, false_def, assign]
        for s in out:
            ast.copy_location(s, node)
            ast.fix_missing_locations(s)
        return out

    # -- while ---------------------------------------------------------------
    def visit_While(self, node: ast.While):
        self.generic_visit(node)
        if node.orelse:
            return node
        if _contains(node.body, (ast.Break, ast.Continue, ast.Return)):
            return node  # python semantics (eager fine; traced errors)
        uid = self._next()
        test = _BoolOpInPred().visit(node.test)
        stores = sorted(_assigned_names(node.body))
        if not stores:
            return node
        c_name, b_name = f"__pd_cond_{uid}", f"__pd_body_{uid}"
        cond_def = ast.FunctionDef(
            name=c_name, args=_fn_args(stores),
            body=[ast.Return(value=test)], decorator_list=[],
            returns=None)
        body_def = ast.FunctionDef(
            name=b_name, args=_fn_args(stores),
            body=list(node.body) + [ast.Return(
                value=_names_load_tuple(stores))],
            decorator_list=[], returns=None)
        call = ast.Call(
            func=_jst_attr("while_"),
            args=[ast.Name(id=c_name, ctx=ast.Load()),
                  ast.Name(id=b_name, ctx=ast.Load()),
                  _init_load_tuple(stores, uid),
                  ast.Constant(value=tuple(stores))],
            keywords=[])
        assign = ast.Assign(targets=[_names_tuple(stores)], value=call)
        out = _init_stmts(stores, uid) + [cond_def, body_def, assign]
        for s in out:
            ast.copy_location(s, node)
            ast.fix_missing_locations(s)
        return out

    # -- for -----------------------------------------------------------------
    def visit_For(self, node: ast.For):
        self.generic_visit(node)
        if node.orelse or not isinstance(node.target, ast.Name):
            return node
        if _contains(node.body, (ast.Break, ast.Continue, ast.Return)):
            return node
        uid = self._next()
        stores = sorted(_assigned_names(node.body) - {node.target.id})
        if not stores:
            return node
        b_name = f"__pd_forbody_{uid}"
        body_def = ast.FunctionDef(
            name=b_name,
            args=_fn_args([node.target.id] + stores),
            body=list(node.body) + [ast.Return(
                value=_names_load_tuple(stores))],
            decorator_list=[], returns=None)
        is_range = (isinstance(node.iter, ast.Call)
                    and isinstance(node.iter.func, ast.Name)
                    and node.iter.func.id == "range")
        if is_range:
            r = node.iter.args
            start = r[0] if len(r) >= 2 else ast.Constant(value=0)
            stop = r[1] if len(r) >= 2 else r[0]
            step = r[2] if len(r) == 3 else ast.Constant(value=1)
            call = ast.Call(
                func=_jst_attr("for_range"),
                args=[start, stop, step,
                      ast.Name(id=b_name, ctx=ast.Load()),
                      _init_load_tuple(stores, uid),
                      ast.Constant(value=tuple(stores))],
                keywords=[])
        else:
            call = ast.Call(
                func=_jst_attr("for_iter"),
                args=[node.iter,
                      ast.Name(id=b_name, ctx=ast.Load()),
                      _init_load_tuple(stores, uid),
                      ast.Constant(value=tuple(stores))],
                keywords=[])
        assign = ast.Assign(targets=[_names_tuple(stores)], value=call)
        out = _init_stmts(stores, uid) + [body_def, assign]
        for s in out:
            ast.copy_location(s, node)
            ast.fix_missing_locations(s)
        return out


def _no_args():
    return ast.arguments(posonlyargs=[], args=[], vararg=None,
                         kwonlyargs=[], kw_defaults=[], kwarg=None,
                         defaults=[])


def _fn_args(names):
    return ast.arguments(
        posonlyargs=[],
        args=[ast.arg(arg=n, annotation=None) for n in names],
        vararg=None, kwonlyargs=[], kw_defaults=[], kwarg=None,
        defaults=[])


# ---------------------------------------------------------------------------
# entry point
# ---------------------------------------------------------------------------

_cache: Dict[Any, Callable] = {}


def convert_function(fn: Callable) -> Callable:
    """AST-convert `fn`'s tensor control flow. Returns the converted
    function (or `fn` itself when conversion is impossible — e.g. no
    source available)."""
    key = getattr(fn, "__wrapped__", fn)
    if key in _cache:
        return _cache[key]
    try:
        src = textwrap.dedent(inspect.getsource(fn))
        tree = ast.parse(src)
        fdef = tree.body[0]
        if not isinstance(fdef, (ast.FunctionDef, ast.AsyncFunctionDef)):
            raise ConversionError("not a function def")
        fdef.decorator_list = []  # strip @to_static etc.
        _EarlyExit().run(fdef)
        _Transformer().visit(fdef)
        ast.fix_missing_locations(tree)
        try:  # jit.set_code_level(>0): show the transformed source
            from .compat import _code_level
            if _code_level() > 0:
                print(f"[dy2static] transformed source of "
                      f"{fn.__name__}:\n{ast.unparse(tree)}")
        except Exception:
            pass
        code = compile(tree, filename=f"<dy2static {fn.__name__}>",
                       mode="exec")
        glb = dict(fn.__globals__)
        glb["_jst"] = jst
        # rebind the original closure by turning freevars into defaults?
        # simpler: exec and wrap with original closure cells when present
        if fn.__closure__:
            # re-close over the original cells: build a wrapper that
            # injects the free variables into globals at call time
            freevars = fn.__code__.co_freevars
            cells = {n: c for n, c in zip(freevars, fn.__closure__)}

            def make(glb=glb):
                loc: Dict[str, Any] = {}
                exec(code, glb, loc)
                return loc[fdef.name]

            inner = None

            @functools.wraps(fn)
            def converted(*args, **kwargs):
                nonlocal inner
                for n, c in cells.items():
                    glb[n] = c.cell_contents
                if inner is None:
                    inner = make()
                return inner(*args, **kwargs)
            _cache[key] = converted
            return converted
        loc: Dict[str, Any] = {}
        exec(code, glb, loc)
        out = functools.wraps(fn)(loc[fdef.name])
        _cache[key] = out
        return out
    except (OSError, TypeError, SyntaxError, ConversionError) as e:
        # no source (REPL, builtins, lambdas) is routine — trace as-is
        # silently; only a real conversion failure is worth a warning
        if isinstance(e, ConversionError):
            warnings.warn(f"dy2static: could not convert {fn!r} "
                          f"({type(e).__name__}: {e}); tracing it as-is")
        _cache[key] = fn
        return fn
