"""paddle.grad: gradients of outputs w.r.t. chosen inputs.

Reference analogue: PartialGradEngine
(/root/reference/paddle/fluid/imperative/partial_grad_engine.cc).

Two modes:
- create_graph=False: a tape sweep identical to backward() but accumulating
  into a result list instead of .grad.
- create_graph=True: the contributing subgraph is replayed as ONE pure
  function (each node stored its pure fn + original arrays) and the gradient
  is computed with jax.vjp *inside a taped op*, so the returned grads carry
  tape history and arbitrary-order differentiation works — jax
  differentiates through the replayed forward, residuals included.
"""
from __future__ import annotations

from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .core import enforce as _enforce
from .framework import Tensor, _unwrap, global_tape, _zero_cotangent


def _normalize(outputs, inputs, grad_outputs):
    outputs = [outputs] if isinstance(outputs, Tensor) else list(outputs)
    inputs = [inputs] if isinstance(inputs, Tensor) else list(inputs)
    if grad_outputs is None:
        grad_outputs = [None] * len(outputs)
    elif isinstance(grad_outputs, Tensor):
        grad_outputs = [grad_outputs]
    else:
        grad_outputs = list(grad_outputs)
    return outputs, inputs, grad_outputs


def partial_grad(outputs, inputs, grad_outputs=None, retain_graph=None,
                 create_graph=False, allow_unused=False, no_grad_vars=None):
    outputs, inputs, grad_outputs = _normalize(outputs, inputs, grad_outputs)
    if retain_graph is None:
        retain_graph = create_graph
    if create_graph:
        return _replay_grad(outputs, inputs, grad_outputs, allow_unused,
                            no_grad_vars, retain_graph)
    return _sweep_grad(outputs, inputs, grad_outputs, allow_unused,
                       no_grad_vars, retain_graph)


def _sweep_grad(outputs, inputs, grad_outputs, allow_unused, no_grad_vars,
                retain_graph):
    no_grad_ids = {id(t) for t in (no_grad_vars or [])}
    tape = global_tape()
    nodes = tape.nodes
    cotan = {}
    max_idx = -1
    input_ids = {id(t): i for i, t in enumerate(inputs)}
    grads = {}  # id(tensor) -> array

    for out, g in zip(outputs, grad_outputs):
        seed = _unwrap(g) if g is not None else jnp.ones_like(out._data)
        # identity contribution when an output is itself a requested input
        if id(out) in input_ids:
            grads[id(out)] = grads[id(out)] + seed if id(out) in grads \
                else seed
        if out._node is None:
            continue
        key = (out._node.idx, out._out_idx)
        cotan[key] = seed if key not in cotan else cotan[key] + seed
        max_idx = max(max_idx, out._node.idx)

    visited = set()
    for i in range(max_idx, -1, -1):
        node = nodes[i]
        outs = [cotan.pop((i, j), None) for j in range(len(node.out_meta))]
        if all(o is None for o in outs):
            continue
        visited.add(i)
        cts = tuple(o if o is not None else _zero_cotangent(*node.out_meta[j])
                    for j, o in enumerate(outs))
        in_grads = node.vjp_fn(tuple(cts) if node.multi else cts[0])
        if not isinstance(in_grads, tuple):
            in_grads = (in_grads,)
        for t, creator, g in zip(node.inputs, node.in_creators, in_grads):
            if t is None or g is None or id(t) in no_grad_ids:
                continue
            if isinstance(g, np.ndarray) and g.dtype == jax.dtypes.float0:
                continue
            if id(t) in input_ids:
                grads[id(t)] = grads[id(t)] + g if id(t) in grads else g
            if t.stop_gradient:
                continue
            if creator is not None:
                key = (creator[0].idx, creator[1])
                cotan[key] = cotan[key] + g if key in cotan else g

    if not retain_graph:
        tape.release(visited)

    results = []
    for t in inputs:
        if id(t) in grads:
            results.append(Tensor(grads[id(t)], stop_gradient=True))
        elif allow_unused:
            results.append(None)
        else:
            results.append(Tensor(jnp.zeros_like(t._data)))
    return results


def _collect_subgraph(outputs):
    """Contributing tape nodes, forward order."""
    needed = set()
    stack = [out._node for out in outputs if out._node is not None]
    while stack:
        node = stack.pop()
        if node.idx in needed:
            continue
        needed.add(node.idx)
        for t, creator in zip(node.inputs, node.in_creators):
            if t is not None and not t.stop_gradient and creator is not None:
                stack.append(creator[0])
    nodes = global_tape().nodes
    return [nodes[i] for i in sorted(needed)]


def _replay_grad(outputs, inputs, grad_outputs, allow_unused, no_grad_vars,
                 retain_graph):
    from .ops.registry import run_op
    no_grad_ids = {id(t) for t in (no_grad_vars or [])}
    node_list = _collect_subgraph(outputs)

    # connectivity check for allow_unused semantics
    touched = {id(t) for n in node_list for t in n.inputs if t is not None}
    touched |= {id(o) for o in outputs}

    k = len(inputs)
    seeds = [
        g if g is not None else Tensor(jnp.ones_like(out._data))
        for out, g in zip(outputs, grad_outputs)
    ]

    out_ids = [id(o) for o in outputs]
    orig_out = {id(o): o._data for o in outputs}

    def grad_fn(*arrs):
        xs, seed_arrs = arrs[:k], arrs[k:]

        def fwd(*xin):
            env = {id(t): a for t, a in zip(inputs, xin)
                   if id(t) not in no_grad_ids}
            for node in node_list:
                ins = [env.get(id(t), a) if t is not None else a
                       for t, a in zip(node.inputs, node.in_arrays)]
                res = node.pure(*ins)
                res = res if isinstance(res, tuple) else (res,)
                for r, ref in zip(res, node.out_refs):
                    t = ref()
                    if t is not None:
                        env[id(t)] = r
            return tuple(env.get(oid, orig_out[oid]) for oid in out_ids)

        _, vjp = jax.vjp(fwd, *xs)
        return vjp(tuple(seed_arrs))

    flat = run_op("partial_grad_replay", grad_fn, (*inputs, *seeds), {})
    results = []
    for t, g in zip(inputs, flat):
        if id(t) not in touched and id(t) not in {id(o) for o in outputs}:
            results.append(None if allow_unused else g)
        else:
            results.append(g)
    if not retain_graph:
        global_tape().release({n.idx for n in node_list})
    return results
