"""Inference API (reference paddle/fluid/inference/: AnalysisPredictor
`api/analysis_predictor.h:82`, AnalysisConfig `api/paddle_analysis_config.h`,
C API `inference/capi/`).

TPU-native: the saved "model" is a serialized jax.export program
(StableHLO) + params — the analysis pass pipeline (fusion, memory
optimization, layout) is XLA's job at AOT-compile time, so Config's
switches map to compile options instead of IR pass lists. The Predictor
surface (named input/output handles, copy_from_cpu/run/copy_to_cpu)
mirrors the reference's zero-copy API.

Two serving surfaces live behind this frontend:
- per-call artifacts: ``create_predictor(Config(...))`` below — one
  exported program, dense inputs, the reference's deployment shape;
- LM request streams: ``create_serving_engine(model, ...)`` — the
  continuous-batching engine (paddle_tpu.serving: paged KV cache,
  bucketed prefill, in-flight admission) for mixed-length traffic
  that a per-call Predictor would serialize behind head-of-line
  batches and per-signature recompiles.
"""
from __future__ import annotations

import json
import os
import pickle
from typing import Dict, List, Optional

import numpy as np

__all__ = ["Config", "Predictor", "Tensor", "create_predictor",
           "create_serving_engine"]


class Config:
    """AnalysisConfig analogue: points at the exported artifact."""

    def __init__(self, model_path: Optional[str] = None,
                 params_path: Optional[str] = None):
        if model_path and model_path.endswith(".pdmodel"):
            model_path = model_path[:-len(".pdmodel")]
        self._prefix = model_path
        self._device = None  # default: jax's default backend
        self._memory_pool_mb = 0
        self._ir_optim = True  # parity flag: XLA always optimizes

    # -- device selection (CUDA/XPU knobs kept for API parity) -------------
    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0):
        self._memory_pool_mb = memory_pool_init_size_mb
        self._device = ("tpu", device_id)  # GPU request maps to the chip

    def enable_tpu(self, device_id=0):
        self._device = ("tpu", device_id)

    def disable_gpu(self):
        self._device = ("cpu", 0)

    def use_gpu(self):
        return self._device is not None and self._device[0] != "cpu"

    def switch_ir_optim(self, flag=True):
        self._ir_optim = flag

    def model_dir(self):
        return os.path.dirname(self._prefix or "")

    def prog_file(self):
        return (self._prefix or "") + ".pdmodel"

    def params_file(self):
        return (self._prefix or "") + ".pdiparams"


class Tensor:
    """ZeroCopyTensor analogue: a named input/output slot."""

    def __init__(self, predictor: "Predictor", name: str, is_input: bool):
        self._p = predictor
        self.name = name
        self._is_input = is_input

    def reshape(self, shape):
        pass  # shapes come from the exported program; kept for API parity

    def copy_from_cpu(self, data: np.ndarray):
        if not self._is_input:
            raise RuntimeError(f"{self.name} is an output handle")
        self._p._feeds[self.name] = np.asarray(data)

    def copy_to_cpu(self) -> np.ndarray:
        if self._is_input:
            raise RuntimeError(f"{self.name} is an input handle")
        return np.asarray(self._p._outputs[self.name])

    def shape(self):
        if self._is_input:
            a = self._p._feeds.get(self.name)
            return list(a.shape) if a is not None else None
        return list(np.shape(self._p._outputs[self.name]))


class Predictor:
    """AnalysisPredictor analogue: deserialize program + params, AOT-run."""

    def __init__(self, config: Config):
        from jax import export as jax_export
        self.config = config
        if config._device is not None and config._device[0] == "cpu":
            # disable_gpu() must actually pin the CPU backend: the TPU
            # plugin overrides JAX_PLATFORMS on its own, and a wedged
            # tunnel would otherwise hang the first exported.call. The
            # update is a silent no-op once any backend has initialized,
            # so verify and fail LOUDLY rather than hang later.
            import jax
            jax.config.update("jax_platforms", "cpu")
            backend = jax.default_backend()
            if backend != "cpu":
                raise RuntimeError(
                    f"Config.disable_gpu(): jax already initialized the "
                    f"'{backend}' backend in this process — construct "
                    "the Predictor before any other jax use, or set "
                    "JAX_PLATFORMS=cpu in the environment")
        prefix = config._prefix
        with open(prefix + ".pdmodel", "rb") as f:
            self._exported = jax_export.deserialize(f.read())
        with open(prefix + ".pdiparams", "rb") as f:
            data = pickle.load(f)
        self._state = {k: np.asarray(v) for k, v in data["state"].items()}
        meta_path = prefix + ".pdmeta.json"
        if os.path.exists(meta_path):
            with open(meta_path) as f:
                meta = json.load(f)
            self._input_names = meta.get("feed_names") or []
            self._output_names = meta.get("fetch_names") or []
        else:
            spec = data.get("meta", {}).get("input_spec") or []
            self._input_names = [f"x{i}" for i in range(len(spec))]
            self._output_names = []
        if not self._input_names:
            # exported in_avals: state tree leaves first, then inputs
            n_state = len(self._state)
            n_in = len(self._exported.in_avals) - n_state
            self._input_names = [f"x{i}" for i in range(n_in)]
        self._feeds: Dict[str, np.ndarray] = {}
        self._outputs: Dict[str, np.ndarray] = {}

    def get_input_names(self) -> List[str]:
        return list(self._input_names)

    def get_output_names(self) -> List[str]:
        if self._output_names:
            return list(self._output_names)
        return [f"out{i}" for i in range(len(self._exported.out_avals))]

    def get_input_handle(self, name: str) -> Tensor:
        return Tensor(self, name, is_input=True)

    def get_output_handle(self, name: str) -> Tensor:
        return Tensor(self, name, is_input=False)

    def run(self, inputs: Optional[List[np.ndarray]] = None):
        if inputs is not None:
            for n, a in zip(self._input_names, inputs):
                self._feeds[n] = np.asarray(a)
        args = [self._feeds[n] for n in self._input_names]
        out = self._exported.call(self._state, *args)
        flat = out if isinstance(out, (list, tuple)) else [out]
        names = self.get_output_names()
        self._outputs = {n: np.asarray(a) for n, a in zip(names, flat)}
        if inputs is not None:
            return [self._outputs[n] for n in names]


def create_predictor(config: Config) -> Predictor:
    return Predictor(config)


def create_serving_engine(model, serving_config=None, warmup=True,
                          plan=None, **config_kw):
    """The serving twin of create_predictor: build a warmed
    continuous-batching ServingEngine over a live GPTForCausalLM.
    Keyword overrides construct a paddle_tpu.serving.ServingConfig
    (e.g. ``max_slots=16, dtype=None``); ``warmup=False`` skips the
    ladder compile (tests that only inspect structure).

    ``plan=MeshPlan(tp=N)`` builds the tensor-parallel engine: ONE
    shard_map program set over the tp axis with the paged K/V pools
    sharded over heads — tp must divide the model's head count
    (validated at config time, the error names both dims)."""
    from ..serving import ServingConfig, ServingEngine
    if serving_config is not None and (config_kw or plan is not None):
        raise ValueError(
            "pass either serving_config or keyword overrides, not both")
    cfg = serving_config or ServingConfig(plan=plan, **config_kw)
    eng = ServingEngine(model, cfg)
    return eng.warmup() if warmup else eng
