"""Tensor, Parameter, and the eager autograd engine.

Design (TPU-first, not a port):

The reference implements an eager runtime as a C++ tracer + grad-node graph +
queue-driven engine (/root/reference/paddle/fluid/imperative/tracer.cc:132,
layer.h:65 VarBase, basic_engine.cc:265 BasicEngine::Execute). On TPU the
right substrate is JAX: every op is a pure function; eager mode executes it
immediately and — when gradients are required — records a tape node holding
the ``jax.vjp`` pullback. ``backward()`` walks the tape in reverse creation
order (a valid topological order for eagerly-created graphs, playing the role
of BasicEngine's dependency-counted queue) and accumulates cotangents
(gradient_accumulator.cc analogue). Eager mode is the debugging/usability
surface; performance comes from the compiled path (paddle_tpu.jit/static),
which traces whole step functions into a single XLA program.
"""
from __future__ import annotations

import threading
import weakref
from typing import Any, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .core import dtypes as _dtypes
from .core import enforce as _enforce
from .core.place import Place, current_place

__all__ = [
    "Tensor", "Parameter", "to_tensor", "no_grad", "enable_grad",
    "is_grad_enabled", "set_grad_enabled", "in_dygraph_mode",
]

Array = jax.Array

# ---------------------------------------------------------------------------
# grad-mode state
# ---------------------------------------------------------------------------

_state = threading.local()


def _grad_enabled() -> bool:
    return getattr(_state, "grad_enabled", True)


def is_grad_enabled() -> bool:
    return _grad_enabled()


class _GradMode:
    def __init__(self, mode: bool):
        self.mode = mode

    def __enter__(self):
        self.prev = _grad_enabled()
        _state.grad_enabled = self.mode
        return self

    def __exit__(self, *exc):
        _state.grad_enabled = self.prev

    def __call__(self, fn):
        import functools

        @functools.wraps(fn)
        def wrapper(*a, **k):
            with _GradMode(self.mode):
                return fn(*a, **k)
        return wrapper


def no_grad(fn=None):
    """Context manager / decorator disabling tape recording."""
    ctx = _GradMode(False)
    return ctx(fn) if fn is not None else ctx


def enable_grad(fn=None):
    ctx = _GradMode(True)
    return ctx(fn) if fn is not None else ctx


class set_grad_enabled:
    """Applies immediately AND usable as a context manager (paddle parity)."""

    def __init__(self, mode: bool):
        self.prev = _grad_enabled()
        _state.grad_enabled = bool(mode)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        _state.grad_enabled = self.prev


def in_dygraph_mode() -> bool:
    return True  # eager is the default mode, as in paddle 2.x


# ---------------------------------------------------------------------------
# The tape
# ---------------------------------------------------------------------------

class TapeNode:
    """One recorded op: holds the vjp pullback and graph edges."""

    __slots__ = ("op_type", "vjp_fn", "inputs", "in_creators", "out_refs",
                 "out_meta", "multi", "idx", "pure", "in_arrays")

    def __init__(self, op_type, vjp_fn, inputs, outputs, idx, multi=False,
                 pure=None, in_arrays=None):
        self.op_type = op_type
        self.vjp_fn = vjp_fn
        self.inputs: List["Tensor"] = inputs
        # snapshot each input's creator NOW: later in-place rebinding of an
        # input tensor must not redirect this node's upstream edges
        # (inplace-version-check analogue, reference tensor.h:77)
        self.in_creators = [
            (t._node, t._out_idx) if t is not None and t._node is not None
            else None
            for t in inputs
        ]
        self.out_refs = [weakref.ref(t) for t in outputs]
        # (shape, dtype) per output so we can build zero cotangents
        self.out_meta = [(t._data.shape, t._data.dtype) for t in outputs]
        self.multi = multi  # did the pure fn return a tuple?
        self.idx = idx
        # replay support (create_graph / double grad): the pure fn over the
        # diff-input arrays, and those original arrays
        self.pure = pure
        self.in_arrays = in_arrays


class Tape:
    def __init__(self):
        self.nodes: List[TapeNode] = []

    def record(self, op_type, vjp_fn, inputs, outputs, multi=False,
               pure=None, in_arrays=None):
        node = TapeNode(op_type, vjp_fn, inputs, outputs, len(self.nodes),
                        multi, pure, in_arrays)
        self.nodes.append(node)
        for i, t in enumerate(outputs):
            t._node = node
            t._out_idx = i
        return node

    def release(self, visited):
        """Free the given node indices and compact the tape, so unrelated
        live graphs keep their autograd state (eager_deletion analogue)."""
        if not visited:
            return
        kept = []
        for n in self.nodes:
            if n.idx in visited:
                for r in n.out_refs:
                    t = r()
                    if t is not None and t._node is n:
                        t._node = None
                n.vjp_fn = None
                n.inputs = []
                n.pure = None
                n.in_arrays = None
            else:
                kept.append(n)
        for j, n in enumerate(kept):
            n.idx = j
        self.nodes = kept

    def clear(self):
        self.release({n.idx for n in self.nodes})


_tape = Tape()


def global_tape() -> Tape:
    return _tape


def _zero_cotangent(shape, dtype):
    if jnp.issubdtype(dtype, jnp.inexact):
        return jnp.zeros(shape, dtype)
    # integer/bool primal outputs take float0 cotangents in jax
    return np.zeros(shape, jax.dtypes.float0)


def backward_from(root: "Tensor", grad: Optional[Array] = None,
                  retain_graph: bool = False):
    """Reverse sweep over the tape starting at ``root``.

    Mirrors BasicEngine (basic_engine.cc:265): instead of refcounted queue
    dispatch we walk tape nodes newest→oldest, which is a topological order
    by construction for eager graphs.
    """
    if root._node is None:
        # leaf with no history: grad is just the seed
        if not root.stop_gradient:
            seed = grad if grad is not None else jnp.ones_like(root._data)
            root._accumulate_grad(seed)
        return
    if grad is None:
        _enforce.enforce(
            root._data.size == 1,
            "backward() on a non-scalar tensor requires an explicit grad",
        )
        grad = jnp.ones_like(root._data)

    # cotangent store keyed by (node idx, out idx); leaf grads go to .grad
    cotan = {}
    cotan[(root._node.idx, root._out_idx)] = grad

    nodes = _tape.nodes
    start = root._node.idx
    visited = set()
    for i in range(start, -1, -1):
        node = nodes[i]
        outs = [cotan.pop((i, j), None) for j in range(len(node.out_meta))]
        if all(o is None for o in outs):
            continue
        visited.add(i)
        cts = tuple(
            o if o is not None else _zero_cotangent(*node.out_meta[j])
            for j, o in enumerate(outs)
        )
        # fire retained-grad on non-leaf outputs
        for j, o in enumerate(outs):
            if o is None:
                continue
            t = node.out_refs[j]()
            if t is not None and t._retain_grad:
                t._accumulate_grad(o)
        in_grads = node.vjp_fn(tuple(cts) if node.multi else cts[0])
        if not isinstance(in_grads, tuple):
            in_grads = (in_grads,)
        for t, creator, g in zip(node.inputs, node.in_creators, in_grads):
            if t is None or t.stop_gradient or g is None:
                continue
            if isinstance(g, np.ndarray) and g.dtype == jax.dtypes.float0:
                continue
            for hook in t._grad_hooks:
                new = hook(g)
                if new is not None:
                    g = new
            if creator is None:
                t._accumulate_grad(g)  # leaf (at record time)
            else:
                key = (creator[0].idx, creator[1])
                cotan[key] = g if key not in cotan else cotan[key] + g

    if not retain_graph:
        _tape.release(visited)


# ---------------------------------------------------------------------------
# Tensor
# ---------------------------------------------------------------------------

class Tensor:
    """Eager tensor over jax.Array with paddle-compatible surface.

    Reference analogue: VarBase (/root/reference/paddle/fluid/imperative/
    layer.h:65) + framework::Tensor (framework/tensor.h:89). Allocation,
    layout, and device residency are XLA's concern; this class carries
    autograd state and API surface only.
    """

    __slots__ = ("_data", "stop_gradient", "_grad", "_node", "_out_idx",
                 "name", "persistable", "_retain_grad", "_grad_hooks",
                 "sharding_spec", "__weakref__")

    def __init__(self, data, dtype=None, place=None, stop_gradient=True,
                 name=None):
        if isinstance(data, Tensor):
            data = data._data
        if isinstance(data, jax.ShapeDtypeStruct):
            # abstract tensor (shape/dtype only, nothing materialized) —
            # the meta-init path for AOT memory receipts of models too
            # big to build concretely (utils/abstract_init.py); mirrors
            # static.Var's aval-only storage
            if dtype is not None:
                data = jax.ShapeDtypeStruct(
                    data.shape, np.dtype(_dtypes.convert_dtype(dtype)))
        elif not isinstance(data, jax.Array):
            np_dtype = _dtypes.convert_dtype(dtype) if dtype else None
            arr = np.asarray(data)
            if np_dtype is None and arr.dtype == np.float64:
                np_dtype = _dtypes.get_default_dtype()
            data = jnp.asarray(arr, dtype=np_dtype)
        elif dtype is not None:
            data = data.astype(_dtypes.convert_dtype(dtype))
        self._data = data
        self.stop_gradient = bool(stop_gradient)
        self._grad: Optional[Array] = None
        self._node: Optional[TapeNode] = None
        self._out_idx = 0
        self.name = name
        self.persistable = False
        self._retain_grad = False
        self._grad_hooks: List[Any] = []
        self.sharding_spec = None  # PartitionSpec annotation (distributed)

    # -- basic properties ---------------------------------------------------
    @property
    def shape(self) -> List[int]:
        return list(self._data.shape)

    @property
    def ndim(self) -> int:
        return self._data.ndim

    @property
    def size(self) -> int:
        return int(self._data.size)

    @property
    def dtype(self):
        return self._data.dtype

    @property
    def place(self) -> Place:
        return current_place()

    @property
    def is_leaf(self) -> bool:
        return self._node is None

    @property
    def grad(self) -> Optional["Tensor"]:
        if self._grad is None:
            return None
        return Tensor(self._grad, stop_gradient=True)

    @grad.setter
    def grad(self, value):
        self._grad = None if value is None else _unwrap(value)

    def _accumulate_grad(self, g: Array):
        g = g.astype(self._data.dtype) if g.dtype != self._data.dtype else g
        self._grad = g if self._grad is None else self._grad + g

    # -- autograd -----------------------------------------------------------
    def backward(self, grad_tensor=None, retain_graph=False):
        _enforce.enforce(
            is_grad_enabled(), "backward() called inside no_grad")
        seed = _unwrap(grad_tensor) if grad_tensor is not None else None
        backward_from(self, seed, retain_graph)

    def clear_grad(self):
        self._grad = None

    def clear_gradient(self, set_to_zero: bool = False):
        if set_to_zero and self._grad is not None:
            self._grad = jnp.zeros_like(self._grad)
        else:
            self._grad = None

    def retain_grads(self):
        self._retain_grad = True

    def register_hook(self, hook):
        self._grad_hooks.append(hook)

        class _Removable:
            def remove(self_inner):
                try:
                    self._grad_hooks.remove(hook)
                except ValueError:
                    pass
        return _Removable()

    def detach(self) -> "Tensor":
        t = Tensor(self._data, stop_gradient=True, name=self.name)
        return t

    def clone(self) -> "Tensor":
        from .ops.registry import run_op
        return run_op("clone", lambda x: x + 0, (self,), {})

    # -- value access -------------------------------------------------------
    def numpy(self) -> np.ndarray:
        return np.asarray(self._data)

    def item(self):
        return self._data.item()

    def tolist(self):
        return self.numpy().tolist()

    def astype(self, dtype) -> "Tensor":
        # route through the REGISTERED cast op (dtype as a serializable
        # attribute) — an ad-hoc lambda here made every program that
        # contained an astype unserializable
        from .ops.registry import run_op
        from .ops.manipulation import cast as _cast_op
        return run_op("cast", _cast_op.__pure_fn__, (self,),
                      {"dtype": str(_dtypes.convert_dtype(dtype))})

    def cast(self, dtype):
        return self.astype(dtype)

    def set_value(self, value):
        """In-place value update (optimizer writes); bypasses the tape."""
        arr = value._data if isinstance(value, Tensor) else jnp.asarray(value)
        _enforce.enforce_shape_match(arr.shape, self._data.shape,
                                     "set_value shape mismatch")
        self._data = arr.astype(self._data.dtype)

    def copy_(self, other, blocking=True):
        self.set_value(other)
        return self

    def cpu(self):
        return self

    def to(self, *args, **kwargs):
        return self

    def pin_memory(self):
        return self

    # -- misc ---------------------------------------------------------------
    def __len__(self):
        if self.ndim == 0:
            raise TypeError("len() of a 0-d tensor")
        return self.shape[0]

    def __repr__(self):
        prefix = "Parameter" if isinstance(self, Parameter) else "Tensor"
        return (f"{prefix}(shape={self.shape}, dtype={self.dtype}, "
                f"stop_gradient={self.stop_gradient},\n{self._data})")

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    def __bool__(self):
        return bool(self._data)

    def __int__(self):
        return int(self._data)

    def __float__(self):
        return float(self._data)

    def __format__(self, spec):
        if self.ndim == 0:
            return format(self.item(), spec)
        return format(str(self), spec)

    def __array__(self, dtype=None):
        a = self.numpy()
        return a.astype(dtype) if dtype is not None else a

    def __jax_array__(self):
        return self._data

    # operator overloads are monkey-patched in ops/__init__.py
    # (math_op_patch.py analogue)


class Parameter(Tensor):
    """Trainable tensor: stop_gradient=False, persistable by default."""

    __slots__ = ("trainable", "optimize_attr", "regularizer", "need_clip")

    def __init__(self, data, dtype=None, name=None, trainable=True):
        super().__init__(data, dtype=dtype, stop_gradient=not trainable,
                         name=name)
        self.persistable = True
        self.trainable = trainable
        self.optimize_attr = {"learning_rate": 1.0}
        self.regularizer = None
        self.need_clip = True


def _unwrap(x):
    return x._data if isinstance(x, Tensor) else x


def to_tensor(data, dtype=None, place=None, stop_gradient=True) -> Tensor:
    """paddle.to_tensor equivalent."""
    return Tensor(data, dtype=dtype, place=place, stop_gradient=stop_gradient)


# -- paddle.framework namespace parity (PEP 562 lazy re-exports) ------------
# Reference python/paddle/framework/__init__.py:16 exports the names below
# from this module path; the implementations live elsewhere in this
# package, and importing them eagerly here would be circular.
_FRAMEWORK_EXPORTS = {
    "create_parameter": ("paddle_tpu.ops.creation", "create_parameter"),
    "ParamAttr": ("paddle_tpu.nn.param_attr", "ParamAttr"),
    "CPUPlace": ("paddle_tpu.core.place", "CPUPlace"),
    "CUDAPlace": ("paddle_tpu.core.place", "CUDAPlace"),
    "CUDAPinnedPlace": ("paddle_tpu.core.place", "CUDAPinnedPlace"),
    "get_default_dtype": ("paddle_tpu.core.dtypes", "get_default_dtype"),
    "set_default_dtype": ("paddle_tpu.core.dtypes", "set_default_dtype"),
    "grad": ("paddle_tpu.autograd_utils", "partial_grad"),
    "LayerList": ("paddle_tpu.nn.layer.container", "LayerList"),
    "load": ("paddle_tpu.serialization", "load"),
    "save": ("paddle_tpu.serialization", "save"),
    "DataParallel": ("paddle_tpu.distributed.parallel", "DataParallel"),
    "seed": ("paddle_tpu.core.generator", "seed"),
    "random": ("paddle_tpu.core.generator", None),
}


def __getattr__(name):
    try:
        modname, attr = _FRAMEWORK_EXPORTS[name]
    except KeyError:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}") from None
    import importlib
    mod = importlib.import_module(modname)
    return mod if attr is None else getattr(mod, attr)
