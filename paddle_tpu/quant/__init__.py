"""Quantization workflow: QAT wrapping, PTQ calibration, program pass.

Reference:
/root/reference/python/paddle/fluid/contrib/slim/quantization/
  quantization_pass.py      (QuantizationTransformPass: auto-insert
                             fake_quant/dequant around targeted ops;
                             QuantizationFreezePass: int8 inference form)
  post_training_quantization.py (PTQ: calibrate scales over sample data)
  imperative/qat.py         (ImperativeQuantAware: dygraph layer wrap)

TPU-first shape: the fake-quant op family (ops/quant_ops.py, STE
custom_vjp) already compiles into the training step; this module adds
the WORKFLOW on top —

- ImperativeQuantAware.quantize(layer): swap each Linear/Conv2D sublayer
  for a Quanted* wrapper: per-channel weight quant-dequant + EMA
  (moving-average abs-max) activation quant-dequant, state carried in
  buffers so TrainStep's functional buffer path updates it in-graph.
- convert(layer): freeze to the inference form — int8 weight storage
  with per-channel scales, frozen activation scales (the
  QuantizationFreezePass capability on the dygraph path).
- PostTrainingQuantization: run calibration batches in eval mode,
  observe abs-max activation scales, emit the converted int8 model.
- QuantizationTransformPass: the static-Program form — rewrites a
  captured Program in place, inserting channel-wise weight
  quant-dequant and dynamic abs-max activation quant-dequant before
  every matmul/conv op. Dynamic (stateless) activation scales replace
  the reference's stateful in-graph scale vars: a functional graph
  prefers recomputing max|x| (one reduction, fused by XLA) over
  threading mutable scale state through the program.
- int8_serving: the TRUE-int8 decode path for the serving engine —
  PTQ per-channel weight scales as pytree leaves (traced, never
  baked), dynamic per-row activation quant, int8×int8→int32
  dot_general, and the logits-drift accuracy receipt
  (``ServingConfig(quant="int8")`` / ``QuantConfig(int8_compute=True)``).
"""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

import jax
import jax.numpy as jnp

from .. import nn
from ..framework import Tensor
from ..nn import functional as F
from ..ops.quant_ops import (
    fake_channel_wise_quantize_abs_max,
    fake_channel_wise_quantize_dequantize_abs_max,
    fake_quantize_dequantize_abs_max,
    fake_quantize_dequantize_moving_average_abs_max,
)

__all__ = [
    "QuantConfig", "ImperativeQuantAware", "quant_aware", "convert",
    "weight_only_quantize",
    "PostTrainingQuantization", "QuantizationTransformPass",
    "QuantizationFreezePass",
    "QuantedLinear", "QuantedConv2D", "FrozenQuantLinear",
    "FrozenQuantConv2D",
    "int8_serving",
]
from . import int8_serving  # noqa: E402  (jax-light: numpy + lazy jax)

_DEFAULT_TYPES = (nn.Linear, nn.Conv2D)


class QuantConfig:
    def __init__(self, weight_bits=8, activation_bits=8,
                 moving_rate=0.9,
                 weight_quantize_type="channel_wise_abs_max",
                 activation_quantize_type="moving_average_abs_max",
                 int8_compute=False):
        assert weight_quantize_type in ("channel_wise_abs_max",
                                        "abs_max")
        # "none" = weight-only quantization (the LLM-serving form):
        # activations stay full precision, no observers needed —
        # conversion is data-free
        assert activation_quantize_type in ("moving_average_abs_max",
                                            "abs_max", "none")
        self.weight_bits = int(weight_bits)
        self.activation_bits = int(activation_bits)
        self.moving_rate = float(moving_rate)
        self.weight_quantize_type = weight_quantize_type
        self.activation_quantize_type = activation_quantize_type
        # int8_compute=True makes frozen layers EXECUTE the matmul/conv
        # in int8 (int8×int8→int32, the MXU's double-rate path; v5e:
        # 394 int8 TOPS vs 197 bf16 TFLOPS) instead of the float
        # simulation (dequantized weights, fake-quantized activations).
        # Needs 8-bit weights+activations and a calibrated act scale;
        # numerics differ from the simulation only by accumulation
        # order (int32 exact vs f32).
        self.int8_compute = bool(int8_compute)


class _QuantedBase(nn.Layer):
    """Shared activation-observer plumbing for Quanted* wrappers."""

    def _init_observer(self, cfg: QuantConfig):
        self.cfg = cfg
        # EMA state as buffers: functional through TrainStep, in-place
        # in eager (moving_average_abs_max state vars of the reference)
        self.register_buffer("_act_accum",
                             Tensor(jnp.zeros((), jnp.float32)))
        self.register_buffer("_act_state",
                             Tensor(jnp.zeros((), jnp.float32)))

    def _quant_act(self, x):
        cfg = self.cfg
        if cfg.activation_quantize_type == "none":
            return x
        if cfg.activation_quantize_type == "abs_max":
            out, scale = fake_quantize_dequantize_abs_max(
                x, bit_length=cfg.activation_bits)
            if self.training:
                # keep the EMA observer moving even in dynamic abs_max
                # mode so convert()/PTQ can freeze a scale — otherwise
                # this config value dead-ends the freeze workflow
                arr = scale._data if isinstance(scale, Tensor) \
                    else scale
                self._act_accum._data = (cfg.moving_rate
                                         * self._act_accum._data + arr)
                self._act_state._data = (cfg.moving_rate
                                         * self._act_state._data + 1.0)
            return out
        out, _scale, accum, state = \
            fake_quantize_dequantize_moving_average_abs_max(
                x, self._act_accum, self._act_state,
                moving_rate=cfg.moving_rate,
                bit_length=cfg.activation_bits,
                is_test=not self.training)
        if self.training:
            self._act_accum._data = accum._data \
                if isinstance(accum, Tensor) else accum
            self._act_state._data = state._data \
                if isinstance(state, Tensor) else state
        return out

    def _quant_weight(self, w, channel_axis):
        cfg = self.cfg
        if cfg.weight_quantize_type == "abs_max":
            out, _ = fake_quantize_dequantize_abs_max(
                w, bit_length=cfg.weight_bits)
            return out
        out, _ = fake_channel_wise_quantize_dequantize_abs_max(
            w, bit_length=cfg.weight_bits, quant_axis=channel_axis)
        return out

    def activation_scale(self) -> float:
        a = float(np.asarray(self._act_accum._data))
        s = float(np.asarray(self._act_state._data))
        return a / max(s, 1e-8)


class QuantedLinear(_QuantedBase):
    """QAT form of nn.Linear (imperative/qat.py QuantizedLinear): both
    the input and the weight pass through fake quant-dequant (STE
    backward), weight per OUTPUT channel (axis 1 for [in, out])."""

    def __init__(self, inner: "nn.Linear", cfg: QuantConfig):
        super().__init__()
        self._init_observer(cfg)
        self.weight = inner.weight
        self.bias = inner.bias

    def forward(self, x):
        xq = self._quant_act(x)
        wq = self._quant_weight(self.weight, channel_axis=1)
        return F.linear(xq, wq, self.bias)


class QuantedConv2D(_QuantedBase):
    """QAT form of nn.Conv2D; weight [out, in, kh, kw] → channel 0."""

    def __init__(self, inner: "nn.Conv2D", cfg: QuantConfig):
        super().__init__()
        self._init_observer(cfg)
        self.weight = inner.weight
        self.bias = inner.bias
        self._stride = inner.stride
        self._padding = inner.padding
        self._dilation = inner.dilation
        self._groups = inner.groups
        self._data_format = inner.data_format or "NCHW"

    def forward(self, x):
        xq = self._quant_act(x)
        wq = self._quant_weight(self.weight, channel_axis=0)
        return F.conv2d(xq, wq, self.bias, self._stride, self._padding,
                        self._dilation, self._groups, self._data_format)


def _qmax(bits):
    return float((1 << (bits - 1)) - 1)


class _FrozenBase(nn.Layer):
    """Inference form: weights STORED int8 (per-channel scales), the
    activation scale frozen from training/calibration — the
    QuantizationFreezePass product."""

    def _freeze_weight(self, w, channel_axis, bits, per_channel=True):
        arr = np.asarray(w._data, np.float32)
        if per_channel:
            axes = tuple(i for i in range(arr.ndim)
                         if i != channel_axis)
            scales = np.maximum(np.abs(arr).max(axis=axes), 1e-8)
            shape = [1] * arr.ndim
            shape[channel_axis] = -1
            sb = scales.reshape(shape)
        else:  # weight_quantize_type="abs_max": one scale per tensor
            scales = np.maximum(np.abs(arr).max(), 1e-8)
            sb = scales
        q = np.clip(np.round(arr / sb * _qmax(bits)),
                    -_qmax(bits) - 1, _qmax(bits)).astype(np.int8)
        self.register_buffer("weight_int8", Tensor(jnp.asarray(q)))
        self.register_buffer(
            "weight_scales", Tensor(jnp.asarray(scales, jnp.float32)))
        self._channel_axis = channel_axis
        self._wbits = bits

    def _weight_dequant_factor(self):
        """Per-channel (or scalar) weight dequant factor sw/qmax."""
        return self.weight_scales._data / _qmax(self._wbits)

    def _dequant_weight(self):
        f = self._weight_dequant_factor()
        if getattr(f, "ndim", 0):  # per-channel
            shape = [1] * self.weight_int8.ndim
            shape[self._channel_axis] = -1
            f = f.reshape(shape)
        return Tensor(self.weight_int8._data.astype(jnp.float32) * f)

    def _act_codes(self, x, bits):
        """x -> (float integer codes, dequant factor s/qmax) — ONE
        source of truth for the activation rounding, shared by the
        float simulation and the int8 execution paths."""
        arr = x._data if isinstance(x, Tensor) else x
        s = max(float(self._act_scale), 1e-8)
        q = _qmax(bits)
        return jnp.round(jnp.clip(arr / s, -1.0, 1.0) * q), s / q

    def _quant_act_frozen(self, x, bits):
        if self._act_scale is None:  # weight-only mode
            return x
        codes, factor = self._act_codes(x, bits)
        return Tensor(codes * factor)

    # -- true int8 execution (cfg.int8_compute) -------------------------
    def _int8_ready(self):
        return (self._int8_exec and self._act_scale is not None
                and self._abits == 8 and self._wbits == 8)

    def _quant_act_int8(self, x):
        """x -> (int8 codes, dequant factor s/qmax)."""
        codes, factor = self._act_codes(x, self._abits)
        return codes.astype(jnp.int8), factor


class FrozenQuantLinear(_FrozenBase):
    def __init__(self, src, act_scale, cfg: QuantConfig):
        super().__init__()
        self._freeze_weight(
            src.weight, 1, cfg.weight_bits,
            cfg.weight_quantize_type == "channel_wise_abs_max")
        self.bias = src.bias
        self._act_scale = None if act_scale is None else float(act_scale)
        self._abits = cfg.activation_bits
        self._int8_exec = bool(getattr(cfg, "int8_compute", False))

    def forward(self, x):
        if self._int8_ready():
            # true int8 execution: int8×int8→int32 dot (MXU
            # double-rate), one float rescale per output channel
            codes, sx = self._quant_act_int8(x)
            wq = self.weight_int8._data                  # [in, out]
            acc = jax.lax.dot_general(
                codes, wq, (((codes.ndim - 1,), (0,)), ((), ())),
                preferred_element_type=jnp.int32)
            y = (acc.astype(jnp.float32) * sx
                 * self._weight_dequant_factor())        # [out] bcast
            if self.bias is not None:
                y = y + self.bias._data
            return Tensor(y)
        xq = self._quant_act_frozen(x, self._abits)
        return F.linear(xq, self._dequant_weight(), self.bias)


class FrozenQuantConv2D(_FrozenBase):
    def __init__(self, src, act_scale, cfg: QuantConfig):
        super().__init__()
        self._freeze_weight(
            src.weight, 0, cfg.weight_bits,
            cfg.weight_quantize_type == "channel_wise_abs_max")
        self.bias = src.bias
        self._act_scale = None if act_scale is None else float(act_scale)
        self._abits = cfg.activation_bits
        self._int8_exec = bool(getattr(cfg, "int8_compute", False))
        def attr(quanted_name, conv_name):
            # src is a QuantedConv2D (post-QAT) or a raw Conv2D; 0 is a
            # legitimate value (padding=0), so no falsy-or chains
            if hasattr(src, quanted_name):
                return getattr(src, quanted_name)
            return getattr(src, conv_name)
        self._stride = attr("_stride", "stride")
        self._padding = attr("_padding", "padding")
        self._dilation = attr("_dilation", "dilation")
        self._groups = attr("_groups", "groups")
        self._data_format = attr("_data_format", "data_format") or "NCHW"

    def forward(self, x):
        if self._int8_ready():
            # int8 conv on the MXU: int8×int8→int32 accumulation, one
            # per-out-channel float rescale (+bias) after — through the
            # public conv2d functional (the registered op)
            codes, sx = self._quant_act_int8(x)
            channel_last = self._data_format == "NHWC"
            acc = F.conv2d(Tensor(codes), self.weight_int8, None,
                           self._stride, self._padding, self._dilation,
                           self._groups, self._data_format,
                           preferred_element_type="int32")
            acc = acc._data if isinstance(acc, Tensor) else acc
            sw = self._weight_dequant_factor()
            ch_axis = acc.ndim - 1 if channel_last else 1
            if getattr(sw, "ndim", 0):
                shape = [1] * acc.ndim
                shape[ch_axis] = -1
                sw = sw.reshape(shape)
            y = acc.astype(jnp.float32) * sx * sw
            if self.bias is not None:
                bshape = [1] * y.ndim
                bshape[ch_axis] = -1
                y = y + jnp.reshape(self.bias._data, bshape)
            return Tensor(y)
        xq = self._quant_act_frozen(x, self._abits)
        return F.conv2d(xq, self._dequant_weight(), self.bias,
                        self._stride, self._padding, self._dilation,
                        self._groups, self._data_format)


def _swap_sublayers(layer, factory, types):
    """Replace matching sublayers in place (recursively); returns count."""
    n = 0
    for name, sub in list(layer._sub_layers.items()):
        if isinstance(sub, types):
            layer._sub_layers[name] = factory(sub)
            n += 1
        else:
            n += _swap_sublayers(sub, factory, types)
    return n


class ImperativeQuantAware:
    """Dygraph QAT entry (imperative/qat.py contract): `quantize(model)`
    swaps every Linear/Conv2D for its Quanted* wrapper IN PLACE."""

    def __init__(self, config: Optional[QuantConfig] = None, **kw):
        self.cfg = config or QuantConfig(**kw)

    def quantize(self, model) -> int:
        cfg = self.cfg

        def factory(sub):
            if isinstance(sub, nn.Conv2D):
                return QuantedConv2D(sub, cfg)
            return QuantedLinear(sub, cfg)
        n = _swap_sublayers(model, factory, _DEFAULT_TYPES)
        if n == 0:
            raise ValueError(
                "quantize() found no Linear/Conv2D sublayers to wrap")
        return n

    def save_quantized_model(self, model, path, input_spec=None):
        from ..jit.api import save as jit_save
        frozen = convert(model, self.cfg)
        jit_save(frozen, path, input_spec=input_spec)
        return frozen


def quant_aware(model, config: Optional[QuantConfig] = None, **kw):
    """paddleslim-style convenience: wrap in place and return model."""
    ImperativeQuantAware(config, **kw).quantize(model)
    return model


def weight_only_quantize(model, weight_bits: int = 8,
                         weight_quantize_type="channel_wise_abs_max"):
    """Data-free weight-only int8 (the LLM-serving form): every
    Linear/Conv2D weight is stored int8 with per-channel scales and
    dequantized at use; activations stay full precision, so no
    training or calibration is needed — quantize and deploy. In place;
    returns the model in eval mode."""
    cfg = QuantConfig(weight_bits=weight_bits,
                      weight_quantize_type=weight_quantize_type,
                      activation_quantize_type="none")

    # single pass straight to the Frozen* form: no throwaway Quanted*
    # wrappers or observer buffers (Frozen* accept raw Linear/Conv2D)
    def factory(sub):
        if isinstance(sub, nn.Conv2D):
            return FrozenQuantConv2D(sub, None, cfg)
        return FrozenQuantLinear(sub, None, cfg)
    n = _swap_sublayers(model, factory, _DEFAULT_TYPES)
    if n == 0:
        raise ValueError(
            "weight_only_quantize() found no Linear/Conv2D sublayers")
    model.eval()
    return model


def convert(model, config: Optional[QuantConfig] = None):
    """Freeze a QAT model to the int8 inference form (weights stored
    int8 + per-channel scales; activation scales frozen from the EMA
    observers). Returns the model with Quanted* sublayers swapped for
    Frozen* IN PLACE.

    Freezing honors each sublayer's QAT-time cfg for the QUANTIZATION
    shape (bits, per-channel-ness — those were trained in), but a
    config passed HERE decides the execution form: int8_compute=True
    at freeze time turns on true int8 execution even if QAT ran with
    the default config."""

    def factory(sub):
        if sub.cfg.activation_quantize_type == "none":
            scale = None  # weight-only: no activation quant at all
        else:
            scale = sub.activation_scale()
            if scale <= 0:
                raise ValueError(
                    "convert(): activation observer never ran — train "
                    "(QAT) or calibrate (PTQ) before converting")
        cfg = sub.cfg
        if config is not None and config.int8_compute \
                and not cfg.int8_compute:
            cfg = QuantConfig(
                weight_bits=cfg.weight_bits,
                activation_bits=cfg.activation_bits,
                moving_rate=cfg.moving_rate,
                weight_quantize_type=cfg.weight_quantize_type,
                activation_quantize_type=cfg.activation_quantize_type,
                int8_compute=True)
        if isinstance(sub, QuantedConv2D):
            return FrozenQuantConv2D(sub, scale, cfg)
        return FrozenQuantLinear(sub, scale, cfg)
    n = _swap_sublayers(model, factory, (QuantedLinear, QuantedConv2D))
    if n == 0:
        raise ValueError("convert() found no Quanted* sublayers; call "
                         "quantize()/PTQ first")
    model.eval()
    return model


class PostTrainingQuantization:
    """PTQ (post_training_quantization.py contract): wrap the model,
    run `batch_nums` calibration batches in EVAL mode so only the
    EMA observers move (weights untouched), then freeze to int8."""

    def __init__(self, model, data_loader, batch_nums: int = 10,
                 config: Optional[QuantConfig] = None, **kw):
        self.model = model
        self.data_loader = data_loader
        self.batch_nums = int(batch_nums)
        self.cfg = config or QuantConfig(**kw)

    def quantize(self):
        ImperativeQuantAware(self.cfg).quantize(self.model)
        # calibration: observers must ACCUMULATE (training-mode op path)
        # while weights stay frozen — no optimizer runs
        self.model.train()
        seen = 0
        for batch in self.data_loader:
            xs = batch if isinstance(batch, (list, tuple)) else (batch,)
            self.model(*xs)
            seen += 1
            if seen >= self.batch_nums:
                break
        if seen == 0:
            raise ValueError("PTQ data_loader yielded no batches")
        return convert(self.model, self.cfg)

    def save_quantized_model(self, path, input_spec=None):
        from ..jit.api import save as jit_save
        jit_save(self.model, path, input_spec=input_spec)


# ---------------------------------------------------------------------------
# static Program pass (quantization_pass.py QuantizationTransformPass)
# ---------------------------------------------------------------------------

_QUANT_TARGET_OPS = {
    "matmul": 1, "matmul_v2": 1, "mul": 1,     # weight slot, [in, out]
    "linear": 1,
    "conv2d": 1,                               # weight slot, [out,...]
}


class QuantizationTransformPass:
    """Insert fake quant-dequant around matmul/conv ops of a captured
    static Program, in place: per-output-channel weight quant for
    captured Parameters, dynamic abs-max quant for activations."""

    def __init__(self, weight_bits=8, activation_bits=8,
                 quantizable_op_type=None):
        self.weight_bits = int(weight_bits)
        self.activation_bits = int(activation_bits)
        self.targets = dict(_QUANT_TARGET_OPS)
        if quantizable_op_type is not None:
            self.targets = {k: v for k, v in self.targets.items()
                            if k in set(quantizable_op_type)}

    def apply(self, program) -> int:
        from ..ops.registry import get_op
        from ..static.program import OpNode, Var

        w_op = "fake_channel_wise_quantize_dequantize_abs_max"
        a_op = "fake_quantize_dequantize_abs_max"
        w_fn, a_fn = get_op(w_op).fn, get_op(a_op).fn

        new_ops: List[OpNode] = []
        n_inserted = 0
        for node in program.ops:
            if node.op_type in self.targets:
                weight_slot = self.targets[node.op_type]
                for slot, vid in enumerate(node.in_ids):
                    if vid is None:
                        continue
                    src = program.vars[vid]
                    is_weight = vid in program.params and \
                        vid not in program.buffer_ids
                    if is_weight and slot == weight_slot:
                        axis = 1 if "conv" not in node.op_type else 0
                        qv = Var(program, f"{src.name}.quantized",
                                 src._data.shape, src._data.dtype)
                        sv = Var(program, f"{src.name}.quant_scale",
                                 (src._data.shape[axis],),
                                 src._data.dtype)
                        new_ops.append(OpNode(
                            w_op, w_fn, [vid], [None],
                            {"bit_length": self.weight_bits,
                             "quant_axis": axis},
                            [qv.var_id, sv.var_id], True))
                    elif not is_weight:
                        qv = Var(program,
                                 f"{src.name or 'act'}.quantized",
                                 src._data.shape, src._data.dtype)
                        sv = Var(program,
                                 f"{src.name or 'act'}.quant_scale",
                                 (), src._data.dtype)
                        new_ops.append(OpNode(
                            a_op, a_fn, [vid], [None],
                            {"bit_length": self.activation_bits},
                            [qv.var_id, sv.var_id], True))
                    else:
                        continue
                    node.in_ids = list(node.in_ids)
                    node.in_ids[slot] = qv.var_id
                    n_inserted += 1
            new_ops.append(node)
        program.ops = new_ops
        return n_inserted


class QuantizationFreezePass:
    """Freeze a QAT static Program for inference
    (quantization_pass.py QuantizationFreezePass): every per-channel
    weight quant-dequant node becomes an int8-STORED parameter plus a
    `fake_dequantize_max_abs` op with baked per-channel scales.
    Activation quant stays the dynamic abs-max form the transform pass
    inserted (stateless in-graph scales — no calibration vars to
    freeze; the reference bakes its moving-average vars instead).

    Apply to `program.clone(for_test=True)` AFTER training; the frozen
    program still serializes (all ops registered, int8 param values
    ride the params section)."""

    def __init__(self, weight_bits=8):
        self.weight_bits = int(weight_bits)

    def apply(self, program) -> int:
        import jax.numpy as _jnp
        from ..framework import Parameter as _Param
        from ..ops.registry import get_op

        w_op = "fake_channel_wise_quantize_dequantize_abs_max"
        dq_op = "fake_dequantize_max_abs"
        dq_fn = get_op(dq_op).fn
        n_frozen = 0
        frozen_scales = {}  # wid -> (scales, qmax): tied weights feed
        #                     several quant nodes; quantize ONCE and
        #                     reuse — re-quantizing the already-int8
        #                     store would bake ~qmax-sized scales
        for node in program.ops:
            if node.op_type != w_op:
                continue
            wid = node.in_ids[0]
            if wid is None or wid not in program.params:
                continue
            axis = int(node.kwargs.get("quant_axis", 0))
            # freeze with the SAME bit width the node trained with
            qmax = _qmax(int(node.kwargs.get("bit_length",
                                             self.weight_bits)))
            if wid in frozen_scales:
                scales, qmax = frozen_scales[wid]
                arr_shape = program.params[wid]._data.shape
            else:
                arr = np.asarray(program.params[wid]._data, np.float32)
                arr_shape = arr.shape
                axes = tuple(i for i in range(arr.ndim) if i != axis)
                scales = np.maximum(np.abs(arr).max(axis=axes), 1e-8)
                shape = [1] * arr.ndim
                shape[axis] = -1
                q = np.clip(
                    np.round(arr / scales.reshape(shape) * qmax),
                    -qmax - 1, qmax).astype(np.int8)
                # the live parameter becomes the int8 store
                p8 = _Param(_jnp.asarray(q))
                p8.name = program.params[wid].name
                p8.stop_gradient = True
                program.params[wid] = p8
                program.buffer_ids.add(wid)  # frozen: no grads/updates
                frozen_scales[wid] = (scales, qmax)
            # clone() shares Var objects with the source program —
            # replace, never mutate, or the TRAINING program's weight
            # var would silently turn int8 too
            from ..static.program import Var as _Var
            old = program.vars[wid]
            if getattr(old, "_frozen_int8", False) is False:
                nv = _Var.__new__(_Var)
                nv._init_symbolic(tuple(arr_shape), np.dtype(np.int8))
                nv.program = program
                nv.name = old.name
                nv.kind = old.kind
                nv.orig_shape = getattr(old, "orig_shape",
                                        tuple(arr_shape))
                nv.symbolic_dims = getattr(old, "symbolic_dims", set())
                nv.var_id = wid
                nv._frozen_int8 = True
                program.vars[wid] = nv
            # rewrite the node: quant-dequant -> dequant(int8, scales)
            node.op_type = dq_op
            node.fn = dq_fn
            node.in_ids = [wid, None, None]
            node.const_args = [None, _jnp.asarray(scales, _jnp.float32),
                               float(qmax)]
            node.kwargs = {"quant_axis": axis}
            # keep only the dequant output; the old scale output var
            # stays in vars but is produced by nothing (never fetched)
            node.out_ids = node.out_ids[:1]
            node.multi = False
            n_frozen += 1
        return n_frozen
