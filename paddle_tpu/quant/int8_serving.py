"""True-int8 serving compute: the PTQ weight path for the decode engine.

The quant package's Frozen* layers already prove the discipline on the
nn side: per-output-channel abs-max weight scales
(``channel_wise_abs_max``), int8×int8→int32 ``dot_general`` on the
MXU's double-rate path (v5e: 394 int8 TOPS vs 197 bf16 TFLOPS), f32
rescale by ``s_x * s_w``. This module is the same math with NO nn
dependency — the serving engine's weight snapshot is a raw params
pytree (models/generation._gpt_params), so the quantized form must be
a pytree too: each block matmul weight ``<name>_w`` becomes a dict
leaf ``{"q8": int8 [in, out], "s": f32 [out]}`` that rides through
jit as TRACED arguments (scale tables never bake into the executable
— graph_lint's baked-constant rule stays clean) and through
``swap_weights`` like any other leaf.

Activations quantize DYNAMICALLY in-graph (per-row abs-max, the
QuantizationTransformPass rationale: stateless, no calibration pass,
exact for the row it scales). Embeddings, layernorms, biases and the
weight-tied lm_head stay in the serving float dtype; sampling stays
f32 — the int8 surface is exactly the four block matmuls
(qkv/proj/fc1/fc2) that dominate decode FLOPs and weight bytes.

Accuracy contract: greedy top-1 agreement vs the f32 parity engine is
receipted per-token by serving_bench (``--quant int8``), with the
logit drift bounded against the bf16 cast as the reference yardstick.
"""
from __future__ import annotations

import numpy as np

__all__ = ["quantize_weight", "quantize_params", "int8_matmul",
           "logits_drift_receipt", "QUANT_WEIGHT_KEYS"]

# the block matmuls that carry the int8 path (generation._mm consumers)
QUANT_WEIGHT_KEYS = ("qkv_w", "proj_w", "fc1_w", "fc2_w")


def _qmax(bits: int) -> int:
    return (1 << (bits - 1)) - 1


def quantize_weight(w, bits: int = 8):
    """Per-output-channel abs-max PTQ of one ``[in, out]`` (or
    ``[..., out]``) matmul weight — the channel_wise_abs_max freeze
    discipline, data-free. Returns the serving pytree leaf
    ``{"q8": int8 codes, "s": f32 dequant factor [out]}`` with
    ``w ≈ q8 * s`` (``s`` pre-divided by qmax so dequant is one
    multiply)."""
    import jax.numpy as jnp
    qmax = _qmax(int(bits))
    arr = np.asarray(w, np.float32)
    axes = tuple(range(arr.ndim - 1))
    scale = np.maximum(np.abs(arr).max(axis=axes), 1e-8)
    q = np.clip(np.round(arr / scale * qmax),
                -qmax - 1, qmax).astype(np.int8)
    return {"q8": jnp.asarray(q),
            "s": jnp.asarray((scale / qmax).astype(np.float32))}


def quantize_params(params, qcfg=None):
    """The engine's int8 build-time cast: every block's four matmul
    weights become int8+scale leaves; everything else (embeddings,
    norms, biases, already-cast floats) passes through untouched. The
    tree STRUCTURE changes — swap_weights re-runs this same transform
    so a standby pool always lands with the matching treedef.

    Order matters under a tp plan: the snapshot build permutes the
    fused-qkv columns head-major BEFORE calling this (quantization is
    per-COLUMN, so permuting float columns permutes codes and scales
    identically — the {"q8","s"} leaves then shard by the float
    parent's SERVING_TP_RULES spec: codes like the weight, scales
    like its output columns)."""
    bits = int(getattr(qcfg, "weight_bits", 8) or 8)
    out = dict(params)
    out["blocks"] = [
        {k: (quantize_weight(v, bits) if k in QUANT_WEIGHT_KEYS else v)
         for k, v in bp.items()}
        for bp in params["blocks"]]
    return out


def int8_matmul(x, q8, s):
    """``x @ w`` through the int8 pipeline: dynamic per-row abs-max
    activation quantization (f32 → int8 codes), int8×int8→int32
    ``dot_general`` (``preferred_element_type`` keeps the accumulator
    exact), then one f32 rescale by ``s_x * s_w``. Output returns in
    x's dtype so the residual stream keeps the serving float dtype."""
    import jax
    import jax.numpy as jnp
    qmax = 127.0
    xf = x.astype(jnp.float32)
    sx = jnp.max(jnp.abs(xf), axis=-1, keepdims=True) / qmax
    sx = jnp.maximum(sx, 1e-12)
    codes = jnp.clip(jnp.round(xf / sx), -128.0, qmax).astype(jnp.int8)
    acc = jax.lax.dot_general(
        codes, q8, (((codes.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)
    return (acc.astype(jnp.float32) * sx * s).astype(x.dtype)


def logits_drift_receipt(params, eps, n_heads, ids, qcfg=None):
    """The accuracy receipt's numeric half: last-position logits over
    one f32 prompt forward, compared across the three serving casts.
    Returns max-abs logit drift for int8 and for bf16 (the reference
    yardstick the ISSUE bounds int8 against) plus whether the greedy
    top-1 tokens agree on these prompts."""
    import jax.numpy as jnp
    from ..models.generation import _cast_params, _ln, _prefill

    def last_logits(p):
        x, _ = _prefill(p, eps, n_heads, ids, ids.shape[1])
        h = _ln(x[:, -1:], p["lnf_w"], p["lnf_b"], eps)
        wte = p["wte"]
        return (h[:, 0] @ wte.T).astype(jnp.float32)

    l32 = last_logits(params)
    l8 = last_logits(quantize_params(params, qcfg))
    lb = last_logits(_cast_params(params, "bfloat16"))
    drift8 = float(jnp.max(jnp.abs(l8 - l32)))
    driftb = float(jnp.max(jnp.abs(lb - l32)))
    agree = float(jnp.mean(
        (jnp.argmax(l8, -1) == jnp.argmax(l32, -1)).astype(
            jnp.float32)))
    return {"logit_drift_int8": round(drift8, 6),
            "logit_drift_bf16": round(driftb, 6),
            "top1_agreement_last": round(agree, 4)}
