"""Seeded RNG state for eager mode, over JAX's splittable PRNG.

Capability-parity with the reference Generator
(/root/reference/paddle/fluid/framework/generator.h): a per-device, seedable
random state visible from Python. TPU-first redesign: instead of a mutable
Philox state threaded through kernels, we hold a jax PRNG key and split it on
every draw — functional underneath, stateful at the framework surface (eager
mode convenience). Compiled/static code paths take explicit keys.
"""
from __future__ import annotations

import threading

import jax
import numpy as np


class Generator:
    def __init__(self, seed: int = 0):
        self._lock = threading.Lock()
        self.manual_seed(seed)

    def manual_seed(self, seed: int):
        with getattr(self, "_lock", threading.Lock()):
            self._seed = int(seed)
            self._key = jax.random.key(self._seed)
            self._offset = 0
        return self

    def seed(self):
        return self._seed

    def split(self) -> jax.Array:
        """Return a fresh subkey; advances internal state."""
        with self._lock:
            self._key, sub = jax.random.split(self._key)
            self._offset += 1
            return sub

    def get_state(self):
        # key material travels in the state so restore is O(1); seed and
        # offset stay for readability + legacy states
        return {"seed": self._seed, "offset": self._offset,
                "key_data": np.asarray(jax.random.key_data(self._key))}

    def set_state(self, state):
        self.manual_seed(state["seed"])
        if state.get("key_data") is not None:
            key = jax.random.wrap_key_data(
                jax.numpy.asarray(state["key_data"]))
        else:  # legacy {seed, offset} state: replay the splits
            key = jax.random.key(self._seed)
            for _ in range(state["offset"]):
                key, _ = jax.random.split(key)
        with self._lock:
            self._key = key
            self._offset = state["offset"]


# created on first use — constructing a PRNG key materializes a device
# array, and importing the package must NEVER initialize the XLA backend
# (it breaks jax.distributed.initialize ordering and hangs imports when
# the device is unreachable)
_default_generator = None
_default_lock = threading.Lock()


def default_generator() -> Generator:
    global _default_generator
    if _default_generator is None:
        with _default_lock:
            if _default_generator is None:
                _default_generator = Generator(
                    np.random.randint(0, 2**31 - 1))
    return _default_generator


def seed(s: int):
    """paddle.seed equivalent: reseed the default eager generator."""
    return default_generator().manual_seed(s)


class _KeyScope:
    """Traced-mode key provider: inside jit-traced code, random draws must
    derive from an explicit (traced) program key instead of host RNG state —
    otherwise every compiled step would replay the same mask. to_static /
    TrainStep open a key_scope around the traced body."""

    def __init__(self, key: jax.Array):
        self.key = key
        self.counter = 0

    def split(self):
        k = jax.random.fold_in(self.key, self.counter)
        self.counter += 1
        return k


_scope_stack = threading.local()


def _scopes():
    if not hasattr(_scope_stack, "stack"):
        _scope_stack.stack = []
    return _scope_stack.stack


class key_scope:
    def __init__(self, key: jax.Array):
        self._scope = _KeyScope(key)

    def __enter__(self):
        _scopes().append(self._scope)
        return self._scope

    def __exit__(self, *exc):
        _scopes().pop()


def next_key() -> jax.Array:
    stack = _scopes()
    if stack:
        return stack[-1].split()
    return default_generator().split()
