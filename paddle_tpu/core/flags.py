"""Typed runtime flag registry with FLAGS_* environment bridge.

TPU-native equivalent of the reference's gflags machinery
(/root/reference/paddle/fluid/platform/flags.cc:33-539 and
pybind/global_value_getter_setter.cc): a typed, documented registry whose
values can be set from the environment (``FLAGS_<name>``) at import time and
read/written at runtime via ``get_flags``/``set_flags``.
"""
from __future__ import annotations

import os
import threading
from typing import Any, Callable, Dict, Optional

_LOCK = threading.RLock()


class _Flag:
    __slots__ = ("name", "type", "default", "value", "help", "validator")

    def __init__(self, name, type_, default, help_, validator=None):
        self.name = name
        self.type = type_
        self.default = default
        self.value = default
        self.help = help_
        self.validator = validator


_REGISTRY: Dict[str, _Flag] = {}


def _parse(type_, raw: str):
    if type_ is bool:
        return raw.lower() in ("1", "true", "yes", "on")
    return type_(raw)


def define_flag(name: str, default: Any, help: str = "",
                type: Optional[type] = None,
                validator: Optional[Callable[[Any], bool]] = None):
    """Register a flag. Environment variable FLAGS_<name> overrides default."""
    t = type or (bool if isinstance(default, bool) else builtins_type(default))
    with _LOCK:
        if name in _REGISTRY:
            raise ValueError(f"flag '{name}' already defined")
        flag = _Flag(name, t, default, help, validator)
        env = os.environ.get("FLAGS_" + name)
        if env is not None:
            flag.value = _parse(t, env)
        _REGISTRY[name] = flag
    return flag


def builtins_type(v):
    return bool if isinstance(v, bool) else v.__class__


def set_flags(flags: Dict[str, Any]):
    """Set one or more flags at runtime (paddle.set_flags equivalent)."""
    with _LOCK:
        for k, v in flags.items():
            k = k[len("FLAGS_"):] if k.startswith("FLAGS_") else k
            if k not in _REGISTRY:
                raise KeyError(f"unknown flag '{k}'")
            f = _REGISTRY[k]
            if isinstance(v, str) and f.type is not str:
                v = _parse(f.type, v)
            if f.validator is not None and not f.validator(v):
                raise ValueError(f"invalid value {v!r} for flag '{k}'")
            f.value = f.type(v) if f.type is not bool else bool(v)


def get_flags(flags=None) -> Dict[str, Any]:
    """Read flags. `flags` may be a name, list of names, or None for all."""
    with _LOCK:
        if flags is None:
            names = list(_REGISTRY)
        elif isinstance(flags, str):
            names = [flags]
        else:
            names = list(flags)
        out = {}
        for k in names:
            k2 = k[len("FLAGS_"):] if k.startswith("FLAGS_") else k
            if k2 not in _REGISTRY:
                raise KeyError(f"unknown flag '{k}'")
            out[k] = _REGISTRY[k2].value
        return out


def flag_value(name: str):
    return _REGISTRY[name].value


# ---------------------------------------------------------------------------
# Core flags (subset of reference platform/flags.cc relevant to a TPU build)
# ---------------------------------------------------------------------------
define_flag("check_nan_inf", False,
            "Scan op outputs for NaN/Inf after each eager op (debug).")
define_flag("eager_op_jit", True,
            "Use a per-op jit cache for eager execution (lower dispatch "
            "overhead; compiled path is the real perf story).")
define_flag("benchmark", False, "Record per-op timing stats in eager mode.")
define_flag("op_stats", False,
            "Count per-op eager dispatches in the stat monitor "
            "(platform/monitor.h analogue).")
define_flag("seed", 0, "Global RNG seed (0 = nondeterministic).")
define_flag("allocator_strategy", "xla",
            "Memory strategy. XLA owns device memory on TPU; this flag exists "
            "for capability parity and host-side pools.")
define_flag("tpu_matmul_precision", "default",
            "jax.lax matmul precision: default|high|highest.")
define_flag("use_bf16_compute", True,
            "Prefer bfloat16 compute in AMP lists (TPU MXU native).")
define_flag("log_level", 0, "Verbosity (glog VLOG analogue).")
define_flag("compile_cache_dir",
            os.environ.get("PD_COMPILE_CACHE_DIR", ""),
            "Persistent XLA compilation-cache directory (PERF_PLAN "
            "staged lever #6: cached executables give the 20-40 s "
            "per-program compile back to reruns). Set via "
            "PD_COMPILE_CACHE_DIR or FLAGS_compile_cache_dir; empty "
            "disables. Applied to jax.config at import when the env "
            "is set, or on demand via apply_compile_cache().")


def apply_compile_cache(path: Optional[str] = None,
                        min_compile_secs: Optional[float] = None) -> bool:
    """Point jax's persistent compilation cache at the configured
    directory. Returns True when a cache was enabled. `path` overrides
    the flag; `min_compile_secs` optionally lowers the admission
    threshold (jax default only persists compiles slower than ~1 s —
    CPU test programs need 0.0 to observe hits). Cache *hits* are
    observable through the sentinel's jax.monitoring listener
    (jax.compile_cache.requests / jax.compile_cache.hits counters)."""
    p = path if path is not None else (
        flag_value("compile_cache_dir")
        # env read again at call time: entry points (bench.py) set
        # PD_COMPILE_CACHE_DIR after this module's import snapshot
        or os.environ.get("PD_COMPILE_CACHE_DIR", ""))
    if not p:
        return False
    import jax
    jax.config.update("jax_compilation_cache_dir", p)
    # jax latches the cache-disabled verdict at the FIRST compile
    # (compilation_cache._cache_checked/_cache_initialized): enabling
    # the dir after anything compiled leaves a permanently-None cache
    # that silently never reads or writes. Reset the latches so
    # mid-process enabling (bench probes compile before main() flips
    # the flag) actually takes effect.
    try:
        from jax._src import compilation_cache as _cc
        _cc.reset_cache()
    except Exception:  # pragma: no cover — internal API drift
        pass
    if min_compile_secs is not None:
        try:
            jax.config.update("jax_persistent_cache_min_compile_time_secs",
                              float(min_compile_secs))
        except Exception:  # pragma: no cover — older config name
            pass
    return True


if os.environ.get("PD_COMPILE_CACHE_DIR"):
    # startup wiring: the env var alone turns the cache on for every
    # entry point (bench, tools, user scripts) without code changes
    apply_compile_cache()
