"""SelectedRows: row-sparse gradient representation + row-wise updates.

Reference: paddle/fluid/framework/selected_rows.h:41 (rows + value slab +
height) and operators/math/selected_rows_functor.* (scatter-add merge,
sgd/adam sparse updates on rows).

TPU design decision: under jit, XLA already turns embedding backward into
a scatter-add — dense materialization never happens on-chip, so the
compiled path needs no SelectedRows. The eager path and host-side update
utilities keep the row-sparse form for the reference's capability surface
(huge embedding tables where a dense [vocab, dim] grad is unaffordable):
grads stay (rows, values) and optimizers update only the touched rows.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["SelectedRows", "embedding_grad_rows", "merge_selected_rows",
           "sparse_row_update"]


class SelectedRows:
    """Row-sparse slab: `value[i]` is the data for logical row `rows[i]`
    of a dense [height, ...] tensor. Rows may repeat (unmerged grads)."""

    def __init__(self, rows, value, height: int):
        self.rows = jnp.asarray(rows, jnp.int32)
        self.value = jnp.asarray(value)
        self.height = int(height)

    @property
    def shape(self):
        return (self.height,) + tuple(self.value.shape[1:])

    def to_dense(self):
        dense = jnp.zeros(self.shape, self.value.dtype)
        return dense.at[self.rows].add(self.value)

    def __repr__(self):
        return (f"SelectedRows(height={self.height}, "
                f"nnz_rows={self.rows.shape[0]}, "
                f"row_shape={self.value.shape[1:]})")


def merge_selected_rows(sr: SelectedRows) -> SelectedRows:
    """Sum duplicate rows (ref scatter::MergeAdd). Static-shape friendly:
    output keeps the same capacity with unique rows front-packed; padding
    rows point at row 0 with zero values."""
    uniq, inv = jnp.unique(sr.rows, return_inverse=True,
                           size=sr.rows.shape[0], fill_value=-1)
    merged = jnp.zeros_like(sr.value)
    merged = merged.at[inv].add(sr.value)
    valid = uniq >= 0
    rows = jnp.where(valid, uniq, 0)
    merged = merged * valid[:, None].astype(merged.dtype)
    return SelectedRows(rows, merged, sr.height)


def embedding_grad_rows(ids, grad_out, height: int) -> SelectedRows:
    """Build the row-sparse gradient of an embedding lookup: ids [...],
    grad_out [..., dim] -> SelectedRows over the table's rows (ref
    lookup_table_v2_grad with is_sparse=True)."""
    flat_ids = jnp.reshape(ids, (-1,))
    flat_g = jnp.reshape(grad_out, (flat_ids.shape[0], -1))
    return merge_selected_rows(
        SelectedRows(flat_ids, flat_g, height))


def sparse_row_update(param, sr: SelectedRows, lr,
                      velocity: Optional[jax.Array] = None,
                      momentum: float = 0.0):
    """SGD/momentum touching only sr's rows (ref
    selected_rows_functor sgd; momentum optional). Returns
    (new_param, new_velocity)."""
    param = jnp.asarray(param)
    if velocity is not None:
        velocity = jnp.asarray(velocity)
    val = sr.value.reshape((sr.rows.shape[0],) + param.shape[1:])
    if velocity is None:
        return param.at[sr.rows].add(-lr * val), None
    v_rows = momentum * velocity[sr.rows] + val
    new_vel = velocity.at[sr.rows].set(v_rows)
    return param.at[sr.rows].add(-lr * v_rows), new_vel
