from . import dtypes, enforce, flags, generator, monitor, place
from .monitor import stat as monitor_stat, get_stats  # noqa: F401
from .selected_rows import (SelectedRows, embedding_grad_rows,  # noqa: F401
                            merge_selected_rows, sparse_row_update)
from .dtypes import (bool_, uint8, int8, int16, int32, int64, float16,
                     bfloat16, float32, float64, complex64, complex128,
                     convert_dtype, set_default_dtype, get_default_dtype)
from .enforce import (EnforceNotMet, InvalidArgumentError, NotFoundError,
                      enforce_eq, wrap_op_error)
from .flags import set_flags, get_flags, define_flag, flag_value
from .generator import Generator, default_generator, seed, next_key
from .place import (Place, CPUPlace, CUDAPinnedPlace, TPUPlace, CUDAPlace, XPUPlace,
                    set_device, get_device, current_place,
                    is_compiled_with_tpu, device_count)
