"""Error/enforce machinery with op-creation stack attribution.

Capability-parity with the reference's PADDLE_ENFORCE/EnforceNotMet
(/root/reference/paddle/fluid/platform/enforce.h) and op call-stack
attachment (/root/reference/paddle/fluid/framework/op_call_stack.cc):
errors raised during op execution carry the op name and the Python stack
where the op was invoked, with framework frames filtered out.
"""
from __future__ import annotations

import traceback


class EnforceNotMet(RuntimeError):
    """Raised when an enforce check fails; carries op attribution."""

    def __init__(self, message, op_type=None, user_stack=None):
        self.op_type = op_type
        self.user_stack = user_stack or []
        full = message
        if op_type:
            full = f"[operator < {op_type} > error] {message}"
        if self.user_stack:
            frames = "".join(self.user_stack)
            full += f"\n\n  [Operator creation stack]:\n{frames}"
        super().__init__(full)


class InvalidArgumentError(EnforceNotMet):
    pass


class NotFoundError(EnforceNotMet):
    pass


class OutOfRangeError(EnforceNotMet):
    pass


class UnimplementedError(EnforceNotMet):
    pass


def _user_frames(limit=6):
    """Extract user-code frames (filter out paddle_tpu internals)."""
    frames = traceback.extract_stack()[:-2]
    keep = [f for f in frames if "paddle_tpu" not in (f.filename or "")]
    return traceback.format_list(keep[-limit:])


def enforce(cond, message="enforce failed", exc=InvalidArgumentError,
            op_type=None):
    if not cond:
        raise exc(message, op_type=op_type, user_stack=_user_frames())


def enforce_eq(a, b, message=None, op_type=None):
    if a != b:
        raise InvalidArgumentError(
            message or f"expected equality, got {a!r} != {b!r}",
            op_type=op_type, user_stack=_user_frames())


def enforce_shape_match(shape_a, shape_b, message=None, op_type=None):
    if tuple(shape_a) != tuple(shape_b):
        raise InvalidArgumentError(
            message or f"shape mismatch: {tuple(shape_a)} vs {tuple(shape_b)}",
            op_type=op_type, user_stack=_user_frames())


def wrap_op_error(op_type, exc: Exception) -> EnforceNotMet:
    """Re-wrap an arbitrary exception raised inside an op kernel so it carries
    the op type and the user's creation stack (op_call_stack.cc analogue)."""
    if isinstance(exc, EnforceNotMet) and exc.op_type:
        return exc
    return EnforceNotMet(str(exc), op_type=op_type, user_stack=_user_frames())
