"""Device/Place layer over JAX devices.

Capability-parity with the reference Place variants
(/root/reference/paddle/fluid/platform/place.h:103 — CPUPlace, CUDAPlace,
XPUPlace, CUDAPinnedPlace) and DeviceContextPool
(/root/reference/paddle/fluid/platform/device_context.h:96,695), redesigned
TPU-first: a Place names a jax.Device; there are no streams or contexts to
manage (XLA owns them); the "pool" is jax.devices(). Meshes for SPMD live in
paddle_tpu.parallel.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax


class Place:
    """Base place: names a logical device kind + index."""

    kind = "unknown"

    def __init__(self, device_id: int = 0):
        self.device_id = int(device_id)

    # -- JAX bridge ---------------------------------------------------------
    def get_device(self) -> jax.Device:
        devs = [d for d in jax.devices() if _kind_of(d) == self.kind]
        if not devs:
            # graceful fallback: whatever the default backend exposes
            devs = jax.devices()
        return devs[self.device_id % len(devs)]

    def __eq__(self, other):
        return (isinstance(other, Place) and other.kind == self.kind
                and other.device_id == self.device_id)

    def __hash__(self):
        return hash((self.kind, self.device_id))

    def __repr__(self):
        return f"Place({self.kind}:{self.device_id})"


class CPUPlace(Place):
    kind = "cpu"


class TPUPlace(Place):
    """The headline device of this framework (reference: CUDAPlace)."""
    kind = "tpu"


class CUDAPlace(Place):  # capability alias: JAX gpu backend
    kind = "gpu"


class CUDAPinnedPlace(Place):
    """Pinned-host place (reference CUDAPinnedPlace): host staging
    memory; on TPU all host arrays are staged by the runtime, so this
    is CPU-kind for placement purposes."""

    def __init__(self):
        super().__init__("cpu", 0)


class XPUPlace(Place):
    kind = "xpu"


def _kind_of(d: jax.Device) -> str:
    plat = d.platform
    # axon/tpu-ish platforms all count as "tpu"
    if plat in ("tpu", "axon"):
        return "tpu"
    return plat


@functools.lru_cache(maxsize=None)
def _default_place() -> Place:
    kinds = {_kind_of(d) for d in jax.devices()}
    if "tpu" in kinds:
        return TPUPlace(0)
    if "gpu" in kinds:
        return CUDAPlace(0)
    return CPUPlace(0)


_current_place: Optional[Place] = None


def set_device(device) -> Place:
    """paddle.set_device equivalent. Accepts 'tpu', 'tpu:1', 'cpu', Place."""
    global _current_place
    if isinstance(device, Place):
        _current_place = device
        return device
    name, _, idx = str(device).partition(":")
    idx = int(idx) if idx else 0
    cls = {"cpu": CPUPlace, "tpu": TPUPlace, "gpu": CUDAPlace,
           "xpu": XPUPlace}.get(name)
    if cls is None:
        raise ValueError(f"unknown device '{device}'")
    _current_place = cls(idx)
    return _current_place


def get_device() -> str:
    p = current_place()
    return f"{p.kind}:{p.device_id}"


def current_place() -> Place:
    return _current_place if _current_place is not None else _default_place()


def is_compiled_with_tpu() -> bool:
    try:
        return any(_kind_of(d) == "tpu" for d in jax.devices())
    except RuntimeError:
        return False


def device_count(kind: Optional[str] = None) -> int:
    if kind is None:
        return len(jax.devices())
    return len([d for d in jax.devices() if _kind_of(d) == kind])
