"""Stat monitor: lock-free-ish named stat registry.

Reference: paddle/fluid/platform/monitor.h:44 (StatValue<T> registry,
STAT_GPU memory counters, ExportedStatValue dump).
"""
from __future__ import annotations

import threading
from typing import Dict, List

__all__ = ["StatValue", "stat", "get_stats", "reset_all", "log_stat"]


class StatValue:
    """A named monotonic/gauge counter (StatValue<T> analogue)."""

    __slots__ = ("name", "_value", "_lock")

    def __init__(self, name: str):
        self.name = name
        self._value = 0
        self._lock = threading.Lock()

    def add(self, v=1):
        with self._lock:
            self._value += v
        return self

    def set(self, v):
        with self._lock:
            self._value = v
        return self

    def get(self):
        return self._value

    def reset(self):
        with self._lock:
            self._value = 0


_registry: Dict[str, StatValue] = {}
_registry_lock = threading.Lock()


def stat(name: str) -> StatValue:
    """Get-or-create the named stat (STAT_INT registration analogue)."""
    s = _registry.get(name)
    if s is None:
        with _registry_lock:
            s = _registry.setdefault(name, StatValue(name))
    return s


def log_stat(name: str, value):
    stat(name).set(value)


def get_stats() -> Dict[str, int]:
    """ExportedStatValue dump."""
    return {k: v.get() for k, v in sorted(_registry.items())}


def reset_all():
    for v in _registry.values():
        v.reset()
