"""Stat monitor: named stat registry (compat shim).

Reference: paddle/fluid/platform/monitor.h:44 (StatValue<T> registry,
STAT_GPU memory counters, ExportedStatValue dump).

This is now a thin compatibility surface over the full metrics runtime
in ``paddle_tpu.observability.metrics``: ``stat(name)`` resolves to an
always-on gauge there (monitor stats are explicitly requested by their
caller, so they bypass the observability enable gate — the
FLAGS_op_stats contract predates the gate), and ``get_stats`` dumps
only the stats created through this API, keeping its historical
"name -> value" shape.
"""
from __future__ import annotations

from typing import Dict

from ..observability import metrics as _metrics

__all__ = ["StatValue", "stat", "get_stats", "reset_all", "log_stat"]

# names created through this API — instruments are re-resolved from the
# registry on every access, so a metrics.clear() (test isolation) can't
# leave monitor callers counting into detached gauges the exporters
# never see
_mine: set = set()

class StatValue(_metrics.Gauge):
    """The observability Gauge with monitor.h's unconditional-count
    semantics baked in: a directly-constructed StatValue records
    regardless of the metrics enable gate (as the pre-shim class did).
    Instances built here are standalone (not registry-resident); use
    stat() for exporter-visible stats."""

    def __init__(self, name: str):
        super().__init__(name, labels=(), always=True)


def stat(name: str) -> StatValue:
    """Get-or-create the named stat (STAT_INT registration analogue)."""
    _mine.add(name)
    return _metrics.gauge(name, _always=True)


def log_stat(name: str, value):
    stat(name).set(value)


def get_stats() -> Dict[str, int]:
    """ExportedStatValue dump (monitor-created stats only)."""
    return {k: _metrics.gauge(k, _always=True).get()
            for k in sorted(_mine)}


def reset_all():
    for k in _mine:
        _metrics.gauge(k, _always=True).reset()
