"""Artifact format versioning + per-op version registry.

Reference: paddle/fluid/framework/op_version_registry.h — every op
carries a version so checkpoints written by an older framework can be
migrated (or rejected with a clear error) at load time. Here the same
contract covers the two durable artifact kinds:

- serialized Programs (static/program.py to_bytes): a program-format
  version plus the per-op versions in force at save time; load migrates
  older formats stepwise and runs per-op migrations for ops whose
  registered version moved.
- paddle.save state bundles (serialization.py): an envelope format
  version; pre-envelope blobs load as legacy (v0).
"""
from __future__ import annotations

from typing import Any, Callable, Dict

__all__ = [
    "PROGRAM_FORMAT_VERSION", "STATE_FORMAT_VERSION",
    "register_op_version", "op_version", "register_op_migration",
    "migrate_program_dict", "migrate_op_entry", "check_state_format",
]

# program pickle layout: v1 = round-2 layout (no op_versions field);
# v2 = adds "op_versions" {op_type: int};
# v3 = adds the backward + optimize sections ("grad_target",
#      "grad_pairs", "var_grads", "optimize", "opt_state") so a saved
#      training program keeps its whole graph — the framework.proto:178
#      contract where grad ops serialize as ordinary block ops
PROGRAM_FORMAT_VERSION = 3
# paddle.save envelope: v0 = raw pickled payload (legacy), v1 = envelope
STATE_FORMAT_VERSION = 1

# -- per-op versions (op_version_registry.h analogue) -----------------------
_OP_VERSIONS: Dict[str, int] = {}
# (op_type, from_version) -> fn(const_args, kwargs) -> (const_args, kwargs)
_OP_MIGRATIONS: Dict[tuple, Callable] = {}


def register_op_version(op_type: str, version: int):
    """Declare the current version of an op's serialized attribute
    layout. Unregistered ops are implicitly version 1."""
    _OP_VERSIONS[op_type] = int(version)


def op_version(op_type: str) -> int:
    return _OP_VERSIONS.get(op_type, 1)


def register_op_migration(op_type: str, from_version: int):
    """Decorator: migration of one op's saved (const_args, kwargs) from
    `from_version` to `from_version + 1`."""
    def deco(fn):
        _OP_MIGRATIONS[(op_type, from_version)] = fn
        return fn
    return deco


def migrate_op_entry(op_type: str, saved_version: int, const_args,
                     kwargs):
    """Bring one deserialized op's attributes up to the current
    registered version."""
    current = op_version(op_type)
    if saved_version > current:
        raise ValueError(
            f"op '{op_type}' was saved at version {saved_version} but "
            f"this framework implements version {current}; upgrade the "
            "framework to load this program")
    v = saved_version
    while v < current:
        fn = _OP_MIGRATIONS.get((op_type, v))
        if fn is None:
            raise ValueError(
                f"op '{op_type}' has no registered migration from "
                f"version {v} -> {v + 1}")
        const_args, kwargs = fn(const_args, kwargs)
        v += 1
    return const_args, kwargs


# -- program format ---------------------------------------------------------
_PROGRAM_MIGRATIONS: Dict[int, Callable[[dict], dict]] = {}


def _register_program_migration(from_version: int):
    def deco(fn):
        _PROGRAM_MIGRATIONS[from_version] = fn
        return fn
    return deco


@_register_program_migration(1)
def _program_v1_to_v2(d: dict) -> dict:
    # v1 had no op_versions: everything it could save was version 1
    d = dict(d)
    d["op_versions"] = {}
    d["version"] = 2
    return d


@_register_program_migration(2)
def _program_v2_to_v3(d: dict) -> dict:
    # v2 dropped the backward/optimize bookkeeping on the floor (the
    # round-3 lost-backward defect); a v2 blob genuinely has none, so
    # the migration is empty sections — loading then fetching a grad var
    # raises NotFoundError loudly instead of returning None
    d = dict(d)
    d.setdefault("grad_target", None)
    d.setdefault("grad_pairs", [])
    d.setdefault("var_grads", [])
    d.setdefault("optimize", None)
    d.setdefault("opt_state", None)
    d["version"] = 3
    return d


def migrate_program_dict(d: dict) -> dict:
    v = int(d.get("version", 1))
    if v > PROGRAM_FORMAT_VERSION:
        raise ValueError(
            f"program was saved with format version {v}; this framework "
            f"reads up to {PROGRAM_FORMAT_VERSION} — upgrade to load it")
    while v < PROGRAM_FORMAT_VERSION:
        fn = _PROGRAM_MIGRATIONS.get(v)
        if fn is None:
            raise ValueError(f"no program migration from version {v}")
        d = fn(d)
        v = int(d["version"])
    return d


# -- state bundle envelope --------------------------------------------------
def check_state_format(data: Any):
    """Return (payload, version) for a loaded paddle.save blob; raises on
    a future format."""
    if isinstance(data, dict) and "__paddle_tpu_format__" in data:
        v = int(data["__paddle_tpu_format__"])
        if v > STATE_FORMAT_VERSION:
            raise ValueError(
                f"checkpoint was saved with format version {v}; this "
                f"framework reads up to {STATE_FORMAT_VERSION}")
        return data["payload"], v
    return data, 0  # legacy pre-envelope blob
