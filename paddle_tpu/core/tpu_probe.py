"""Wedge-safe TPU liveness probe.

The first jax call of a process must never gamble on a hung backend: a
wedged TPU tunnel blocks backend init forever and an in-process hang is
unrecoverable (the round-2 postmortem: bench rc=1, dryrun rc=124). The
probe initializes the backend, runs a matmul, and host-reads the result
in a THROWAWAY subprocess under a timeout — SIGTERM with a grace period
before SIGKILL, because a hard kill mid-TPU-execution can wedge a
merely-slow tunnel permanently.

Consumers: bench.py, tools/tpu_first_light.py, examples that default to
the accelerator but must degrade to CPU instead of hanging.
"""
from __future__ import annotations

import os
import subprocess
import sys

__all__ = ["probe_tpu", "ensure_tpu_or_cpu", "probe_kernel_dropout"]


def probe_tpu(timeout_s: float = None):
    """-> (on_tpu: bool, platform_or_error: str)."""
    timeout_s = timeout_s or float(os.environ.get("PD_TPU_PROBE_TIMEOUT",
                                                  180))
    code = ("import jax, jax.numpy as jnp; d = jax.devices(); "
            "x = jnp.ones((128, 128)) @ jnp.ones((128, 128)); "
            "assert float(x[0, 0]) == 128.0; "
            "print('PLATFORM', d[0].platform, flush=True)")
    proc = subprocess.Popen([sys.executable, "-c", code],
                            stdout=subprocess.PIPE,
                            stderr=subprocess.PIPE, text=True)
    try:
        stdout, stderr = proc.communicate(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        proc.terminate()
        try:
            proc.communicate(timeout=15)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.communicate()
        return False, (f"backend init/exec timed out after {timeout_s:.0f}s"
                       " (wedged TPU tunnel)")
    if proc.returncode != 0:
        tail = (stderr or "").strip().splitlines()[-1:] or ["no stderr"]
        return False, f"backend init failed rc={proc.returncode}: {tail[0]}"
    out = (stdout or "").strip().split()
    plat = out[-1] if out else "?"
    if plat in ("tpu", "axon"):
        return True, plat
    return False, plat  # healthy non-TPU host: not an error


def probe_kernel_dropout(timeout_s: float = 600.0):
    """Run kernel_dropout_available() in a THROWAWAY subprocess with
    the same SIGTERM-grace semantics as probe_tpu (a hard kill mid-
    Mosaic-compile can wedge a merely-slow tunnel). The ONE shared
    implementation for bench.py and tools/tpu_first_light.py.

    -> "ok" | "fallback" | "error: <detail>" — callers pin
    PD_KERNEL_DROPOUT to "1" only for "ok"."""
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    code = ("import sys; sys.path.insert(0, %r); "
            "from paddle_tpu.ops.pallas_kernels import "
            "kernel_dropout_available; "
            "print('KD_OK' if kernel_dropout_available() else 'KD_NO',"
            " flush=True)" % repo)
    env = dict(os.environ)
    env.pop("PD_KERNEL_DROPOUT", None)  # a stale pin would
    # short-circuit the probe and re-propagate itself
    proc = subprocess.Popen([sys.executable, "-c", code], env=env,
                            stdout=subprocess.PIPE,
                            stderr=subprocess.PIPE, text=True)
    try:
        stdout, stderr = proc.communicate(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        proc.terminate()
        try:
            proc.communicate(timeout=15)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.communicate()
        return f"error: probe timed out after {timeout_s:.0f}s"
    if "KD_OK" in (stdout or ""):
        return "ok"
    if "KD_NO" in (stdout or ""):
        return "fallback"  # clean self-check refusal (e.g. MosaicError)
    tail = (stderr or "").strip().splitlines()[-1:] or ["no stderr"]
    return f"error: rc={proc.returncode}: {tail[0][:160]}"


def ensure_tpu_or_cpu(timeout_s: float = None, quiet: bool = False):
    """Probe; on failure force the CPU platform BEFORE any jax call in
    this process. Returns (on_tpu, info). For program entry points that
    prefer the accelerator but must never hang on a dead one."""
    on_tpu, info = probe_tpu(timeout_s)
    if not on_tpu:
        if not quiet and info != "cpu":
            print(f"[paddle_tpu] TPU unavailable ({info}); "
                  "falling back to CPU", file=sys.stderr)
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax
        jax.config.update("jax_platforms", "cpu")
    return on_tpu, info
