"""Wedge-safe TPU liveness probe.

The first jax call of a process must never gamble on a hung backend: a
wedged TPU tunnel blocks backend init forever and an in-process hang is
unrecoverable (the round-2 postmortem: bench rc=1, dryrun rc=124). The
probe initializes the backend, runs a matmul, and host-reads the result
in a THROWAWAY subprocess under a timeout — SIGTERM with a grace period
before SIGKILL, because a hard kill mid-TPU-execution can wedge a
merely-slow tunnel permanently.

Consumers: bench.py, tools/tpu_first_light.py, examples that default to
the accelerator but must degrade to CPU instead of hanging.
"""
from __future__ import annotations

import os
import subprocess
import sys

__all__ = ["probe_tpu", "ensure_tpu_or_cpu"]


def probe_tpu(timeout_s: float = None):
    """-> (on_tpu: bool, platform_or_error: str)."""
    timeout_s = timeout_s or float(os.environ.get("PD_TPU_PROBE_TIMEOUT",
                                                  180))
    code = ("import jax, jax.numpy as jnp; d = jax.devices(); "
            "x = jnp.ones((128, 128)) @ jnp.ones((128, 128)); "
            "assert float(x[0, 0]) == 128.0; "
            "print('PLATFORM', d[0].platform, flush=True)")
    proc = subprocess.Popen([sys.executable, "-c", code],
                            stdout=subprocess.PIPE,
                            stderr=subprocess.PIPE, text=True)
    try:
        stdout, stderr = proc.communicate(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        proc.terminate()
        try:
            proc.communicate(timeout=15)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.communicate()
        return False, (f"backend init/exec timed out after {timeout_s:.0f}s"
                       " (wedged TPU tunnel)")
    if proc.returncode != 0:
        tail = (stderr or "").strip().splitlines()[-1:] or ["no stderr"]
        return False, f"backend init failed rc={proc.returncode}: {tail[0]}"
    out = (stdout or "").strip().split()
    plat = out[-1] if out else "?"
    if plat in ("tpu", "axon"):
        return True, plat
    return False, plat  # healthy non-TPU host: not an error


def ensure_tpu_or_cpu(timeout_s: float = None, quiet: bool = False):
    """Probe; on failure force the CPU platform BEFORE any jax call in
    this process. Returns (on_tpu, info). For program entry points that
    prefer the accelerator but must never hang on a dead one."""
    on_tpu, info = probe_tpu(timeout_s)
    if not on_tpu:
        if not quiet and info != "cpu":
            print(f"[paddle_tpu] TPU unavailable ({info}); "
                  "falling back to CPU", file=sys.stderr)
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax
        jax.config.update("jax_platforms", "cpu")
    return on_tpu, info
