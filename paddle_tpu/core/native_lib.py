"""Loader for the native host-runtime library (csrc/runtime.cpp).

Builds on demand (like io/native_feed.py) and exposes the C ABI via
ctypes. Every consumer must tolerate `runtime_lib() is None` (no
toolchain) with a pure-Python fallback — native is the fast path, not a
hard dependency.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

__all__ = ["runtime_lib"]

_CSRC = os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))), "csrc")
_SO = os.path.join(_CSRC, "libpaddletpu_runtime.so")
_lock = threading.Lock()
_lib = None
_tried = False


def _build() -> Optional[str]:
    src = os.path.join(_CSRC, "runtime.cpp")
    if not os.path.exists(src):
        return None
    if os.path.exists(_SO) and \
            os.path.getmtime(_SO) >= os.path.getmtime(src):
        return _SO
    # the Makefile is the single source of truth for build flags
    res = subprocess.run(
        ["make", "-C", _CSRC, "libpaddletpu_runtime.so"],
        capture_output=True, text=True)
    if res.returncode != 0 or not os.path.exists(_SO):
        return None
    return _SO


def _bind(lib):
    i64, i32, cp = ctypes.c_int64, ctypes.c_int, ctypes.c_char_p
    u64 = ctypes.c_uint64
    lib.pd_prof_enable.argtypes = [i32]
    lib.pd_prof_now.restype = i64
    lib.pd_prof_span.argtypes = [cp, cp, i64, i64, i64]
    lib.pd_prof_count.restype = i64
    lib.pd_prof_dump.argtypes = [cp]
    lib.pd_prof_dump.restype = i32
    lib.pd_prof_summary.argtypes = [ctypes.c_char_p,
                                    ctypes.POINTER(i64),
                                    ctypes.POINTER(i64),
                                    ctypes.POINTER(i64), i32]
    lib.pd_prof_summary.restype = i32
    lib.pd_rdzv_serve.argtypes = [i32, cp, i32, i32]
    lib.pd_rdzv_serve.restype = i32
    lib.pd_rdzv_serve_done.argtypes = [i32]
    lib.pd_rdzv_serve_done.restype = i32
    lib.pd_rdzv_close.argtypes = [i32]
    lib.pd_rdzv_fetch.argtypes = [cp, i32, ctypes.c_char_p, i32, i32]
    lib.pd_rdzv_fetch.restype = i32
    lib.pd_shm_open.argtypes = [cp, u64, i32]
    lib.pd_shm_open.restype = i32
    lib.pd_shm_push.argtypes = [i32, ctypes.c_char_p, u64]
    lib.pd_shm_push.restype = i32
    lib.pd_shm_pop.argtypes = [i32, ctypes.c_char_p, u64, i32]
    lib.pd_shm_pop.restype = i64
    lib.pd_shm_count.argtypes = [i32]
    lib.pd_shm_count.restype = u64
    lib.pd_shm_close.argtypes = [i32]
    return lib


def runtime_lib():
    """The loaded native runtime, or None when unavailable."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        so = _build()
        if so is None:
            return None
        try:
            _lib = _bind(ctypes.CDLL(so))
        except OSError:
            _lib = None
    return _lib
