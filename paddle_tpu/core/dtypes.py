"""Dtype system for paddle_tpu.

TPU-first design: the canonical dtype set mirrors what the MXU/VPU support
natively (bfloat16 is first-class; float16 is supported but bf16 preferred).
Mirrors the capability of the reference dtype enum
(/root/reference/paddle/fluid/framework/framework.proto:106 VarType.Type)
without the LoD/encoding baggage — JAX/XLA owns layouts.
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

# Canonical dtypes (name -> jnp dtype)
bool_ = jnp.bool_
uint8 = jnp.uint8
int8 = jnp.int8
int16 = jnp.int16
int32 = jnp.int32
int64 = jnp.int64
float16 = jnp.float16
bfloat16 = jnp.bfloat16
float32 = jnp.float32
float64 = jnp.float64
complex64 = jnp.complex64
complex128 = jnp.complex128

_ALIASES = {
    "bool": bool_, "uint8": uint8, "int8": int8, "int16": int16,
    "int32": int32, "int64": int64, "float16": float16, "half": float16,
    "bfloat16": bfloat16, "bf16": bfloat16, "float32": float32,
    "float": float32, "fp32": float32, "float64": float64, "double": float64,
    "complex64": complex64, "complex128": complex128,
}

FLOATING = (float16, bfloat16, float32, float64)
INTEGER = (uint8, int8, int16, int32, int64)


def convert_dtype(dtype):
    """Normalize a user-provided dtype (str / np / jnp) to a numpy dtype obj."""
    if dtype is None:
        return None
    if isinstance(dtype, str):
        if dtype not in _ALIASES:
            raise ValueError(f"Unknown dtype '{dtype}'")
        return np.dtype(_ALIASES[dtype])
    return np.dtype(dtype)


def is_floating(dtype) -> bool:
    d = convert_dtype(dtype)
    return d in (np.dtype(t) for t in FLOATING)


def is_integer(dtype) -> bool:
    d = convert_dtype(dtype)
    return d in (np.dtype(t) for t in INTEGER)


# Default dtype management (mirrors paddle.set_default_dtype)
_default_dtype = np.dtype(np.float32)


def set_default_dtype(dtype):
    global _default_dtype
    d = convert_dtype(dtype)
    if not is_floating(d):
        raise TypeError(f"default dtype must be floating, got {d}")
    _default_dtype = d


def get_default_dtype():
    return _default_dtype
