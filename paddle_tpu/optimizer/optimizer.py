"""Optimizer base (python/paddle/optimizer/optimizer.py:48 parity).

TPU-first design: every optimizer is defined by two PURE functions —
``init_state(param) -> state dict`` and
``update_rule(param, grad, state, lr) -> (new_param, new_state)`` —
so the same rule drives both the eager ``step()`` (paddle surface) and
compiled/sharded training steps (paddle_tpu.static.TrainStep applies the
rule over a param pytree inside jit/pjit; ZeRO sharding shards `state`
over the dp axis). The reference instead writes one CUDA kernel per
optimizer (/root/reference/paddle/fluid/operators/optimizers/).
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import jax
import jax.numpy as jnp

from ..framework import Parameter, Tensor, no_grad
from .lr import LRScheduler

__all__ = ["Optimizer"]


class Optimizer:
    # hyperparameters exposed to the pure update rule
    _hyper_defaults: Dict[str, Any] = {}

    def __init__(self, learning_rate=0.001, parameters=None,
                 weight_decay=None, grad_clip=None, multi_precision=False,
                 name=None):
        self._lr = learning_rate
        self._parameters = list(parameters) if parameters is not None \
            else None
        self._grad_clip = grad_clip
        self._multi_precision = multi_precision
        self._wd_mode = "l2"
        if isinstance(weight_decay, float) or isinstance(weight_decay, int):
            self._weight_decay = float(weight_decay)
        elif weight_decay is None:
            self._weight_decay = 0.0
        else:  # regularizer.L1Decay/L2Decay-like object with a coeff
            self._weight_decay = float(
                getattr(weight_decay, "_coeff",
                        getattr(weight_decay, "coeff", 0.0)))
            self._wd_mode = getattr(weight_decay, "mode", "l2")
        # state: id(param) -> dict name->jax array
        self._accumulators: Dict[int, Dict[str, Any]] = {}
        self._step_count = 0

    # -- pure rule (override) ------------------------------------------------
    def init_state(self, param: jax.Array) -> Dict[str, Any]:
        return {}

    def update_rule(self, p, g, state, lr):
        raise NotImplementedError

    # -- master weights (multi_precision) -------------------------------------
    # Reference: python/paddle/optimizer/adam.py:30 multi_precision — low-
    # precision params keep an fp32 master copy in optimizer state; the
    # update runs in fp32 and the param is the cast-down of the master.
    _LOW_PRECISION = (jnp.float16, jnp.bfloat16)

    def _uses_master(self, param) -> bool:
        return bool(self._multi_precision) and \
            param.dtype in self._LOW_PRECISION

    def _init_param_state(self, param):
        if self._uses_master(param):
            master = param.astype(jnp.float32)
            st = self.init_state(master)  # fp32 moments
            st["master_weight"] = master
            return st
        return self.init_state(param)

    def _apply_one(self, p, g, state, lr):
        """One param update honoring weight decay + master weights.
        Pure: usable eagerly and under jit."""
        wd = self._weight_decay
        master = state.get("master_weight") if isinstance(state, dict) \
            else None
        if master is not None:
            inner = {k: v for k, v in state.items() if k != "master_weight"}
            g32 = g.astype(jnp.float32)
            if wd and not self._decoupled_wd:
                g32 = g32 + wd * (jnp.sign(master)
                                  if self._wd_mode == "l1" else master)
            new_master, new_state = self.update_rule(master, g32, inner, lr)
            if self._decoupled_wd and wd:
                new_master = new_master - lr * wd * master
            new_state["master_weight"] = new_master
            return new_master.astype(p.dtype), new_state
        g = g.astype(p.dtype)
        if wd and not self._decoupled_wd:
            g = g + wd * (jnp.sign(p) if self._wd_mode == "l1" else p)
        new_p, new_state = self.update_rule(p, g, state, lr)
        if self._decoupled_wd and wd:
            new_p = new_p - lr * wd * p
        return new_p, new_state

    # decoupled weight decay? (AdamW) — L2-style adds wd*p to grad
    _decoupled_wd = False

    # -- LR ------------------------------------------------------------------
    def get_lr(self) -> float:
        if isinstance(self._lr, LRScheduler):
            return float(self._lr())
        return float(self._lr)

    def set_lr(self, value):
        self._lr = value

    def set_lr_scheduler(self, scheduler):
        self._lr = scheduler

    @property
    def _learning_rate(self):
        return self._lr

    # -- eager step ----------------------------------------------------------
    def _param_list(self):
        if self._parameters is None:
            raise ValueError(
                "optimizer constructed without parameters; pass parameters= "
                "or use the functional API")
        return self._parameters

    @no_grad()
    def step(self):
        params = self._param_list()
        pg = [(p, p.grad) for p in params
              if not p.stop_gradient and p._grad is not None]
        if self._grad_clip is not None:
            pg = self._grad_clip(pg)
        lr = self.get_lr()
        self._step_count += 1
        for p, g in pg:
            if g is None:
                continue
            garr = g._data if isinstance(g, Tensor) else g
            state = self._accumulators.get(id(p))
            if state is None:
                state = self._init_param_state(p._data)
                self._accumulators[id(p)] = state
            new_p, new_state = self._apply_one(p._data, garr, state, lr)
            p._data = new_p
            self._accumulators[id(p)] = new_state

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        from ..static.program import Var, append_backward
        if isinstance(loss, Var):
            # static mode: record the optimize stage on the program;
            # Executor.run compiles fwd+bwd+update into one executable
            prog = loss.program
            pairs = append_backward(loss, parameters)
            prog._optimize = (self, loss)
            return None, pairs
        loss.backward()
        self.step()
        return None, [(p, p.grad) for p in self._param_list()]

    def clear_grad(self, set_to_zero=False):
        for p in self._param_list():
            p.clear_gradient(set_to_zero)

    clear_gradients = clear_grad

    # -- functional API for compiled steps ------------------------------------
    def init_state_tree(self, params_tree):
        """init_state over a pytree of arrays (for jit'd train steps).
        Adds fp32 master_weight entries for low-precision params when
        multi_precision is on."""
        return jax.tree_util.tree_map(self._init_param_state, params_tree)

    def apply_gradients_tree(self, params_tree, grads_tree, state_tree,
                             lr=None):
        """Pure pytree update: returns (new_params, new_state). Usable under
        jit/pjit/shard_map; lr may be a traced scalar."""
        lr = lr if lr is not None else self.get_lr()
        flat_p, tdef = jax.tree_util.tree_flatten(params_tree)
        flat_g = tdef.flatten_up_to(grads_tree)
        flat_s = tdef.flatten_up_to(state_tree)
        new = [self._apply_one(p, g, s, lr)
               for p, g, s in zip(flat_p, flat_g, flat_s)]
        new_p = tdef.unflatten([a for a, _ in new])
        new_s = tdef.unflatten([b for _, b in new])
        return new_p, new_s

    # -- state dict ------------------------------------------------------------
    def state_dict(self):
        out = {"_step_count": self._step_count}
        params = self._parameters or []
        for i, p in enumerate(params):
            key = p.name or f"param_{i}"
            state = self._accumulators.get(id(p))
            if state:
                out[key] = {k: Tensor(v) if isinstance(v, jax.Array) else v
                            for k, v in state.items()}
        if isinstance(self._lr, LRScheduler):
            out["LR_Scheduler"] = self._lr.state_dict()
        return out

    def set_state_dict(self, state):
        self._step_count = state.get("_step_count", 0)
        params = self._parameters or []
        for i, p in enumerate(params):
            key = p.name or f"param_{i}"
            if key in state:
                self._accumulators[id(p)] = {
                    k: (v._data if isinstance(v, Tensor) else v)
                    for k, v in state[key].items()}
        if "LR_Scheduler" in state and isinstance(self._lr, LRScheduler):
            self._lr.set_state_dict(state["LR_Scheduler"])

    set_dict = set_state_dict
