"""Concrete optimizers (python/paddle/optimizer/* + reference
operators/optimizers/ CUDA kernels — here: pure jnp update rules).

SGD, Momentum, Adam, AdamW, Adagrad, Adadelta, Adamax, RMSProp, Lamb,
Lars — each a pair of pure functions on arrays (see Optimizer docstring).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .optimizer import Optimizer

__all__ = ["SGD", "Momentum", "Adam", "AdamW", "Adagrad", "Adadelta",
           "Adamax", "RMSProp", "Lamb", "Lars"]


class SGD(Optimizer):
    def update_rule(self, p, g, state, lr):
        return p - lr * g, state


class Momentum(Optimizer):
    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision, name)
        self._momentum = momentum
        self._nesterov = use_nesterov

    def init_state(self, param):
        return {"velocity": jnp.zeros_like(param)}

    def update_rule(self, p, g, state, lr):
        v = self._momentum * state["velocity"] + g
        if self._nesterov:
            p_new = p - lr * (g + self._momentum * v)
        else:
            p_new = p - lr * v
        return p_new, {"velocity": v}


class Adam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, lazy_mode=False, multi_precision=False,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision, name)
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon

    def init_state(self, param):
        return {
            "moment1": jnp.zeros_like(param),
            "moment2": jnp.zeros_like(param),
            "beta1_pow": jnp.ones((), param.dtype),
            "beta2_pow": jnp.ones((), param.dtype),
        }

    def update_rule(self, p, g, state, lr):
        b1, b2, eps = self._beta1, self._beta2, self._epsilon
        m = b1 * state["moment1"] + (1 - b1) * g
        v = b2 * state["moment2"] + (1 - b2) * jnp.square(g)
        b1p = state["beta1_pow"] * b1
        b2p = state["beta2_pow"] * b2
        m_hat = m / (1 - b1p)
        v_hat = v / (1 - b2p)
        p_new = p - lr * m_hat / (jnp.sqrt(v_hat) + eps)
        return p_new, {"moment1": m, "moment2": v, "beta1_pow": b1p,
                       "beta2_pow": b2p}


class AdamW(Adam):
    _decoupled_wd = True

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=0.01,
                 lr_ratio=None, apply_decay_param_fun=None, grad_clip=None,
                 lazy_mode=False, multi_precision=False, name=None):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         weight_decay, grad_clip, lazy_mode, multi_precision,
                         name)
        self._apply_decay_param_fun = apply_decay_param_fun


class Adagrad(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, parameters=None,
                 weight_decay=None, grad_clip=None, initial_accumulator_value
                 =0.0, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name=name)
        self._epsilon = epsilon
        self._init_acc = initial_accumulator_value

    def init_state(self, param):
        return {"moment": jnp.full_like(param, self._init_acc)}

    def update_rule(self, p, g, state, lr):
        acc = state["moment"] + jnp.square(g)
        p_new = p - lr * g / (jnp.sqrt(acc) + self._epsilon)
        return p_new, {"moment": acc}


class Adadelta(Optimizer):
    def __init__(self, learning_rate=0.001, epsilon=1e-6, rho=0.95,
                 parameters=None, weight_decay=None, grad_clip=None,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name=name)
        self._epsilon = epsilon
        self._rho = rho

    def init_state(self, param):
        return {"avg_squared_grad": jnp.zeros_like(param),
                "avg_squared_update": jnp.zeros_like(param)}

    def update_rule(self, p, g, state, lr):
        rho, eps = self._rho, self._epsilon
        sg = rho * state["avg_squared_grad"] + (1 - rho) * jnp.square(g)
        update = (jnp.sqrt(state["avg_squared_update"] + eps)
                  / jnp.sqrt(sg + eps)) * g
        su = rho * state["avg_squared_update"] + (1 - rho) * jnp.square(
            update)
        return p - lr * update, {"avg_squared_grad": sg,
                                 "avg_squared_update": su}


class Adamax(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name=name)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def init_state(self, param):
        return {"moment": jnp.zeros_like(param),
                "inf_norm": jnp.zeros_like(param),
                "beta1_pow": jnp.ones((), param.dtype)}

    def update_rule(self, p, g, state, lr):
        b1, b2, eps = self._beta1, self._beta2, self._epsilon
        m = b1 * state["moment"] + (1 - b1) * g
        u = jnp.maximum(b2 * state["inf_norm"], jnp.abs(g))
        b1p = state["beta1_pow"] * b1
        p_new = p - (lr / (1 - b1p)) * m / (u + eps)
        return p_new, {"moment": m, "inf_norm": u, "beta1_pow": b1p}


class RMSProp(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name=name)
        self._rho, self._epsilon = rho, epsilon
        self._momentum, self._centered = momentum, centered

    def init_state(self, param):
        s = {"mean_square": jnp.zeros_like(param),
             "momentum": jnp.zeros_like(param)}
        if self._centered:
            s["mean_grad"] = jnp.zeros_like(param)
        return s

    def update_rule(self, p, g, state, lr):
        rho, eps = self._rho, self._epsilon
        ms = rho * state["mean_square"] + (1 - rho) * jnp.square(g)
        new_state = {"mean_square": ms}
        if self._centered:
            mg = rho * state["mean_grad"] + (1 - rho) * g
            denom = jnp.sqrt(ms - jnp.square(mg) + eps)
            new_state["mean_grad"] = mg
        else:
            denom = jnp.sqrt(ms + eps)
        mom = self._momentum * state["momentum"] + lr * g / denom
        new_state["momentum"] = mom
        return p - mom, new_state


class Lamb(Optimizer):
    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01,
                 beta1=0.9, beta2=0.999, epsilon=1e-6, parameters=None,
                 grad_clip=None, exclude_from_weight_decay_fn=None,
                 name=None):
        super().__init__(learning_rate, parameters, None, grad_clip,
                         name=name)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon
        self._lamb_wd = lamb_weight_decay
        self._exclude_fn = exclude_from_weight_decay_fn

    def init_state(self, param):
        return {"moment1": jnp.zeros_like(param),
                "moment2": jnp.zeros_like(param),
                "beta1_pow": jnp.ones((), param.dtype),
                "beta2_pow": jnp.ones((), param.dtype)}

    def update_rule(self, p, g, state, lr):
        b1, b2, eps = self._beta1, self._beta2, self._epsilon
        m = b1 * state["moment1"] + (1 - b1) * g
        v = b2 * state["moment2"] + (1 - b2) * jnp.square(g)
        b1p = state["beta1_pow"] * b1
        b2p = state["beta2_pow"] * b2
        m_hat = m / (1 - b1p)
        v_hat = v / (1 - b2p)
        r = m_hat / (jnp.sqrt(v_hat) + eps) + self._lamb_wd * p
        w_norm = jnp.linalg.norm(p.astype(jnp.float32))
        r_norm = jnp.linalg.norm(r.astype(jnp.float32))
        trust = jnp.where((w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0)
        p_new = p - lr * trust.astype(p.dtype) * r
        return p_new, {"moment1": m, "moment2": v, "beta1_pow": b1p,
                       "beta2_pow": b2p}


class Lars(Optimizer):
    """LARS (reference lars_momentum_op)."""

    def __init__(self, learning_rate, momentum=0.9, lars_coeff=0.001,
                 lars_weight_decay=0.0005, parameters=None, grad_clip=None,
                 epsilon=1e-9, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip,
                         name=name)
        self._momentum = momentum
        self._lars_coeff = lars_coeff
        self._lars_wd = lars_weight_decay
        self._epsilon = epsilon

    def init_state(self, param):
        return {"velocity": jnp.zeros_like(param)}

    def update_rule(self, p, g, state, lr):
        w_norm = jnp.linalg.norm(p.astype(jnp.float32))
        g_norm = jnp.linalg.norm(g.astype(jnp.float32))
        local_lr = jnp.where(
            (w_norm > 0) & (g_norm > 0),
            self._lars_coeff * w_norm
            / (g_norm + self._lars_wd * w_norm + self._epsilon), 1.0)
        v = self._momentum * state["velocity"] + lr * local_lr.astype(
            p.dtype) * (g + self._lars_wd * p)
        return p - v, {"velocity": v}
