"""Weight-averaging training utilities: EMA, ModelAverage, Lookahead.

Reference: python/paddle/fluid/optimizer.py — ExponentialMovingAverage
(:3466, shadow vars updated as s = decay*s + (1-decay)*p with optional
thres_steps-ramped decay and bias correction), ModelAverage (:3157,
sliding-window parameter sums with apply/restore scopes) and
LookaheadOptimizer (:5238, slow/fast weights: every k steps
slow += alpha*(fast-slow), fast = slow).

TPU-first: all three are pure array transforms over the live parameter
list — shadow state is a dict of jax arrays, apply()/restore() swap
param buffers in place (no Program rewriting), and every update is a
handful of fused elementwise ops XLA executes in one kernel. Usable
from eager loops and from hapi callbacks alike.
"""
from __future__ import annotations

import contextlib
from typing import Dict, Optional

import jax.numpy as jnp
import numpy as np

from ..framework import no_grad

__all__ = ["ExponentialMovingAverage", "ModelAverage",
           "LookaheadOptimizer"]


def _swap_scope(obj, params, new_value_of, need_restore):
    """Back up live params, swap in new values, return a context manager
    that restores on exit. Nested apply() without restore() would clobber
    the backup with already-swapped weights — refuse instead."""
    if obj._backup is not None:
        raise RuntimeError(
            f"{type(obj).__name__}.apply() is already active; call "
            "restore() (or leave the `with` scope) before applying again")
    obj._backup = {id(p): p._data for p in params}
    for p in params:
        p._data = new_value_of(p).astype(p._data.dtype)
    if not need_restore:
        # the swap is permanent: discard the backup so later apply()
        # calls aren't refused and a stray restore() can't roll params
        # back to this stale snapshot
        obj._backup = None

    @contextlib.contextmanager
    def scope():
        try:
            yield
        finally:
            if need_restore:
                obj.restore()
    return scope()


class ExponentialMovingAverage:
    """EMA of parameters (fluid/optimizer.py:3466 parity).

    update() after each optimizer step; apply() swaps EMA weights in
    (optionally as a context manager), restore() swaps back.
    With thres_steps/bias correction: decay_t = min(decay,
    (1+t)/(10+t)) like the reference's ramped schedule.
    """

    def __init__(self, parameters, decay: float = 0.999,
                 thres_steps: bool = False, name: Optional[str] = None):
        self._params = [p for p in parameters if not p.stop_gradient]
        self.decay = float(decay)
        self.thres_steps = bool(thres_steps)
        self._step = 0
        self._shadow: Dict[int, jnp.ndarray] = {
            id(p): jnp.asarray(p._data) for p in self._params}
        self._backup: Optional[Dict[int, jnp.ndarray]] = None

    def _decay_t(self) -> float:
        if not self.thres_steps:
            return self.decay
        t = self._step
        return min(self.decay, (1.0 + t) / (10.0 + t))

    @no_grad()
    def update(self):
        self._step += 1
        d = self._decay_t()
        for p in self._params:
            s = self._shadow[id(p)]
            self._shadow[id(p)] = d * s + (1.0 - d) * p._data

    @no_grad()
    def apply(self, need_restore: bool = True):
        """Swap EMA weights into the live params. Returns a context
        manager when used with `with ema.apply(): ...`; without `with`,
        call restore() manually."""
        return _swap_scope(self, self._params,
                           lambda p: self._shadow[id(p)], need_restore)

    @no_grad()
    def restore(self):
        if self._backup is None:
            return
        for p in self._params:
            p._data = self._backup[id(p)]
        self._backup = None

    def state_dict(self):
        return {"step": self._step,
                "shadow": {i: np.asarray(s) for i, (k, s) in
                           enumerate(self._shadow.items())}}

    def set_state_dict(self, state):
        self._step = int(state["step"])
        for i, p in enumerate(self._params):
            self._shadow[id(p)] = jnp.asarray(state["shadow"][i])


class ModelAverage:
    """Sliding-window parameter averaging (fluid/optimizer.py:3157
    parity): accumulates parameter sums each step; apply() swaps in the
    window average for evaluation, restore() swaps back.

    The window holds at most max_average_window steps and at least
    min_average_window (the reference's average_window_rate bounds the
    window relative to total steps; here the rate caps growth the same
    way: window <= average_window_rate * num_updates).
    """

    def __init__(self, average_window_rate: float = 0.15, parameters=None,
                 min_average_window: int = 10000,
                 max_average_window: int = 10000, name=None):
        self._params = [p for p in (parameters or [])
                        if not p.stop_gradient]
        self.rate = float(average_window_rate)
        self.min_window = int(min_average_window)
        self.max_window = int(max_average_window)
        self._num_updates = 0
        self._window = 0
        self._sum: Dict[int, jnp.ndarray] = {
            id(p): jnp.zeros_like(p._data) for p in self._params}
        self._backup: Optional[Dict[int, jnp.ndarray]] = None

    @no_grad()
    def step(self):
        """Accumulate the current parameters into the window (call after
        each optimizer step)."""
        self._num_updates += 1
        self._window += 1
        limit = max(self.min_window,
                    min(self.max_window,
                        int(self.rate * self._num_updates) or 1))
        for p in self._params:
            self._sum[id(p)] = self._sum[id(p)] + p._data
        if self._window > limit:
            # restart the window from the running mean (the reference
            # rotates previous-sum blocks; a mean-seeded restart keeps
            # the same bounded-window semantics with O(1) state)
            for p in self._params:
                mean = self._sum[id(p)] / self._window
                self._sum[id(p)] = mean
            self._window = 1

    @no_grad()
    def apply(self, executor=None, need_restore: bool = True):
        if self._window == 0:
            raise RuntimeError(
                "ModelAverage.apply() before any step(): the window is "
                "empty (the reference errors on zero accumulates too)")
        w = self._window
        return _swap_scope(self, self._params,
                           lambda p: self._sum[id(p)] / w, need_restore)

    @no_grad()
    def restore(self, executor=None):
        if self._backup is None:
            return
        for p in self._params:
            p._data = self._backup[id(p)]
        self._backup = None


class LookaheadOptimizer:
    """Lookahead wrapper (fluid/optimizer.py:5238 parity): the inner
    optimizer updates fast weights every step; every k steps the slow
    weights move slow += alpha*(fast-slow) and fast resets to slow."""

    def __init__(self, inner_optimizer, alpha: float = 0.5, k: int = 5):
        assert inner_optimizer is not None
        self.inner_optimizer = inner_optimizer
        self.alpha = float(alpha)
        self.k = int(k)
        self._step_count = 0
        self._slow: Optional[Dict[int, jnp.ndarray]] = None

    def _params(self):
        return [p for p in self.inner_optimizer._param_list()
                if not p.stop_gradient]

    @no_grad()
    def step(self):
        if self._slow is None:
            self._slow = {id(p): jnp.asarray(p._data)
                          for p in self._params()}
        self.inner_optimizer.step()
        self._step_count += 1
        if self._step_count % self.k == 0:
            a = self.alpha
            for p in self._params():
                slow = self._slow[id(p)]
                slow = slow + a * (p._data - slow)
                self._slow[id(p)] = slow
                p._data = slow.astype(p._data.dtype)

    def clear_grad(self, *a, **k):
        return self.inner_optimizer.clear_grad(*a, **k)

    def get_lr(self):
        return self.inner_optimizer.get_lr()

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        loss.backward()
        self.step()
        return None, [(p, p.grad) for p in self._params()]
