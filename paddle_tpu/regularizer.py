"""paddle.regularizer — L1/L2 weight decay
(reference python/paddle/regularizer.py:15). The reference injects
regularization as extra grad ops during append_backward; here the
optimizer's pure update rule fuses the decay term into the (jitted)
parameter update (optimizer/optimizer.py _apply_one), which XLA folds
into the same fusion as the optimizer math."""

__all__ = ["L1Decay", "L2Decay"]


class WeightDecayRegularizer:
    mode = "l2"

    def __init__(self, coeff=0.0):
        self.coeff = float(coeff)

    def __repr__(self):
        return f"{type(self).__name__}(coeff={self.coeff})"


class L1Decay(WeightDecayRegularizer):
    """Adds coeff * sign(param) to the gradient (sparsity-encouraging)."""
    mode = "l1"


class L2Decay(WeightDecayRegularizer):
    """Adds coeff * param to the gradient."""
    mode = "l2"
