from .dataloader import (DataLoader, WorkerInfo,  # noqa: F401
                         get_worker_info)
from .dataset import (ChainDataset, ComposeDataset, ConcatDataset, Dataset,
                      IterableDataset, Subset, TensorDataset,
                      random_split)  # noqa: F401
from .sampler import (BatchSampler, BucketBatchSampler, bucket_collate,
                      DistributedBatchSampler, RandomSampler,
                      Sampler, SequenceSampler, WeightedRandomSampler,
                      SubsetRandomSampler)  # noqa: F401
from .fleet_dataset import (DatasetBase, DatasetFactory,  # noqa: F401
                            InMemoryDataset, QueueDataset)
