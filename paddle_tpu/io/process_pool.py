"""Multiprocess DataLoader workers with a death watchdog.

Reference: python/paddle/fluid/dataloader/dataloader_iter.py:379
(_worker_loop: index batches in, collated samples out through
shared-memory tensors) and imperative/data_loader.cc (SIGCHLD watchdog
killing the job when a worker dies instead of hanging the queue).

TPU-first shape: spawned workers own the Python-heavy work (decode,
tokenize, augment) that the thread pool can't parallelize under the GIL;
batches return through the native shared-memory ring (csrc/runtime.cpp
pd_shm_*, one ring per worker — no pickling large arrays through pipes)
with an mp.Queue fallback when the native lib is unavailable. Worker
death is detected by a monitor thread polling exitcodes (the portable
equivalent of the reference's SIGCHLD handler — signal handlers only fire
on the main thread, a poller works everywhere) and surfaces as a
RuntimeError on the consumer instead of a hang.
"""
from __future__ import annotations

import io
import itertools
import multiprocessing as mp
import os
import pickle
import queue
import threading
import time
from typing import Callable, Optional

import numpy as np

__all__ = ["ProcessPool"]

_POOL_SEQ = itertools.count(1)


def _pack(seq, ok, payload):
    """(seq, ok, batch-of-ndarrays-or-exception) -> bytes."""
    return pickle.dumps((seq, ok, payload), protocol=4)


def _worker_loop(worker_id, dataset, collate_fn, index_q, ring_name,
                 result_q, init_fn, seed, num_workers=0):
    # reference dataloader_iter._worker_loop exposes get_worker_info()
    from .dataloader import WorkerInfo, _set_worker_info
    _set_worker_info(WorkerInfo(worker_id, num_workers, dataset))
    if init_fn is not None:
        init_fn(worker_id)
    np.random.seed((seed + worker_id) % (2**32))
    ring = None
    if ring_name is not None:
        try:
            from .shm_ring import ShmRing
            ring = ShmRing(name=ring_name, create=False)
        except Exception:
            ring = None

    def emit(blob):
        if ring is not None:
            ring.push_bytes(blob)
        else:
            result_q.put(blob)

    while True:
        item = index_q.get()
        if item is None:
            return
        seq, idxs = item
        try:
            batch = collate_fn([dataset[i] for i in idxs])
            emit(_pack(seq, True, batch))
        except Exception as e:  # surfaced on the consumer side
            try:
                emit(_pack(seq, False, e))
            except Exception:
                emit(_pack(seq, False,
                           RuntimeError(f"worker {worker_id}: "
                                        f"{type(e).__name__}: {e}")))


class ProcessPool:
    """Order-preserving map of collate over index batches in fork()ed
    worker processes. API mirrors the in-module thread pool (submit/get/
    shutdown) so DataLoader switches on num_workers + mode only."""

    def __init__(self, dataset, collate_fn, num_workers,
                 use_shared_memory=True, worker_init_fn=None,
                 ring_capacity=32 << 20, timeout=0):
        # forkserver, not fork or spawn: the parent runs JAX's thread
        # pools, and fork()ing a multithreaded process corrupts them
        # (the reference forks because its parent is thread-light; ours
        # is not), while plain spawn re-executes the user's __main__
        # script for every worker (breaking guard-less scripts). The
        # forkserver daemon starts clean (no jax) and forks workers from
        # there. Dataset/collate_fn must be picklable — the same
        # contract as the reference's multiprocess DataLoader.
        ctx = mp.get_context("forkserver")
        self._timeout = timeout or None
        self.index_q = ctx.Queue()
        self.result_q = ctx.Queue()
        self.rings = []
        self.procs = []
        self.out = {}
        self.cv = threading.Condition()
        self.dead: Optional[str] = None
        self._closed = False

        ring_names = []
        from ..core.native_lib import runtime_lib
        if use_shared_memory and runtime_lib() is None:
            # ShmRing's pure-python fallback is in-process only — a
            # fork()ed child would push into its own copy; use the
            # mp.Queue path instead
            use_shared_memory = False
        if use_shared_memory:
            try:
                from .shm_ring import ShmRing
                pool_id = next(_POOL_SEQ)   # names unique across pools
                for w in range(num_workers):
                    r = ShmRing(name=f"/pd_dl_{os.getpid()}_{pool_id}_{w}",
                                capacity=ring_capacity, create=True)
                    self.rings.append(r)
                    ring_names.append(r.name)
            except Exception:
                self.rings = []
                ring_names = []
        if not ring_names:
            ring_names = [None] * num_workers

        seed = int.from_bytes(os.urandom(4), "little")
        for w in range(num_workers):
            p = ctx.Process(
                target=_worker_loop,
                args=(w, dataset, collate_fn, self.index_q, ring_names[w],
                      self.result_q, worker_init_fn, seed, num_workers),
                daemon=True)
            p.start()
            self.procs.append(p)

        # result drainers: one per ring (pop_bytes blocks per-ring), plus
        # ALWAYS the mp.Queue drainer — a worker whose ring attach fails
        # falls back to the queue, and its batches must still arrive
        self._drainers = []
        for r in self.rings:
            t = threading.Thread(target=self._drain_ring, args=(r,),
                                 daemon=True)
            t.start()
            self._drainers.append(t)
        t = threading.Thread(target=self._drain_queue, daemon=True)
        t.start()
        self._drainers.append(t)

        # watchdog: dead worker -> error out instead of hanging
        self._watchdog = threading.Thread(target=self._watch, daemon=True)
        self._watchdog.start()

    # -- internals -----------------------------------------------------------
    def _store(self, blob):
        seq, ok, payload = pickle.loads(blob)
        with self.cv:
            self.out[seq] = (ok, payload)
            self.cv.notify_all()

    def _drain_ring(self, ring):
        while not self._closed:
            try:
                blob = ring.pop_bytes(timeout=0.2)
            except Exception:
                if self._closed:
                    return
                continue
            if blob:
                self._store(blob)

    def _drain_queue(self):
        while not self._closed:
            try:
                blob = self.result_q.get(timeout=0.2)
            except queue.Empty:
                continue
            except (EOFError, OSError):
                return
            self._store(blob)

    def _watch(self):
        while not self._closed:
            for p in self.procs:
                # ANY exit before shutdown() is unexpected — a clean
                # sys.exit() from a dataset mid-epoch must not hang the
                # consumer either (normal exits only happen after the
                # shutdown sentinel, when _closed is already set)
                if p.exitcode is not None and not self._closed:
                    with self.cv:
                        self.dead = (f"DataLoader worker (pid {p.pid}) "
                                     f"exited unexpectedly with code "
                                     f"{p.exitcode}")
                        self.cv.notify_all()
                    return
            time.sleep(0.1)

    # -- API -----------------------------------------------------------------
    def submit(self, seq, idxs):
        self.index_q.put((seq, list(idxs)))

    def get(self, seq):
        deadline = (time.time() + self._timeout) if self._timeout else None
        with self.cv:
            while seq not in self.out:
                if self.dead:
                    raise RuntimeError(self.dead)
                remaining = None
                if deadline is not None:
                    remaining = deadline - time.time()
                    if remaining <= 0:
                        raise TimeoutError(
                            f"DataLoader batch {seq} timed out")
                self.cv.wait(timeout=remaining if remaining else 0.5)
            ok, val = self.out.pop(seq)
        if not ok:
            raise val
        return val

    def shutdown(self):
        if self._closed:
            return
        self._closed = True
        for _ in self.procs:
            try:
                self.index_q.put(None)
            except Exception:
                pass
        for p in self.procs:
            p.join(timeout=2.0)
            if p.is_alive():
                p.terminate()
        # drainers must be out of pop_bytes before the rings unmap —
        # closing a segment under a blocked reader is a use-after-unmap
        for t in self._drainers:
            t.join(timeout=2.0)
        for r in self.rings:
            try:
                r.close()
            except Exception:
                pass

    def __del__(self):
        try:
            self.shutdown()
        except Exception:
            pass
