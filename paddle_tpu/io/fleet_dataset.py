"""Dataset-driven training: the Trainer/DeviceWorker capability.

Reference: framework/trainer.h:53 (TrainerBase -> MultiTrainer),
device_worker.h:149 (HogwildWorker), framework/data_set.h:43
(Dataset/DatasetImpl with in-memory global shuffle + channels),
fluid.DatasetFactory ("QueueDataset" / "InMemoryDataset") and
Executor.train_from_dataset / infer_from_dataset (fluid/executor.py).

TPU-first redesign: the reference spins one hogwild thread per core,
each racing lock-free updates into shared parameters. On TPU the chip
IS the parallelism — one process feeds one compiled step whose batch
dimension does the work of the thread pool, so "num threads" configures
the C++ *feeder* (csrc/datafeed.cpp parse/shuffle threads), not racing
updaters, and the update is exact instead of hogwild-approximate. The
file format, slot config, shuffle semantics and the
train_from_dataset driver loop keep the reference's shape.
"""
from __future__ import annotations

import glob as _glob
from typing import List, Optional, Sequence, Tuple

import numpy as np

__all__ = ["DatasetBase", "QueueDataset", "InMemoryDataset",
           "DatasetFactory"]


class DatasetBase:
    """Slot-configured file dataset (reference DatasetImpl).

    Slots are (name, size, dtype) with dtype float32|int64; files use the
    MultiSlot text format of csrc/datafeed.cpp ("size v1 .. vn" per slot,
    ';'-separated). `set_use_var` derives slots from static feed Vars.
    """

    def __init__(self):
        self.filelist: List[str] = []
        self.batch_size = 1
        self.thread_num = 2
        self.slots: List[Tuple[str, int, str]] = []
        self.queue_capacity = 8
        self._shuffle = False
        self._seed = 0

    # -- reference config surface -------------------------------------------
    def set_filelist(self, files):
        out = []
        for f in files:
            hits = sorted(_glob.glob(f))
            out.extend(hits if hits else [f])
        self.filelist = out

    def set_batch_size(self, bs):
        self.batch_size = int(bs)

    def set_thread(self, n):
        self.thread_num = int(n)

    def set_queue_num(self, n):
        self.queue_capacity = int(n)

    def set_shuffle(self, shuffle: bool):
        """Streaming shuffle inside the C++ feeder (QueueDataset path);
        InMemoryDataset prefers load_into_memory + local_shuffle."""
        self._shuffle = bool(shuffle)

    def set_seed(self, seed: int):
        """Seed for the feeder's streaming shuffle and the default
        local_shuffle/global_shuffle permutation."""
        self._seed = int(seed)

    def set_slots(self, slots):
        self.slots = [(str(n), int(s), str(d)) for n, s, d in slots]

    def set_use_var(self, var_list):
        """Derive slot config from feed Vars (paddle.static.data): name,
        flattened per-sample size, dtype family."""
        slots = []
        for v in var_list:
            shape = getattr(v, "orig_shape", None) or tuple(v.shape)
            per_sample = 1
            for s in shape[1:]:
                per_sample *= int(s if s else 1)
            dt = str(getattr(v, "dtype", "float32"))
            kind = "int64" if ("int" in dt) else "float32"
            slots.append((v.name, per_sample, kind))
        self.slots = slots

    def _feed(self, shuffle=None):
        from .native_feed import NativeMultiSlotFeed
        return NativeMultiSlotFeed(
            self.filelist, self.batch_size,
            [(s, d) for _, s, d in self.slots],
            num_threads=self.thread_num,
            queue_capacity=self.queue_capacity,
            shuffle=self._shuffle if shuffle is None else shuffle,
            seed=self._seed)

    def slot_names(self):
        return [n for n, _, _ in self.slots]

    def __iter__(self):
        """Yield feed dicts {slot_name: np.ndarray [bs, size]}."""
        names = self.slot_names()
        for batch in self._feed():
            yield dict(zip(names, batch))


class QueueDataset(DatasetBase):
    """Streaming dataset (reference QueueDataset): files are parsed by
    the C++ feeder's thread pool and consumed batch-by-batch; nothing is
    held in memory."""


class InMemoryDataset(DatasetBase):
    """Out-of-core load + in-memory shuffle (reference InMemoryDataset:
    load_into_memory -> local_shuffle/global_shuffle -> train)."""

    def __init__(self):
        super().__init__()
        self._samples: Optional[List[Tuple[np.ndarray, ...]]] = None

    def load_into_memory(self):
        samples = []
        for batch in self._feed(shuffle=False):
            for i in range(batch[0].shape[0]):
                samples.append(tuple(a[i] for a in batch))
        self._samples = samples

    def local_shuffle(self, seed: Optional[int] = None):
        assert self._samples is not None, "call load_into_memory() first"
        rng = np.random.RandomState(self._seed if seed is None else seed)
        rng.shuffle(self._samples)

    def global_shuffle(self, fleet=None, thread_num=None):
        """Reference global_shuffle reshards samples over trainers by
        hash; with data-parallel input sharding each rank owns its own
        files, so the global pass reduces to a seed-synchronized local
        shuffle (every rank permutes with the same seed)."""
        assert self._samples is not None, "call load_into_memory() first"
        seed = self._seed
        if fleet is not None:
            seed = getattr(fleet, "global_shuffle_seed", self._seed)
        np.random.RandomState(seed).shuffle(self._samples)

    def release_memory(self):
        self._samples = None

    def get_memory_data_size(self, fleet=None):
        return 0 if self._samples is None else len(self._samples)

    def __iter__(self):
        if self._samples is None:
            yield from super().__iter__()
            return
        names = self.slot_names()
        bs = self.batch_size
        for start in range(0, len(self._samples), bs):
            chunk = self._samples[start:start + bs]
            arrays = [np.stack([s[j] for s in chunk])
                      for j in range(len(self.slots))]
            yield dict(zip(names, arrays))


class DatasetFactory:
    """fluid.DatasetFactory parity."""

    def create_dataset(self, datafeed_class="QueueDataset"):
        if datafeed_class == "InMemoryDataset":
            return InMemoryDataset()
        if datafeed_class == "QueueDataset":
            return QueueDataset()
        raise ValueError(f"unknown dataset class {datafeed_class!r}")
