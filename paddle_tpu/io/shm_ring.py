"""Shared-memory blob ring for DataLoader worker processes.

Reference: memory/allocation/mmap_allocator.cc (shared-mem tensor buffers
for loader workers) + fluid/dataloader/dataloader_iter.py worker loop
(workers push batches to the main process).

csrc/runtime.cpp pd_shm_*: a named POSIX shm segment holding a ring of
length-prefixed blobs guarded by a process-shared robust mutex — workers
push pickled/packed batches, the host loop pops them without a pipe
round-trip. Falls back to a multiprocessing.Queue-equivalent in-process
deque when the native lib is unavailable (single-process mode only).
"""
from __future__ import annotations

import collections
import ctypes
import itertools
import os
import pickle
import threading
from typing import Any, Optional

from ..core.native_lib import runtime_lib

__all__ = ["ShmRing"]


class ShmRing:
    """Fixed-capacity cross-process blob queue."""

    _seq = itertools.count(1)
    _fb_registry: dict = {}
    _fb_lock = threading.Lock()

    def __init__(self, name: Optional[str] = None,
                 capacity: int = 64 << 20, create: bool = True,
                 force: bool = False):
        """capacity only matters for the creator; attachers
        (create=False) always adopt the creator's capacity from the shm
        header. Creating over an existing segment fails unless
        force=True (which severs/unlinks the old ring)."""
        if name is None:
            # pid alone would collide across ShmRing instances in one
            # process — add a per-process sequence number (itertools
            # .count: atomic under the GIL, unlike `+= 1`)
            name = f"/pd_ring_{os.getpid()}_{next(ShmRing._seq)}"
        self.name = name
        if not self.name.startswith("/"):
            self.name = "/" + self.name
        self.capacity = int(capacity)
        self._lib = runtime_lib()
        self._handle = None
        self._fallback = None
        if self._lib is not None:
            mode = 0 if not create else (2 if force else 1)
            h = self._lib.pd_shm_open(self.name.encode(), self.capacity,
                                      mode)
            if h == -5:
                raise FileExistsError(
                    f"shm ring {self.name} already exists; pass "
                    "force=True to replace it")
            if h < 0:
                raise OSError(
                    f"shm ring open failed ({h}) for {self.name}")
            self._handle = h
        else:  # in-process fallback (no cross-process support); a
            # process-level registry keeps the create/attach/exclusive
            # contract identical to the native path
            with ShmRing._fb_lock:
                existing = ShmRing._fb_registry.get(self.name)
                if create:
                    if existing is not None and not force:
                        raise FileExistsError(
                            f"shm ring {self.name} already exists; pass "
                            "force=True to replace it")
                    entry = (collections.deque(), threading.Condition())
                    ShmRing._fb_registry[self.name] = entry
                    self._fb_owner = True
                else:
                    if existing is None:
                        raise OSError(
                            f"shm ring open failed (-1) for {self.name}")
                    entry = existing
                    self._fb_owner = False
            self._fallback, self._cv = entry

    # -- raw bytes -----------------------------------------------------------
    def push_bytes(self, data: bytes):
        if self._handle is not None:
            rc = self._lib.pd_shm_push(self._handle, data, len(data))
            if rc != 0:
                raise OSError(f"shm push failed ({rc})")
            return
        with self._cv:
            self._fallback.append(bytes(data))
            self._cv.notify()

    def pop_bytes(self, timeout: Optional[float] = None) -> bytes:
        if self._handle is not None:
            cap = 1 << 20
            while True:
                buf = ctypes.create_string_buffer(cap)
                t_ms = -1 if timeout is None else int(timeout * 1000)
                n = self._lib.pd_shm_pop(self._handle, buf, cap, t_ms)
                if n >= 0:
                    return buf.raw[:int(n)]
                if n == -4:
                    raise TimeoutError("shm ring pop timed out")
                if n in (-1, -2, -3):
                    raise OSError(f"shm pop failed ({n})")
                # buffer too small: -n is the required size
                cap = -int(n)
        with self._cv:
            if not self._fallback:
                if not self._cv.wait_for(lambda: bool(self._fallback),
                                         timeout):
                    raise TimeoutError("ring pop timed out")
            return self._fallback.popleft()

    # -- python objects (batches) -------------------------------------------
    def put(self, obj: Any):
        self.push_bytes(pickle.dumps(obj, protocol=4))

    def get(self, timeout: Optional[float] = None) -> Any:
        return pickle.loads(self.pop_bytes(timeout))

    def qsize(self) -> int:
        if self._handle is not None:
            return int(self._lib.pd_shm_count(self._handle))
        return len(self._fallback)

    def close(self):
        if self._handle is not None:
            self._lib.pd_shm_close(self._handle)
            self._handle = None
        if self._fallback is not None and getattr(self, "_fb_owner", False):
            with ShmRing._fb_lock:
                if ShmRing._fb_registry.get(self.name) is not None and \
                        ShmRing._fb_registry[self.name][0] is self._fallback:
                    del ShmRing._fb_registry[self.name]
            self._fb_owner = False

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
