"""Python binding for the native C++ data-feed runtime (csrc/datafeed.cpp).

Reference analogue: MultiSlotDataFeed + Dataset
(/root/reference/paddle/fluid/framework/data_feed.cc, data_set.cc) — file
parsing, per-thread shuffle windows, and the bounded blocking queue all run
in C++ threads; Python only receives filled numpy buffers (ctypes, no
pybind11 in this image).
"""
from __future__ import annotations

import ctypes
import os
import subprocess
from typing import List, Sequence, Tuple

import numpy as np

__all__ = ["NativeMultiSlotFeed", "build_native_lib"]

_LIB = None
_CSRC = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "csrc")
_SO = os.path.join(_CSRC, "libpaddletpu_datafeed.so")


def build_native_lib(force=False):
    """Compile csrc/datafeed.cpp (cpp_extension-style on-demand jit
    build; reference utils/cpp_extension/load parity)."""
    if os.path.exists(_SO) and not force:
        src_m = os.path.getmtime(os.path.join(_CSRC, "datafeed.cpp"))
        if os.path.getmtime(_SO) >= src_m:
            return _SO
    subprocess.run(["make", "-C", _CSRC], check=True,
                   capture_output=True)
    return _SO


def _lib():
    global _LIB
    if _LIB is None:
        so = build_native_lib()
        lib = ctypes.CDLL(so)
        lib.df_create.restype = ctypes.c_void_p
        lib.df_create.argtypes = [
            ctypes.POINTER(ctypes.c_char_p), ctypes.c_int, ctypes.c_int,
            ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int),
            ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.c_uint64,
        ]
        lib.df_start.argtypes = [ctypes.c_void_p]
        lib.df_next.restype = ctypes.c_int
        lib.df_next.argtypes = [ctypes.c_void_p,
                                ctypes.POINTER(ctypes.c_void_p),
                                ctypes.POINTER(ctypes.c_void_p)]
        lib.df_destroy.argtypes = [ctypes.c_void_p]
        _LIB = lib
    return _LIB


class NativeMultiSlotFeed:
    """Iterate batches parsed by the C++ feeder.

    slots: list of (size, dtype) with dtype in ("float32", "int64").
    Yields per-batch tuples of numpy arrays [batch, slot_size] per slot
    (trailing partial batches are truncated to the actual size).
    """

    def __init__(self, file_list: Sequence[str], batch_size: int,
                 slots: Sequence[Tuple[int, str]], num_threads: int = 2,
                 queue_capacity: int = 8, shuffle: bool = False,
                 seed: int = 0):
        self.files = [os.fspath(f) for f in file_list]
        self.batch_size = batch_size
        self.slots = list(slots)
        self.num_threads = num_threads
        self.queue_capacity = queue_capacity
        self.shuffle = shuffle
        self.seed = seed

    def __iter__(self):
        lib = _lib()
        n = len(self.files)
        c_files = (ctypes.c_char_p * n)(
            *[f.encode() for f in self.files])
        sizes = (ctypes.c_int * len(self.slots))(
            *[int(s) for s, _ in self.slots])
        is_i64 = (ctypes.c_int * len(self.slots))(
            *[1 if d == "int64" else 0 for _, d in self.slots])
        handle = lib.df_create(c_files, n, self.batch_size, sizes, is_i64,
                               len(self.slots), self.num_threads,
                               self.queue_capacity,
                               1 if self.shuffle else 0, self.seed)
        lib.df_start(handle)
        try:
            # preallocate per-slot buffers at full batch size
            fbufs, ibufs = [], []
            arrays = []
            for size, dt in self.slots:
                arr = np.empty((self.batch_size, size),
                               np.float32 if dt == "float32" else np.int64)
                arrays.append(arr)
                if dt == "float32":
                    fbufs.append(arr.ctypes.data_as(ctypes.c_void_p))
                else:
                    ibufs.append(arr.ctypes.data_as(ctypes.c_void_p))
            farr = (ctypes.c_void_p * max(len(fbufs), 1))(*fbufs) \
                if fbufs else (ctypes.c_void_p * 1)()
            iarr = (ctypes.c_void_p * max(len(ibufs), 1))(*ibufs) \
                if ibufs else (ctypes.c_void_p * 1)()
            while True:
                bs = lib.df_next(handle, farr, iarr)
                if bs == 0:
                    return
                yield tuple(a[:bs].copy() for a in arrays)
        finally:
            lib.df_destroy(handle)
