"""DataLoader: batching, multiprocess workers, device prefetch.

Reference: fluid/reader.py:149 DataLoader + dataloader_iter.py:379
_worker_loop (worker procs + shared-mem tensors + SIGCHLD watchdog) and
the C++ double-buffering reader (operators/reader/buffered_reader.cc).

TPU-first: host workers produce numpy batches; a prefetch thread stages
the NEXT batch onto device (jax.device_put, optionally sharded over the
mesh per a ShardingPlan) while the current step runs — the
buffered_reader's H2D overlap without custom streams.
"""
from __future__ import annotations

import itertools
import queue
import threading
from typing import Callable, Optional

import jax
import numpy as np

from ..framework import Tensor
from .dataset import Dataset, IterableDataset
from .sampler import BatchSampler

__all__ = ["DataLoader", "default_collate_fn"]


def default_collate_fn(batch):
    sample = batch[0]
    if isinstance(sample, (list, tuple)):
        return type(sample)(default_collate_fn([b[i] for b in batch])
                            for i in range(len(sample)))
    if isinstance(sample, dict):
        return {k: default_collate_fn([b[k] for b in batch])
                for k in sample}
    if isinstance(sample, Tensor):
        return np.stack([np.asarray(s._data) for s in batch])
    if isinstance(sample, np.ndarray):
        return np.stack(batch)
    if isinstance(sample, (int, float, np.number)):
        return np.asarray(batch)
    if isinstance(sample, str):
        return list(batch)
    return np.asarray(batch)


class _WorkerPool:
    """Thread pool mapping collate over index batches. Threads (not procs)
    because numpy transforms release the GIL and jax arrays can't cross
    process boundaries cheaply; the reference's process pool exists to
    dodge Python-heavy decoding, which belongs in the C++ feeder."""

    def __init__(self, fn, num_workers, prefetch, dataset=None):
        self.fn = fn
        self.num_workers = num_workers
        self.dataset = dataset
        self.in_q = queue.Queue()
        self.out = {}
        self.cv = threading.Condition()
        self.workers = []
        self.closed = False
        for wid in range(num_workers):
            t = threading.Thread(target=self._loop, args=(wid,),
                                 daemon=True)
            t.start()
            self.workers.append(t)

    def _loop(self, wid=0):
        _set_worker_info(WorkerInfo(wid, self.num_workers, self.dataset))
        while True:
            item = self.in_q.get()
            if item is None:
                return
            seq, payload = item
            try:
                res = (True, self.fn(payload))
            except Exception as e:  # surfaced on the consumer side
                res = (False, e)
            with self.cv:
                self.out[seq] = res
                self.cv.notify_all()

    def submit(self, seq, payload):
        self.in_q.put((seq, payload))

    def get(self, seq):
        with self.cv:
            while seq not in self.out:
                self.cv.wait()
            ok, val = self.out.pop(seq)
        if not ok:
            raise val
        return val

    def shutdown(self):
        for _ in self.workers:
            self.in_q.put(None)


class DataLoader:
    def __init__(self, dataset: Dataset, feed_list=None, places=None,
                 return_list=True, batch_sampler=None, batch_size=1,
                 shuffle=False, drop_last=False, collate_fn=None,
                 num_workers=0, use_buffer_reader=True, prefetch_factor=2,
                 use_shared_memory=True, timeout=0, worker_init_fn=None,
                 persistent_workers=False, sharding_plan=None,
                 worker_mode="thread"):
        """worker_mode: "thread" (default — numpy transforms release the
        GIL, zero serialization) or "process" (forkserver workers for
        Python-heavy decode/tokenize, shared-memory return path + death
        watchdog — the reference dataloader_iter.py:379 architecture).
        Process mode requires a picklable dataset/collate_fn; datasets
        defined in a script's __main__ need the standard
        `if __name__ == "__main__":` guard (as with torch/paddle
        multiprocess loaders)."""
        self.dataset = dataset
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = num_workers
        self.worker_mode = worker_mode
        self.use_shared_memory = use_shared_memory
        self.worker_init_fn = worker_init_fn
        self.timeout = timeout
        self.prefetch_factor = max(prefetch_factor, 2)
        self.use_buffer_reader = use_buffer_reader
        self.sharding_plan = sharding_plan
        self.iterable = not isinstance(dataset, IterableDataset)
        if self.iterable:
            if batch_sampler is not None:
                self.batch_sampler = batch_sampler
            elif batch_size is None:
                self.batch_sampler = None
            else:
                self.batch_sampler = BatchSampler(
                    dataset, shuffle=shuffle, batch_size=batch_size,
                    drop_last=drop_last)
        else:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last

    def __len__(self):
        if self.batch_sampler is not None:
            return len(self.batch_sampler)
        raise TypeError("IterableDataset DataLoader has no len()")

    # -- iteration ----------------------------------------------------------
    def _batches(self):
        if self.iterable:
            if self.batch_sampler is None:
                for i in range(len(self.dataset)):
                    yield self.dataset[i]
                return
            make = lambda idxs: [self.dataset[i] for i in idxs]
            if self.num_workers > 0:
                if self.worker_mode == "process":
                    from .process_pool import ProcessPool
                    pool = ProcessPool(
                        self.dataset, self.collate_fn, self.num_workers,
                        use_shared_memory=self.use_shared_memory,
                        worker_init_fn=self.worker_init_fn,
                        timeout=self.timeout)
                else:
                    pool = _WorkerPool(
                        lambda idxs: self.collate_fn(make(idxs)),
                        self.num_workers, self.prefetch_factor,
                        dataset=self.dataset)
                try:
                    # windowed submission: at most workers*prefetch
                    # batches in flight, so a slow consumer doesn't pile
                    # a whole epoch of results into parent RAM
                    window = self.num_workers * self.prefetch_factor
                    it = enumerate(iter(self.batch_sampler))
                    in_flight = []
                    for seq, idxs in itertools.islice(it, window):
                        pool.submit(seq, idxs)
                        in_flight.append(seq)
                    next_take = 0
                    while next_take < len(in_flight):
                        out = pool.get(in_flight[next_take])
                        next_take += 1
                        for seq, idxs in itertools.islice(it, 1):
                            pool.submit(seq, idxs)
                            in_flight.append(seq)
                        yield out
                finally:
                    pool.shutdown()
            else:
                for idxs in self.batch_sampler:
                    yield self.collate_fn(make(idxs))
        else:
            batch = []
            for sample in self.dataset:
                batch.append(sample)
                if len(batch) == (self.batch_size or 1):
                    yield self.collate_fn(batch)
                    batch = []
            if batch and not getattr(self, "drop_last", False):
                yield self.collate_fn(batch)

    def _to_device(self, batch):
        def put(a):
            if isinstance(a, np.ndarray):
                if self.sharding_plan is not None:
                    return Tensor(self.sharding_plan.place(
                        a, self.sharding_plan.data_spec(a)))
                return Tensor(jax.device_put(a))
            return a
        if isinstance(batch, (list, tuple)):
            return type(batch)(put(b) for b in batch)
        if isinstance(batch, dict):
            return {k: put(v) for k, v in batch.items()}
        return put(batch)

    def __iter__(self):
        from ..observability import flight_recorder as _fr
        from ..observability import metrics as _obs
        import time as _time
        gen = self._batches()
        if not self.use_buffer_reader:
            for b in gen:
                if _obs._enabled:
                    _obs.counter("dataloader.batches_total").add(1)
                yield self._to_device(b)
            return
        # double-buffer: device-put batch N+1 while N is consumed
        q: "queue.Queue" = queue.Queue(maxsize=self.prefetch_factor)
        sentinel = object()

        class _Error:
            def __init__(self, exc):
                self.exc = exc

        def producer():
            try:
                for b in gen:
                    q.put(self._to_device(b))
            except Exception as e:
                q.put(_Error(e))
            q.put(sentinel)

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        while True:
            _rec_m, _rec_f = _obs._enabled, _fr._enabled
            if _rec_m or _rec_f:
                # host-input-pipeline health: time the consumer spends
                # BLOCKED on the prefetch queue (≈0 when the loader
                # keeps ahead of the step) + standing queue depth
                _t0 = _time.perf_counter()
                item = q.get()
                _wait_s = _time.perf_counter() - _t0
                if _rec_m:
                    _obs.histogram("dataloader.wait_ms").observe(
                        _wait_s * 1e3)
                    _obs.gauge("dataloader.prefetch_depth").set(
                        q.qsize())
                    if not (item is sentinel
                            or isinstance(item, _Error)):
                        _obs.counter("dataloader.batches_total").add(1)
                if _rec_f:
                    # black box + goodput: input-starved wall-clock
                    _fr.dataloader_wait(_wait_s)
            else:
                item = q.get()
            if item is sentinel:
                return
            if isinstance(item, _Error):
                raise item.exc
            yield item


# -- worker introspection (reference io.get_worker_info) -------------------

class WorkerInfo:
    """Reference paddle.io.get_worker_info payload: inside a DataLoader
    worker returns (id, num_workers, dataset); in the main process
    returns None."""

    def __init__(self, wid, num_workers, dataset):
        self.id = wid
        self.num_workers = num_workers
        self.dataset = dataset


_worker_info = threading.local()


def _set_worker_info(info):
    _worker_info.value = info


def get_worker_info():
    return getattr(_worker_info, "value", None)
