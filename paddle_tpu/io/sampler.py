"""Samplers incl. DistributedBatchSampler (python/paddle/io parity)."""
from __future__ import annotations

import math
from typing import Iterator, List

import numpy as np

__all__ = ["Sampler", "SequenceSampler", "RandomSampler",
           "WeightedRandomSampler", "SubsetRandomSampler", "BatchSampler",
           "DistributedBatchSampler", "BucketBatchSampler",
           "bucket_collate"]


class Sampler:
    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self):
        raise NotImplementedError

    def __len__(self):
        return len(self.data_source)


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))


class RandomSampler(Sampler):
    def __init__(self, data_source, replacement=False, num_samples=None,
                 generator=None):
        super().__init__(data_source)
        self.replacement = replacement
        self._num = num_samples

    @property
    def num_samples(self):
        return self._num if self._num is not None else len(self.data_source)

    def __iter__(self):
        n = len(self.data_source)
        if self.replacement:
            return iter(np.random.randint(0, n, self.num_samples).tolist())
        return iter(np.random.permutation(n)[:self.num_samples].tolist())

    def __len__(self):
        return self.num_samples


class WeightedRandomSampler(Sampler):
    def __init__(self, weights, num_samples, replacement=True):
        self.weights = np.asarray(weights, dtype=np.float64)
        self.num_samples = num_samples
        self.replacement = replacement

    def __iter__(self):
        p = self.weights / self.weights.sum()
        idx = np.random.choice(len(self.weights), self.num_samples,
                               replace=self.replacement, p=p)
        return iter(idx.tolist())

    def __len__(self):
        return self.num_samples


class SubsetRandomSampler(Sampler):
    def __init__(self, indices):
        self.indices = list(indices)

    def __iter__(self):
        return iter(np.random.permutation(self.indices).tolist())

    def __len__(self):
        return len(self.indices)


class BatchSampler(Sampler):
    def __init__(self, dataset=None, sampler=None, shuffle=False,
                 batch_size=1, drop_last=False):
        self.batch_size = batch_size
        self.drop_last = drop_last
        if sampler is not None:
            self.sampler = sampler
        elif shuffle:
            self.sampler = RandomSampler(dataset)
        else:
            self.sampler = SequenceSampler(dataset)

    def __iter__(self):
        batch = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size


class DistributedBatchSampler(BatchSampler):
    """Rank-strided sharding of the dataset (reference
    distributed_batch_sampler parity). On a single TPU host driving all
    chips, per-chip sharding happens at device_put time instead; this
    sampler covers the multi-host (process) split."""

    def __init__(self, dataset, batch_size, num_replicas=None, rank=None,
                 shuffle=False, drop_last=False):
        from ..distributed.env import get_rank, get_world_size
        self.dataset = dataset
        self.batch_size = batch_size
        self.nranks = num_replicas if num_replicas is not None else \
            get_world_size()
        self.rank = rank if rank is not None else get_rank()
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.epoch = 0
        self.num_samples = int(math.ceil(len(dataset) / self.nranks))
        self.total_size = self.num_samples * self.nranks

    def __iter__(self):
        n = len(self.dataset)
        if self.shuffle:
            rng = np.random.RandomState(self.epoch)
            indices = rng.permutation(n).tolist()
            self.epoch += 1
        else:
            indices = list(range(n))
        indices += indices[: self.total_size - n]  # pad to equal shards
        local = indices[self.rank::self.nranks]
        batch = []
        for idx in local:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        if self.drop_last:
            return self.num_samples // self.batch_size
        return (self.num_samples + self.batch_size - 1) // self.batch_size

    def set_epoch(self, epoch):
        self.epoch = epoch


class BucketBatchSampler(BatchSampler):
    """Length-bucketed batching: the framework-level variable-length
    policy (DESIGN.md "LoD" section).

    The reference threads LoD metadata through kernels so every batch
    can be ragged (`lod_tensor.h:114`); XLA needs static shapes, so
    ragged batches become `(padded, lengths)` pairs drawn from a SMALL
    FIXED SET of padded shapes: samples are grouped by which bucket
    boundary their length fits under, and every emitted batch is padded
    to its bucket's boundary by `bucket_collate` — one XLA compilation
    per bucket, never one per shape (bench.py's dynamic-shape config
    proves compiles == buckets). With drop_last=False, per-bucket
    remainder batches are smaller along the batch dim and add one
    compilation each; pass drop_last=True when a strict
    one-compile-per-bucket guarantee matters.

    lengths: per-sample lengths (ints), or None to call len() on each
    sample of `dataset`. boundaries: ascending bucket upper bounds;
    samples longer than the last boundary go to a final overflow bucket
    sized by the max observed length (rounded up to `multiple`).
    """

    def __init__(self, dataset=None, lengths=None, boundaries=(64, 128,
                 256, 512), batch_size=1, shuffle=False, drop_last=False,
                 multiple=8):
        if lengths is None:
            lengths = [len(dataset[i]) for i in range(len(dataset))]
        if dataset is None:
            dataset = range(len(lengths))  # lengths determine the stream
        super().__init__(dataset=dataset, shuffle=shuffle,
                         batch_size=batch_size, drop_last=drop_last)
        self.lengths = np.asarray(lengths, np.int64)
        bounds = sorted(int(b) for b in boundaries)
        mx = int(self.lengths.max()) if len(self.lengths) else 1
        if mx > bounds[-1]:
            bounds.append(-(-mx // multiple) * multiple)
        self.boundaries = bounds
        self._num_batches = None  # lazily computed, then cached

    def collate(self, pad_value=0):
        """The matching collate_fn: built over self.boundaries, which
        already includes the overflow bucket's rounded bound — always
        use this (or bucket_collate(sampler)) so collate and sampler
        agree on the padded-shape set."""
        return bucket_collate(self, pad_value=pad_value)

    def bucket_of(self, length: int) -> int:
        for i, b in enumerate(self.boundaries):
            if length <= b:
                return i
        return len(self.boundaries) - 1

    def __iter__(self):
        pending: dict = {}
        for idx in self.sampler:
            b = self.bucket_of(int(self.lengths[idx]))
            pending.setdefault(b, []).append(idx)
            if len(pending[b]) == self.batch_size:
                yield pending.pop(b)
        for b in sorted(pending):
            if not self.drop_last:
                yield pending[b]

    def __len__(self):
        # exact and precomputed: lengths and boundaries are fixed at
        # construction (consumers like LR schedulers and progress bars
        # call len() repeatedly)
        if self._num_batches is None:
            counts: dict = {}
            for ln in self.lengths:
                b = self.bucket_of(int(ln))
                counts[b] = counts.get(b, 0) + 1
            total = 0
            for c in counts.values():
                total += c // self.batch_size
                if not self.drop_last and c % self.batch_size:
                    total += 1
            self._num_batches = total
        return self._num_batches


def bucket_collate(boundaries, pad_value=0):
    """collate_fn companion to BucketBatchSampler: stacks variable-length
    1D+ samples into (padded [B, T, ...], lengths [B]) with T = the
    smallest bucket boundary fitting the batch — the LoD-replacement
    convention consumed by ops/sequence.py and the RNN ops'
    sequence_length arguments.

    Pass the BucketBatchSampler itself (preferred) so the collate uses
    the sampler's boundary list INCLUDING the overflow bucket's rounded
    bound — building from a raw boundary tuple while the sampler added
    an overflow bucket would give overflow batches per-batch shapes."""
    if isinstance(boundaries, BucketBatchSampler):
        bounds = list(boundaries.boundaries)
    else:
        bounds = sorted(int(b) for b in boundaries)

    def collate(samples):
        arrs = [np.asarray(s) for s in samples]
        lens = np.asarray([a.shape[0] for a in arrs], np.int64)
        mx = int(lens.max())
        t = next((b for b in bounds if b >= mx), bounds[-1])
        if t < mx:
            raise ValueError(
                f"sample length {mx} exceeds the largest bucket bound "
                f"{bounds[-1]}; build the collate from the sampler "
                "(bucket_collate(sampler)) so the overflow bucket is "
                "included")
        tail = arrs[0].shape[1:]
        out = np.full((len(arrs), t) + tail, pad_value,
                      arrs[0].dtype)
        for i, a in enumerate(arrs):
            out[i, :a.shape[0]] = a
        return out, lens

    return collate
