"""Dataset abstractions (python/paddle/io + fluid/dataloader parity)."""
from __future__ import annotations

import bisect
from typing import Iterable, List, Sequence

import numpy as np

__all__ = ["Dataset", "IterableDataset", "TensorDataset", "ComposeDataset",
           "ChainDataset", "ConcatDataset", "Subset", "random_split"]


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise RuntimeError("IterableDataset does not support indexing")

    def __len__(self):
        raise RuntimeError("IterableDataset has no len()")


class TensorDataset(Dataset):
    def __init__(self, tensors):
        from ..framework import Tensor
        self.tensors = tensors
        n = len(tensors[0])
        assert all(len(t) == n for t in tensors)

    def __getitem__(self, idx):
        return tuple(t[idx] for t in self.tensors)

    def __len__(self):
        return len(self.tensors[0])


class ComposeDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)
        assert self.datasets
        n = len(self.datasets[0])
        assert all(len(d) == n for d in self.datasets)

    def __len__(self):
        return len(self.datasets[0])

    def __getitem__(self, idx):
        out = []
        for d in self.datasets:
            item = d[idx]
            out.extend(item if isinstance(item, (list, tuple)) else [item])
        return tuple(out)


class ChainDataset(IterableDataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __iter__(self):
        for d in self.datasets:
            yield from d


class ConcatDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)
        self.cumsizes = np.cumsum([len(d) for d in self.datasets]).tolist()

    def __len__(self):
        return self.cumsizes[-1]

    def __getitem__(self, idx):
        if idx < 0:
            idx += len(self)
        i = bisect.bisect_right(self.cumsizes, idx)
        prev = self.cumsizes[i - 1] if i > 0 else 0
        return self.datasets[i][idx - prev]


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset, lengths, generator=None):
    total = len(dataset)
    if all(isinstance(l, float) for l in lengths):
        lengths = [int(round(l * total)) for l in lengths]
        lengths[-1] = total - sum(lengths[:-1])
    assert sum(lengths) == total
    perm = np.random.permutation(total)
    out, off = [], 0
    for n in lengths:
        out.append(Subset(dataset, perm[off:off + n].tolist()))
        off += n
    return out
