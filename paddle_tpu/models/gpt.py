"""GPT-class decoder LM (causal; the long-context / hybrid-parallel demo).

Uses causal flash attention; same TP annotations as ERNIE; the sp axis can
shard the sequence (ring attention path) via
paddle_tpu.distributed.ring for long contexts.
"""
from __future__ import annotations

import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from .. import nn
from ..nn import functional as F
from ..distributed.env import TENSOR_AXIS
from ..ops import creation, manipulation

__all__ = ["GPTConfig", "GPTModel", "GPTForCausalLM"]


class GPTConfig:
    def __init__(self, vocab_size=50304, hidden_size=768, num_layers=12,
                 num_heads=12, max_seq_len=1024, dropout=0.1,
                 layer_norm_eps=1e-5, use_flash_attention=True,
                 scan_layers=False, chunked_ce=False,
                 ce_vocab_block=2048):
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.num_heads = num_heads
        self.max_seq_len = max_seq_len
        self.dropout = dropout
        self.layer_norm_eps = layer_norm_eps
        self.use_flash_attention = use_flash_attention
        # chunked_ce: training-only — forward returns HIDDEN states and
        # chunked_lm_loss streams the tied head through vocab blocks
        # (F.linear_cross_entropy, no [b*s, vocab] logits). generate()
        # reads weights directly (models/generation.py) and is
        # unaffected, but logits-consuming eval flows need this off
        self.chunked_ce = chunked_ce
        self.ce_vocab_block = ce_vocab_block
        # one lax.scan over stacked block params — compile time / HLO
        # size O(1) in depth (nn.ScannedStack; see models/ernie.py)
        self.scan_layers = bool(scan_layers)

    @classmethod
    def tiny(cls, **kw):
        return cls(vocab_size=512, hidden_size=64, num_layers=2,
                   num_heads=4, max_seq_len=128, **kw)


class GPTBlock(nn.Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        h = config.hidden_size
        self.ln1 = nn.LayerNorm(h, epsilon=config.layer_norm_eps)
        self.ln2 = nn.LayerNorm(h, epsilon=config.layer_norm_eps)
        self.num_heads = config.num_heads
        self.head_dim = h // config.num_heads
        self.qkv = nn.Linear(h, 3 * h)
        self.qkv.weight.sharding_spec = P(None, TENSOR_AXIS)
        self.qkv.bias.sharding_spec = P(TENSOR_AXIS)
        self.proj = nn.Linear(h, h)
        self.proj.weight.sharding_spec = P(TENSOR_AXIS, None)
        self.fc1 = nn.Linear(h, 4 * h)
        self.fc1.weight.sharding_spec = P(None, TENSOR_AXIS)
        self.fc1.bias.sharding_spec = P(TENSOR_AXIS)
        self.fc2 = nn.Linear(4 * h, h)
        self.fc2.weight.sharding_spec = P(TENSOR_AXIS, None)
        self.dropout = nn.Dropout(config.dropout)
        self.use_flash = config.use_flash_attention

    def forward(self, x):
        b, s, h = x.shape
        xn = self.ln1(x)
        qkv = self.qkv(xn).reshape([b, s, 3, self.num_heads, self.head_dim])
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        p = self.dropout.p
        if self.use_flash:
            ctx = F.flash_attention(q, k, v, causal=True, dropout=p,
                                    training=self.training)
        else:
            ctx = F.scaled_dot_product_attention(
                q, k, v, is_causal=True, dropout_p=p,
                training=self.training)
        x = x + self.dropout(self.proj(ctx.reshape([b, s, h])))
        x = x + self.dropout(self.fc2(F.gelu(self.fc1(self.ln2(x)))))
        return x


class GPTModel(nn.Layer):
    def __init__(self, config: GPTConfig = None, **kwargs):
        super().__init__()
        self.config = config or GPTConfig(**kwargs)
        cfg = self.config
        self.wte = nn.Embedding(cfg.vocab_size, cfg.hidden_size)
        self.wte.weight.sharding_spec = P(TENSOR_AXIS, None)
        self.wpe = nn.Embedding(cfg.max_seq_len, cfg.hidden_size)
        self.drop = nn.Dropout(cfg.dropout)
        if cfg.scan_layers:
            self.blocks = nn.ScannedStack(
                [GPTBlock(cfg) for _ in range(cfg.num_layers)],
                op_name="gpt_scanned_blocks")
        else:
            self.blocks = nn.LayerList([GPTBlock(cfg)
                                        for _ in range(cfg.num_layers)])
        self.ln_f = nn.LayerNorm(cfg.hidden_size,
                                 epsilon=cfg.layer_norm_eps)

    def forward(self, input_ids):
        b, s = input_ids.shape
        pos = creation.arange(s, dtype="int32")
        pos = manipulation.expand(manipulation.unsqueeze(pos, 0), [b, s])
        x = self.drop(self.wte(input_ids) + self.wpe(pos))
        if isinstance(self.blocks, nn.ScannedStack):
            x = self.blocks(x)
        else:
            for blk in self.blocks:
                x = blk(x)
        return self.ln_f(x)


class GPTForCausalLM(nn.Layer):
    def __init__(self, config: GPTConfig = None, **kwargs):
        super().__init__()
        self.gpt = GPTModel(config, **kwargs)

    def forward(self, input_ids):
        h = self.gpt(input_ids)
        if self.gpt.config.chunked_ce:
            return h   # head moves into chunked_lm_loss
        w = self.gpt.wte.weight
        # 2D head matmul: keeps the [b*s, vocab] logits row-major so XLA
        # never transpose-copies the largest tensor (see ernie.py)
        b, s = h.shape[0], h.shape[1]
        h2 = h.reshape([-1, h.shape[-1]])
        return F.linear(h2, manipulation.t(w)).reshape([b, s, -1])

    @staticmethod
    def lm_loss(logits, labels):
        return F.cross_entropy(
            logits[:, :-1].reshape([-1, logits.shape[-1]]),
            labels[:, 1:].reshape([-1]))

    def chunked_lm_loss(self, hidden, labels):
        """Loss for chunked_ce=True models: `hidden` is forward()'s
        output; the tied head + CE stream through vocab blocks — the
        [b*s, vocab] logits never exist. Bind as the TrainStep loss_fn:
        TrainStep(model, model.chunked_lm_loss, ...)."""
        cfg = self.gpt.config
        h2 = hidden[:, :-1].reshape([-1, hidden.shape[-1]])
        w_t = manipulation.t(self.gpt.wte.weight)
        return F.linear_cross_entropy(
            h2, w_t, None, labels[:, 1:].reshape([-1]),
            vocab_block=min(cfg.ce_vocab_block, cfg.vocab_size))

    def generate(self, input_ids, max_new_tokens=32, temperature=0.0,
                 top_k=None, eos_token_id=None, pad_token_id=0,
                 num_beams=1, seed=0, dtype=None, prompt_lens=None,
                 top_p=None):
        """KV-cache autoregressive decode compiled as one XLA program
        (models/generation.py); temperature=0 is greedy, num_beams>1
        is beam search over the same cache machinery. dtype="bfloat16"
        serves in bf16 (≈2× decode throughput on TPU; sampling and
        layernorm stay f32). prompt_lens [B] batches ragged
        (right-padded) prompts in one program."""
        from .generation import generate_gpt
        return generate_gpt(self, input_ids, max_new_tokens=max_new_tokens,
                            temperature=temperature, top_k=top_k,
                            eos_token_id=eos_token_id,
                            pad_token_id=pad_token_id,
                            num_beams=num_beams, seed=seed, dtype=dtype,
                            prompt_lens=prompt_lens, top_p=top_p)
