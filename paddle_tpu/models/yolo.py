"""YOLOv3-class detector (BASELINE config 4's trainable workload).

The reference core ships the YOLO op family — training loss
(/root/reference/paddle/fluid/operators/detection/yolov3_loss_op.cc),
box decode (yolo_box_op.cc), multi-class NMS (multiclass_nms_op.cc) —
and PaddleDetection composes them into PP-YOLO models. This module is
the TPU-native composition: a static-shape DarkNet-tiny backbone +
FPN-style top-down neck + three scale heads, trained through the same
TrainStep/AMP machinery as every other model and served through
yolo_box + multiclass_nms (ops/detection.py — already static-shape /
MXU-friendly). Variable input sizes go through the bucketing policy
(io/sampler.py): one XLA compilation per bucket, no recompile storms
(tests/test_yolo.py asserts the compile count).

TPU-first choices vs the reference composition:
- everything static-shape: gt boxes ride a fixed [N, B, 4] pad-to-max
  layout (the loss masks invalid rows), NMS outputs fixed
  [N, keep_top_k, 6] with valid counts — no LoD/dynamic tensors;
- BN + leaky stay in f32 under AMP O1 while convs run bf16 on the MXU;
- the three heads return a tuple (pp would shard them; XLA fuses the
  shared neck), loss is the sum of per-scale yolov3_loss means.
"""
from __future__ import annotations

from .. import nn
from ..nn import functional as F
from ..ops import detection as det
from ..ops.manipulation import concat, transpose

__all__ = ["YOLOv3", "DarkNetTiny", "yolov3_default_anchors"]

# COCO-style 9 anchors (w, h in input pixels), smallest → largest
yolov3_default_anchors = (10, 13, 16, 30, 33, 23,
                          30, 61, 62, 45, 59, 119,
                          116, 90, 156, 198, 373, 326)


class _ConvBN(nn.Layer):
    """conv → BN → leaky_relu, the darknet unit."""

    def __init__(self, cin, cout, k=3, stride=1):
        super().__init__()
        self.conv = nn.Conv2D(cin, cout, k, stride=stride,
                              padding=k // 2, bias_attr=False)
        self.bn = nn.BatchNorm2D(cout)

    def forward(self, x):
        return F.leaky_relu(self.bn(self.conv(x)), 0.1)


class DarkNetTiny(nn.Layer):
    """Compact darknet: returns (c3, c4, c5) at strides 8/16/32 with
    channels (4w, 8w, 16w). width=16 gives the darknet-tiny scale;
    tests shrink it."""

    def __init__(self, width=16):
        super().__init__()
        w = width
        self.stem = _ConvBN(3, w)                      # /1
        self.d1 = _ConvBN(w, 2 * w, stride=2)          # /2
        self.d2 = _ConvBN(2 * w, 2 * w)
        self.d3 = _ConvBN(2 * w, 4 * w, stride=2)      # /4
        self.d4 = _ConvBN(4 * w, 4 * w)
        self.d5 = _ConvBN(4 * w, 4 * w, stride=2)      # /8  -> c3
        self.d6 = _ConvBN(4 * w, 8 * w)
        self.d7 = _ConvBN(8 * w, 8 * w, stride=2)      # /16 -> c4
        self.d8 = _ConvBN(8 * w, 16 * w)
        self.d9 = _ConvBN(16 * w, 16 * w, stride=2)    # /32 -> c5
        self.out_channels = (4 * w, 8 * w, 16 * w)

    def forward(self, x):
        x = self.d2(self.d1(self.stem(x)))
        c3 = self.d5(self.d4(self.d3(x)))
        c4 = self.d7(self.d6(c3))
        c5 = self.d9(self.d8(c4))
        return c3, c4, c5


class YOLOv3(nn.Layer):
    """Three-scale YOLOv3 head over a feature backbone.

    forward(images [N,3,H,W]) -> (p5, p4, p3): per-scale raw head
    outputs [N, A*(5+C), H/d, W/d] for d in (32, 16, 8) — the exact
    layout yolov3_loss / yolo_box consume.
    """

    downsamples = (32, 16, 8)

    def __init__(self, num_classes=80,
                 anchors=yolov3_default_anchors,
                 anchor_masks=((6, 7, 8), (3, 4, 5), (0, 1, 2)),
                 width=16, ignore_thresh=0.7, backbone=None):
        super().__init__()
        self.num_classes = int(num_classes)
        self.anchors = tuple(anchors)
        self.anchor_masks = tuple(tuple(m) for m in anchor_masks)
        self.ignore_thresh = float(ignore_thresh)
        self.backbone = backbone or DarkNetTiny(width)
        c3, c4, c5 = self.backbone.out_channels
        per = lambda m: len(m) * (5 + self.num_classes)

        self.neck5 = _ConvBN(c5, c5 // 2, k=1)
        self.head5 = nn.Sequential(
            _ConvBN(c5 // 2, c5),
            nn.Conv2D(c5, per(self.anchor_masks[0]), 1))

        self.lat4 = _ConvBN(c5 // 2, c4 // 2, k=1)     # to upsample
        self.neck4 = _ConvBN(c4 + c4 // 2, c4 // 2, k=1)
        self.head4 = nn.Sequential(
            _ConvBN(c4 // 2, c4),
            nn.Conv2D(c4, per(self.anchor_masks[1]), 1))

        self.lat3 = _ConvBN(c4 // 2, c3 // 2, k=1)
        self.neck3 = _ConvBN(c3 + c3 // 2, c3 // 2, k=1)
        self.head3 = nn.Sequential(
            _ConvBN(c3 // 2, c3),
            nn.Conv2D(c3, per(self.anchor_masks[2]), 1))

    def forward(self, images):
        c3, c4, c5 = self.backbone(images)
        t5 = self.neck5(c5)
        p5 = self.head5(t5)
        u4 = F.interpolate(self.lat4(t5), scale_factor=2,
                           mode="nearest")
        t4 = self.neck4(concat((c4, u4), axis=1))
        p4 = self.head4(t4)
        u3 = F.interpolate(self.lat3(t4), scale_factor=2,
                           mode="nearest")
        t3 = self.neck3(concat((c3, u3), axis=1))
        p3 = self.head3(t3)
        return p5, p4, p3

    # -- training -------------------------------------------------------
    def loss(self, outputs, gt_box, gt_label, gt_score=None):
        """Sum of per-scale yolov3_loss means. gt_box [N,B,4] cx,cy,w,h
        normalized to the image; invalid rows have w=h=0."""
        total = None
        for out, mask, down in zip(outputs, self.anchor_masks,
                                   self.downsamples):
            per_img = det.yolov3_loss(
                out, gt_box, gt_label, anchors=list(self.anchors),
                anchor_mask=list(mask), class_num=self.num_classes,
                ignore_thresh=self.ignore_thresh,
                downsample_ratio=down, gt_score=gt_score)
            scale_loss = per_img.mean()
            total = scale_loss if total is None else total + scale_loss
        return total

    # -- inference ------------------------------------------------------
    def predict(self, outputs, im_size, conf_thresh=0.05,
                nms_threshold=0.45, keep_top_k=100, nms_type="hard"):
        """Decode + multi-class NMS. im_size [N,2] int (h, w).
        Returns (dets [N, keep_top_k, 6] rows [label, score, x1,y1,x2,y2],
        valid_counts [N]) — static shapes, padded rows label -1.

        nms_type: "hard" (multiclass_nms, while-loop suppression) or
        "matrix" (matrix_nms — PP-YOLOv2's default; score decay by
        max-IoU, pure matrix math, the MXU-friendly form)."""
        boxes, scores = [], []
        for out, mask, down in zip(outputs, self.anchor_masks,
                                   self.downsamples):
            lvl_anchors = []
            for a in mask:
                lvl_anchors += [self.anchors[2 * a],
                                self.anchors[2 * a + 1]]
            b, s = det.yolo_box(out, im_size, anchors=lvl_anchors,
                                class_num=self.num_classes,
                                conf_thresh=conf_thresh,
                                downsample_ratio=down)
            boxes.append(b)
            scores.append(s)
        allb = concat(boxes, axis=1)
        alls = transpose(concat(scores, axis=1), [0, 2, 1])
        if nms_type == "matrix":
            return det.matrix_nms(
                allb, alls, score_threshold=conf_thresh,
                post_threshold=conf_thresh, keep_top_k=keep_top_k,
                background_label=-1, normalized=False)
        if nms_type != "hard":
            raise ValueError(f"nms_type={nms_type!r}: must be 'hard' "
                             "or 'matrix'")
        return det.multiclass_nms(
            allb, alls,
            score_threshold=conf_thresh, nms_threshold=nms_threshold,
            keep_top_k=keep_top_k, background_label=-1,
            normalized=False)
