"""Autoregressive generation with a KV cache — one compiled decode loop.

Reference decoding surface: beam_search ops
(/root/reference/paddle/fluid/operators/beam_search_op.cc, exposed via
layers/rnn.py dynamic_decode) driven one step at a time from Python —
every step is an executor round-trip. The TPU-native form is ONE jitted
program: prefill computes the prompt's per-layer K/V into a
statically-shaped cache, then a `lax.scan` over decode steps updates the
cache in place (`dynamic_update_slice`) and attends over the valid
prefix with an iota mask. Static shapes throughout: the cache is sized
to prompt_len + max_new_tokens, finished rows keep emitting pad — XLA
compiles the whole generation once per (batch, prompt_len,
max_new_tokens) signature.

Supports greedy and temperature/top-k sampling over GPTForCausalLM
(weight-tied head). Correctness contract: greedy decode through the
cache equals argmax over full re-forward logits at every step
(tests/test_generation.py).

This module is ALSO the numerical reference for the continuous-batching
serving engine: paddle_tpu/serving/programs.py imports `_ln`, `_attend`,
`_prefill`, `_pick` (and the engine `_gpt_params`/`_cast_params`) so the
paged-cache decode is the same ops in the same order with only the cache
addressing changed — that reuse is what makes the paged-vs-dense greedy
parity contract bit-exact in f32 (tests/test_serving_engine.py). A
change to these helpers must keep both suites green.
"""
from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp

from ..framework import Tensor

__all__ = ["generate_gpt"]


def _ln(x, w, b, eps):
    # moments in f32 regardless of storage dtype: bf16 serving (the
    # dtype= cast below) would otherwise lose layernorm precision
    xf = x.astype(jnp.float32)
    mu = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.var(xf, axis=-1, keepdims=True)
    return (((xf - mu) / jnp.sqrt(var + eps)).astype(x.dtype) * w + b)


def _block_params(blk):
    return {
        "ln1_w": blk.ln1.weight._data, "ln1_b": blk.ln1.bias._data,
        "ln2_w": blk.ln2.weight._data, "ln2_b": blk.ln2.bias._data,
        "qkv_w": blk.qkv.weight._data, "qkv_b": blk.qkv.bias._data,
        "proj_w": blk.proj.weight._data, "proj_b": blk.proj.bias._data,
        "fc1_w": blk.fc1.weight._data, "fc1_b": blk.fc1.bias._data,
        "fc2_w": blk.fc2.weight._data, "fc2_b": blk.fc2.bias._data,
    }


# decode-key -> state_dict-name, derived from the one layout table in
# _block_params so a GPTBlock param rename can't go stale here
_SCAN_BLOCK_KEYS = {
    k: k[:-2] + (".weight" if k.endswith("_w") else ".bias")
    for k in ("ln1_w", "ln1_b", "ln2_w", "ln2_b", "qkv_w", "qkv_b",
              "proj_w", "proj_b", "fc1_w", "fc1_b", "fc2_w", "fc2_b")
}


def _gpt_params(model):
    gpt = model.gpt
    from ..nn.layer.scanned import ScannedStack
    if isinstance(gpt.blocks, ScannedStack):
        # scan_layers: slice the [L, ...] stacks into per-layer dicts —
        # the decode loop is already per-layer, so generation works
        # identically off either parameter layout
        stk = gpt.blocks
        get = {k: getattr(stk, stk._mangled[n])._data
               for k, n in _SCAN_BLOCK_KEYS.items()}
        blocks = [{k: v[i] for k, v in get.items()}
                  for i in range(stk.L)]
    else:
        blocks = [_block_params(b) for b in gpt.blocks]
    return {
        "wte": gpt.wte.weight._data,
        "wpe": gpt.wpe.weight._data,
        "lnf_w": gpt.ln_f.weight._data, "lnf_b": gpt.ln_f.bias._data,
        "blocks": blocks,
    }


def _mm(x, bp, name):
    """One block matmul through either the float weight
    (``<name>_w``: the training/bf16 serving path, unchanged HLO) or
    the serving int8 snapshot (a ``{"q8", "s"}`` leaf from
    quant/int8_serving — per-channel PTQ codes + dequant scales riding
    the params pytree as traced arguments). The branch is a trace-time
    isinstance on the pytree structure, so the float path compiles to
    exactly the ``x @ w`` it always was — the f32 greedy parity
    contract is untouched."""
    w = bp[name + "_w"]
    if isinstance(w, dict):
        from ..quant.int8_serving import int8_matmul
        return int8_matmul(x, w["q8"], w["s"])
    return x @ w


def _attend(q, kc, vc, n_valid, scale):
    """q [B,N,1,hd] over cache kc/vc [B,N,T,hd], masked to n_valid
    (scalar, or [B] for ragged per-row prompt lengths)."""
    s = jnp.einsum("bnqh,bnkh->bnqk", q, kc) * scale
    pos = jnp.arange(kc.shape[2])
    if getattr(n_valid, "ndim", 0):
        mask = pos[None, None, None, :] < n_valid[:, None, None, None]
    else:
        mask = pos[None, None, None, :] < n_valid
    s = jnp.where(mask, s, -1e30)
    p = jax.nn.softmax(s.astype(jnp.float32), axis=-1).astype(q.dtype)
    return jnp.einsum("bnqk,bnkh->bnqh", p, vc)


def _step_hidden(params, eps, n_heads, x, caches, pos):
    """One token's hidden state through all blocks, updating caches.

    x: [B, 1, H]; caches: list of (k [B,N,T,hd], v [B,N,T,hd]);
    pos: index where this token's K/V land — a scalar (uniform
    prompts) or [B] (ragged prompts: each row writes at its own next
    position and attends over its own valid prefix)."""
    new_caches = []
    hd = x.shape[-1] // n_heads
    scale = 1.0 / math.sqrt(hd)
    ragged = bool(getattr(pos, "ndim", 0))
    for bp, (kc, vc) in zip(params["blocks"], caches):
        b = x.shape[0]
        xn = _ln(x, bp["ln1_w"], bp["ln1_b"], eps)
        qkv = (_mm(xn, bp, "qkv") + bp["qkv_b"]).reshape(
            b, 1, 3, n_heads, hd)
        q = jnp.einsum("bsnh->bnsh", qkv[:, :, 0])
        k = jnp.einsum("bsnh->bnsh", qkv[:, :, 1])
        v = jnp.einsum("bsnh->bnsh", qkv[:, :, 2])
        if ragged:
            # per-row scatter: row i writes its K/V at pos[i]
            bi = jnp.arange(b)
            kc = kc.at[bi, :, pos].set(k[:, :, 0])
            vc = vc.at[bi, :, pos].set(v[:, :, 0])
        else:
            kc = jax.lax.dynamic_update_slice_in_dim(kc, k, pos,
                                                     axis=2)
            vc = jax.lax.dynamic_update_slice_in_dim(vc, v, pos,
                                                     axis=2)
        ctx = _attend(q, kc, vc, pos + 1, scale)
        ctx = jnp.einsum("bnsh->bsnh", ctx).reshape(b, 1, -1)
        x = x + _mm(ctx, bp, "proj") + bp["proj_b"]
        ff = _ln(x, bp["ln2_w"], bp["ln2_b"], eps)
        ff = jax.nn.gelu(_mm(ff, bp, "fc1") + bp["fc1_b"],
                         approximate=False)
        x = x + _mm(ff, bp, "fc2") + bp["fc2_b"]
        new_caches.append((kc, vc))
    return x, new_caches


def _prefill(params, eps, n_heads, ids, total_len, prompt_lens=None,
             qkv_heads_major=False, tp_reduce=None, head_dim=None):
    """Full forward over the prompt, returning per-layer caches sized to
    total_len and the last hidden state. Uses the same big-matmul form
    as training (the MXU-efficient path) — only decode is token-wise.

    prompt_lens [B] (ragged, right-padded prompts): keys beyond each
    row's true length are masked; their junk cache slots are
    progressively OVERWRITTEN by the decode loop's per-row scatter, so
    they are never attended to.

    qkv_heads_major / tp_reduce: the tensor-parallel hooks. Inside a
    tp shard_map the qkv columns are laid out (heads, 3, hd) — so each
    chip's contiguous shard carries WHOLE heads with their q,k,v —
    and the proj/fc2 partial contractions need an all-reduce before
    the bias. Both default off; the tp=1 graph is byte-for-byte the
    one this function always built (the parity contract). head_dim
    must be given explicitly under tp (n_heads is then the LOCAL head
    count while the replicated hidden stays global)."""
    b, s = ids.shape
    hd = head_dim or params["wte"].shape[1] // n_heads
    scale = 1.0 / math.sqrt(hd)
    x = params["wte"][ids] + params["wpe"][jnp.arange(s)][None]
    cm = jnp.tril(jnp.ones((s, s), bool))
    if prompt_lens is not None:
        cm = (cm[None, None]
              & (jnp.arange(s)[None, :]
                 < prompt_lens[:, None])[:, None, None, :])
    caches = []
    for bp in params["blocks"]:
        xn = _ln(x, bp["ln1_w"], bp["ln1_b"], eps)
        qkv = _mm(xn, bp, "qkv") + bp["qkv_b"]
        if qkv_heads_major:
            qkv = jnp.einsum("bsnch->bscnh", qkv.reshape(
                b, s, n_heads, 3, hd))
        else:
            qkv = qkv.reshape(b, s, 3, n_heads, hd)
        q = jnp.einsum("bsnh->bnsh", qkv[:, :, 0])
        k = jnp.einsum("bsnh->bnsh", qkv[:, :, 1])
        v = jnp.einsum("bsnh->bnsh", qkv[:, :, 2])
        att = jnp.einsum("bnqh,bnkh->bnqk", q, k) * scale
        att = jnp.where(cm, att, -1e30)
        p = jax.nn.softmax(att.astype(jnp.float32), axis=-1).astype(
            x.dtype)
        ctx = jnp.einsum("bnqk,bnkh->bnqh", p, v)
        ctx = jnp.einsum("bnsh->bsnh", ctx).reshape(b, s, -1)
        proj = _mm(ctx, bp, "proj")
        if tp_reduce is not None:
            proj = tp_reduce(proj)
        x = x + proj + bp["proj_b"]
        ff = _ln(x, bp["ln2_w"], bp["ln2_b"], eps)
        ff = jax.nn.gelu(_mm(ff, bp, "fc1") + bp["fc1_b"],
                         approximate=False)
        f2 = _mm(ff, bp, "fc2")
        if tp_reduce is not None:
            f2 = tp_reduce(f2)
        x = x + f2 + bp["fc2_b"]
        kc = jnp.zeros((b, n_heads, total_len, hd), k.dtype)
        vc = jnp.zeros((b, n_heads, total_len, hd), v.dtype)
        kc = jax.lax.dynamic_update_slice_in_dim(kc, k, 0, axis=2)
        vc = jax.lax.dynamic_update_slice_in_dim(vc, v, 0, axis=2)
        caches.append((kc, vc))
    return x, caches


def _pick(logits, key, temperature, top_k, top_p=None):
    logits = logits.astype(jnp.float32)  # sampling math in f32 even
    # when the matmuls ran in bf16 (argmax is cast-invariant)
    if temperature == 0.0:  # greedy (static python branch)
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits / temperature
    need_p = top_p is not None and float(top_p) < 1.0
    if top_k is not None or need_p:
        # ONE descending sort serves both filters (vocab-size sort is
        # the dominant sampling cost per decode step)
        sorted_l = jnp.sort(logits, axis=-1)[:, ::-1]
    if top_k is not None:
        k = min(int(top_k), logits.shape[-1])  # HF-style clamp
        kth = sorted_l[:, k - 1][:, None]
        logits = jnp.where(logits >= kth, logits, -1e30)
    if need_p:
        # nucleus sampling: keep the smallest prefix of the
        # descending-probability order whose mass reaches top_p (the
        # first token past the threshold stays in — HF semantics; the
        # top token's EXCLUSIVE mass is 0, so it always survives).
        # Sequential-filter semantics: when top_k is also set, the
        # nucleus mass is computed over the top_k-masked distribution
        # (HF warper order). Static-shape: sort + cumsum + where.
        base = sorted_l
        if top_k is not None:
            base = jnp.where(
                jnp.arange(base.shape[-1])[None, :] < k, base, -1e30)
        probs = jax.nn.softmax(base, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        keep = (cum - probs) < float(top_p)
        kth = jnp.min(jnp.where(keep, base, jnp.inf), axis=-1,
                      keepdims=True)
        logits = jnp.where(logits >= kth, logits, -1e30)
    return jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)


def _cast_params(params, dtype):
    """Serving-dtype cast INSIDE the jitted program: one streamed
    f32→bf16 pass over the weights per call (vs per decode step), no
    host-side cached copy that could go stale after a weight update."""
    if dtype is None:
        return params
    dt = jnp.dtype(dtype)
    return jax.tree_util.tree_map(
        lambda a: (a.astype(dt)
                   if jnp.issubdtype(a.dtype, jnp.floating) else a),
        params)


@functools.lru_cache(maxsize=64)
def _build_run(eps, n_heads, temperature, top_k, eos_token_id,
               pad_token_id, max_new_tokens, prompt, total, dtype,
               ragged=False, top_p=None):
    """One jitted decode program per static signature — repeated
    generate() calls with the same shapes/sampling config reuse the
    compiled executable (params/ids/key[/prompt_lens] are traced
    arguments). ragged=True compiles the per-row-position form: each
    batch row prefills over its own prompt_lens[i]-long prefix, then
    decodes writing K/V at its own next position."""

    def run(params, ids, key, prompt_lens=None):
        params = _cast_params(params, dtype)
        b = ids.shape[0]
        pl = prompt_lens if ragged else None
        x, caches = _prefill(params, eps, n_heads, ids, total,
                             prompt_lens=pl)
        if ragged:
            idx = (prompt_lens - 1).astype(jnp.int32)
            last = jnp.take_along_axis(
                x, idx[:, None, None], axis=1)          # [B, 1, H]
            h_last = _ln(last, params["lnf_w"], params["lnf_b"], eps)
            pos0 = prompt_lens.astype(jnp.int32)
        else:
            h_last = _ln(x[:, -1:], params["lnf_w"], params["lnf_b"],
                         eps)
            pos0 = jnp.int32(prompt)
        logits = (h_last[:, 0] @ params["wte"].T)

        def body(carry, step_key):
            caches, logits, pos, done = carry
            tok = _pick(logits, step_key, temperature, top_k,
                        top_p)
            if eos_token_id is not None:
                tok = jnp.where(done, pad_token_id, tok)
                done = done | (tok == eos_token_id)
            emb_pos = (params["wpe"][pos] if ragged
                       else params["wpe"][pos][None])
            x = (params["wte"][tok] + emb_pos)[:, None, :]
            x, caches = _step_hidden(params, eps, n_heads, x, caches,
                                     pos)
            h = _ln(x, params["lnf_w"], params["lnf_b"], eps)
            logits = h[:, 0] @ params["wte"].T
            return (caches, logits, pos + 1, done), tok

        keys = jax.random.split(key, max_new_tokens)
        done0 = jnp.zeros((b,), bool)
        (_, _, _, _), toks = jax.lax.scan(
            body, (caches, logits, pos0, done0), keys)
        return jnp.concatenate([ids, toks.T], axis=1)

    return jax.jit(run)


@functools.lru_cache(maxsize=64)
def _build_beam_run(eps, n_heads, num_beams, eos_token_id, pad_token_id,
                    max_new_tokens, prompt, total, dtype):
    """Beam-search decode sharing the KV-cache machinery: beams live as
    batch rows [B*W], each step expands with the beam_search_step op's
    semantics (ops/extras.py, ref beam_search_op.cc), reorders the
    caches by parent beam, and the token/parent trail is walked back
    with gather_tree (ref gather_tree_op.cc)."""
    from ..ops.extras import beam_search_step, gather_tree
    bs_step = beam_search_step.__pure_fn__
    tree = gather_tree.__pure_fn__
    w = num_beams

    def run(params, ids, key):
        del key
        params = _cast_params(params, dtype)
        b = ids.shape[0]
        # prefill ONCE over the B prompts, then repeat the caches and
        # final logits across beams (duplicate rows would recompute the
        # identical prompt forward W times)
        x, caches = _prefill(params, eps, n_heads, ids, total)
        caches = jax.tree_util.tree_map(
            lambda c: jnp.repeat(c, w, axis=0), caches)
        h_last = _ln(x[:, -1:], params["lnf_w"], params["lnf_b"], eps)
        logits = jnp.repeat(h_last[:, 0] @ params["wte"].T, w,
                            axis=0)                         # [B*W, V]
        scores0 = jnp.tile(
            jnp.asarray([0.0] + [-1e30] * (w - 1), jnp.float32), (b, 1))
        done0 = jnp.zeros((b, w), bool)

        def body(carry, _):
            caches, logits, pos, scores, done = carry
            logp = jax.nn.log_softmax(
                logits.astype(jnp.float32), axis=-1).reshape(b, w, -1)
            if eos_token_id is not None:
                # finished beams only extend with pad at zero cost
                v = logp.shape[-1]
                frozen = jnp.full((v,), -1e30).at[pad_token_id].set(0.0)
                logp = jnp.where(done[:, :, None], frozen[None, None],
                                 logp)
            scores, toks, parents = bs_step(logp, scores, beam_size=w)
            if eos_token_id is not None:
                done = jnp.take_along_axis(done, parents, axis=1)
                done = done | (toks == eos_token_id)
            # reorder beam rows (KV caches + emitted state) by parent
            gidx = (jnp.arange(b)[:, None] * w + parents).reshape(-1)
            caches = jax.tree_util.tree_map(
                lambda c: jnp.take(c, gidx, axis=0), caches)
            flat_toks = toks.reshape(-1)
            x = (params["wte"][flat_toks]
                 + params["wpe"][pos][None])[:, None, :]
            x, caches = _step_hidden(params, eps, n_heads, x, caches,
                                     pos)
            h = _ln(x, params["lnf_w"], params["lnf_b"], eps)
            logits = h[:, 0] @ params["wte"].T
            return (caches, logits, pos + 1, scores, done), (toks,
                                                             parents)

        (_, _, _, scores, _), (toks, parents) = jax.lax.scan(
            body, (caches, logits, jnp.int32(prompt), scores0, done0),
            jnp.arange(max_new_tokens))
        seqs = tree(toks, parents)                         # [T, B, W]
        best = jnp.argmax(scores, axis=1)                  # [B]
        best_toks = jnp.take_along_axis(
            seqs, best[None, :, None], axis=2)[:, :, 0]    # [T, B]
        return (jnp.concatenate([ids, best_toks.T.astype(jnp.int32)],
                                axis=1),
                jnp.take_along_axis(scores, best[:, None], 1)[:, 0])

    return jax.jit(run)


def generate_gpt(model, input_ids, max_new_tokens=32, temperature=0.0,
                 top_k: Optional[int] = None,
                 eos_token_id: Optional[int] = None, pad_token_id=0,
                 num_beams=1, seed=0, dtype=None, prompt_lens=None,
                 top_p: Optional[float] = None):
    """KV-cache decode for GPTForCausalLM. temperature=0 -> greedy;
    num_beams>1 -> beam search (temperature/top_k/top_p ignored —
    beams expand by log-prob, not sampling).

    prompt_lens [B] int (ragged batching — the reference's LoD-driven
    dynamic_decode capability, TPU-style): input_ids is right-padded
    to a common length with any valid token id (pad_token_id by
    convention); row i's true prompt is its first prompt_lens[i] ids.
    Each row prefill-masks its padding, then decode writes K/V at its
    OWN next position, so rows of different lengths batch in one
    compiled program. Generated tokens still land in out[:, P:] for
    every row (out[i, prompt_lens[i]:P] keeps the pad filler).

    dtype="bfloat16" casts the float params (and with them the KV
    cache) for the decode — single-token decode is HBM-bound on
    weight reads, so bf16 serving roughly halves step latency on TPU.
    Layernorm moments and sampling stay in f32. Default None keeps
    the training dtype (exact greedy-equals-full-forward contract).

    Returns int32 [B, prompt_len + max_new_tokens]; rows that hit
    eos_token_id keep emitting pad_token_id afterwards.
    """
    cfg = model.gpt.config
    params = _gpt_params(model)
    dtype = None if dtype is None else str(jnp.dtype(dtype))
    ids = jnp.asarray(input_ids._data if isinstance(input_ids, Tensor)
                      else input_ids, jnp.int32)
    b, prompt = ids.shape
    if top_p is not None and not (0.0 < float(top_p) <= 1.0):
        # fail loudly host-side: top_p<=0 would mask EVERY token and
        # degenerate to uniform sampling over the whole vocab
        raise ValueError(f"top_p must be in (0, 1]; got {top_p}")
    total = prompt + int(max_new_tokens)
    if total > cfg.max_seq_len:
        raise ValueError(
            f"prompt+max_new_tokens={total} exceeds max_seq_len="
            f"{cfg.max_seq_len}")
    if num_beams > 1:
        if prompt_lens is not None:
            raise ValueError("prompt_lens is not supported with beam "
                             "search yet — pad to a common length")
        run = _build_beam_run(
            float(cfg.layer_norm_eps), int(cfg.num_heads),
            int(num_beams),
            None if eos_token_id is None else int(eos_token_id),
            int(pad_token_id), int(max_new_tokens), prompt, total,
            dtype)
        out, _scores = run(params, ids, jax.random.key(seed))
        return Tensor(out)
    ragged = prompt_lens is not None
    if ragged:
        import numpy as _np
        pl_host = _np.asarray(prompt_lens._data
                              if isinstance(prompt_lens, Tensor)
                              else prompt_lens)
        # fail loudly host-side: under jit, out-of-range lengths clamp
        # silently and the decode attends junk cache slots
        if pl_host.shape != (b,):
            raise ValueError(
                f"prompt_lens shape {pl_host.shape} != ({b},)")
        if pl_host.min() < 1 or pl_host.max() > prompt:
            raise ValueError(
                f"prompt_lens must be in [1, {prompt}] (padded prompt "
                f"width); got min={pl_host.min()} max={pl_host.max()}")
    run = _build_run(
        float(cfg.layer_norm_eps), int(cfg.num_heads),
        float(temperature), None if top_k is None else int(top_k),
        None if eos_token_id is None else int(eos_token_id),
        int(pad_token_id), int(max_new_tokens), prompt, total, dtype,
        ragged, None if top_p is None else float(top_p))
    if ragged:
        pl = jnp.asarray(prompt_lens._data
                         if isinstance(prompt_lens, Tensor)
                         else prompt_lens, jnp.int32)
        out = run(params, ids, jax.random.key(seed), pl)
    else:
        out = run(params, ids, jax.random.key(seed))
    return Tensor(out)
