from ..vision.models import (LeNet, MobileNetV1, MobileNetV2, ResNet, VGG,
                             mobilenet_v1, mobilenet_v2, resnet18, resnet34,
                             resnet50, resnet101, resnet152, vgg11, vgg13,
                             vgg16, vgg19)  # noqa: F401
from .ernie import (ErnieConfig, ErnieModel, ErnieForPretraining,
                    ErnieStageFirst, ErnieStageMiddle, ErnieStageLast,
                    ernie_pipeline_stages,
                    ErnieForSequenceClassification)  # noqa: F401
from .gpt import GPTConfig, GPTModel, GPTForCausalLM  # noqa: F401
from .generation import generate_gpt  # noqa: F401
from .yolo import YOLOv3, DarkNetTiny, yolov3_default_anchors  # noqa: F401
