"""ERNIE/BERT-class encoder (the BASELINE config-3/5 flagship).

Capability-parity with the reference's ERNIE workloads (the north star in
SURVEY.md §0 and BASELINE.md): transformer encoder pretraining with MLM +
NSP heads. TPU-first construction:
- fused flash/SDPA attention (single XLA fusion region per block)
- every parameter carries a tensor-parallel PartitionSpec annotation
  (qkv/ffn-in column-split, proj/ffn-out row-split, embeddings
  vocab-split) so ShardingPlan/pjit shards it over 'tp' with zero code
  changes — the reference needs distinct Column/RowParallelLinear model
  code (fleet meta_parallel) for this
- bf16-friendly: LayerNorm/softmax stay fp32 under AMP lists
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from .. import nn
from ..nn import functional as F
from ..distributed.env import TENSOR_AXIS
from ..framework import Parameter, Tensor
from ..observability.anatomy import scope as _scope
from ..ops import creation, manipulation

__all__ = ["ErnieConfig", "ErnieModel", "ErnieForPretraining",
           "ErnieScannedEncoder",
           "ErnieForSequenceClassification", "ErnieStageFirst",
           "ErnieStageMiddle", "ErnieStageLast", "ernie_pipeline_stages"]


class ErnieConfig:
    def __init__(self, vocab_size=30522, hidden_size=768,
                 num_hidden_layers=12, num_attention_heads=12,
                 intermediate_size=3072, hidden_act="gelu",
                 hidden_dropout_prob=0.1, attention_probs_dropout_prob=0.1,
                 max_position_embeddings=512, type_vocab_size=2,
                 initializer_range=0.02, layer_norm_eps=1e-12,
                 use_flash_attention=True, moe_num_experts=0,
                 moe_top_k=2, moe_every_n_layers=2,
                 moe_capacity_factor=1.25, moe_aux_weight=0.01,
                 sequence_parallel=False, scan_layers=False,
                 chunked_ce=False, ce_vocab_block=2048):
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.num_hidden_layers = num_hidden_layers
        self.num_attention_heads = num_attention_heads
        self.intermediate_size = intermediate_size
        self.hidden_act = hidden_act
        self.hidden_dropout_prob = hidden_dropout_prob
        self.attention_probs_dropout_prob = attention_probs_dropout_prob
        self.max_position_embeddings = max_position_embeddings
        self.type_vocab_size = type_vocab_size
        self.initializer_range = initializer_range
        self.layer_norm_eps = layer_norm_eps
        self.use_flash_attention = use_flash_attention
        # chunked_ce: the MLM head + CE stream through vocab blocks
        # (F.linear_cross_entropy) — the [b*s, vocab] logits are never
        # materialized. forward() then returns the transformed HIDDEN
        # states in place of logits, and pretraining_loss must be the
        # INSTANCE method chunked_pretraining_loss (it owns the tied
        # decoder weights); eval/generate flows should keep this off
        self.chunked_ce = chunked_ce
        self.ce_vocab_block = ce_vocab_block
        # MoE variant: every n-th layer's FFN becomes a top-k expert
        # mixture over the 'ep' mesh axis (distributed/moe.py); 0 = dense
        self.moe_num_experts = moe_num_experts
        self.moe_top_k = moe_top_k
        self.moe_every_n_layers = moe_every_n_layers
        self.moe_capacity_factor = moe_capacity_factor
        self.moe_aux_weight = moe_aux_weight
        if moe_num_experts > 0 and moe_every_n_layers < 1:
            raise ValueError(
                "moe_every_n_layers must be >= 1 when experts are "
                "enabled (set moe_num_experts=0 for a dense model)")
        # long-context mode: attention runs sequence-parallel over the
        # 'sp' mesh axis (distributed/ring.py) — each chip holds 1/sp of
        # the sequence. True/"ring" = ppermute ring (blockwise, O(s/P)
        # memory); "ulysses" = all-to-all head resharding (local full
        # attention over n/P heads). Requires attention dropout 0 (no
        # dropout state across hops/resharding).
        if sequence_parallel not in (False, True, "ring", "ulysses"):
            raise ValueError(
                f"sequence_parallel must be False/True/'ring'/'ulysses',"
                f" got {sequence_parallel!r}")
        self.sequence_parallel = sequence_parallel
        if sequence_parallel and attention_probs_dropout_prob > 0:
            raise ValueError(
                "sequence_parallel requires "
                "attention_probs_dropout_prob=0 (ring attention carries "
                "no dropout)")
        # scan_layers: run all encoder blocks as ONE lax.scan over
        # stacked parameters — compile time and HLO size O(1) in depth
        # (a 48-layer model lowers as fast as a 2-layer one). Requires
        # homogeneous blocks: no interleaved MoE.
        self.scan_layers = bool(scan_layers)
        if self.scan_layers and moe_num_experts > 0:
            raise ValueError(
                "scan_layers needs homogeneous blocks; interleaved MoE "
                "layers differ from dense ones (set moe_num_experts=0 "
                "or scan_layers=False)")

    @classmethod
    def base(cls, **kw):
        return cls(**kw)

    @classmethod
    def large(cls, **kw):
        return cls(hidden_size=1024, num_hidden_layers=24,
                   num_attention_heads=16, intermediate_size=4096, **kw)

    @classmethod
    def tiny(cls, **kw):
        """For tests/dryruns."""
        return cls(vocab_size=1024, hidden_size=64, num_hidden_layers=2,
                   num_attention_heads=4, intermediate_size=128,
                   max_position_embeddings=64, **kw)


def _init_linear(layer, std, col_spec=None, row_spec=None):
    from ..nn.initializer import Normal
    layer.weight.set_value(Normal(0, std)(tuple(layer.weight.shape),
                                          layer.weight.dtype))
    if col_spec is not None:
        layer.weight.sharding_spec = col_spec
    return layer


def _lens_to_additive_mask(kv_lens, s):
    """[b] right-padding lengths -> additive [b, 1, 1, s] mask (the
    SDPA fallback form; the flash path consumes kv_lens directly)."""
    pos = creation.arange(0, s, 1, "int64")
    am = (manipulation.unsqueeze(pos, [0])
          < manipulation.unsqueeze(kv_lens, [1]))
    return (1.0 - manipulation.unsqueeze(
        am, [1, 2]).astype("float32")) * -1e9


@functools.lru_cache(maxsize=8)
def _ring_attention_fn(mesh, mode="ring"):
    """One shard_map'd ring-attention closure per mesh (Mesh is hashable
    — equal-but-distinct meshes share an entry, and lru eviction keeps
    retired meshes from pinning device refs forever), shared by every
    attention layer (a per-layer closure would re-trace its vjp per
    layer per step). Layout [b, s_local, heads, dim]; batch rides 'dp'
    and heads stay 'tp'-sharded when those axes exist, so the ring
    composes with dp/tp without gathering."""
    import paddle_tpu.distributed as dist
    batch_ax = "dp" if "dp" in mesh.axis_names else None
    head_ax = TENSOR_AXIS if TENSOR_AXIS in mesh.axis_names else None
    spec = P(batch_ax, "sp", head_ax, None)

    attn = dist.ring_flash_attention if mode != "ulysses" \
        else dist.ulysses_attention

    def body(qq, kk, vv):
        return attn(qq, kk, vv, causal=False, group="sp")
    return dist.shard_parallel(
        body, mesh, in_specs=(spec, spec, spec), out_specs=spec,
        axes=("sp",)).__wrapped_smap__


from ..ops.registry import register_op as _register_op  # noqa: E402


@_register_op("attention_sp", tags=("mesh",))
def _attention_sp_op(q, k, v, mode="ring"):
    """Sequence-parallel attention as a REGISTERED op: only `mode` is an
    attribute; the mesh is re-resolved from the runtime at every call
    (dist.set_mesh state, like a Place — not program state), so captured
    programs serialize and a loaded program runs under whatever 'sp'
    mesh the resuming host has. The previous ad-hoc closure made
    save succeed and load fail (ADVICE r3)."""
    from ..distributed.env import get_mesh
    mesh = get_mesh()
    if mesh is None or "sp" not in mesh.axis_names:
        raise ValueError(
            "attention_sp op needs the global mesh to carry an 'sp' "
            "axis: dist.set_mesh(build_mesh({'dp': ..., 'sp': ...}))")
    return _ring_attention_fn(mesh, mode)(q, k, v)


class ErnieSelfAttention(nn.Layer):
    def __init__(self, config: ErnieConfig):
        super().__init__()
        h = config.hidden_size
        self.num_heads = config.num_attention_heads
        self.head_dim = h // self.num_heads
        self.use_flash = config.use_flash_attention
        self.dropout_p = config.attention_probs_dropout_prob
        self.seq_parallel = config.sequence_parallel
        std = config.initializer_range
        self.qkv = _init_linear(nn.Linear(h, 3 * h), std)
        self.qkv.weight.sharding_spec = P(None, TENSOR_AXIS)
        self.qkv.bias.sharding_spec = P(TENSOR_AXIS)
        self.out = _init_linear(nn.Linear(h, h), std)
        self.out.weight.sharding_spec = P(TENSOR_AXIS, None)

    def forward(self, x, attn_mask=None, kv_lens=None):
        # anatomy scope: everything here (qkv/proj matmuls, the
        # attention math) attributes to "attn" in the one-executable
        # HLO — backward included (transpose(jvp(attn)) paths)
        with _scope("attn"):
            return self._forward(x, attn_mask, kv_lens)

    def _forward(self, x, attn_mask=None, kv_lens=None):
        b, s, h = x.shape
        qkv = self.qkv(x).reshape([b, s, 3, self.num_heads, self.head_dim])
        q = qkv[:, :, 0]
        k = qkv[:, :, 1]
        v = qkv[:, :, 2]
        if self.seq_parallel:
            if attn_mask is not None or kv_lens is not None:
                raise ValueError(
                    "sequence_parallel attention takes no attention_mask"
                    "/kv_lens — pad to full blocks (io/sampler.py"
                    " bucketing) so every position is real, or run the"
                    " dense model")
            # mesh presence is validated inside the registered op (the
            # single serialization-safe entry point)
            mode = "ulysses" if self.seq_parallel == "ulysses" else "ring"
            ctx = _attention_sp_op(q, k, v, mode=mode)
            return self.out(ctx.reshape([b, s, h]))
        if attn_mask is None and self.use_flash:
            # kv_lens (right-padded batches) keeps the blockwise flash
            # form — a [b, s] padding mask need not force SDPA
            ctx = F.flash_attention(q, k, v, dropout=self.dropout_p,
                                    training=self.training,
                                    kv_lens=kv_lens)
        else:
            if kv_lens is not None and attn_mask is None:
                attn_mask = _lens_to_additive_mask(kv_lens, s)
            ctx = F.scaled_dot_product_attention(
                q, k, v, attn_mask=attn_mask, dropout_p=self.dropout_p,
                training=self.training)
        ctx = ctx.reshape([b, s, h])
        return self.out(ctx)


class ErnieLayer(nn.Layer):
    def __init__(self, config: ErnieConfig, use_moe: bool = False):
        super().__init__()
        h = config.hidden_size
        std = config.initializer_range
        self.attention = ErnieSelfAttention(config)
        self.attn_norm = nn.LayerNorm(h, epsilon=config.layer_norm_eps)
        self.use_moe = bool(use_moe and config.moe_num_experts > 0)
        if self.use_moe:
            from ..distributed.moe import MoELayer
            self.moe = MoELayer(
                h, config.intermediate_size, config.moe_num_experts,
                top_k=config.moe_top_k,
                capacity_factor=config.moe_capacity_factor,
                aux_weight=config.moe_aux_weight,
                activation=config.hidden_act)
        else:
            self.ffn_in = _init_linear(
                nn.Linear(h, config.intermediate_size), std)
            self.ffn_in.weight.sharding_spec = P(None, TENSOR_AXIS)
            self.ffn_in.bias.sharding_spec = P(TENSOR_AXIS)
            self.ffn_out = _init_linear(
                nn.Linear(config.intermediate_size, h), std)
            self.ffn_out.weight.sharding_spec = P(TENSOR_AXIS, None)
        self.ffn_norm = nn.LayerNorm(h, epsilon=config.layer_norm_eps)
        self.dropout = nn.Dropout(config.hidden_dropout_prob)
        self.act = config.hidden_act

    def forward(self, x, attn_mask=None, kv_lens=None):
        attn = self.attention(x, attn_mask, kv_lens=kv_lens)
        with _scope("attn"):
            x = self.attn_norm(x + self.dropout(attn))
        with _scope("mlp"):
            if self.use_moe:
                ffn = self.moe(x)
            else:
                ffn = self.ffn_out(getattr(F, self.act)(self.ffn_in(x)))
            x = self.ffn_norm(x + self.dropout(ffn))
        return x


class ErnieScannedEncoder(nn.ScannedStack):
    """All encoder blocks as ONE ``lax.scan`` over stacked parameters
    (nn.ScannedStack) — compile time and HLO size O(1) in depth.
    ``encoder.0.attention.qkv.weight [h,3h]`` x L becomes
    ``attention.qkv.weight [L,h,3h]``; tp specs shift past the stack
    axis. ``load_from_layers`` imports unrolled weights (parity tests
    compare both forms on identical values); the attention mask rides
    as a real op input."""

    def __init__(self, config: ErnieConfig, num_blocks=None):
        n = config.num_hidden_layers if num_blocks is None \
            else int(num_blocks)
        super().__init__(
            [ErnieLayer(config) for _ in range(n)],
            op_name="ernie_scanned_encoder")


def _is_moe_layer(config: ErnieConfig, i: int) -> bool:
    """MoE placement rule: every n-th block (1-indexed), when the config
    enables experts — the standard interleaved-MoE transformer layout."""
    return (config.moe_num_experts > 0
            and (i + 1) % config.moe_every_n_layers == 0)


class ErnieEmbeddings(nn.Layer):
    def __init__(self, config: ErnieConfig):
        super().__init__()
        self.word_embeddings = nn.Embedding(config.vocab_size,
                                            config.hidden_size)
        self.word_embeddings.weight.sharding_spec = P(TENSOR_AXIS, None)
        self.position_embeddings = nn.Embedding(
            config.max_position_embeddings, config.hidden_size)
        self.token_type_embeddings = nn.Embedding(config.type_vocab_size,
                                                  config.hidden_size)
        self.layer_norm = nn.LayerNorm(config.hidden_size,
                                       epsilon=config.layer_norm_eps)
        self.dropout = nn.Dropout(config.hidden_dropout_prob)

    def forward(self, input_ids, token_type_ids=None, position_ids=None):
        with _scope("embed"):
            b, s = input_ids.shape
            if position_ids is None:
                position_ids = creation.arange(s, dtype="int32")
                position_ids = manipulation.expand(
                    manipulation.unsqueeze(position_ids, 0), [b, s])
            if token_type_ids is None:
                token_type_ids = creation.zeros([b, s], dtype="int32")
            emb = (self.word_embeddings(input_ids)
                   + self.position_embeddings(position_ids)
                   + self.token_type_embeddings(token_type_ids))
            return self.dropout(self.layer_norm(emb))


class ErnieModel(nn.Layer):
    def __init__(self, config: ErnieConfig = None, **kwargs):
        super().__init__()
        self.config = config or ErnieConfig(**kwargs)
        self.embeddings = ErnieEmbeddings(self.config)
        if self.config.scan_layers:
            self.encoder = ErnieScannedEncoder(self.config)
        else:
            self.encoder = nn.LayerList(
                [ErnieLayer(self.config,
                            use_moe=_is_moe_layer(self.config, i))
                 for i in range(self.config.num_hidden_layers)])
        self.pooler = nn.Linear(self.config.hidden_size,
                                self.config.hidden_size)

    def moe_aux_loss(self):
        """Sum of the last forward's expert load-balancing losses (None
        for a dense config). Traced Tensors: usable inside a TrainStep
        loss_fn during the same forward trace."""
        if isinstance(self.encoder, ErnieScannedEncoder):
            return None  # scan_layers excludes MoE by construction
        total = None
        for lyr in self.encoder:
            if getattr(lyr, "use_moe", False) and \
                    lyr.moe.aux_loss is not None:
                total = lyr.moe.aux_loss if total is None \
                    else total + lyr.moe.aux_loss
        return total

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None, seq_lens=None):
        x = self.embeddings(input_ids, token_type_ids, position_ids)
        if attention_mask is not None and seq_lens is not None:
            raise ValueError("pass attention_mask OR seq_lens, not both")
        if attention_mask is not None:
            # [b, s] 1/0 mask -> additive [b, 1, 1, s]. A 1/0 mask is
            # GENERAL key masking (it need not be contiguous), so it
            # cannot be silently folded to lengths — right-padded
            # batches should pass seq_lens, which keeps the blockwise
            # varlen flash form instead of materialized SDPA.
            am = manipulation.unsqueeze(attention_mask, [1, 2])
            attention_mask = (1.0 - am.astype("float32")) * -1e9
        if seq_lens is not None and not self.config.use_flash_attention:
            # non-flash configs take the additive form ONCE here rather
            # than per layer (the flash path consumes kv_lens directly)
            attention_mask = _lens_to_additive_mask(
                seq_lens, x.shape[1])
            seq_lens = None
        if isinstance(self.encoder, ErnieScannedEncoder):
            if seq_lens is not None:
                raise ValueError(
                    "scan_layers encoder takes attention_mask, not "
                    "seq_lens (the scanned stack carries the additive "
                    "mask form)")
            x = self.encoder(x, attention_mask)
        else:
            for layer in self.encoder:
                x = layer(x, attention_mask, kv_lens=seq_lens)
        pooled = F.tanh(self.pooler(x[:, 0]))
        return x, pooled


class ErnieForPretraining(nn.Layer):
    """MLM + NSP heads (the pretraining objective of BASELINE config 3)."""

    def __init__(self, config: ErnieConfig = None, **kwargs):
        super().__init__()
        self.ernie = ErnieModel(config, **kwargs)
        self.moe_aux_loss = self.ernie.moe_aux_loss
        cfg = self.ernie.config
        self.config = cfg
        self.mlm_transform = nn.Linear(cfg.hidden_size, cfg.hidden_size)
        self.mlm_norm = nn.LayerNorm(cfg.hidden_size,
                                     epsilon=cfg.layer_norm_eps)
        self.mlm_bias = self.create_parameter(
            (cfg.vocab_size,), is_bias=True)
        self.mlm_bias.sharding_spec = P(TENSOR_AXIS)
        self.nsp = nn.Linear(cfg.hidden_size, 2)

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None, seq_lens=None):
        seq, pooled = self.ernie(input_ids, token_type_ids, position_ids,
                                 attention_mask, seq_lens=seq_lens)
        with _scope("mlm_head_ce"):
            h = self.mlm_norm(F.gelu(self.mlm_transform(seq)))
            if self.config.chunked_ce:
                # the head matmul moves INTO the loss
                # (chunked_pretraining_loss streams it through vocab
                # blocks); logits are never built
                return h, self.nsp(pooled)
            # weight-tied decoder: logits = h @ E^T  (vocab-sharded
            # matmul). Done in 2D [b*s, hidden] — a 3D dot here gives
            # the [b, s, V] logits a batch-major layout that XLA then
            # has to transpose-copy (a multi-GB move at vocab scale);
            # the flat matmul keeps the natural row-major layout and
            # reshape back is a free bitcast.
            b, s = h.shape[0], h.shape[1]
            w = self.ernie.embeddings.word_embeddings.weight
            h2 = h.reshape([-1, h.shape[-1]])
            lg = F.linear(h2, manipulation.t(w))
            # bias in the LOGITS dtype: under AMP O1 the f32 bias param
            # would promote the whole [b*s, vocab] tensor to f32 — the
            # exact multi-GB head buffer the fused-CE rework removed
            # (tests/test_head_hlo_receipt.py guards this)
            bias = self.mlm_bias if self.mlm_bias.dtype == lg.dtype \
                else self.mlm_bias.astype(lg.dtype)
            logits = (lg + bias).reshape([b, s, -1])
            nsp_logits = self.nsp(pooled)
            return logits, nsp_logits

    def chunked_pretraining_loss(self, outputs, mlm_labels,
                                 nsp_labels=None, ignore_index=-100):
        """Loss for chunked_ce=True models: outputs carry HIDDEN states
        (forward skipped the head matmul); the tied-decoder projection
        + CE stream through vocab blocks via F.linear_cross_entropy —
        no [b*s, vocab] logits ever exist. Bind as the TrainStep
        loss_fn: TrainStep(model, model.chunked_pretraining_loss, ...)
        — the tied weights are read inside the traced step, so their
        grads flow exactly like the dense path's."""
        h, nsp_logits = outputs
        with _scope("mlm_head_ce"):
            w_t = manipulation.t(
                self.ernie.embeddings.word_embeddings.weight)
            mlm = F.linear_cross_entropy(
                h.reshape([-1, h.shape[-1]]), w_t, self.mlm_bias,
                mlm_labels.reshape([-1]),
                vocab_block=min(self.config.ce_vocab_block,
                                self.config.vocab_size),
                ignore_index=ignore_index)
            if nsp_labels is None:
                return mlm
            nsp = F.cross_entropy(nsp_logits, nsp_labels.reshape([-1]))
            return mlm + nsp

    @staticmethod
    def pretraining_loss(outputs, mlm_labels, nsp_labels=None,
                         ignore_index=-100):
        # CE belongs to the head's scope: the fused softmax-CE over the
        # [b*s, vocab] logits IS the "+ce" half of mlm_head_ce (the
        # ~20%-of-FLOPs row the anatomy receipt pins)
        logits, nsp_logits = outputs
        with _scope("mlm_head_ce"):
            mlm = F.cross_entropy(
                logits.reshape([-1, logits.shape[-1]]),
                mlm_labels.reshape([-1]), ignore_index=ignore_index)
            if nsp_labels is None:
                return mlm
            nsp = F.cross_entropy(nsp_logits, nsp_labels.reshape([-1]))
            return mlm + nsp


class ErnieForSequenceClassification(nn.Layer):
    def __init__(self, config: ErnieConfig = None, num_classes=2, **kwargs):
        super().__init__()
        self.ernie = ErnieModel(config, **kwargs)
        cfg = self.ernie.config
        self.dropout = nn.Dropout(cfg.hidden_dropout_prob)
        self.classifier = nn.Linear(cfg.hidden_size, num_classes)

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None):
        _, pooled = self.ernie(input_ids, token_type_ids, position_ids,
                               attention_mask)
        return self.classifier(self.dropout(pooled))


# ---------------------------------------------------------------------------
# pipeline-parallel stage decomposition
# ---------------------------------------------------------------------------
# Reference: PipelineOptimizer splits the ERNIE program by device_guard
# (fluid/optimizer.py:3718) — embedding on the first device, lm head on
# the last. Here the split is explicit heterogeneous stage Layers driven
# by distributed.pipeline_engine.PipelineParallel. The MLM decoder weight
# is UNTIED from the word embedding across a pipeline split (tying would
# need a per-step tied-grad allreduce between first and last stage —
# Megatron's _allreduce_word_embedding_grads; the throughput cost on ICI
# buys nothing at pretraining loss parity, so we keep stages independent
# and document the decision here).

def _stage_blocks(config, num_blocks, first_index):
    """A pipeline stage's run of encoder blocks: a ScannedStack when
    config.scan_layers (compile O(1) in the stage's depth — the same
    win per stage as for the whole encoder), else an unrolled
    LayerList (required for interleaved MoE placement)."""
    if config.scan_layers and num_blocks > 0:
        # num_blocks == 0 (more stages than layers, or the solo-stage
        # split) stays an empty LayerList: the identity stage
        return ErnieScannedEncoder(config, num_blocks)
    return nn.LayerList(
        [ErnieLayer(config, use_moe=_is_moe_layer(config,
                                                  first_index + j))
         for j in range(num_blocks)])


def _run_blocks(blocks, x, attention_mask):
    if isinstance(blocks, nn.ScannedStack):
        return blocks(x, attention_mask)
    for b in blocks:
        x = b(x, attention_mask)
    return x


def _stage_moe_aux(blocks):
    """Weighted sum of the blocks' MoE aux losses from the last forward
    (None when the stage is dense) — the pipeline engine's
    pipeline_local_loss contract."""
    if isinstance(blocks, nn.ScannedStack):
        return None  # scan_layers excludes MoE by construction
    total = None
    for b in blocks:
        if getattr(b, "use_moe", False) and b.moe.aux_loss is not None:
            a = b.moe.aux_weight * b.moe.aux_loss
            total = a if total is None else total + a
    return total


class ErnieStageFirst(nn.Layer):
    """Embeddings + leading encoder blocks -> hidden states.

    With an attention_mask, the additive [b,1,1,s] form is built here
    once and threaded to later stages as part of the activation tuple
    (the same mask plumbing ErnieModel.forward does in one program)."""

    def __init__(self, config: ErnieConfig, num_blocks: int,
                 first_index: int = 0):
        super().__init__()
        self.embeddings = ErnieEmbeddings(config)
        self.blocks = _stage_blocks(config, num_blocks, first_index)

    def forward(self, input_ids, attention_mask=None):
        x = self.embeddings(input_ids)
        if attention_mask is not None:
            am = manipulation.unsqueeze(attention_mask, [1, 2])
            attention_mask = (1.0 - am.astype("float32")) * -1e9
        x = _run_blocks(self.blocks, x, attention_mask)
        if attention_mask is not None:
            return x, attention_mask
        return x

    def pipeline_local_loss(self):
        return _stage_moe_aux(self.blocks)


class ErnieStageMiddle(nn.Layer):
    """A run of encoder blocks (hidden -> hidden)."""

    def __init__(self, config: ErnieConfig, num_blocks: int,
                 first_index: int = 0):
        super().__init__()
        self.blocks = _stage_blocks(config, num_blocks, first_index)

    def forward(self, x, attention_mask=None):
        x = _run_blocks(self.blocks, x, attention_mask)
        if attention_mask is not None:
            return x, attention_mask
        return x

    def pipeline_local_loss(self):
        return _stage_moe_aux(self.blocks)


class ErnieStageLast(nn.Layer):
    """Trailing blocks + pooler + MLM/NSP heads (hidden -> logits)."""

    def __init__(self, config: ErnieConfig, num_blocks: int,
                 first_index: int = 0):
        super().__init__()
        self.blocks = _stage_blocks(config, num_blocks, first_index)
        self.pooler = nn.Linear(config.hidden_size, config.hidden_size)
        self.mlm_transform = nn.Linear(config.hidden_size,
                                       config.hidden_size)
        self.mlm_norm = nn.LayerNorm(config.hidden_size,
                                     epsilon=config.layer_norm_eps)
        self.decoder = nn.Linear(config.hidden_size, config.vocab_size)
        self.decoder.weight.sharding_spec = P(None, TENSOR_AXIS)
        self.nsp = nn.Linear(config.hidden_size, 2)

    def forward(self, x, attention_mask=None):
        x = _run_blocks(self.blocks, x, attention_mask)
        with _scope("mlm_head_ce"):
            pooled = F.tanh(self.pooler(x[:, 0]))
            h = self.mlm_norm(F.gelu(self.mlm_transform(x)))
            # 2D decoder matmul for the same layout reason as
            # ErnieForPretraining.forward (vocab-sized logits stay
            # row-major)
            b0, s0 = h.shape[0], h.shape[1]
            logits = self.decoder(h.reshape([-1, h.shape[-1]])).reshape(
                [b0, s0, -1])
            return logits, self.nsp(pooled)

    def pipeline_local_loss(self):
        return _stage_moe_aux(self.blocks)


def ernie_pipeline_stages(config: ErnieConfig, num_stages: int):
    """Split an ERNIE pretraining model into heterogeneous pp stages.

    Blocks are distributed as evenly as possible; stage 0 additionally
    carries the embeddings, the last stage the pooler + heads (the
    device_guard placement of the reference's pipeline ERNIE).
    """
    assert num_stages >= 1
    L = config.num_hidden_layers
    base, extra = divmod(L, num_stages)
    counts = [base + (1 if i < extra else 0) for i in range(num_stages)]
    if num_stages == 1:
        class _Solo(nn.Layer):
            def __init__(self):
                super().__init__()
                self.first = ErnieStageFirst(config, 0)
                self.last = ErnieStageLast(config, L, first_index=0)

            def forward(self, input_ids):
                return self.last(self.first(input_ids))

            def pipeline_local_loss(self):
                return self.last.pipeline_local_loss()
        return [_Solo()]
    stages = [ErnieStageFirst(config, counts[0])]
    start = counts[0]
    for i in range(1, num_stages - 1):
        stages.append(ErnieStageMiddle(config, counts[i],
                                       first_index=start))
        start += counts[i]
    stages.append(ErnieStageLast(config, counts[-1], first_index=start))
    return stages
