"""paddle.sysconfig — header/library install paths
(reference python/paddle/sysconfig.py:15). Points at the csrc tree
whose C API (paddle_tpu_capi.h) and shared objects back the native
runtime."""
import os

__all__ = ["get_include", "get_lib"]

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def get_include():
    """Directory containing the C/C++ headers (csrc/)."""
    return os.path.join(_ROOT, "csrc")


def get_lib():
    """Directory containing the built native libraries."""
    return os.path.join(_ROOT, "csrc", "build")
