"""Static-facade compatibility surface (reference python/paddle/static).

The rows here are the reference's executor/scope-era API
(`static/__init__.py` re-exports) that the TPU design subsumes with the
compiled Program/Executor: each entry is either a thin, REAL
implementation over the existing machinery (save/load state, py_func
via jax.pure_callback, Print via jax.debug.print, accuracy/auc
compositions, places) or a documented config shim whose job XLA owns
(BuildStrategy/ExecutionStrategy knobs, ParallelExecutor).
"""
from __future__ import annotations

import contextlib
import pickle
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..core import place as _place
from ..core.enforce import EnforceNotMet
from ..ops.registry import register_op
from .program import (Executor, Program, Var, default_main_program,
                      program_guard)

__all__ = [
    "global_scope", "scope_guard", "Scope", "BuildStrategy",
    "ExecutionStrategy", "CompiledProgram", "ParallelExecutor", "Print",
    "py_func", "name_scope", "WeightNormParamAttr", "save", "load",
    "save_vars", "load_vars", "load_program_state", "set_program_state",
    "cpu_places", "cuda_places", "xpu_places", "Variable", "accuracy",
    "auc",
]

Variable = Var  # reference fluid.framework.Variable alias


# ---------------------------------------------------------------- places

def cpu_places(device_count=None):
    """static.cpu_places parity: CPUPlace list (device_count or 1)."""
    n = device_count or 1
    return [_place.CPUPlace() for _ in range(n)]


def cuda_places(device_ids=None):
    """Reference cuda_places → the accelerator places of this host (on
    TPU every CUDAPlace request maps to the chip backend)."""
    ids = device_ids if device_ids is not None else range(
        max(1, len([d for d in jax.devices()
                    if d.platform != "cpu"]) or 1))
    return [_place.CUDAPlace(i) for i in ids]


def xpu_places(device_ids=None):
    ids = device_ids if device_ids is not None else [0]
    return [_place.XPUPlace(i) for i in ids]


# ----------------------------------------------------------------- scope

class Scope:
    """Name → persistable view (reference Scope:52). The TPU design has
    no scope hierarchy — programs are pure functions over explicit
    environments (DESIGN.md) — so a Scope resolves names across the
    Programs registered with it (every Program an Executor runs is
    attached to the global scope). find_var covers persistables
    (parameters/buffers), the dominant reference use (checkpoint IO)."""

    class _VarView:
        def __init__(self, tensor):
            self._t = tensor

        def get_tensor(self):
            return np.asarray(self._t._data)

        def set(self, value, place=None):
            self._t._data = jnp.asarray(np.asarray(value))

    def __init__(self):
        import weakref
        # weak refs: attaching a Program to the scope must not extend
        # its lifetime (a long-lived process building per-eval programs
        # would otherwise leak every parameter array forever)
        self._programs = weakref.WeakValueDictionary()
        self._order = 0

    def _attach(self, program: Program):
        for p in self._programs.values():
            if p is program:
                return
        self._programs[self._order] = program
        self._order += 1

    def find_var(self, name: str):
        for k in sorted(self._programs.keys(), reverse=True):
            prog = self._programs.get(k)
            if prog is None:
                continue
            for vid, t in prog.params.items():
                if prog.vars[vid].name == name or t.name == name:
                    return Scope._VarView(t)
        return None

    def var(self, name: str):
        return self.find_var(name)


_global_scope = Scope()
_scope_stack: List[Scope] = []


def global_scope() -> Scope:
    return _scope_stack[-1] if _scope_stack else _global_scope


@contextlib.contextmanager
def scope_guard(scope: Scope):
    _scope_stack.append(scope)
    try:
        yield
    finally:
        _scope_stack.pop()


_orig_exe_run = Executor.run


def _run_and_register(self, program=None, *args, **kwargs):
    prog = program if program is not None else default_main_program()
    if isinstance(prog, CompiledProgram):
        prog = prog._program
        program = prog
    if isinstance(prog, Program):
        global_scope()._attach(prog)
    return _orig_exe_run(self, program, *args, **kwargs)


Executor.run = _run_and_register


# ------------------------------------------------- executor-config shims

class BuildStrategy:
    """Reference BuildStrategy (details/build_strategy.h): every field is
    a graph-pass toggle (fusion, memory reuse, reduce strategy). Under
    XLA those passes are the compiler; the knobs are accepted and
    recorded so strategy-driven code runs unchanged, and have no effect
    by design."""

    class ReduceStrategy:
        AllReduce = 0
        Reduce = 1

    class GradientScaleStrategy:
        CoeffNumDevice = 0
        One = 1
        Customized = 2

    def __init__(self):
        self.reduce_strategy = BuildStrategy.ReduceStrategy.AllReduce
        self.gradient_scale_strategy = \
            BuildStrategy.GradientScaleStrategy.CoeffNumDevice
        self.fuse_elewise_add_act_ops = False
        self.fuse_bn_act_ops = False
        self.fuse_all_reduce_ops = False
        self.enable_inplace = True
        self.memory_optimize = True
        self.build_cse = False
        self.debug_graphviz_path = ""


class ExecutionStrategy:
    """Reference ExecutionStrategy: thread counts / drop-scope cadence
    for the SSA executors. One jitted program has no op threads or
    local scopes; accepted for parity."""

    def __init__(self):
        self.num_threads = 1
        self.num_iteration_per_drop_scope = 100
        self.num_iteration_per_run = 1


class CompiledProgram:
    """Reference CompiledProgram/with_data_parallel: multi-device SSA
    graphs. Here compilation IS Executor.run's jit cache, and data
    parallelism is a ShardingPlan over a mesh — this wrapper carries the
    strategy objects and unwraps at run."""

    def __init__(self, program, build_strategy: Optional[BuildStrategy]
                 = None):
        self._program = program
        self._build_strategy = build_strategy or BuildStrategy()
        self._places = None
        self._loss_name = None

    def with_data_parallel(self, loss_name=None, build_strategy=None,
                           exec_strategy=None, places=None):
        self._loss_name = loss_name
        if build_strategy is not None:
            self._build_strategy = build_strategy
        self._places = places
        return self


class ParallelExecutor:
    """Legacy ParallelExecutor facade (reference
    framework/parallel_executor.cc): delegates to the Executor; the
    multi-device SSA replication it existed for is the SPMD
    partitioner's job (DESIGN.md Parallelism)."""

    def __init__(self, use_cuda=False, loss_name=None, main_program=None,
                 share_vars_from=None, exec_strategy=None,
                 build_strategy=None, num_trainers=1, trainer_id=0,
                 scope=None):
        self._program = main_program or default_main_program()
        self._exe = Executor()
        self._loss_name = loss_name

    def run(self, fetch_list, feed=None, feed_dict=None,
            return_numpy=True):
        feed = feed if feed is not None else feed_dict
        prog = self._program
        resolved = []
        for f in fetch_list:
            if isinstance(f, str):
                resolved.append(prog.vars[prog.var_names[f]])
            else:
                resolved.append(f)
        return self._exe.run(prog, feed=feed, fetch_list=resolved,
                             return_numpy=return_numpy)


# --------------------------------------------------------- runtime ops

def Print(input, first_n=-1, message="", summarize=20,
          print_tensor_name=True, print_tensor_type=True,
          print_tensor_shape=True, print_tensor_lod=False,
          print_phase="both"):
    """Reference controlflow/print_op facade over the already-registered
    'print' op (ops/misc_ops.py — brace-safe jax.debug.print with
    first_n/summarize handling)."""
    from ..ops.misc_ops import print_op
    return print_op(input, message=message or "print:",
                    first_n=first_n, summarize=summarize)


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    """Reference static.py_func: run arbitrary python inside the graph.

    TPU-native form: jax.pure_callback — the callback executes host-side
    at run time, inside jit. `out` declares result shape/dtype (a Var
    template from create_var, a (shape, dtype) tuple, or a list of
    either). backward_func, when given, defines the VJP the same way.
    """
    xs = x if isinstance(x, (list, tuple)) else [x]

    def _is_pair(o):
        # a single-output declaration: (shape, dtype)
        if not (isinstance(o, tuple) and len(o) == 2):
            return False
        try:
            np.dtype(o[1])
            return True
        except TypeError:
            return False

    # multi-output: a list, or a tuple of Vars/pairs — a bare
    # (shape, dtype) pair declares ONE output
    multi_out = isinstance(out, list) or (
        isinstance(out, tuple) and not _is_pair(out)
        and len(out) > 0 and isinstance(out[0], (Var, tuple, list)))
    outs = list(out) if multi_out else [out]

    def spec_of(o):
        if isinstance(o, Var):
            return jax.ShapeDtypeStruct(tuple(o._data.shape),
                                        o._data.dtype)
        shape, dtype = o
        return jax.ShapeDtypeStruct(tuple(shape), jnp.dtype(dtype))

    specs = [spec_of(o) for o in outs]
    single = not multi_out

    def call(*arrays):
        res = func(*[np.asarray(a) for a in arrays])
        res = res if isinstance(res, (list, tuple)) else [res]
        return [np.asarray(r, s.dtype).reshape(s.shape)
                for r, s in zip(res, specs)]

    name = getattr(func, "__name__", "py_func")

    # op_wrapper: one-off eager/captured op, NOT added to the global
    # registry (a per-call registration would leak one entry per
    # py_func call site for the process lifetime)
    from ..ops.registry import op_wrapper

    def _impl(*arrays):
        res = jax.pure_callback(
            call, specs if not single else specs[:1], *arrays,
            vmap_method="sequential")
        return res[0] if single else tuple(res)

    impl = op_wrapper(_impl, name=f"py_func_{name}")

    if backward_func is not None:
        fwd_plain = impl.__pure_fn__

        @jax.custom_vjp
        def with_grad(*arrays):
            return fwd_plain(*arrays)

        def fwd(*arrays):
            return fwd_plain(*arrays), arrays

        def bwd(res_args, g):
            gs = g if isinstance(g, (list, tuple)) else [g]
            in_specs = [jax.ShapeDtypeStruct(np.shape(a), a.dtype)
                        for a in res_args]

            def bcall(*vals):
                n = len(res_args)
                r = backward_func(*[np.asarray(v) for v in vals])
                r = r if isinstance(r, (list, tuple)) else [r]
                return [np.asarray(v, s.dtype).reshape(s.shape)
                        for v, s in zip(r, in_specs)]

            outs_b = jax.pure_callback(bcall, in_specs,
                                       *(list(res_args) + list(gs)),
                                       vmap_method="sequential")
            return tuple(outs_b)

        with_grad.defvjp(fwd, bwd)
        from ..ops.registry import op_wrapper
        return op_wrapper(with_grad, name=f"py_func_{name}")(*xs)
    return impl(*xs)


def register_op_once(name):
    """register_op that tolerates re-registration (used by the static
    metric helpers below, which register a FIXED set of names)."""
    def deco(fn):
        from ..ops import registry as _r
        if name in _r.OPS:
            del _r.OPS[name]
        return register_op(name)(fn)
    return deco


@contextlib.contextmanager
def name_scope(prefix):
    """Reference static.name_scope: prefixes generated op/var names (a
    debugging aid). Var names here come from the unique-name generator;
    the prefix is pushed onto it for the scope's duration."""
    from ..utils import unique_name as _un
    if hasattr(_un, "guard_prefix"):
        with _un.guard_prefix(prefix):
            yield
    else:  # no generator hook: parity no-op
        yield


class WeightNormParamAttr:
    """Reference WeightNormParamAttr (fluid/param_attr.py): requests the
    weight-norm reparameterization for a parameter. The eager-world
    equivalent here is nn.utils.weight_norm(layer, dim=dim) applied
    after construction; this class carries the config so reference code
    parses, and Layers that accept a ParamAttr treat it as a plain
    attr. dim/name/initializer are preserved."""

    def __init__(self, dim=None, name=None, initializer=None,
                 learning_rate=1.0, regularizer=None, trainable=True,
                 do_model_average=False, need_clip=True):
        self.dim = dim
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable


# ---------------------------------------------------- state save / load

def load_program_state(path_prefix: str):
    """name -> ndarray dict from static.save output."""
    path = path_prefix + ".pdparams" if not path_prefix.endswith(
        ".pdparams") else path_prefix
    with open(path, "rb") as f:
        return pickle.load(f)


def set_program_state(program: Program, state: Dict[str, np.ndarray]):
    for vid, t in program.params.items():
        nm = program.vars[vid].name or t.name
        if nm in state:
            t._data = jnp.asarray(np.asarray(state[nm]))


def save(program: Program, path_prefix: str):
    """static.save parity: persistables -> <prefix>.pdparams (pickled
    name->ndarray dict)."""
    state = {}
    for vid, t in program.params.items():
        nm = program.vars[vid].name or t.name or f"param_{vid}"
        state[nm] = np.asarray(t._data)
    with open(path_prefix + ".pdparams", "wb") as f:
        pickle.dump(state, f, protocol=4)


def load(program: Program, path_prefix: str, executor=None,
         var_list=None):
    set_program_state(program, load_program_state(path_prefix))


def save_vars(executor, dirname, main_program=None, vars=None,
              predicate=None, filename=None):
    prog = main_program or default_main_program()
    import os
    os.makedirs(dirname, exist_ok=True)
    save(prog, os.path.join(dirname, filename or "params"))


def load_vars(executor, dirname, main_program=None, vars=None,
              predicate=None, filename=None):
    import os
    prog = main_program or default_main_program()
    load(prog, os.path.join(dirname, filename or "params"))


# ------------------------------------------------------- static metrics

def accuracy(input, label, k=1, correct=None, total=None):
    """Reference metrics/accuracy_op: top-k accuracy of `input` against
    integer `label` [N] or [N, 1] — delegates to the existing
    paddle_tpu.metric.accuracy functional (one implementation)."""
    from ..metric import accuracy as _metric_accuracy
    return _metric_accuracy(input, label, k=k, correct=correct,
                            total=total)


def auc(input, label, curve="ROC", num_thresholds=4095, topk=1,
        slide_steps=1):
    """Reference metrics/auc_op, stateless batch form: histogram the
    positive-class scores into num_thresholds buckets and run the
    trapezoidal sweep (the fleet metric helper applies the same formula
    across workers)."""

    @register_op_once("auc_static")
    def _auc(x, lbl, num_thresholds=4095):
        scores = x[:, 1] if x.ndim == 2 and x.shape[1] >= 2 else \
            x.reshape(-1)
        lb = lbl.reshape(-1).astype(jnp.float32)
        idx = jnp.clip((scores * num_thresholds).astype(jnp.int32), 0,
                       num_thresholds)
        pos = jnp.zeros(num_thresholds + 1).at[idx].add(lb)
        neg = jnp.zeros(num_thresholds + 1).at[idx].add(1.0 - lb)
        tot_pos = jnp.cumsum(pos[::-1])
        tot_neg = jnp.cumsum(neg[::-1])
        new_neg = tot_neg
        prev_neg = jnp.concatenate([jnp.zeros(1), tot_neg[:-1]])
        prev_pos = jnp.concatenate([jnp.zeros(1), tot_pos[:-1]])
        area = jnp.sum((new_neg - prev_neg) * (prev_pos + tot_pos) / 2.0)
        denom = jnp.maximum(tot_pos[-1] * tot_neg[-1], 1e-12)
        return area / denom

    return _auc(input, label, num_thresholds=num_thresholds)
