"""static.io: save/load_inference_model (reference python/paddle/static/io.py
save_inference_model/load_inference_model, fluid/io.py save_persistables).

The saved artifact is {prefix}.pdmodel (serialized jax.export program,
portable StableHLO compiled for cpu+tpu), {prefix}.pdiparams (weights),
{prefix}.pdmeta.json (feed/fetch names) — the ProgramDesc+params pair of
the reference, but compiler-native.
"""
from __future__ import annotations

import json
import os
import pickle
from typing import List, Optional, Sequence

import numpy as np

__all__ = ["save_inference_model", "load_inference_model",
           "serialize_program", "deserialize_program"]


def save_inference_model(path_prefix: str, feed_vars=None, fetch_vars=None,
                         executor=None, layer=None, input_spec=None,
                         feed_names: Optional[Sequence[str]] = None,
                         fetch_names: Optional[Sequence[str]] = None,
                         **kwargs):
    """Export a Layer's eval forward as a deployable artifact.

    Dygraph-style usage (the TPU-native path):
        save_inference_model(prefix, layer=model, input_spec=[InputSpec...])
    The reference's (feed_vars, fetch_vars, executor) static signature is
    accepted for parity: feed_vars may be the layer and fetch_vars the
    input_spec list when called positionally from 2.0-style code.
    """
    from ..jit.api import InputSpec, export_forward
    from ..nn.layer.layers import Layer

    # tolerate the 2.0 positional style: (prefix, layer, input_spec)
    if layer is None and isinstance(feed_vars, Layer):
        layer = feed_vars
        if input_spec is None and fetch_vars is not None:
            input_spec = fetch_vars
    if layer is None or input_spec is None:
        raise ValueError(
            "save_inference_model needs layer= and input_spec= (or the "
            "positional (path, layer, input_spec) form)")

    os.makedirs(os.path.dirname(path_prefix) or ".", exist_ok=True)
    exported = export_forward(layer, input_spec)
    with open(path_prefix + ".pdmodel", "wb") as f:
        f.write(exported.serialize())
    state = {k: np.asarray(v._data) for k, v in layer.state_dict().items()}
    meta = {"class": type(layer).__name__,
            "input_spec": [{"shape": list(s.shape),
                            "dtype": str(np.dtype(s.dtype))}
                           for s in input_spec]}
    with open(path_prefix + ".pdiparams", "wb") as f:
        pickle.dump({"state": state, "meta": meta}, f)
    feed_names = list(feed_names) if feed_names else [
        getattr(s, "name", None) or f"x{i}"
        for i, s in enumerate(input_spec)]
    fetch_names = list(fetch_names) if fetch_names else [
        f"out{i}" for i in range(len(exported.out_avals))]
    with open(path_prefix + ".pdmeta.json", "w") as f:
        json.dump({"feed_names": feed_names, "fetch_names": fetch_names},
                  f)


def load_inference_model(path_prefix: str, executor=None, **kwargs):
    """Returns (predictor, feed_names, fetch_names) — the reference returns
    (program, feed_names, fetch_targets) to pass to Executor.run; here the
    program IS executable (an AOT-compiled Predictor), call
    predictor.run([arrays]) directly."""
    from ..inference import Config, create_predictor
    pred = create_predictor(Config(path_prefix))
    return pred, pred.get_input_names(), pred.get_output_names()


def serialize_program(layer, input_spec) -> bytes:
    """Serialized portable program bytes (ref static/io.py
    serialize_program)."""
    from ..jit.api import export_forward
    return export_forward(layer, input_spec).serialize()


def deserialize_program(data: bytes):
    from jax import export as jax_export
    return jax_export.deserialize(data)
