"""paddle.static.nn namespace (control flow + layer functionals)."""
from ..ops.control_flow import (case, cond, fori_loop, scan, switch_case,
                                while_loop)  # noqa: F401
from ..nn.functional import *  # noqa: F401,F403
