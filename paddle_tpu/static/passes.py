"""Program-IR pass framework (reference framework/ir/pass.h:43 +
~88 passes; the pass CONCEPTS are reused, not the implementations).

Design stance recorded in DESIGN.md: XLA subsumes the reference's
fusion/layout/memory passes (fc_fuse, conv_bn_fuse, inplace/memory
reuse, stream analysis — a hand fusion pass on this IR would fight the
compiler). What a TPU-native Program IR still legitimately wants are
the passes that shrink or canonicalize the TRACED graph before it is
jitted — they cut retrace/compile time and serialized-program size,
which XLA cannot do because they happen before XLA sees the module:

- constant_folding_pass: ops whose every input slot is a captured
  literal run ONCE at pass time; consumers read the folded literal
  (reference ir/constant_folding equivalent at the Program level).
- cse_pass: structurally identical ops (same type/inputs/attrs) are
  deduplicated; later consumers rewire to the first occurrence.
- identity_elimination_pass: identity scale/cast/reshape/dropout-eval
  ops drop out; consumers rewire to the op's input.
- dead_code_elimination_pass(targets): backward slice to the ops the
  targets need (framework/prune.cc semantics, in-place form of
  Program.prune).

Passes register in PASS_REGISTRY (REGISTER_PASS analogue) and run via
apply_pass(program, name) or a PassBuilder pipeline
(details/build_strategy.h pass-builder analogue). Every pass returns a
NEW Program; the input is never mutated.
"""
from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence

import jax
import numpy as np

__all__ = ["Pass", "PASS_REGISTRY", "register_pass", "apply_pass",
           "PassBuilder"]

PASS_REGISTRY: Dict[str, Callable] = {}


def register_pass(name: str):
    def deco(fn):
        PASS_REGISTRY[name] = fn
        fn.pass_name = name
        return fn
    return deco


def apply_pass(program, names, **kwargs):
    """Run one pass (str) or a sequence of passes over `program`;
    returns the transformed clone (ir.apply_pass analogue)."""
    if isinstance(names, str):
        names = [names]
    p = program
    for n in names:
        if n not in PASS_REGISTRY:
            raise KeyError(
                f"unknown pass '{n}' (registered: "
                f"{sorted(PASS_REGISTRY)})")
        p = PASS_REGISTRY[n](p, **kwargs)
    return p


class Pass:
    """Subclassable form (reference ir::Pass): set name, override
    apply(program) -> program. Instantiating registers it."""

    name: str = ""

    def __init_subclass__(cls, **kw):
        super().__init_subclass__(**kw)
        if cls.name:
            PASS_REGISTRY[cls.name] = lambda prog, _c=cls, **k: \
                _c().apply(prog, **k)

    def apply(self, program, **kwargs):
        raise NotImplementedError


class PassBuilder:
    """Ordered pass pipeline (details/build_strategy pass-builder
    analogue): append/insert/remove passes, then apply_all."""

    def __init__(self, passes: Optional[Sequence[str]] = None):
        self._passes: List[str] = list(passes or [])

    def append_pass(self, name: str):
        if name not in PASS_REGISTRY:
            raise KeyError(f"unknown pass '{name}'")
        self._passes.append(name)
        return self

    def insert_pass(self, idx: int, name: str):
        if name not in PASS_REGISTRY:
            raise KeyError(f"unknown pass '{name}'")
        self._passes.insert(idx, name)
        return self

    def remove_pass(self, name: str):
        self._passes = [p for p in self._passes if p != name]
        return self

    def all_passes(self) -> List[str]:
        return list(self._passes)

    def apply_all(self, program, **kwargs):
        return apply_pass(program, self._passes, **kwargs)


# -------------------------------------------------------------------------
# helpers
# -------------------------------------------------------------------------

def _is_prng_key(c):
    try:
        return hasattr(c, "dtype") and jax.dtypes.issubdtype(
            c.dtype, jax.dtypes.prng_key)
    except Exception:
        return False


def _target_ids(prog, targets) -> set:
    """Resolve Var objects / names to var ids. Pass `targets=` to any
    eliminating pass to keep intermediates you intend to FETCH later —
    an eliminated var's fetch fails loudly in the Executor (computable
    check), never silently."""
    out = set()
    for t in (targets or ()):
        out.add(t.var_id if hasattr(t, "var_id")
                else prog.var_by_name(t).var_id)
    return out


def _protected_ids(prog) -> set:
    """Var ids a pass must keep producible: buffer write-backs, grad
    bookkeeping, optimizer loss."""
    keep = {v for _, v in getattr(prog, "_buffer_writes", ())}
    keep |= {b for b, _ in getattr(prog, "_buffer_writes", ())}
    gt = getattr(prog, "_grad_target", None)
    if gt is not None:
        keep.add(gt)
    for _, gv in getattr(prog, "_grad_pairs", ()):
        keep.add(gv.var_id)
    for s in getattr(prog, "_var_grads", ()):
        keep.update(s.get("targets", ()))
        keep.update(s.get("inputs", ()))
        keep.update(s.get("grad_vars", ()))
    if prog._optimize is not None:
        keep.add(prog._optimize[1].var_id)
    return keep


def _rewire(ops, mapping: Dict[int, int]):
    """Replace consumed var ids per `mapping` in every op's in_ids."""
    for node in ops:
        node.in_ids = [mapping.get(i, i) if i is not None else None
                       for i in node.in_ids]


def _rewire_const(ops, folded: Dict[int, object]):
    """Turn consumed var ids in `folded` into literal const slots."""
    for node in ops:
        for k, i in enumerate(node.in_ids):
            if i is not None and i in folded:
                node.in_ids[k] = None
                node.const_args[k] = folded[i]


def _const_digest(c):
    if _is_prng_key(c):
        return ("<key>",)
    if hasattr(c, "shape") and hasattr(c, "dtype"):
        arr = np.asarray(c)
        if arr.size > 4096:   # don't hash big captured tensors
            return ("<big>", id(c))
        return ("arr", str(arr.dtype), arr.shape, arr.tobytes())
    if isinstance(c, (list, tuple)):
        return (type(c).__name__,) + tuple(_const_digest(x) for x in c)
    try:
        hash(c)
        # tag the python type: 2, 2.0 and True hash equal but bake
        # different dtype promotions (same hazard registry._hashable
        # guards against in the eager jit cache)
        return ("lit", type(c).__name__, c)
    except TypeError:
        return ("<unhash>", id(c))


# -------------------------------------------------------------------------
# the passes
# -------------------------------------------------------------------------

# ops whose replay draws fresh rng or mutates state — never folded/CSE'd
_IMPURE = {"dropout_op", "dropout_nd", "alpha_dropout", "sdpa_dropout",
           "flash_attention_dropout", "uniform_random",
           "gaussian_random", "randint", "bernoulli", "multinomial",
           "randperm", "batch_norm_op"}


def _impure(node):
    return (node.op_type in _IMPURE
            or any(_is_prng_key(c) for c in node.const_args))


@register_pass("constant_folding_pass")
def constant_folding_pass(prog, freeze_buffers=False, targets=None, **_):
    """Evaluate ops whose every input is a compile-time constant once
    at pass time; consumers get the result as a literal slot.

    Constants are literal const slots (python scalars / numpy arrays
    passed positionally). With freeze_buffers=True — the reference's
    fold-for-INFERENCE scenario — captured stop_gradient buffers that
    the program never writes back are treated as constants too and get
    BAKED IN: later mutation of the live buffer tensor no longer
    affects the folded program (same contract as the quant freeze
    pass). Never use freeze_buffers on a training program."""
    p = prog.clone()
    folded: Dict[int, object] = {}
    if freeze_buffers:
        written = {b for b, _ in getattr(p, "_buffer_writes", ())}
        for vid in p.buffer_ids:
            if vid not in written and vid in p.params:
                folded[vid] = p.params[vid]._data
    kept = []
    protected = _protected_ids(p) | _target_ids(p, targets)
    for node in p.ops:
        _rewire_const([node], folded)
        can = (not _impure(node)
               and all(i is None for i in node.in_ids)
               and not any(o in protected for o in node.out_ids))
        if not can:
            kept.append(node)
            continue
        res = node.fn(*node.const_args, **node.kwargs)
        res = tuple(res) if isinstance(res, (list, tuple)) else (res,)
        for vid, r in zip(node.out_ids, res):
            folded[vid] = r
            # NOTE: Var objects are SHARED with the input program
            # (clone() is shallow over vars) — never write folded
            # values onto them; the fold lives only in const slots
    p.ops = kept
    return p


@register_pass("cse_pass")
def cse_pass(prog, targets=None, **_):
    """Common-subexpression elimination: later ops structurally equal
    to an earlier one are dropped; consumers rewire to the first.
    Protected vars (buffer writes, grad bookkeeping, optimizer loss)
    and explicit `targets` keep their producing op."""
    p = prog.clone()
    protected = _protected_ids(p) | _target_ids(p, targets)
    seen: Dict[tuple, List[int]] = {}
    mapping: Dict[int, int] = {}
    kept = []
    for node in p.ops:
        _rewire([node], mapping)
        if _impure(node) or any(o in protected for o in node.out_ids):
            kept.append(node)
            continue
        key = (node.op_type, tuple(node.in_ids),
               tuple(_const_digest(c) for c in node.const_args),
               tuple(sorted((k, _const_digest(v))
                            for k, v in node.kwargs.items())))
        prev = seen.get(key)
        if prev is None:
            seen[key] = node.out_ids
            kept.append(node)
        else:
            for old, new in zip(node.out_ids, prev):
                mapping[old] = new
    p.ops = kept
    return p


# identity detectors: op_type -> fn(node, prog) -> input slot to
# forward, or None when not an identity
_UNKNOWN = object()  # runtime-tensor attr slot: value not known statically


def _ident_scale(node, prog):
    kw = node.kwargs
    cargs = node.const_args

    def attr(name, pos, default):
        if name in kw:
            return kw[name]
        if len(node.in_ids) > pos and node.in_ids[pos] is not None:
            return _UNKNOWN       # traced var: can't prove identity
        if len(cargs) > pos and cargs[pos] is not None:
            return cargs[pos]
        return default
    scale = attr("scale", 1, 1.0)
    bias = attr("bias", 2, 0.0)
    if scale == 1.0 and bias == 0.0 and node.in_ids[0] is not None:
        return 0
    return None


def _ident_dropout_eval(node, prog):
    """dropout_eval is identity unless downscale_in_infer scales by
    (1-p) (nn/functional/common.py _dropout_eval)."""
    if node.in_ids[0] is None:
        return None
    mode = node.kwargs.get("mode", "upscale_in_train")
    p = node.kwargs.get("p", 0.5)
    if mode == "downscale_in_infer" and p != 0.0:
        return None
    return 0


def _ident_cast(node, prog):
    vid = node.in_ids[0]
    if vid is None:
        return None
    src = prog.vars.get(vid)
    out = prog.vars.get(node.out_ids[0])
    if src is not None and out is not None and \
            str(src.dtype) == str(out.dtype):
        return 0
    return None


def _ident_reshape(node, prog):
    vid = node.in_ids[0]
    if vid is None:
        return None
    src = prog.vars.get(vid)
    out = prog.vars.get(node.out_ids[0])
    if src is not None and out is not None and \
            tuple(src.shape) == tuple(out.shape):
        return 0
    return None


_IDENTITY = {"scale": _ident_scale, "cast": _ident_cast,
             "reshape": _ident_reshape,
             "dropout_eval": _ident_dropout_eval}


@register_pass("identity_elimination_pass")
def identity_elimination_pass(prog, targets=None, **_):
    """Drop no-op scale(1,0)/cast-to-same/reshape-to-same ops and
    rewire consumers to the input."""
    p = prog.clone()
    mapping: Dict[int, int] = {}
    protected = _protected_ids(p) | _target_ids(p, targets)
    kept = []
    for node in p.ops:
        _rewire([node], mapping)
        det = _IDENTITY.get(node.op_type)
        slot = det(node, p) if det else None
        if slot is None or node.out_ids[0] in protected:
            kept.append(node)
            continue
        mapping[node.out_ids[0]] = node.in_ids[slot]
    p.ops = kept
    return p


@register_pass("quantization_transform_pass")
def quantization_transform_pass(prog, weight_bits=8, activation_bits=8,
                                quantizable_op_type=None, **_):
    """Adapter: the quant QAT rewrite (quant/__init__.py
    QuantizationTransformPass) through the unified pass registry."""
    from ..quant import QuantizationTransformPass
    p = prog.clone()
    QuantizationTransformPass(
        weight_bits=weight_bits, activation_bits=activation_bits,
        quantizable_op_type=quantizable_op_type).apply(p)
    return p


@register_pass("quantization_freeze_pass")
def quantization_freeze_pass(prog, weight_bits=8, **_):
    """Adapter: int8 inference freeze (quant/__init__.py
    QuantizationFreezePass) through the unified pass registry."""
    from ..quant import QuantizationFreezePass
    p = prog.clone()
    QuantizationFreezePass(weight_bits=weight_bits).apply(p)
    return p


@register_pass("dead_code_elimination_pass")
def dead_code_elimination_pass(prog, targets=None, **_):
    """Backward slice: keep only ops needed for `targets` (+ protected
    state: buffer writes, grad bookkeeping, optimizer loss). With no
    targets, keeps ops reachable from protected state only —
    equivalent to pruning pure dead tails. Shares Program.prune's
    liveness algorithm (program.py backward_slice)."""
    from .program import backward_slice
    p = prog.clone()
    needed = _protected_ids(p) | _target_ids(p, targets)
    if not needed:
        return p
    p.ops, _ = backward_slice(p.ops, needed)
    return p
