"""paddle_tpu.static: compiled-execution facade.

TrainStep (whole-step compilation) is the workhorse; the Program/Executor
feed-fetch surface (reference python/paddle/static) is layered on top in
program.py.
"""
from ..jit.api import InputSpec  # noqa: F401
from .train_step import TrainStep  # noqa: F401
from .program import (Program, program_guard, default_main_program,
                      default_startup_program, data, Executor,
                      append_backward, gradients)  # noqa: F401
from .passes import (Pass, PassBuilder, apply_pass,  # noqa: F401
                     PASS_REGISTRY, register_pass)
from . import nn  # noqa: F401
from . import io  # noqa: F401
from .io import (save_inference_model, load_inference_model,  # noqa: F401
                 serialize_program, deserialize_program)
from .compat import (global_scope, scope_guard, Scope,  # noqa: F401
                     BuildStrategy, ExecutionStrategy, CompiledProgram,
                     ParallelExecutor, Print, py_func, name_scope,
                     WeightNormParamAttr, save, load, save_vars,
                     load_vars, load_program_state, set_program_state,
                     cpu_places, cuda_places, xpu_places, Variable,
                     accuracy, auc)


def _enable_static_mode():
    from . import program
    program._static_mode = True


def nn_placeholder(*a, **k):
    return data(*a, **k)
