"""TrainStep: whole-step compilation (the TPU performance path).

The reference runs training as a per-op interpreter loop
(executor.cc:461 / dygraph tracer) — on TPU that would leave the MXU idle
between dispatches. Here the entire step (forward + loss + backward +
optimizer update + LR schedule + loss scaling) compiles to ONE XLA
executable via jax.jit, with parameters/optimizer state as donated pytree
inputs so updates happen in-place in HBM.

Sharding: pass a Mesh + a ShardingPlan (paddle_tpu.distributed) and every
pytree leaf gets a NamedSharding — XLA inserts the collectives (DP grad
all-reduce ≡ reference's c_allreduce_sum graph rewrite, ZeRO state
sharding ≡ sharding_optimizer.py — but as compiler-placed reduce-scatter/
all-gather over ICI instead of graph surgery).
"""
from __future__ import annotations

from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core.generator import key_scope, next_key
from ..framework import Tensor, no_grad
from ..jit.api import _unwrap_tree, _wrap_tree
from ..nn.layer.layers import Layer
from ..observability import flight_recorder as _fr
from ..observability import memory as _mem
from ..observability import metrics as _obs
from ..observability.anatomy import scope as _scope
from ..observability.sentinel import RecompileSentinel, signature_of
from ..optimizer.optimizer import Optimizer
from ..optimizer.lr import LRScheduler

__all__ = ["TrainStep"]


def _microslice(a, idx, accum):
    """Slice microbatch idx of `accum` along the batch dim."""
    if jnp.ndim(a) == 0:
        return a
    micro = a.shape[0] // accum
    return jax.lax.dynamic_slice_in_dim(a, idx * micro, micro, axis=0)


class TrainStep:
    """Compiled training step.

    loss_fn(outputs, *labels) -> scalar Tensor, written in paddle ops.
    Usage:
        step = TrainStep(model, loss_fn, optimizer)
        loss = step(inputs, labels)   # one fused XLA step
    """

    def __init__(self, layer: Layer, loss_fn: Callable,
                 optimizer: Optimizer, amp_level: Optional[str] = None,
                 amp_dtype="bfloat16", mesh=None, sharding_plan=None,
                 donate: bool = True, grad_accum_steps: int = 1,
                 grad_transform: Optional[Callable] = None,
                 strategy_state: Optional[Dict[str, Any]] = None,
                 remat: bool = False, remat_policy=None, scaler=None,
                 sentry=None):
        self.layer = layer
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        self.amp_level = amp_level
        self.amp_dtype = amp_dtype
        # In-graph dynamic loss scaling (reference
        # operators/amp/{check_finite_and_unscale,update_loss_scaling}
        # ops): pass an amp.GradScaler/AmpScaler and its state lives in
        # strategy_state as traced scalars — scale/unscale, the finite
        # check, the skip-step select, and the scale update all compile
        # into the step; no host sync (unlike GradScaler.step eager-side).
        self._scaler_cfg = None
        if scaler is not None and getattr(scaler, "_enable", True):
            self._scaler_cfg = {
                "init_scale": float(scaler._scale),
                "incr_ratio": float(scaler._incr_ratio),
                "decr_ratio": float(scaler._decr_ratio),
                "incr_every_n": int(scaler._incr_every_n),
                "decr_every_n": int(scaler._decr_every_n),
                "dynamic": bool(scaler._dynamic),
            }
        self.mesh = mesh
        self.sharding_plan = sharding_plan
        self.grad_accum_steps = grad_accum_steps
        # fleet meta-optimizer hooks: grad_transform(grads, strat_state,
        # params) -> (grads, strat_state) runs between backward and the
        # optimizer update (DGC / fp16-allreduce analogues); remat wraps
        # the forward in jax.checkpoint (recompute_optimizer.py analogue).
        self.grad_transform = grad_transform
        self.strategy_state = strategy_state if strategy_state is not None \
            else {}
        # numeric-integrity sentry (observability.sentry.NumericSentry):
        # per-scope grad/param stats + the every-K fingerprint probe
        # compile INTO the one step program as scalar outputs; the
        # host-side monitor turns them into sentry.* gauges and
        # flight-recorder anomaly events. None = the program is
        # bit-identical to a sentry-less build (gate-down guard).
        self.sentry = sentry
        if sentry is not None:
            sentry.init_state(self.strategy_state)
        self.remat = remat
        self.remat_policy = remat_policy

        state = layer.state_dict()
        self._trainable_names = [k for k, t in state.items()
                                 if not t.stop_gradient]
        self._buffer_names = [k for k, t in state.items() if t.stop_gradient]
        self.params = {k: state[k]._data for k in self._trainable_names}
        self.buffers = {k: state[k]._data for k in self._buffer_names}
        # name -> live Tensor, so every step can re-point the Layer's
        # tensors at the freshly-returned arrays (zero-copy pointer
        # swap). Without this, the donated step deletes the arrays the
        # Layer still references and any later eager use of the model
        # (predict after training — ordinary dygraph flow) dies with
        # "Array has been deleted".
        self._state_tensors = dict(state)
        # abstract (meta-init) layer: params are ShapeDtypeStructs — the
        # step can only be AOT-lowered (aot_lower), never executed;
        # optimizer state stays abstract via eval_shape
        self._abstract = any(
            isinstance(v, jax.ShapeDtypeStruct)
            for v in self.params.values())
        if self._abstract:
            if amp_level == "O2":
                dt = jnp.dtype(amp_dtype)
                self.params = {
                    k: (jax.ShapeDtypeStruct(v.shape, dt)
                        if jnp.issubdtype(v.dtype, jnp.floating) else v)
                    for k, v in self.params.items()}
                if not optimizer._multi_precision:
                    optimizer._multi_precision = True
            self.opt_state = jax.eval_shape(optimizer.init_state_tree,
                                            self.params)
        elif amp_level == "O2":
            # pure-low-precision mode (reference amp O2 / pure_fp16):
            # params themselves are cast down; the optimizer keeps fp32
            # masters (multi_precision is mandatory for fp16 training)
            dt = jnp.dtype(amp_dtype)
            orig = dict(self.params)
            self.params = {
                k: v.astype(dt) if jnp.issubdtype(v.dtype, jnp.floating)
                else v
                for k, v in self.params.items()}
            if not optimizer._multi_precision:
                optimizer._multi_precision = True
            self.opt_state = optimizer.init_state_tree(self.params)
            # masters must come from the ORIGINAL fp32 values, not the
            # cast-down params (adam.py multi_precision keeps full
            # precision; round-tripping through fp16 would quantize
            # every weight at init)
            for k, st in self.opt_state.items():
                if isinstance(st, dict) and "master_weight" in st:
                    st["master_weight"] = orig[k].astype(jnp.float32)
        else:
            self.opt_state = optimizer.init_state_tree(self.params)
        if self._scaler_cfg is not None:
            cfg = self._scaler_cfg
            self.strategy_state.setdefault(
                "amp_scale", jnp.asarray(cfg["init_scale"], jnp.float32))
            self.strategy_state.setdefault("amp_good",
                                           jnp.asarray(0, jnp.int32))
            self.strategy_state.setdefault("amp_bad",
                                           jnp.asarray(0, jnp.int32))
            # cumulative skipped-step count, accumulated IN-GRAPH: the
            # always-available ground truth for loss-scale skips that
            # needs no host sync and rides every checkpoint
            self.strategy_state.setdefault("amp_skipped",
                                           jnp.asarray(0, jnp.int32))
        self._accum_grads = None
        self._accum_count = 0
        self._steps_done = 0
        self._donate = donate
        self._step_fn = None  # built lazily (data shardings need structure)
        self._grad_fn = None
        # one-train-executable guard, observed every step (always-on —
        # the counter bypasses the metrics gate)
        self.recompile_sentinel = RecompileSentinel("train")
        if self.mesh is not None and self.sharding_plan is not None \
                and not self._abstract:
            # place params/opt-state/buffers per the plan up front
            # (abstract states can't be device_put; aot_lower's
            # in_shardings carry the placement instead)
            plan = self.sharding_plan
            state = layer.state_dict()
            self.params = {
                k: plan.place(v, plan.param_spec(k, state.get(k)))
                for k, v in self.params.items()}
            self.opt_state = {
                k: {n: (plan.place(v, plan.state_spec(k, state.get(k)))
                        if np.ndim(v) > 0 else v)
                    for n, v in st.items()}
                for k, st in self.opt_state.items()}

    # -- pure step ----------------------------------------------------------
    def _forward_loss(self, params, buffers, key, inputs, labels):
        layer = self.layer
        state = layer.state_dict()
        saved = {k: t._data for k, t in state.items()}
        try:
            for k, a in params.items():
                state[k]._data = a
            for k, a in buffers.items():
                state[k]._data = a
            ctx = key_scope(key)
            from ..amp.auto_cast import auto_cast
            with no_grad(), ctx:
                if self.amp_level:
                    with auto_cast(level=self.amp_level,
                                   dtype=self.amp_dtype):
                        out = layer(*_wrap_tree(inputs))
                        loss = self.loss_fn(out, *_wrap_tree(labels))
                else:
                    out = layer(*_wrap_tree(inputs))
                    loss = self.loss_fn(out, *_wrap_tree(labels))
            new_buffers = {k: state[k]._data for k in self._buffer_names}
            return (loss._data.astype(jnp.float32),
                    (new_buffers, _unwrap_tree(out)))
        finally:
            for k, a in saved.items():
                state[k]._data = a

    def _build(self, in_arrays, lbl_arrays):
        optimizer = self.optimizer
        accum = self.grad_accum_steps
        fwd_loss = self._forward_loss
        if self.remat:
            fwd_loss = jax.checkpoint(
                self._forward_loss, policy=self.remat_policy,
                static_argnums=())

        scaler_cfg = self._scaler_cfg

        def step(params, opt_state, buffers, strat, key, lr, inputs,
                 labels):
            scale = strat["amp_scale"] if scaler_cfg is not None else None

            def scaled_loss(p, b, k, i, l):
                loss, aux = fwd_loss(p, b, k, i, l)
                if scale is not None:
                    loss = loss * scale
                return loss, aux

            if accum > 1:
                # gradient merge (reference gradient_merge_optimizer.py):
                # split the batch into accum microbatches, scan, average
                def micro(idx):
                    sl = jax.tree_util.tree_map(
                        lambda a: _microslice(a, idx, accum), inputs)
                    ll = jax.tree_util.tree_map(
                        lambda a: _microslice(a, idx, accum), labels)
                    k = jax.random.fold_in(key, idx)
                    gf = jax.value_and_grad(
                        lambda p: scaled_loss(p, buffers, k, sl, ll),
                        has_aux=True)
                    return gf

                def body(carry, idx):
                    g_acc, l_acc = carry
                    (loss, (nb, _)), grads = micro(idx)(params)
                    g_acc = jax.tree_util.tree_map(
                        lambda a, b: a + b, g_acc, grads)
                    return (g_acc, l_acc + loss), nb
                zero_g = jax.tree_util.tree_map(jnp.zeros_like, params)
                (g_sum, l_sum), nbs = jax.lax.scan(
                    body, (zero_g, jnp.zeros((), jnp.float32)),
                    jnp.arange(accum))
                grads = jax.tree_util.tree_map(lambda a: a / accum, g_sum)
                loss = l_sum / accum
                new_buffers = jax.tree_util.tree_map(
                    lambda a: a[-1], nbs)
            else:
                grad_fn = jax.value_and_grad(
                    lambda p: scaled_loss(p, buffers, key, inputs,
                                          labels), has_aux=True)
                (loss, (new_buffers, _)), grads = grad_fn(params)
            found_inf = None
            if scale is not None:
                from ..amp.functional import (check_finite_and_unscale_tree,
                                              update_loss_scaling_state)
                with _scope("loss_scale"):
                    grads, found_inf = check_finite_and_unscale_tree(
                        grads, scale)
                    loss = loss / scale
            # PRE-SYNC grads: the sentry's per-rank tell — after the
            # grad_transform's collective every replica holds the same
            # (possibly already-poisoned) values and nothing can name
            # the chip that produced the corruption
            pre_sync_grads = grads
            if self.grad_transform is not None:
                grads, strat = self.grad_transform(grads, strat, params)
            with _scope("optimizer"):
                new_params, new_opt = optimizer.apply_gradients_tree(
                    params, grads, opt_state, lr=lr)
            if found_inf is not None:
                # skipped-step semantics: on overflow keep params and
                # optimizer state exactly as they were
                with _scope("loss_scale"):
                    keep = lambda new, old: jax.tree_util.tree_map(
                        lambda n, o: jnp.where(found_inf, o, n), new, old)
                    new_params = keep(new_params, params)
                    new_opt = keep(new_opt, opt_state)
                    strat = dict(strat)
                    if scaler_cfg["dynamic"]:
                        ns, ng, nb = update_loss_scaling_state(
                            scale, strat["amp_good"], strat["amp_bad"],
                            found_inf,
                            incr_ratio=scaler_cfg["incr_ratio"],
                            decr_ratio=scaler_cfg["decr_ratio"],
                            incr_every_n=scaler_cfg["incr_every_n"],
                            decr_every_n=scaler_cfg["decr_every_n"])
                        strat.update(amp_scale=ns, amp_good=ng,
                                     amp_bad=nb)
            # tiny scalar extras riding the step's existing results
            # (zero additional dispatches, still ONE executable):
            # amp skip visibility + the numeric sentry's stat streams
            extras: Dict[str, Any] = {}
            if found_inf is not None:
                with _scope("loss_scale"):
                    strat = dict(strat)
                    strat["amp_skipped"] = (
                        strat["amp_skipped"]
                        + found_inf.astype(jnp.int32))
                    extras["amp"] = {"found_inf": found_inf,
                                     "scale": strat["amp_scale"]}
            if self.sentry is not None:
                s_out, strat = self.sentry.instrument(
                    pre_sync_grads, new_params, loss, strat)
                extras["sentry"] = s_out
            return new_params, new_opt, new_buffers, strat, loss, extras

        jit_kwargs = {}
        if self._donate:
            jit_kwargs["donate_argnums"] = (0, 1, 2, 3)
        if self.mesh is not None and self.sharding_plan is not None:
            plan = self.sharding_plan
            in_sh, out_sh = plan.step_shardings(self)
            data_in = jax.tree_util.tree_map(
                lambda a: plan.named(plan.data_spec(a)), in_arrays)
            lbl_in = jax.tree_util.tree_map(
                lambda a: plan.named(plan.data_spec(a)), lbl_arrays)
            jit_kwargs["in_shardings"] = in_sh + (data_in, lbl_in)
            jit_kwargs["out_shardings"] = out_sh
        return jax.jit(step, **jit_kwargs)

    # -- AOT lowering (memory receipts) -------------------------------------
    def aot_lower(self, inputs, labels=()):
        """Lower (and let the caller .compile()) the full training step
        from avals alone — no parameter, optimizer-state, or activation
        bytes are ever allocated. Pairs with
        utils.abstract_init.abstract_parameters() for models too big to
        materialize; `compiled.memory_analysis()` then yields the
        per-device peak the step would need — the hardware-independent
        fits-in-HBM receipt (tests/test_memory_receipts.py)."""
        def aval(x):
            if isinstance(x, jax.ShapeDtypeStruct):
                return x
            return jax.ShapeDtypeStruct(np.shape(x), np.asarray(x).dtype)
        inputs = inputs if isinstance(inputs, (list, tuple)) else (inputs,)
        labels = labels if isinstance(labels, (list, tuple)) else (labels,)
        in_avals = jax.tree_util.tree_map(aval, tuple(inputs))
        lbl_avals = jax.tree_util.tree_map(aval, tuple(labels))
        step = self._build(in_avals, lbl_avals)
        key_aval = jax.eval_shape(lambda: jax.random.key(0))
        lr_aval = jax.ShapeDtypeStruct((), jnp.float32)
        strat_avals = jax.tree_util.tree_map(aval, self.strategy_state)
        buf_avals = jax.tree_util.tree_map(aval, self.buffers)
        opt_avals = jax.tree_util.tree_map(aval, self.opt_state)
        param_avals = jax.tree_util.tree_map(aval, self.params)
        return step.lower(param_avals, opt_avals, buf_avals, strat_avals,
                          key_aval, lr_aval, in_avals, lbl_avals)

    # -- eval / predict -----------------------------------------------------
    def build_eval_fn(self):
        def ev(params, buffers, key, inputs):
            layer = self.layer
            state = layer.state_dict()
            saved = {k: t._data for k, t in state.items()}
            mode = layer.training
            try:
                layer.eval()
                for k, a in {**params, **buffers}.items():
                    state[k]._data = a
                with no_grad(), key_scope(key):
                    out = layer(*_wrap_tree(inputs))
                return _unwrap_tree(out)
            finally:
                layer.training = mode
                for lyr in layer.sublayers(include_self=True):
                    lyr.training = mode
                for k, a in saved.items():
                    state[k]._data = a
        return jax.jit(ev)

    # -- the step call ------------------------------------------------------
    def __call__(self, inputs, labels=()):
        inputs = inputs if isinstance(inputs, (list, tuple)) else (inputs,)
        labels = labels if isinstance(labels, (list, tuple)) else (labels,)
        in_arrays = _unwrap_tree(tuple(inputs))
        lbl_arrays = _unwrap_tree(tuple(labels))
        if self._step_fn is None:
            self._step_fn = self._build(in_arrays, lbl_arrays)
        lr = jnp.asarray(self.optimizer.get_lr(), jnp.float32)
        key = next_key()
        # black-box step bracket (one bool read when disabled): the
        # flight recorder's step events drive the hang watchdog's
        # progress clock and the goodput "train" bucket
        _tok = _fr.step_begin("train_step", self._steps_done)
        try:
            (self.params, self.opt_state, self.buffers,
             self.strategy_state, loss, extras) = self._step_fn(
                self.params, self.opt_state, self.buffers,
                self.strategy_state, key, lr, in_arrays, lbl_arrays)
        except Exception as e:
            # OOM sentry (memory plane): zero cost unless the dispatch
            # actually dies — a RESOURCE_EXHAUSTED leaves the always-on
            # counter, the flight-recorder `oom` breadcrumb and a
            # post-mortem receipt (top scopes + remediation hint)
            # before the fault propagates
            _mem.handle_dispatch_oom("train_step", e,
                                     step=self._steps_done)
            raise
        if _tok is not None and _fr.sync_steps():
            # device-complete before the bracket closes, so step.end
            # durations measure real work, not async dispatch latency
            jax.block_until_ready(loss)
        _fr.step_end("train_step", self._steps_done, _tok)
        if "amp" in extras and (_obs._enabled or _fr._enabled):
            # loss-scale skip visibility: the found_inf branch keeps
            # params/opt-state untouched — a silent no-op step unless
            # someone says so. The host read is GATED on an armed
            # observability plane: a per-step device sync would break
            # the no-host-sync contract of the in-graph scaler on the
            # hottest path. The ungated ground truth is the in-graph
            # cumulative strategy_state["amp_skipped"] (checkpointed,
            # readable at any sync point with zero per-step cost).
            skipped = bool(np.asarray(extras["amp"]["found_inf"]))
            scale_v = float(np.asarray(extras["amp"]["scale"]))
            if skipped:
                _obs.counter("amp.loss_scale.skipped_total",
                             _always=True).add(1)
                _fr.record("loss_scale.skip", step=self._steps_done,
                           scale=scale_v)
            if _obs._enabled:
                _obs.gauge("amp.loss_scale.scale").set(scale_v)
        if self.sentry is not None:
            self.sentry.consume(self._steps_done, extras["sentry"])
        self._steps_done += 1
        if isinstance(self.optimizer._lr, LRScheduler):
            pass  # caller steps the scheduler per its own schedule
        if _obs._enabled:
            _obs.counter("train.steps_total").add(1)
        # sentinel is ALWAYS on (counter bypasses the metrics gate): a
        # silent retrace is a contract violation whether or not anyone
        # is scraping; cost is one cache-size read + input-shapes walk
        self.recompile_sentinel.observe(
            int(self._step_fn._cache_size()), expected=1,
            signature=signature_of((in_arrays, lbl_arrays)))
        # keep the Layer's tensors pointing at live (undonated) arrays —
        # dygraph semantics: the model is usable eagerly at any time
        self.sync_to_layer()
        return Tensor(loss)

    def sync_to_layer(self):
        """Re-point the Layer's Tensors at the step's live arrays
        (zero-copy). Called after every step — the donated executable
        deletes the arrays the Layer previously referenced — and kept
        public for checkpoint/restore flows."""
        st = self._state_tensors
        for k, a in self.params.items():
            st[k]._data = a
        for k, a in self.buffers.items():
            st[k]._data = a

    def rebind_layer(self):
        """Re-resolve the Tensor cache against the LIVE layer. The
        per-step sync_to_layer uses a construction-time name->Tensor
        cache (an O(tensors) state_dict() walk per step would be
        hot-loop drag); if the layer's tensors are REPLACED after
        construction (re-init, sublayer swap, quant convert()), that
        cache feeds orphaned Tensor objects while the live layer keeps
        donated/deleted arrays. Checkpoint flows call this; call it
        yourself after any in-place layer surgery while a TrainStep is
        bound."""
        live = self.layer.state_dict()
        for k, t in live.items():
            if k in self._state_tensors:
                self._state_tensors[k] = t

    def state_dict(self):
        self.rebind_layer()
        self.sync_to_layer()
        return {"model": self.layer.state_dict(),
                "opt_state": self.opt_state,
                "opt": self.optimizer.state_dict(),
                "strategy_state": self.strategy_state}

    def set_state_dict(self, state):
        """Restore a state_dict() checkpoint (params/buffers into the
        layer, optimizer + strategy state — DGC error-feedback buffers,
        rampup counters — into the step). Arrays are COPIED: the compiled
        step donates its state buffers each call, so sharing them with the
        checkpoint source would invalidate the source's state."""
        self.rebind_layer()
        def copy_arr(v):
            a = v._data if isinstance(v, Tensor) else v
            return jnp.array(np.asarray(a))
        model = state.get("model") or {}
        own = self.layer.state_dict()
        for k, v in model.items():
            arr = copy_arr(v)
            if k in own:
                own[k]._data = arr
            if k in self.params:
                self.params[k] = arr
            if k in self.buffers:
                self.buffers[k] = arr
        if state.get("opt_state") is not None:
            self.opt_state = jax.tree_util.tree_map(copy_arr,
                                                    state["opt_state"])
        if state.get("opt") is not None:
            self.optimizer.set_state_dict(state["opt"])
        if state.get("strategy_state") is not None:
            self.strategy_state = jax.tree_util.tree_map(
                copy_arr, state["strategy_state"])
            # re-seed the keys THIS build requires that the restored
            # candidate may predate (a pre-sentry checkpoint, an
            # amp run older than the in-graph skip counter): the
            # wholesale replace must never hand the compiled step a
            # strategy pytree missing the keys it was traced with —
            # that KeyErrors inside the very rollback the numeric
            # remediation performs
            if self._scaler_cfg is not None:
                cfg = self._scaler_cfg
                self.strategy_state.setdefault(
                    "amp_scale",
                    jnp.asarray(cfg["init_scale"], jnp.float32))
                self.strategy_state.setdefault(
                    "amp_good", jnp.asarray(0, jnp.int32))
                self.strategy_state.setdefault(
                    "amp_bad", jnp.asarray(0, jnp.int32))
                self.strategy_state.setdefault(
                    "amp_skipped", jnp.asarray(0, jnp.int32))
            if self.sentry is not None:
                self.sentry.init_state(self.strategy_state)
        else:
            # rollback consistency: int8-EF residuals are time-coupled
            # to the params they quantized — restoring params WITHOUT
            # the matching strategy state must purge live residuals
            # (reset is unbiased; a residual from the rolled-back
            # future is not)
            from ..distributed.comm import purge_residual_state
            purge_residual_state(self.strategy_state)
