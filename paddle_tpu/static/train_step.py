"""TrainStep: whole-step compilation (the TPU performance path).

The reference runs training as a per-op interpreter loop
(executor.cc:461 / dygraph tracer) — on TPU that would leave the MXU idle
between dispatches. Here the entire step (forward + loss + backward +
optimizer update + LR schedule + loss scaling) compiles to ONE XLA
executable via jax.jit, with parameters/optimizer state as donated pytree
inputs so updates happen in-place in HBM.

Sharding: pass a Mesh + a ShardingPlan (paddle_tpu.distributed) and every
pytree leaf gets a NamedSharding — XLA inserts the collectives (DP grad
all-reduce ≡ reference's c_allreduce_sum graph rewrite, ZeRO state
sharding ≡ sharding_optimizer.py — but as compiler-placed reduce-scatter/
all-gather over ICI instead of graph surgery).
"""
from __future__ import annotations

from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core.generator import key_scope, next_key
from ..framework import Tensor, no_grad
from ..jit.api import _unwrap_tree, _wrap_tree
from ..nn.layer.layers import Layer
from ..optimizer.optimizer import Optimizer
from ..optimizer.lr import LRScheduler

__all__ = ["TrainStep"]


class TrainStep:
    """Compiled training step.

    loss_fn(outputs, *labels) -> scalar Tensor, written in paddle ops.
    Usage:
        step = TrainStep(model, loss_fn, optimizer)
        loss = step(inputs, labels)   # one fused XLA step
    """

    def __init__(self, layer: Layer, loss_fn: Callable,
                 optimizer: Optimizer, amp_level: Optional[str] = None,
                 amp_dtype="bfloat16", mesh=None, sharding_plan=None,
                 donate: bool = True, grad_accum_steps: int = 1):
        self.layer = layer
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        self.amp_level = amp_level
        self.amp_dtype = amp_dtype
        self.mesh = mesh
        self.sharding_plan = sharding_plan
        self.grad_accum_steps = grad_accum_steps

        state = layer.state_dict()
        self._trainable_names = [k for k, t in state.items()
                                 if not t.stop_gradient]
        self._buffer_names = [k for k, t in state.items() if t.stop_gradient]
        self.params = {k: state[k]._data for k in self._trainable_names}
        self.buffers = {k: state[k]._data for k in self._buffer_names}
        self.opt_state = optimizer.init_state_tree(self.params)
        self._accum_grads = None
        self._accum_count = 0
        self._step_fn = self._build(donate)
        self._grad_fn = None

    # -- pure step ----------------------------------------------------------
    def _forward_loss(self, params, buffers, key, inputs, labels):
        layer = self.layer
        state = layer.state_dict()
        saved = {k: t._data for k, t in state.items()}
        try:
            for k, a in params.items():
                state[k]._data = a
            for k, a in buffers.items():
                state[k]._data = a
            ctx = key_scope(key)
            from ..amp.auto_cast import auto_cast
            with no_grad(), ctx:
                if self.amp_level:
                    with auto_cast(level=self.amp_level,
                                   dtype=self.amp_dtype):
                        out = layer(*_wrap_tree(inputs))
                        loss = self.loss_fn(out, *_wrap_tree(labels))
                else:
                    out = layer(*_wrap_tree(inputs))
                    loss = self.loss_fn(out, *_wrap_tree(labels))
            new_buffers = {k: state[k]._data for k in self._buffer_names}
            return (loss._data.astype(jnp.float32),
                    (new_buffers, _unwrap_tree(out)))
        finally:
            for k, a in saved.items():
                state[k]._data = a

    def _build(self, donate):
        optimizer = self.optimizer

        def step(params, opt_state, buffers, key, lr, inputs, labels):
            grad_fn = jax.value_and_grad(
                lambda p: self._forward_loss(p, buffers, key, inputs,
                                             labels), has_aux=True)
            (loss, (new_buffers, _)), grads = grad_fn(params)
            new_params, new_opt = optimizer.apply_gradients_tree(
                params, grads, opt_state, lr=lr)
            return new_params, new_opt, new_buffers, loss

        jit_kwargs = {}
        if donate:
            jit_kwargs["donate_argnums"] = (0, 1, 2)
        if self.mesh is not None and self.sharding_plan is not None:
            in_sh, out_sh = self.sharding_plan.step_shardings(self)
            jit_kwargs["in_shardings"] = in_sh
            jit_kwargs["out_shardings"] = out_sh
        return jax.jit(step, **jit_kwargs)

    # -- eval / predict -----------------------------------------------------
    def build_eval_fn(self):
        def ev(params, buffers, key, inputs):
            layer = self.layer
            state = layer.state_dict()
            saved = {k: t._data for k, t in state.items()}
            mode = layer.training
            try:
                layer.eval()
                for k, a in {**params, **buffers}.items():
                    state[k]._data = a
                with no_grad(), key_scope(key):
                    out = layer(*_wrap_tree(inputs))
                return _unwrap_tree(out)
            finally:
                layer.training = mode
                for lyr in layer.sublayers(include_self=True):
                    lyr.training = mode
                for k, a in saved.items():
                    state[k]._data = a
        return jax.jit(ev)

    # -- the step call ------------------------------------------------------
    def __call__(self, inputs, labels=()):
        inputs = inputs if isinstance(inputs, (list, tuple)) else (inputs,)
        labels = labels if isinstance(labels, (list, tuple)) else (labels,)
        in_arrays = _unwrap_tree(tuple(inputs))
        lbl_arrays = _unwrap_tree(tuple(labels))
        lr = jnp.asarray(self.optimizer.get_lr(), jnp.float32)
        key = next_key()
        self.params, self.opt_state, self.buffers, loss = self._step_fn(
            self.params, self.opt_state, self.buffers, key, lr, in_arrays,
            lbl_arrays)
        if isinstance(self.optimizer._lr, LRScheduler):
            pass  # caller steps the scheduler per its own schedule
        return Tensor(loss)

    def sync_to_layer(self):
        """Write compiled-state arrays back into the Layer's Tensors (for
        checkpointing / switching back to eager)."""
        state = self.layer.state_dict()
        for k, a in {**self.params, **self.buffers}.items():
            state[k]._data = a

    def state_dict(self):
        self.sync_to_layer()
        return {"model": self.layer.state_dict(),
                "opt_state": self.opt_state,
                "opt": self.optimizer.state_dict()}
