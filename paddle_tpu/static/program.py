"""Static Program / Executor (declarative path).

Reference: ProgramDesc + Executor feed/fetch
(/root/reference/paddle/fluid/framework/framework.proto:202,
framework/executor.cc:289, python/paddle/fluid/executor.py:475,
backward.py:1337 append_backward).

TPU-first redesign: a Program is a captured op graph — every op routed
through the registry while a program_guard is active appends an OpNode
(pure fn + symbolic vars, shapes inferred with jax.eval_shape — the
InferShape pass). Executor.run lowers the whole program (plus appended
backward/optimizer stages) to ONE jitted function keyed by feed shapes —
the "Program → XLA executable" pipeline replacing the reference's per-op
interpreter loop. Parameters created inside the guard are captured
constants whose storage the Executor updates in place after optimizer
programs run.
"""
from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core import dtypes as _dtypes
from ..core.enforce import EnforceNotMet, NotFoundError
from ..core.generator import key_scope, next_key
from ..framework import Parameter, Tensor
from ..ops import registry as _registry

__all__ = ["Program", "program_guard", "default_main_program",
           "default_startup_program", "data", "Executor",
           "append_backward", "gradients"]

_static_mode = False


# placeholder extent for None/-1 dims during capture-time shape
# inference. NOT 1: batch=1 placeholders silently specialize
# broadcasting/squeeze semantics at capture while the Executor re-jits
# per real shape — a mismatch class the reference doesn't have (its
# InferShape propagates -1 symbolically). A distinctive prime makes the
# placeholder inert for broadcasting and lets capture warn when the
# value leaks into op attributes (a python-side `x.shape[0]` read).
SYMBOLIC_DIM = 509


class Var(Tensor):
    """Symbolic variable: carries aval only (no data). Lives in a Program.

    Subclasses Tensor so every op / layer treats it uniformly; `_data`
    holds a zero placeholder of the right aval for shape inference.
    `orig_shape` preserves the declared shape (None/-1 dims intact);
    `symbolic_dims` indexes them."""

    def __init__(self, program, name, shape, dtype, kind="intermediate"):
        dtype = _dtypes.convert_dtype(dtype)
        self.orig_shape = tuple(None if (s is None or s < 0) else int(s)
                                for s in shape)
        self.symbolic_dims = {i for i, s in enumerate(self.orig_shape)
                              if s is None}
        shape = tuple(SYMBOLIC_DIM if s is None else s
                      for s in self.orig_shape)
        self._init_symbolic(shape, dtype)
        self.program = program
        self.name = name
        self.kind = kind  # feed | param | intermediate | fetch
        self.var_id = program._new_var_id(self)

    def _init_symbolic(self, shape, dtype):
        """Aval-only placeholder: capture never executes ops, so _data is
        a ShapeDtypeStruct (shape/dtype carrier) — no SYMBOLIC_DIM-extent
        buffer is ever materialized (a [None,1024,4096] activation would
        otherwise allocate a 509-batch zeros array per Var)."""
        self._data = jax.ShapeDtypeStruct(tuple(shape), np.dtype(dtype))
        self.stop_gradient = True
        self._grad = None
        self._node = None
        self._out_idx = 0
        self.persistable = False
        self._retain_grad = False
        self._grad_hooks = []
        self.sharding_spec = None

    def __repr__(self):
        return (f"Var(name={self.name}, shape={self.shape}, "
                f"dtype={self.dtype}, kind={self.kind})")


class OpNode:
    __slots__ = ("fn", "in_ids", "const_args", "kwargs", "out_ids",
                 "op_type", "n_outs", "multi")

    def __init__(self, op_type, fn, in_ids, const_args, kwargs, out_ids,
                 multi):
        self.op_type = op_type
        self.fn = fn
        self.in_ids = in_ids          # positional slots: var_id or None
        self.const_args = const_args  # positional slots: constants
        self.kwargs = kwargs
        self.out_ids = out_ids
        self.multi = multi


def backward_slice(ops, target_ids):
    """Liveness slice shared by Program.prune and the
    dead_code_elimination_pass (static/passes.py): returns (kept_ops,
    needed_var_ids) for the ops that can reach `target_ids`."""
    needed = set(target_ids)
    kept: List[OpNode] = []
    for node in reversed(ops):
        if any(o in needed for o in node.out_ids):
            kept.append(node)
            needed.update(i for i in node.in_ids if i is not None)
    kept.reverse()
    return kept, needed


class Program:
    """Captured graph (ProgramDesc analogue)."""

    def __init__(self):
        self.vars: Dict[int, Var] = {}
        self.var_names: Dict[str, int] = {}
        self.ops: List[OpNode] = []
        self.feeds: List[int] = []
        self.params: Dict[int, Parameter] = {}  # var_id -> live Parameter
        self.buffer_ids: set = set()  # captured stop_gradient tensors
        # (buffer_var_id, value_var_id): after a run, write the computed
        # value back into the live buffer (BN running stats)
        self._buffer_writes: List[Tuple[int, int]] = []
        self._counter = 0
        self._optimize = None  # (optimizer, loss_var)
        # live optimizer accumulator tree for the static train path; owned
        # by the Program (not the Executor cache) so a serialized
        # mid-training program resumes with exact moments/step counts
        self._opt_state = None
        self.random_seed = None

    def _new_var_id(self, var) -> int:
        vid = self._counter
        self._counter += 1
        self.vars[vid] = var
        if var.name:
            self.var_names[var.name] = vid
        return vid

    def var_by_name(self, name) -> Var:
        if name not in self.var_names:
            raise NotFoundError(f"var '{name}' not in program")
        return self.vars[self.var_names[name]]

    def global_block(self):
        return self

    def all_parameters(self):
        return list(self.params.values())

    def list_vars(self):
        return list(self.vars.values())

    def clone(self, for_test=False):
        p = Program()
        p.vars = dict(self.vars)
        p.var_names = dict(self.var_names)
        p.ops = [OpNode(n.op_type, n.fn, list(n.in_ids),
                        list(n.const_args), dict(n.kwargs),
                        list(n.out_ids), n.multi) for n in self.ops]
        p.feeds = list(self.feeds)
        p.params = dict(self.params)
        p.buffer_ids = set(self.buffer_ids)
        p._buffer_writes = list(self._buffer_writes)
        p._counter = self._counter
        p.random_seed = self.random_seed
        if not for_test:
            # backward/optimize bookkeeping travels with a train clone;
            # a test clone is forward-only by construction (reference
            # clone(for_test=True) prunes the backward blocks)
            p._grad_target = getattr(self, "_grad_target", None)
            p._grad_pairs = list(getattr(self, "_grad_pairs", ()))
            p._var_grads = list(getattr(self, "_var_grads", ()))
            p._optimize = self._optimize
            # COPY, not alias: the Executor donates the opt-state
            # buffers into its jitted step, which would leave the other
            # program holding deleted arrays after one train run
            if self._opt_state is not None:
                p._opt_state = jax.tree_util.tree_map(
                    jnp.array, self._opt_state)
        if for_test:
            # flip train-mode ops (reference clone prunes/rewires the
            # test program: dropout becomes identity/downscale,
            # batch_norm switches to running-stat normalization)
            for node in p.ops:
                if node.op_type in ("dropout_op", "dropout_nd",
                                    "alpha_dropout"):
                    # drop the rng-key positional slot (x, key) -> (x,);
                    # alpha_dropout's eval form is identity (p/mode
                    # kwargs absent -> dropout_eval passes through)
                    node.op_type = "dropout_eval"
                    node.fn = _registry.get_op("dropout_eval").fn
                    node.in_ids = node.in_ids[:1]
                    node.const_args = node.const_args[:1]
                    node.kwargs = {k: node.kwargs[k]
                                   for k in ("p", "mode")
                                   if k in node.kwargs}
                elif node.op_type == "sdpa_dropout":
                    # (q, k, v, key) -> deterministic SDPA over (q, k, v)
                    node.op_type = "scaled_dot_product_attention"
                    node.fn = _registry.get_op(
                        "scaled_dot_product_attention").fn
                    node.in_ids = node.in_ids[:3]
                    node.const_args = node.const_args[:3]
                    node.kwargs = {k: v for k, v in node.kwargs.items()
                                   if k != "dropout_p"}
                elif node.op_type == "flash_attention_dropout":
                    # (q, k, v, drop_key, kv_lens) -> deterministic
                    # flash over (q, k, v, kv_lens): drop ONLY the rng
                    # key; the varlen bound must survive into the eval
                    # clone or it would attend over padding keys
                    node.op_type = "flash_attention_op"
                    node.fn = _registry.get_op("flash_attention_op").fn
                    node.in_ids = node.in_ids[:3] + node.in_ids[4:5]
                    node.const_args = (node.const_args[:3]
                                       + node.const_args[4:5])
                    node.kwargs = {k: v for k, v in node.kwargs.items()
                                   if k in ("causal", "block_size")}
                elif node.op_type == "batch_norm_op":
                    node.kwargs = dict(node.kwargs, training=False)
        return p

    def prune(self, targets) -> "Program":
        """Backward-slice the graph to the ops needed for `targets`
        (framework/prune.cc analogue)."""
        target_ids = set()
        for t in targets if isinstance(targets, (list, tuple)) \
                else [targets]:
            target_ids.add(t.var_id if isinstance(t, Var)
                           else self.var_by_name(t).var_id)
        kept, needed = backward_slice(self.ops, target_ids)
        p = Program()
        p.ops = kept
        live = set(needed) | {o for n in kept for o in n.out_ids}
        p.vars = {vid: v for vid, v in self.vars.items() if vid in live}
        p.var_names = {nm: vid for nm, vid in self.var_names.items()
                       if vid in live}
        p.feeds = [f for f in self.feeds if f in needed]
        p.params = {vid: t for vid, t in self.params.items()
                    if vid in needed}
        p.buffer_ids = {b for b in self.buffer_ids if b in needed}
        p._buffer_writes = [(b, v) for b, v in self._buffer_writes
                            if b in needed and v in live]
        # carry backward bookkeeping only where every referenced var
        # survived the slice (pruning to an inference target drops it)
        gt = getattr(self, "_grad_target", None)
        if gt is not None and gt in live:
            p._grad_target = gt
            p._grad_pairs = [(pv, gv)
                             for pv, gv in getattr(self, "_grad_pairs", ())
                             if pv.var_id in live]
            for pv, gv in p._grad_pairs:
                p.vars.setdefault(gv.var_id, gv)
                if gv.name:
                    p.var_names.setdefault(gv.name, gv.var_id)
        p._var_grads = [
            s for s in getattr(self, "_var_grads", ())
            if all(t in live for t in s["targets"])
            and all(i in live for i in s["inputs"])]
        for s in p._var_grads:
            for gid in s["grad_vars"]:
                gv = self.vars[gid]
                p.vars.setdefault(gid, gv)
                if gv.name:
                    p.var_names.setdefault(gv.name, gid)
        p._counter = self._counter
        p.random_seed = self.random_seed
        return p

    # -- serialization (framework.proto ProgramDesc analogue) ----------------
    def to_bytes(self, include_params: bool = True) -> bytes:
        """Serialize: ops as registry names + attrs, vars as metadata,
        params (optionally) as values. Round-trips through from_bytes."""
        import pickle

        def enc(v):
            if isinstance(v, jax.Array):
                if jnp.issubdtype(v.dtype, jax.dtypes.prng_key):
                    # rng-key consts (dropout keys): store the raw bits
                    return ("__key__", np.asarray(jax.random.key_data(v)))
                return ("__arr__", np.asarray(v))
            if isinstance(v, (tuple, list)):
                # nested containers (getitem idx attrs hold arrays inside
                # tuples); markers above can't collide with real data
                return type(v)(enc(x) for x in v)
            return v
        ops = []
        for n in self.ops:
            if n.op_type not in _registry.OPS or \
                    _registry.OPS[n.op_type].fn is not n.fn:
                raise EnforceNotMet(
                    f"op '{n.op_type}' is not a registered op; programs "
                    "with ad-hoc functions cannot be serialized",
                    op_type=n.op_type)
            ops.append((n.op_type, list(n.in_ids),
                        [enc(c) for c in n.const_args],
                        {k: enc(v) for k, v in n.kwargs.items()},
                        list(n.out_ids), n.multi))
        vars_meta = {
            vid: (v.name, tuple(v._data.shape), str(v._data.dtype),
                  v.kind, getattr(v, "orig_shape", None))
            for vid, v in self.vars.items()}
        params = {
            vid: (t.name, np.asarray(t._data) if include_params else None,
                  str(t._data.dtype))
            for vid, t in self.params.items()}
        # -- backward + optimize sections (format v3). In the reference,
        # append_backward's grad ops are ordinary ops inside the
        # serialized ProgramDesc blocks (framework.proto:178,
        # backward.py:1337) so a saved training program keeps its whole
        # graph; here the equivalent bookkeeping is the grad target /
        # (param, grad) ids / gradients() specs plus the optimize stage.
        grad_pairs = [(pv.var_id, gv.var_id)
                      for pv, gv in getattr(self, "_grad_pairs", ())]
        var_grads = [
            {"targets": list(s["targets"]), "inputs": list(s["inputs"]),
             "grad_vars": list(s["grad_vars"]),
             "tgrads": [None if g is None else np.asarray(g)
                        for g in s["tgrads"]]}
            for s in getattr(self, "_var_grads", ())]
        optimize = None
        if self._optimize is not None:
            import copy
            opt, loss_var = self._optimize[0], self._optimize[1]
            opt2 = copy.copy(opt)
            # live Parameters / eager accumulators don't belong in the
            # artifact; the static path's state rides _opt_state below
            opt2._parameters = None
            opt2._accumulators = {}
            optimize = (pickle.dumps(opt2, protocol=4), loss_var.var_id)
        opt_state = None
        if self._opt_state is not None:
            opt_state = jax.tree_util.tree_map(
                lambda x: np.asarray(x), self._opt_state)
        from ..core.version_compat import (PROGRAM_FORMAT_VERSION,
                                           op_version)
        return pickle.dumps({
            "version": PROGRAM_FORMAT_VERSION,
            "op_versions": {n.op_type: op_version(n.op_type)
                            for n in self.ops},
            "vars": vars_meta, "ops": ops,
            "feeds": list(self.feeds), "params": params,
            "buffer_ids": sorted(self.buffer_ids),
            "buffer_writes": list(self._buffer_writes),
            "counter": self._counter, "random_seed": self.random_seed,
            "grad_target": getattr(self, "_grad_target", None),
            "grad_pairs": grad_pairs, "var_grads": var_grads,
            "optimize": optimize, "opt_state": opt_state,
        }, protocol=4)

    @staticmethod
    def from_bytes(blob: bytes) -> "Program":
        import pickle
        from ..core.version_compat import (migrate_program_dict,
                                           migrate_op_entry)
        d = migrate_program_dict(pickle.loads(blob))
        saved_op_versions = d.get("op_versions", {})

        def dec(v):
            if isinstance(v, tuple) and len(v) == 2:
                if v[0] == "__arr__":
                    return jnp.asarray(v[1])
                if v[0] == "__key__":
                    return jax.random.wrap_key_data(jnp.asarray(v[1]))
            if isinstance(v, (tuple, list)):
                return type(v)(dec(x) for x in v)
            return v
        p = Program()
        for vid, meta in sorted(d["vars"].items()):
            name, shape, dtype, kind = meta[:4]
            orig = meta[4] if len(meta) > 4 else None
            v = Var.__new__(Var)
            v._init_symbolic(tuple(shape), dtype)
            v.program = p
            v.name = name
            v.kind = kind
            v.orig_shape = orig if orig is not None else tuple(shape)
            v.symbolic_dims = {i for i, s in enumerate(v.orig_shape)
                               if s is None}
            v.var_id = vid
            p.vars[vid] = v
            if name:
                p.var_names[name] = vid
        for op_type, in_ids, const_args, kwargs, out_ids, multi in \
                d["ops"]:
            fn = _registry.get_op(op_type).fn
            const_args = [dec(c) for c in const_args]
            kwargs = {k: dec(v) for k, v in kwargs.items()}
            # per-op version check + migration (op_version_registry.h)
            const_args, kwargs = migrate_op_entry(
                op_type, int(saved_op_versions.get(op_type, 1)),
                const_args, kwargs)
            p.ops.append(OpNode(op_type, fn, in_ids, const_args, kwargs,
                                out_ids, multi))
        p.feeds = list(d["feeds"])
        p.buffer_ids = set(d.get("buffer_ids", ()))
        p._buffer_writes = [tuple(x) for x in d.get("buffer_writes", ())]
        for vid, (name, value, dtype) in d["params"].items():
            arr = jnp.asarray(value) if value is not None else \
                jnp.zeros(p.vars[vid]._data.shape, dtype)
            t = Parameter(arr)
            t.name = name
            t.stop_gradient = vid in p.buffer_ids
            p.params[vid] = t
        p._counter = d["counter"]
        p.random_seed = d.get("random_seed")
        # -- backward + optimize sections (v3) --
        gt = d.get("grad_target")
        if gt is not None:
            p._grad_target = gt
        pairs = [(p.vars[pvid], p.vars[gvid])
                 for pvid, gvid in d.get("grad_pairs", ())]
        if pairs:
            p._grad_pairs = pairs
        vgs = d.get("var_grads", ())
        if vgs:
            p._var_grads = [dict(s) for s in vgs]
        opt = d.get("optimize")
        if opt is not None:
            opt_blob, loss_vid = opt
            p._optimize = (pickle.loads(opt_blob), p.vars[loss_vid])
        if d.get("opt_state") is not None:
            p._opt_state = jax.tree_util.tree_map(
                jnp.asarray, d["opt_state"])
        return p

    def save(self, path: str, include_params: bool = True):
        with open(path, "wb") as f:
            f.write(self.to_bytes(include_params))

    @staticmethod
    def load(path: str) -> "Program":
        with open(path, "rb") as f:
            return Program.from_bytes(f.read())

    # -- capture ------------------------------------------------------------
    def capture_param(self, t: Tensor) -> Var:
        """Register a live Parameter/Tensor used by the program.
        stop_gradient captures (BN running stats and other buffers) are
        tracked in buffer_ids: no grads, no optimizer updates."""
        for vid, p in self.params.items():
            if p is t:
                return self.vars[vid]
        name = t.name or f"param_{len(self.params)}"
        kind = "buffer" if t.stop_gradient else "param"
        v = Var(self, name, t._data.shape, t._data.dtype, kind=kind)
        self.params[v.var_id] = t
        if t.stop_gradient:
            self.buffer_ids.add(v.var_id)
        return v

    def add_op(self, op_type, fn, args, kwargs):
        in_ids, const_args = [], []
        for a in args:
            if isinstance(a, Var) and a.program is self:
                in_ids.append(a.var_id)
                const_args.append(None)
            elif isinstance(a, Tensor):
                pv = self.capture_param(a)
                in_ids.append(pv.var_id)
                const_args.append(None)
            else:
                in_ids.append(None)
                const_args.append(a)
        kw = {}
        for k, v in kwargs.items():
            if isinstance(v, Var) and v.program is self:
                raise EnforceNotMet(
                    "tensor kwargs not supported in static capture; pass "
                    "positionally", op_type=op_type)
            kw[k] = v._data if isinstance(v, Tensor) else v

        # a SYMBOLIC_DIM-valued attribute almost certainly came from
        # reading a placeholder dim (user code did `x.shape[0]` while
        # building the program) — it would bake the placeholder into the
        # graph where the real batch size belongs
        def _leaks(v):
            if isinstance(v, (int, np.integer)):
                return int(v) == SYMBOLIC_DIM
            if isinstance(v, (list, tuple)):
                return any(_leaks(x) for x in v)
            return False
        if any(_leaks(c) for c in const_args) or \
                any(_leaks(v) for v in kw.values()):
            import warnings
            warnings.warn(
                f"static capture of op '{op_type}': an attribute equals "
                f"the symbolic-dim placeholder ({SYMBOLIC_DIM}); if this "
                "came from reading a data() placeholder's shape, derive "
                "it inside the op from the input instead (paddle.shape)",
                stacklevel=3)

        # InferShape via eval_shape on the pure fn
        def shaped(*xs):
            full = [x if x is not None else c
                    for x, c in zip(xs, const_args)]
            res = fn(*full, **kw)
            return tuple(res) if isinstance(res, (list, tuple)) else res

        in_avals = [
            jax.ShapeDtypeStruct(self.vars[i]._data.shape,
                                 self.vars[i]._data.dtype)
            if i is not None else None
            for i in in_ids
        ]
        out_aval = jax.eval_shape(shaped, *in_avals)
        multi = isinstance(out_aval, tuple)
        outs = list(out_aval) if multi else [out_aval]
        out_vars = [Var(self, f"tmp_{self._counter}", o.shape, o.dtype)
                    for o in outs]
        self.ops.append(OpNode(op_type, fn, in_ids, const_args, kw,
                               [v.var_id for v in out_vars], multi))
        if multi:
            return tuple(out_vars)
        return out_vars[0]

    # -- replay -------------------------------------------------------------
    def build_callable(self, fetch_ids: Sequence[int],
                       grad_of: Optional[Sequence[int]] = None):
        """pure(feed_arrays, param_arrays, key) -> (fetches, grads?)"""
        feeds = list(self.feeds)
        param_ids = list(self.params.keys())
        # grads/updates apply only to trainable captures, never buffers
        train_pos = [k for k, vid in enumerate(param_ids)
                     if vid not in self.buffer_ids]
        ops = list(self.ops)
        fetch_set = set(fetch_ids)
        # lazily compute var grads: only specs whose @GRAD vars are
        # actually fetched cost a differentiated replay
        var_grad_specs = [
            s for s in getattr(self, "_var_grads", [])
            if any(g in fetch_set for g in s["grad_vars"])]

        def _is_prng_key(c):
            try:
                return hasattr(c, "dtype") and jax.dtypes.issubdtype(
                    c.dtype, jax.dtypes.prng_key)
            except Exception:
                return False

        def replay(env, override=None):
            for node in ops:
                # rng ops capture a trace-time key in const_args; replay
                # must NOT bake it (every Executor.run would reuse the
                # same dropout mask) — draw a fresh key from the per-run
                # key_scope instead (deterministic given the run key)
                ins = [env[i] if i is not None
                       else (next_key() if _is_prng_key(c) else c)
                       for i, c in zip(node.in_ids, node.const_args)]
                res = node.fn(*ins, **node.kwargs)
                res = tuple(res) if isinstance(res, (list, tuple)) else \
                    (res,)
                for vid, r in zip(node.out_ids, res):
                    # `override` cuts the graph at chosen vars (static
                    # gradients() wrt intermediates)
                    env[vid] = override[vid] if override and \
                        vid in override else r
            return env

        def apply_var_grads(env, feed_arrays, param_arrays):
            for spec in var_grad_specs:
                in_ids_ = spec["inputs"]
                xs = [env[i] for i in in_ids_]

                def h(xvals):
                    e = {}
                    for vid, a in zip(feeds, feed_arrays):
                        e[vid] = a
                    for vid, a in zip(param_ids, param_arrays):
                        e[vid] = a
                    ov = dict(zip(in_ids_, xvals))
                    e.update(ov)
                    e = replay(e, override=ov)
                    total = jnp.zeros((), jnp.float32)
                    for tid, tg in zip(spec["targets"], spec["tgrads"]):
                        tval = e[tid].astype(jnp.float32)
                        if tg is None:
                            total = total + tval.sum()
                        else:
                            total = total + (tval
                                             * jnp.asarray(tg)).sum()
                    return total
                gs = jax.grad(h)(xs)
                for gid, g in zip(spec["grad_vars"], gs):
                    env[gid] = g
            return env

        def pure(feed_arrays, param_arrays, key):
            with key_scope(key):
                env = {}
                for vid, a in zip(feeds, feed_arrays):
                    env[vid] = a
                for vid, a in zip(param_ids, param_arrays):
                    env[vid] = a
                if grad_of:
                    def loss_fn(t_arrays):
                        e = dict(env)
                        for pos, a in zip(train_pos, t_arrays):
                            e[param_ids[pos]] = a
                        e = replay(e)
                        return e[grad_of[0]].astype(jnp.float32).sum(), e
                    (loss, env), grads = jax.value_and_grad(
                        loss_fn, has_aux=True)(
                        [param_arrays[k] for k in train_pos])
                    # expose PARAM@GRAD vars for fetching
                    pairs = getattr(self, "_grad_pairs", None)
                    if pairs:
                        gmap = {pv.var_id: gv.var_id for pv, gv in pairs}
                        for pos, g in zip(train_pos, grads):
                            vid = param_ids[pos]
                            if vid in gmap:
                                env[gmap[vid]] = g
                    env = apply_var_grads(env, feed_arrays, param_arrays)
                    fetches = [env.get(i) for i in fetch_ids]
                    return fetches, grads
                env = replay(env)
                env = apply_var_grads(env, feed_arrays, param_arrays)
                return [env.get(i) for i in fetch_ids], None
        return pure, param_ids, train_pos


_default_main = Program()
_default_startup = Program()
_guard_stack: List[Tuple[Program, Program]] = []


def default_main_program() -> Program:
    if _guard_stack:
        return _guard_stack[-1][0]
    return _default_main


def default_startup_program() -> Program:
    if _guard_stack:
        return _guard_stack[-1][1]
    return _default_startup


def _static_tracer(op_type, fn, args, kwargs):
    prog = default_main_program()
    return prog.add_op(op_type, fn, args, kwargs)


class program_guard:
    def __init__(self, main_program=None, startup_program=None):
        self.main = main_program if main_program is not None else Program()
        self.startup = startup_program if startup_program is not None \
            else Program()

    def __enter__(self):
        _guard_stack.append((self.main, self.startup))
        _registry.set_static_tracer(_static_tracer)
        return self.main, self.startup

    def __exit__(self, *exc):
        _guard_stack.pop()
        if not _guard_stack:
            _registry.set_static_tracer(None)


def data(name, shape, dtype="float32", lod_level=0):
    """Feed placeholder (paddle.static.data)."""
    prog = default_main_program()
    v = Var(prog, name, shape, dtype, kind="feed")
    prog.feeds.append(v.var_id)
    return v


def append_backward(loss, parameter_list=None, no_grad_set=None,
                    callbacks=None):
    """Mark loss for gradient computation; returns (param, grad_var) pairs.

    Grad vars are materialized at Executor.run time via jax.value_and_grad
    over the replayed program (backward.py:1337 analogue — the grad-op
    chain is jax's, not hand-appended)."""
    prog = loss.program if isinstance(loss, Var) else default_main_program()
    if int(np.prod(loss._data.shape)) != 1:
        raise EnforceNotMet(
            f"append_backward loss must be a scalar, got shape "
            f"{tuple(loss._data.shape)} (reference backward.py enforces "
            "loss.shape == [1])", op_type="append_backward")
    prog._grad_target = loss.var_id

    def resolve_name(item):
        if isinstance(item, str):
            return item
        nm = getattr(item, "name", None)
        if nm:
            return nm
        for vid, p in prog.params.items():  # unnamed Parameter: identity
            if p is item:
                return prog.vars[vid].name
        return None
    skip = {resolve_name(i) for i in (no_grad_set or ())} - {None}
    keep_names = None
    if parameter_list is not None:
        keep_names = {resolve_name(p) for p in parameter_list} - {None}
    pairs = []
    for vid, p in prog.params.items():
        if vid in prog.buffer_ids:
            continue
        name = prog.vars[vid].name
        if name in skip or (keep_names is not None
                            and name not in keep_names):
            continue
        gv = Var(prog, f"{name}@GRAD", p._data.shape,
                 p._data.dtype, kind="grad")
        pairs.append((prog.vars[vid], gv))
    prog._grad_pairs = pairs
    return pairs


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    """Static d(targets)/d(inputs) for ARBITRARY program vars
    (backward.py:1932 `paddle.static.gradients` analogue).

    Returns grad Vars (name `<input>@GRAD`) fetchable through
    Executor.run. inputs may be feeds, params, or intermediates — for an
    intermediate the graph is cut at that var (its upstream is treated
    as constant), matching the reference's grad semantics.
    """
    targets = targets if isinstance(targets, (list, tuple)) else [targets]
    inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    prog = targets[0].program
    skip = {item if isinstance(item, str) else item.name
            for item in (no_grad_set or ())}
    inputs = [v for v in inputs if v.name not in skip]
    if target_gradients is not None:
        tg = target_gradients if isinstance(target_gradients,
                                            (list, tuple)) \
            else [target_gradients]
    else:
        tg = [None] * len(targets)
    grad_vars = []
    for v in inputs:
        gv = Var(prog, f"{v.name}@GRAD", v._data.shape, v._data.dtype,
                 kind="grad")
        grad_vars.append(gv)
    spec = {
        "targets": [t.var_id for t in targets],
        "inputs": [v.var_id for v in inputs],
        "grad_vars": [g.var_id for g in grad_vars],
        "tgrads": [None if g is None else np.asarray(
            g._data if isinstance(g, Tensor) else g) for g in tg],
    }
    prog._var_grads = getattr(prog, "_var_grads", [])
    prog._var_grads.append(spec)
    return grad_vars


class Executor:
    """Feed/fetch runner (executor.py:475 analogue). Compiles the whole
    program per feed-shape signature."""

    def __init__(self, place=None):
        self.place = place
        self._cache: Dict[Any, Any] = {}
        self._computable_cache: Dict[Any, set] = {}

    # -- Dataset-driven loops (trainer.h:53 / executor.py
    #    train_from_dataset capability; see io/fleet_dataset.py) --------------
    def train_from_dataset(self, program=None, dataset=None, scope=None,
                           thread=0, debug=False, fetch_list=None,
                           fetch_info=None, print_period=100):
        """Drive the program over a slot Dataset (QueueDataset /
        InMemoryDataset). One compiled step per feed shape; the C++
        feeder's threads replace the reference's hogwild workers (the
        update is exact, not racy — see io/fleet_dataset.py)."""
        return self._run_from_dataset(program, dataset, fetch_list,
                                      fetch_info, print_period,
                                      train=True)

    def infer_from_dataset(self, program=None, dataset=None, scope=None,
                           thread=0, debug=False, fetch_list=None,
                           fetch_info=None, print_period=100):
        """Forward-only sweep over a Dataset (reference
        infer_from_dataset); pass an eval program
        (program.clone(for_test=True) with no optimizer attached)."""
        return self._run_from_dataset(program, dataset, fetch_list,
                                      fetch_info, print_period,
                                      train=False)

    def _run_from_dataset(self, program, dataset, fetch_list, fetch_info,
                          print_period, train):
        if dataset is None:
            raise EnforceNotMet("dataset must be provided",
                                op_type="train_from_dataset")
        prog = program if program is not None else default_main_program()
        if not train and prog._optimize is not None:
            raise EnforceNotMet(
                "infer_from_dataset got a program with an optimizer "
                "attached; pass program.clone(for_test=True)",
                op_type="infer_from_dataset")
        if train and prog._optimize is None:
            raise EnforceNotMet(
                "train_from_dataset needs a program with an optimizer "
                "(call optimizer.minimize(loss) inside the "
                "program_guard) — otherwise the sweep would be forward-"
                "only", op_type="train_from_dataset")
        feed_names = {prog.vars[i].name for i in prog.feeds}
        fetch_list = fetch_list or []
        names = (fetch_info or
                 [getattr(f, "name", str(f)) for f in fetch_list])
        step = 0
        last_fetch = None
        for batch in dataset:
            feed = {k: v for k, v in batch.items() if k in feed_names}
            missing = feed_names - set(feed)
            if missing:
                raise EnforceNotMet(
                    f"dataset slots {sorted(batch)} do not cover "
                    f"program feeds {sorted(missing)} (set_use_var with "
                    "the program's data() vars)",
                    op_type="train_from_dataset")
            last_fetch = self.run(prog, feed=feed, fetch_list=fetch_list)
            step += 1
            if fetch_list and print_period and step % print_period == 0:
                vals = ", ".join(
                    f"{n}={np.asarray(v).ravel()[:4]}"
                    for n, v in zip(names, last_fetch))
                print(f"[{'train' if train else 'infer'}_from_dataset] "
                      f"step {step}: {vals}")
        return last_fetch

    def run(self, program=None, feed=None, fetch_list=None,
            return_numpy=True):
        prog = program if program is not None else default_main_program()
        feed = feed or {}
        fetch_list = fetch_list or []
        if not prog.ops and not prog.params:
            return []  # empty program (startup with no ops)

        fetch_ids = []
        for f in fetch_list:
            if isinstance(f, Var):
                fetch_ids.append(f.var_id)
            elif isinstance(f, str):
                fetch_ids.append(prog.var_by_name(f).var_id)
            else:
                raise NotFoundError(f"bad fetch entry {f!r}")

        train = prog._optimize is not None
        grad_target = getattr(prog, "_grad_target", None)
        grad_ids = [grad_target] if (train or grad_target is not None) \
            else None
        if train:
            grad_ids = [prog._optimize[1].var_id]

        # Every fetch must be statically computable by this program —
        # feed, captured param/buffer, op output, or a grad var whose
        # backward section is present. A silent None here hid the
        # lost-backward serialization bug for a whole round; the
        # reference's enforce machinery (enforce.h, op_call_stack) turns
        # exactly this class into a loud NotFoundError. Cached per
        # program shape: run() is the per-batch hot path and the set is
        # invariant for a given (program, op count, grad sections).
        comp_key = (id(prog), len(prog.ops), bool(grad_ids),
                    len(getattr(prog, "_var_grads", ())))
        computable = self._computable_cache.get(comp_key)
        if computable is None:
            computable = set(prog.feeds) | set(prog.params.keys())
            for node in prog.ops:
                computable.update(node.out_ids)
            if grad_ids:
                computable.update(
                    gv.var_id
                    for _, gv in getattr(prog, "_grad_pairs", ()))
            for s in getattr(prog, "_var_grads", ()):
                computable.update(s["grad_vars"])
            self._computable_cache[comp_key] = computable
        for fid in fetch_ids:
            if fid not in computable:
                v = prog.vars.get(fid)
                name = getattr(v, "name", None) or f"<id {fid}>"
                kind = getattr(v, "kind", "?")
                hint = ""
                if kind == "grad":
                    hint = ("; this is a grad var but the program has no "
                            "active backward section (append_backward/"
                            "gradients bookkeeping absent — was the "
                            "program serialized by an older framework?)")
                raise NotFoundError(
                    f"fetch var '{name}' (id {fid}, kind={kind}) is not "
                    f"producible by this program: it is not a feed, "
                    f"captured parameter, or output of any of its "
                    f"{len(prog.ops)} ops{hint}",
                    op_type="fetch")

        # BN running stats etc.: fetch the updated values and write them
        # back into the live buffers after the run
        buffer_writes = list(getattr(prog, "_buffer_writes", ()))
        fetch_ids_full = list(fetch_ids) + [v for _, v in buffer_writes]

        sig = (id(prog), len(prog.ops), tuple(sorted(feed)), train,
               tuple(fetch_ids_full),
               tuple((k, np.asarray(v).shape) for k, v in sorted(
                   feed.items())))
        entry = self._cache.get(sig)
        if entry is None:
            pure, param_ids, train_pos = prog.build_callable(
                fetch_ids_full, grad_ids)
            if train:
                optimizer = prog._optimize[0]

                def train_fn(feed_arrays, param_arrays, opt_state, lr,
                             key):
                    fetches, grads = pure(feed_arrays, param_arrays, key)
                    t_arrays = [param_arrays[k] for k in train_pos]
                    new_t, opt_t = optimizer.apply_gradients_tree(
                        t_arrays, list(grads), opt_state, lr=lr)
                    new_params = list(param_arrays)
                    for k, a in zip(train_pos, new_t):
                        new_params[k] = a
                    return fetches, new_params, opt_t
                jitted = jax.jit(train_fn, donate_argnums=(1, 2))
                entry = ("train", jitted, param_ids)
            else:
                jitted = jax.jit(pure)
                entry = ("infer", jitted, param_ids)
            self._cache[sig] = entry

        kind, jitted, param_ids = entry
        feed_arrays = []
        for vid in prog.feeds:
            nm = prog.vars[vid].name
            if nm not in feed:
                raise NotFoundError(f"missing feed '{nm}'")
            feed_arrays.append(jnp.asarray(np.asarray(feed[nm])))
        param_arrays = [prog.params[i]._data for i in param_ids]
        key = next_key()
        if kind == "train":
            optimizer = prog._optimize[0]
            # accumulator tree lives on the Program (not this cache) so
            # to_bytes mid-training captures it and a loaded program
            # resumes with exact moments
            if prog._opt_state is None:
                prog._opt_state = optimizer.init_state_tree(
                    [prog.params[i]._data for i in param_ids
                     if i not in prog.buffer_ids])
            lr = jnp.asarray(optimizer.get_lr(), jnp.float32)
            fetches, new_params, new_opt = jitted(
                feed_arrays, param_arrays, prog._opt_state, lr, key)
            for vid, arr in zip(param_ids, new_params):
                prog.params[vid]._data = arr
            prog._opt_state = new_opt
        else:
            fetches, _ = jitted(feed_arrays, param_arrays, key)
        n_user = len(fetch_ids)
        for (bvid, _), val in zip(buffer_writes, fetches[n_user:]):
            if val is not None:
                prog.params[bvid]._data = jnp.asarray(val)
        fetches = fetches[:n_user]
        if return_numpy:
            return [np.asarray(f) if f is not None else None
                    for f in fetches]
        return [Tensor(f) if f is not None else None for f in fetches]

    def close(self):
        self._cache.clear()
        self._computable_cache.clear()
