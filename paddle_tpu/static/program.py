"""Static Program / Executor (declarative path).

Reference: ProgramDesc + Executor feed/fetch
(/root/reference/paddle/fluid/framework/framework.proto:202,
framework/executor.cc:289, python/paddle/fluid/executor.py:475,
backward.py:1337 append_backward).

TPU-first redesign: a Program is a captured op graph — every op routed
through the registry while a program_guard is active appends an OpNode
(pure fn + symbolic vars, shapes inferred with jax.eval_shape — the
InferShape pass). Executor.run lowers the whole program (plus appended
backward/optimizer stages) to ONE jitted function keyed by feed shapes —
the "Program → XLA executable" pipeline replacing the reference's per-op
interpreter loop. Parameters created inside the guard are captured
constants whose storage the Executor updates in place after optimizer
programs run.
"""
from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core import dtypes as _dtypes
from ..core.enforce import EnforceNotMet, NotFoundError
from ..core.generator import key_scope, next_key
from ..framework import Parameter, Tensor
from ..ops import registry as _registry

__all__ = ["Program", "program_guard", "default_main_program",
           "default_startup_program", "data", "Executor", "append_backward"]

_static_mode = False


class Var(Tensor):
    """Symbolic variable: carries aval only (no data). Lives in a Program.

    Subclasses Tensor so every op / layer treats it uniformly; `_data`
    holds a zero placeholder of the right aval for shape inference."""

    def __init__(self, program, name, shape, dtype, kind="intermediate"):
        dtype = _dtypes.convert_dtype(dtype)
        shape = tuple(1 if s is None or s < 0 else int(s) for s in shape)
        super().__init__(jnp.zeros(shape, dtype), stop_gradient=True)
        self.program = program
        self.name = name
        self.kind = kind  # feed | param | intermediate | fetch
        self.var_id = program._new_var_id(self)

    def __repr__(self):
        return (f"Var(name={self.name}, shape={self.shape}, "
                f"dtype={self.dtype}, kind={self.kind})")


class OpNode:
    __slots__ = ("fn", "in_ids", "const_args", "kwargs", "out_ids",
                 "op_type", "n_outs", "multi")

    def __init__(self, op_type, fn, in_ids, const_args, kwargs, out_ids,
                 multi):
        self.op_type = op_type
        self.fn = fn
        self.in_ids = in_ids          # positional slots: var_id or None
        self.const_args = const_args  # positional slots: constants
        self.kwargs = kwargs
        self.out_ids = out_ids
        self.multi = multi


class Program:
    """Captured graph (ProgramDesc analogue)."""

    def __init__(self):
        self.vars: Dict[int, Var] = {}
        self.var_names: Dict[str, int] = {}
        self.ops: List[OpNode] = []
        self.feeds: List[int] = []
        self.params: Dict[int, Parameter] = {}  # var_id -> live Parameter
        self._counter = 0
        self._optimize = None  # (optimizer, loss_var, grad_map)
        self.random_seed = None

    def _new_var_id(self, var) -> int:
        vid = self._counter
        self._counter += 1
        self.vars[vid] = var
        if var.name:
            self.var_names[var.name] = vid
        return vid

    def var_by_name(self, name) -> Var:
        if name not in self.var_names:
            raise NotFoundError(f"var '{name}' not in program")
        return self.vars[self.var_names[name]]

    def global_block(self):
        return self

    def all_parameters(self):
        return list(self.params.values())

    def list_vars(self):
        return list(self.vars.values())

    def clone(self, for_test=False):
        import copy
        p = Program()
        p.vars = dict(self.vars)
        p.var_names = dict(self.var_names)
        p.ops = list(self.ops)
        p.feeds = list(self.feeds)
        p.params = dict(self.params)
        p._counter = self._counter
        return p

    # -- capture ------------------------------------------------------------
    def capture_param(self, t: Tensor) -> Var:
        """Register a live Parameter/Tensor used by the program."""
        for vid, p in self.params.items():
            if p is t:
                return self.vars[vid]
        name = t.name or f"param_{len(self.params)}"
        v = Var(self, name, t._data.shape, t._data.dtype, kind="param")
        self.params[v.var_id] = t
        return v

    def add_op(self, op_type, fn, args, kwargs):
        in_ids, const_args = [], []
        for a in args:
            if isinstance(a, Var) and a.program is self:
                in_ids.append(a.var_id)
                const_args.append(None)
            elif isinstance(a, Tensor):
                pv = self.capture_param(a)
                in_ids.append(pv.var_id)
                const_args.append(None)
            else:
                in_ids.append(None)
                const_args.append(a)
        kw = {}
        for k, v in kwargs.items():
            if isinstance(v, Var) and v.program is self:
                raise EnforceNotMet(
                    "tensor kwargs not supported in static capture; pass "
                    "positionally", op_type=op_type)
            kw[k] = v._data if isinstance(v, Tensor) else v

        # InferShape via eval_shape on the pure fn
        def shaped(*xs):
            full = [x if x is not None else c
                    for x, c in zip(xs, const_args)]
            res = fn(*full, **kw)
            return tuple(res) if isinstance(res, (list, tuple)) else res

        in_avals = [
            jax.ShapeDtypeStruct(self.vars[i]._data.shape,
                                 self.vars[i]._data.dtype)
            if i is not None else None
            for i in in_ids
        ]
        out_aval = jax.eval_shape(shaped, *in_avals)
        multi = isinstance(out_aval, tuple)
        outs = list(out_aval) if multi else [out_aval]
        out_vars = [Var(self, f"tmp_{self._counter}", o.shape, o.dtype)
                    for o in outs]
        self.ops.append(OpNode(op_type, fn, in_ids, const_args, kw,
                               [v.var_id for v in out_vars], multi))
        if multi:
            return tuple(out_vars)
        return out_vars[0]

    # -- replay -------------------------------------------------------------
    def build_callable(self, fetch_ids: Sequence[int],
                       grad_of: Optional[Sequence[int]] = None):
        """pure(feed_arrays, param_arrays, key) -> (fetches, grads?)"""
        feeds = list(self.feeds)
        param_ids = list(self.params.keys())
        ops = list(self.ops)

        def replay(env):
            for node in ops:
                ins = [env[i] if i is not None else c
                       for i, c in zip(node.in_ids, node.const_args)]
                res = node.fn(*ins, **node.kwargs)
                res = tuple(res) if isinstance(res, (list, tuple)) else \
                    (res,)
                for vid, r in zip(node.out_ids, res):
                    env[vid] = r
            return env

        def pure(feed_arrays, param_arrays, key):
            with key_scope(key):
                env = {}
                for vid, a in zip(feeds, feed_arrays):
                    env[vid] = a
                for vid, a in zip(param_ids, param_arrays):
                    env[vid] = a
                if grad_of:
                    def loss_fn(p_arrays):
                        e = dict(env)
                        for vid, a in zip(param_ids, p_arrays):
                            e[vid] = a
                        e = replay(e)
                        return e[grad_of[0]].astype(jnp.float32).sum(), e
                    (loss, env), grads = jax.value_and_grad(
                        loss_fn, has_aux=True)(list(param_arrays))
                    # expose PARAM@GRAD vars for fetching
                    pairs = getattr(self, "_grad_pairs", None)
                    if pairs:
                        gmap = {pv.var_id: gv.var_id for pv, gv in pairs}
                        for vid, g in zip(param_ids, grads):
                            if vid in gmap:
                                env[gmap[vid]] = g
                    fetches = [env.get(i) for i in fetch_ids]
                    return fetches, grads
                env = replay(env)
                return [env.get(i) for i in fetch_ids], None
        return pure, param_ids


_default_main = Program()
_default_startup = Program()
_guard_stack: List[Tuple[Program, Program]] = []


def default_main_program() -> Program:
    if _guard_stack:
        return _guard_stack[-1][0]
    return _default_main


def default_startup_program() -> Program:
    if _guard_stack:
        return _guard_stack[-1][1]
    return _default_startup


def _static_tracer(op_type, fn, args, kwargs):
    prog = default_main_program()
    return prog.add_op(op_type, fn, args, kwargs)


class program_guard:
    def __init__(self, main_program=None, startup_program=None):
        self.main = main_program if main_program is not None else Program()
        self.startup = startup_program if startup_program is not None \
            else Program()

    def __enter__(self):
        _guard_stack.append((self.main, self.startup))
        _registry.set_static_tracer(_static_tracer)
        return self.main, self.startup

    def __exit__(self, *exc):
        _guard_stack.pop()
        if not _guard_stack:
            _registry.set_static_tracer(None)


def data(name, shape, dtype="float32", lod_level=0):
    """Feed placeholder (paddle.static.data)."""
    prog = default_main_program()
    v = Var(prog, name, shape, dtype, kind="feed")
    prog.feeds.append(v.var_id)
    return v


def append_backward(loss, parameter_list=None, no_grad_set=None,
                    callbacks=None):
    """Mark loss for gradient computation; returns (param, grad_var) pairs.

    Grad vars are materialized at Executor.run time via jax.value_and_grad
    over the replayed program (backward.py:1337 analogue — the grad-op
    chain is jax's, not hand-appended)."""
    prog = loss.program if isinstance(loss, Var) else default_main_program()
    prog._grad_target = loss.var_id
    pairs = []
    for vid, p in prog.params.items():
        gv = Var(prog, f"{prog.vars[vid].name}@GRAD", p._data.shape,
                 p._data.dtype, kind="grad")
        pairs.append((prog.vars[vid], gv))
    prog._grad_pairs = pairs
    return pairs


class Executor:
    """Feed/fetch runner (executor.py:475 analogue). Compiles the whole
    program per feed-shape signature."""

    def __init__(self, place=None):
        self.place = place
        self._cache: Dict[Any, Any] = {}

    def run(self, program=None, feed=None, fetch_list=None,
            return_numpy=True):
        prog = program if program is not None else default_main_program()
        feed = feed or {}
        fetch_list = fetch_list or []
        if not prog.ops and not prog.params:
            return []  # empty program (startup with no ops)

        fetch_ids = []
        for f in fetch_list:
            if isinstance(f, Var):
                fetch_ids.append(f.var_id)
            elif isinstance(f, str):
                fetch_ids.append(prog.var_by_name(f).var_id)
            else:
                raise NotFoundError(f"bad fetch entry {f!r}")

        train = prog._optimize is not None
        grad_target = getattr(prog, "_grad_target", None)
        grad_ids = [grad_target] if (train or grad_target is not None) \
            else None
        if train:
            grad_ids = [prog._optimize[1].var_id]

        sig = (id(prog), len(prog.ops), tuple(sorted(feed)), train,
               tuple(fetch_ids),
               tuple((k, np.asarray(v).shape) for k, v in sorted(
                   feed.items())))
        entry = self._cache.get(sig)
        if entry is None:
            pure, param_ids = prog.build_callable(fetch_ids, grad_ids)
            if train:
                optimizer = prog._optimize[0]

                def train_fn(feed_arrays, param_arrays, opt_state, lr, key):
                    fetches, grads = pure(feed_arrays, param_arrays, key)
                    params_t, opt_t = optimizer.apply_gradients_tree(
                        list(param_arrays), list(grads), opt_state, lr=lr)
                    return fetches, params_t, opt_t
                jitted = jax.jit(train_fn, donate_argnums=(1, 2))
                opt_state = [prog._optimize[0].init_state(
                    prog.params[i]._data) for i in param_ids]
                entry = ("train", jitted, param_ids, opt_state)
            else:
                jitted = jax.jit(pure)
                entry = ("infer", jitted, param_ids, None)
            self._cache[sig] = entry

        kind, jitted, param_ids, opt_state = entry
        feed_arrays = []
        for vid in prog.feeds:
            nm = prog.vars[vid].name
            if nm not in feed:
                raise NotFoundError(f"missing feed '{nm}'")
            feed_arrays.append(jnp.asarray(np.asarray(feed[nm])))
        param_arrays = [prog.params[i]._data for i in param_ids]
        key = next_key()
        if kind == "train":
            optimizer = prog._optimize[0]
            lr = jnp.asarray(optimizer.get_lr(), jnp.float32)
            fetches, new_params, new_opt = jitted(
                feed_arrays, param_arrays, opt_state, lr, key)
            for vid, arr in zip(param_ids, new_params):
                prog.params[vid]._data = arr
            self._cache[sig] = (kind, jitted, param_ids, new_opt)
        else:
            fetches, _ = jitted(feed_arrays, param_arrays, key)
        if return_numpy:
            return [np.asarray(f) if f is not None else None
                    for f in fetches]
        return [Tensor(f) if f is not None else None for f in fetches]

    def close(self):
        self._cache.clear()
