"""Tensor creation ops.

Parity surface: python/paddle/tensor/creation.py in the reference. These do
not take tensor inputs so they bypass the tape (created tensors are leaves
with stop_gradient=True, as in paddle).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core import dtypes as _dtypes
from ..core.generator import next_key
from ..framework import Tensor, _unwrap, to_tensor

__all__ = [
    "set_printoptions",
    "zeros", "ones", "full", "empty", "zeros_like", "ones_like", "full_like",
    "empty_like", "arange", "linspace", "logspace", "eye", "diag", "diagflat",
    "tril", "triu", "meshgrid", "assign", "clone_", "rand", "randn",
    "randint", "randperm", "uniform", "normal", "bernoulli", "multinomial",
    "standard_normal", "tril_indices", "triu_indices", "one_hot",
    "numel", "create_parameter",
]


def _dt(dtype, default=None):
    if dtype is None:
        return default or _dtypes.get_default_dtype()
    return _dtypes.convert_dtype(dtype)


def _shape(shape):
    if isinstance(shape, Tensor):
        shape = shape.tolist()
    if isinstance(shape, (int, np.integer)):
        return (int(shape),)
    return tuple(int(_unwrap(s)) if not isinstance(s, int) else s
                 for s in shape)


def zeros(shape, dtype=None, name=None):
    return Tensor(jnp.zeros(_shape(shape), _dt(dtype)))


def ones(shape, dtype=None, name=None):
    return Tensor(jnp.ones(_shape(shape), _dt(dtype)))


def full(shape, fill_value, dtype=None, name=None):
    fill = _unwrap(fill_value)
    return Tensor(jnp.full(_shape(shape), fill, _dt(dtype)))


def empty(shape, dtype=None, name=None):
    return zeros(shape, dtype)


def zeros_like(x, dtype=None, name=None):
    return Tensor(jnp.zeros_like(_unwrap(x), dtype=_dt(dtype, np.dtype(
        _unwrap(x).dtype))))


def ones_like(x, dtype=None, name=None):
    return Tensor(jnp.ones_like(_unwrap(x), dtype=_dt(dtype, np.dtype(
        _unwrap(x).dtype))))


def full_like(x, fill_value, dtype=None, name=None):
    a = _unwrap(x)
    return Tensor(jnp.full_like(a, _unwrap(fill_value),
                                dtype=_dt(dtype, np.dtype(a.dtype))))


def empty_like(x, dtype=None, name=None):
    return zeros_like(x, dtype)


def arange(start=0, end=None, step=1, dtype=None, name=None):
    start, end, step = _unwrap(start), _unwrap(end), _unwrap(step)
    if end is None:
        start, end = 0, start
    if dtype is None:
        py = (start, end, step)
        dtype = (np.int64 if all(
            isinstance(v, (int, np.integer)) for v in py) else
            _dtypes.get_default_dtype())
    return Tensor(jnp.arange(start, end, step, dtype=_dtypes.convert_dtype(
        dtype)))


def linspace(start, stop, num, dtype=None, name=None):
    return Tensor(jnp.linspace(_unwrap(start), _unwrap(stop), int(num),
                               dtype=_dt(dtype)))


def logspace(start, stop, num, base=10.0, dtype=None, name=None):
    return Tensor(jnp.logspace(_unwrap(start), _unwrap(stop), int(num),
                               base=base, dtype=_dt(dtype)))


def eye(num_rows, num_columns=None, dtype=None, name=None):
    return Tensor(jnp.eye(num_rows, num_columns, dtype=_dt(dtype)))


def diag(x, offset=0, padding_value=0, name=None):
    a = _unwrap(x)
    if a.ndim == 1 and padding_value != 0:
        d = jnp.diag(a, k=offset)
        mask = jnp.eye(*d.shape, k=offset, dtype=bool)
        return Tensor(jnp.where(mask, d, padding_value))
    return Tensor(jnp.diag(a, k=offset))


def diagflat(x, offset=0, name=None):
    return Tensor(jnp.diagflat(_unwrap(x), k=offset))


def tril(x, diagonal=0, name=None):
    return _tape_unary(x, lambda a: jnp.tril(a, k=diagonal), "tril")


def triu(x, diagonal=0, name=None):
    return _tape_unary(x, lambda a: jnp.triu(a, k=diagonal), "triu")


def _tape_unary(x, fn, name):
    from .registry import run_op
    return run_op(name, fn, (x,), {})


def meshgrid(*args, **kwargs):
    arrays = [_unwrap(a) for a in (args[0] if len(args) == 1 and
              isinstance(args[0], (list, tuple)) else args)]
    return [Tensor(m) for m in jnp.meshgrid(*arrays, indexing="ij")]


def assign(x, output=None):
    a = _unwrap(x)
    if not isinstance(a, jax.Array):
        a = jnp.asarray(a)
    if output is not None:
        output.set_value(a)
        return output
    return Tensor(a)


def clone_(x):
    return Tensor(_unwrap(x))


def numel(x, name=None):
    return Tensor(jnp.asarray(_unwrap(x).size, dtype=jnp.int64))


def one_hot(x, num_classes, name=None):
    return Tensor(jax.nn.one_hot(_unwrap(x), num_classes,
                                 dtype=_dtypes.get_default_dtype()))


def tril_indices(row, col, offset=0, dtype="int64"):
    r, c = np.tril_indices(row, offset, col)
    return Tensor(jnp.asarray(np.stack([r, c]), dtype=_dt(dtype, np.int64)))


def triu_indices(row, col=None, offset=0, dtype="int64"):
    r, c = np.triu_indices(row, offset, col if col is not None else row)
    return Tensor(jnp.asarray(np.stack([r, c]), dtype=_dt(dtype, np.int64)))


# -- random creation --------------------------------------------------------

def rand(shape, dtype=None, name=None):
    return uniform(shape, dtype, min=0.0, max=1.0)


def uniform(shape, dtype=None, min=-1.0, max=1.0, seed=0, name=None):
    key = jax.random.key(seed) if seed else next_key()
    return Tensor(jax.random.uniform(key, _shape(shape), _dt(dtype),
                                     minval=_unwrap(min), maxval=_unwrap(max)))


def randn(shape, dtype=None, name=None):
    key = next_key()
    return Tensor(jax.random.normal(key, _shape(shape), _dt(dtype)))


standard_normal = randn


def normal(mean=0.0, std=1.0, shape=None, name=None):
    mean_a, std_a = _unwrap(mean), _unwrap(std)
    if shape is None:
        shape = jnp.broadcast_shapes(jnp.shape(mean_a), jnp.shape(std_a))
    n = jax.random.normal(next_key(), _shape(shape),
                          _dtypes.get_default_dtype())
    return Tensor(n * std_a + mean_a)


def randint(low=0, high=None, shape=(1,), dtype="int64", name=None):
    if high is None:
        low, high = 0, low
    return Tensor(jax.random.randint(next_key(), _shape(shape), low, high,
                                     dtype=_dt(dtype, np.int64)))


def randperm(n, dtype="int64", name=None):
    return Tensor(jax.random.permutation(next_key(), n).astype(
        _dt(dtype, np.int64)))


def bernoulli(x, name=None):
    a = _unwrap(x)
    return Tensor(jax.random.bernoulli(next_key(), a).astype(a.dtype))


def multinomial(x, num_samples=1, replacement=False, name=None):
    a = _unwrap(x)
    logits = jnp.log(jnp.maximum(a, 1e-30))
    if replacement:
        out = jax.random.categorical(next_key(), logits, axis=-1,
                                     shape=(*a.shape[:-1], num_samples))
    else:
        key = next_key()
        g = jax.random.gumbel(key, a.shape)
        _, out = jax.lax.top_k(logits + g, num_samples)
    return Tensor(out.astype(jnp.int64))


def create_parameter(shape, dtype=None, name=None, default_initializer=None):
    from ..framework import Parameter
    if default_initializer is not None:
        data = default_initializer(_shape(shape), _dt(dtype))
    else:
        data = jnp.zeros(_shape(shape), _dt(dtype))
    return Parameter(data, name=name)


def set_printoptions(precision=None, threshold=None, edgeitems=None,
                     sci_mode=None, linewidth=None):
    """paddle.set_printoptions (ref tensor printing config): Tensor
    __repr__ renders through numpy, so this forwards to
    numpy.set_printoptions (sci_mode -> suppress)."""
    import numpy as _np
    kw = {}
    if precision is not None:
        kw["precision"] = int(precision)
    if threshold is not None:
        kw["threshold"] = int(threshold)
    if edgeitems is not None:
        kw["edgeitems"] = int(edgeitems)
    if linewidth is not None:
        kw["linewidth"] = int(linewidth)
    if sci_mode is not None:
        kw["suppress"] = not bool(sci_mode)
    _np.set_printoptions(**kw)
