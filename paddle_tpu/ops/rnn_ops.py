"""Op-level RNN family + CPU-fusion ops from the reference.

Reference specs: rnn_op.h / cudnn_lstm_op.cu.cc (multi-layer bidirectional
LSTM/GRU/RNN with dropout + sequence_length masking), lstm_op.h (single
fused layer), lstm_unit_op.h, gru_unit_op.h, fusion_lstm_op.cc,
fusion_gru_op.cc, fusion_repeated_fc_relu_op.cc,
fusion_seqconv_eltadd_relu_op.cc, fusion_seqexpand_concat_fc_op.cc,
fusion_seqpool_concat_op.cc, fusion_squared_mat_sub_op.cc, batch_fc_op.cc,
rank_attention_op.cc (all under /root/reference/paddle/fluid/operators/).

TPU design: every "fusion_" op in the reference exists because CPU
dispatch of the unfused graph is slow; under XLA the composition compiles
to the same fused program, so these ops are thin compositions kept for
API/capability parity — the time loop itself is one lax.scan (one XLA
while op), precomputing x@W_ih for the whole sequence up front (the same
trick fusion_lstm's batched GEMM does). Gate order is (i, f, g, o) —
matching nn/layer/rnn.py cells — not the reference's (i, c, f, o); the
weights are this framework's own, so only internal consistency matters.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..framework import Tensor, _unwrap
from .registry import register_op

__all__ = [
    "rnn", "lstm", "lstm_unit", "gru_unit", "fusion_lstm", "fusion_gru",
    "fusion_repeated_fc_relu", "fusion_seqconv_eltadd_relu",
    "fusion_seqexpand_concat_fc", "fusion_seqpool_concat",
    "fusion_squared_mat_sub", "batch_fc", "rank_attention",
]


def _lstm_step(xg, h, c, whh):
    gates = xg + h @ whh.T
    i, f, g, o = jnp.split(gates, 4, axis=-1)
    c2 = jax.nn.sigmoid(f) * c + jax.nn.sigmoid(i) * jnp.tanh(g)
    h2 = jax.nn.sigmoid(o) * jnp.tanh(c2)
    return h2, c2


def _gru_step(xg, h, whh, bhh):
    gh = h @ whh.T + bhh
    ri, zi, ni = jnp.split(xg, 3, axis=-1)
    rh, zh, nh = jnp.split(gh, 3, axis=-1)
    r = jax.nn.sigmoid(ri + rh)
    z = jax.nn.sigmoid(zi + zh)
    n = jnp.tanh(ni + r * nh)
    return (1 - z) * n + z * h


def _reverse_valid(x_tmajor, lengths):
    """Reverse each sequence within its valid prefix: position p maps to
    lengths[b]-1-p for p < lengths[b], identity past it (padding stays in
    place). Self-inverse, so the same map un-reverses scan outputs."""
    t_steps = x_tmajor.shape[0]
    t = jnp.arange(t_steps)[:, None]
    src = jnp.where(t < lengths[None, :], lengths[None, :] - 1 - t, t)
    return jnp.take_along_axis(x_tmajor, src[:, :, None], axis=0)


def _scan_layer(x_tmajor, h0, c0, wih, whh, bih, bhh, mode, lengths):
    """One direction of one layer over [T,B,D]; length-masked carries."""
    t_steps = x_tmajor.shape[0]
    # hoist the input projection out of the scan: one big GEMM on the MXU
    xg = x_tmajor @ wih.T + bih
    if mode == "LSTM":
        xg = xg + bhh

    tpos = jnp.arange(t_steps)

    def body(carry, inp):
        t, xg_t = inp
        h, c = carry
        if mode == "LSTM":
            h2, c2 = _lstm_step(xg_t, h, c, whh)
        elif mode == "GRU":
            h2, c2 = _gru_step(xg_t, h, whh, bhh), c
        else:
            z = xg_t + h @ whh.T + bhh
            h2 = jnp.tanh(z) if mode == "RNN_TANH" else jax.nn.relu(z)
            c2 = c
        if lengths is not None:
            live = (t < lengths)[:, None]
            h2 = jnp.where(live, h2, h)
            c2 = jnp.where(live, c2, c)
        return (h2, c2), h2

    (hT, cT), outs = jax.lax.scan(body, (h0, c0), (tpos, xg))
    if lengths is not None:
        mask = (tpos[:, None] < lengths[None, :])[:, :, None]
        outs = outs * mask.astype(outs.dtype)
    return outs, hT, cT


@register_op("rnn")
def rnn(x, *weights, mode="LSTM", num_layers=1, is_bidirec=False,
        hidden_size=None, sequence_length=None, initial_states=None,
        dropout_prob=0.0, dropout_key=None, time_major=False, name=None):
    """The reference `rnn` op (rnn_op.h; also the capability of
    cudnn_lstm/lstmp/gru ops): multi-layer, optionally bidirectional
    LSTM/GRU/RNN over a whole sequence in one compiled scan per
    layer-direction.

    weights: flat per (layer, direction): wih, whh, bih, bhh.
    Returns (out, h_final [L*D,B,H], c_final [L*D,B,H] (LSTM only)).
    """
    num_dir = 2 if is_bidirec else 1
    assert len(weights) == 4 * num_layers * num_dir, (
        f"expected {4 * num_layers * num_dir} weight arrays, "
        f"got {len(weights)}")
    xs = x if time_major else jnp.swapaxes(x, 0, 1)       # [T,B,D]
    b = xs.shape[1]
    h = weights[1].shape[-1]                              # whh [G*H, H]
    lengths = (jnp.asarray(sequence_length)
               if sequence_length is not None else None)

    finals_h, finals_c = [], []
    inp = xs
    for layer in range(num_layers):
        outs_dir = []
        for d in range(num_dir):
            base = 4 * (layer * num_dir + d)
            wih, whh, bih, bhh = weights[base:base + 4]
            if initial_states is not None:
                idx = layer * num_dir + d
                if mode == "LSTM":
                    h0, c0 = initial_states[0][idx], initial_states[1][idx]
                else:
                    h0 = initial_states[idx]
                    c0 = jnp.zeros((b, h), xs.dtype)
            else:
                h0 = jnp.zeros((b, h), xs.dtype)
                c0 = jnp.zeros((b, h), xs.dtype)
            if d == 1:
                seq = (_reverse_valid(inp, lengths)
                       if lengths is not None else jnp.flip(inp, 0))
            else:
                seq = inp
            outs, hT, cT = _scan_layer(seq, h0, c0, wih, whh, bih, bhh,
                                       mode, lengths)
            if d == 1:
                outs = (_reverse_valid(outs, lengths)
                        if lengths is not None else jnp.flip(outs, 0))
            outs_dir.append(outs)
            finals_h.append(hT)
            finals_c.append(cT)
        inp = (outs_dir[0] if num_dir == 1
               else jnp.concatenate(outs_dir, axis=-1))
        if dropout_prob > 0 and layer < num_layers - 1 \
                and dropout_key is not None:
            keep = jax.random.bernoulli(
                jax.random.fold_in(dropout_key, layer),
                1.0 - dropout_prob, inp.shape)
            inp = inp * keep.astype(inp.dtype) / (1.0 - dropout_prob)
    out = inp if time_major else jnp.swapaxes(inp, 0, 1)
    h_final = jnp.stack(finals_h, axis=0)
    if mode == "LSTM":
        return out, h_final, jnp.stack(finals_c, axis=0)
    return out, h_final


@register_op("lstm")
def lstm(x, wih, whh, bih, bhh, sequence_length=None, is_reverse=False,
         name=None):
    """Single fused LSTM layer (ref lstm_op.h / fusion_lstm_op.cc with the
    LoD input replaced by (padded [B,T,D], lengths)). Returns
    (hidden [B,T,H], cell_final [B,H], hidden_final [B,H])."""
    xs = jnp.swapaxes(x, 0, 1)
    lens = (jnp.asarray(sequence_length)
            if sequence_length is not None else None)
    if is_reverse:
        xs = _reverse_valid(xs, lens) if lens is not None \
            else jnp.flip(xs, 0)
    b = xs.shape[1]
    h = whh.shape[-1]
    outs, hT, cT = _scan_layer(
        xs, jnp.zeros((b, h), x.dtype), jnp.zeros((b, h), x.dtype),
        wih, whh, bih, bhh, "LSTM", lens)
    if is_reverse:
        outs = _reverse_valid(outs, lens) if lens is not None \
            else jnp.flip(outs, 0)
    return jnp.swapaxes(outs, 0, 1), hT, cT


@register_op("lstm_unit")
def lstm_unit(x, c_prev, forget_bias=0.0, name=None):
    """One LSTM cell tick on precomputed gates (ref lstm_unit_op.h):
    x [B,4H] split (i,f,g,o); f gets forget_bias. Returns (c, h)."""
    i, f, g, o = jnp.split(x, 4, axis=-1)
    c = (jax.nn.sigmoid(f + forget_bias) * c_prev
         + jax.nn.sigmoid(i) * jnp.tanh(g))
    h = jax.nn.sigmoid(o) * jnp.tanh(c)
    return c, h


@register_op("gru_unit")
def gru_unit(x, h_prev, weight, bias=None, origin_mode=False, name=None):
    """One GRU tick (ref gru_unit_op.h): x [B,3H] input projection,
    weight [H,3H] packs (W_update|W_reset in [:, :2H], W_cand in [:, 2H:]).
    Returns (hidden, reset_hidden_prev, gate)."""
    h_size = h_prev.shape[-1]
    g = x
    if bias is not None:
        g = g + bias
    ur = g[:, :2 * h_size] + h_prev @ weight[:, :2 * h_size]
    u, r = jnp.split(jax.nn.sigmoid(ur), 2, axis=-1)
    rhp = r * h_prev
    c = jnp.tanh(g[:, 2 * h_size:] + rhp @ weight[:, 2 * h_size:])
    if origin_mode:
        h = u * h_prev + (1 - u) * c
    else:
        h = (1 - u) * h_prev + u * c
    gate = jnp.concatenate([u, r, c], axis=-1)
    return h, rhp, gate


@register_op("fusion_lstm")
def fusion_lstm(x, wih, whh, bih, bhh, sequence_length=None,
                is_reverse=False, name=None):
    """ref fusion_lstm_op.cc — identical computation to `lstm` here (the
    reference fuses the per-sequence GEMMs; XLA already compiles `lstm`
    that way). Kept as its own registered op for parity."""
    return lstm.__pure_fn__(x, wih, whh, bih, bhh,
                            sequence_length=sequence_length,
                            is_reverse=is_reverse)


@register_op("fusion_gru")
def fusion_gru(x, wih, whh, bih, bhh, sequence_length=None,
               is_reverse=False, name=None):
    """ref fusion_gru_op.cc: single fused GRU layer over (padded,
    lengths). Returns (hidden [B,T,H], hidden_final [B,H])."""
    xs = jnp.swapaxes(x, 0, 1)
    lens = (jnp.asarray(sequence_length)
            if sequence_length is not None else None)
    if is_reverse:
        xs = _reverse_valid(xs, lens) if lens is not None \
            else jnp.flip(xs, 0)
    b = xs.shape[1]
    h = whh.shape[-1]
    outs, hT, _ = _scan_layer(
        xs, jnp.zeros((b, h), x.dtype), jnp.zeros((b, h), x.dtype),
        wih, whh, bih, bhh, "GRU", lens)
    if is_reverse:
        outs = _reverse_valid(outs, lens) if lens is not None \
            else jnp.flip(outs, 0)
    return jnp.swapaxes(outs, 0, 1), hT


@register_op("fusion_repeated_fc_relu")
def _fusion_repeated_fc_relu_impl(x, *wbs):
    """ref fusion_repeated_fc_relu_op.cc: x -> [fc+relu] * N. wbs is
    (w1, b1, w2, b2, ...)."""
    out = x
    for i in range(0, len(wbs), 2):
        out = jax.nn.relu(out @ wbs[i] + wbs[i + 1])
    return out


def fusion_repeated_fc_relu(x, weights, biases):
    flat = []
    for w, b in zip(weights, biases):
        flat += [w, b]
    return _fusion_repeated_fc_relu_impl(x, *flat)


@register_op("fusion_seqconv_eltadd_relu")
def fusion_seqconv_eltadd_relu(x, filt, bias, length=None, context_length=3,
                               context_start=None, name=None):
    """ref fusion_seqconv_eltadd_relu_op.cc: sequence_conv + bias + relu."""
    from .misc_ops import sequence_conv
    out = sequence_conv.__pure_fn__(x, filt, length=length,
                                    context_length=context_length,
                                    context_start=context_start)
    return jax.nn.relu(out + bias)


@register_op("fusion_seqexpand_concat_fc")
def _fusion_seqexpand_concat_fc_impl(ref, *rest, fc_act="relu"):
    """ref fusion_seqexpand_concat_fc_op.cc: broadcast per-sequence
    vectors over time, concat with the reference input, then fc+act.
    ref: [B,T,D0]; rest = (x1 [B,D1], ..., xk, w [(D0+ΣDi), M], b [M])."""
    xs, w, b = rest[:-2], rest[-2], rest[-1]
    t = ref.shape[1]
    cols = [ref] + [jnp.broadcast_to(v[:, None, :],
                                     (v.shape[0], t, v.shape[1]))
                    for v in xs]
    cat = jnp.concatenate(cols, axis=-1)
    out = cat @ w + b
    return jax.nn.relu(out) if fc_act == "relu" else jnp.tanh(out)


def fusion_seqexpand_concat_fc(ref, xs, w, b, fc_act="relu"):
    return _fusion_seqexpand_concat_fc_impl(ref, *xs, w, b, fc_act=fc_act)


@register_op("fusion_seqpool_concat")
def _fusion_seqpool_concat_impl(*xs, pooltype="SUM", lengths=None):
    """ref fusion_seqpool_concat_op.cc: sequence_pool each [B,T,D] input
    then concat along features."""
    from .sequence import sequence_pool
    outs = []
    for i, x in enumerate(xs):
        l = None if lengths is None else lengths[i]
        outs.append(sequence_pool.__pure_fn__(
            x, pooltype.lower(), length=l))
    return jnp.concatenate(outs, axis=-1)


def fusion_seqpool_concat(xs, pooltype="SUM", lengths=None):
    return _fusion_seqpool_concat_impl(*xs, pooltype=pooltype,
                                       lengths=lengths)


@register_op("fusion_squared_mat_sub")
def fusion_squared_mat_sub(x, y, scalar=1.0, name=None):
    """ref fusion_squared_mat_sub_op.cc: scalar * ((x@y)^2 - x^2@y^2)."""
    return scalar * (jnp.square(x @ y) - jnp.square(x) @ jnp.square(y))


@register_op("batch_fc")
def batch_fc(x, w, bias=None, name=None):
    """Per-slot batched fc (ref batch_fc_op.cu): x [S,N,D], w [S,D,M],
    bias [S,1,M] -> relu(x@w + b) per slot."""
    out = jnp.einsum("snd,sdm->snm", x, w)
    if bias is not None:
        out = out + bias
    return jax.nn.relu(out)


@register_op("rank_attention")
def rank_attention(x, rank, rank_param, max_rank=3, name=None):
    """Rank-gated parameter selection (capability of
    rank_attention_op.cu, simplified to the dense regular case: instead
    of the reference's rank_offset CSR layout, `rank` gives each
    instance's rank id directly): out[b] = x[b] @ rank_param[rank[b]]."""
    r = jnp.clip(rank.reshape(-1).astype(jnp.int32), 0,
                 rank_param.shape[0] - 1)
    return jnp.einsum("bd,bdm->bm", x, rank_param[r])
