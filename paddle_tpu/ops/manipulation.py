"""Shape / layout / indexing ops (paddle.tensor.manipulation parity).

Reference surface: python/paddle/tensor/manipulation.py and the reshape /
concat / gather / scatter / slice op families under
/root/reference/paddle/fluid/operators/. All static-shape on XLA; dynamic
result shapes (unique, nonzero, masked_select) are eager-only by design —
inside jit users get the _with_counts/padded variants.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core import dtypes as _dtypes
from ..framework import Tensor, _unwrap
from .registry import register_op

__all__ = [
    "broadcast_shape", "rank", "shape",
    "reshape", "transpose", "concat", "split", "chunk", "stack", "unstack",
    "squeeze", "unsqueeze", "flatten", "gather", "gather_nd", "scatter",
    "scatter_nd", "scatter_nd_add", "slice", "strided_slice", "expand",
    "expand_as", "broadcast_to", "broadcast_tensors", "tile", "flip", "roll",
    "cast", "unique", "unique_consecutive", "masked_select", "index_select",
    "index_sample", "where", "pad", "repeat_interleave", "take_along_axis",
    "put_along_axis", "unbind", "moveaxis", "swapaxes", "as_real",
    "as_complex", "tensordot", "unfold", "view", "view_as", "atleast_1d",
    "atleast_2d", "atleast_3d", "crop", "tolist", "rot90_", "shard_index",
    "reverse", "t",
]


def _norm_shape(shape):
    if isinstance(shape, Tensor):
        shape = shape.tolist()
    return tuple(int(_unwrap(s)) if not isinstance(s, (int, np.integer))
                 else int(s) for s in shape)


@register_op("reshape")
def reshape(x, shape, name=None):
    shape = _norm_shape(shape)
    # paddle semantics: 0 means copy the corresponding input dim
    shape = tuple(x.shape[i] if s == 0 else s for i, s in enumerate(shape))
    return jnp.reshape(x, shape)


@register_op("transpose")
def transpose(x, perm=None, name=None):
    return jnp.transpose(x, axes=tuple(perm) if perm is not None else None)


@register_op("t")
def t(x, name=None):
    return jnp.swapaxes(x, -1, -2) if jnp.ndim(x) >= 2 else x


@register_op("moveaxis")
def moveaxis(x, source, destination, name=None):
    return jnp.moveaxis(x, source, destination)


@register_op("swapaxes")
def swapaxes(x, axis0, axis1, name=None):
    return jnp.swapaxes(x, axis0, axis1)


@register_op("concat_op")
def _concat_impl(*xs, axis=0):
    return jnp.concatenate(xs, axis=axis)


def concat(x, axis=0, name=None):
    axis = int(_unwrap(axis)) if not isinstance(axis, int) else axis
    return _concat_impl(*x, axis=axis)


@register_op("split_op")
def _split_impl(x, sections, axis):
    if isinstance(sections, int):
        return tuple(jnp.split(x, sections, axis=axis))
    # sections list, possibly with one -1
    sections = list(sections)
    if -1 in sections:
        total = x.shape[axis]
        known = sum(s for s in sections if s != -1)
        sections[sections.index(-1)] = total - known
    offsets = np.cumsum(sections)[:-1].tolist()
    return tuple(jnp.split(x, offsets, axis=axis))


def split(x, num_or_sections, axis=0, name=None):
    axis = int(_unwrap(axis)) if not isinstance(axis, int) else axis
    out = _split_impl(x, num_or_sections, axis=axis)
    return list(out)


def chunk(x, chunks, axis=0, name=None):
    return split(x, chunks, axis=axis)


@register_op("stack_op")
def _stack_impl(*xs, axis=0):
    return jnp.stack(xs, axis=axis)


def stack(x, axis=0, name=None):
    return _stack_impl(*x, axis=axis)


@register_op("unstack_op")
def _unstack_impl(x, axis, num):
    return tuple(jnp.squeeze(s, axis=axis)
                 for s in jnp.split(x, num, axis=axis))


def unstack(x, axis=0, num=None, name=None):
    num = num if num is not None else x.shape[axis]
    return list(_unstack_impl(x, axis=axis, num=num))


def unbind(input, axis=0):
    return unstack(input, axis=axis)


@register_op("squeeze")
def squeeze(x, axis=None, name=None):
    if axis is None:
        return jnp.squeeze(x)
    axes = (axis,) if isinstance(axis, int) else tuple(axis)
    axes = tuple(a for a in axes if x.shape[a] == 1)
    return jnp.squeeze(x, axis=axes) if axes else x


@register_op("unsqueeze")
def unsqueeze(x, axis, name=None):
    axes = (axis,) if isinstance(axis, int) else tuple(int(_unwrap(a))
                                                       for a in axis)
    return jnp.expand_dims(x, axis=axes)


@register_op("flatten_op")
def flatten(x, start_axis=0, stop_axis=-1, name=None):
    nd = jnp.ndim(x)
    start = start_axis % nd if nd else 0
    stop = stop_axis % nd if nd else 0
    shape = x.shape
    new = shape[:start] + (int(np.prod(shape[start:stop + 1], dtype=np.int64))
                           if stop >= start else 1,) + shape[stop + 1:]
    return jnp.reshape(x, new)


@register_op("cast")
def cast(x, dtype):
    return x.astype(_dtypes.convert_dtype(dtype))


@register_op("gather_op")
def gather(x, index, axis=0, name=None):
    axis = int(_unwrap(axis)) if not isinstance(axis, int) else axis
    idx = jnp.asarray(index)
    if idx.ndim == 0:
        idx = idx[None]
    return jnp.take(x, idx, axis=axis)


@register_op("gather_nd")
def gather_nd(x, index, name=None):
    index = jnp.asarray(index)
    idx_tuple = tuple(jnp.moveaxis(index, -1, 0))
    return x[idx_tuple]


@register_op("scatter_op")
def scatter(x, index, updates, overwrite=True, name=None):
    index = jnp.asarray(index).reshape(-1)
    if overwrite:
        return x.at[index].set(updates)
    # paddle: non-overwrite zeroes target rows then adds
    zeroed = x.at[index].set(jnp.zeros_like(updates))
    return zeroed.at[index].add(updates)


@register_op("scatter_nd_add")
def scatter_nd_add(x, index, updates, name=None):
    index = jnp.asarray(index)
    idx_tuple = tuple(jnp.moveaxis(index, -1, 0))
    return x.at[idx_tuple].add(updates)


def scatter_nd(index, updates, shape, name=None):
    from .creation import zeros
    z = zeros(shape, dtype=updates.dtype if hasattr(updates, "dtype")
              else None)
    return scatter_nd_add(z, index, updates)


@register_op("slice_op")
def slice(input, axes, starts, ends, name=None):
    out = input
    for ax, st, en in zip(axes, starts, ends):
        st = int(_unwrap(st)) if not isinstance(st, int) else st
        en = int(_unwrap(en)) if not isinstance(en, int) else en
        dim = input.shape[ax]
        st = max(st + dim, 0) if st < 0 else min(st, dim)
        en = max(en + dim, 0) if en < 0 else min(en, dim)
        out = jax.lax.slice_in_dim(out, st, en, axis=ax)
    return out


@register_op("strided_slice_op")
def strided_slice(x, axes, starts, ends, strides, name=None):
    slices = [np.s_[:]] * jnp.ndim(x)
    for ax, st, en, sd in zip(axes, starts, ends, strides):
        slices[ax] = np.s_[int(_unwrap(st)):int(_unwrap(en)):int(_unwrap(sd))]
    return x[tuple(slices)]


@register_op("expand_op")
def expand(x, shape, name=None):
    shape = _norm_shape(shape)
    # -1 means keep input dim
    nd_in = jnp.ndim(x)
    pad = len(shape) - nd_in
    full = tuple(
        x.shape[i - pad] if s == -1 else s for i, s in enumerate(shape))
    return jnp.broadcast_to(x, full)


def expand_as(x, y, name=None):
    return expand(x, list(_unwrap(y).shape))


@register_op("broadcast_to_op")
def broadcast_to(x, shape, name=None):
    return jnp.broadcast_to(x, _norm_shape(shape))


def broadcast_tensors(inputs, name=None):
    arrays = [_unwrap(i) for i in inputs]
    shape = jnp.broadcast_shapes(*[a.shape for a in arrays])
    return [broadcast_to(i, shape) for i in inputs]


@register_op("tile_op")
def tile(x, repeat_times, name=None):
    return jnp.tile(x, _norm_shape(repeat_times))


@register_op("flip")
def flip(x, axis, name=None):
    axes = (axis,) if isinstance(axis, int) else tuple(axis)
    return jnp.flip(x, axis=axes)


def reverse(x, axis, name=None):
    return flip(x, axis)


@register_op("roll_op")
def roll(x, shifts, axis=None, name=None):
    return jnp.roll(x, shifts, axis=axis)


@register_op("where_op")
def where(condition, x=None, y=None, name=None):
    if x is None and y is None:
        raise ValueError("use paddle.nonzero for 1-arg where (eager only)")
    return jnp.where(condition.astype(bool) if hasattr(condition, "astype")
                     else condition, x, y)


@register_op("pad_op")
def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):
    pad = [int(_unwrap(p)) for p in pad] if not isinstance(pad, int) else pad
    nd = jnp.ndim(x)
    if isinstance(pad, int):
        cfg = [(pad, pad)] * nd
    elif len(pad) == 2 * nd:
        # paddle layout: (before_0, after_0, before_1, after_1, ...)
        cfg = [(pad[2 * i], pad[2 * i + 1]) for i in range(nd)]
    else:
        # NCHW/NCL/NCDHW spatial-only pad, given innermost-first pairs
        n_spatial = len(pad) // 2
        cfg = [(0, 0)] * nd
        if data_format in ("NCHW", "NCL", "NCDHW"):
            spatial_axes = list(range(nd - n_spatial, nd))
        else:  # NHWC-ish: spatial dims are 1..nd-2
            spatial_axes = list(range(1, 1 + n_spatial))
        for i, ax in enumerate(reversed(spatial_axes)):
            cfg[ax] = (pad[2 * i], pad[2 * i + 1])
    jmode = {"constant": "constant", "reflect": "reflect",
             "replicate": "edge", "circular": "wrap"}[mode]
    if jmode == "constant":
        return jnp.pad(x, cfg, mode=jmode, constant_values=value)
    return jnp.pad(x, cfg, mode=jmode)


@register_op("repeat_interleave_op")
def repeat_interleave(x, repeats, axis=None, name=None):
    total = None
    if not isinstance(repeats, int):
        r = np.asarray(_unwrap(repeats))
        total = int(r.sum())
        repeats = jnp.asarray(r)
    return jnp.repeat(x, repeats, axis=axis, total_repeat_length=total)


@register_op("take_along_axis_op")
def take_along_axis(arr, indices, axis, broadcast=True, name=None):
    return jnp.take_along_axis(arr, jnp.asarray(indices), axis=axis)


@register_op("put_along_axis_op")
def put_along_axis(arr, indices, values, axis, reduce="assign", name=None):
    idx = jnp.asarray(indices)
    vals = jnp.broadcast_to(jnp.asarray(values), idx.shape).astype(arr.dtype)
    nd = jnp.ndim(arr)
    ax = axis % nd
    ix = jnp.meshgrid(*[jnp.arange(s) for s in idx.shape], indexing="ij")
    ix[ax] = idx
    if reduce == "assign":
        return arr.at[tuple(ix)].set(vals)
    if reduce == "add":
        return arr.at[tuple(ix)].add(vals)
    if reduce == "multiply":
        return arr.at[tuple(ix)].multiply(vals)
    raise ValueError(f"unknown reduce '{reduce}'")


@register_op("index_select_op")
def index_select(x, index, axis=0, name=None):
    return jnp.take(x, jnp.asarray(index).reshape(-1), axis=axis)


@register_op("index_sample_op")
def index_sample(x, index):
    idx = jnp.asarray(index)
    return jnp.take_along_axis(x, idx, axis=1)


@register_op("tensordot_op")
def tensordot(x, y, axes=2, name=None):
    if isinstance(axes, (list, tuple)):
        axes = tuple(tuple(a) if isinstance(a, (list, tuple)) else a
                     for a in axes)
    return jnp.tensordot(x, y, axes=axes)


@register_op("as_real")
def as_real(x, name=None):
    return jnp.stack([jnp.real(x), jnp.imag(x)], axis=-1)


@register_op("as_complex")
def as_complex(x, name=None):
    return jax.lax.complex(x[..., 0], x[..., 1])


@register_op("unfold_tensor")
def unfold(x, axis, size, step, name=None):
    n = (x.shape[axis] - size) // step + 1
    starts = jnp.arange(n) * step
    idx = starts[:, None] + jnp.arange(size)[None, :]
    out = jnp.take(x, idx.reshape(-1), axis=axis)
    nd = jnp.ndim(x)
    ax = axis % nd
    new_shape = x.shape[:ax] + (n, size) + x.shape[ax + 1:]
    out = jnp.reshape(out, new_shape)
    # paddle puts the window dim last
    return jnp.moveaxis(out, ax + 1, -1)


def view(x, shape_or_dtype, name=None):
    if isinstance(shape_or_dtype, (list, tuple)):
        return reshape(x, shape_or_dtype)
    return cast(x, shape_or_dtype)


def view_as(x, other, name=None):
    return reshape(x, list(_unwrap(other).shape))


def atleast_1d(*inputs):
    outs = [Tensor(jnp.atleast_1d(_unwrap(i))) for i in inputs]
    return outs[0] if len(outs) == 1 else outs


def atleast_2d(*inputs):
    outs = [Tensor(jnp.atleast_2d(_unwrap(i))) for i in inputs]
    return outs[0] if len(outs) == 1 else outs


def atleast_3d(*inputs):
    outs = [Tensor(jnp.atleast_3d(_unwrap(i))) for i in inputs]
    return outs[0] if len(outs) == 1 else outs


@register_op("crop_op")
def crop(x, shape=None, offsets=None, name=None):
    shape = _norm_shape(shape)
    offsets = [0] * len(shape) if offsets is None else [
        int(_unwrap(o)) for o in offsets]
    return jax.lax.dynamic_slice(x, offsets, shape)


@register_op("shard_index_op")
def shard_index(input, index_num, nshards, shard_id, ignore_value=-1):
    shard_size = (index_num + nshards - 1) // nshards
    in_shard = (input // shard_size) == shard_id
    return jnp.where(in_shard, input % shard_size, ignore_value)


# -- eager-only dynamic-shape ops -------------------------------------------

def unique(x, return_index=False, return_inverse=False, return_counts=False,
           axis=None, dtype="int64", name=None):
    a = np.asarray(_unwrap(x))
    res = np.unique(a, return_index=return_index,
                    return_inverse=return_inverse,
                    return_counts=return_counts, axis=axis)
    if not isinstance(res, tuple):
        return Tensor(jnp.asarray(res))
    return tuple(Tensor(jnp.asarray(r)) for r in res)


def unique_consecutive(x, return_inverse=False, return_counts=False,
                       axis=None, dtype="int64", name=None):
    a = np.asarray(_unwrap(x))
    if axis is None:
        a = a.reshape(-1)
        axis = 0
    keep = np.ones(a.shape[axis], dtype=bool)
    sl = [np.s_[:]] * a.ndim
    vals = np.moveaxis(a, axis, 0)
    keep[1:] = np.any(
        vals[1:].reshape(a.shape[axis] - 1, -1)
        != vals[:-1].reshape(a.shape[axis] - 1, -1), axis=1)
    out = np.compress(keep, a, axis=axis)
    rets = [Tensor(jnp.asarray(out))]
    if return_inverse:
        inv = np.cumsum(keep) - 1
        rets.append(Tensor(jnp.asarray(inv.astype(np.int64))))
    if return_counts:
        idx = np.flatnonzero(keep)
        counts = np.diff(np.append(idx, a.shape[axis]))
        rets.append(Tensor(jnp.asarray(counts.astype(np.int64))))
    return rets[0] if len(rets) == 1 else tuple(rets)


def masked_select(x, mask, name=None):
    a, m = np.asarray(_unwrap(x)), np.asarray(_unwrap(mask))
    return Tensor(jnp.asarray(a[np.broadcast_to(m, a.shape)]))


def tolist(x):
    return np.asarray(_unwrap(x)).tolist()


def rot90_(x, k, axes):
    from .math import rot90
    return rot90(x, k, axes)


def broadcast_shape(x_shape, y_shape):
    """paddle.broadcast_shape: the numpy-broadcast result shape."""
    import numpy as _np
    return list(_np.broadcast_shapes(tuple(x_shape), tuple(y_shape)))


@register_op("shape_op")
def shape(input, name=None):
    """paddle.shape as a Tensor (ref shape_op: runtime shape). Static
    under XLA, so this is the traced constant shape."""
    return jnp.asarray(input.shape, jnp.int32)


def rank(input, name=None):
    """paddle.rank: ndim as a 0-D Tensor (ref rank_op)."""
    from ..framework import Tensor
    arr = input._data if isinstance(input, Tensor) else input
    return Tensor(jnp.asarray(jnp.ndim(arr), jnp.int32))
