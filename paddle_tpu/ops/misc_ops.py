"""Long-tail reference ops: partial/slab utilities, positional encoding,
time-axis convs, PS id sharding, SPP, sequence conv/scatter, debug print.

Reference specs (semantics only; all implementations are jnp/lax-first):
  partial_concat_op.cc, partial_sum_op.cc, pad_constant_like_op.cc,
  space_to_depth_op.cc, conv_shift_op.cc, row_conv_op.cc,
  add_position_encoding_op.cc, shuffle_batch_op.cc, filter_by_instag_op.cc,
  merge_ids_op.cc / split_ids_op.cc, split_selected_rows_op.cc,
  get_tensor_from_selected_rows_op.cc, spp_op.cc, sequence_conv_op.cc,
  sequence_scatter_op.cc, sequence_topk_avg_pooling_op.cc, print_op.cc,
  select_input_op.cc / select_output_op.cc, l1_norm_op.cc,
  squared_l2_norm_op.cc, squared_l2_distance_op.cc (all under
  /root/reference/paddle/fluid/operators/).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..framework import Tensor, _unwrap
from .registry import register_op

__all__ = [
    "print_op", "select_input", "select_output", "partial_concat",
    "partial_sum", "pad_constant_like", "space_to_depth", "conv_shift",
    "row_conv", "add_position_encoding", "shuffle_batch",
    "filter_by_instag", "merge_ids", "split_ids", "split_selected_rows",
    "get_tensor_from_selected_rows", "spp", "sequence_conv",
    "sequence_scatter", "sequence_topk_avg_pooling", "l1_norm",
    "squared_l2_norm", "squared_l2_distance",
]


@register_op("print")
def print_op(x, message="", first_n=-1, summarize=20, print_phase="both",
             name=None):
    """Identity that prints its input (ref print_op.cc). Works under jit
    via jax.debug.print; `first_n`/`summarize` are host-side conveniences
    honoured eagerly."""
    if isinstance(x, jax.core.Tracer):
        jax.debug.print("{msg}{val}", msg=message, val=x)
        return x
    flat = np.asarray(x).ravel()
    shown = flat if summarize < 0 else flat[:summarize]
    print(f"{message}shape={tuple(np.shape(x))} values={shown.tolist()}")
    return x


@register_op("select_input")
def _select_input_impl(*args):
    """out = inputs[mask] (ref select_input_op.cc). Last positional is the
    scalar branch index; under jit this is lax.switch, so all inputs must
    share shape/dtype (same restriction the reference's fused branches
    have after conditional_block lowering)."""
    xs, mask = args[:-1], args[-1]
    idx = jnp.clip(jnp.asarray(mask, jnp.int32).reshape(()), 0,
                   len(xs) - 1)
    return jax.lax.switch(idx, [lambda i=i: xs[i] for i in range(len(xs))])


def select_input(inputs, mask):
    return _select_input_impl(*inputs, mask)


@register_op("select_output")
def _select_output_impl(x, mask, n_out=2):
    """Route x to output[mask]; other outputs are zeros of x's shape (ref
    select_output_op.cc writes only the selected branch var; zero-filled
    twins keep XLA shapes static)."""
    idx = jnp.asarray(mask, jnp.int32).reshape(())
    return tuple(jnp.where(jnp.equal(idx, i), x, jnp.zeros_like(x))
                 for i in range(int(n_out)))


def select_output(x, mask, n_out=2):
    return _select_output_impl(x, mask, n_out=int(n_out))


@register_op("partial_concat")
def _partial_concat_impl(*xs, start_index=0, length=-1):
    """Concat columns [start, start+length) of each [B, M] input
    (ref partial_concat_op.cc)."""
    m = xs[0].shape[1]
    s = start_index if start_index >= 0 else m + start_index
    e = m if length < 0 else s + length
    return jnp.concatenate([x[:, s:e] for x in xs], axis=1)


def partial_concat(x, start_index=0, length=-1, name=None):
    return _partial_concat_impl(*x, start_index=int(start_index),
                                length=int(length))


@register_op("partial_sum")
def _partial_sum_impl(*xs, start_index=0, length=-1):
    """Sum of column slices [start, start+length) over inputs
    (ref partial_sum_op.cc)."""
    m = xs[0].shape[1]
    s = start_index if start_index >= 0 else m + start_index
    e = m if length < 0 else s + length
    out = xs[0][:, s:e]
    for x in xs[1:]:
        out = out + x[:, s:e]
    return out


def partial_sum(x, start_index=0, length=-1, name=None):
    return _partial_sum_impl(*x, start_index=int(start_index),
                             length=int(length))


@register_op("pad_constant_like")
def pad_constant_like(x, y, pad_value=0.0, name=None):
    """Pad y at the end of every dim up to x's shape (ref
    pad_constant_like_op.cc: output shape = X.shape, data = Y padded)."""
    pads = [(0, int(xs) - int(ys)) for xs, ys in zip(x.shape, y.shape)]
    return jnp.pad(y, pads, constant_values=pad_value)


@register_op("space_to_depth")
def space_to_depth(x, blocksize, name=None):
    """NCHW [N,C,H,W] -> [N, C*b*b, H/b, W/b] (ref space_to_depth_op.cc;
    inverse of pixel_shuffle)."""
    b = int(blocksize)
    n, c, h, w = x.shape
    x = x.reshape(n, c, h // b, b, w // b, b)
    x = x.transpose(0, 3, 5, 1, 2, 4)
    return x.reshape(n, c * b * b, h // b, w // b)


@register_op("conv_shift")
def conv_shift(x, y, name=None):
    """Circular convolution (ref conv_shift_op.cc): X [B,M], Y [B,N] with
    odd N << M; out[b,i] = sum_j x[b, (i + j - N//2) mod M] * y[b, j]."""
    m, n = x.shape[1], y.shape[1]
    half = n // 2
    idx = (jnp.arange(m)[:, None] + jnp.arange(n)[None, :] - half) % m
    windows = x[:, idx]                       # [B, M, N]
    return jnp.einsum("bmn,bn->bm", windows, y)


@register_op("row_conv")
def row_conv(x, filt, name=None):
    """Lookahead row convolution (ref row_conv_op.cc): x [B,T,D],
    filter [k,D]; out[b,t,d] = sum_{j<k, t+j<T} x[b,t+j,d]*filter[j,d]."""
    k = filt.shape[0]
    t = x.shape[1]
    padded = jnp.pad(x, ((0, 0), (0, k - 1), (0, 0)))
    out = jnp.zeros_like(x)
    for j in range(k):
        out = out + padded[:, j:j + t, :] * filt[j][None, None, :]
    return out


@register_op("add_position_encoding")
def add_position_encoding(x, alpha=1.0, beta=1.0, name=None):
    """out = alpha*x + beta*PE with the reference's half-split sinusoid
    (add_position_encoding_op.h: first half sin, second half cos)."""
    b, t, d = x.shape
    half = d // 2
    pos = jnp.arange(t, dtype=x.dtype)[:, None]
    div = jnp.power(jnp.asarray(10000.0, x.dtype),
                    jnp.arange(half, dtype=x.dtype) / half)
    pe = jnp.concatenate([jnp.sin(pos / div), jnp.cos(pos / div)], axis=1)
    if pe.shape[1] < d:
        pe = jnp.pad(pe, ((0, 0), (0, d - pe.shape[1])))
    return alpha * x + beta * pe[None, :, :]


@register_op("shuffle_batch", tags=("rng",))
def shuffle_batch(x, seed=None, name=None):
    """Random permutation of rows (ref shuffle_batch_op.cc). Returns
    (out, shuffle_idx) so the order can be undone/reused. seed=None
    draws a fresh key per call from the framework generator."""
    if seed is None:
        from ..core.generator import next_key
        key = next_key()
    else:
        key = jax.random.key(int(seed))
    perm = jax.random.permutation(key, x.shape[0])
    return x[perm], perm.astype(jnp.int32)


def filter_by_instag(ins, ins_tag_lengths, ins_tags, filter_tags,
                     out_val_if_empty=0):
    """Keep rows whose tag set intersects filter_tags (ref
    filter_by_instag_op.cc). Eager-only (dynamic output rows), like the
    reference's LoD output. ins: [B, D]; ins_tags: flat int tags;
    ins_tag_lengths: [B] tags per row. Returns (filtered, index,
    loss_weight)."""
    ins = np.asarray(_unwrap(ins))
    tags = np.asarray(_unwrap(ins_tags)).ravel()
    lens = np.asarray(_unwrap(ins_tag_lengths)).ravel()
    fset = set(int(t) for t in np.asarray(_unwrap(filter_tags)).ravel())
    keep, off = [], 0
    for i, l in enumerate(lens):
        if fset.intersection(int(t) for t in tags[off:off + int(l)]):
            keep.append(i)
        off += int(l)
    if not keep:
        out = np.full((1,) + ins.shape[1:], out_val_if_empty, ins.dtype)
        return (Tensor(jnp.asarray(out)),
                Tensor(jnp.asarray([0], jnp.int64)),
                Tensor(jnp.asarray([0.0], jnp.float32)))
    idx = np.asarray(keep, np.int64)
    return (Tensor(jnp.asarray(ins[idx])), Tensor(jnp.asarray(idx)),
            Tensor(jnp.ones((len(keep),), jnp.float32)))


def split_ids(ids, shard_num):
    """Shard ids by `id % shard_num` (ref split_ids_op.cc). Eager-only
    (dynamic shapes), returns a python list of id arrays."""
    ids = np.asarray(_unwrap(ids)).ravel()
    return [Tensor(jnp.asarray(ids[ids % shard_num == s]))
            for s in range(int(shard_num))]


def merge_ids(ids, rows, values):
    """Inverse of split_ids for looked-up rows (ref merge_ids_op.cc):
    reassemble per-shard embedding rows into the original id order."""
    ids = np.asarray(_unwrap(ids)).ravel()
    dim = np.asarray(_unwrap(values[0])).shape[-1]
    out = np.zeros((ids.shape[0], dim),
                   np.asarray(_unwrap(values[0])).dtype)
    for shard_rows, shard_vals in zip(rows, values):
        r = np.asarray(_unwrap(shard_rows)).ravel()
        v = np.asarray(_unwrap(shard_vals))
        pos = {int(idv): i for i, idv in enumerate(r)}
        for i, idv in enumerate(ids):
            if int(idv) in pos:
                out[i] = v[pos[int(idv)]]
    return Tensor(jnp.asarray(out))


def split_selected_rows(sr, height_sections):
    """Split a SelectedRows by contiguous height sections (ref
    split_selected_rows_op.cc) — the PS shard scatter."""
    from ..core.selected_rows import SelectedRows
    rows = np.asarray(sr.rows)
    vals = np.asarray(sr.value)
    outs, start = [], 0
    for h in height_sections:
        m = (rows >= start) & (rows < start + h)
        outs.append(SelectedRows(jnp.asarray(rows[m] - start),
                                 jnp.asarray(vals[m]), int(h)))
        start += h
    return outs


def get_tensor_from_selected_rows(sr):
    """SelectedRows value slab as a dense tensor (ref
    get_tensor_from_selected_rows_op.cc)."""
    return Tensor(sr.value)


@register_op("spp")
def spp(x, pyramid_height=3, pooling_type="max", name=None):
    """Spatial pyramid pooling (ref spp_op.cc): concat of adaptive pools
    at 1x1, 2x2, ... 2^(h-1) bins, flattened: [N, C*sum(4^l)]."""
    from ..nn.functional.pooling import _adaptive
    n, c = x.shape[0], x.shape[1]
    outs = []
    for l in range(int(pyramid_height)):
        bins = 2 ** l
        p = _adaptive(x, (bins, bins), 2, False,
                      "max" if pooling_type == "max" else "avg")
        outs.append(p.reshape(n, c * bins * bins))
    return jnp.concatenate(outs, axis=1)


@register_op("sequence_conv")
def sequence_conv(x, filt, length=None, context_length=3, context_start=None,
                  name=None):
    """Per-timestep context-window linear map (ref sequence_conv_op.cc):
    x [B,T,D], filter [context_length*D, M]; window rows outside [0,T) or
    beyond `length` are zero — LoD replaced by the (padded, lengths)
    convention of ops/sequence.py."""
    cl = int(context_length)
    start = -((cl - 1) // 2) if context_start is None else int(context_start)
    b, t, d = x.shape
    cols = []
    for j in range(cl):
        off = start + j
        shifted = jnp.roll(x, -off, axis=1)
        pos = jnp.arange(t) + off
        valid = (pos >= 0) & (pos < t)
        if length is not None:
            valid = valid[None, :] & (pos[None, :] <
                                      jnp.asarray(length)[:, None])
            shifted = shifted * valid[:, :, None].astype(x.dtype)
        else:
            shifted = shifted * valid[None, :, None].astype(x.dtype)
        cols.append(shifted)
    im2col = jnp.concatenate(cols, axis=-1)          # [B,T,cl*D]
    return im2col @ filt                             # [B,T,M]


@register_op("sequence_scatter")
def sequence_scatter(x, index, updates, length=None, name=None):
    """Scatter-add per-sequence updates into x (ref
    sequence_scatter_op.cc): x [B,D], index [B,K] column ids, updates
    [B,K]; positions past `length[b]` are ignored."""
    upd = updates
    if length is not None:
        mask = (jnp.arange(index.shape[1])[None, :]
                < jnp.asarray(length)[:, None])
        upd = upd * mask.astype(updates.dtype)
    rows = jnp.broadcast_to(jnp.arange(x.shape[0])[:, None], index.shape)
    return x.at[rows, index].add(upd)


@register_op("sequence_topk_avg_pooling")
def sequence_topk_avg_pooling(x, topks=(1,), name=None):
    """Top-k average pooling over the last axis per channel (ref
    sequence_topk_avg_pooling_op.cc, text-matching pyramid): x [B,C,N] ->
    [B, C*len(topks)] where each slot is mean(top-k)."""
    ks = tuple(int(k) for k in topks)
    kmax = max(ks)
    top = jax.lax.top_k(x, kmax)[0]                  # [B,C,kmax] sorted
    csum = jnp.cumsum(top, axis=-1)
    outs = [csum[..., k - 1] / k for k in ks]
    return jnp.concatenate(outs, axis=-1)


@register_op("l1_norm")
def l1_norm(x, name=None):
    """sum(|x|) (ref l1_norm_op.cc)."""
    return jnp.sum(jnp.abs(x))


@register_op("squared_l2_norm")
def squared_l2_norm(x, name=None):
    """sum(x^2) (ref squared_l2_norm_op.cc) — the grad-clip workhorse."""
    return jnp.sum(jnp.square(x))


@register_op("squared_l2_distance")
def squared_l2_distance(x, y, name=None):
    """Row-wise ||x - y||^2 (ref squared_l2_distance_op.cc). Returns
    (sub_result, out) like the reference (sub kept for the grad path;
    here for API parity)."""
    sub = x - (y if y.shape[0] == x.shape[0]
               else jnp.broadcast_to(y, x.shape))
    return sub, jnp.sum(jnp.square(sub), axis=tuple(range(1, sub.ndim)))
