"""Detection ops (paddle.fluid.layers.detection / operators/detection parity).

Reference surface: /root/reference/paddle/fluid/operators/detection/ (~18K LoC
CUDA/C++: yolo_box_op.h, box_coder_op.h, prior_box_op.h, multiclass_nms_op.cc,
matrix_nms_op.cc, roi_align_op.*, generate_proposals_op.cc, ...) and the
python wrappers in python/paddle/fluid/layers/detection.py.

TPU-first redesign, not a translation:

* Everything is static-shape. The reference's NMS family returns LoD tensors
  with data-dependent row counts; XLA cannot do that inside jit, so every op
  here returns fixed-capacity outputs padded with sentinel label -1 / score 0
  plus an explicit valid-count tensor. This is the bucketing/padding policy
  SURVEY.md §7 hard-part (b) calls for, applied uniformly.
* Greedy hard-NMS is an O(K^2) IoU matrix plus a `lax.fori_loop` over the K
  sorted candidates — the IoU matrix is one fused VPU kernel under XLA, and
  the loop carries only a K-bit keep mask (no dynamic gather/scatter).
* matrix_nms is already pure matrix math (upper-triangular max-IoU decay) and
  maps to TPU almost verbatim from its math definition.
* roi_align/roi_pool use vectorized bilinear gathers (vmap over ROIs) instead
  of the reference's per-pixel scalar loops.

All ops are registered in the global registry so they trace into Programs and
are differentiable where meaningful (roi_align, sigmoid_focal_loss, yolov3
pieces).
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
import numpy as np

from ..framework import Tensor, _unwrap
from .registry import register_op

__all__ = [
    "iou_similarity", "box_coder", "box_clip", "prior_box",
    "density_prior_box", "anchor_generator", "yolo_box", "yolov3_loss",
    "multiclass_nms", "matrix_nms", "nms", "roi_align", "roi_pool",
    "generate_proposals", "distribute_fpn_proposals", "collect_fpn_proposals",
    "sigmoid_focal_loss", "bipartite_match", "target_assign",
    "detection_output", "polygon_box_transform", "mine_hard_examples",
]


# ---------------------------------------------------------------------------
# box geometry helpers
# ---------------------------------------------------------------------------

def _box_area(boxes, normalized=True):
    off = 0.0 if normalized else 1.0
    w = jnp.maximum(boxes[..., 2] - boxes[..., 0] + off, 0.0)
    h = jnp.maximum(boxes[..., 3] - boxes[..., 1] + off, 0.0)
    return w * h


def _pairwise_iou(a, b, normalized=True):
    """a: [N,4], b: [M,4] -> [N,M] IoU (xyxy)."""
    off = 0.0 if normalized else 1.0
    lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
    rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = jnp.maximum(rb - lt + off, 0.0)
    inter = wh[..., 0] * wh[..., 1]
    union = _box_area(a, normalized)[:, None] + _box_area(b, normalized)[None, :] - inter
    return jnp.where(union > 0, inter / union, 0.0)


@register_op("iou_similarity")
def iou_similarity(x, y, box_normalized=True, name=None):
    """IoU between every box in x [N,4] and y [M,4] -> [N,M].

    Ref: operators/detection/iou_similarity_op.{h,cc}.
    """
    return _pairwise_iou(x, y, normalized=box_normalized)


@register_op("box_coder")
def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True, axis=0,
              variance=None, name=None):
    """Encode/decode boxes against priors (ref box_coder_op.h:41,118).

    encode: target [R,4], prior [C,4] -> [R,C,4] offsets.
    decode: target [R,C,4] (or [R,4]), prior broadcast on `axis` -> [R,C,4].
    prior_box_var may be a [C,4] array or `variance` a python list of 4.
    """
    norm = 1.0 if box_normalized else 0.0
    off = 1.0 - norm

    def center_size(b):
        w = b[..., 2] - b[..., 0] + off
        h = b[..., 3] - b[..., 1] + off
        cx = b[..., 0] + w / 2
        cy = b[..., 1] + h / 2
        return cx, cy, w, h

    pcx, pcy, pw, ph = center_size(prior_box)
    if code_type == "encode_center_size":
        tcx = (target_box[..., 2] + target_box[..., 0]) / 2
        tcy = (target_box[..., 3] + target_box[..., 1]) / 2
        tw = target_box[..., 2] - target_box[..., 0] + off
        th = target_box[..., 3] - target_box[..., 1] + off
        # broadcast row(target) x col(prior)
        ox = (tcx[:, None] - pcx[None, :]) / pw[None, :]
        oy = (tcy[:, None] - pcy[None, :]) / ph[None, :]
        ow = jnp.log(jnp.abs(tw[:, None] / pw[None, :]))
        oh = jnp.log(jnp.abs(th[:, None] / ph[None, :]))
        out = jnp.stack([ox, oy, ow, oh], axis=-1)
        if prior_box_var is not None:
            out = out / prior_box_var[None, :, :]
        elif variance:
            out = out / jnp.asarray(variance, out.dtype)
        return out
    elif code_type == "decode_center_size":
        t = target_box
        if t.ndim == 2:
            t = t[:, None, :]
        if axis == 0:
            pcx_, pcy_, pw_, ph_ = (v[None, :] for v in (pcx, pcy, pw, ph))
            pvar = None if prior_box_var is None else prior_box_var[None, :, :]
        else:
            pcx_, pcy_, pw_, ph_ = (v[:, None] for v in (pcx, pcy, pw, ph))
            pvar = None if prior_box_var is None else prior_box_var[:, None, :]
        if pvar is not None:
            t = t * pvar
        elif variance:
            t = t * jnp.asarray(variance, t.dtype)
        dcx = t[..., 0] * pw_ + pcx_
        dcy = t[..., 1] * ph_ + pcy_
        dw = jnp.exp(t[..., 2]) * pw_
        dh = jnp.exp(t[..., 3]) * ph_
        return jnp.stack([dcx - dw / 2, dcy - dh / 2,
                          dcx + dw / 2 - off, dcy + dh / 2 - off], axis=-1)
    raise ValueError(f"unknown code_type {code_type!r}")


@register_op("box_clip")
def box_clip(input, im_info, name=None):
    """Clip boxes [..., 4] to image bounds. im_info: [H, W, scale] per image
    (ref box_clip_op.h — clips to im_info/scale - 1)."""
    im_info = jnp.asarray(im_info)
    if im_info.ndim == 1:
        h = im_info[0] / im_info[2] - 1
        w = im_info[1] / im_info[2] - 1
    else:
        h = im_info[:, 0] / im_info[:, 2] - 1
        w = im_info[:, 1] / im_info[:, 2] - 1
        shape = (-1,) + (1,) * (input.ndim - 2)
        h = h.reshape(shape)
        w = w.reshape(shape)
    x1 = jnp.clip(input[..., 0], 0, w)
    y1 = jnp.clip(input[..., 1], 0, h)
    x2 = jnp.clip(input[..., 2], 0, w)
    y2 = jnp.clip(input[..., 3], 0, h)
    return jnp.stack([x1, y1, x2, y2], axis=-1)


# ---------------------------------------------------------------------------
# anchor / prior generation
# ---------------------------------------------------------------------------

@register_op("prior_box")
def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5, min_max_aspect_ratios_order=False,
              name=None):
    """SSD prior boxes (ref prior_box_op.{h,cc}).

    input: feature map [N,C,H,W]; image: [N,C,IH,IW].
    Returns (boxes [H,W,P,4], variances [H,W,P,4]), normalized xyxy.
    """
    h, w = int(input.shape[2]), int(input.shape[3])
    img_h, img_w = int(image.shape[2]), int(image.shape[3])
    min_sizes = [float(s) for s in np.atleast_1d(min_sizes)]
    max_sizes = [float(s) for s in np.atleast_1d(max_sizes)] if max_sizes else []
    # expand aspect ratios like ExpandAspectRatios (flip adds 1/r)
    ars = [1.0]
    for r in np.atleast_1d(aspect_ratios):
        r = float(r)
        if not any(abs(r - e) < 1e-6 for e in ars):
            ars.append(r)
            if flip:
                ars.append(1.0 / r)
    step_w = float(steps[0]) if steps[0] else img_w / w
    step_h = float(steps[1]) if steps[1] else img_h / h

    widths, heights = [], []
    for ms in min_sizes:
        if min_max_aspect_ratios_order:
            widths.append(ms); heights.append(ms)
            if max_sizes:
                mx = max_sizes[min_sizes.index(ms)]
                s = math.sqrt(ms * mx)
                widths.append(s); heights.append(s)
            for r in ars:
                if abs(r - 1.0) < 1e-6:
                    continue
                widths.append(ms * math.sqrt(r)); heights.append(ms / math.sqrt(r))
        else:
            for r in ars:
                widths.append(ms * math.sqrt(r)); heights.append(ms / math.sqrt(r))
            if max_sizes:
                mx = max_sizes[min_sizes.index(ms)]
                s = math.sqrt(ms * mx)
                widths.append(s); heights.append(s)
    pw = jnp.asarray(widths, jnp.float32)
    ph = jnp.asarray(heights, jnp.float32)

    cx = (jnp.arange(w, dtype=jnp.float32) + offset) * step_w
    cy = (jnp.arange(h, dtype=jnp.float32) + offset) * step_h
    cxg, cyg = jnp.meshgrid(cx, cy)  # [H,W]
    boxes = jnp.stack([
        (cxg[..., None] - pw / 2) / img_w,
        (cyg[..., None] - ph / 2) / img_h,
        (cxg[..., None] + pw / 2) / img_w,
        (cyg[..., None] + ph / 2) / img_h,
    ], axis=-1)  # [H,W,P,4]
    if clip:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    var = jnp.broadcast_to(jnp.asarray(variance, jnp.float32), boxes.shape)
    return boxes, var


@register_op("density_prior_box")
def density_prior_box(input, image, densities, fixed_sizes, fixed_ratios,
                      variance=(0.1, 0.1, 0.2, 0.2), clip=False,
                      steps=(0.0, 0.0), offset=0.5, flatten_to_2d=False,
                      name=None):
    """Densified priors (ref density_prior_box_op.h). Returns (boxes, vars)."""
    h, w = int(input.shape[2]), int(input.shape[3])
    img_h, img_w = int(image.shape[2]), int(image.shape[3])
    step_w = float(steps[0]) if steps[0] else img_w / w
    step_h = float(steps[1]) if steps[1] else img_h / h
    centers = []
    dims = []
    for size, dens in zip(fixed_sizes, densities):
        for ratio in fixed_ratios:
            bw = size * math.sqrt(ratio)
            bh = size / math.sqrt(ratio)
            shift = int(step_w / dens)
            for di in range(dens):
                for dj in range(dens):
                    centers.append((dj * shift + shift / 2.0 - step_w / 2.0,
                                    di * shift + shift / 2.0 - step_h / 2.0))
                    dims.append((bw, bh))
    offs = jnp.asarray(centers, jnp.float32)      # [P,2]
    whs = jnp.asarray(dims, jnp.float32)          # [P,2]
    cx = (jnp.arange(w, dtype=jnp.float32) + offset) * step_w
    cy = (jnp.arange(h, dtype=jnp.float32) + offset) * step_h
    cxg, cyg = jnp.meshgrid(cx, cy)
    ccx = cxg[..., None] + offs[:, 0]
    ccy = cyg[..., None] + offs[:, 1]
    boxes = jnp.stack([
        (ccx - whs[:, 0] / 2) / img_w,
        (ccy - whs[:, 1] / 2) / img_h,
        (ccx + whs[:, 0] / 2) / img_w,
        (ccy + whs[:, 1] / 2) / img_h,
    ], axis=-1)
    if clip:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    var = jnp.broadcast_to(jnp.asarray(variance, jnp.float32), boxes.shape)
    if flatten_to_2d:
        boxes = boxes.reshape(-1, 4)
        var = var.reshape(-1, 4)
    return boxes, var


@register_op("anchor_generator")
def anchor_generator(input, anchor_sizes, aspect_ratios,
                     variance=(0.1, 0.1, 0.2, 0.2), stride=(16.0, 16.0),
                     offset=0.5, name=None):
    """RPN anchors (ref anchor_generator_op.h). boxes in absolute xyxy."""
    h, w = int(input.shape[2]), int(input.shape[3])
    sw, sh = float(stride[0]), float(stride[1])
    dims = []
    for r in aspect_ratios:
        for s in anchor_sizes:
            area = sw * sh
            area_ratios = area / r
            base_w = round(math.sqrt(area_ratios))
            base_h = round(base_w * r)
            scale_w = s / sw
            scale_h = s / sh
            dims.append((scale_w * base_w, scale_h * base_h))
    whs = jnp.asarray(dims, jnp.float32)  # [A,2]
    cx = (jnp.arange(w, dtype=jnp.float32) * sw) + offset * sw
    cy = (jnp.arange(h, dtype=jnp.float32) * sh) + offset * sh
    cxg, cyg = jnp.meshgrid(cx, cy)
    anchors = jnp.stack([
        cxg[..., None] - 0.5 * whs[:, 0],
        cyg[..., None] - 0.5 * whs[:, 1],
        cxg[..., None] + 0.5 * whs[:, 0],
        cyg[..., None] + 0.5 * whs[:, 1],
    ], axis=-1)  # [H,W,A,4]
    var = jnp.broadcast_to(jnp.asarray(variance, jnp.float32), anchors.shape)
    return anchors, var


# ---------------------------------------------------------------------------
# YOLO
# ---------------------------------------------------------------------------

@register_op("yolo_box")
def yolo_box(x, img_size, anchors, class_num, conf_thresh,
             downsample_ratio, clip_bbox=True, scale_x_y=1.0, name=None):
    """Decode YOLOv3 head output (ref yolo_box_op.h:28-82 GetYoloBox et al).

    x: [N, A*(5+C), H, W]; img_size: [N,2] (h,w) int.
    Returns (boxes [N, A*H*W, 4] xyxy in image coords, scores [N,A*H*W,C]).
    Candidates with objectness < conf_thresh are zeroed (reference skips
    writing them; zero-filled output is bit-identical to its memset).
    """
    n, _, h, w = x.shape
    an = len(anchors) // 2
    anc = jnp.asarray(anchors, x.dtype).reshape(an, 2)
    scale = float(scale_x_y)
    bias = -0.5 * (scale - 1.0)
    in_h = downsample_ratio * h
    in_w = downsample_ratio * w

    x = x.reshape(n, an, 5 + class_num, h, w)
    tx, ty, tw, th, tobj = (x[:, :, 0], x[:, :, 1], x[:, :, 2], x[:, :, 3],
                            x[:, :, 4])
    cls = x[:, :, 5:]                                  # [N,A,C,H,W]
    gx = jnp.arange(w, dtype=x.dtype)                  # l (cols)
    gy = jnp.arange(h, dtype=x.dtype)                  # k (rows)
    img_h = img_size[:, 0].astype(x.dtype).reshape(n, 1, 1, 1)
    img_w = img_size[:, 1].astype(x.dtype).reshape(n, 1, 1, 1)

    cx = (gx[None, None, None, :] + jax.nn.sigmoid(tx) * scale + bias) \
        * img_w / w
    cy = (gy[None, None, :, None] + jax.nn.sigmoid(ty) * scale + bias) \
        * img_h / h
    bw = jnp.exp(tw) * anc[None, :, 0, None, None] * img_w / in_w
    bh = jnp.exp(th) * anc[None, :, 1, None, None] * img_h / in_h
    x1, y1 = cx - bw / 2, cy - bh / 2
    x2, y2 = cx + bw / 2, cy + bh / 2
    if clip_bbox:
        x1 = jnp.maximum(x1, 0.0)
        y1 = jnp.maximum(y1, 0.0)
        x2 = jnp.minimum(x2, img_w - 1)
        y2 = jnp.minimum(y2, img_h - 1)
    boxes = jnp.stack([x1, y1, x2, y2], axis=-1)       # [N,A,H,W,4]
    conf = jax.nn.sigmoid(tobj)                        # [N,A,H,W]
    keep = (conf >= conf_thresh).astype(x.dtype)
    boxes = boxes * keep[..., None]
    scores = jax.nn.sigmoid(cls) * (conf * keep)[:, :, None]   # [N,A,C,H,W]
    boxes = boxes.reshape(n, an * h * w, 4)
    scores = jnp.moveaxis(scores, 2, -1).reshape(n, an * h * w, class_num)
    return boxes, scores


@register_op("yolov3_loss")
def yolov3_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
                ignore_thresh, downsample_ratio, gt_score=None,
                use_label_smooth=False, scale_x_y=1.0, name=None):
    """YOLOv3 training loss (ref yolov3_loss_op.h semantics, vectorized).

    x: [N, M*(5+C), H, W]; gt_box: [N,B,4] (cx,cy,w,h, normalized to image);
    gt_label: [N,B] int; returns per-image loss [N].
    Objectness targets: best-anchor match per gt assigns positives; negatives
    ignore when best IoU vs any gt > ignore_thresh.
    """
    n, _, h, w = x.shape
    mask = list(anchor_mask)
    m = len(mask)
    an_all = jnp.asarray(anchors, jnp.float32).reshape(-1, 2)
    in_w = downsample_ratio * w
    in_h = downsample_ratio * h
    scale = float(scale_x_y)
    bias = -0.5 * (scale - 1.0)

    x = x.reshape(n, m, 5 + class_num, h, w).astype(jnp.float32)
    px, py, pw, ph, pobj = (x[:, :, 0], x[:, :, 1], x[:, :, 2], x[:, :, 3],
                            x[:, :, 4])
    pcls = x[:, :, 5:]                                  # [N,M,C,H,W]

    gtb = gt_box.astype(jnp.float32)                    # [N,B,4] cx cy w h
    valid = (gtb[..., 2] > 0) & (gtb[..., 3] > 0)       # [N,B]

    # best anchor (over ALL anchors) per gt by shape-only IoU at origin
    gw = gtb[..., 2] * in_w
    gh = gtb[..., 3] * in_h
    inter = (jnp.minimum(gw[..., None], an_all[:, 0])
             * jnp.minimum(gh[..., None], an_all[:, 1]))
    union = gw[..., None] * gh[..., None] + an_all[:, 0] * an_all[:, 1] - inter
    best_anchor = jnp.argmax(inter / jnp.maximum(union, 1e-9), axis=-1)  # [N,B]
    # position in this level's mask, or -1
    mask_arr = jnp.asarray(mask)
    an_pos = jnp.argmax(best_anchor[..., None] == mask_arr, axis=-1)
    in_level = jnp.any(best_anchor[..., None] == mask_arr, axis=-1) & valid

    gi = jnp.clip((gtb[..., 0] * w).astype(jnp.int32), 0, w - 1)  # [N,B]
    gj = jnp.clip((gtb[..., 1] * h).astype(jnp.int32), 0, h - 1)

    # scatter gt targets onto the grid: obj mask, tx ty tw th, class
    tgt_shape = (n, m, h, w)
    obj_mask = jnp.zeros(tgt_shape, jnp.float32)
    b_idx = jnp.broadcast_to(jnp.arange(n)[:, None], gi.shape)
    sel = in_level
    obj_mask = obj_mask.at[b_idx, an_pos, gj, gi].add(
        jnp.where(sel, 1.0, 0.0))
    obj_mask = jnp.minimum(obj_mask, 1.0)

    tx = gtb[..., 0] * w - gi
    ty = gtb[..., 1] * h - gj
    an_w = an_all[mask_arr][:, 0]
    an_h = an_all[mask_arr][:, 1]
    tw_t = jnp.log(jnp.maximum(gw / jnp.maximum(an_w[an_pos], 1e-9), 1e-9))
    th_t = jnp.log(jnp.maximum(gh / jnp.maximum(an_h[an_pos], 1e-9), 1e-9))
    box_scale = 2.0 - gtb[..., 2] * gtb[..., 3]

    def gather_pred(p):
        return p[b_idx, an_pos, gj, gi]                 # [N,B]

    bce = lambda logit, label: (jnp.maximum(logit, 0) - logit * label
                                + jnp.log1p(jnp.exp(-jnp.abs(logit))))
    sel_f = jnp.where(sel, 1.0, 0.0)
    # coordinate losses (reference uses sigmoid-CE for x,y; L1 for w,h)
    loss_x = bce(gather_pred(px), tx) * box_scale * sel_f
    loss_y = bce(gather_pred(py), ty) * box_scale * sel_f
    loss_w = jnp.abs(gather_pred(pw) - tw_t) * box_scale * sel_f
    loss_h = jnp.abs(gather_pred(ph) - th_t) * box_scale * sel_f

    # objectness: positives at assigned cells; negatives elsewhere unless
    # predicted box IoU vs any gt exceeds ignore_thresh
    pred_boxes = _yolo_pred_boxes(px, py, pw, ph, an_all[mask_arr], w, h,
                                  in_w, in_h, scale, bias)  # [N,M,H,W,4] cxcywh norm
    ious = _iou_cxcywh(pred_boxes.reshape(n, -1, 4), gtb, valid)  # [N,MHW,B]
    best_iou = jnp.max(ious, axis=-1).reshape(n, m, h, w)
    noobj_mask = ((best_iou <= ignore_thresh).astype(jnp.float32)
                  * (1.0 - obj_mask))
    loss_obj = (bce(pobj, jnp.ones_like(pobj)) * obj_mask
                + bce(pobj, jnp.zeros_like(pobj)) * noobj_mask)

    # classification at positive cells; label smoothing per ref
    # yolov3_loss_op.h:285-291 (pos = 1-sw, neg = sw, sw = min(1/C, 1/40))
    sw = min(1.0 / class_num, 1.0 / 40) if use_label_smooth else 0.0
    onehot = jax.nn.one_hot(gt_label, class_num, dtype=jnp.float32)
    onehot = onehot * (1.0 - 2.0 * sw) + sw
    pcls_g = pcls[b_idx[..., None], an_pos[..., None],
                  jnp.arange(class_num)[None, None, :], gj[..., None],
                  gi[..., None]]                       # [N,B,C]
    score_w = sel_f if gt_score is None else sel_f * gt_score
    loss_cls = (bce(pcls_g, onehot).sum(-1) * score_w)

    per_img = (loss_x.sum(1) + loss_y.sum(1) + loss_w.sum(1) + loss_h.sum(1)
               + loss_obj.sum((1, 2, 3)) + loss_cls.sum(1))
    return per_img


def _yolo_pred_boxes(px, py, pw, ph, anc, w, h, in_w, in_h, scale, bias):
    gx = jnp.arange(w, dtype=jnp.float32)
    gy = jnp.arange(h, dtype=jnp.float32)
    cx = (gx[None, None, None, :] + jax.nn.sigmoid(px) * scale + bias) / w
    cy = (gy[None, None, :, None] + jax.nn.sigmoid(py) * scale + bias) / h
    bw = jnp.exp(jnp.clip(pw, -10, 10)) * anc[None, :, 0, None, None] / in_w
    bh = jnp.exp(jnp.clip(ph, -10, 10)) * anc[None, :, 1, None, None] / in_h
    return jnp.stack([cx, cy, bw, bh], axis=-1)


def _iou_cxcywh(pred, gt, valid):
    """pred [N,P,4], gt [N,B,4] both cx,cy,w,h -> IoU [N,P,B]."""
    def xyxy(b):
        return jnp.stack([b[..., 0] - b[..., 2] / 2, b[..., 1] - b[..., 3] / 2,
                          b[..., 0] + b[..., 2] / 2, b[..., 1] + b[..., 3] / 2],
                         -1)
    p = xyxy(pred)
    g = xyxy(gt)
    lt = jnp.maximum(p[:, :, None, :2], g[:, None, :, :2])
    rb = jnp.minimum(p[:, :, None, 2:], g[:, None, :, 2:])
    wh = jnp.maximum(rb - lt, 0.0)
    inter = wh[..., 0] * wh[..., 1]
    pa = jnp.maximum(p[..., 2] - p[..., 0], 0) * jnp.maximum(p[..., 3] - p[..., 1], 0)
    ga = jnp.maximum(g[..., 2] - g[..., 0], 0) * jnp.maximum(g[..., 3] - g[..., 1], 0)
    union = pa[:, :, None] + ga[:, None, :] - inter
    iou = jnp.where(union > 0, inter / union, 0.0)
    return jnp.where(valid[:, None, :], iou, 0.0)


# ---------------------------------------------------------------------------
# NMS family — fixed-capacity, mask-based (TPU static shapes)
# ---------------------------------------------------------------------------

def _greedy_nms_mask(boxes, scores, iou_threshold, normalized=True,
                     eta=1.0):
    """Greedy hard NMS over pre-sorted (desc) candidates.

    boxes [K,4], scores [K] sorted descending. Returns keep mask [K] bool.
    One O(K^2) IoU matrix + a fori_loop carrying (keep mask, adaptive
    threshold) — no dynamic shapes, no gather in the loop body.

    eta < 1 enables adaptive NMS (ref multiclass_nms_op.cc NMSFast: after
    each kept box, while threshold > 0.5 it decays by eta).
    """
    k = boxes.shape[0]
    iou = _pairwise_iou(boxes, boxes, normalized)      # [K,K]

    def body(i, carry):
        keep, thr = carry
        # candidate i survives iff no higher-ranked kept box suppresses it
        alive = ~jnp.any((iou[:, i] > thr) & keep & (jnp.arange(k) < i))
        kept = alive & keep[i]
        thr = jnp.where(kept & (eta < 1.0) & (thr > 0.5), thr * eta, thr)
        return keep.at[i].set(kept), thr

    init = scores > -jnp.inf                            # all candidates
    thr0 = jnp.asarray(iou_threshold, iou.dtype)
    keep, _ = jax.lax.fori_loop(0, k, body, (init, thr0))
    return keep


@register_op("nms")
def nms(boxes, scores, iou_threshold=0.3, top_k=-1, name=None):
    """Single-class hard NMS. Returns (keep_indices [K] sorted by score,
    keep_mask [K]) where K = top_k or num boxes. Padded entries index -1."""
    k = boxes.shape[0] if top_k in (-1, None) else min(int(top_k),
                                                       boxes.shape[0])
    sc, order = jax.lax.top_k(scores, k)
    bx = boxes[order]
    keep = _greedy_nms_mask(bx, sc, iou_threshold)
    idx = jnp.where(keep, order, -1)
    return idx, keep


@register_op("multiclass_nms")
def multiclass_nms(bboxes, scores, score_threshold=0.05, nms_top_k=400,
                   keep_top_k=100, nms_threshold=0.3, normalized=True,
                   nms_eta=1.0, background_label=0, return_index=False,
                   name=None):
    """Multi-class NMS (ref multiclass_nms_op.cc semantics, static shapes).

    bboxes: [N, M, 4]; scores: [N, C, M].
    Returns (out [N, keep_top_k, 6] rows = [label, score, x1,y1,x2,y2],
    valid_counts [N]); padded rows have label -1. The reference returns a
    LoD tensor with data-dependent rows — fixed capacity + counts is the
    XLA-native equivalent (callers slice by valid_counts on host).
    """
    n, num_boxes, _ = bboxes.shape
    num_cls = scores.shape[1]
    k = min(int(nms_top_k), num_boxes) if nms_top_k > 0 else num_boxes

    def per_image(bx, sc):
        # per class: top-k, threshold, nms
        def per_class(c_scores):
            s, order = jax.lax.top_k(c_scores, k)
            b = bx[order]
            valid = s > score_threshold
            keep = _greedy_nms_mask(b, jnp.where(valid, s, -jnp.inf),
                                    nms_threshold, normalized,
                                    eta=nms_eta) & valid
            return b, jnp.where(keep, s, -1.0), order
        cb, cs, cidx = jax.vmap(per_class)(sc)          # [C,k,4],[C,k],[C,k]
        labels = jnp.broadcast_to(jnp.arange(num_cls)[:, None], cs.shape)
        if background_label >= 0:
            cs = jnp.where(labels == background_label, -1.0, cs)
        flat_s = cs.reshape(-1)
        flat_b = cb.reshape(-1, 4)
        flat_l = labels.reshape(-1)
        flat_i = cidx.reshape(-1)
        kk = min(int(keep_top_k), flat_s.shape[0]) if keep_top_k > 0 \
            else flat_s.shape[0]
        s_top, sel = jax.lax.top_k(flat_s, kk)
        good = s_top > 0
        out = jnp.concatenate([
            jnp.where(good, flat_l[sel], -1).astype(bx.dtype)[:, None],
            jnp.where(good, s_top, 0.0)[:, None],
            flat_b[sel] * good[:, None].astype(bx.dtype),
        ], axis=1)
        return out, good.sum().astype(jnp.int32), jnp.where(good, flat_i[sel], -1)

    out, counts, index = jax.vmap(per_image)(bboxes, scores)
    if return_index:
        return out, counts, index
    return out, counts


@register_op("matrix_nms")
def matrix_nms(bboxes, scores, score_threshold=0.05, post_threshold=0.0,
               nms_top_k=400, keep_top_k=100, use_gaussian=False,
               gaussian_sigma=2.0, background_label=0, normalized=True,
               name=None):
    """Matrix NMS (ref matrix_nms_op.cc) — decay by max-IoU with any
    higher-scored same-class box; pure matrix math, ideal on TPU.

    Returns (out [N, keep_top_k, 6], valid_counts [N])."""
    n, num_boxes, _ = bboxes.shape
    num_cls = scores.shape[1]
    k = min(int(nms_top_k), num_boxes) if nms_top_k > 0 else num_boxes

    def per_image(bx, sc):
        def per_class(c_scores):
            s, order = jax.lax.top_k(c_scores, k)
            b = bx[order]
            valid = s > score_threshold
            iou = _pairwise_iou(b, b, normalized)
            tri = jnp.tril(iou, -1)                     # [k,k] j<i
            max_iou = jnp.max(tri, axis=1)              # compensate IoU
            if use_gaussian:
                # ref matrix_nms_op.cc:87 decay_score<T,true>:
                # exp((max_iou^2 - iou^2) * sigma)
                decay = jnp.exp((max_iou[None, :] ** 2 - tri ** 2)
                                * gaussian_sigma)
            else:
                decay = (1.0 - tri) / jnp.maximum(1.0 - max_iou[None, :], 1e-9)
            decay = jnp.where(jnp.tril(jnp.ones_like(iou, bool), -1),
                              decay, jnp.inf)
            # ref :154 initializes min_decay = 1.0 — decay never amplifies
            dec = jnp.minimum(jnp.min(decay, axis=1), 1.0)
            dec = jnp.where(jnp.arange(k) == 0, 1.0, dec)
            s2 = jnp.where(valid, s * dec, -1.0)
            if post_threshold > 0:
                s2 = jnp.where(s2 > post_threshold, s2, -1.0)
            return b, s2
        cb, cs = jax.vmap(per_class)(sc)
        labels = jnp.broadcast_to(jnp.arange(num_cls)[:, None], cs.shape)
        if background_label >= 0:
            cs = jnp.where(labels == background_label, -1.0, cs)
        flat_s = cs.reshape(-1)
        flat_b = cb.reshape(-1, 4)
        flat_l = labels.reshape(-1)
        kk = min(int(keep_top_k), flat_s.shape[0]) if keep_top_k > 0 \
            else flat_s.shape[0]
        s_top, sel = jax.lax.top_k(flat_s, kk)
        good = s_top > 0
        out = jnp.concatenate([
            jnp.where(good, flat_l[sel], -1).astype(bx.dtype)[:, None],
            jnp.where(good, s_top, 0.0)[:, None],
            flat_b[sel] * good[:, None].astype(bx.dtype),
        ], axis=1)
        return out, good.sum().astype(jnp.int32)

    return jax.vmap(per_image)(bboxes, scores)


# ---------------------------------------------------------------------------
# ROI ops
# ---------------------------------------------------------------------------

@register_op("roi_align")
def roi_align(input, rois, output_size, spatial_scale=1.0, sampling_ratio=-1,
              rois_num=None, aligned=True, name=None):
    """ROIAlign (ref roi_align_op.* bilinear sampling), vmapped over ROIs.

    input: [N,C,H,W]; rois: [R,4] xyxy (image coords) or [R,5] with batch idx
    in col 0 (when rois_num is None and width 5). Differentiable.

    sampling_ratio<=0: the reference picks ceil(roi_size/out) samples
    PER ROI (data-dependent); static XLA shapes can't — this op uses a
    fixed 2x2 grid instead, the value detection heads overwhelmingly
    configure explicitly. Pass a positive sampling_ratio for exact
    reference parity (tests/test_op_config_grids.py sweeps those).
    """
    if isinstance(output_size, int):
        ph = pw = output_size
    else:
        ph, pw = output_size
    n, c, h, w = input.shape
    if rois.shape[-1] == 5:
        batch_idx = rois[:, 0].astype(jnp.int32)
        boxes = rois[:, 1:]
    elif rois_num is not None:
        rois_num = jnp.asarray(rois_num)
        batch_idx = jnp.repeat(jnp.arange(n), rois_num,
                               total_repeat_length=rois.shape[0])
        boxes = rois
    else:
        batch_idx = jnp.zeros((rois.shape[0],), jnp.int32)
        boxes = rois
    offset = 0.5 if aligned else 0.0

    def one_roi(box, b):
        x1 = box[0] * spatial_scale - offset
        y1 = box[1] * spatial_scale - offset
        x2 = box[2] * spatial_scale - offset
        y2 = box[3] * spatial_scale - offset
        rw = x2 - x1
        rh = y2 - y1
        if not aligned:
            rw = jnp.maximum(rw, 1.0)
            rh = jnp.maximum(rh, 1.0)
        bin_h = rh / ph
        bin_w = rw / pw
        sr = sampling_ratio if sampling_ratio > 0 else 2
        # sample grid: [ph, sr] y coords, [pw, sr] x coords
        iy = (jnp.arange(ph)[:, None] * bin_h + y1
              + (jnp.arange(sr) + 0.5) * bin_h / sr)   # [ph,sr]
        ix = (jnp.arange(pw)[:, None] * bin_w + x1
              + (jnp.arange(sr) + 0.5) * bin_w / sr)   # [pw,sr]
        feat = jax.lax.dynamic_index_in_dim(input, b, 0, False)  # [C,H,W]

        def bilinear(y, x):
            inb = (y >= -1.0) & (y <= h) & (x >= -1.0) & (x <= w)
            y = jnp.clip(y, 0.0, h - 1)
            x = jnp.clip(x, 0.0, w - 1)
            y0 = jnp.floor(y)
            x0 = jnp.floor(x)
            y1_ = jnp.clip(y0 + 1, 0, h - 1)
            x1_ = jnp.clip(x0 + 1, 0, w - 1)
            ly = y - y0
            lx = x - x0
            y0i, x0i, y1i, x1i = (y0.astype(jnp.int32), x0.astype(jnp.int32),
                                  y1_.astype(jnp.int32), x1_.astype(jnp.int32))
            v = (feat[:, y0i, x0i] * (1 - ly) * (1 - lx)
                 + feat[:, y0i, x1i] * (1 - ly) * lx
                 + feat[:, y1i, x0i] * ly * (1 - lx)
                 + feat[:, y1i, x1i] * ly * lx)
            return jnp.where(inb, v, 0.0)

        # average over sr*sr samples per bin
        ys = iy.reshape(ph, sr, 1, 1, 1)                # broadcast vs xs
        xs = ix.reshape(1, 1, pw, sr, 1)
        yy = jnp.broadcast_to(ys, (ph, sr, pw, sr, 1))[..., 0]
        xx = jnp.broadcast_to(xs, (ph, sr, pw, sr, 1))[..., 0]
        vals = bilinear(yy.reshape(-1), xx.reshape(-1))  # [C, ph*sr*pw*sr]
        vals = vals.reshape(c, ph, sr, pw, sr)
        return vals.mean(axis=(2, 4))                    # [C,ph,pw]

    return jax.vmap(one_roi)(boxes, batch_idx)


@register_op("roi_pool")
def roi_pool(input, rois, output_size, spatial_scale=1.0, rois_num=None,
             name=None):
    """ROI max pooling (ref roi_pool_op.*). rois in xyxy image coords."""
    if isinstance(output_size, int):
        ph = pw = output_size
    else:
        ph, pw = output_size
    n, c, h, w = input.shape
    if rois.shape[-1] == 5:
        batch_idx = rois[:, 0].astype(jnp.int32)
        boxes = rois[:, 1:]
    elif rois_num is not None:
        batch_idx = jnp.repeat(jnp.arange(n), jnp.asarray(rois_num),
                               total_repeat_length=rois.shape[0])
        boxes = rois
    else:
        batch_idx = jnp.zeros((rois.shape[0],), jnp.int32)
        boxes = rois

    ygrid = jnp.arange(h)
    xgrid = jnp.arange(w)

    def one_roi(box, b):
        x1 = jnp.round(box[0] * spatial_scale)
        y1 = jnp.round(box[1] * spatial_scale)
        x2 = jnp.round(box[2] * spatial_scale)
        y2 = jnp.round(box[3] * spatial_scale)
        rw = jnp.maximum(x2 - x1 + 1, 1.0)
        rh = jnp.maximum(y2 - y1 + 1, 1.0)
        bin_h = rh / ph
        bin_w = rw / pw
        feat = jax.lax.dynamic_index_in_dim(input, b, 0, False)  # [C,H,W]

        def one_bin(i, j):
            ys = jnp.clip(jnp.floor(y1 + i * bin_h), 0, h).astype(jnp.int32)
            ye = jnp.clip(jnp.ceil(y1 + (i + 1) * bin_h), 0, h).astype(jnp.int32)
            xs = jnp.clip(jnp.floor(x1 + j * bin_w), 0, w).astype(jnp.int32)
            xe = jnp.clip(jnp.ceil(x1 + (j + 1) * bin_w), 0, w).astype(jnp.int32)
            m = ((ygrid[:, None] >= ys) & (ygrid[:, None] < ye)
                 & (xgrid[None, :] >= xs) & (xgrid[None, :] < xe))
            empty = ~jnp.any(m)
            v = jnp.where(m[None], feat, -jnp.inf).max(axis=(1, 2))
            return jnp.where(empty, 0.0, v)

        ii, jj = jnp.meshgrid(jnp.arange(ph), jnp.arange(pw), indexing="ij")
        vals = jax.vmap(one_bin)(ii.reshape(-1), jj.reshape(-1))  # [ph*pw,C]
        return vals.T.reshape(c, ph, pw)

    return jax.vmap(one_roi)(boxes, batch_idx)


# ---------------------------------------------------------------------------
# proposals / FPN
# ---------------------------------------------------------------------------

@register_op("generate_proposals")
def generate_proposals(scores, bbox_deltas, im_shape, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, eta=1.0,
                       pixel_offset=True, name=None):
    """RPN proposal generation (ref generate_proposals_v2 semantics).

    scores [N,A,H,W]; bbox_deltas [N,4A,H,W]; anchors [H,W,A,4] or [HWA,4];
    im_shape [N,2]. Returns (rois [N, post_nms_top_n, 4], roi_probs
    [N, post_nms_top_n, 1], rois_num [N]). Fixed-capacity, zero-padded.
    """
    n = scores.shape[0]
    anchors = anchors.reshape(-1, 4)
    variances = variances.reshape(-1, 4)
    a = scores.shape[1]
    off = 1.0 if pixel_offset else 0.0

    def per_image(sc, deltas, im):
        s = jnp.transpose(sc, (1, 2, 0)).reshape(-1)          # [HWA]
        d = deltas.reshape(a, 4, *deltas.shape[1:])
        d = jnp.transpose(d, (2, 3, 0, 1)).reshape(-1, 4)     # [HWA,4]
        k = min(int(pre_nms_top_n), s.shape[0])
        s_top, order = jax.lax.top_k(s, k)
        anc = anchors[order]
        var = variances[order]
        dd = d[order]
        # decode (BoxCoder decode_center_size with per-anchor variances)
        aw = anc[:, 2] - anc[:, 0] + off
        ah = anc[:, 3] - anc[:, 1] + off
        acx = anc[:, 0] + aw / 2
        acy = anc[:, 1] + ah / 2
        cx = var[:, 0] * dd[:, 0] * aw + acx
        cy = var[:, 1] * dd[:, 1] * ah + acy
        bw = jnp.exp(jnp.minimum(var[:, 2] * dd[:, 2], 10.0)) * aw
        bh = jnp.exp(jnp.minimum(var[:, 3] * dd[:, 3], 10.0)) * ah
        props = jnp.stack([cx - bw / 2, cy - bh / 2,
                           cx + bw / 2 - off, cy + bh / 2 - off], -1)
        # clip to image
        props = jnp.stack([
            jnp.clip(props[:, 0], 0, im[1] - off),
            jnp.clip(props[:, 1], 0, im[0] - off),
            jnp.clip(props[:, 2], 0, im[1] - off),
            jnp.clip(props[:, 3], 0, im[0] - off)], -1)
        # filter small
        ws = props[:, 2] - props[:, 0] + off
        hs = props[:, 3] - props[:, 1] + off
        ok = (ws >= min_size) & (hs >= min_size)
        s_f = jnp.where(ok, s_top, -jnp.inf)
        keep = _greedy_nms_mask(props, s_f, nms_thresh,
                                normalized=not pixel_offset, eta=eta) & ok
        s_keep = jnp.where(keep, s_f, -jnp.inf)
        kk = min(int(post_nms_top_n), k)
        s_fin, sel = jax.lax.top_k(s_keep, kk)
        good = jnp.isfinite(s_fin)
        rois = props[sel] * good[:, None]
        return rois, jnp.where(good, s_fin, 0.0)[:, None], \
            good.sum().astype(jnp.int32)

    return jax.vmap(per_image)(scores, bbox_deltas, im_shape)


@register_op("distribute_fpn_proposals")
def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, pixel_offset=True, rois_num=None,
                             name=None):
    """Assign each ROI to an FPN level (ref distribute_fpn_proposals_op).

    fpn_rois [R,4]. Returns (level_ids [R] in [0, L), restore_index [R],
    per-level masks [L,R]). Static-shape variant: callers use the mask to
    zero out rows instead of materializing ragged per-level lists.
    """
    off = 1.0 if pixel_offset else 0.0
    ws = fpn_rois[:, 2] - fpn_rois[:, 0] + off
    hs = fpn_rois[:, 3] - fpn_rois[:, 1] + off
    scale = jnp.sqrt(jnp.maximum(ws * hs, 1e-6))
    lvl = jnp.floor(jnp.log2(scale / refer_scale + 1e-6)) + refer_level
    lvl = jnp.clip(lvl, min_level, max_level).astype(jnp.int32)
    num_l = max_level - min_level + 1
    ids = lvl - min_level
    masks = jax.nn.one_hot(ids, num_l, dtype=jnp.bool_).T   # [L,R]
    order = jnp.argsort(ids, stable=True)
    restore = jnp.argsort(order, stable=True)
    return ids, restore, masks


@register_op("collect_fpn_proposals")
def collect_fpn_proposals(multi_rois, multi_scores, post_nms_top_n,
                          rois_num_per_level=None, name=None):
    """Merge per-level ROIs by score, keep top post_nms_top_n
    (ref collect_fpn_proposals_op). multi_rois: list of [Ri,4]."""
    rois = jnp.concatenate(list(multi_rois), axis=0)
    scores = jnp.concatenate([s.reshape(-1) for s in multi_scores], axis=0)
    k = min(int(post_nms_top_n), scores.shape[0])
    s_top, sel = jax.lax.top_k(scores, k)
    return rois[sel], s_top


# ---------------------------------------------------------------------------
# losses / assignment
# ---------------------------------------------------------------------------

@register_op("sigmoid_focal_loss")
def sigmoid_focal_loss(x, label, fg_num, gamma=2.0, alpha=0.25, name=None):
    """Focal loss (ref sigmoid_focal_loss_op.h). x: [N,C] logits;
    label: [N,1] int in [0,C] (0 = background); fg_num: [1] int."""
    n, c = x.shape
    label = label.reshape(-1)
    fg = jnp.maximum(jnp.asarray(fg_num, jnp.float32).reshape(()), 1.0)
    # per-class binary target: label-1 == class index
    tgt = jax.nn.one_hot(label - 1, c, dtype=x.dtype)
    p = jax.nn.sigmoid(x)
    ce_pos = -jnp.log(jnp.maximum(p, 1e-12))
    ce_neg = -jnp.log(jnp.maximum(1 - p, 1e-12))
    loss = (tgt * alpha * ((1 - p) ** gamma) * ce_pos
            + (1 - tgt) * (1 - alpha) * (p ** gamma) * ce_neg)
    return loss / fg


@register_op("bipartite_match")
def bipartite_match(dist_matrix, match_type="bipartite", dist_threshold=0.5,
                    name=None):
    """Greedy bipartite matching (ref bipartite_match_op.cc BipartiteMatch).

    dist_matrix [N,M] (rows = gt, cols = priors). Returns
    (match_indices [M] int: row matched to each col or -1,
     match_dist [M]). match_type='per_prediction' additionally matches
    unmatched cols to their argmax row when dist > dist_threshold.
    """
    n, m = dist_matrix.shape

    def body(_, state):
        match_idx, match_d, used_r, used_c = state
        masked = jnp.where(used_r[:, None] | used_c[None, :], -jnp.inf,
                           dist_matrix)
        flat = jnp.argmax(masked)
        r, c2 = flat // m, flat % m
        best = masked.reshape(-1)[flat]
        ok = jnp.isfinite(best) & (best > -jnp.inf)
        match_idx = jnp.where(ok, match_idx.at[c2].set(r), match_idx)
        match_d = jnp.where(ok, match_d.at[c2].set(
            jnp.maximum(best, 0.0)), match_d)
        used_r = jnp.where(ok, used_r.at[r].set(True), used_r)
        used_c = jnp.where(ok, used_c.at[c2].set(True), used_c)
        return match_idx, match_d, used_r, used_c

    init = (jnp.full((m,), -1, jnp.int32), jnp.zeros((m,), dist_matrix.dtype),
            jnp.zeros((n,), bool), jnp.zeros((m,), bool))
    match_idx, match_d, _, _ = jax.lax.fori_loop(0, min(n, m), body, init)
    if match_type == "per_prediction":
        col_best = jnp.argmax(dist_matrix, axis=0)
        col_dist = jnp.max(dist_matrix, axis=0)
        extra = (match_idx < 0) & (col_dist > dist_threshold)
        match_idx = jnp.where(extra, col_best.astype(jnp.int32), match_idx)
        match_d = jnp.where(extra, col_dist, match_d)
    return match_idx, match_d


@register_op("target_assign")
def target_assign(input, matched_indices, negative_indices=None,
                  mismatch_value=0, name=None):
    """Gather rows of `input` [N,K] by matched_indices [M] (−1 → mismatch
    value, weight 0) → (out [M,K], out_weight [M,1]).
    Ref target_assign_op.h."""
    mi = matched_indices.reshape(-1)
    ok = mi >= 0
    safe = jnp.maximum(mi, 0)
    out = jnp.where(ok[:, None], input[safe],
                    jnp.asarray(mismatch_value, input.dtype))
    wt = ok.astype(input.dtype)[:, None]
    return out, wt


@register_op("detection_output")
def detection_output(loc, scores, prior_box, prior_box_var,
                     background_label=0, nms_threshold=0.3, nms_top_k=400,
                     keep_top_k=200, score_threshold=0.01, nms_eta=1.0,
                     name=None):
    """SSD head post-processing: decode against priors + multiclass NMS
    (ref layers/detection.py detection_output). loc [N,M,4], scores [N,M,C]
    (softmax-ed), priors [M,4]. Returns (out [N,keep_top_k,6], counts [N])."""
    decoded = jax.vmap(lambda l: _decode_ssd(prior_box, prior_box_var, l))(loc)
    sc = jnp.transpose(scores, (0, 2, 1))               # [N,C,M]
    return multiclass_nms.__pure_fn__(
        decoded, sc, score_threshold=score_threshold, nms_top_k=nms_top_k,
        keep_top_k=keep_top_k, nms_threshold=nms_threshold,
        nms_eta=nms_eta, background_label=background_label)


def _decode_ssd(prior, pvar, loc):
    pw = prior[:, 2] - prior[:, 0]
    ph_ = prior[:, 3] - prior[:, 1]
    pcx = prior[:, 0] + pw / 2
    pcy = prior[:, 1] + ph_ / 2
    t = loc * pvar
    cx = t[:, 0] * pw + pcx
    cy = t[:, 1] * ph_ + pcy
    bw = jnp.exp(t[:, 2]) * pw
    bh = jnp.exp(t[:, 3]) * ph_
    return jnp.stack([cx - bw / 2, cy - bh / 2, cx + bw / 2, cy + bh / 2], -1)


@register_op("polygon_box_transform")
def polygon_box_transform(input, name=None):
    """OCR quad offsets → absolute coords (ref polygon_box_transform_op.cc):
    even channels += 4*col_idx, odd channels += 4*row_idx, where input is
    [N, 8or9, H, W] offset maps (channels are x,y interleaved)."""
    n, c, h, w = input.shape
    col = jnp.arange(w, dtype=input.dtype)[None, :] * 4
    row = jnp.arange(h, dtype=input.dtype)[:, None] * 4
    is_x = (jnp.arange(c) % 2 == 0)[:, None, None]
    return jnp.where(is_x, col[None] - input, row[None] - input)


@register_op("mine_hard_examples")
def mine_hard_examples(cls_loss, loc_loss, match_indices, match_dist,
                       neg_pos_ratio=3.0, neg_dist_threshold=0.5,
                       sample_size=None, mining_type="max_negative",
                       name=None):
    """OHEM negative mining (ref mine_hard_examples_op.cc, max_negative mode).

    cls_loss/loc_loss [N,M]; match_indices [N,M] (−1 = unmatched). Returns
    neg_mask [N,M] bool marking selected negatives.
    """
    if mining_type == "hard_example":
        # ref mine_hard_examples_op.cc: kHardExample ranks cls+loc loss
        # over EVERY prior (IsEligibleMining is all-true), caps the
        # selection at sample_size, but only originally-unmatched
        # selected priors become negatives (matched ones stay positives)
        if sample_size is None:
            raise ValueError(
                "mining_type='hard_example' requires sample_size")
        loss = cls_loss if loc_loss is None else cls_loss + loc_loss
        eligible = jnp.ones_like(match_indices, dtype=bool)
        num_sel = jnp.full((cls_loss.shape[0],), int(sample_size),
                           jnp.int32)
        neg_only = match_indices < 0
    elif mining_type == "max_negative":
        # kMaxNegative ranks by cls_loss alone (loc_loss is only folded
        # in under kHardExample, mine_hard_examples_op.cc)
        loss = cls_loss
        eligible = (match_indices < 0) & (match_dist < neg_dist_threshold)
        num_pos = jnp.sum(match_indices >= 0, axis=1)
        num_sel = (num_pos * neg_pos_ratio).astype(jnp.int32)
        if sample_size is not None:
            num_sel = jnp.minimum(num_sel, sample_size)
        neg_only = True
    else:
        raise ValueError(f"unknown mining_type {mining_type!r}")
    sel_loss = jnp.where(eligible, loss, -jnp.inf)
    order = jnp.argsort(-sel_loss, axis=1)
    rank = jnp.argsort(order, axis=1)
    return eligible & (rank < num_sel[:, None]) & neg_only
