"""Statistics ops (paddle.tensor.stat parity)."""
from __future__ import annotations

import jax.numpy as jnp

from .registry import register_op

__all__ = ["std", "var", "numel_stat"]


@register_op("reduce_std")
def std(x, axis=None, unbiased=True, keepdim=False, name=None):
    ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
    return jnp.std(x, axis=ax, ddof=1 if unbiased else 0, keepdims=keepdim)


@register_op("reduce_var")
def var(x, axis=None, unbiased=True, keepdim=False, name=None):
    ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
    return jnp.var(x, axis=ax, ddof=1 if unbiased else 0, keepdims=keepdim)


def numel_stat(x):
    from .creation import numel
    return numel(x)
