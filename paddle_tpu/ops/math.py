"""Elementwise / reduction / math ops (paddle.tensor.math parity).

Reference surface: python/paddle/tensor/math.py + operators/elementwise/,
operators/reduce_ops/ in /root/reference. Every op is a pure jnp function
registered in the op registry; grads come from jax.vjp (no hand-written grad
kernels — XLA fuses the backward).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..framework import Tensor, _unwrap
from .registry import register_op

__all__ = [
    "floor_mod", "mm",
    "add", "subtract", "multiply", "divide", "floor_divide", "mod",
    "remainder", "pow", "float_power", "matmul", "abs", "sqrt", "rsqrt",
    "exp", "expm1", "log", "log2", "log10", "log1p", "sin", "cos", "tan",
    "asin", "acos", "atan", "sinh", "cosh", "tanh", "asinh", "acosh",
    "atanh", "atan2", "floor", "ceil", "round", "trunc", "frac", "sign",
    "square", "reciprocal", "neg", "clip", "maximum", "minimum", "fmax",
    "fmin", "sum", "mean", "max", "min", "prod", "nansum", "nanmean",
    "cumsum", "cumprod", "cummax", "cummin", "logsumexp", "logcumsumexp",
    "isnan", "isinf", "isfinite", "erf", "erfinv", "lerp", "addmm", "inner",
    "outer", "dot", "kron", "trace", "diff", "angle", "conj", "real", "imag",
    "deg2rad", "rad2deg", "gcd", "lcm", "heaviside", "rot90", "amax", "amin",
    "stanh", "rsub_", "logaddexp", "hypot", "ldexp", "copysign", "nextafter",
    "signbit", "scale", "increment", "multiply_", "add_n", "count_nonzero",
]


def _axis(axis):
    if axis is None:
        return None
    if isinstance(axis, Tensor):
        axis = axis.tolist()
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return int(axis)


# -- binary elementwise ------------------------------------------------------

@register_op("elementwise_add")
def add(x, y, name=None):
    return jnp.add(x, y)


@register_op("elementwise_sub")
def subtract(x, y, name=None):
    return jnp.subtract(x, y)


@register_op("elementwise_mul")
def multiply(x, y, name=None):
    return jnp.multiply(x, y)


@register_op("elementwise_div")
def divide(x, y, name=None):
    return jnp.true_divide(x, y)


@register_op("elementwise_floordiv")
def floor_divide(x, y, name=None):
    return jnp.floor_divide(x, y)


@register_op("elementwise_mod")
def mod(x, y, name=None):
    return jnp.mod(x, y)


remainder = mod


@register_op("elementwise_pow")
def pow(x, y, name=None):
    return jnp.power(x, y)


float_power = pow


@register_op("elementwise_max")
def maximum(x, y, name=None):
    return jnp.maximum(x, y)


@register_op("elementwise_min")
def minimum(x, y, name=None):
    return jnp.minimum(x, y)


@register_op("elementwise_fmax")
def fmax(x, y, name=None):
    return jnp.fmax(x, y)


@register_op("elementwise_fmin")
def fmin(x, y, name=None):
    return jnp.fmin(x, y)


@register_op("atan2")
def atan2(x, y, name=None):
    return jnp.arctan2(x, y)


@register_op("logaddexp")
def logaddexp(x, y, name=None):
    return jnp.logaddexp(x, y)


@register_op("hypot")
def hypot(x, y, name=None):
    return jnp.hypot(x, y)


@register_op("ldexp")
def ldexp(x, y, name=None):
    return jnp.ldexp(x, jnp.asarray(y, jnp.int32))


@register_op("copysign")
def copysign(x, y, name=None):
    return jnp.copysign(x, y)


@register_op("nextafter")
def nextafter(x, y, name=None):
    return jnp.nextafter(x, y)


@register_op("heaviside")
def heaviside(x, y, name=None):
    return jnp.heaviside(x, y)


@register_op("gcd")
def gcd(x, y, name=None):
    return jnp.gcd(jnp.asarray(x), jnp.asarray(y))


@register_op("lcm")
def lcm(x, y, name=None):
    return jnp.lcm(jnp.asarray(x), jnp.asarray(y))


# -- matmul family -----------------------------------------------------------

@register_op("matmul_v2")
def matmul(x, y, transpose_x=False, transpose_y=False, name=None):
    if transpose_x:
        x = jnp.swapaxes(x, -1, -2) if jnp.ndim(x) > 1 else x
    if transpose_y:
        y = jnp.swapaxes(y, -1, -2) if jnp.ndim(y) > 1 else y
    return jnp.matmul(x, y)


@register_op("addmm")
def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    return beta * input + alpha * jnp.matmul(x, y)


@register_op("inner")
def inner(x, y, name=None):
    return jnp.inner(x, y)


@register_op("outer")
def outer(x, y, name=None):
    return jnp.outer(x, y)


@register_op("dot")
def dot(x, y, name=None):
    return jnp.sum(x * y, axis=-1)


@register_op("kron")
def kron(x, y, name=None):
    return jnp.kron(x, y)


# -- unary -------------------------------------------------------------------

def _unary(opname, fn):
    @register_op(opname)
    def op(x, name=None):
        return fn(x)
    op.__name__ = opname
    return op


abs = _unary("abs", jnp.abs)
sqrt = _unary("sqrt", jnp.sqrt)
rsqrt = _unary("rsqrt", jax.lax.rsqrt)
exp = _unary("exp", jnp.exp)
expm1 = _unary("expm1", jnp.expm1)
log = _unary("log", jnp.log)
log2 = _unary("log2", jnp.log2)
log10 = _unary("log10", jnp.log10)
log1p = _unary("log1p", jnp.log1p)
sin = _unary("sin", jnp.sin)
cos = _unary("cos", jnp.cos)
tan = _unary("tan", jnp.tan)
asin = _unary("asin", jnp.arcsin)
acos = _unary("acos", jnp.arccos)
atan = _unary("atan", jnp.arctan)
sinh = _unary("sinh", jnp.sinh)
cosh = _unary("cosh", jnp.cosh)
tanh = _unary("tanh", jnp.tanh)
asinh = _unary("asinh", jnp.arcsinh)
acosh = _unary("acosh", jnp.arccosh)
atanh = _unary("atanh", jnp.arctanh)
floor = _unary("floor", jnp.floor)
ceil = _unary("ceil", jnp.ceil)
round = _unary("round", jnp.round)
trunc = _unary("trunc", jnp.trunc)
sign = _unary("sign", jnp.sign)
square = _unary("square", jnp.square)
reciprocal = _unary("reciprocal", lambda x: 1.0 / x)
neg = _unary("neg", jnp.negative)
erf = _unary("erf", jax.scipy.special.erf)
erfinv = _unary("erfinv", jax.scipy.special.erfinv)
angle = _unary("angle", jnp.angle)
conj = _unary("conj", jnp.conj)
real = _unary("real", jnp.real)
imag = _unary("imag", jnp.imag)
deg2rad = _unary("deg2rad", jnp.deg2rad)
rad2deg = _unary("rad2deg", jnp.rad2deg)
signbit = _unary("signbit", jnp.signbit)
isnan = _unary("isnan", jnp.isnan)
isinf = _unary("isinf", jnp.isinf)
isfinite = _unary("isfinite", jnp.isfinite)


@register_op("frac")
def frac(x, name=None):
    return x - jnp.trunc(x)


@register_op("stanh")
def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    return scale_b * jnp.tanh(scale_a * x)


@register_op("clip")
def clip(x, min=None, max=None, name=None):
    return jnp.clip(x, min, max)


@register_op("lerp")
def lerp(x, y, weight, name=None):
    return x + weight * (y - x)


@register_op("scale")
def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    out = x * scale + bias if bias_after_scale else (x + bias) * scale
    return out


def increment(x, value=1.0, name=None):
    x.set_value(_unwrap(x) + value)
    return x


def multiply_(x, y, name=None):
    x.set_value(_unwrap(x) * _unwrap(y))
    return x


def rsub_(x, y):
    return subtract(y, x)


@register_op("add_n")
def _add_n_impl(*xs):
    out = xs[0]
    for x in xs[1:]:
        out = out + x
    return out


def add_n(inputs, name=None):
    if isinstance(inputs, Tensor):
        return _add_n_impl(inputs)
    return _add_n_impl(*inputs)


# -- reductions --------------------------------------------------------------

@register_op("reduce_sum")
def sum(x, axis=None, dtype=None, keepdim=False, name=None):
    out = jnp.sum(x, axis=_axis(axis), keepdims=keepdim)
    return out.astype(dtype) if dtype is not None else out


@register_op("reduce_mean")
def mean(x, axis=None, keepdim=False, name=None):
    return jnp.mean(x, axis=_axis(axis), keepdims=keepdim)


@register_op("reduce_max")
def max(x, axis=None, keepdim=False, name=None):
    return jnp.max(x, axis=_axis(axis), keepdims=keepdim)


@register_op("reduce_min")
def min(x, axis=None, keepdim=False, name=None):
    return jnp.min(x, axis=_axis(axis), keepdims=keepdim)


amax, amin = max, min


@register_op("reduce_prod")
def prod(x, axis=None, keepdim=False, dtype=None, name=None):
    out = jnp.prod(x, axis=_axis(axis), keepdims=keepdim)
    return out.astype(dtype) if dtype is not None else out


@register_op("nansum")
def nansum(x, axis=None, dtype=None, keepdim=False, name=None):
    out = jnp.nansum(x, axis=_axis(axis), keepdims=keepdim)
    return out.astype(dtype) if dtype is not None else out


@register_op("nanmean")
def nanmean(x, axis=None, keepdim=False, name=None):
    return jnp.nanmean(x, axis=_axis(axis), keepdims=keepdim)


@register_op("logsumexp")
def logsumexp(x, axis=None, keepdim=False, name=None):
    return jax.scipy.special.logsumexp(x, axis=_axis(axis), keepdims=keepdim)


@register_op("count_nonzero")
def count_nonzero(x, axis=None, keepdim=False, name=None):
    return jnp.count_nonzero(x, axis=_axis(axis), keepdims=keepdim).astype(
        jnp.int64)


@register_op("cumsum")
def cumsum(x, axis=None, dtype=None, name=None):
    if axis is None:
        x = jnp.reshape(x, (-1,))
        axis = 0
    out = jnp.cumsum(x, axis=_axis(axis))
    return out.astype(dtype) if dtype is not None else out


@register_op("logcumsumexp")
def logcumsumexp(x, axis=None, name=None):
    if axis is None:
        x = jnp.reshape(x, (-1,))
        axis = 0
    return jax.lax.cumlogsumexp(x, axis=_axis(axis))


@register_op("cumprod")
def cumprod(x, dim=None, dtype=None, name=None):
    out = jnp.cumprod(x, axis=_axis(dim))
    return out.astype(dtype) if dtype is not None else out


@register_op("cummax")
def _cummax_impl(x, axis):
    vals = jax.lax.associative_scan(jnp.maximum, x, axis=axis)
    return vals


def cummax(x, axis=None, dtype="int64", name=None):
    a = _unwrap(x)
    if axis is None:
        x = x.reshape([-1]) if isinstance(x, Tensor) else Tensor(
            a.reshape(-1))
        axis = 0
    vals = _cummax_impl(x, axis=axis)
    idx = _running_arg(_unwrap(vals), _unwrap(x), axis)
    return vals, Tensor(idx.astype(jnp.int64))


def cummin(x, axis=None, dtype="int64", name=None):
    a = _unwrap(x)
    if axis is None:
        x = Tensor(a.reshape(-1)) if not isinstance(x, Tensor) else \
            x.reshape([-1])
        axis = 0
    vals = _cummin_impl(x, axis=axis)
    idx = _running_arg(_unwrap(vals), _unwrap(x), axis)
    return vals, Tensor(idx.astype(jnp.int64))


@register_op("cummin")
def _cummin_impl(x, axis):
    return jax.lax.associative_scan(jnp.minimum, x, axis=axis)


def _running_arg(vals, x, axis):
    # index where the running extreme was attained
    eq = vals == x
    n = x.shape[axis]
    ar = jnp.arange(n).reshape([-1 if i == axis % x.ndim else 1
                                for i in range(x.ndim)])
    idx = jnp.where(eq, ar, -1)
    return jax.lax.associative_scan(jnp.maximum, idx, axis=axis)


@register_op("trace_op")
def trace(x, offset=0, axis1=0, axis2=1, name=None):
    return jnp.trace(x, offset=offset, axis1=axis1, axis2=axis2)


@register_op("diff")
def diff(x, n=1, axis=-1, prepend=None, append=None, name=None):
    return jnp.diff(x, n=n, axis=axis, prepend=prepend, append=append)


@register_op("rot90")
def rot90(x, k=1, axes=(0, 1), name=None):
    return jnp.rot90(x, k=k, axes=tuple(axes))


# reference aliases (python/paddle/__init__.py DEFINE_ALIAS rows)
floor_mod = mod
mm = matmul
