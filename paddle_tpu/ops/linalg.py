"""Linear algebra ops (paddle.tensor.linalg / paddle.linalg parity).

Reference surface: python/paddle/tensor/linalg.py + cholesky/inverse/svd
ops in /root/reference/paddle/fluid/operators/. On TPU the decompositions
lower through XLA's linalg custom calls; matmuls hit the MXU.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..framework import Tensor, _unwrap
from .registry import register_op

__all__ = [
    "bmm", "mv", "norm", "vector_norm", "matrix_norm", "cholesky",
    "cholesky_solve", "inverse", "det", "slogdet", "svd", "qr", "lu", "eig",
    "eigh", "eigvals", "eigvalsh", "solve", "triangular_solve", "lstsq",
    "matrix_power", "matrix_rank", "pinv", "cross", "corrcoef",
    "cov", "histogram", "histogramdd", "bincount", "multi_dot", "dist",
]
# "cond" (matrix condition number) is deliberately NOT star-exported: the
# top-level `paddle.cond` is the control-flow op (ops/control_flow.py).
# The condition number stays reachable as paddle.linalg.cond.


@register_op("bmm")
def bmm(x, y, name=None):
    return jnp.matmul(x, y)


@register_op("mv")
def mv(x, vec, name=None):
    return jnp.matmul(x, vec)


@register_op("p_norm")
def norm(x, p=None, axis=None, keepdim=False, name=None):
    if p is None:
        p = "fro" if axis is None or not np.isscalar(axis) else 2
    if isinstance(axis, (list, tuple)) and len(axis) == 2:
        return jnp.linalg.norm(x, ord=p, axis=tuple(axis), keepdims=keepdim)
    if axis is None and p == "fro":
        return jnp.sqrt(jnp.sum(jnp.square(x)))
    if p == "inf":
        p = jnp.inf
    elif p == "-inf":
        p = -jnp.inf
    return jnp.linalg.norm(x, ord=p, axis=axis, keepdims=keepdim)


def vector_norm(x, p=2.0, axis=None, keepdim=False, name=None):
    return norm(x, p=p, axis=axis, keepdim=keepdim)


@register_op("matrix_norm")
def matrix_norm(x, p="fro", axis=(-2, -1), keepdim=False, name=None):
    return jnp.linalg.norm(x, ord=p, axis=tuple(axis), keepdims=keepdim)


@register_op("dist")
def dist(x, y, p=2, name=None):
    d = x - y
    if p == 0:
        return jnp.sum(d != 0).astype(d.dtype)
    if p == float("inf"):
        return jnp.max(jnp.abs(d))
    if p == float("-inf"):
        return jnp.min(jnp.abs(d))
    return jnp.power(jnp.sum(jnp.power(jnp.abs(d), p)), 1.0 / p)


@register_op("cholesky")
def cholesky(x, upper=False, name=None):
    L = jnp.linalg.cholesky(x)
    return jnp.swapaxes(L, -1, -2) if upper else L


@register_op("cholesky_solve")
def cholesky_solve(x, y, upper=False, name=None):
    L = jnp.swapaxes(y, -1, -2) if upper else y
    z = jax.scipy.linalg.solve_triangular(L, x, lower=True)
    return jax.scipy.linalg.solve_triangular(
        jnp.swapaxes(L, -1, -2), z, lower=False)


@register_op("inverse")
def inverse(x, name=None):
    return jnp.linalg.inv(x)


@register_op("determinant")
def det(x, name=None):
    return jnp.linalg.det(x)


@register_op("slogdeterminant")
def _slogdet_impl(x):
    sign, logdet = jnp.linalg.slogdet(x)
    return jnp.stack([sign, logdet])


def slogdet(x, name=None):
    return _slogdet_impl(x)


@register_op("svd_op")
def svd(x, full_matrices=False, name=None):
    u, s, vh = jnp.linalg.svd(x, full_matrices=full_matrices)
    return u, s, jnp.swapaxes(vh, -1, -2)


@register_op("qr_op")
def qr(x, mode="reduced", name=None):
    return jnp.linalg.qr(x, mode=mode)


@register_op("lu_op")
def lu(x, pivot=True, get_infos=False, name=None):
    lu_, piv = jax.scipy.linalg.lu_factor(x)
    return lu_, piv.astype(jnp.int32) + 1  # paddle returns 1-based pivots


@register_op("eig_op")
def eig(x, name=None):
    return jnp.linalg.eig(x)


@register_op("eigh_op")
def eigh(x, UPLO="L", name=None):
    return jnp.linalg.eigh(x, UPLO=UPLO)


@register_op("eigvals_op")
def eigvals(x, name=None):
    return jnp.linalg.eigvals(x)


@register_op("eigvalsh_op")
def eigvalsh(x, UPLO="L", name=None):
    return jnp.linalg.eigvalsh(x, UPLO=UPLO)


@register_op("solve_op")
def solve(x, y, name=None):
    return jnp.linalg.solve(x, y)


@register_op("triangular_solve_op")
def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False,
                     name=None):
    return jax.scipy.linalg.solve_triangular(
        x, y, lower=not upper, trans=1 if transpose else 0,
        unit_diagonal=unitriangular)


@register_op("lstsq_op")
def lstsq(x, y, rcond=None, driver=None, name=None):
    sol, res, rank, sv = jnp.linalg.lstsq(x, y, rcond=rcond)
    return sol, res, rank, sv


@register_op("matrix_power_op")
def matrix_power(x, n, name=None):
    return jnp.linalg.matrix_power(x, n)


@register_op("matrix_rank_op")
def matrix_rank(x, tol=None, hermitian=False, name=None):
    return jnp.linalg.matrix_rank(x, rtol=tol)


@register_op("pinv_op")
def pinv(x, rcond=1e-15, hermitian=False, name=None):
    return jnp.linalg.pinv(x, rtol=rcond, hermitian=hermitian)


@register_op("cross_op")
def cross(x, y, axis=9, name=None):
    if axis == 9:  # paddle default: first axis of size 3
        shape = x.shape
        axis = next((i for i, s in enumerate(shape) if s == 3), -1)
    return jnp.cross(x, y, axis=axis)


@register_op("cond_op")
def cond(x, p=None, name=None):
    return jnp.linalg.cond(x, p=p)


@register_op("corrcoef_op")
def corrcoef(x, rowvar=True, name=None):
    return jnp.corrcoef(x, rowvar=rowvar)


@register_op("cov_op")
def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None, name=None):
    return jnp.cov(x, rowvar=rowvar, ddof=1 if ddof else 0,
                   fweights=fweights, aweights=aweights)


@register_op("histogram_op")
def histogram(input, bins=100, min=0, max=0, name=None):
    lo, hi = (None, None) if (min == 0 and max == 0) else (min, max)
    if lo is None:
        lo, hi = jnp.min(input), jnp.max(input)
    hist, _ = jnp.histogram(input, bins=bins, range=(lo, hi))
    return hist.astype(jnp.int64)


def histogramdd(x, bins=10, ranges=None, density=False, weights=None,
                name=None):
    a = np.asarray(_unwrap(x))
    w = np.asarray(_unwrap(weights)) if weights is not None else None
    hist, edges = np.histogramdd(a, bins=bins, range=ranges, density=density,
                                 weights=w)
    return Tensor(jnp.asarray(hist)), [Tensor(jnp.asarray(e)) for e in edges]


@register_op("bincount_op")
def bincount(x, weights=None, minlength=0, name=None):
    length = max(int(np.asarray(_unwrap(x)).max(initial=0)) + 1, minlength)
    out = jnp.bincount(jnp.asarray(x), weights=weights, length=length)
    return out if weights is not None else out.astype(jnp.int64)


def multi_dot(x, name=None):
    arrays = [_unwrap(a) for a in x]
    from .registry import run_op
    return run_op("multi_dot", lambda *xs: jnp.linalg.multi_dot(xs),
                  tuple(x), {})
