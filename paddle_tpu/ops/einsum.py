"""einsum (paddle.einsum parity) — straight to jnp.einsum, which XLA maps
onto MXU contractions."""
from __future__ import annotations

import jax.numpy as jnp

from .registry import register_op

__all__ = ["einsum"]


@register_op("einsum")
def _einsum_impl(*operands, equation=""):
    return jnp.einsum(equation, *operands)


def einsum(equation, *operands):
    return _einsum_impl(*operands, equation=equation)
