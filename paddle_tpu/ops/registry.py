"""Op registry and eager dispatch.

The single op registry that feeds both the eager path and the compiled path —
capability-parity with the reference's OpRegistry/OperatorWithKernel dispatch
(/root/reference/paddle/fluid/framework/op_registry.h, operator.cc:1068
RunImpl, :1207 ChooseKernel) redesigned for XLA: an "op" here is a pure JAX
function. There is no kernel choice by (place, dtype, layout, library) —
XLA owns that — so OpInfo reduces to {name, pure_fn, metadata}. Gradients
come from jax.vjp instead of per-op grad makers; eager autograd records tape
nodes (see paddle_tpu.framework).

Eager dispatch order (the TraceOp analogue, tracer.cc:132):
  1. AMP autocast of inputs (amp_auto_cast.cc analogue, via hook)
  2. unwrap Tensors → jax arrays
  3. if grad required: jax.vjp(pure_fn)(arrays), record tape node
     else: pure_fn(arrays)
  4. NaN/Inf scan if FLAGS_check_nan_inf (nan_inf_utils_detail analogue)
  5. wrap outputs
"""
from __future__ import annotations

import contextlib
import functools
import threading
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core import enforce as _enforce
from ..core.flags import flag_value
from ..framework import Tensor, _unwrap, global_tape, is_grad_enabled
from ..observability import metrics as _obs

__all__ = ["register_op", "run_op", "get_op", "OPS", "op_wrapper"]


class OpInfo:
    __slots__ = ("name", "fn", "ndiff", "tags")

    def __init__(self, name, fn, ndiff=None, tags=()):
        self.name = name
        self.fn = fn
        self.ndiff = ndiff  # number of leading positional args that are
        # differentiable tensor inputs; None = all Tensor positionals
        self.tags = set(tags)


OPS: Dict[str, OpInfo] = {}

# hook installed by paddle_tpu.amp when an auto_cast context is active;
# signature: (op_name, args, kwargs) -> (args, kwargs)
_amp_hook: Optional[Callable] = None
_amp_lock = threading.Lock()


def set_amp_hook(hook):
    global _amp_hook
    with _amp_lock:
        _amp_hook = hook


# static-capture tracer installed by paddle_tpu.static.program_guard;
# signature: (op_type, pure_fn, args, kwargs) -> Var(s)
_static_tracer: Optional[Callable] = None


def set_static_tracer(tracer):
    global _static_tracer
    _static_tracer = tracer


@contextlib.contextmanager
def no_static_capture():
    """Suspend program capture inside a composite op's body.

    A captured op whose fn itself executes layers (the scanned ERNIE
    encoder) would otherwise re-enter the tracer during add_op's
    eval_shape: the inner ops get appended to the Program a second time
    with shape-inference tracers baked into their attrs. The composite
    is the op; its internals are not program structure."""
    global _static_tracer
    prev = _static_tracer
    _static_tracer = None
    try:
        yield
    finally:
        _static_tracer = prev


def get_op(name: str) -> OpInfo:
    if name not in OPS:
        raise _enforce.NotFoundError(f"op '{name}' is not registered")
    return OPS[name]


def register_op(name: str, tags=()):
    """Decorator: register a pure jax function as a framework op.

    Convention: positional args that arrive as Tensor/jax.Array are the
    differentiable inputs; keyword args are attributes (non-differentiable,
    tensors allowed but treated as constants).
    """
    def deco(fn):
        if name in OPS:
            raise ValueError(f"op '{name}' already registered")
        OPS[name] = OpInfo(name, fn, tags=tags)

        @functools.wraps(fn)
        def eager(*args, **kwargs):
            return run_op(name, fn, args, kwargs)
        eager.__op_name__ = name
        eager.__pure_fn__ = fn
        return eager
    return deco


def op_wrapper(fn, name=None):
    """Wrap an unregistered pure function for one-off eager execution."""
    nm = name or getattr(fn, "__name__", "anonymous")

    @functools.wraps(fn)
    def eager(*args, **kwargs):
        return run_op(nm, fn, args, kwargs)
    eager.__pure_fn__ = fn
    return eager


# ---------------------------------------------------------------------------
# eager fast path (SURVEY §7 hard-part (a); FLAGS_eager_op_jit)
#
# The slow path pays a fresh jax.vjp trace per grad-mode op (~0.7ms/op
# measured on CPU vs ~10µs for the math). The fast path caches, per
# (op, attribute-values) key, a jitted forward and a jitted
# recompute-backward (jax.vjp replayed inside jit — residuals are never
# stored; backward re-runs the forward, the standard TPU remat trade).
# jax.jit re-lowers per input aval automatically, so avals are not part
# of the key. Ops that cannot trace (data-dependent output shapes) fall
# back to the slow path and are blacklisted by name after one attempt.
# ---------------------------------------------------------------------------

_EAGER_FAST: Dict[Any, tuple] = {}
_EAGER_NOJIT: set = set()
_UNHASHABLE = object()


def _hashable(v):
    # numerics are tagged with their type: 2, 2.0 and True hash equal but
    # bake different dtypes/promotions into the cached closure
    if isinstance(v, (int, float, bool)):
        return (type(v).__name__, v)
    if isinstance(v, (str, type(None), bytes)):
        return v
    if isinstance(v, (np.integer, np.floating, np.bool_)):
        return (type(v.item()).__name__, v.item())
    if isinstance(v, np.dtype):
        return str(v)
    if isinstance(v, type):
        return v
    if isinstance(v, slice):  # getitem attrs
        return ("slice", _hashable(v.start), _hashable(v.stop),
                _hashable(v.step))
    if v is Ellipsis:
        return "..."
    if isinstance(v, (tuple, list)):
        items = tuple(_hashable(x) for x in v)
        return _UNHASHABLE if _UNHASHABLE in items else items
    if isinstance(v, dict):
        items = tuple(sorted((k, _hashable(x)) for k, x in v.items()))
        return (_UNHASHABLE if any(x is _UNHASHABLE for _, x in items)
                else items)
    if callable(v) and getattr(v, "__name__", None):
        return v  # function attributes (e.g. activations) key by identity
    return _UNHASHABLE


def _fast_entry(name, pure, plain_args, tensor_pos, plain_kwargs,
                tensor_keys):
    consts = tuple(_hashable(a) for i, a in enumerate(plain_args)
                   if i not in tensor_pos)
    kw = tuple(sorted((k, _hashable(v)) for k, v in plain_kwargs.items()
                      if k not in tensor_keys))
    if _UNHASHABLE in consts or any(v is _UNHASHABLE for _, v in kw):
        return None
    key = (name, tuple(tensor_pos), tuple(tensor_keys), consts, kw)
    entry = _EAGER_FAST.get(key)
    if entry is None:
        fwd = jax.jit(pure)

        def bwd(diff, cts):
            return jax.vjp(pure, *diff)[1](cts)

        entry = (fwd, jax.jit(bwd))
        _EAGER_FAST[key] = entry
    return entry


def _check_nan_inf(name, arrays):
    for a in arrays:
        if isinstance(a, jax.Array) and jnp.issubdtype(a.dtype, jnp.inexact):
            if not bool(jnp.isfinite(a).all()):
                raise _enforce.EnforceNotMet(
                    f"NaN or Inf found in output of op", op_type=name)


_cfast_checked = False
_cfast = None


def _slow_flags():
    """Debug flags that must see every op on the python path."""
    return (flag_value("check_nan_inf") or flag_value("op_stats")
            or not flag_value("eager_op_jit"))


def _get_cfast():
    global _cfast, _cfast_checked
    if not _cfast_checked:
        _cfast_checked = True
        from .cfast import cfast_module
        _cfast = cfast_module()
    return _cfast


def run_op(name: str, fn: Callable, args: tuple, kwargs: dict):
    """Execute one op eagerly, recording a tape node if grads are needed.
    Under a program_guard, append to the captured Program instead."""
    if _static_tracer is not None:
        return _static_tracer(name, fn, args, kwargs)
    if _obs._enabled:
        # per-op dispatch counter (monitor.h STAT_ADD wired into TraceOp;
        # the disabled path above is one module-bool read)
        _obs.counter("op.dispatch.total", op=name).add(1)
    if _amp_hook is not None:
        args, kwargs = _amp_hook(name, args, kwargs)
    elif _cfast is not None or not _cfast_checked:
        # C fast path (op_function_generator.cc core.ops analogue):
        # no-grad scalar-attr calls dispatch fully in C — scan, cache
        # key, jit call, Tensor wrap — and return here. NotImplemented
        # = fall through to the python path (grads, complex attrs,
        # rng/mesh ops). Debug flags force the python path so their
        # hooks still observe every op.
        cf = _cfast if _cfast is not None else _get_cfast()
        if cf is not None and not _slow_flags():
            from .. import profiler as _profiler
            if not _profiler._enabled:
                try:
                    res = cf.fast_op(name, fn, args, kwargs,
                                     is_grad_enabled())
                except _enforce.EnforceNotMet:
                    raise
                except Exception as e:
                    # same op attribution the python path gives
                    raise _enforce.wrap_op_error(name, e) from e
                if res is not NotImplemented:
                    return res

    # split positional args and kwargs into diff-tensor slots and
    # pass-through slots; Tensor/jax.Array in either position is a
    # differentiable input
    tensor_pos = []
    tensor_keys = []
    arrays = []
    input_tensors = []
    plain_args = list(args)
    for i, a in enumerate(plain_args):
        if isinstance(a, Tensor):
            tensor_pos.append(i)
            arrays.append(a._data)
            input_tensors.append(a)
        elif isinstance(a, jax.Array):
            tensor_pos.append(i)
            arrays.append(a)
            input_tensors.append(None)
    plain_kwargs = dict(kwargs)
    for k, v in kwargs.items():
        if isinstance(v, Tensor):
            tensor_keys.append(k)
            arrays.append(v._data)
            input_tensors.append(v)
        elif isinstance(v, jax.Array):
            tensor_keys.append(k)
            arrays.append(v)
            input_tensors.append(None)

    requires = (is_grad_enabled()
                and any(t is not None and not t.stop_gradient
                        for t in input_tensors))

    npos = len(tensor_pos)

    # sanitized templates: tensor slots cleared so the pure closure (which
    # the fast path caches) never pins call-time tensors alive
    tset = set(tensor_pos)
    arg_template = tuple(None if i in tset else a
                         for i, a in enumerate(plain_args))
    kw_template = {k: (None if k in tensor_keys else v)
                   for k, v in plain_kwargs.items()}

    def pure(*diff):
        full = list(arg_template)
        for pos, val in zip(tensor_pos, diff[:npos]):
            full[pos] = val
        kw = dict(kw_template)
        for key, val in zip(tensor_keys, diff[npos:]):
            kw[key] = val
        res = fn(*full, **kw)
        # normalize list outputs to tuple so vjp cotangent structure is stable
        return tuple(res) if isinstance(res, list) else res

    fast = None
    if name not in _EAGER_NOJIT and flag_value("eager_op_jit"):
        info = OPS.get(name)
        # only registry fns are cacheable: ad-hoc closures passed to
        # run_op (rnn cell steps) capture call state the key can't see,
        # "rng"-tagged ops draw generator keys inside the fn body — jit
        # would freeze the first key as a constant — and "mesh"-tagged
        # ops resolve the global device mesh at call time (a cached
        # closure would pin a retired mesh)
        if info is not None and info.fn is fn and "rng" not in info.tags \
                and "mesh" not in info.tags:
            fast = _fast_entry(name, pure, plain_args, tensor_pos,
                               plain_kwargs, tensor_keys)

    vjp_fn = None
    try:
        from .. import profiler as _profiler
        span = (_profiler.RecordEvent(name, "Operator")
                if _profiler._enabled else contextlib.nullcontext())
        with span:
            if fast is not None:
                fwd_jit, bwd_jit = fast
                try:
                    out = fwd_jit(*arrays)
                    if requires:
                        in_tuple = tuple(arrays)

                        def vjp_fn(cts, _b=bwd_jit, _a=in_tuple):
                            return _b(_a, cts)
                except _enforce.EnforceNotMet:
                    raise
                except Exception:
                    # not traceable (data-dependent shapes etc.): run the
                    # slow path; blacklist the op only if that succeeds
                    if requires:
                        out, vjp_fn = jax.vjp(pure, *arrays)
                    else:
                        out = pure(*arrays)
                    _EAGER_NOJIT.add(name)
                    if _obs._enabled:
                        _obs.counter("op.fallback.total", op=name).add(1)
            elif requires:
                out, vjp_fn = jax.vjp(pure, *arrays)
            else:
                out = pure(*arrays)
    except _enforce.EnforceNotMet:
        raise
    except Exception as e:  # attach op attribution (op_call_stack analogue)
        raise _enforce.wrap_op_error(name, e) from e

    multi = isinstance(out, (tuple, list))
    outs = list(out) if multi else [out]

    if flag_value("check_nan_inf"):
        _check_nan_inf(name, outs)
    if flag_value("op_stats"):
        from ..core.monitor import stat
        stat(f"op.{name}.count").add(1)

    out_tensors = [
        o if isinstance(o, Tensor)
        else Tensor(o, stop_gradient=not requires)
        for o in outs
    ]
    if requires:
        global_tape().record(name, vjp_fn, input_tensors, out_tensors,
                             multi=multi, pure=pure, in_arrays=arrays)
    if multi:
        return tuple(out_tensors)
    return out_tensors[0]
