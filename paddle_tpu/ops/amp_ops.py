"""AMP graph ops: check_finite_and_unscale / update_loss_scaling.

Reference: operators/amp/check_finite_and_unscale_op.cc (inputs X...,
Scale -> Out..., FoundInfinite) and update_loss_scaling_op.cc
(FoundInfinite + counters -> new LossScaling/counters). The reference
implements these as graph ops so fp16 loss scaling never syncs to the
host; here the same contract is a registered op over varargs tensors,
and static/train_step.py composes the pytree forms
(amp/functional.py) directly into the compiled step.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..amp.functional import (check_finite_and_unscale_tree,
                              update_loss_scaling_state)
from .registry import register_op

__all__ = ["check_finite_and_unscale", "update_loss_scaling"]


@register_op("check_finite_and_unscale")
def check_finite_and_unscale(*xs, scale=None):
    """Unscale xs by 1/scale; last output is the found_infinite flag.

    Returns (x0/scale, ..., xn/scale, found_inf). Unlike the reference
    (which leaves Out undefined when FoundInfinite), outputs are always
    the unscaled values — callers gate the optimizer update on the flag
    (the TrainStep does this with jnp.where).
    """
    if scale is None:
        raise ValueError("check_finite_and_unscale requires scale=")
    out, found_inf = check_finite_and_unscale_tree(
        list(xs), jnp.asarray(scale))
    return tuple(out) + (found_inf,)


@register_op("update_loss_scaling")
def update_loss_scaling(found_inf, prev_loss_scaling, in_good_steps,
                        in_bad_steps, incr_ratio=2.0, decr_ratio=0.5,
                        incr_every_n_steps=1000,
                        decr_every_n_nan_or_inf=1, stop_update=False):
    """Dynamic loss-scale update (update_loss_scaling_op.cc contract).

    Returns (loss_scaling, good_steps, bad_steps).
    """
    scale, good, bad = update_loss_scaling_state(
        jnp.asarray(prev_loss_scaling, jnp.float32),
        jnp.asarray(in_good_steps, jnp.int32),
        jnp.asarray(in_bad_steps, jnp.int32),
        jnp.asarray(found_inf, bool),
        incr_ratio=incr_ratio, decr_ratio=decr_ratio,
        incr_every_n=incr_every_n_steps,
        decr_every_n=decr_every_n_nan_or_inf)
    if stop_update:
        return (jnp.asarray(prev_loss_scaling, jnp.float32),
                jnp.asarray(in_good_steps, jnp.int32),
                jnp.asarray(in_bad_steps, jnp.int32))
    return scale, good, bad
