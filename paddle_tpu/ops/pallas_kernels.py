"""Pallas TPU kernels for the hot ops.

The reference implements its hot paths as hand-written CUDA
(/root/reference/paddle/fluid/operators/fused/multihead_matmul_op.cu,
fused_elemwise_activation, the cuDNN bindings). The TPU-native equivalent is
a small set of Pallas/Mosaic kernels that own the MXU/VMEM schedule where XLA
fusion is not enough. This module provides flash attention (forward +
backward) as blocked online-softmax kernels:

- forward: grid (batch*heads, q_blocks, k_blocks); q/k/v tiles staged in
  VMEM, accumulator + running (m, l) stats in VMEM scratch that persists
  across the sequential k-block grid dimension; emits O and the per-row
  logsumexp needed by the backward.
- backward: the standard two-kernel split — a dq kernel iterating k-blocks
  innermost, and a dk/dv kernel iterating q-blocks innermost — each
  recomputing P = exp(QK^T·scale − lse) on the fly (no O(s²) residuals).

Everything is O(seq·block) memory, causal blocks above the diagonal are
skipped, and inputs are padded to MXU-friendly (128, 128) tiles. The
portable lax.scan reference lives in paddle_tpu.nn.functional.attention;
correctness of this kernel is tested against it (interpret mode on CPU,
compiled on TPU).
"""
from __future__ import annotations

import functools
import os
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["flash_attention_mha", "pallas_available"]

# Max block sizes along the q/k sequence dims. Large blocks amortize the
# per-grid-step overhead (DMA setup + Mosaic loop) — with head_dim 64 a
# 128x128 block is only ~4 MFLOP, far too little to hide ~1us/step; 512-wide
# blocks put ~134 MFLOP per step while staying well under VMEM (~1.5 MB).
# Env-tunable (PD_FLASH_BQ / PD_FLASH_BK) so a hardware session can sweep
# per-generation VMEM sweet spots without code edits. Values must be
# 128-multiples (>= 128): _pick_block would otherwise silently round,
# turning a sweep data point into a duplicate measurement.


def _block_env(name: str, default: int) -> int:
    v = int(os.environ.get(name, default))
    if v < 128 or v % 128:
        raise ValueError(
            f"{name}={v} invalid: flash block sizes must be multiples "
            "of 128 (MXU tile), >= 128")
    return v


_BQ = _block_env("PD_FLASH_BQ", 512)
_BK = _block_env("PD_FLASH_BK", 512)
_NEG = -1e30


def pallas_available() -> bool:
    """True when a TPU backend (incl. the axon plugin) is the default."""
    try:
        return jax.devices()[0].platform in ("tpu", "axon")
    except Exception:  # pragma: no cover - no backend at all
        return False


@functools.lru_cache(maxsize=1)
def kernel_dropout_available() -> bool:
    """Self-check of the in-kernel dropout path on the current backend.

    The Pallas TPU interpreter stubs prng_random_bits to zeros (every
    link dropped), so the dropout kernel must only be trusted where a
    tiny probe shows real RNG behavior: deterministic per seed,
    seed-sensitive, and not degenerate. Cached per process; callers
    fall back to SDPA-with-dropout when this fails.

    PD_KERNEL_DROPOUT=0/1 overrides the probe entirely: a degraded
    tunnel can stall any device work, and this probe runs in-process
    (a subprocess cannot share the exclusively-held TPU), so a
    supervisor that already probed in a throwaway process can pin the
    decision and keep the main run hang-safe."""
    forced = (os.environ.get("PD_KERNEL_DROPOUT") or "").strip().lower()
    if forced in ("0", "false", "no"):
        return False
    if not pallas_available():
        # a stale =1 pin must not route dropout into a kernel that
        # cannot run here (e.g. the pin leaked onto a CPU-only host)
        return False
    if forced:
        return True
    try:
        import numpy as np
        rng = np.random.RandomState(0)
        q = jnp.asarray(rng.randn(1, 128, 1, 64), jnp.float32)
        base = np.asarray(flash_attention_mha(q, q, q))
        a = np.asarray(flash_attention_mha(q, q, q, dropout_p=0.5,
                                           seed=3))
        a2 = np.asarray(flash_attention_mha(q, q, q, dropout_p=0.5,
                                            seed=3))
        b = np.asarray(flash_attention_mha(q, q, q, dropout_p=0.5,
                                           seed=4))
        # the backward kernels REGENERATE the mask (same prng_seed
        # path but their own Mosaic lowering) — a training step hits
        # them immediately, so the probe must too, or a bwd-only
        # rejection would crash the step instead of falling back
        g = np.asarray(jax.grad(
            lambda q: flash_attention_mha(q, q, q, dropout_p=0.5,
                                          seed=3).sum())(q))
        return (np.allclose(a, a2)
                and np.abs(a - b).max() > 1e-6
                and np.abs(a).max() > 1e-6
                and np.abs(a - base).max() > 1e-6
                and np.isfinite(g).all()
                and np.abs(g).max() > 1e-6)
    except Exception:  # pragma: no cover — kernel/backend quirk
        return False


def _ceil_to(x: int, m: int) -> int:
    return (x + m - 1) // m * m


def _pick_block(s: int, bmax: int) -> tuple:
    """Pad s to 128-row tiles and pick the largest block ≤ bmax that divides
    the padded length — so padding waste is bounded by one 128 tile, never
    a full 512 block (sq=520 pads to 640 with bq=128, not to 1024)."""
    s_p = _ceil_to(s, 128)
    nb = s_p // 128
    for kt in range(min(bmax // 128, nb), 0, -1):
        if nb % kt == 0:
            return s_p, 128 * kt
    return s_p, 128


def _masked_probs(q, k, lse_row, i, j, *, scale, causal, bq, bk, sk):
    """Shared logits→probabilities block for the backward kernels:
    P = exp(QK^T·scale − lse) with key-padding and causal masks. The forward
    kernel computes its own online-softmax variant of the same masking —
    keep the mask logic here and there in sync."""
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * scale
    col = j * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    mask = col < sk
    if causal:
        row = i * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
        mask = mask & (row >= col)
    s = jnp.where(mask, s, _NEG)
    p = jnp.exp(s - lse_row[:, None])
    return jnp.where(mask, p, 0.0)


# ---------------------------------------------------------------- forward

def _drop_mask(seed_ref, bh, i, j, nq, nk, bq, bk, dropout_p):
    """Deterministic per-(batch·head, q-block, k-block) keep mask: the
    backward kernels REGENERATE the forward's mask from the same seed
    tuple instead of storing an O(s²) mask (the flash-dropout trick).

    Mosaic on real TPU rejects prng_seed with >2 values ("Setting seed
    with more than 2 values is not supported", v5e libtpu 0.0.34), so
    the (bh, i, j) block coordinate folds into ONE collision-free
    linear index (nq/nk are static grid bounds) and we seed with
    exactly (user_seed, block_index)."""
    block_index = (bh * nq + i) * nk + j
    pltpu.prng_seed(seed_ref[0], block_index)
    bits = pltpu.bitcast(pltpu.prng_random_bits((bq, bk)), jnp.uint32)
    threshold = jnp.uint32(min(int(dropout_p * 4294967296.0),
                               4294967295))
    return bits >= threshold  # keep with prob 1 - p


def _fwd_kernel(seed_ref, q_ref, k_ref, v_ref, o_ref, lse_ref,
                acc_ref, m_ref, l_ref,
                *, scale, causal, bq, bk, nq, nk, sk, dropout_p):
    bh = pl.program_id(0)
    i = pl.program_id(1)
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG)
        l_ref[:] = jnp.zeros_like(l_ref)

    # causal: skip blocks strictly above the diagonal
    run = True
    if causal:
        run = j * bk <= (i + 1) * bq - 1

    @pl.when(run)
    def _():
        q = q_ref[0]
        k = k_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        col = j * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
        mask = col < sk
        if causal:
            row = i * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            mask = mask & (row >= col)
        s = jnp.where(mask, s, _NEG)

        m_prev = m_ref[:, 0]
        m_cur = jnp.max(s, axis=-1)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new[:, None])
        p = jnp.where(mask, p, 0.0)
        corr = jnp.exp(m_prev - m_new)
        # the softmax denominator sums over ALL links (dropout zeroes
        # entries of the NORMALIZED probs), so l uses the unmasked p
        l_new = l_ref[:, 0] * corr + jnp.sum(p, axis=-1)
        if dropout_p > 0.0:
            keep = _drop_mask(seed_ref, bh, i, j, nq, nk, bq, bk, dropout_p)
            p = jnp.where(keep, p / (1.0 - dropout_p), 0.0)
        pv = jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        acc_ref[:] = acc_ref[:] * corr[:, None] + pv
        m_ref[:] = jnp.broadcast_to(m_new[:, None], m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new[:, None], l_ref.shape)

    @pl.when(j == nk - 1)
    def _():
        l = l_ref[:, 0]
        l_safe = jnp.maximum(l, 1e-30)
        o_ref[0] = (acc_ref[:] / l_safe[:, None]).astype(o_ref.dtype)
        # lse stored sublane-replicated (8, bq) to satisfy TPU tiling
        lse = m_ref[:, 0] + jnp.log(l_safe)
        lse_ref[0] = jnp.broadcast_to(lse[None, :], lse_ref.shape[1:])


def _flash_fwd_pallas(q, k, v, causal, scale, interpret, dropout_p=0.0,
                      seed=None):
    """q,k,v: [bh, s, h] padded to (128,128) tiles. Returns (o, lse)."""
    bh, sq, h = q.shape
    sk = k.shape[1]
    sq_p, bq = _pick_block(sq, _BQ)
    sk_p, bk = _pick_block(sk, _BK)
    h_p = h
    q = jnp.pad(q, ((0, 0), (0, sq_p - sq), (0, 0)))
    k = jnp.pad(k, ((0, 0), (0, sk_p - sk), (0, 0)))
    v = jnp.pad(v, ((0, 0), (0, sk_p - sk), (0, 0)))
    nq, nk = sq_p // bq, sk_p // bk
    seed_arr = jnp.asarray(
        [0 if seed is None else seed], jnp.int32).reshape(1)

    kern = functools.partial(
        _fwd_kernel, scale=scale, causal=causal,
        bq=bq, bk=bk, nq=nq, nk=nk, sk=sk, dropout_p=float(dropout_p))
    o, lse = pl.pallas_call(
        kern,
        grid=(bh, nq, nk),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((1, bq, h_p), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bk, h_p), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, bk, h_p), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, h_p), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, 8, bq), lambda b, i, j: (b, 0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sq_p, h_p), q.dtype),
            jax.ShapeDtypeStruct((bh, 8, sq_p), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, h_p), jnp.float32),
            pltpu.VMEM((bq, 128), jnp.float32),
            pltpu.VMEM((bq, 128), jnp.float32),
        ],
        interpret=interpret,
    )(seed_arr, q, k, v)
    return o[:, :sq, :h], lse[:, 0, :sq]


# --------------------------------------------------------------- backward

def _dq_kernel(seed_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
               dq_ref, acc_ref, *, scale, causal, bq, bk, nq, nk, sk,
               dropout_p):
    bh = pl.program_id(0)
    i = pl.program_id(1)
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    run = True
    if causal:
        run = j * bk <= (i + 1) * bq - 1

    @pl.when(run)
    def _():
        k = k_ref[0]
        p = _masked_probs(q_ref[0], k, lse_ref[0, 0], i, j, scale=scale,
                          causal=causal, bq=bq, bk=bk, sk=sk)
        dp = jax.lax.dot_general(
            do_ref[0], v_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        if dropout_p > 0.0:
            keep = _drop_mask(seed_ref, bh, i, j, nq, nk, bq, bk, dropout_p)
            dp = jnp.where(keep, dp / (1.0 - dropout_p), 0.0)
        ds = p * (dp - delta_ref[0, 0][:, None])
        acc_ref[:] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * scale

    @pl.when(j == nk - 1)
    def _():
        dq_ref[0] = acc_ref[:].astype(dq_ref.dtype)


def _dkv_kernel(seed_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, dk_acc, dv_acc,
                *, scale, causal, bq, bk, nq, nk, sk, dropout_p):
    bh = pl.program_id(0)
    j = pl.program_id(1)  # k block
    i = pl.program_id(2)  # q block (innermost)

    @pl.when(i == 0)
    def _():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    run = True
    if causal:
        run = (i + 1) * bq - 1 >= j * bk

    @pl.when(run)
    def _():
        q = q_ref[0]
        do = do_ref[0]
        p = _masked_probs(q, k_ref[0], lse_ref[0, 0], i, j, scale=scale,
                          causal=causal, bq=bq, bk=bk, sk=sk)
        if dropout_p > 0.0:
            # same seed tuple (bh, q-block i, k-block j) as the forward
            keep = _drop_mask(seed_ref, bh, i, j, nq, nk, bq, bk, dropout_p)
            pd = jnp.where(keep, p / (1.0 - dropout_p), 0.0)
        else:
            pd = p
        # dv += (dropout(P))^T @ dO
        pt = pd.astype(do.dtype)
        dv_acc[:] += jax.lax.dot_general(
            pt, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(
            do, v_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        if dropout_p > 0.0:
            dp = jnp.where(keep, dp / (1.0 - dropout_p), 0.0)
        ds = p * (dp - delta_ref[0, 0][:, None])
        # dk += dS^T @ Q * scale
        dk_acc[:] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * scale

    @pl.when(i == nq - 1)
    def _():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _flash_bwd_pallas(q, k, v, o, lse, do, causal, scale, interpret,
                      dropout_p=0.0, seed=None):
    bh, sq, h = q.shape
    sk = k.shape[1]
    sq_p, bq = _pick_block(sq, _BQ)
    sk_p, bk = _pick_block(sk, _BK)
    h_p = h
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)
    qp = jnp.pad(q, ((0, 0), (0, sq_p - sq), (0, 0)))
    kp = jnp.pad(k, ((0, 0), (0, sk_p - sk), (0, 0)))
    vp = jnp.pad(v, ((0, 0), (0, sk_p - sk), (0, 0)))
    dop = jnp.pad(do, ((0, 0), (0, sq_p - sq), (0, 0)))
    # padded q rows: lse=0 → p=exp(-1e30)≈0 under mask anyway; keep 0.
    # lse/delta carried sublane-replicated (bh, 8, sq) for TPU tiling.
    lsep = jnp.broadcast_to(
        jnp.pad(lse, ((0, 0), (0, sq_p - sq)))[:, None, :], (bh, 8, sq_p))
    deltap = jnp.broadcast_to(
        jnp.pad(delta, ((0, 0), (0, sq_p - sq)))[:, None, :], (bh, 8, sq_p))
    nq, nk = sq_p // bq, sk_p // bk
    seed_arr = (jnp.zeros((1,), jnp.int32) if seed is None
                else jnp.asarray(seed, jnp.int32).reshape(1))

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, causal=causal,
                          bq=bq, bk=bk, nq=nq, nk=nk, sk=sk,
                          dropout_p=float(dropout_p)),
        grid=(bh, nq, nk),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((1, bq, h_p), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bk, h_p), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, bk, h_p), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, bq, h_p), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, 8, bq), lambda b, i, j: (b, 0, i)),
            pl.BlockSpec((1, 8, bq), lambda b, i, j: (b, 0, i)),
        ],
        out_specs=pl.BlockSpec((1, bq, h_p), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, sq_p, h_p), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, h_p), jnp.float32)],
        interpret=interpret,
    )(seed_arr, qp, kp, vp, dop, lsep, deltap)

    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, causal=causal,
                          bq=bq, bk=bk, nq=nq, nk=nk, sk=sk,
                          dropout_p=float(dropout_p)),
        grid=(bh, nk, nq),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((1, bq, h_p), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, bk, h_p), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, bk, h_p), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, bq, h_p), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, 8, bq), lambda b, j, i: (b, 0, i)),
            pl.BlockSpec((1, 8, bq), lambda b, j, i: (b, 0, i)),
        ],
        out_specs=[
            pl.BlockSpec((1, bk, h_p), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, bk, h_p), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sk_p, h_p), k.dtype),
            jax.ShapeDtypeStruct((bh, sk_p, h_p), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, h_p), jnp.float32),
            pltpu.VMEM((bk, h_p), jnp.float32),
        ],
        interpret=interpret,
    )(seed_arr, qp, kp, vp, dop, lsep, deltap)

    return dq[:, :sq, :h], dk[:, :sk, :h], dv[:, :sk, :h]


# ------------------------------------------------------------- public API

@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def _flash_mha(q, k, v, seed, causal, scale, interpret, dropout_p):
    o, _ = _flash_fwd_pallas(q, k, v, causal, scale, interpret,
                             dropout_p=dropout_p, seed=seed)
    return o


def _flash_mha_fwd(q, k, v, seed, causal, scale, interpret, dropout_p):
    o, lse = _flash_fwd_pallas(q, k, v, causal, scale, interpret,
                               dropout_p=dropout_p, seed=seed)
    return o, (q, k, v, seed, o, lse)


def _flash_mha_bwd(causal, scale, interpret, dropout_p, res, do):
    q, k, v, seed, o, lse = res
    dq, dk, dv = _flash_bwd_pallas(q, k, v, o, lse, do, causal, scale,
                                   interpret, dropout_p=dropout_p,
                                   seed=seed)
    import numpy as np
    dseed = np.zeros(np.shape(seed), jax.dtypes.float0)
    return dq, dk, dv, dseed


_flash_mha.defvjp(_flash_mha_fwd, _flash_mha_bwd)


def flash_attention_mha(query, key, value, causal=False, scale=None,
                        interpret=False, dropout_p=0.0, seed=None):
    """Flash attention over [batch, seq, num_heads, head_dim] inputs.

    Pallas TPU kernel (Mosaic) with custom VJP; O(seq·block) memory.
    dropout_p applies attention-probs dropout INSIDE the kernel (the
    backward regenerates each block's keep-mask from (seed, block)
    instead of storing it); `seed` is a traced int32 scalar — vary it
    per training step. `interpret=True` runs the same kernels under the
    Pallas interpreter (used by the CPU test suite).
    """
    b, sq, n, h = query.shape
    sk = key.shape[1]
    if scale is None:
        scale = 1.0 / math.sqrt(h)
    # pad head_dim up to a full 128-lane tile for Mosaic; zero columns are
    # exact no-ops for QK^T, PV, and all three gradients, sliced off below
    h_p = _ceil_to(h, 128)
    q = jnp.einsum("bsnh->bnsh", query).reshape(b * n, sq, h)
    k = jnp.einsum("bsnh->bnsh", key).reshape(b * n, sk, h)
    v = jnp.einsum("bsnh->bnsh", value).reshape(b * n, sk, h)
    if h_p != h:
        pad = ((0, 0), (0, 0), (0, h_p - h))
        q, k, v = jnp.pad(q, pad), jnp.pad(k, pad), jnp.pad(v, pad)
    seed_arr = (jnp.zeros((1,), jnp.int32) if seed is None
                else jnp.asarray(seed, jnp.int32).reshape(1))
    o = _flash_mha(q, k, v, seed_arr, bool(causal), float(scale),
                   bool(interpret), float(dropout_p))
    return jnp.einsum("bnsh->bsnh", o.reshape(b, n, sq, h_p)[..., :h])
