"""TensorArray + array ops (reference LoDTensorArray capability).

Reference: framework/lod_tensor_array.h, operators/tensor_array_to_tensor_op.cc,
operators/array_operator.h (write_to_array / read_from_array),
operators/controlflow/ array ops and lod_array_length_op.cc.

TPU design: the reference's TensorArray is the mutable spine of its
while-loop RNNs. Here eager code gets a functional python-list TensorArray
(writes return a new array — fits the tape), while *compiled* loops use
lax.scan's native stacking instead; tensor_array_to_tensor is a registered
op so the concat/stack step itself is jit-able.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..framework import Tensor, _unwrap
from .registry import register_op

__all__ = ["TensorArray", "write_to_array", "read_from_array",
           "array_write", "array_read",
           "array_length", "tensor_array_to_tensor", "create_array"]


class TensorArray:
    """Functional tensor array: write returns a new TensorArray sharing
    unwritten slots (structural sharing via list copy)."""

    def __init__(self, items=None):
        self._items = list(items or [])

    def write(self, i, x):
        i = int(_unwrap(i))
        items = list(self._items)
        if i == len(items):
            items.append(x)
        elif i < len(items):
            items[i] = x
        else:
            items.extend([None] * (i - len(items)))
            items.append(x)
        return TensorArray(items)

    def append(self, x):
        return self.write(len(self._items), x)

    def read(self, i):
        return self._items[int(_unwrap(i))]

    def __len__(self):
        return len(self._items)

    def __iter__(self):
        return iter(self._items)

    def stack(self, axis=0):
        from .manipulation import stack
        return stack(list(self._items), axis=axis)

    def concat(self, axis=0):
        from .manipulation import concat
        return concat(list(self._items), axis=axis)


def create_array(dtype="float32", initialized_list=None):
    """paddle.tensor.create_array parity."""
    return TensorArray(initialized_list)


def write_to_array(array, i, x):
    """ref write_to_array op: array[i] = x (functional — returns the new
    array)."""
    if array is None:
        array = TensorArray()
    return array.write(i, x)


def read_from_array(array, i):
    """ref read_from_array op."""
    return array.read(i)


def array_length(array):
    """ref lod_array_length op."""
    return len(array)


# paddle.tensor namespace names for the same ops; note array_write's
# reference signature is (x, i, array=None) — tensor/array.py:89
def array_write(x, i, array=None):
    return write_to_array(array, i, x)


array_read = read_from_array


@register_op("tensor_array_to_tensor")
def _tensor_array_to_tensor_impl(*xs, axis=0, use_stack=False):
    if use_stack:
        out = jnp.stack(xs, axis=axis)
        index = jnp.full((len(xs),), 1, jnp.int32)
    else:
        out = jnp.concatenate(xs, axis=axis)
        index = jnp.asarray([x.shape[axis] for x in xs], jnp.int32)
    return out, index


def tensor_array_to_tensor(array, axis=0, use_stack=False, name=None):
    """ref tensor_array_to_tensor_op.cc: returns (tensor, out_index) where
    out_index records each element's extent along `axis`."""
    items = list(array) if isinstance(array, TensorArray) else list(array)
    return _tensor_array_to_tensor_impl(*items, axis=axis,
                                        use_stack=use_stack)
