"""Op-surface batch 2: vision sampling, CRF/decoding, segment pools,
special math — reference ops that had no equivalent yet.

Reference citations per op in docstrings (paths under
/root/reference/paddle/fluid/operators/).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .registry import register_op

__all__ = ["affine_grid", "grid_sample", "max_unpool2d", "multiplex",
           "segment_sum", "segment_mean", "segment_max", "segment_min",
           "linear_chain_crf", "viterbi_decode", "gather_tree",
           "beam_search_step", "diagonal", "diag_embed", "bucketize",
           "renorm", "poisson", "lgamma", "digamma", "polygamma", "logit",
           "frexp", "trapezoid", "cumulative_trapezoid", "vander", "cdist",
           "block_diag", "householder_product", "affine_channel",
           "py_func"]


# ---------------------------------------------------------------------------
# vision sampling
# ---------------------------------------------------------------------------

@register_op("affine_grid")
def affine_grid(theta, out_shape, align_corners=True, name=None):
    """Affine sampling grid (ref affine_grid_op.cc): theta [N,2,3],
    out_shape (N,C,H,W) -> grid [N,H,W,2] of (x,y) in [-1,1] source
    coords."""
    n, _, h, w = [int(s) for s in out_shape]
    if align_corners:
        ys = jnp.linspace(-1.0, 1.0, h)
        xs = jnp.linspace(-1.0, 1.0, w)
    else:
        ys = (jnp.arange(h) * 2 + 1) / h - 1.0
        xs = (jnp.arange(w) * 2 + 1) / w - 1.0
    gy, gx = jnp.meshgrid(ys, xs, indexing="ij")      # [H,W]
    ones = jnp.ones_like(gx)
    base = jnp.stack([gx, gy, ones], -1).reshape(-1, 3)   # [HW,3]
    out = jnp.einsum("nij,pj->npi", jnp.asarray(theta), base)
    return out.reshape(theta.shape[0], h, w, 2)


@register_op("grid_sample")
def grid_sample(x, grid, mode="bilinear", padding_mode="zeros",
                align_corners=True, name=None):
    """Sample x [N,C,H,W] at grid [N,Ho,Wo,2] (x,y in [-1,1]) — ref
    grid_sampler_op.cc. Gather-based: XLA lowers to dynamic-slices."""
    n, c, h, w = x.shape
    gx, gy = grid[..., 0], grid[..., 1]
    if align_corners:
        fx = (gx + 1) * (w - 1) / 2
        fy = (gy + 1) * (h - 1) / 2
    else:
        fx = ((gx + 1) * w - 1) / 2
        fy = ((gy + 1) * h - 1) / 2

    def in_bounds(ix, iy):
        return ((ix >= 0) & (ix < w) & (iy >= 0) & (iy < h))

    if padding_mode == "border":
        fx = jnp.clip(fx, 0, w - 1)
        fy = jnp.clip(fy, 0, h - 1)
    elif padding_mode == "reflection":
        def reflect(v, lo, hi):
            # triangular wave over [lo, hi]: identity in range,
            # mirrored outside (the previous abs(...%..)-rng form was
            # the INVERTED wave — it flipped in-range coordinates too;
            # caught by the torch grid_sample cross-check)
            rng = hi - lo
            if rng <= 0:
                return jnp.full_like(v, lo)
            v = rng - jnp.abs((v - lo) % (2 * rng) - rng)
            return v + lo
        if align_corners:
            fx = reflect(fx, 0.0, w - 1.0)
            fy = reflect(fy, 0.0, h - 1.0)
        else:
            fx = jnp.clip(reflect(fx + 0.5, 0.0, float(w)) - 0.5,
                          0, w - 1)
            fy = jnp.clip(reflect(fy + 0.5, 0.0, float(h)) - 0.5,
                          0, h - 1)

    if mode == "nearest":
        ix = jnp.round(fx).astype(jnp.int32)
        iy = jnp.round(fy).astype(jnp.int32)
        mask = in_bounds(ix, iy) if padding_mode == "zeros" else \
            jnp.ones_like(ix, bool)
        v = jax.vmap(
            lambda img, jx, jy, m: img[:, jnp.clip(jy, 0, h - 1),
                                       jnp.clip(jx, 0, w - 1)]
            * m.astype(img.dtype))(x, ix, iy, mask)
        return v  # [N,C,Ho,Wo]

    x0 = jnp.floor(fx).astype(jnp.int32)
    y0 = jnp.floor(fy).astype(jnp.int32)
    x1, y1 = x0 + 1, y0 + 1
    wa = (x1 - fx) * (y1 - fy)
    wb = (fx - x0) * (y1 - fy)
    wc = (x1 - fx) * (fy - y0)
    wd = (fx - x0) * (fy - y0)

    def corner(ix, iy, wgt):
        if padding_mode == "zeros":
            m = in_bounds(ix, iy)
            wgt = wgt * m.astype(wgt.dtype)
        jx = jnp.clip(ix, 0, w - 1)
        jy = jnp.clip(iy, 0, h - 1)
        v = jax.vmap(lambda img, ax, ay: img[:, ay, ax])(x, jx, jy)
        return v * wgt[:, None]

    out = (corner(x0, y0, wa) + corner(x1, y0, wb)
           + corner(x0, y1, wc) + corner(x1, y1, wd))
    return out


@register_op("max_unpool2d")
def max_unpool2d(x, indices, kernel_size, stride=None, padding=0,
                 output_size=None, data_format="NCHW", name=None):
    """Inverse of max_pool2d with indices (ref unpool_op.cc): scatter
    pooled values back to their argmax positions."""
    if data_format != "NCHW":
        raise ValueError(
            f"max_unpool2d supports NCHW only, got {data_format!r}")
    n, c, h, w = x.shape
    ks = kernel_size if isinstance(kernel_size, (tuple, list)) else \
        (kernel_size, kernel_size)
    st = stride or ks
    st = st if isinstance(st, (tuple, list)) else (st, st)
    if output_size is None:
        out_h = (h - 1) * st[0] + ks[0] - 2 * (
            padding if isinstance(padding, int) else padding[0])
        out_w = (w - 1) * st[1] + ks[1] - 2 * (
            padding if isinstance(padding, int) else padding[1])
    else:
        out_h, out_w = [int(s) for s in output_size[-2:]]
    flat_idx = indices.reshape(n, c, -1).astype(jnp.int32)
    flat_val = x.reshape(n, c, -1)
    out = jnp.zeros((n, c, out_h * out_w), x.dtype)
    out = jax.vmap(jax.vmap(lambda o, i, v: o.at[i].set(v)))(
        out, flat_idx, flat_val)
    return out.reshape(n, c, out_h, out_w)


@register_op("affine_channel")
def affine_channel(x, scale, bias, data_format="NCHW", name=None):
    """Per-channel scale+bias (ref affine_channel_op.cc — folded-BN form
    used by detection models)."""
    if data_format == "NCHW":
        shape = (1, -1) + (1,) * (x.ndim - 2)
    else:
        shape = (1,) * (x.ndim - 1) + (-1,)
    return x * scale.reshape(shape) + bias.reshape(shape)


# ---------------------------------------------------------------------------
# manipulation / segment pools
# ---------------------------------------------------------------------------

@register_op("multiplex")
def multiplex(inputs, index, name=None):
    """Row-wise select among candidate tensors (ref multiplex_op.cc):
    out[i] = inputs[index[i]][i]."""
    stacked = jnp.stack(list(inputs), axis=0)          # [K,N,...]
    idx = jnp.reshape(jnp.asarray(index), (-1,)).astype(jnp.int32)
    rows = jnp.arange(stacked.shape[1])
    return stacked[idx, rows]


def _segment(op_name, reduce_fn, fill):
    def fn(data, segment_ids, name=None):
        num = int(jnp.max(segment_ids)) + 1 if not isinstance(
            segment_ids, jax.core.Tracer) else None
        if num is None:
            raise ValueError(
                f"{op_name}: segment_ids must be concrete (static segment "
                "count); inside jit pass num_segments via jax.ops")
        return reduce_fn(data, jnp.asarray(segment_ids), num)
    fn.__name__ = op_name
    return register_op(op_name)(fn)


segment_sum = _segment(
    "segment_sum",
    lambda d, s, n: jax.ops.segment_sum(d, s, num_segments=n), 0)
segment_mean = _segment(
    "segment_mean",
    lambda d, s, n: jax.ops.segment_sum(d, s, num_segments=n)
    / jnp.maximum(jax.ops.segment_sum(jnp.ones_like(d), s,
                                      num_segments=n), 1), 0)
segment_max = _segment(
    "segment_max",
    lambda d, s, n: jax.ops.segment_max(d, s, num_segments=n), -jnp.inf)
segment_min = _segment(
    "segment_min",
    lambda d, s, n: jax.ops.segment_min(d, s, num_segments=n), jnp.inf)


@register_op("block_diag")
def block_diag(inputs, name=None):
    """Assemble a block-diagonal matrix (ref paddle.block_diag)."""
    mats = [jnp.atleast_2d(jnp.asarray(m)) for m in inputs]
    rows = sum(m.shape[0] for m in mats)
    cols = sum(m.shape[1] for m in mats)
    out = jnp.zeros((rows, cols), mats[0].dtype)
    r = c = 0
    for m in mats:
        out = jax.lax.dynamic_update_slice(out, m.astype(out.dtype),
                                           (r, c))
        r += m.shape[0]
        c += m.shape[1]
    return out


@register_op("diagonal")
def diagonal(x, offset=0, axis1=0, axis2=1, name=None):
    """ref diagonal op (paddle.diagonal)."""
    return jnp.diagonal(x, offset=offset, axis1=axis1, axis2=axis2)


@register_op("diag_embed")
def diag_embed(x, offset=0, dim1=-2, dim2=-1, name=None):
    """Batched vectors -> batched diagonal matrices (ref diag_embed_op)."""
    x = jnp.asarray(x)
    m = x.shape[-1] + abs(offset)
    eye = jnp.eye(m, k=offset, dtype=x.dtype)
    rows = jnp.arange(x.shape[-1]) + max(-offset, 0)
    out = jnp.zeros(x.shape[:-1] + (m, m), x.dtype)
    diag = x[..., :, None] * eye[rows]                  # [..., L, m]
    out = out.at[..., rows, :].add(diag)
    if dim1 != -2 or dim2 != -1:
        out = jnp.moveaxis(out, (-2, -1), (dim1, dim2))
    return out


# ---------------------------------------------------------------------------
# CRF / sequence decoding
# ---------------------------------------------------------------------------

@register_op("linear_chain_crf")
def linear_chain_crf(emission, transition, label, length=None, name=None):
    """Linear-chain CRF negative log-likelihood (ref
    linear_chain_crf_op.cc). emission [B,T,N]; transition [N+2,N]
    (row 0 = start scores, row 1 = stop scores, rows 2.. = pairwise);
    label [B,T] int; length [B] valid lengths. Returns nll [B]."""
    b, t, n = emission.shape
    start = transition[0]
    stop = transition[1]
    trans = transition[2:]
    if length is None:
        length = jnp.full((b,), t, jnp.int32)
    mask = jnp.arange(t)[None, :] < length[:, None]      # [B,T]

    # log partition via forward recursion
    def step(alpha, inp):
        emit, m = inp                                    # [B,N], [B]
        new = emit[:, None, :] + trans[None] + alpha[:, :, None]
        new = jax.scipy.special.logsumexp(new, axis=1)
        return jnp.where(m[:, None], new, alpha), None
    alpha0 = start[None] + emission[:, 0]
    alpha, _ = jax.lax.scan(
        step, alpha0,
        (jnp.moveaxis(emission, 1, 0)[1:], jnp.moveaxis(mask, 1, 0)[1:]))
    logz = jax.scipy.special.logsumexp(alpha + stop[None], axis=1)

    # gold path score
    lbl = jnp.asarray(label).astype(jnp.int32)
    emit_score = jnp.sum(
        jnp.take_along_axis(emission, lbl[..., None], -1)[..., 0] * mask,
        axis=1)
    pair = trans[lbl[:, :-1], lbl[:, 1:]]                # [B,T-1]
    pair_score = jnp.sum(pair * mask[:, 1:], axis=1)
    last_idx = jnp.maximum(length - 1, 0)
    last_lbl = jnp.take_along_axis(lbl, last_idx[:, None], 1)[:, 0]
    gold = (start[lbl[:, 0]] + emit_score + pair_score + stop[last_lbl])
    return logz - gold


@register_op("viterbi_decode")
def viterbi_decode(emission, transition, length=None,
                   include_bos_eos_tag=True, name=None):
    """Viterbi best path (ref viterbi_decode_op / crf_decoding_op.cc).
    Returns (scores [B], paths [B,T])."""
    b, t, n = emission.shape
    if include_bos_eos_tag:
        start, stop, trans = (transition[0], transition[1], transition[2:])
    else:
        start = jnp.zeros((n,), emission.dtype)
        stop = jnp.zeros((n,), emission.dtype)
        trans = transition
    if length is None:
        length = jnp.full((b,), t, jnp.int32)
    mask = jnp.arange(t)[None, :] < length[:, None]

    def step(carry, inp):
        alpha = carry
        emit, m = inp
        cand = alpha[:, :, None] + trans[None]           # [B,N,N]
        best_prev = jnp.argmax(cand, axis=1)             # [B,N]
        new = jnp.max(cand, axis=1) + emit
        new = jnp.where(m[:, None], new, alpha)
        return new, best_prev
    alpha0 = start[None] + emission[:, 0]
    alpha, back = jax.lax.scan(
        step, alpha0,
        (jnp.moveaxis(emission, 1, 0)[1:], jnp.moveaxis(mask, 1, 0)[1:]))
    final = alpha + stop[None]
    scores = jnp.max(final, axis=1)
    last = jnp.argmax(final, axis=1)                     # [B]

    def walk(carry, bp_m):
        cur = carry
        bp, m = bp_m                                     # [B,N], [B]
        prev = jnp.take_along_axis(bp, cur[:, None], 1)[:, 0]
        cur = jnp.where(m, prev, cur)
        return cur, cur
    mask_rev = jnp.moveaxis(mask, 1, 0)[1:][::-1]
    _, path_rev = jax.lax.scan(walk, last, (back[::-1], mask_rev))
    paths = jnp.concatenate([path_rev[::-1], last[None]], axis=0)
    return scores, jnp.moveaxis(paths, 0, 1).astype(jnp.int64)


@register_op("gather_tree")
def gather_tree(ids, parents, name=None):
    """Back-trace beam-search parent pointers into full sequences (ref
    gather_tree_op.cc). ids/parents [T,B,W]. Returns [T,B,W]."""
    t = ids.shape[0]

    def step(carry, inp):
        beam = carry                                    # [B,W] beam index
        step_ids, step_parents = inp
        tok = jnp.take_along_axis(step_ids, beam, axis=1)
        beam = jnp.take_along_axis(step_parents, beam, axis=1)
        return beam, tok
    init = jnp.broadcast_to(jnp.arange(ids.shape[2])[None],
                            ids.shape[1:]).astype(ids.dtype)
    _, toks = jax.lax.scan(step, init, (ids[::-1], parents[::-1]))
    return toks[::-1]


@register_op("beam_search_step")
def beam_search_step(log_probs, scores, beam_size=4, end_token=None,
                     name=None):
    """One beam-search expansion (ref beam_search_op.cc semantics,
    static-shape): log_probs [B,W,V] next-token scores, scores [B,W]
    running beam scores. Returns (new_scores [B,W], token_ids [B,W],
    parent_ids [B,W])."""
    b, w, v = log_probs.shape
    total = scores[:, :, None] + log_probs               # [B,W,V]
    flat = total.reshape(b, w * v)
    new_scores, idx = jax.lax.top_k(flat, beam_size)
    parents = (idx // v).astype(jnp.int32)
    tokens = (idx % v).astype(jnp.int32)
    return new_scores, tokens, parents


# ---------------------------------------------------------------------------
# special math
# ---------------------------------------------------------------------------

@register_op("lgamma")
def lgamma(x, name=None):
    """ref lgamma_op."""
    return jax.lax.lgamma(jnp.asarray(x).astype(jnp.float32)
                          if jnp.issubdtype(jnp.asarray(x).dtype,
                                            jnp.integer) else x)


@register_op("digamma")
def digamma(x, name=None):
    """ref digamma_op."""
    return jax.lax.digamma(x)


@register_op("polygamma")
def polygamma(x, n, name=None):
    """ref polygamma op (paddle.polygamma)."""
    return jax.scipy.special.polygamma(n, x)


@register_op("poisson", tags=("rng",))
def poisson(x, name=None):
    """Sample Poisson(lambda=x) elementwise (ref poisson_op)."""
    from ..core.generator import next_key
    return jax.random.poisson(next_key(), x, shape=jnp.shape(x))


@register_op("logit")
def logit(x, eps=None, name=None):
    """ref logit_op: log(x/(1-x)) with optional clipping."""
    if eps is not None:
        x = jnp.clip(x, eps, 1.0 - eps)
    return jnp.log(x) - jnp.log1p(-x)


def frexp(x, name=None):
    """ref paddle.frexp: mantissa/exponent decomposition."""
    from ..framework import Tensor
    arr = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    m, e = jnp.frexp(arr)
    return Tensor(m), Tensor(e.astype(jnp.int32))


@register_op("bucketize")
def bucketize(x, sorted_sequence, out_int32=False, right=False, name=None):
    """ref bucketize (searchsorted over a 1-D boundary set)."""
    side = "right" if right else "left"
    out = jnp.searchsorted(jnp.asarray(sorted_sequence), x, side=side)
    return out.astype(jnp.int32 if out_int32 else jnp.int64)


@register_op("renorm")
def renorm(x, p, axis, max_norm, name=None):
    """ref renorm_op: scale slices along `axis` whose p-norm exceeds
    max_norm down to max_norm."""
    axes = tuple(i for i in range(x.ndim) if i != axis % x.ndim)
    norms = jnp.sum(jnp.abs(x) ** p, axis=axes, keepdims=True) ** (1.0 / p)
    factor = jnp.where(norms > max_norm, max_norm / (norms + 1e-7), 1.0)
    return x * factor


@register_op("trapezoid")
def trapezoid(y, x=None, dx=None, axis=-1, name=None):
    """ref paddle.trapezoid."""
    if x is not None:
        return jax.scipy.integrate.trapezoid(y, x=jnp.asarray(x),
                                             axis=axis)
    return jax.scipy.integrate.trapezoid(y, dx=dx or 1.0, axis=axis)


@register_op("cumulative_trapezoid")
def cumulative_trapezoid(y, x=None, dx=None, axis=-1, name=None):
    """ref paddle.cumulative_trapezoid."""
    y = jnp.moveaxis(y, axis, -1)
    if x is not None:
        d = jnp.diff(jnp.moveaxis(jnp.asarray(x), axis, -1)
                     if jnp.ndim(x) > 1 else jnp.asarray(x), axis=-1)
    else:
        d = dx or 1.0
    avg = (y[..., 1:] + y[..., :-1]) / 2.0
    out = jnp.cumsum(avg * d, axis=-1)
    return jnp.moveaxis(out, -1, axis)


@register_op("vander")
def vander(x, n=None, increasing=False, name=None):
    """ref paddle.vander."""
    m = n if n is not None else x.shape[0]
    powers = jnp.arange(m) if increasing else jnp.arange(m - 1, -1, -1)
    return x[:, None] ** powers[None, :].astype(x.dtype)


@register_op("cdist")
def cdist(x, y, p=2.0, compute_mode=None, name=None):
    """Pairwise p-distance between row sets (ref paddle.cdist):
    x [..,M,D], y [..,N,D] -> [..,M,N]."""
    diff = x[..., :, None, :] - y[..., None, :, :]
    if p == 2.0:
        return jnp.sqrt(jnp.sum(diff * diff, -1) + 1e-30)
    if p == float("inf"):
        return jnp.max(jnp.abs(diff), -1)
    return jnp.sum(jnp.abs(diff) ** p, -1) ** (1.0 / p)


@register_op("householder_product")
def householder_product(x, tau, name=None):
    """Q from Householder reflectors (ref paddle.linalg
    .householder_product / LAPACK orgqr). x [M,N] reflectors in columns,
    tau [N]."""
    m, n = x.shape[-2], x.shape[-1]
    q = jnp.eye(m, dtype=x.dtype)
    q = jnp.broadcast_to(q, x.shape[:-2] + (m, m))
    for i in range(n - 1, -1, -1):
        v = x[..., :, i]
        v = jnp.where(jnp.arange(m) < i, 0.0, v)
        v = v.at[..., i].set(1.0)
        t = tau[..., i]
        vq = jnp.einsum("...k,...kj->...j", v, q)       # v^T q
        q = q - t[..., None, None] * jnp.einsum(
            "...i,...j->...ij", v, vq)                  # q -= tau v (v^T q)
    return q


# ---------------------------------------------------------------------------
# py_func: arbitrary python as an op (ref py_func_op.cc)
# ---------------------------------------------------------------------------

def py_func(func, x, out_shape=None, out_dtype="float32",
            backward_func=None, name=None):
    """Run a numpy-level python function as a framework op (ref
    operators/py_func_op.cc + static.nn.py_func). Works eagerly and under
    jit (via pure_callback when out_shape is given)."""
    from ..framework import Tensor
    from .registry import run_op

    xs = x if isinstance(x, (list, tuple)) else (x,)

    def pure(*arrays):
        if any(isinstance(a, jax.core.Tracer) for a in arrays):
            if out_shape is None:
                raise ValueError(
                    "py_func under jit needs out_shape/out_dtype")
            out_sds = jax.ShapeDtypeStruct(tuple(out_shape),
                                           np.dtype(out_dtype))
            return jax.pure_callback(
                lambda *a: np.asarray(func(*a)), out_sds, *arrays,
                vmap_method="sequential")
        return jnp.asarray(func(*[np.asarray(a) for a in arrays]))

    return run_op("py_func", pure, tuple(xs), {})
