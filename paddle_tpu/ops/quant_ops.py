"""Quantization-aware-training ops (reference fake_quantize_op.cc family).

Reference specs: operators/fake_quantize_op.{cc,h} —
  fake_quantize_abs_max, fake_quantize_range_abs_max,
  fake_quantize_moving_average_abs_max, fake_quantize_dequantize_abs_max,
  fake_quantize_dequantize_moving_average_abs_max,
  fake_channel_wise_quantize_abs_max,
  fake_channel_wise_quantize_dequantize_abs_max,
  moving_average_abs_max_scale
and operators/fake_dequantize_op.cc (fake_dequantize_max_abs).

TPU design: the *_dequantize ops are differentiable with the
straight-through estimator (the reference registers FakeQuantDequantGradOp
passing dY through; here it is one jax.custom_vjp shared by the family).
Stateful scale tracking (range / moving-average) is functional: state
tensors go in, updated state comes out — fits the compiled TrainStep where
state lives in strategy_state, no mutable op attributes.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .registry import register_op

__all__ = [
    "fake_quantize_abs_max", "fake_quantize_dequantize_abs_max",
    "fake_quantize_range_abs_max", "fake_quantize_moving_average_abs_max",
    "fake_quantize_dequantize_moving_average_abs_max",
    "fake_channel_wise_quantize_abs_max",
    "fake_channel_wise_quantize_dequantize_abs_max",
    "moving_average_abs_max_scale", "fake_dequantize_max_abs",
]


def _qmax(bit_length):
    return float((1 << (int(bit_length) - 1)) - 1)


@jax.custom_vjp
def _quant_dequant_ste(x, scale, qmax):
    s = jnp.maximum(scale, 1e-8)
    return jnp.round(jnp.clip(x / s, -1.0, 1.0) * qmax) * s / qmax


def _qd_fwd(x, scale, qmax):
    return _quant_dequant_ste(x, scale, qmax), None


def _qd_bwd(_, g):
    return g, None, None          # straight-through: dX = dY


_quant_dequant_ste.defvjp(_qd_fwd, _qd_bwd)


@register_op("fake_quantize_abs_max")
def fake_quantize_abs_max(x, bit_length=8, name=None):
    """out = round(x / max|x| * qmax) (integers stored as float), returns
    (out, scale) — ref FakeQuantizeAbsMaxKernel."""
    scale = jnp.max(jnp.abs(x))
    s = jnp.maximum(scale, 1e-8)
    q = _qmax(bit_length)
    return jnp.round(jnp.clip(x / s, -1.0, 1.0) * q), scale


@register_op("fake_quantize_dequantize_abs_max")
def fake_quantize_dequantize_abs_max(x, bit_length=8, name=None):
    """QAT quant-dequant with STE gradient; returns (out, scale)."""
    scale = jnp.max(jnp.abs(x))
    return _quant_dequant_ste(x, scale, _qmax(bit_length)), scale


@register_op("fake_quantize_range_abs_max")
def fake_quantize_range_abs_max(x, in_scale, scales_window, iter_idx,
                                window_size=10000, bit_length=8,
                                is_test=False, name=None):
    """Windowed max-abs scale tracking (ref FakeQuantizeRangeAbsMaxKernel):
    train mode records max|x| into a circular window and takes the window
    max as scale. Returns (out, out_scale, scales_window, iter_idx+1)."""
    q = _qmax(bit_length)
    cur = jnp.max(jnp.abs(x))
    if is_test:
        s = jnp.maximum(in_scale.reshape(()), 1e-8)
        return (jnp.round(jnp.clip(x / s, -1.0, 1.0) * q), in_scale,
                scales_window, iter_idx)
    slot = jnp.mod(iter_idx.astype(jnp.int32), window_size)
    window = scales_window.at[slot].set(cur)
    n_seen = jnp.minimum(iter_idx.astype(jnp.int32) + 1, window_size)
    mask = jnp.arange(window.shape[0]) < n_seen
    scale = jnp.max(jnp.where(mask, window, 0.0))
    s = jnp.maximum(scale, 1e-8)
    return (jnp.round(jnp.clip(x / s, -1.0, 1.0) * q), scale, window,
            iter_idx + 1)


def _moving_average_scale(accum, state, cur, rate):
    state2 = rate * state + 1.0
    accum2 = rate * accum + cur
    return accum2, state2, accum2 / state2


@register_op("fake_quantize_moving_average_abs_max")
def fake_quantize_moving_average_abs_max(x, in_accum, in_state,
                                         moving_rate=0.9, bit_length=8,
                                         is_test=False, name=None):
    """EMA max-abs scale (ref FakeQuantizeMovingAverageAbsMaxKernel).
    Returns (out, scale, accum, state)."""
    q = _qmax(bit_length)
    if is_test:
        scale = in_accum / jnp.maximum(in_state, 1e-8)
        accum, state = in_accum, in_state
    else:
        cur = jnp.max(jnp.abs(x))
        accum, state, scale = _moving_average_scale(
            in_accum, in_state, cur, moving_rate)
    s = jnp.maximum(scale, 1e-8)
    return jnp.round(jnp.clip(x / s, -1.0, 1.0) * q), scale, accum, state


@register_op("fake_quantize_dequantize_moving_average_abs_max")
def fake_quantize_dequantize_moving_average_abs_max(
        x, in_accum, in_state, moving_rate=0.9, bit_length=8,
        is_test=False, name=None):
    """QAT quant-dequant with EMA scale and STE grad. Returns
    (out, scale, accum, state)."""
    if is_test:
        scale = in_accum / jnp.maximum(in_state, 1e-8)
        accum, state = in_accum, in_state
    else:
        cur = jnp.max(jnp.abs(x))
        accum, state, scale = _moving_average_scale(
            in_accum, in_state, cur, moving_rate)
    out = _quant_dequant_ste(x, scale, _qmax(bit_length))
    return out, scale, accum, state


@register_op("fake_channel_wise_quantize_abs_max")
def fake_channel_wise_quantize_abs_max(x, bit_length=8, quant_axis=0,
                                       name=None):
    """Per-channel quantize (ref FakeChannelWiseQuantizeAbsMaxKernel);
    returns (out, scales[C])."""
    axes = tuple(i for i in range(x.ndim) if i != quant_axis)
    scales = jnp.max(jnp.abs(x), axis=axes)
    shape = [1] * x.ndim
    shape[quant_axis] = -1
    s = jnp.maximum(scales.reshape(shape), 1e-8)
    q = _qmax(bit_length)
    return jnp.round(jnp.clip(x / s, -1.0, 1.0) * q), scales


@register_op("fake_channel_wise_quantize_dequantize_abs_max")
def fake_channel_wise_quantize_dequantize_abs_max(x, bit_length=8,
                                                  quant_axis=0, name=None):
    """Per-channel QAT quant-dequant with STE grad; returns (out, scales)."""
    axes = tuple(i for i in range(x.ndim) if i != quant_axis)
    scales = jnp.max(jnp.abs(x), axis=axes)
    shape = [1] * x.ndim
    shape[quant_axis] = -1
    out = _quant_dequant_ste(x, scales.reshape(shape), _qmax(bit_length))
    return out, scales


@register_op("moving_average_abs_max_scale")
def moving_average_abs_max_scale(x, in_accum, in_state, moving_rate=0.9,
                                 is_test=False, name=None):
    """Scale observer only — out = x (ref MovingAverageAbsMaxScaleKernel).
    Returns (out, scale, accum, state)."""
    if is_test:
        return x, in_accum / jnp.maximum(in_state, 1e-8), in_accum, in_state
    cur = jnp.max(jnp.abs(x))
    accum, state, scale = _moving_average_scale(in_accum, in_state, cur,
                                                moving_rate)
    return x, scale, accum, state


@register_op("fake_dequantize_max_abs")
def fake_dequantize_max_abs(x, scale, max_range, quant_axis=None,
                            name=None):
    """out = x * scale / max_range (ref fake_dequantize_op.cc).

    quant_axis: broadcast a per-channel [C] scale along that axis of x
    (the freeze-pass form where x is an int8-stored weight); None keeps
    the reference's plain trailing-dim broadcast."""
    if quant_axis is not None and jnp.ndim(scale) == 1:
        shape = [1] * jnp.ndim(x)
        shape[quant_axis] = -1
        scale = jnp.reshape(scale, shape)
    if jnp.issubdtype(jnp.asarray(x).dtype, jnp.integer):
        x = jnp.asarray(x).astype(jnp.float32)  # int8-stored weights
    return x * scale / max_range
