"""Op namespace aggregation + Tensor method patching.

The reference patches arithmetic/indexing methods onto its eager tensor in
python/paddle/fluid/dygraph/math_op_patch.py and monkey-patches the tensor
namespace in python/paddle/tensor/__init__.py. Same move here: every op in
this package becomes a Tensor method, and Python operators route through the
registry (so they are taped for autograd).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..framework import Tensor, _unwrap
from . import (amp_ops, creation, detection, extras, linalg, logic,
               manipulation, math, search, sequence, stat)
from .amp_ops import *  # noqa: F401,F403
from .creation import *  # noqa: F401,F403
from .extras import *  # noqa: F401,F403
from .loss_extra import *  # noqa: F401,F403
from .misc_ops import *  # noqa: F401,F403
from .quant_ops import *  # noqa: F401,F403
from .rnn_ops import *  # noqa: F401,F403
from .tensor_array import *  # noqa: F401,F403
from .vision_extra import *  # noqa: F401,F403
from .detection import *  # noqa: F401,F403
from .sequence import *  # noqa: F401,F403
from .linalg import *  # noqa: F401,F403
from .logic import *  # noqa: F401,F403
from .manipulation import *  # noqa: F401,F403
from .math import *  # noqa: F401,F403
from .control_flow import (cond, while_loop, bounded_while_loop, case,
                           switch_case, scan, fori_loop)  # noqa: F401
from .einsum import einsum  # noqa: F401
from .registry import OPS, get_op, op_wrapper, register_op, run_op
from .search import *  # noqa: F401,F403
from .stat import *  # noqa: F401,F403

__all__ = (creation.__all__ + math.__all__ + manipulation.__all__
           + logic.__all__ + search.__all__ + linalg.__all__ + stat.__all__
           + detection.__all__ + sequence.__all__ + extras.__all__
           + amp_ops.__all__
           + ["einsum", "cond", "while_loop", "bounded_while_loop",
              "case", "switch_case", "scan", "fori_loop",
              "reshape_", "squeeze_", "unsqueeze_", "scatter_",
              "tanh_"])


# ---------------------------------------------------------------------------
# operator overloads (math_op_patch.py analogue)
# ---------------------------------------------------------------------------

# Indexing as registered ops: the index (ints/slices/Ellipsis/arrays)
# rides in the `idx` attribute so captured programs serialize — the
# previous ad-hoc lambdas made any program containing x[...] unsaveable
# (reference slice_op / set_value_op are likewise ordinary proto ops).
@register_op("getitem")
def _getitem_op(x, idx=()):
    return x[idx]


@register_op("setitem")
def _setitem_op(x, v, idx=()):
    return x.at[idx].set(v.astype(x.dtype) if hasattr(v, "astype") else v)


def _binary_method(fn, reverse=False):
    def method(self, other):
        if isinstance(other, (list, tuple, np.ndarray)):
            other = Tensor(other)
        # python scalars stay scalars either way so jnp weak-type promotion
        # applies identically to `x - 2` and `2 - x`
        if reverse:
            return fn(other, self)
        return fn(self, other)
    return method


def _rebind_inplace(target: Tensor, out: Tensor):
    """Make an op's output *be* `target` on the tape (inplace semantics).

    If the op was taped, the node's output slot is repointed at `target`
    and target's creator becomes that node, so gradients flow through the
    inplace write. Inplace on a grad-requiring leaf is rejected, as the
    write would orphan the leaf's .grad accumulation.
    """
    import weakref

    from ..framework import is_grad_enabled
    if (not target.stop_gradient and target._node is None
            and is_grad_enabled() and out._node is not None):
        raise RuntimeError(
            "in-place operation on a leaf tensor that requires grad is not "
            "allowed; use set_value() (no tape) or operate out-of-place")
    target._data = out._data
    if out._node is not None:
        target._node = out._node
        target._out_idx = out._out_idx
        out._node.out_refs[out._out_idx] = weakref.ref(target)
        out._node = None


def _patch_tensor_methods():
    T = Tensor
    T.__add__ = _binary_method(math.add)
    T.__radd__ = _binary_method(math.add, reverse=True)
    T.__sub__ = _binary_method(math.subtract)
    T.__rsub__ = _binary_method(math.subtract, reverse=True)
    T.__mul__ = _binary_method(math.multiply)
    T.__rmul__ = _binary_method(math.multiply, reverse=True)
    T.__truediv__ = _binary_method(math.divide)
    T.__rtruediv__ = _binary_method(math.divide, reverse=True)
    T.__floordiv__ = _binary_method(math.floor_divide)
    T.__mod__ = _binary_method(math.mod)
    T.__pow__ = _binary_method(math.pow)
    T.__rpow__ = _binary_method(math.pow, reverse=True)
    T.__matmul__ = _binary_method(math.matmul)
    T.__neg__ = lambda self: math.neg(self)
    T.__abs__ = lambda self: math.abs(self)
    T.__invert__ = lambda self: logic.logical_not(self)
    T.__eq__ = _binary_method(logic.equal)
    T.__ne__ = _binary_method(logic.not_equal)
    T.__lt__ = _binary_method(logic.less_than)
    T.__le__ = _binary_method(logic.less_equal)
    T.__gt__ = _binary_method(logic.greater_than)
    T.__ge__ = _binary_method(logic.greater_equal)
    T.__hash__ = object.__hash__  # __eq__ override would drop hashability
    T.__and__ = _binary_method(logic.logical_and)
    T.__or__ = _binary_method(logic.logical_or)
    T.__xor__ = _binary_method(logic.logical_xor)

    def _unwrap_item(it):
        if isinstance(it, Tensor):
            return it._data
        if isinstance(it, tuple):
            return tuple(_unwrap_item(i) for i in it)
        return it

    def _getitem(self, item):
        return _getitem_op(self, idx=_unwrap_item(item))

    def _setitem(self, item, value):
        out = _setitem_op(self, value, idx=_unwrap_item(item))
        _rebind_inplace(self, out)

    T.__getitem__ = _getitem
    T.__setitem__ = _setitem

    # method versions of namespace ops
    _method_table = {}
    for mod in (creation, math, manipulation, logic, search, linalg, stat):
        for nm in mod.__all__:
            _method_table.setdefault(nm, getattr(mod, nm))
    skip = {"create_parameter", "broadcast_tensors",
            "set_printoptions", "broadcast_shape"}
    for nm, fn in _method_table.items():
        if nm in skip or hasattr(T, nm):
            continue
        setattr(T, nm, fn)

    # inplace-suffix conveniences (x.add_(y) etc.) — tape-aware: the output
    # node is rewired onto self so downstream backward sees the write
    # (TensorInplaceVersion analogue, reference framework/tensor.h:77)
    def _make_inplace(fn):
        def inplace(self, *a, **k):
            out = fn(self, *a, **k)
            _rebind_inplace(self, out)
            return self
        return inplace
    for nm in ("add", "subtract", "multiply", "divide", "clip", "scale",
               "floor", "ceil", "exp", "sqrt", "reciprocal", "round",
               "tanh"):
        setattr(T, nm + "_", _make_inplace(getattr(math, nm)))
    for nm in ("reshape", "squeeze", "unsqueeze"):
        setattr(T, nm + "_", _make_inplace(getattr(manipulation, nm)))
    setattr(T, "scatter_", _make_inplace(getattr(manipulation, "scatter")))
    # method forms of ops living outside the namespace-table modules
    from . import extras as _extras
    if not hasattr(T, "multiplex"):
        T.multiplex = _extras.multiplex
    if not hasattr(T, "to_tensor"):
        def _to_tensor_method(self, dtype=None, stop_gradient=None,
                              place=None, **k):
            out = self
            if dtype is not None:
                out = out.astype(dtype)
            if stop_gradient is not None and out is self:
                out = self.clone() if not self.stop_gradient else                     Tensor(self._data)
            if stop_gradient is not None:
                out.stop_gradient = bool(stop_gradient)
            return out
        T.to_tensor = _to_tensor_method

    T.mm = math.matmul
    # Tensor.cond is the matrix condition number (the control-flow `cond`
    # is never a Tensor method), kept even though linalg.__all__ omits it
    T.cond = linalg.cond
    T.dim = lambda self: self.ndim
    T.rank = lambda self: Tensor(jnp.asarray(self.ndim))
    T.numel = lambda self: creation.numel(self)


_patch_tensor_methods()


def _functional_inplace(fn):
    """paddle.reshape_(x, ...)-style module-level inplace form sharing
    the ONE tape-correct rebind implementation (_rebind_inplace):
    leaf-with-grad writes are rejected, node out_refs are rewired."""
    import functools as _ft

    @_ft.wraps(fn)
    def f(x, *a, **k):
        out = fn(x, *a, **k)
        if isinstance(x, Tensor) and isinstance(out, Tensor):
            _rebind_inplace(x, out)
            return x
        return out
    f.__name__ = fn.__name__ + "_"
    return f


reshape_ = _functional_inplace(manipulation.reshape)
squeeze_ = _functional_inplace(manipulation.squeeze)
unsqueeze_ = _functional_inplace(manipulation.unsqueeze)
scatter_ = _functional_inplace(manipulation.scatter)
tanh_ = _functional_inplace(math.tanh)

# paddle.tensor namespace carries to_tensor too (reference
# tensor/creation.py to_tensor); implementation lives in framework.py
from ..framework import to_tensor  # noqa: F401,E402
