"""Vision long-tail ops: deformable convs, position-sensitive ROI pools,
perspective ROI transform, correlation cost volume, tree/var convs,
cross-replica batch norm.

Reference specs: operators/deformable_conv_op.{cc,cu} (+ _v1),
deformable_psroi_pooling_op.{cc,cu}, psroi_pool_op.{h,cc},
prroi_pool_op.{h,cc}, roi_perspective_transform_op.cc, correlation_op.cc
(contrib), tree_conv_op.cc + math/tree2col.cc, var_conv_2d_op.cc,
sync_batch_norm_op.cu (all under /root/reference/paddle/fluid/operators/).

TPU design notes:
- deformable sampling is a vectorized bilinear gather (one jnp.take per
  corner) — XLA lowers it to batched dynamic-slices; no per-point CUDA
  kernel needed, and it is differentiable through jax.vjp (the reference
  hand-writes the atomicAdd backward).
- sync_batch_norm is lax.pmean over a named mesh axis — the XLA-native
  equivalent of the reference's ncclAllReduce of (sum, square_sum).
- prroi_pool integrates bilinear patches exactly like the reference but
  over a fixed fine sample grid (integral ≈ dense average) — documented
  approximation, differentiable everywhere.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .registry import register_op

__all__ = [
    "deformable_conv", "deformable_conv_v1", "psroi_pool", "prroi_pool",
    "deformable_psroi_pooling", "roi_perspective_transform", "correlation",
    "tree_conv", "var_conv_2d", "sync_batch_norm",
]


def _bilinear_gather(feat, y, x):
    """feat [C,H,W]; y,x arbitrary same-shaped float coords → [C, *y.shape]
    with zero padding outside."""
    h, w = feat.shape[-2:]
    y0 = jnp.floor(y)
    x0 = jnp.floor(x)
    wy1 = y - y0
    wx1 = x - x0

    def tap(yy, xx, wt):
        inside = (yy >= 0) & (yy <= h - 1) & (xx >= 0) & (xx <= w - 1)
        yi = jnp.clip(yy.astype(jnp.int32), 0, h - 1)
        xi = jnp.clip(xx.astype(jnp.int32), 0, w - 1)
        vals = feat[:, yi, xi]                    # [C, *shape]
        return vals * (wt * inside.astype(feat.dtype))

    return (tap(y0, x0, (1 - wy1) * (1 - wx1))
            + tap(y0, x0 + 1, (1 - wy1) * wx1)
            + tap(y0 + 1, x0, wy1 * (1 - wx1))
            + tap(y0 + 1, x0 + 1, wy1 * wx1))


@register_op("deformable_conv")
def deformable_conv(x, offset, mask, weight, bias=None, stride=1, padding=0,
                    dilation=1, deformable_groups=1, groups=1, name=None):
    """Deformable conv v2 (ref deformable_conv_op.cc; v1 = mask None):
    x [N,C,H,W], offset [N, dg*2*kh*kw, Ho, Wo] channel order
    (..., ky, kx, {dy,dx}), mask [N, dg*kh*kw, Ho, Wo],
    weight [Cout, C//groups, kh, kw]."""
    s = (stride, stride) if isinstance(stride, int) else tuple(stride)
    p = (padding, padding) if isinstance(padding, int) else tuple(padding)
    d = (dilation, dilation) if isinstance(dilation, int) else tuple(dilation)
    n, c, h, w = x.shape
    cout, cpg, kh, kw = weight.shape
    dg = int(deformable_groups)
    ho = (h + 2 * p[0] - d[0] * (kh - 1) - 1) // s[0] + 1
    wo = (w + 2 * p[1] - d[1] * (kw - 1) - 1) // s[1] + 1

    off = offset.reshape(n, dg, kh * kw, 2, ho, wo)
    msk = (jnp.ones((n, dg, kh * kw, ho, wo), x.dtype) if mask is None
           else mask.reshape(n, dg, kh * kw, ho, wo))

    base_y = (jnp.arange(ho) * s[0] - p[0])[:, None]       # [Ho,1]
    base_x = (jnp.arange(wo) * s[1] - p[1])[None, :]       # [1,Wo]
    ky = (jnp.arange(kh) * d[0])[:, None].repeat(kw, 1).reshape(-1)
    kx = (jnp.arange(kw) * d[1])[None, :].repeat(kh, 0).reshape(-1)

    def per_image(xi, offi, mski):
        # sample positions [dg, K, Ho, Wo]
        y = (base_y[None, None] + ky[None, :, None, None]
             + offi[:, :, 0])
        xx = (base_x[None, None] + kx[None, :, None, None]
              + offi[:, :, 1])
        cols = []
        cpd = c // dg
        for g in range(dg):
            sampled = _bilinear_gather(xi[g * cpd:(g + 1) * cpd],
                                       y[g], xx[g])       # [cpd,K,Ho,Wo]
            cols.append(sampled * mski[g][None])
        return jnp.concatenate(cols, axis=0)              # [C,K,Ho,Wo]

    cols = jax.vmap(per_image)(x, off, msk)               # [N,C,K,Ho,Wo]
    wmat = weight.reshape(groups, cout // groups, cpg * kh * kw)
    cols_g = cols.reshape(n, groups, cpg * kh * kw, ho, wo)
    out = jnp.einsum("ngkhw,gok->ngohw", cols_g, wmat).reshape(
        n, cout, ho, wo)
    if bias is not None:
        out = out + bias[None, :, None, None]
    return out


@register_op("deformable_conv_v1")
def deformable_conv_v1(x, offset, weight, bias=None, stride=1, padding=0,
                       dilation=1, deformable_groups=1, groups=1, name=None):
    """Deformable conv v1 (no modulation mask; ref deformable_conv_v1_op)."""
    return deformable_conv.__pure_fn__(
        x, offset, None, weight, bias=bias, stride=stride, padding=padding,
        dilation=dilation, deformable_groups=deformable_groups,
        groups=groups)


def _roi_to_bins(box, spatial_scale, ph, pw):
    x1, y1, x2, y2 = (box[0], box[1], box[2], box[3])
    x1 = x1 * spatial_scale
    y1 = y1 * spatial_scale
    x2 = x2 * spatial_scale
    y2 = y2 * spatial_scale
    bh = jnp.maximum(y2 - y1, 0.1) / ph
    bw = jnp.maximum(x2 - x1, 0.1) / pw
    return x1, y1, bh, bw


@register_op("psroi_pool")
def psroi_pool(x, rois, output_channels, pooled_height=7, pooled_width=7,
               spatial_scale=1.0, rois_num=None, name=None):
    """Position-sensitive ROI pooling (ref psroi_pool_op.h): input channel
    block (c*ph*pw + i*pw + j) feeds output [c, i, j]; average over each
    bin's integer pixel grid. rois [R,5] (batch_idx,x1,y1,x2,y2) or [R,4]
    with rois_num."""
    n, c, h, w = x.shape
    ph, pw = int(pooled_height), int(pooled_width)
    oc = int(output_channels)
    if rois.shape[-1] == 5:
        batch_idx = rois[:, 0].astype(jnp.int32)
        boxes = rois[:, 1:]
    elif rois_num is not None:
        batch_idx = jnp.repeat(jnp.arange(n), jnp.asarray(rois_num),
                               total_repeat_length=rois.shape[0])
        boxes = rois
    else:
        batch_idx = jnp.zeros((rois.shape[0],), jnp.int32)
        boxes = rois

    ys = jnp.arange(h, dtype=x.dtype)
    xs = jnp.arange(w, dtype=x.dtype)

    def one_roi(box, b):
        x1, y1, bh, bw = _roi_to_bins(box, spatial_scale, ph, pw)
        feat = jax.lax.dynamic_index_in_dim(x, b, 0, False)  # [C,H,W]
        feat = feat.reshape(oc, ph * pw, h, w)
        outs = []
        for i in range(ph):
            for j in range(pw):
                hs = jnp.floor(y1 + i * bh)
                he = jnp.ceil(y1 + (i + 1) * bh)
                ws_ = jnp.floor(x1 + j * bw)
                we = jnp.ceil(x1 + (j + 1) * bw)
                mask = (((ys >= hs) & (ys < he))[:, None]
                        & ((xs >= ws_) & (xs < we))[None, :])
                mf = mask.astype(x.dtype)
                area = jnp.maximum(mf.sum(), 1.0)
                v = (feat[:, i * pw + j] * mf[None]).sum((-2, -1)) / area
                outs.append(v)                           # [oc]
        return jnp.stack(outs, axis=1).reshape(oc, ph, pw)

    return jax.vmap(one_roi)(boxes, batch_idx)


@register_op("prroi_pool")
def prroi_pool(x, rois, pooled_height=7, pooled_width=7, spatial_scale=1.0,
               rois_num=None, samples=4, name=None):
    """Precise ROI pooling (ref prroi_pool_op.h): integral of the bilinear
    surface over each bin, here via a dense `samples`x`samples` bilinear
    grid per bin (exact integral replaced by fine-grid average —
    everywhere-differentiable like the reference)."""
    n, c, h, w = x.shape
    ph, pw = int(pooled_height), int(pooled_width)
    if rois.shape[-1] == 5:
        batch_idx = rois[:, 0].astype(jnp.int32)
        boxes = rois[:, 1:]
    elif rois_num is not None:
        batch_idx = jnp.repeat(jnp.arange(n), jnp.asarray(rois_num),
                               total_repeat_length=rois.shape[0])
        boxes = rois
    else:
        batch_idx = jnp.zeros((rois.shape[0],), jnp.int32)
        boxes = rois
    sr = int(samples)

    def one_roi(box, b):
        x1, y1, bh, bw = _roi_to_bins(box, spatial_scale, ph, pw)
        iy = (y1 + jnp.arange(ph)[:, None] * bh
              + (jnp.arange(sr) + 0.5) * bh / sr)        # [ph,sr]
        ix = (x1 + jnp.arange(pw)[:, None] * bw
              + (jnp.arange(sr) + 0.5) * bw / sr)        # [pw,sr]
        yy = iy.reshape(-1)[:, None]                     # [ph*sr,1]
        xx = ix.reshape(-1)[None, :]                     # [1,pw*sr]
        feat = jax.lax.dynamic_index_in_dim(x, b, 0, False)
        g = _bilinear_gather(feat, jnp.broadcast_to(yy, (ph * sr, pw * sr)),
                             jnp.broadcast_to(xx, (ph * sr, pw * sr)))
        g = g.reshape(c, ph, sr, pw, sr)
        return g.mean((2, 4))

    return jax.vmap(one_roi)(boxes, batch_idx)


@register_op("deformable_psroi_pooling")
def deformable_psroi_pooling(x, rois, trans, output_channels,
                             pooled_height=7, pooled_width=7,
                             spatial_scale=1.0, trans_std=0.1,
                             rois_num=None, name=None):
    """PS-ROI pooling with learned per-bin offsets (ref
    deformable_psroi_pooling_op): trans [R, 2, ph, pw] shifts each bin by
    (dy,dx)*trans_std*roi_size before pooling."""
    n, c, h, w = x.shape
    ph, pw = int(pooled_height), int(pooled_width)
    oc = int(output_channels)
    if rois.shape[-1] == 5:
        batch_idx = rois[:, 0].astype(jnp.int32)
        boxes = rois[:, 1:]
    elif rois_num is not None:
        batch_idx = jnp.repeat(jnp.arange(n), jnp.asarray(rois_num),
                               total_repeat_length=rois.shape[0])
        boxes = rois
    else:
        batch_idx = jnp.zeros((rois.shape[0],), jnp.int32)
        boxes = rois
    ys = jnp.arange(h, dtype=x.dtype)
    xs = jnp.arange(w, dtype=x.dtype)

    def one_roi(box, b, tr):
        x1, y1, bh, bw = _roi_to_bins(box, spatial_scale, ph, pw)
        feat = jax.lax.dynamic_index_in_dim(x, b, 0, False)
        feat = feat.reshape(oc, ph * pw, h, w)
        rh = bh * ph
        rw = bw * pw
        outs = []
        for i in range(ph):
            for j in range(pw):
                dy = tr[0, i, j] * trans_std * rh
                dx = tr[1, i, j] * trans_std * rw
                hs = jnp.floor(y1 + i * bh + dy)
                he = jnp.ceil(y1 + (i + 1) * bh + dy)
                ws_ = jnp.floor(x1 + j * bw + dx)
                we = jnp.ceil(x1 + (j + 1) * bw + dx)
                mask = (((ys >= hs) & (ys < he))[:, None]
                        & ((xs >= ws_) & (xs < we))[None, :])
                mf = mask.astype(x.dtype)
                area = jnp.maximum(mf.sum(), 1.0)
                outs.append(
                    (feat[:, i * pw + j] * mf[None]).sum((-2, -1)) / area)
        return jnp.stack(outs, axis=1).reshape(oc, ph, pw)

    return jax.vmap(one_roi)(boxes, batch_idx, trans)


@register_op("roi_perspective_transform")
def roi_perspective_transform(x, rois, transformed_height,
                              transformed_width, spatial_scale=1.0,
                              rois_num=None, name=None):
    """Perspective-warp quadrilateral ROIs to a rectangle (ref
    roi_perspective_transform_op.cc): rois [R, 8] four (x,y) corners in
    order tl, tr, br, bl (or [R, 9] with the batch index in col 0, or
    [R, 8] + rois_num per image); output [R, C, th, tw]
    bilinear-sampled from the ROI's own image."""
    n, c, h, w = x.shape
    th, tw = int(transformed_height), int(transformed_width)
    if rois.shape[-1] == 9:
        batch_idx = rois[:, 0].astype(jnp.int32)
        rois = rois[:, 1:]
    elif rois_num is not None:
        batch_idx = jnp.repeat(jnp.arange(n), jnp.asarray(rois_num),
                               total_repeat_length=rois.shape[0])
    else:
        batch_idx = jnp.zeros((rois.shape[0],), jnp.int32)

    def homography(quad):
        # solve a 8x8 system mapping (0,0),(tw-1,0),(tw-1,th-1),(0,th-1)
        # to the 4 scaled corners
        src = jnp.asarray([[0.0, 0.0], [tw - 1.0, 0.0],
                           [tw - 1.0, th - 1.0], [0.0, th - 1.0]], x.dtype)
        dst = quad.reshape(4, 2) * spatial_scale
        rows = []
        rhs = []
        for k in range(4):
            sx, sy = src[k, 0], src[k, 1]
            dx, dy = dst[k, 0], dst[k, 1]
            rows.append(jnp.stack([sx, sy, jnp.asarray(1.0, x.dtype),
                                   jnp.zeros((), x.dtype),
                                   jnp.zeros((), x.dtype),
                                   jnp.zeros((), x.dtype),
                                   -dx * sx, -dx * sy]))
            rows.append(jnp.stack([jnp.zeros((), x.dtype),
                                   jnp.zeros((), x.dtype),
                                   jnp.zeros((), x.dtype),
                                   sx, sy, jnp.asarray(1.0, x.dtype),
                                   -dy * sx, -dy * sy]))
            rhs += [dx, dy]
        a = jnp.stack(rows)
        bvec = jnp.stack(rhs)
        sol = jnp.linalg.solve(a, bvec)
        return jnp.concatenate([sol, jnp.ones((1,), x.dtype)]).reshape(3, 3)

    gy, gx = jnp.meshgrid(jnp.arange(th, dtype=x.dtype),
                          jnp.arange(tw, dtype=x.dtype), indexing="ij")
    ones = jnp.ones_like(gx)
    grid = jnp.stack([gx, gy, ones], axis=0).reshape(3, -1)   # [3, th*tw]

    def one_roi(quad, b):
        m = homography(quad)
        p = m @ grid
        px = p[0] / jnp.where(jnp.abs(p[2]) < 1e-8, 1e-8, p[2])
        py = p[1] / jnp.where(jnp.abs(p[2]) < 1e-8, 1e-8, p[2])
        feat = jax.lax.dynamic_index_in_dim(x, b, 0, False)
        out = _bilinear_gather(feat, py.reshape(th, tw), px.reshape(th, tw))
        return out

    return jax.vmap(one_roi)(rois, batch_idx)


@register_op("correlation")
def correlation(x1, x2, pad_size=4, kernel_size=1, max_displacement=4,
                stride1=1, stride2=1, corr_type_multiply=1, name=None):
    """FlowNet correlation cost volume (ref contrib correlation_op):
    out[n, (dy,dx), h, w] = mean over channels and the kernel_size^2
    patch of x1[.., h+u, w+v] * x2[.., h+dy+u, w+dx+v], displacements
    |dy|,|dx| <= max_displacement in stride2 steps, output positions
    subsampled by stride1. Out-of-image taps are zero (the reference's
    pad_size zero-padding, applied here by masking)."""
    d = int(max_displacement)
    s1, s2 = int(stride1), int(stride2)
    k = int(kernel_size)
    kr = (k - 1) // 2
    offs = list(range(-d, d + 1, s2))
    hdim, wdim = x2.shape[2], x2.shape[3]

    def shift_masked(x, dy, dx):
        rolled = jnp.roll(x, (-dy, -dx), axis=(2, 3))
        hval = jnp.arange(hdim) + dy
        wval = jnp.arange(wdim) + dx
        valid = (((hval >= 0) & (hval < hdim))[:, None]
                 & ((wval >= 0) & (wval < wdim))[None, :])
        return rolled * valid[None, None].astype(x.dtype)

    outs = []
    norm = float(k * k)
    for dy in offs:
        for dx in offs:
            acc = None
            for u in range(-kr, k - kr):
                for v in range(-kr, k - kr):
                    a = shift_masked(x1, u, v)
                    b = shift_masked(x2, dy + u, dx + v)
                    term = (a * b).mean(1)
                    acc = term if acc is None else acc + term
            outs.append(acc / norm)
    out = jnp.stack(outs, axis=1)
    if s1 > 1:
        out = out[:, :, ::s1, ::s1]
    return out


@register_op("tree_conv")
def tree_conv(nodes, edges, filt, max_depth=2, name=None):
    """Tree-based convolution (ref tree_conv_op.cc + math/tree2col.cc),
    default window depth 2 (node + its children): nodes [B, N, F], edges
    [B, E, 2] (parent, child; -1 padded), filter [F, 3, out, filters].
    Position weights follow TBCNN: eta_t = 1 for the root of the window,
    children split eta_l/eta_r by sibling position. Output
    [B, N, out, filters] (relu'd sum over window)."""
    b, n, f = nodes.shape
    adj = jnp.zeros((b, n, n), nodes.dtype)
    pr = edges[..., 0].astype(jnp.int32)
    ch = edges[..., 1].astype(jnp.int32)
    valid = (pr >= 0) & (ch >= 0)
    bi = jnp.broadcast_to(jnp.arange(b)[:, None], pr.shape)
    adj = adj.at[bi, jnp.where(valid, pr, 0),
                 jnp.where(valid, ch, 0)].max(
        valid.astype(nodes.dtype))
    n_child = adj.sum(-1)                                  # [B,N]
    # sibling order index along the child axis
    order = jnp.cumsum(adj, axis=-1) - 1.0                 # [B,N,N]
    denom = jnp.maximum(n_child - 1.0, 1.0)[:, :, None]
    eta_r = jnp.where(adj > 0, order / denom, 0.0)
    eta_l = jnp.where(adj > 0, 1.0 - eta_r, 0.0) * adj
    eta_r = eta_r * adj
    wt, wl, wr = filt[:, 0], filt[:, 1], filt[:, 2]        # [F,out,filters]
    self_term = jnp.einsum("bnf,fok->bnok", nodes, wt)
    left = jnp.einsum("bnm,bmf,fok->bnok", eta_l, nodes, wl)
    right = jnp.einsum("bnm,bmf,fok->bnok", eta_r, nodes, wr)
    return jax.nn.relu(self_term + left + right)


@register_op("var_conv_2d")
def var_conv_2d(x, row_lengths, col_lengths, weight, output_channels,
                kernel_h=3, kernel_w=3, stride_h=1, stride_w=1, name=None):
    """Variable-size 2D conv (ref var_conv_2d_op.cc): each sample's valid
    region is (row_lengths[i], col_lengths[i]) inside the padded [B,C,H,W];
    conv output is masked to the valid (ceil(h/s), ceil(w/s)) region."""
    s_h, s_w = int(stride_h), int(stride_w)
    pad_h = (int(kernel_h) - 1) // 2
    pad_w = (int(kernel_w) - 1) // 2
    out = jax.lax.conv_general_dilated(
        x, weight, (s_h, s_w), [(pad_h, pad_h), (pad_w, pad_w)],
        dimension_numbers=("NCHW", "OIHW", "NCHW"))
    ho, wo = out.shape[2], out.shape[3]
    vh = jnp.ceil(jnp.asarray(row_lengths, x.dtype) / s_h)
    vw = jnp.ceil(jnp.asarray(col_lengths, x.dtype) / s_w)
    mask = ((jnp.arange(ho)[None, :] < vh[:, None])[:, None, :, None]
            & (jnp.arange(wo)[None, :] < vw[:, None])[:, None, None, :])
    return out * mask.astype(out.dtype)


@register_op("sync_batch_norm")
def sync_batch_norm(x, weight, bias, running_mean, running_var,
                    momentum=0.9, epsilon=1e-5, training=True,
                    axis_name=None, data_format="NCHW", name=None):
    """Cross-replica batch norm (ref sync_batch_norm_op.cu: NCCL
    allreduce of per-device (sum, square_sum); here lax.pmean over the
    named mesh axis — inside shard_map/pmap pass axis_name="dp").
    Returns (y, mean_out, variance_out, saved_mean, saved_inv_std)."""
    reduce_axes = ((0, 2, 3) if x.ndim == 4 and data_format == "NCHW"
                   else (0,) + tuple(range(2, x.ndim))
                   if data_format == "NCHW" else
                   tuple(range(x.ndim - 1)))
    shape = [1] * x.ndim
    ch_axis = 1 if data_format == "NCHW" else x.ndim - 1
    shape[ch_axis] = -1
    if training:
        mean = jnp.mean(x, axis=reduce_axes)
        sqmean = jnp.mean(jnp.square(x), axis=reduce_axes)
        if axis_name is not None:
            mean = jax.lax.pmean(mean, axis_name)
            sqmean = jax.lax.pmean(sqmean, axis_name)
        var = sqmean - jnp.square(mean)
        mean_out = momentum * running_mean + (1 - momentum) * mean
        var_out = momentum * running_var + (1 - momentum) * var
    else:
        mean, var = running_mean, running_var
        mean_out, var_out = running_mean, running_var
    inv_std = jax.lax.rsqrt(var + epsilon)
    y = (x - mean.reshape(shape)) * inv_std.reshape(shape)
    y = y * weight.reshape(shape) + bias.reshape(shape)
    return y, mean_out, var_out, mean, inv_std
