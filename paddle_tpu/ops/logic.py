"""Comparison / logical ops (paddle.tensor.logic parity).

Reference surface: python/paddle/tensor/logic.py + operators/controlflow
compare ops in /root/reference.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..framework import Tensor, _unwrap
from .registry import register_op

__all__ = [
    "all", "any",
    "equal", "not_equal", "greater_than", "greater_equal", "less_than",
    "less_equal", "equal_all", "allclose", "isclose", "logical_and",
    "logical_or", "logical_not", "logical_xor", "bitwise_and", "bitwise_or",
    "bitwise_not", "bitwise_xor", "is_empty", "is_tensor", "isreal",
    "bitwise_left_shift", "bitwise_right_shift",
]


def _cmp(name, fn):
    @register_op(name)
    def op(x, y, name=None):
        return fn(x, y)
    op.__name__ = name
    return op


equal = _cmp("equal", jnp.equal)
not_equal = _cmp("not_equal", jnp.not_equal)
greater_than = _cmp("greater_than", jnp.greater)
greater_equal = _cmp("greater_equal", jnp.greater_equal)
less_than = _cmp("less_than", jnp.less)
less_equal = _cmp("less_equal", jnp.less_equal)
logical_and = _cmp("logical_and", jnp.logical_and)
logical_or = _cmp("logical_or", jnp.logical_or)
logical_xor = _cmp("logical_xor", jnp.logical_xor)
bitwise_and = _cmp("bitwise_and", jnp.bitwise_and)
bitwise_or = _cmp("bitwise_or", jnp.bitwise_or)
bitwise_xor = _cmp("bitwise_xor", jnp.bitwise_xor)
bitwise_left_shift = _cmp("bitwise_left_shift", jnp.left_shift)
bitwise_right_shift = _cmp("bitwise_right_shift", jnp.right_shift)


@register_op("logical_not")
def logical_not(x, name=None):
    return jnp.logical_not(x)


@register_op("bitwise_not")
def bitwise_not(x, name=None):
    return jnp.bitwise_not(x)


@register_op("isreal")
def isreal(x, name=None):
    return jnp.isreal(x)


def equal_all(x, y, name=None):
    a, b = _unwrap(x), _unwrap(y)
    if a.shape != b.shape:
        return Tensor(jnp.asarray(False))
    return Tensor(jnp.all(a == b))


def allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return Tensor(jnp.allclose(_unwrap(x), _unwrap(y), rtol=rtol, atol=atol,
                               equal_nan=equal_nan))


@register_op("isclose")
def isclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return jnp.isclose(x, y, rtol=rtol, atol=atol, equal_nan=equal_nan)


def is_empty(x, name=None):
    return Tensor(jnp.asarray(_unwrap(x).size == 0))


def is_tensor(x):
    return isinstance(x, Tensor)


@register_op("reduce_all")
def all(x, axis=None, keepdim=False, name=None):  # noqa: A001
    """paddle.all (ref reduce_all_op)."""
    return jnp.all(x, axis=axis, keepdims=keepdim)


@register_op("reduce_any")
def any(x, axis=None, keepdim=False, name=None):  # noqa: A001
    """paddle.any (ref reduce_any_op)."""
    return jnp.any(x, axis=axis, keepdims=keepdim)
