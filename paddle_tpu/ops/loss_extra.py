"""Loss / ranking / ads-model ops from the reference's long tail.

Reference specs (semantics; implementations are jnp-first):
  hinge_loss_op.h (l = max(0, 1 - x*(2y-1))),
  huber_loss_op.h (0.5*d^2 inside delta, delta*(|d|-0.5*delta) outside),
  modified_huber_loss_op.h (-4v if v<-1, (1-v)^2 if v<1, 0 else; v=x*(2y-1)),
  rank_loss_op.h (log(1+exp(l-r)) - label*(l-r)),
  bpr_loss_op.h (Bayesian personalized ranking over classes),
  center_loss_op.h (0.5*||x - centers[label]||^2 + center EMA update),
  teacher_student_sigmoid_loss_op.h (click + teacher-score double sigmoid),
  fsp_op.h (flow-of-solution-procedure matrix for distillation),
  cvm_op.h (show/click log transforms), data_norm_op.cc
  (means=sum/size, scales=sqrt(size/square_sum)),
  nce_op.h (noise-contrastive estimation with log-uniform sampling),
  sample_logits_op.h (sampled-softmax gather),
  hierarchical_sigmoid_op.h + math/matrix_bit_code.h (SimpleCode paths),
  match_matrix_tensor_op.cc (x W_c y^T text-match tensors).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..framework import Tensor, _unwrap
from .registry import register_op

__all__ = [
    "hinge_loss", "huber_loss", "modified_huber_loss", "rank_loss",
    "bpr_loss", "center_loss", "teacher_student_sigmoid_loss", "fsp",
    "cvm", "data_norm", "nce", "sample_logits", "hierarchical_sigmoid",
    "match_matrix_tensor",
]


@register_op("hinge_loss")
def hinge_loss(logits, labels, name=None):
    """l = max(0, 1 - logits*(2*labels - 1)); labels in {0,1}."""
    return jnp.maximum(0.0, 1.0 - logits * (2.0 * labels - 1.0))


@register_op("huber_loss")
def huber_loss(input, label, delta=1.0, name=None):
    """Returns (residual, loss) like the reference (residual kept for the
    grad path there; here for output parity)."""
    r = label - input
    a = jnp.abs(r)
    loss = jnp.where(a <= delta, 0.5 * r * r, delta * (a - 0.5 * delta))
    return r, loss


@register_op("modified_huber_loss")
def modified_huber_loss(logits, labels, name=None):
    v = logits * (2.0 * labels - 1.0)
    return jnp.where(v < -1.0, -4.0 * v,
                     jnp.where(v < 1.0, jnp.square(1.0 - v), 0.0))


@register_op("rank_loss")
def rank_loss(label, left, right, name=None):
    d = left - right
    return jnp.log(1.0 + jnp.exp(d)) - label * d


@register_op("bpr_loss")
def bpr_loss(logits, label, name=None):
    """-mean_{j != label} log(sigmoid(x_label - x_j)) per row."""
    b, c = logits.shape
    pos = jnp.take_along_axis(
        logits, label.reshape(b, 1).astype(jnp.int32), axis=1)
    diff = pos - logits                                  # [B, C]
    # log(sigmoid(d)) = -log(1 + exp(-d)); clip like TolerableValue
    logsig = -jnp.log1p(jnp.clip(jnp.exp(-diff), 0.0, 1e20))
    mask = jnp.ones((b, c), logits.dtype) - jax.nn.one_hot(
        label.reshape(b).astype(jnp.int32), c, dtype=logits.dtype)
    return (-(logsig * mask).sum(axis=1, keepdims=True)
            / (c - 1)).astype(logits.dtype)


@register_op("center_loss")
def center_loss(x, label, centers, alpha=0.05, need_update=True, name=None):
    """Returns (loss [B,1], sample_center_diff [B,D], centers_out).
    Center update: c -= alpha * sum(diff_c) / (1 + count_c)."""
    lbl = label.reshape(-1).astype(jnp.int32)
    diff = x - centers[lbl]
    loss = 0.5 * jnp.sum(jnp.square(diff), axis=1, keepdims=True)
    if need_update:
        k = centers.shape[0]
        sums = jnp.zeros_like(centers).at[lbl].add(diff)
        counts = jnp.zeros((k,), x.dtype).at[lbl].add(1.0)
        centers_out = centers + alpha * sums / (1.0 + counts)[:, None]
    else:
        centers_out = centers
    return loss, diff, centers_out


@register_op("teacher_student_sigmoid_loss")
def teacher_student_sigmoid_loss(x, label, soft_max_up_bound=15.0,
                                 soft_max_lower_bound=-15.0, name=None):
    """Double sigmoid CE keyed on the label coding scheme in
    teacher_student_sigmoid_loss_op.h (label<-1: no-click no-teacher;
    -1<=label<0: click no-teacher; else click bit + teacher score)."""
    sp = jnp.maximum(x, 0.0) + jnp.log1p(jnp.exp(-jnp.abs(x)))
    no_click = sp                       # z=0, no teacher
    click = sp - x                      # z=1, no teacher
    z2 = jnp.where(label < 1.0, label, label - 1.0)
    clk = jnp.where(label < 1.0, 0.0, 1.0)
    with_teacher = sp - clk * x + sp - z2 * x
    return jnp.where(label < -1.0, no_click,
                     jnp.where(label < 0.0, click, with_teacher))


@register_op("fsp")
def fsp(x, y, name=None):
    """FSP distillation matrix (fsp_op.h): out[b,i,j] =
    mean_hw x[b,i,h,w] * y[b,j,h,w]."""
    h, w = x.shape[2], x.shape[3]
    return jnp.einsum("bihw,bjhw->bij", x, y) / (h * w)


@register_op("cvm")
def cvm(x, cvm_in=None, use_cvm=True, name=None):
    """Show/click feature transform (cvm_op.h): col0=log(show+1),
    col1=log(click+1)-col0 when use_cvm, else drop the two cvm cols."""
    if use_cvm:
        c0 = jnp.log(x[:, 0:1] + 1.0)
        c1 = jnp.log(x[:, 1:2] + 1.0) - c0
        return jnp.concatenate([c0, c1, x[:, 2:]], axis=1)
    return x[:, 2:]


@register_op("data_norm")
def data_norm(x, batch_size, batch_sum, batch_square_sum, epsilon=1e-4,
              name=None):
    """Global-statistics normalization (data_norm_op.cc): means =
    batch_sum/batch_size, scales = sqrt(batch_size/batch_square_sum);
    returns (y, means, scales)."""
    means = batch_sum / batch_size
    scales = jnp.sqrt(batch_size / batch_square_sum)
    return (x - means[None, :]) * scales[None, :], means, scales


# ---------------------------------------------------------------------------
# sampled-class ops (nce / sample_logits) with the reference's log-uniform
# sampler: P(x) = ln(1 + 1/(x+1)) / ln(range+1)  (math/sampler.h)
# ---------------------------------------------------------------------------

def _log_uniform_sample(key, shape, range_):
    u = jax.random.uniform(key, shape)
    s = jnp.exp(u * np.log(range_ + 1.0)) - 1.0
    return jnp.clip(s.astype(jnp.int32), 0, range_ - 1)


def _log_uniform_prob(x, range_):
    return jnp.log1p(1.0 / (x.astype(jnp.float32) + 1.0)) / np.log(
        range_ + 1.0)


@register_op("nce", tags=("rng",))
def nce(x, label, weight, bias=None, num_total_classes=None,
        num_neg_samples=10, seed=None, sampler="log_uniform", name=None):
    """Noise-contrastive estimation (nce_op.h). Returns (cost [B,1],
    sample_logits, sample_labels). o = sigmoid(x.W[c] + b[c]);
    cost = -log(o/(o+kq)) for true c, -log(kq/(o+kq)) for sampled.
    seed=None (default) draws a fresh key per call from the framework
    generator — fixed seeds are for reproducible tests only."""
    n = int(num_total_classes or weight.shape[0])
    b = x.shape[0]
    k = int(num_neg_samples)
    if seed is None:
        from ..core.generator import next_key
        key = next_key()
    else:
        key = jax.random.key(int(seed))
    if sampler == "uniform":
        neg = jax.random.randint(key, (b, k), 0, n)
        q = jnp.full((b, k), 1.0 / n)
    else:
        neg = _log_uniform_sample(key, (b, k), n)
        q = _log_uniform_prob(neg, n)
    pos = label.reshape(b, 1).astype(jnp.int32)
    samples = jnp.concatenate([pos, neg], axis=1)        # [B, 1+K]
    w = weight[samples]                                  # [B,1+K,D]
    logits = jnp.einsum("bd,bkd->bk", x, w)
    if bias is not None:
        logits = logits + bias[samples]
    o = jax.nn.sigmoid(logits)
    qpos = (jnp.full((b, 1), 1.0 / n) if sampler == "uniform"
            else _log_uniform_prob(pos, n))
    kq = k * jnp.concatenate([qpos, q], axis=1)
    eps = 1e-12
    cost_true = -jnp.log(o[:, :1] / (o[:, :1] + kq[:, :1]) + eps)
    cost_neg = -jnp.log(kq[:, 1:] / (o[:, 1:] + kq[:, 1:]) + eps)
    cost = cost_true.sum(1, keepdims=True) + cost_neg.sum(1, keepdims=True)
    return cost, logits, samples


@register_op("sample_logits", tags=("rng",))
def sample_logits(logits, label, num_samples=10, seed=None, uniq=True,
                  remove_accidental_hits=True, use_customized_samples=False,
                  customized_samples=None, customized_probabilities=None,
                  name=None):
    """Sampled-softmax gather (sample_logits_op.h): returns (samples,
    probabilities, sampled_logits, sampled_label). Sampled logits are
    logits[samples] - log(q) (subtract-log-q trick); accidental hits of
    the true class among negatives get -1e20."""
    b, n = logits.shape
    nt = label.shape[1] if label.ndim > 1 else 1
    pos = label.reshape(b, nt).astype(jnp.int32)
    if use_customized_samples:
        neg = customized_samples.astype(jnp.int32)
        q_neg = customized_probabilities
    else:
        if seed is None:
            from ..core.generator import next_key
            key = next_key()
        else:
            key = jax.random.key(int(seed))
        neg = _log_uniform_sample(key, (b, int(num_samples)), n)
        q_neg = _log_uniform_prob(neg, n)
    samples = jnp.concatenate([pos, neg], axis=1)
    q_pos = _log_uniform_prob(pos, n)
    probs = jnp.concatenate([q_pos, q_neg], axis=1)
    gathered = jnp.take_along_axis(logits, samples, axis=1)
    sampled = gathered - jnp.log(probs + 1e-12)
    if remove_accidental_hits:
        hit = (neg[:, None, :] == pos[:, :, None]).any(axis=1)
        sampled = sampled.at[:, nt:].add(
            jnp.where(hit, -1e20, 0.0).astype(sampled.dtype))
    sampled_label = jnp.broadcast_to(
        jnp.arange(nt, dtype=jnp.int32)[None, :], (b, nt))
    return samples, probs, sampled, sampled_label


@register_op("hierarchical_sigmoid")
def hierarchical_sigmoid(x, label, w, bias=None, num_classes=2, name=None):
    """Default-tree hsigmoid (hierarchical_sigmoid_op.h + SimpleCode in
    math/matrix_bit_code.h: c = label + num_classes, node index at bit b
    is (c>>(b+1))-1, target bit is c&(1<<b), path length =
    highest_set_bit(c)-1). Returns (cost [B,1], pre_out)."""
    n = int(num_classes)
    b = x.shape[0]
    max_len = int(np.floor(np.log2(2 * n - 1)))
    c = label.reshape(b).astype(jnp.int32) + n
    bits = jnp.arange(max_len, dtype=jnp.int32)          # [L]
    length = (jnp.floor(jnp.log2(c.astype(jnp.float32)))
              ).astype(jnp.int32)                        # FindLastSet-1
    valid = bits[None, :] < length[:, None]              # [B, L]
    idx = jnp.clip((c[:, None] >> (bits[None, :] + 1)) - 1, 0,
                   w.shape[0] - 1)                       # [B, L]
    bit = ((c[:, None] >> bits[None, :]) & 1).astype(x.dtype)
    pre = jnp.einsum("bd,bld->bl", x, w[idx])
    if bias is not None:
        pre = pre + bias.reshape(-1)[idx]
    # BCE-with-logits against the path bits, masked to real path length
    sp = jnp.maximum(pre, 0.0) + jnp.log1p(jnp.exp(-jnp.abs(pre)))
    loss = (sp - bit * pre) * valid.astype(x.dtype)
    return loss.sum(axis=1, keepdims=True), pre


@register_op("match_matrix_tensor")
def match_matrix_tensor(x, y, w, dim_t=None, name=None):
    """Text-match tensors (match_matrix_tensor_op.cc): x [B,T1,D1],
    y [B,T2,D2], w [D1,C,D2] -> out [B,C,T1,T2]; returns (out, tmp) where
    tmp = x·w ([B,T1,C,D2])."""
    tmp = jnp.einsum("bsd,dce->bsce", x, w)
    out = jnp.einsum("bsce,bte->bcst", tmp, y)
    return out, tmp
