"""Control-flow ops (reference operators/controlflow/conditional_block_op,
while_op + python layers/control_flow.py cond/while_loop).

TPU-first: paddle.static.nn.cond / paddle.static.nn.while_loop map to
lax.cond / lax.while_loop so data-dependent control flow stays inside one
compiled program (the reference interprets sub-blocks on the host). In
eager mode with concrete tensors they just branch in Python — same
semantics, zero tracing overhead.
"""
from __future__ import annotations

from typing import Callable, List, Sequence

import jax
import jax.numpy as jnp

from ..framework import Tensor, _unwrap
from .registry import run_op

__all__ = ["cond", "while_loop", "bounded_while_loop", "case",
           "switch_case", "scan", "fori_loop"]


def _is_traced(x):
    import jax.core
    return isinstance(x, jax.core.Tracer)


def cond(pred, true_fn, false_fn=None, operands=(), name=None):
    """paddle.static.nn.cond. Eager: plain python branch. Traced (inside
    jit/to_static): lax.cond keeps both branches in-graph."""
    p = _unwrap(pred)
    operands = tuple(operands)
    if not _is_traced(p):
        if bool(p):
            return true_fn(*operands)
        return false_fn(*operands) if false_fn is not None else None

    def wrap(fn):
        def pure(*arrays):
            out = fn(*[Tensor(a) for a in arrays])
            if isinstance(out, (list, tuple)):
                return tuple(_unwrap(o) for o in out)
            return _unwrap(out)
        return pure

    if false_fn is None:
        # lax.cond requires both branches to return the same structure;
        # there is no traced equivalent of "do nothing, return None"
        raise ValueError(
            "paddle.cond inside jit/to_static requires both true_fn and "
            "false_fn (branches must return the same structure); got "
            "false_fn=None")
    arrays = tuple(_unwrap(o) for o in operands)
    out = jax.lax.cond(p, wrap(true_fn), wrap(false_fn), *arrays)
    if isinstance(out, tuple):
        return tuple(Tensor(o) for o in out)
    return Tensor(out)


def while_loop(cond_fn, body_fn, loop_vars, is_test=False, name=None):
    """paddle.static.nn.while_loop → lax.while_loop (structured carry)."""
    arrays = [_unwrap(v) for v in loop_vars]

    def c(vals):
        out = cond_fn(*[Tensor(v) for v in vals])
        return _unwrap(out)

    def b(vals):
        out = body_fn(*[Tensor(v) for v in vals])
        out = out if isinstance(out, (list, tuple)) else (out,)
        return tuple(_unwrap(o) for o in out)

    res = jax.lax.while_loop(c, b, tuple(arrays))
    return [Tensor(r) for r in res]


def bounded_while_loop(cond_fn, body_fn, loop_vars, max_iters: int,
                       name=None):
    """Differentiable while: a lax.scan over `max_iters` steps where
    iterations past the (dynamic) exit condition pass the carry through
    unchanged. Reverse-mode differentiable — the TPU answer to the
    reference while_op's backward (backward.py builds grad blocks for
    while; lax.while_loop has no transpose, masked scan does).

    Semantics match while_loop as long as the true iteration count never
    exceeds max_iters (excess iterations are silently truncated — choose
    the bound accordingly)."""
    arrays = [_unwrap(v) for v in loop_vars]

    def step(carry, _):
        vals = carry
        pred = _unwrap(cond_fn(*[Tensor(v) for v in vals]))
        out = body_fn(*[Tensor(v) for v in vals])
        out = out if isinstance(out, (list, tuple)) else (out,)
        new_vals = tuple(
            jnp.where(pred, _unwrap(o).astype(jnp.asarray(v).dtype), v)
            for o, v in zip(out, vals))
        return new_vals, None

    res, _ = jax.lax.scan(step, tuple(arrays), None, length=int(max_iters))
    return [Tensor(r) for r in res]


def fori_loop(lower, upper, body_fn, init, name=None):
    def b(i, val):
        out = body_fn(Tensor(jnp.asarray(i)), Tensor(val))
        return _unwrap(out)
    return Tensor(jax.lax.fori_loop(int(_unwrap(lower)),
                                    int(_unwrap(upper)), b,
                                    _unwrap(init)))


def scan(f, init, xs, name=None):
    """lax.scan surface for sequence programs (rnn-style)."""
    def body(carry, x):
        c, y = f(Tensor(carry), Tensor(x))
        return _unwrap(c), _unwrap(y)
    carry, ys = jax.lax.scan(body, _unwrap(init), _unwrap(xs))
    return Tensor(carry), Tensor(ys)


def case(pred_fn_pairs, default=None, name=None):
    """paddle.static.nn.case: first true predicate wins (eager)."""
    for pred, fn in pred_fn_pairs:
        if bool(_unwrap(pred)):
            return fn()
    if default is not None:
        return default()
    raise ValueError("no case matched and no default given")


def switch_case(branch_index, branch_fns, default=None, name=None):
    idx = _unwrap(branch_index)
    if isinstance(branch_fns, dict):
        keys = sorted(branch_fns)
        fns = [branch_fns[k] for k in keys]
        if not _is_traced(idx):
            i = int(idx)
            if i in branch_fns:
                return branch_fns[i]()
            return default() if default else None
        karr = jnp.asarray(keys)
        pos = jnp.searchsorted(karr, idx)
        if default is not None:
            # unmatched keys route to the default branch (appended last);
            # mirrors the eager path above
            matched = (pos < len(keys)) & (karr[jnp.minimum(
                pos, len(keys) - 1)] == idx)
            fns = fns + [default]
            idx = jnp.where(matched, pos, len(keys))
        else:
            idx = pos
    else:
        fns = list(branch_fns)
        if not _is_traced(idx):
            i = int(idx)
            if 0 <= i < len(fns):
                return fns[i]()
            return default() if default else None
        if default is not None:
            in_range = (idx >= 0) & (idx < len(fns))
            fns = fns + [default]
            idx = jnp.where(in_range, idx, len(fns) - 1)

    def wrap(fn):
        def pure(_):
            return _unwrap(fn())
        return pure
    out = jax.lax.switch(idx, [wrap(f) for f in fns], 0)
    return Tensor(out)
