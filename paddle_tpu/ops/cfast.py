"""Bridge to the C eager fast dispatch (csrc/fast_dispatch.c).

Reference analogue: build-time codegen of one C function per op
(/root/reference/paddle/fluid/pybind/op_function_generator.cc:488 —
`core.ops.<op>` fast entries used by dygraph python). Here one generic
C entry covers the whole registry: C scans the call, keys its own
cache, invokes the cached jitted forward and wraps the outputs as
Tensors without executing Python bytecode. run_op consults it first
and falls back seamlessly (the C entry returns NotImplemented for
grad-required calls, non-scalar attrs, rng/mesh ops, unjittable ops).

Builds on demand through csrc/Makefile; every consumer must tolerate
`cfast_module() is None` (no toolchain) — native is the fast path,
never a dependency.
"""
from __future__ import annotations

import importlib.machinery
import importlib.util
import os
import subprocess
import threading
from typing import Optional

__all__ = ["cfast_module", "make_jit"]

_CSRC = os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))), "csrc")
_SO = os.path.join(_CSRC, "paddle_tpu_cfast.so")
_lock = threading.Lock()
_mod = None
_tried = False


def _build() -> Optional[str]:
    src = os.path.join(_CSRC, "fast_dispatch.c")
    if not os.path.exists(src):
        return None
    if os.path.exists(_SO) and \
            os.path.getmtime(_SO) >= os.path.getmtime(src):
        return _SO
    res = subprocess.run(
        ["make", "-C", _CSRC, "paddle_tpu_cfast.so"],
        capture_output=True, text=True)
    if res.returncode != 0 or not os.path.exists(_SO):
        return None
    return _SO


def make_jit(name, fn, args, kwargs):
    """One-time cache-miss callback from C: build the jitted forward
    for this (op, signature), or None when the op must stay on the
    python path (rng/mesh tags, blacklisted, jit disabled, or the
    first real call fails to trace)."""
    import jax

    from ..framework import Tensor
    from .registry import OPS, _EAGER_NOJIT

    info = OPS.get(name)
    if (name in _EAGER_NOJIT or info is None or info.fn is not fn
            or "rng" in info.tags or "mesh" in info.tags):
        return None
    tensor_pos = [i for i, a in enumerate(args)
                  if isinstance(a, Tensor)]
    arg_template = tuple(None if isinstance(a, Tensor) else a
                         for a in args)
    kw = dict(kwargs)

    def pure(*diff):
        full = list(arg_template)
        for p, v in zip(tensor_pos, diff):
            full[p] = v
        res = fn(*full, **kw)
        return tuple(res) if isinstance(res, list) else res

    # validate by ABSTRACT trace: catches untraceable ops (and plain
    # bad calls) without executing or compiling. Refusing here caches
    # None for THIS signature only — a genuinely erroneous call (shape
    # mismatch) must not blacklist the op the way a slow-path-proven
    # jit failure does (registry run_op blacklists only after the slow
    # path succeeded where jit failed).
    avals = [jax.ShapeDtypeStruct(a._data.shape, a._data.dtype)
             for a in args if isinstance(a, Tensor)]
    try:
        jax.eval_shape(pure, *avals)
    except Exception:
        return None
    return jax.jit(pure)


def cfast_module():
    """The loaded C extension module, or None (built lazily once)."""
    global _mod, _tried
    if _mod is not None or _tried:
        return _mod
    with _lock:
        if _mod is not None or _tried:
            return _mod
        _tried = True
        if os.environ.get("PD_DISABLE_CFAST", "").strip() in (
                "1", "true", "yes"):
            return None
        so = _build()
        if so is None:
            return None
        try:
            loader = importlib.machinery.ExtensionFileLoader(
                "paddle_tpu_cfast", so)
            spec = importlib.util.spec_from_loader(
                "paddle_tpu_cfast", loader)
            mod = importlib.util.module_from_spec(spec)
            loader.exec_module(mod)
            from ..framework import Tensor
            mod.init_fastpath(Tensor, make_jit)
            _mod = mod
        except Exception:
            _mod = None
        return _mod
