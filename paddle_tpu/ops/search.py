"""Search / sort ops (paddle.tensor.search parity).

Reference surface: python/paddle/tensor/search.py + argsort/topk/where ops
under /root/reference/paddle/fluid/operators/. top_k uses jax.lax.top_k
(maps to a fast XLA TPU sort); dynamic-shape results (nonzero) are
eager-only.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..framework import Tensor, _unwrap
from .registry import register_op

__all__ = [
    "argmax", "argmin", "argsort", "sort", "topk", "nonzero", "kthvalue",
    "mode", "median", "nanmedian", "quantile", "nanquantile", "searchsorted",
    "index_of_max",
]


@register_op("arg_max")
def argmax(x, axis=None, keepdim=False, dtype="int64", name=None):
    out = jnp.argmax(x, axis=axis, keepdims=keepdim if axis is not None
                     else False)
    return out.astype(jnp.dtype(str(dtype)) if isinstance(dtype, str)
                      else dtype)


@register_op("arg_min")
def argmin(x, axis=None, keepdim=False, dtype="int64", name=None):
    out = jnp.argmin(x, axis=axis, keepdims=keepdim if axis is not None
                     else False)
    return out.astype(jnp.dtype(str(dtype)) if isinstance(dtype, str)
                      else dtype)


@register_op("argsort")
def argsort(x, axis=-1, descending=False, name=None):
    out = jnp.argsort(x, axis=axis, descending=descending)
    return out.astype(jnp.int64)


@register_op("sort_op")
def sort(x, axis=-1, descending=False, name=None):
    out = jnp.sort(x, axis=axis, descending=descending)
    return out


@register_op("top_k_v2")
def _topk_impl(x, k, axis, largest):
    if axis != -1 and axis != jnp.ndim(x) - 1:
        x = jnp.moveaxis(x, axis, -1)
    vals, idx = jax.lax.top_k(x if largest else -x, k)
    if not largest:
        vals = -vals
    if axis != -1 and axis != jnp.ndim(vals) - 1 + 0:
        vals = jnp.moveaxis(vals, -1, axis)
        idx = jnp.moveaxis(idx, -1, axis)
    return vals, idx.astype(jnp.int64)


def topk(x, k, axis=-1, largest=True, sorted=True, name=None):
    k = int(_unwrap(k))
    nd = _unwrap(x).ndim
    axis = axis % nd if nd else 0
    vals, idx = _topk_impl(x, k=k, axis=axis if nd else -1,
                           largest=largest)
    return vals, idx


@register_op("kthvalue_op")
def _kthvalue_impl(x, k, axis, keepdim):
    sorted_vals = jnp.sort(x, axis=axis)
    sorted_idx = jnp.argsort(x, axis=axis)
    vals = jnp.take(sorted_vals, k - 1, axis=axis)
    idx = jnp.take(sorted_idx, k - 1, axis=axis)
    if keepdim:
        vals = jnp.expand_dims(vals, axis)
        idx = jnp.expand_dims(idx, axis)
    return vals, idx.astype(jnp.int64)


def kthvalue(x, k, axis=-1, keepdim=False, name=None):
    return _kthvalue_impl(x, k=int(_unwrap(k)), axis=axis, keepdim=keepdim)


@register_op("mode_op")
def _mode_impl(x, axis, keepdim):
    x_m = jnp.moveaxis(x, axis, -1)
    sorted_v = jnp.sort(x_m, axis=-1)
    n = x_m.shape[-1]
    # run-length trick: count occurrences of each sorted value
    eq = sorted_v[..., :, None] == sorted_v[..., None, :]
    counts = eq.sum(-1)
    best = jnp.argmax(counts, axis=-1)
    vals = jnp.take_along_axis(sorted_v, best[..., None], axis=-1)[..., 0]
    # index of the *last* occurrence in the original array (paddle semantics)
    match = x_m == vals[..., None]
    ar = jnp.arange(n)
    idx = jnp.max(jnp.where(match, ar, -1), axis=-1)
    if keepdim:
        vals, idx = vals[..., None], idx[..., None]
        return jnp.moveaxis(vals, -1, axis), jnp.moveaxis(
            idx.astype(jnp.int64), -1, axis)
    return vals, idx.astype(jnp.int64)


def mode(x, axis=-1, keepdim=False, name=None):
    return _mode_impl(x, axis=axis, keepdim=keepdim)


@register_op("median_op")
def median(x, axis=None, keepdim=False, mode="avg", name=None):
    return jnp.median(x, axis=axis, keepdims=keepdim)


@register_op("nanmedian_op")
def nanmedian(x, axis=None, keepdim=False, name=None):
    return jnp.nanmedian(x, axis=axis, keepdims=keepdim)


@register_op("quantile_op")
def quantile(x, q, axis=None, keepdim=False, interpolation="linear",
             name=None):
    return jnp.quantile(x, jnp.asarray(q), axis=axis, keepdims=keepdim,
                        method=interpolation)


@register_op("nanquantile_op")
def nanquantile(x, q, axis=None, keepdim=False, name=None):
    return jnp.nanquantile(x, jnp.asarray(q), axis=axis, keepdims=keepdim)


@register_op("searchsorted_op")
def searchsorted(sorted_sequence, values, out_int32=False, right=False,
                 name=None):
    side = "right" if right else "left"
    if jnp.ndim(sorted_sequence) == 1:
        out = jnp.searchsorted(sorted_sequence, values, side=side)
    else:
        out = jax.vmap(lambda s, v: jnp.searchsorted(s, v, side=side))(
            sorted_sequence.reshape(-1, sorted_sequence.shape[-1]),
            values.reshape(-1, values.shape[-1]))
        out = out.reshape(values.shape)
    return out.astype(jnp.int32 if out_int32 else jnp.int64)


def nonzero(x, as_tuple=False):
    a = np.asarray(_unwrap(x))
    nz = np.nonzero(a)
    if as_tuple:
        return tuple(Tensor(jnp.asarray(i[:, None].astype(np.int64)))
                     for i in nz)
    return Tensor(jnp.asarray(np.stack(nz, axis=1).astype(np.int64)))


def index_of_max(x, axis=None):
    return argmax(x, axis=axis)
