"""Sequence ops (paddle.fluid.layers.sequence_lod / operators/sequence_ops
parity).

The reference represents variable-length batches as LoDTensors — a flat data
tensor plus nested offset tables (lod_tensor.h:114) — and every sequence op
walks those offsets with per-sequence scalar loops. That representation is
hostile to XLA (data-dependent shapes), so this framework uses the padded
representation as the canonical one: a batch is `[B, T_max, ...]` plus an
explicit `length [B]` int tensor. This is SURVEY.md §7 hard-part (b)'s
bucketing/padding policy made first-class — and it is also exactly what
`sequence_pad`/`sequence_unpad` convert to/from in the reference, so the API
surface lines up: ops that consumed LoD there take `(x, length)` here.

All masks are built with `sequence_mask`; reductions run over the full padded
tensor with mask-select, which XLA fuses into single VPU kernels.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..framework import Tensor, _unwrap
from .registry import register_op

__all__ = [
    "sequence_mask", "sequence_pad", "sequence_unpad", "sequence_pool",
    "sequence_first_step", "sequence_last_step", "sequence_softmax",
    "sequence_expand", "sequence_expand_as", "sequence_reverse",
    "sequence_concat", "sequence_slice", "sequence_reshape",
    "sequence_enumerate",
]


@register_op("sequence_mask")
def sequence_mask(x, maxlen=None, dtype="int64", name=None):
    """[B] lengths -> [B, maxlen] 0/1 mask (ref sequence_mask_op.h)."""
    x = jnp.asarray(x)
    if maxlen is None:
        maxlen = int(jnp.max(x))  # eager-only when maxlen unspecified
    rng = jnp.arange(maxlen, dtype=x.dtype)
    mask = rng[None, :] < x[..., None]
    return mask.astype(jnp.dtype(str(dtype)) if isinstance(dtype, str)
                       else dtype)


@register_op("sequence_pad")
def sequence_pad(x, pad_value, length, maxlen=None, name=None):
    """Flat packed [sum(L), D] + lengths [B] -> ([B, maxlen, D], length).

    Ref sequence_pad_op — LoD input becomes (flat, lengths) here. Inverse of
    sequence_unpad. Static maxlen required under jit.
    """
    length = jnp.asarray(length)
    b = length.shape[0]
    if maxlen is None:
        maxlen = int(jnp.max(length))
    starts = jnp.concatenate([jnp.zeros((1,), length.dtype),
                              jnp.cumsum(length)[:-1]])
    feat = x.shape[1:] if x.ndim > 1 else ()
    idx = starts[:, None] + jnp.arange(maxlen)
    idx = jnp.clip(idx, 0, x.shape[0] - 1)
    out = x[idx]                                       # [B, maxlen, *feat]
    mask = jnp.arange(maxlen)[None, :] < length[:, None]
    pad = jnp.asarray(pad_value, x.dtype)
    mask = mask.reshape(b, maxlen, *([1] * len(feat)))
    return jnp.where(mask, out, pad), length


@register_op("sequence_unpad")
def sequence_unpad(x, length, name=None):
    """[B, T, D] + lengths -> packed [sum(L), D]. Dynamic output shape —
    eager-only (the compiled path keeps data padded; ref sequence_unpad_op)."""
    length = np.asarray(_unwrap(length) if isinstance(length, Tensor)
                        else length)
    xs = []
    xa = x
    for i, l in enumerate(length):
        xs.append(xa[i, :int(l)])
    return jnp.concatenate(xs, axis=0)


@register_op("sequence_pool")
def sequence_pool(x, pool_type, length=None, is_test=False, pad_value=0.0,
                  name=None):
    """Masked pooling over the time axis of [B, T, D] (+lengths).

    pool_type in {sum, average, sqrt, max, min, last, first}. Empty sequences
    produce pad_value (ref sequence_pool_op.h).
    """
    t = x.shape[1]
    if length is None:
        length = jnp.full((x.shape[0],), t, jnp.int32)
    length = jnp.asarray(length)
    mask = (jnp.arange(t)[None, :] < length[:, None])
    maskf = mask.astype(x.dtype)[..., None]
    lf = jnp.maximum(length.astype(x.dtype), 1)[:, None]
    pt = pool_type.lower()
    if pt == "sum":
        out = (x * maskf).sum(1)
    elif pt == "average":
        out = (x * maskf).sum(1) / lf
    elif pt == "sqrt":
        out = (x * maskf).sum(1) / jnp.sqrt(lf)
    elif pt == "max":
        out = jnp.where(maskf > 0, x, -jnp.inf).max(1)
    elif pt == "min":
        out = jnp.where(maskf > 0, x, jnp.inf).min(1)
    elif pt == "last":
        idx = jnp.maximum(length - 1, 0)
        out = jnp.take_along_axis(x, idx[:, None, None].astype(jnp.int32),
                                  axis=1)[:, 0]
    elif pt == "first":
        out = x[:, 0]
    else:
        raise ValueError(f"unknown pool_type {pool_type!r}")
    empty = (length == 0).reshape(-1, *([1] * (out.ndim - 1)))
    return jnp.where(empty, jnp.asarray(pad_value, x.dtype), out)


@register_op("sequence_first_step")
def sequence_first_step(x, length=None, name=None):
    return sequence_pool.__pure_fn__(x, "first", length)


@register_op("sequence_last_step")
def sequence_last_step(x, length=None, name=None):
    return sequence_pool.__pure_fn__(x, "last", length)


@register_op("sequence_softmax")
def sequence_softmax(x, length=None, name=None):
    """Per-sequence masked softmax over time axis of [B, T] or [B, T, 1]."""
    squeeze = x.ndim == 3 and x.shape[-1] == 1
    z = x[..., 0] if squeeze else x
    t = z.shape[1]
    if length is None:
        length = jnp.full((z.shape[0],), t, jnp.int32)
    mask = jnp.arange(t)[None, :] < jnp.asarray(length)[:, None]
    z = jnp.where(mask, z, -jnp.inf)
    out = jax.nn.softmax(z, axis=1)
    out = jnp.where(mask, out, 0.0)
    return out[..., None] if squeeze else out


@register_op("sequence_reverse")
def sequence_reverse(x, length=None, name=None):
    """Reverse valid prefix of each row of [B, T, ...] in time
    (ref sequence_reverse_op.h)."""
    t = x.shape[1]
    if length is None:
        return jnp.flip(x, axis=1)
    length = jnp.asarray(length)
    pos = jnp.arange(t)[None, :]
    rev = length[:, None] - 1 - pos
    idx = jnp.where(pos < length[:, None], rev, pos).astype(jnp.int32)
    return jnp.take_along_axis(
        x, idx.reshape(idx.shape + (1,) * (x.ndim - 2)), axis=1)


@register_op("sequence_expand")
def sequence_expand(x, y_length, ref_level=0, name=None):
    """Repeat each row i of x by y_length[i] and pad: [B, D] + [B] ->
    [B, max_rep, D] (padded variant of ref sequence_expand_op: the LoD
    output's ragged repeat becomes an explicit repeat axis + mask)."""
    y_length = jnp.asarray(y_length)
    max_rep = int(jnp.max(y_length)) if not isinstance(
        y_length, jax.core.Tracer) else None
    if max_rep is None:
        raise ValueError("sequence_expand needs concrete y_length under jit; "
                         "pass maxlen-padded inputs instead")
    out = jnp.repeat(x[:, None], max_rep, axis=1)
    mask = jnp.arange(max_rep)[None, :] < y_length[:, None]
    return out * mask.reshape(mask.shape + (1,) * (x.ndim - 1)).astype(x.dtype)


@register_op("sequence_expand_as")
def sequence_expand_as(x, y, name=None):
    """Broadcast each row of x [B, D] across y's time axis [B, T, ...] ->
    [B, T, D]."""
    t = y.shape[1]
    return jnp.repeat(x[:, None], t, axis=1)


@register_op("sequence_concat")
def sequence_concat(xs, lengths=None, name=None):
    """Concat along time axis, compacting valid prefixes when lengths given:
    list of [B, Ti, D] (+ lengths [B] each) -> ([B, sum(Ti), D], length)."""
    if lengths is None:
        out = jnp.concatenate(list(xs), axis=1)
        t = out.shape[1]
        return out, jnp.full((out.shape[0],), t, jnp.int32)
    xs = list(xs)
    lengths = [jnp.asarray(l) for l in lengths]
    b = xs[0].shape[0]
    t_out = sum(int(x.shape[1]) for x in xs)
    total = sum(lengths)
    feat = xs[0].shape[2:]
    out = jnp.zeros((b, t_out) + tuple(feat), xs[0].dtype)
    # scatter each source's valid prefix at the running per-row offset
    offset = jnp.zeros((b,), lengths[0].dtype)
    for x, l in zip(xs, lengths):
        t = x.shape[1]
        pos = jnp.arange(t)[None, :]
        dst = offset[:, None] + pos                    # [B, t]
        valid = pos < l[:, None]
        dst = jnp.where(valid, dst, t_out)             # out-of-range drops
        bidx = jnp.broadcast_to(jnp.arange(b)[:, None], dst.shape)
        out = out.at[bidx, dst].set(x, mode="drop")
        offset = offset + l
    return out, total


@register_op("sequence_slice")
def sequence_slice(x, offset, length, name=None):
    """Per-row slice of the time axis: [B, T, D], offset [B], length [B] ->
    [B, max(length), D] padded (ref sequence_slice_op.h)."""
    offset = jnp.asarray(offset).reshape(-1)
    length = jnp.asarray(length).reshape(-1)
    max_l = int(jnp.max(length))
    pos = jnp.arange(max_l)[None, :]
    idx = jnp.clip(offset[:, None] + pos, 0, x.shape[1] - 1).astype(jnp.int32)
    out = jnp.take_along_axis(
        x, idx.reshape(idx.shape + (1,) * (x.ndim - 2)), axis=1)
    mask = pos < length[:, None]
    return out * mask.reshape(mask.shape + (1,) * (x.ndim - 2)).astype(x.dtype)


@register_op("sequence_reshape")
def sequence_reshape(x, new_dim, name=None):
    """[B, T, D] -> [B, T*D/new_dim, new_dim] (ref sequence_reshape_op)."""
    b = x.shape[0]
    return x.reshape(b, -1, new_dim)


@register_op("sequence_enumerate")
def sequence_enumerate(x, win_size, pad_value=0, name=None):
    """Sliding windows over time: [B, T] ids -> [B, T, win_size]
    (ref sequence_enumerate_op.h; positions past the end take pad_value)."""
    t = x.shape[1]
    pos = jnp.arange(t)[:, None] + jnp.arange(win_size)[None, :]   # [T,W]
    valid = pos < t
    idx = jnp.clip(pos, 0, t - 1)
    out = x[:, idx]                                     # [B,T,W]
    return jnp.where(valid[None], out, jnp.asarray(pad_value, x.dtype))
