"""paddle.batch — minibatch-aggregating reader decorator
(reference python/paddle/batch.py:18)."""

__all__ = ["batch"]


def batch(reader, batch_size, drop_last=False):
    """Wrap a sample-yielding reader into a batch-yielding reader."""
    if batch_size <= 0:
        raise ValueError("batch_size should be a positive integer, "
                         f"got {batch_size}")

    def batch_reader():
        buf = []
        for item in reader():
            buf.append(item)
            if len(buf) == batch_size:
                yield buf
                buf = []
        if buf and not drop_last:
            yield buf

    return batch_reader
