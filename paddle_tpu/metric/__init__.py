"""Metrics (python/paddle/metric/metrics.py parity)."""
from __future__ import annotations

import numpy as np

from ..framework import Tensor, _unwrap

__all__ = ["Metric", "Accuracy", "Precision", "Recall", "Auc", "accuracy"]


def _np(x):
    return np.asarray(_unwrap(x))


class Metric:
    def __init__(self):
        pass

    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        return self.__class__.__name__.lower()

    def compute(self, pred, label):
        return pred, label


class Accuracy(Metric):
    def __init__(self, topk=(1,), name=None):
        super().__init__()
        self.topk = (topk,) if isinstance(topk, int) else tuple(topk)
        self.maxk = max(self.topk)
        self._name = name or "acc"
        self.reset()

    def reset(self):
        self.total = np.zeros(len(self.topk))
        self.count = np.zeros(len(self.topk))

    def compute(self, pred, label):
        p = _np(pred)
        l = _np(label)
        if l.ndim == p.ndim and l.shape[-1] == 1:
            l = l[..., 0]
        topk_idx = np.argsort(-p, axis=-1)[..., :self.maxk]
        correct = topk_idx == l[..., None]
        return Tensor(correct.astype(np.float32))

    def update(self, correct):
        c = _np(correct)
        for i, k in enumerate(self.topk):
            hit = c[..., :k].sum(-1).mean()
            self.total[i] += c[..., :k].sum()
            self.count[i] += c.shape[0] if c.ndim > 1 else 1
        return (self.total / np.maximum(self.count, 1))[0]

    def accumulate(self):
        res = self.total / np.maximum(self.count, 1)
        return res[0] if len(self.topk) == 1 else list(res)

    def name(self):
        if len(self.topk) == 1:
            return self._name
        return [f"{self._name}_top{k}" for k in self.topk]


class Precision(Metric):
    def __init__(self, name="precision"):
        super().__init__()
        self._name = name
        self.reset()

    def reset(self):
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        p = (_np(preds) > 0.5).astype(np.int64).ravel()
        l = _np(labels).astype(np.int64).ravel()
        self.tp += int(((p == 1) & (l == 1)).sum())
        self.fp += int(((p == 1) & (l == 0)).sum())

    def accumulate(self):
        denom = self.tp + self.fp
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Recall(Metric):
    def __init__(self, name="recall"):
        super().__init__()
        self._name = name
        self.reset()

    def reset(self):
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        p = (_np(preds) > 0.5).astype(np.int64).ravel()
        l = _np(labels).astype(np.int64).ravel()
        self.tp += int(((p == 1) & (l == 1)).sum())
        self.fn += int(((p == 0) & (l == 1)).sum())

    def accumulate(self):
        denom = self.tp + self.fn
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Auc(Metric):
    def __init__(self, curve="ROC", num_thresholds=4095, name="auc"):
        super().__init__()
        self.num_thresholds = num_thresholds
        self._name = name
        self.reset()

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds + 1)
        self._stat_neg = np.zeros(self.num_thresholds + 1)

    def update(self, preds, labels):
        p = _np(preds)
        if p.ndim == 2:
            p = p[:, -1]
        l = _np(labels).ravel()
        bins = (p.ravel() * self.num_thresholds).astype(np.int64)
        bins = np.clip(bins, 0, self.num_thresholds)
        for b, lab in zip(bins, l):
            if lab:
                self._stat_pos[b] += 1
            else:
                self._stat_neg[b] += 1

    def accumulate(self):
        tot_pos = self._stat_pos.sum()
        tot_neg = self._stat_neg.sum()
        if tot_pos == 0 or tot_neg == 0:
            return 0.0
        # trapezoid over thresholds, descending
        pos = np.cumsum(self._stat_pos[::-1])
        neg = np.cumsum(self._stat_neg[::-1])
        tpr = pos / tot_pos
        fpr = neg / tot_neg
        return float(np.trapezoid(tpr, fpr))

    def name(self):
        return self._name


def accuracy(input, label, k=1, correct=None, total=None, name=None):
    """Functional accuracy (fluid layers.accuracy parity)."""
    import jax.numpy as jnp
    from ..ops.registry import run_op

    def impl(p, l):
        if l.ndim == p.ndim and l.shape[-1] == 1:
            l = l[..., 0]
        top = jnp.argsort(-p, axis=-1)[..., :k]
        hit = jnp.any(top == l[..., None], axis=-1)
        return jnp.mean(hit.astype(jnp.float32))
    return run_op("accuracy", impl, (input, label), {})


# reference metric/__init__.py does `from . import metrics`; this module
# IS the implementation, so the submodule name aliases back to it
import sys as _sys
metrics = _sys.modules[__name__]
_sys.modules[__name__ + ".metrics"] = metrics
