"""paddle.device — device selection & capability queries
(reference python/paddle/device.py:24). TPU-first: get/set_device map
onto the Place layer over jax devices (core/place.py); CUDA-specific
queries report absence rather than raising."""
from .core.place import XPUPlace, get_device, set_device  # noqa: F401

__all__ = ["get_cudnn_version", "set_device", "get_device", "XPUPlace",
           "is_compiled_with_xpu"]


def is_compiled_with_xpu():
    return False


def is_compiled_with_cuda():
    return False


def get_cudnn_version():
    """No cuDNN in a TPU build (reference returns None when absent)."""
    return None
