"""Weight initializers (python/paddle/fluid/initializer.py parity).

Each initializer is a callable (shape, dtype) -> jax array, drawing keys
from the framework generator so paddle.seed() makes init deterministic.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ..core.generator import next_key
from ..framework import Tensor, _unwrap

__all__ = [
    "Bilinear", "set_global_initializer",
    "Initializer", "Constant", "Normal", "TruncatedNormal", "Uniform",
    "XavierNormal", "XavierUniform", "KaimingNormal", "KaimingUniform",
    "Assign", "Orthogonal", "Dirac", "calculate_gain",
]


def _fans(shape):
    shape = tuple(shape)
    if len(shape) == 0:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        return shape[0], shape[1]
    # conv kernels [out_c, in_c, *spatial]
    receptive = int(np.prod(shape[2:]))
    return shape[1] * receptive, shape[0] * receptive


def calculate_gain(nonlinearity, param=None):
    gains = {
        "sigmoid": 1.0, "linear": 1.0, "conv1d": 1.0, "conv2d": 1.0,
        "conv3d": 1.0, "tanh": 5.0 / 3.0, "relu": math.sqrt(2.0),
        "leaky_relu": math.sqrt(2.0 / (1 + (param if param is not None
                                            else 0.01) ** 2)),
        "selu": 3.0 / 4.0,
    }
    if nonlinearity not in gains:
        raise ValueError(f"unknown nonlinearity '{nonlinearity}'")
    return gains[nonlinearity]


class Initializer:
    def __call__(self, shape, dtype):
        raise NotImplementedError


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def __call__(self, shape, dtype):
        return jnp.full(tuple(shape), _unwrap(self.value), dtype)


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype):
        return (jax.random.normal(next_key(), tuple(shape), dtype) * self.std
                + self.mean)


class TruncatedNormal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype):
        n = jax.random.truncated_normal(next_key(), -2.0, 2.0, tuple(shape),
                                        dtype)
        return n * self.std + self.mean


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0):
        self.low, self.high = low, high

    def __call__(self, shape, dtype):
        return jax.random.uniform(next_key(), tuple(shape), dtype,
                                  minval=self.low, maxval=self.high)


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype):
        fi, fo = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        std = self.gain * math.sqrt(2.0 / (fi + fo))
        return jax.random.normal(next_key(), tuple(shape), dtype) * std


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype):
        fi, fo = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        limit = self.gain * math.sqrt(6.0 / (fi + fo))
        return jax.random.uniform(next_key(), tuple(shape), dtype,
                                  minval=-limit, maxval=limit)


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0,
                 nonlinearity="relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def __call__(self, shape, dtype):
        fi, _ = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        gain = calculate_gain(self.nonlinearity, self.negative_slope)
        std = gain / math.sqrt(fi)
        return jax.random.normal(next_key(), tuple(shape), dtype) * std


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0,
                 nonlinearity="relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def __call__(self, shape, dtype):
        fi, _ = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        gain = calculate_gain(self.nonlinearity, self.negative_slope)
        limit = gain * math.sqrt(3.0 / fi)
        return jax.random.uniform(next_key(), tuple(shape), dtype,
                                  minval=-limit, maxval=limit)


class Assign(Initializer):
    def __init__(self, value):
        self.value = value

    def __call__(self, shape, dtype):
        arr = jnp.asarray(_unwrap(self.value), dtype)
        assert tuple(arr.shape) == tuple(shape), \
            f"Assign shape {arr.shape} != param shape {tuple(shape)}"
        return arr


class Orthogonal(Initializer):
    def __init__(self, gain=1.0):
        self.gain = gain

    def __call__(self, shape, dtype):
        return jax.nn.initializers.orthogonal(scale=self.gain)(
            next_key(), tuple(shape), dtype)


class Dirac(Initializer):
    def __init__(self, groups=1):
        self.groups = groups

    def __call__(self, shape, dtype):
        return jax.nn.initializers.delta_orthogonal()(
            next_key(), tuple(shape), dtype)


# reference nn/initializer is a package of per-initializer modules
# (assign/constant/kaiming/normal/uniform/xavier); expose matching
# namespaces over the classes above for import parity
from types import SimpleNamespace as _NS  # noqa: E402

assign = _NS(Assign=Assign, NumpyArrayInitializer=Assign)
constant = _NS(Constant=Constant, ConstantInitializer=Constant)
kaiming = _NS(KaimingNormal=KaimingNormal, KaimingUniform=KaimingUniform,
              MSRAInitializer=KaimingNormal)
normal = _NS(Normal=Normal, TruncatedNormal=TruncatedNormal,
             NormalInitializer=Normal)
uniform = _NS(Uniform=Uniform, UniformInitializer=Uniform)
xavier = _NS(XavierNormal=XavierNormal, XavierUniform=XavierUniform,
             XavierInitializer=XavierNormal)


def _dt(dtype):
    import jax.numpy as jnp
    from ..core import dtypes as _dtypes
    return _dtypes.convert_dtype(dtype) if isinstance(dtype, str) \
        else dtype


class Bilinear(Initializer):
    """Bilinear-upsampling kernel init (reference
    initializer.BilinearInitializer: the classic transposed-conv
    upsample weights)."""

    def __call__(self, shape, dtype="float32"):
        import numpy as _np
        if len(shape) != 4:
            raise ValueError("Bilinear expects a conv kernel shape "
                             "[c_out, c_in, kh, kw]")
        c_out, c_in, kh, kw = shape
        f_h, f_w = (kh + 1) // 2, (kw + 1) // 2
        ch = (2 * f_h - 1 - f_h % 2) / (2.0 * f_h)
        cw = (2 * f_w - 1 - f_w % 2) / (2.0 * f_w)
        og = _np.ogrid[:kh, :kw]
        filt = ((1 - abs(og[0] / f_h - ch))
                * (1 - abs(og[1] / f_w - cw))).astype(_np.float32)
        w = _np.zeros(shape, _np.float32)
        for i in range(min(c_out, c_in)):
            w[i, i] = filt
        import jax.numpy as jnp
        return jnp.asarray(w, dtype=_dt(dtype))


BilinearInitializer = Bilinear

_global_weight_init = None
_global_bias_init = None


def set_global_initializer(weight_init, bias_init=None):
    """Reference initializer.set_global_initializer: the defaults
    Layer.create_parameter falls back to when no ParamAttr/initializer
    is given. Pass None to restore the built-in defaults."""
    global _global_weight_init, _global_bias_init
    _global_weight_init = weight_init
    _global_bias_init = bias_init


def _global_default(is_bias):
    return _global_bias_init if is_bias else _global_weight_init
