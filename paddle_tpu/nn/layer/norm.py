"""Normalization layers (python/paddle/nn/layer/norm.py parity).

BatchNorm keeps running stats as non-trainable buffers updated functionally
by F.batch_norm; SyncBatchNorm computes batch stats with a cross-replica
psum when running inside a sharded (shard_map/pjit) region — the TPU-native
equivalent of the reference's NCCL-based sync_batch_norm_op
(/root/reference/paddle/fluid/operators/sync_batch_norm_op.cu).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...framework import Tensor
from .. import functional as F
from ..initializer import Constant
from ..param_attr import ParamAttr
from .layers import Layer

__all__ = ["BatchNorm", "BatchNorm1D", "BatchNorm2D", "BatchNorm3D",
           "SyncBatchNorm", "LayerNorm", "GroupNorm", "InstanceNorm1D",
           "InstanceNorm2D", "InstanceNorm3D", "LocalResponseNorm",
           "SpectralNorm"]


class _BatchNormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-05,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 use_global_stats=None, name=None):
        super().__init__()
        self.num_features = num_features
        self.momentum = momentum
        self.epsilon = epsilon
        self.data_format = data_format
        self.use_global_stats = use_global_stats
        if weight_attr is False:
            self.weight = None
        else:
            self.weight = self.create_parameter(
                (num_features,), attr=ParamAttr._to_attr(weight_attr),
                default_initializer=Constant(1.0))
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter(
                (num_features,), attr=ParamAttr._to_attr(bias_attr),
                is_bias=True)
        self.register_buffer("_mean", Tensor(jnp.zeros((num_features,))))
        self.register_buffer("_variance", Tensor(jnp.ones((num_features,))))

    def forward(self, x):
        return F.batch_norm(
            x, self._mean, self._variance, self.weight, self.bias,
            training=self.training, momentum=self.momentum,
            epsilon=self.epsilon, data_format=self.data_format,
            use_global_stats=self.use_global_stats)

    def extra_repr(self):
        return f"num_features={self.num_features}"


class BatchNorm(_BatchNormBase):
    """fluid-style BatchNorm (act arg accepted for parity)."""

    def __init__(self, num_channels, act=None, momentum=0.9, epsilon=1e-05,
                 param_attr=None, bias_attr=None, data_layout="NCHW",
                 in_place=False, use_global_stats=False,
                 trainable_statistics=False, **kwargs):
        super().__init__(num_channels, momentum, epsilon, param_attr,
                         bias_attr, data_layout,
                         use_global_stats or None)
        self._act = act

    def forward(self, x):
        out = super().forward(x)
        if self._act == "relu":
            return F.relu(out)
        return out


class BatchNorm1D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-05,
                 weight_attr=None, bias_attr=None, data_format="NCL",
                 use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr,
                         bias_attr, data_format, use_global_stats)


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-05,
                 weight_attr=None, bias_attr=None, data_format="NCDHW",
                 use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr,
                         bias_attr, data_format, use_global_stats)


class SyncBatchNorm(_BatchNormBase):
    """Cross-replica batch norm. Inside a shard_map'd training step the
    batch statistics are all-reduced over the data-parallel mesh axis; in
    plain eager mode it degrades to local BatchNorm (single replica)."""

    def __init__(self, num_features, momentum=0.9, epsilon=1e-05,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr,
                         bias_attr, data_format)

    def forward(self, x):
        from ...distributed.env import current_axis_name
        axis = current_axis_name("dp")
        if not self.training or axis is None:
            return super().forward(x)
        from ...ops.registry import run_op

        ch_axis = 1 if self.data_format[1] == "C" else x._data.ndim - 1

        def impl(x, w, b):
            axes = tuple(i for i in range(x.ndim) if i != ch_axis)
            mean = jax.lax.pmean(jnp.mean(x, axis=axes), axis)
            mean_sq = jax.lax.pmean(jnp.mean(jnp.square(x), axis=axes), axis)
            var = mean_sq - jnp.square(mean)
            shape = [1] * x.ndim
            shape[ch_axis] = x.shape[ch_axis]
            out = (x - mean.reshape(shape)) * jax.lax.rsqrt(
                var.reshape(shape) + self.epsilon)
            if w is not None:
                out = out * w.reshape(shape)
            if b is not None:
                out = out + b.reshape(shape)
            return out, mean, var

        out, mean, var = run_op("sync_batch_norm", impl,
                                (x, self.weight, self.bias), {})
        self._mean.set_value(self.momentum * self._mean._data
                             + (1 - self.momentum) * mean._data)
        self._variance.set_value(self.momentum * self._variance._data
                                 + (1 - self.momentum) * var._data)
        return out

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        """Recursively swap BatchNorm* sublayers for SyncBatchNorm."""
        if isinstance(layer, _BatchNormBase) and not isinstance(
                layer, SyncBatchNorm):
            new = cls(layer.num_features, layer.momentum, layer.epsilon,
                      data_format=layer.data_format)
            if layer.weight is not None:
                new.weight.set_value(layer.weight)
            if layer.bias is not None:
                new.bias.set_value(layer.bias)
            new._mean.set_value(layer._mean)
            new._variance.set_value(layer._variance)
            return new
        for name, sub in list(layer._sub_layers.items()):
            layer._sub_layers[name] = cls.convert_sync_batchnorm(sub)
        return layer


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-05, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = (normalized_shape,)
        self.normalized_shape = tuple(normalized_shape)
        self.epsilon = epsilon
        if weight_attr is False:
            self.weight = None
        else:
            self.weight = self.create_parameter(
                self.normalized_shape, attr=ParamAttr._to_attr(weight_attr),
                default_initializer=Constant(1.0))
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter(
                self.normalized_shape, attr=ParamAttr._to_attr(bias_attr),
                is_bias=True)

    def forward(self, x):
        return F.layer_norm(x, self.normalized_shape, self.weight,
                            self.bias, self.epsilon)

    def extra_repr(self):
        return f"normalized_shape={self.normalized_shape}"


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-05,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self.num_groups = num_groups
        self.num_channels = num_channels
        self.epsilon = epsilon
        self.data_format = data_format
        self.weight = None if weight_attr is False else \
            self.create_parameter((num_channels,),
                                  attr=ParamAttr._to_attr(weight_attr),
                                  default_initializer=Constant(1.0))
        self.bias = None if bias_attr is False else self.create_parameter(
            (num_channels,), attr=ParamAttr._to_attr(bias_attr),
            is_bias=True)

    def forward(self, x):
        return F.group_norm(x, self.num_groups, self.epsilon, self.weight,
                            self.bias, self.data_format)


class _InstanceNormBase(Layer):
    def __init__(self, num_features, epsilon=1e-05, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self.num_features = num_features
        self.epsilon = epsilon
        self.data_format = data_format
        if weight_attr is False or bias_attr is False:
            self.weight = None
            self.bias = None
        else:
            self.weight = self.create_parameter(
                (num_features,), attr=ParamAttr._to_attr(weight_attr),
                default_initializer=Constant(1.0))
            self.bias = self.create_parameter(
                (num_features,), attr=ParamAttr._to_attr(bias_attr),
                is_bias=True)

    def forward(self, x):
        return F.instance_norm(x, weight=self.weight, bias=self.bias,
                               eps=self.epsilon,
                               data_format=self.data_format)


class InstanceNorm1D(_InstanceNormBase):
    pass


class InstanceNorm2D(_InstanceNormBase):
    pass


class InstanceNorm3D(_InstanceNormBase):
    pass


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=0.0001, beta=0.75, k=1.0,
                 data_format="NCHW", name=None):
        super().__init__()
        self.size = size
        self.alpha = alpha
        self.beta = beta
        self.k = k
        self.data_format = data_format

    def forward(self, x):
        return F.local_response_norm(x, self.size, self.alpha, self.beta,
                                     self.k, self.data_format)


class SpectralNorm(Layer):
    """Spectral norm of a weight (power iteration), reference
    spectral_norm_op.cc."""

    def __init__(self, weight_shape, dim=0, power_iters=1, eps=1e-12,
                 dtype="float32"):
        super().__init__()
        self.dim = dim
        self.power_iters = power_iters
        self.eps = eps
        h = weight_shape[dim]
        w = 1
        for i, s in enumerate(weight_shape):
            if i != dim:
                w *= s
        from ..initializer import Normal
        self.weight_u = self.create_parameter(
            (h,), default_initializer=Normal(0, 1))
        self.weight_v = self.create_parameter(
            (w,), default_initializer=Normal(0, 1))
        self.weight_u.stop_gradient = True
        self.weight_v.stop_gradient = True

    def forward(self, weight):
        from ...ops.registry import run_op

        def impl(w, u, v):
            wm = jnp.moveaxis(w, self.dim, 0).reshape(w.shape[self.dim], -1)
            for _ in range(self.power_iters):
                v = wm.T @ u
                v = v / (jnp.linalg.norm(v) + self.eps)
                u = wm @ v
                u = u / (jnp.linalg.norm(u) + self.eps)
            sigma = u @ wm @ v
            return w / sigma
        return run_op("spectral_norm", impl,
                      (weight, self.weight_u, self.weight_v), {})
