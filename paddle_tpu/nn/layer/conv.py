"""Conv layers (python/paddle/nn/layer/conv.py parity)."""
from __future__ import annotations

import numpy as np

from .. import functional as F
from ..initializer import KaimingUniform
from ..param_attr import ParamAttr
from .layers import Layer

__all__ = ["Conv1D", "Conv2D", "Conv3D", "Conv1DTranspose",
           "Conv2DTranspose", "Conv3DTranspose"]


def _ntuple(v, n):
    return (int(v),) * n if isinstance(v, (int, np.integer)) \
        else tuple(int(i) for i in v)


class _ConvNd(Layer):
    ndim = 2
    transpose = False

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, dilation=1, groups=1,
                 padding_mode="zeros", weight_attr=None, bias_attr=None,
                 data_format=None, name=None):
        super().__init__()
        n = self.ndim
        self.in_channels = in_channels
        self.out_channels = out_channels
        self.kernel_size = _ntuple(kernel_size, n)
        self.stride = stride
        self.padding = padding
        self.output_padding = output_padding
        self.dilation = dilation
        self.groups = groups
        self.padding_mode = padding_mode
        self.data_format = data_format
        if self.transpose:
            wshape = (in_channels, out_channels // groups) + self.kernel_size
        else:
            wshape = (out_channels, in_channels // groups) + self.kernel_size
        fan_in = (in_channels // groups) * int(np.prod(self.kernel_size))
        self.weight = self.create_parameter(
            wshape, attr=ParamAttr._to_attr(weight_attr),
            default_initializer=None if weight_attr
            else KaimingUniform(fan_in=fan_in))
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter(
                (out_channels,), attr=ParamAttr._to_attr(bias_attr),
                is_bias=True)

    def extra_repr(self):
        return (f"{self.in_channels}, {self.out_channels}, "
                f"kernel_size={self.kernel_size}, stride={self.stride}")


class Conv1D(_ConvNd):
    ndim = 1

    def forward(self, x):
        return F.conv1d(x, self.weight, self.bias, self.stride, self.padding,
                        self.dilation, self.groups,
                        self.data_format or "NCL")


class Conv2D(_ConvNd):
    ndim = 2

    def forward(self, x):
        return F.conv2d(x, self.weight, self.bias, self.stride, self.padding,
                        self.dilation, self.groups,
                        self.data_format or "NCHW")


class Conv3D(_ConvNd):
    ndim = 3

    def forward(self, x):
        return F.conv3d(x, self.weight, self.bias, self.stride, self.padding,
                        self.dilation, self.groups,
                        self.data_format or "NCDHW")


class Conv1DTranspose(_ConvNd):
    ndim = 1
    transpose = True

    def forward(self, x, output_size=None):
        return F.conv1d_transpose(
            x, self.weight, self.bias, self.stride, self.padding,
            self.output_padding, self.groups, self.dilation, output_size,
            self.data_format or "NCL")


class Conv2DTranspose(_ConvNd):
    ndim = 2
    transpose = True

    def forward(self, x, output_size=None):
        return F.conv2d_transpose(
            x, self.weight, self.bias, self.stride, self.padding,
            self.output_padding, self.groups, self.dilation, output_size,
            self.data_format or "NCHW")


class Conv3DTranspose(_ConvNd):
    ndim = 3
    transpose = True

    def forward(self, x, output_size=None):
        return F.conv3d_transpose(
            x, self.weight, self.bias, self.stride, self.padding,
            self.output_padding, self.groups, self.dilation, output_size,
            self.data_format or "NCDHW")
