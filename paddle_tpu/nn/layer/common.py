"""Common layers (python/paddle/nn/layer/common.py parity)."""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ...framework import Tensor
from .. import functional as F
from ..initializer import Constant, Normal, XavierNormal
from ..param_attr import ParamAttr
from .layers import Layer

__all__ = [
    "PairwiseDistance",
    "Linear", "Dropout", "Dropout2D", "Dropout3D", "AlphaDropout",
    "Embedding", "Flatten", "Pad1D", "Pad2D", "Pad3D", "ZeroPad2D",
    "Upsample", "UpsamplingNearest2D", "UpsamplingBilinear2D",
    "CosineSimilarity", "Bilinear", "Identity", "PixelShuffle",
    "PixelUnshuffle", "ChannelShuffle", "Unfold", "Fold",
]


class Identity(Layer):
    def forward(self, x):
        return x


class Linear(Layer):
    """y = xW + b, weight [in, out] (reference nn/layer/common.py Linear)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.weight = self.create_parameter(
            (in_features, out_features),
            attr=ParamAttr._to_attr(weight_attr),
            default_initializer=None if weight_attr else XavierNormal())
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter(
                (out_features,), attr=ParamAttr._to_attr(bias_attr),
                is_bias=True)

    def forward(self, x):
        return F.linear(x, self.weight, self.bias)

    def extra_repr(self):
        return (f"in_features={self.in_features}, "
                f"out_features={self.out_features}")


class Dropout(Layer):
    def __init__(self, p=0.5, axis=None, mode="upscale_in_train", name=None):
        super().__init__()
        self.p = p
        self.axis = axis
        self.mode = mode

    def forward(self, x):
        return F.dropout(x, self.p, axis=self.axis, training=self.training,
                         mode=self.mode)

    def extra_repr(self):
        return f"p={self.p}"


class Dropout2D(Layer):
    def __init__(self, p=0.5, data_format="NCHW", name=None):
        super().__init__()
        self.p = p
        self.data_format = data_format

    def forward(self, x):
        return F.dropout2d(x, self.p, training=self.training,
                           data_format=self.data_format)


class Dropout3D(Layer):
    def __init__(self, p=0.5, data_format="NCDHW", name=None):
        super().__init__()
        self.p = p
        self.data_format = data_format

    def forward(self, x):
        return F.dropout3d(x, self.p, training=self.training,
                           data_format=self.data_format)


class AlphaDropout(Layer):
    def __init__(self, p=0.5, name=None):
        super().__init__()
        self.p = p

    def forward(self, x):
        return F.alpha_dropout(x, self.p, training=self.training)


class Embedding(Layer):
    def __init__(self, num_embeddings, embedding_dim, padding_idx=None,
                 sparse=False, weight_attr=None, name=None):
        super().__init__()
        self.num_embeddings = num_embeddings
        self.embedding_dim = embedding_dim
        self.padding_idx = padding_idx
        self.weight = self.create_parameter(
            (num_embeddings, embedding_dim),
            attr=ParamAttr._to_attr(weight_attr),
            default_initializer=None if weight_attr else XavierNormal())
        if padding_idx is not None:
            with_pad = np.asarray(self.weight.numpy())
            with_pad[padding_idx] = 0
            self.weight.set_value(with_pad)

    def forward(self, x):
        return F.embedding(x, self.weight, padding_idx=self.padding_idx)

    def extra_repr(self):
        return f"{self.num_embeddings}, {self.embedding_dim}"


class Flatten(Layer):
    def __init__(self, start_axis=1, stop_axis=-1):
        super().__init__()
        self.start_axis = start_axis
        self.stop_axis = stop_axis

    def forward(self, x):
        from ...ops.manipulation import flatten
        return flatten(x, self.start_axis, self.stop_axis)


class _PadNd(Layer):
    data_format_default = "NCHW"

    def __init__(self, padding, mode="constant", value=0.0,
                 data_format=None, name=None):
        super().__init__()
        self.padding = padding
        self.mode = mode
        self.value = value
        self.data_format = data_format or self.data_format_default

    def forward(self, x):
        return F.pad(x, self.padding, mode=self.mode, value=self.value,
                     data_format=self.data_format)


class Pad1D(_PadNd):
    data_format_default = "NCL"


class Pad2D(_PadNd):
    data_format_default = "NCHW"


class Pad3D(_PadNd):
    data_format_default = "NCDHW"


class ZeroPad2D(Pad2D):
    def __init__(self, padding, data_format="NCHW", name=None):
        super().__init__(padding, mode="constant", value=0.0,
                         data_format=data_format)


class Upsample(Layer):
    def __init__(self, size=None, scale_factor=None, mode="nearest",
                 align_corners=False, align_mode=0, data_format="NCHW",
                 name=None):
        super().__init__()
        self.size = size
        self.scale_factor = scale_factor
        self.mode = mode
        self.align_corners = align_corners
        self.align_mode = align_mode
        self.data_format = data_format

    def forward(self, x):
        return F.interpolate(x, self.size, self.scale_factor, self.mode,
                             self.align_corners, self.align_mode,
                             self.data_format)


class UpsamplingNearest2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW",
                 name=None):
        super().__init__(size, scale_factor, "nearest",
                         data_format=data_format)


class UpsamplingBilinear2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW",
                 name=None):
        super().__init__(size, scale_factor, "bilinear", True,
                         data_format=data_format)


class CosineSimilarity(Layer):
    def __init__(self, axis=1, eps=1e-8):
        super().__init__()
        self.axis = axis
        self.eps = eps

    def forward(self, x1, x2):
        return F.cosine_similarity(x1, x2, axis=self.axis, eps=self.eps)


class Bilinear(Layer):
    def __init__(self, in1_features, in2_features, out_features,
                 weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        self.weight = self.create_parameter(
            (out_features, in1_features, in2_features),
            attr=ParamAttr._to_attr(weight_attr))
        self.bias = None if bias_attr is False else self.create_parameter(
            (out_features,), is_bias=True)

    def forward(self, x1, x2):
        return F.bilinear(x1, x2, self.weight, self.bias)


class PixelShuffle(Layer):
    def __init__(self, upscale_factor, data_format="NCHW", name=None):
        super().__init__()
        self.upscale_factor = upscale_factor
        self.data_format = data_format

    def forward(self, x):
        return F.pixel_shuffle(x, self.upscale_factor, self.data_format)


class PixelUnshuffle(Layer):
    def __init__(self, downscale_factor, data_format="NCHW", name=None):
        super().__init__()
        self.downscale_factor = downscale_factor
        self.data_format = data_format

    def forward(self, x):
        return F.pixel_unshuffle(x, self.downscale_factor, self.data_format)


class ChannelShuffle(Layer):
    def __init__(self, groups, data_format="NCHW", name=None):
        super().__init__()
        self.groups = groups
        self.data_format = data_format

    def forward(self, x):
        return F.channel_shuffle(x, self.groups, self.data_format)


class Unfold(Layer):
    def __init__(self, kernel_sizes, strides=1, paddings=0, dilations=1,
                 name=None):
        super().__init__()
        self.args = (kernel_sizes, strides, paddings, dilations)

    def forward(self, x):
        return F.unfold(x, *self.args)


class Fold(Layer):
    def __init__(self, output_sizes, kernel_sizes, strides=1, paddings=0,
                 dilations=1, name=None):
        super().__init__()
        self.output_sizes = output_sizes
        self.args = (kernel_sizes, strides, paddings, dilations)

    def forward(self, x):
        return F.fold(x, self.output_sizes, *self.args)


class PairwiseDistance(Layer):
    """p-norm distance between row pairs (reference nn.PairwiseDistance
    over p_norm_op on x - y)."""

    def __init__(self, p=2.0, epsilon=1e-6, keepdim=False, name=None):
        super().__init__()
        self.p = float(p)
        self.epsilon = float(epsilon)
        self.keepdim = bool(keepdim)

    def forward(self, x, y):
        from ...ops.registry import run_op
        import jax.numpy as jnp
        import math as _math

        def impl(a, b, p=self.p, eps=self.epsilon, kd=self.keepdim):
            # reference adds epsilon to the DIFFERENCE (perturbs the
            # vector, not the summed powers) and supports p=inf
            d = a - b + eps
            if _math.isinf(p):
                return jnp.abs(d).max(axis=-1, keepdims=kd)
            return (jnp.abs(d) ** p).sum(
                axis=-1, keepdims=kd) ** (1.0 / p)
        return run_op("pairwise_distance", impl, (x, y), {})
