"""RNN layers (python/paddle/nn/layer/rnn.py parity).

Reference: rnn_op + cudnn_lstm (/root/reference/paddle/fluid/operators/
rnn_op.h, cudnn_lstm_op.cu.cc). TPU-first: the time loop is a single
lax.scan inside one registered op, so the whole sequence compiles to one
fused XLA while-loop — no per-step dispatch, cuDNN not needed.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ...framework import Tensor, _unwrap
from ...ops.registry import run_op
from .. import functional as F
from ..initializer import Uniform, XavierUniform
from ..param_attr import ParamAttr
from .layers import Layer

__all__ = ["RNNCellBase", "SimpleRNNCell", "LSTMCell", "GRUCell", "RNN", "BiRNN",
           "SimpleRNN", "LSTM", "GRU"]


class RNNCellBase(Layer):
    def get_initial_states(self, batch_ref, shape=None, dtype=None,
                           init_value=0.0, batch_dim_idx=0):
        batch = _unwrap(batch_ref).shape[batch_dim_idx]
        from ...ops.creation import full
        return full((batch, self.hidden_size), init_value,
                    dtype or "float32")


class SimpleRNNCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, activation="tanh",
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.activation = activation
        std = 1.0 / math.sqrt(hidden_size)
        init = Uniform(-std, std)
        self.weight_ih = self.create_parameter(
            (hidden_size, input_size), attr=ParamAttr._to_attr(
                weight_ih_attr), default_initializer=init)
        self.weight_hh = self.create_parameter(
            (hidden_size, hidden_size), attr=ParamAttr._to_attr(
                weight_hh_attr), default_initializer=init)
        self.bias_ih = self.create_parameter(
            (hidden_size,), attr=ParamAttr._to_attr(bias_ih_attr),
            default_initializer=init, is_bias=True)
        self.bias_hh = self.create_parameter(
            (hidden_size,), attr=ParamAttr._to_attr(bias_hh_attr),
            default_initializer=init, is_bias=True)

    @property
    def state_shape(self):
        return (self.hidden_size,)

    def _step(self, x, h, wih, whh, bih, bhh):
        z = x @ wih.T + bih + h @ whh.T + bhh
        return jnp.tanh(z) if self.activation == "tanh" else jax.nn.relu(z)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        out = run_op("simple_rnn_cell", self._step,
                     (inputs, states, self.weight_ih, self.weight_hh,
                      self.bias_ih, self.bias_hh), {})
        return out, out


class LSTMCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 proj_size=None, name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        std = 1.0 / math.sqrt(hidden_size)
        init = Uniform(-std, std)
        self.weight_ih = self.create_parameter(
            (4 * hidden_size, input_size),
            attr=ParamAttr._to_attr(weight_ih_attr),
            default_initializer=init)
        self.weight_hh = self.create_parameter(
            (4 * hidden_size, hidden_size),
            attr=ParamAttr._to_attr(weight_hh_attr),
            default_initializer=init)
        self.bias_ih = self.create_parameter(
            (4 * hidden_size,), attr=ParamAttr._to_attr(bias_ih_attr),
            default_initializer=init, is_bias=True)
        self.bias_hh = self.create_parameter(
            (4 * hidden_size,), attr=ParamAttr._to_attr(bias_hh_attr),
            default_initializer=init, is_bias=True)

    @property
    def state_shape(self):
        return ((self.hidden_size,), (self.hidden_size,))

    @staticmethod
    def _step(x, h, c, wih, whh, bih, bhh):
        gates = x @ wih.T + bih + h @ whh.T + bhh
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
        g = jnp.tanh(g)
        c_new = f * c + i * g
        h_new = o * jnp.tanh(c_new)
        return h_new, c_new

    def forward(self, inputs, states=None):
        if states is None:
            h = self.get_initial_states(inputs)
            c = self.get_initial_states(inputs)
        else:
            h, c = states
        h_new, c_new = run_op(
            "lstm_cell", self._step,
            (inputs, h, c, self.weight_ih, self.weight_hh, self.bias_ih,
             self.bias_hh), {})
        return h_new, (h_new, c_new)


class GRUCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        std = 1.0 / math.sqrt(hidden_size)
        init = Uniform(-std, std)
        self.weight_ih = self.create_parameter(
            (3 * hidden_size, input_size),
            attr=ParamAttr._to_attr(weight_ih_attr),
            default_initializer=init)
        self.weight_hh = self.create_parameter(
            (3 * hidden_size, hidden_size),
            attr=ParamAttr._to_attr(weight_hh_attr),
            default_initializer=init)
        self.bias_ih = self.create_parameter(
            (3 * hidden_size,), attr=ParamAttr._to_attr(bias_ih_attr),
            default_initializer=init, is_bias=True)
        self.bias_hh = self.create_parameter(
            (3 * hidden_size,), attr=ParamAttr._to_attr(bias_hh_attr),
            default_initializer=init, is_bias=True)

    @property
    def state_shape(self):
        return (self.hidden_size,)

    @staticmethod
    def _step(x, h, wih, whh, bih, bhh):
        gi = x @ wih.T + bih
        gh = h @ whh.T + bhh
        ri, zi, ni = jnp.split(gi, 3, axis=-1)
        rh, zh, nh = jnp.split(gh, 3, axis=-1)
        r = jax.nn.sigmoid(ri + rh)
        z = jax.nn.sigmoid(zi + zh)
        n = jnp.tanh(ni + r * nh)
        return (1 - z) * n + z * h

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        h = run_op("gru_cell", self._step,
                   (inputs, states, self.weight_ih, self.weight_hh,
                    self.bias_ih, self.bias_hh), {})
        return h, h


class RNN(Layer):
    """Runs a cell over time with lax.scan (recurrent_op analogue)."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        cell = self.cell
        is_lstm = isinstance(cell, LSTMCell)
        if initial_states is None:
            ref = inputs if not self.time_major else inputs
            batch_axis = 1 if self.time_major else 0
            from ...ops.creation import zeros
            b = _unwrap(inputs).shape[batch_axis]
            h0 = zeros((b, cell.hidden_size))
            initial_states = (h0, zeros((b, cell.hidden_size))) if is_lstm \
                else h0

        params = (cell.weight_ih, cell.weight_hh, cell.bias_ih, cell.bias_hh)

        time_major = self.time_major
        reverse = self.is_reverse
        step = cell._step

        def impl(x, *args):
            if is_lstm:
                h0, c0 = args[0], args[1]
                wih, whh, bih, bhh = args[2:6]
            else:
                h0 = args[0]
                wih, whh, bih, bhh = args[1:5]
            xs = x if time_major else jnp.swapaxes(x, 0, 1)
            if reverse:
                xs = jnp.flip(xs, 0)

            if is_lstm:
                def body(carry, xt):
                    h, c = carry
                    h2, c2 = step(xt, h, c, wih, whh, bih, bhh)
                    return (h2, c2), h2
                (hT, cT), outs = jax.lax.scan(body, (h0, c0), xs)
                final = (hT, cT)
            else:
                def body(h, xt):
                    h2 = step(xt, h, wih, whh, bih, bhh)
                    return h2, h2
                hT, outs = jax.lax.scan(body, h0, xs)
                final = (hT,)
            if reverse:
                outs = jnp.flip(outs, 0)
            if not time_major:
                outs = jnp.swapaxes(outs, 0, 1)
            return (outs,) + final

        if is_lstm:
            h0, c0 = initial_states
            res = run_op("rnn_scan", impl, (inputs, h0, c0) + params, {})
            outs, hT, cT = res
            return outs, (hT, cT)
        res = run_op("rnn_scan", impl, (inputs, initial_states) + params, {})
        outs, hT = res
        return outs, hT


class BiRNN(Layer):
    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.rnn_fw = RNN(cell_fw, False, time_major)
        self.rnn_bw = RNN(cell_bw, True, time_major)

    def forward(self, inputs, initial_states=None, sequence_length=None):
        states = initial_states or (None, None)
        out_f, st_f = self.rnn_fw(inputs, states[0])
        out_b, st_b = self.rnn_bw(inputs, states[1])
        from ...ops.manipulation import concat
        return concat([out_f, out_b], axis=-1), (st_f, st_b)


class _RNNBase(Layer):
    cell_cls = None
    n_states = 1

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 activation=None, weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.time_major = time_major
        self.dropout = dropout
        self.bidirectional = direction in ("bidirect", "bidirectional")
        num_dir = 2 if self.bidirectional else 1
        self.num_directions = num_dir

        kwargs = {}
        if activation is not None and self.cell_cls is SimpleRNNCell:
            kwargs["activation"] = activation

        from .container import LayerList
        self._cells = LayerList()
        for layer in range(num_layers):
            in_size = input_size if layer == 0 else hidden_size * num_dir
            for d in range(num_dir):
                self._cells.append(self.cell_cls(
                    in_size, hidden_size, weight_ih_attr=weight_ih_attr,
                    weight_hh_attr=weight_hh_attr, bias_ih_attr=bias_ih_attr,
                    bias_hh_attr=bias_hh_attr, **kwargs))

    def forward(self, inputs, initial_states=None, sequence_length=None):
        from ...ops.manipulation import concat, stack
        num_dir = self.num_directions
        batch_axis = 1 if self.time_major else 0
        b = _unwrap(inputs).shape[batch_axis]
        x = inputs
        final_h, final_c = [], []
        for layer in range(self.num_layers):
            outs = []
            for d in range(num_dir):
                cell = self._cells[layer * num_dir + d]
                rnn = RNN(cell, is_reverse=(d == 1),
                          time_major=self.time_major)
                init = None
                if initial_states is not None:
                    idx = layer * num_dir + d
                    if self.n_states == 2:
                        h0s, c0s = initial_states
                        init = (h0s[idx], c0s[idx])
                    else:
                        init = initial_states[idx]
                out, st = rnn(x, init)
                outs.append(out)
                if self.n_states == 2:
                    final_h.append(st[0])
                    final_c.append(st[1])
                else:
                    final_h.append(st)
            x = outs[0] if num_dir == 1 else concat(outs, axis=-1)
            if self.dropout > 0 and layer < self.num_layers - 1:
                x = F.dropout(x, self.dropout, training=self.training)
        if self.n_states == 2:
            return x, (stack(final_h, axis=0), stack(final_c, axis=0))
        return x, stack(final_h, axis=0)


class SimpleRNN(_RNNBase):
    cell_cls = SimpleRNNCell


class LSTM(_RNNBase):
    cell_cls = LSTMCell
    n_states = 2


class GRU(_RNNBase):
    cell_cls = GRUCell
