"""Layer: the module base class.

Reference: python/paddle/fluid/dygraph/layers.py:76 (`Layer`) — parameters/
sublayers/buffers registries, hooks, state_dict, train/eval. TPU additions:
`functional_call` (run forward with an explicit param pytree — the bridge to
jit/pjit compiled steps) and pytree registration so whole Layers can cross
jax transforms.
"""
from __future__ import annotations

import collections
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from ...core import dtypes as _dtypes
from ...core.enforce import InvalidArgumentError
from ...framework import Parameter, Tensor
from ..initializer import Initializer, XavierNormal

__all__ = ["Layer"]


class HookRemoveHelper:
    _next_id = 0

    def __init__(self, hooks: Dict[int, Callable]):
        self._hooks = hooks
        self._id = HookRemoveHelper._next_id
        HookRemoveHelper._next_id += 1

    def remove(self):
        self._hooks.pop(self._id, None)


class Layer:
    def __init__(self, name_scope=None, dtype="float32"):
        self.training = True
        self._dtype = _dtypes.convert_dtype(dtype)
        self._parameters: Dict[str, Parameter] = collections.OrderedDict()
        self._sub_layers: Dict[str, "Layer"] = collections.OrderedDict()
        self._buffers: Dict[str, Tensor] = collections.OrderedDict()
        self._non_persistable_buffer_names = set()
        self._forward_pre_hooks: Dict[int, Callable] = collections.OrderedDict()
        self._forward_post_hooks: Dict[int, Callable] = collections.OrderedDict()
        self._name_scope = name_scope or self.__class__.__name__.lower()

    # -- forward ------------------------------------------------------------
    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    def __call__(self, *inputs, **kwargs):
        for hook in list(self._forward_pre_hooks.values()):
            res = hook(self, inputs)
            if res is not None:
                inputs = res if isinstance(res, tuple) else (res,)
        out = self.forward(*inputs, **kwargs)
        for hook in list(self._forward_post_hooks.values()):
            res = hook(self, inputs, out)
            if res is not None:
                out = res
        return out

    def register_forward_pre_hook(self, hook) -> HookRemoveHelper:
        h = HookRemoveHelper(self._forward_pre_hooks)
        self._forward_pre_hooks[h._id] = hook
        return h

    def register_forward_post_hook(self, hook) -> HookRemoveHelper:
        h = HookRemoveHelper(self._forward_post_hooks)
        self._forward_post_hooks[h._id] = hook
        return h

    # -- registration magic --------------------------------------------------
    def __setattr__(self, name, value):
        params = self.__dict__.get("_parameters")
        layers = self.__dict__.get("_sub_layers")
        buffers = self.__dict__.get("_buffers")
        if isinstance(value, Parameter):
            if params is None:
                raise InvalidArgumentError(
                    "call super().__init__() before assigning parameters")
            for d in (layers, buffers):
                if d is not None:
                    d.pop(name, None)
            params[name] = value
            self.__dict__.pop(name, None)
        elif isinstance(value, Layer):
            if layers is None:
                raise InvalidArgumentError(
                    "call super().__init__() before assigning sublayers")
            for d in (params, buffers):
                if d is not None:
                    d.pop(name, None)
            layers[name] = value
            self.__dict__.pop(name, None)
        elif buffers is not None and name in buffers:
            if value is None:
                buffers[name] = value
            elif isinstance(value, Tensor):
                buffers[name] = value
            else:
                buffers[name].set_value(value)
        else:
            if params is not None:
                params.pop(name, None)
            if layers is not None:
                layers.pop(name, None)
            object.__setattr__(self, name, value)

    def __getattr__(self, name):
        # only called when normal lookup fails
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                return d[name]
        raise AttributeError(
            f"'{type(self).__name__}' object has no attribute '{name}'")

    def __delattr__(self, name):
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                del d[name]
                return
        object.__delattr__(self, name)

    def __dir__(self):
        return (list(super().__dir__()) + list(self._parameters)
                + list(self._sub_layers) + list(self._buffers))

    # -- explicit registration ----------------------------------------------
    def add_parameter(self, name: str, parameter: Optional[Parameter]):
        if parameter is not None and not isinstance(parameter, Parameter):
            raise InvalidArgumentError(
                f"add_parameter expects Parameter, got {type(parameter)}")
        self._parameters[name] = parameter
        return parameter

    def add_sublayer(self, name: str, sublayer: "Layer"):
        self._sub_layers[str(name)] = sublayer
        return sublayer

    def register_buffer(self, name: str, tensor: Optional[Tensor],
                        persistable: bool = True):
        self._buffers[name] = tensor
        if not persistable:
            self._non_persistable_buffer_names.add(name)
        return tensor

    def create_parameter(self, shape, attr=None, dtype=None,
                         is_bias=False, default_initializer=None):
        """ParamAttr-lite parameter factory (fluid layer_helper analogue)."""
        from ..param_attr import ParamAttr
        dtype = _dtypes.convert_dtype(dtype) if dtype else self._dtype
        init = default_initializer
        name = None
        trainable = True
        if isinstance(attr, ParamAttr):
            init = attr.initializer or init
            name = attr.name
            trainable = attr.trainable
        elif attr is False and is_bias:
            return None
        # precedence (reference set_global_initializer): an explicit
        # ParamAttr initializer wins; otherwise a global default (when
        # set) overrides the layer's built-in default_initializer
        attr_init = isinstance(attr, ParamAttr) and \
            attr.initializer is not None
        if not attr_init:
            from .. import initializer as _init_mod
            g = _init_mod._global_default(is_bias)
            if g is not None:
                init = g
        if init is None:
            from ..initializer import Constant
            init = Constant(0.0) if is_bias else XavierNormal()
        data = init(tuple(int(s) for s in shape), dtype)
        p = Parameter(data, name=name, trainable=trainable)
        return p

    # -- traversal ----------------------------------------------------------
    def named_parameters(self, prefix="", include_sublayers=True
                         ) -> Iterator[Tuple[str, Parameter]]:
        seen = set()
        for name, p in self._parameters.items():
            if p is not None and id(p) not in seen:
                seen.add(id(p))
                yield (prefix + name if not prefix else
                       f"{prefix}.{name}"), p
        if include_sublayers:
            for lname, layer in self._sub_layers.items():
                if layer is None:
                    continue
                sub_prefix = f"{prefix}.{lname}" if prefix else lname
                for n, p in layer.named_parameters(sub_prefix):
                    if id(p) not in seen:
                        seen.add(id(p))
                        yield n, p

    def parameters(self, include_sublayers=True) -> List[Parameter]:
        return [p for _, p in self.named_parameters(
            include_sublayers=include_sublayers)]

    def named_buffers(self, prefix="", include_sublayers=True):
        for name, b in self._buffers.items():
            if b is not None:
                yield (f"{prefix}.{name}" if prefix else name), b
        if include_sublayers:
            for lname, layer in self._sub_layers.items():
                if layer is None:
                    continue
                sub_prefix = f"{prefix}.{lname}" if prefix else lname
                yield from layer.named_buffers(sub_prefix)

    def buffers(self, include_sublayers=True):
        return [b for _, b in self.named_buffers(
            include_sublayers=include_sublayers)]

    def children(self) -> Iterator["Layer"]:
        for layer in self._sub_layers.values():
            if layer is not None:
                yield layer

    def named_children(self):
        for name, layer in self._sub_layers.items():
            if layer is not None:
                yield name, layer

    def sublayers(self, include_self=False) -> List["Layer"]:
        out = [self] if include_self else []
        for layer in self.children():
            out.append(layer)
            out.extend(layer.sublayers())
        return out

    def named_sublayers(self, prefix="", include_self=False):
        if include_self:
            yield prefix, self
        for name, layer in self.named_children():
            sub_prefix = f"{prefix}.{name}" if prefix else name
            yield sub_prefix, layer
            yield from layer.named_sublayers(sub_prefix)

    def apply(self, fn) -> "Layer":
        for layer in self.children():
            layer.apply(fn)
        fn(self)
        return self

    # -- modes --------------------------------------------------------------
    def train(self):
        self.training = True
        for layer in self.children():
            layer.train()
        return self

    def eval(self):
        self.training = False
        for layer in self.children():
            layer.eval()
        return self

    # -- state dict ----------------------------------------------------------
    def state_dict(self, destination=None, include_sublayers=True,
                   structured_name_prefix="", use_hook=True):
        dest = destination if destination is not None else \
            collections.OrderedDict()
        for name, p in self._parameters.items():
            if p is not None:
                dest[structured_name_prefix + name] = p
        for name, b in self._buffers.items():
            if b is not None and name not in \
                    self._non_persistable_buffer_names:
                dest[structured_name_prefix + name] = b
        if include_sublayers:
            for lname, layer in self._sub_layers.items():
                if layer is not None:
                    layer.state_dict(
                        dest, True, structured_name_prefix + lname + ".")
        return dest

    def set_state_dict(self, state_dict, use_structured_name=True):
        own = self.state_dict()
        missing, unexpected = [], []
        for k, v in state_dict.items():
            if k in own:
                arr = v._data if isinstance(v, Tensor) else jnp.asarray(
                    np.asarray(v))
                own[k].set_value(arr)
            else:
                unexpected.append(k)
        for k in own:
            if k not in state_dict:
                missing.append(k)
        return missing, unexpected

    set_dict = set_state_dict
    load_dict = set_state_dict

    # -- dtype / device movement ---------------------------------------------
    def _transform(self, fn):
        def visit(layer):
            for k, p in layer._parameters.items():
                if p is not None:
                    p._data = fn(p._data)
            for k, b in layer._buffers.items():
                if b is not None:
                    b._data = fn(b._data)
        self.apply(visit)
        return self

    def to(self, device=None, dtype=None, blocking=None):
        if dtype is not None:
            d = _dtypes.convert_dtype(dtype)
            self._transform(lambda a: a.astype(d)
                            if jnp.issubdtype(a.dtype, jnp.floating) else a)
        return self

    def astype(self, dtype):
        return self.to(dtype=dtype)

    def float(self):
        return self.to(dtype="float32")

    def half(self):
        return self.to(dtype="float16")

    def bfloat16(self):
        return self.to(dtype="bfloat16")

    # -- functional bridge (the TPU-first addition) ---------------------------
    def functional_call(self, params: Dict[str, Any], *inputs, **kwargs):
        """Run forward with parameters/buffers taken from `params` (a flat
        state-dict-keyed mapping of jax arrays). This is how compiled
        (jit/pjit) training steps call a Layer: parameters become explicit
        function inputs so XLA sees one pure program."""
        saved = {}
        own = self.state_dict()
        try:
            for k, arr in params.items():
                if k in own:
                    saved[k] = own[k]._data
                    own[k]._data = arr if not isinstance(arr, Tensor) \
                        else arr._data
            return self(*inputs, **kwargs)
        finally:
            for k, a in saved.items():
                own[k]._data = a

    def raw_state(self) -> Dict[str, Any]:
        """state_dict as raw jax arrays (pytree leaf form for jit)."""
        return {k: v._data for k, v in self.state_dict().items()}

    def clear_gradients(self):
        for p in self.parameters():
            p.clear_grad()

    def __repr__(self):
        extra = self.extra_repr()
        lines = []
        for name, layer in self._sub_layers.items():
            sub = repr(layer).split("\n")
            sub = [sub[0]] + ["  " + s for s in sub[1:]]
            lines.append(f"  ({name}): " + "\n".join(sub))
        main = f"{type(self).__name__}({extra}"
        if lines:
            return main + "\n" + "\n".join(lines) + "\n)"
        return main + ")"

    def extra_repr(self):
        return ""


def _layer_len(self):
    return len(self._sub_layers)


Layer.__len__ = _layer_len
