"""ScannedStack: L structurally-identical blocks as ONE lax.scan.

TPU-first depth scaling (no reference equivalent — its Program unrolls
ops per layer): XLA compiles an unrolled L-block transformer as L
copies of the same HLO, so compile time and program size grow linearly
in depth — the practical blocker for 10B-class single-program compiles.
Stacking each block parameter to [L, *shape] and scanning one block
body makes both O(1) in depth; per-layer weights stream through the
same compiled body.

Used by models.ernie.ErnieScannedEncoder and models.gpt (GPTConfig
scan_layers). Parameters keep the unrolled count/shapes, just stacked;
sharding specs shift right past the stack axis. The whole scan rides
run_op so the eager tape differentiates it as one node; static Program
capture records it as a single (unregistered) op and to_bytes rejects
it loudly at save — serialize the unrolled form instead.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...framework import Parameter, Tensor
from .layers import Layer

__all__ = ["ScannedStack"]


class ScannedStack(Layer):
    def __init__(self, layers, op_name: str = "scanned_stack"):
        """`layers`: constructed, structurally-identical blocks whose
        forward is `block(x, *extra)` with x the carried tensor and
        `extra` per-call (non-scanned) side inputs."""
        super().__init__()
        assert len(layers) >= 1
        from jax.sharding import PartitionSpec as P
        self.L = len(layers)
        self._op_name = op_name
        tmpl = layers[0]
        # the template executes the scan body; deliberately NOT a
        # registered sublayer (its own values never train — the stacked
        # tensors are the real parameters)
        object.__setattr__(self, "_template", tmpl)
        buffers = [k for k, t in tmpl.state_dict().items()
                   if t.stop_gradient]
        if buffers:
            # the scan body discards functionalize's new_state, so a
            # buffer-carrying block (BatchNorm running stats) would
            # train fine but serve stale statistics forever — refuse
            # loudly instead of silently freezing them
            raise ValueError(
                f"ScannedStack blocks must be buffer-free; template "
                f"carries {buffers} — buffer updates would be dropped "
                "by the scan (use the unrolled form, or normalize with "
                "buffer-less layers like LayerNorm)")
        self._names = list(tmpl.state_dict().keys())
        self._mangled = {n: "stk__" + n.replace(".", "__")
                         for n in self._names}
        for n in self._names:
            per = [l.state_dict()[n] for l in layers]
            stacked = jnp.stack([t._data for t in per])
            p = Parameter(stacked, name=self._mangled[n])
            p.stop_gradient = per[0].stop_gradient
            spec = getattr(per[0], "sharding_spec", None)
            if spec is not None:
                p.sharding_spec = P(*((None,) + tuple(spec)))
            setattr(self, self._mangled[n], p)

    def load_from_layers(self, layer_list):
        """Import an unrolled stack's (iterable of blocks) weights."""
        layer_list = list(layer_list)
        assert len(layer_list) == self.L
        for n in self._names:
            stacked = jnp.stack(
                [lyr.state_dict()[n]._data for lyr in layer_list])
            getattr(self, self._mangled[n])._data = stacked

    def export_to_layers(self, layer_list):
        """Write the stacks back into an unrolled stack's blocks (the
        inverse of load_from_layers — checkpoint interop both ways)."""
        layer_list = list(layer_list)
        assert len(layer_list) == self.L
        for n in self._names:
            stacked = getattr(self, self._mangled[n])._data
            for i, lyr in enumerate(layer_list):
                lyr.state_dict()[n]._data = stacked[i]

    def forward(self, x, *extra):
        from ...core.generator import next_key
        from ...jit.api import functionalize
        from ...ops.registry import no_static_capture, run_op
        tmpl = self._template
        for lyr in tmpl.sublayers(include_self=True):
            lyr.training = self.training
        pure = functionalize(tmpl.forward, tmpl)
        names = self._names
        key0 = next_key()  # folded per layer inside the scan
        L = self.L
        # side inputs ride as real op inputs (never closures): static
        # capture then sees plain tensor slots; trailing Nones drop so
        # the template's own defaults apply
        extra = list(extra)
        while extra and extra[-1] is None:
            extra.pop()
        n_extra = len(extra)

        def scan_body(x_arr, extra_arrs, flat):
            stacks = dict(zip(names, flat))

            def body(h, xs):
                layer_state, i = xs
                out, _ = pure(layer_state, jax.random.fold_in(key0, i),
                              h, *extra_arrs)
                return out, None

            with no_static_capture():
                out, _ = jax.lax.scan(
                    body, x_arr, (stacks, jnp.arange(L)))
            return out

        flat = [getattr(self, self._mangled[n]) for n in names]

        def op_fn(x_arr, *rest):
            return scan_body(x_arr, rest[:n_extra], rest[n_extra:])

        return run_op(self._op_name, op_fn, (x, *extra, *flat), {})
