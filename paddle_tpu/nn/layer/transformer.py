"""Transformer layers (python/paddle/nn/layer/transformer.py parity).

Reference: MultiHeadAttention / TransformerEncoder(Layer) / Decoder at
/root/reference/python/paddle/nn/layer/transformer.py — there, attention is
composed from separate matmul/softmax ops. Here the core product is
F.scaled_dot_product_attention / F.flash_attention (fused, O(seq) memory),
and the layers carry sharding metadata hooks used by the tensor-parallel
variants in paddle_tpu.distributed.parallel_layers.
"""
from __future__ import annotations

import collections

import jax.numpy as jnp

from .. import functional as F
from ..param_attr import ParamAttr
from .common import Dropout, Linear
from .container import LayerList
from .layers import Layer
from .norm import LayerNorm

__all__ = ["MultiHeadAttention", "TransformerEncoderLayer",
           "TransformerEncoder", "TransformerDecoderLayer",
           "TransformerDecoder", "Transformer"]


class MultiHeadAttention(Layer):
    Cache = collections.namedtuple("Cache", ["k", "v"])
    StaticCache = collections.namedtuple("StaticCache", ["k", "v"])

    def __init__(self, embed_dim, num_heads, dropout=0.0, kdim=None,
                 vdim=None, need_weights=False, weight_attr=None,
                 bias_attr=None, use_flash=True):
        super().__init__()
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        assert self.head_dim * num_heads == embed_dim
        self.kdim = kdim or embed_dim
        self.vdim = vdim or embed_dim
        self.need_weights = need_weights
        self.dropout = dropout
        self.use_flash = use_flash
        self.q_proj = Linear(embed_dim, embed_dim, weight_attr, bias_attr)
        self.k_proj = Linear(self.kdim, embed_dim, weight_attr, bias_attr)
        self.v_proj = Linear(self.vdim, embed_dim, weight_attr, bias_attr)
        self.out_proj = Linear(embed_dim, embed_dim, weight_attr, bias_attr)

    def _split_heads(self, x):
        b, s, _ = x.shape
        return x.reshape([b, s, self.num_heads, self.head_dim])

    def forward(self, query, key=None, value=None, attn_mask=None,
                cache=None):
        key = query if key is None else key
        value = query if value is None else value
        q = self._split_heads(self.q_proj(query))
        if isinstance(cache, self.StaticCache):
            k, v = cache.k, cache.v
        else:
            k = self._split_heads(self.k_proj(key))
            v = self._split_heads(self.v_proj(value))
            if isinstance(cache, self.Cache):
                from ...ops.manipulation import concat
                k = concat([cache.k, k], axis=1)
                v = concat([cache.v, v], axis=1)
                cache = self.Cache(k, v)
        # reference MultiHeadAttention applies dropout to the attention
        # WEIGHTS (python/paddle/nn/layer/transformer.py: weights =
        # F.dropout(softmax(product))), not to the projected output
        if attn_mask is None and self.use_flash and not self.need_weights:
            out = F.flash_attention(q, k, v, dropout=self.dropout,
                                    training=self.training)
        else:
            out = F.scaled_dot_product_attention(
                q, k, v, attn_mask=attn_mask, dropout_p=self.dropout,
                training=self.training)
        b, s = out.shape[0], out.shape[1]
        out = out.reshape([b, s, self.embed_dim])
        out = self.out_proj(out)
        if isinstance(cache, self.Cache):
            return out, cache
        return out

    def gen_cache(self, key, value=None, type=None):
        if type == MultiHeadAttention.StaticCache:
            k = self._split_heads(self.k_proj(key))
            v = self._split_heads(self.v_proj(value if value is not None
                                              else key))
            return self.StaticCache(k, v)
        from ...ops.creation import zeros
        b = key.shape[0]
        k = zeros([b, 0, self.num_heads, self.head_dim])
        v = zeros([b, 0, self.num_heads, self.head_dim])
        return self.Cache(k, v)


class TransformerEncoderLayer(Layer):
    def __init__(self, d_model, nhead, dim_feedforward, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None):
        super().__init__()
        self.normalize_before = normalize_before
        self.self_attn = MultiHeadAttention(
            d_model, nhead, attn_dropout if attn_dropout is not None
            else dropout, weight_attr=weight_attr, bias_attr=bias_attr)
        self.linear1 = Linear(d_model, dim_feedforward, weight_attr,
                              bias_attr)
        self.linear2 = Linear(dim_feedforward, d_model, weight_attr,
                              bias_attr)
        self.norm1 = LayerNorm(d_model)
        self.norm2 = LayerNorm(d_model)
        self.dropout1 = Dropout(dropout)
        self.dropout2 = Dropout(dropout)
        self.act_dropout = Dropout(act_dropout if act_dropout is not None
                                   else dropout)
        self.activation = activation

    def _act(self, x):
        return getattr(F, self.activation)(x)

    def forward(self, src, src_mask=None, cache=None):
        residual = src
        if self.normalize_before:
            src = self.norm1(src)
        if cache is None:
            src = self.self_attn(src, src, src, src_mask)
        else:
            src, cache = self.self_attn(src, src, src, src_mask, cache)
        src = residual + self.dropout1(src)
        if not self.normalize_before:
            src = self.norm1(src)
        residual = src
        if self.normalize_before:
            src = self.norm2(src)
        src = self.linear2(self.act_dropout(self._act(self.linear1(src))))
        src = residual + self.dropout2(src)
        if not self.normalize_before:
            src = self.norm2(src)
        return src if cache is None else (src, cache)

    def gen_cache(self, src):
        return self.self_attn.gen_cache(src)


class TransformerEncoder(Layer):
    def __init__(self, encoder_layer, num_layers, norm=None):
        super().__init__()
        import copy
        self.layers = LayerList(
            [encoder_layer if i == 0 else _clone_layer(encoder_layer)
             for i in range(num_layers)])
        self.num_layers = num_layers
        self.norm = norm

    def forward(self, src, src_mask=None, cache=None):
        output = src
        new_caches = []
        for i, layer in enumerate(self.layers):
            if cache is None:
                output = layer(output, src_mask)
            else:
                output, c = layer(output, src_mask, cache[i])
                new_caches.append(c)
        if self.norm is not None:
            output = self.norm(output)
        return output if cache is None else (output, new_caches)

    def gen_cache(self, src):
        return [layer.gen_cache(src) for layer in self.layers]


class TransformerDecoderLayer(Layer):
    def __init__(self, d_model, nhead, dim_feedforward, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None):
        super().__init__()
        self.normalize_before = normalize_before
        ad = attn_dropout if attn_dropout is not None else dropout
        self.self_attn = MultiHeadAttention(d_model, nhead, ad,
                                            weight_attr=weight_attr,
                                            bias_attr=bias_attr)
        self.cross_attn = MultiHeadAttention(d_model, nhead, ad,
                                             weight_attr=weight_attr,
                                             bias_attr=bias_attr)
        self.linear1 = Linear(d_model, dim_feedforward, weight_attr,
                              bias_attr)
        self.linear2 = Linear(dim_feedforward, d_model, weight_attr,
                              bias_attr)
        self.norm1 = LayerNorm(d_model)
        self.norm2 = LayerNorm(d_model)
        self.norm3 = LayerNorm(d_model)
        self.dropout1 = Dropout(dropout)
        self.dropout2 = Dropout(dropout)
        self.dropout3 = Dropout(dropout)
        self.act_dropout = Dropout(act_dropout if act_dropout is not None
                                   else dropout)
        self.activation = activation

    def _act(self, x):
        return getattr(F, self.activation)(x)

    def forward(self, tgt, memory, tgt_mask=None, memory_mask=None,
                cache=None):
        residual = tgt
        if self.normalize_before:
            tgt = self.norm1(tgt)
        if cache is None:
            tgt = self.self_attn(tgt, tgt, tgt, tgt_mask)
        else:
            tgt, new_inc = self.self_attn(tgt, tgt, tgt, tgt_mask, cache[0])
        tgt = residual + self.dropout1(tgt)
        if not self.normalize_before:
            tgt = self.norm1(tgt)
        residual = tgt
        if self.normalize_before:
            tgt = self.norm2(tgt)
        if cache is None:
            tgt = self.cross_attn(tgt, memory, memory, memory_mask)
        else:
            tgt = self.cross_attn(tgt, memory, memory, memory_mask,
                                  cache[1])
            if isinstance(tgt, tuple):
                tgt = tgt[0]
        tgt = residual + self.dropout2(tgt)
        if not self.normalize_before:
            tgt = self.norm2(tgt)
        residual = tgt
        if self.normalize_before:
            tgt = self.norm3(tgt)
        tgt = self.linear2(self.act_dropout(self._act(self.linear1(tgt))))
        tgt = residual + self.dropout3(tgt)
        if not self.normalize_before:
            tgt = self.norm3(tgt)
        if cache is None:
            return tgt
        return tgt, (new_inc, cache[1])

    def gen_cache(self, memory):
        inc = self.self_attn.gen_cache(memory)
        sta = self.cross_attn.gen_cache(memory, memory,
                                        MultiHeadAttention.StaticCache)
        return inc, sta


class TransformerDecoder(Layer):
    def __init__(self, decoder_layer, num_layers, norm=None):
        super().__init__()
        self.layers = LayerList(
            [decoder_layer if i == 0 else _clone_layer(decoder_layer)
             for i in range(num_layers)])
        self.num_layers = num_layers
        self.norm = norm

    def forward(self, tgt, memory, tgt_mask=None, memory_mask=None,
                cache=None):
        output = tgt
        new_caches = []
        for i, layer in enumerate(self.layers):
            if cache is None:
                output = layer(output, memory, tgt_mask, memory_mask)
            else:
                output, c = layer(output, memory, tgt_mask, memory_mask,
                                  cache[i])
                new_caches.append(c)
        if self.norm is not None:
            output = self.norm(output)
        return output if cache is None else (output, new_caches)

    def gen_cache(self, memory, do_zip=False):
        caches = [layer.gen_cache(memory) for layer in self.layers]
        if do_zip:
            return list(zip(*caches))
        return caches


def _clone_layer(layer):
    """Fresh re-construction of an encoder/decoder layer (new params)."""
    cls = type(layer)
    if isinstance(layer, TransformerEncoderLayer):
        new = cls(layer.norm1.normalized_shape[0],
                  layer.self_attn.num_heads,
                  layer.linear1.out_features,
                  dropout=layer.dropout1.p,
                  activation=layer.activation,
                  normalize_before=layer.normalize_before)
        return new
    if isinstance(layer, TransformerDecoderLayer):
        new = cls(layer.norm1.normalized_shape[0],
                  layer.self_attn.num_heads,
                  layer.linear1.out_features,
                  dropout=layer.dropout1.p,
                  activation=layer.activation,
                  normalize_before=layer.normalize_before)
        return new
    import copy
    return copy.deepcopy(layer)


class Transformer(Layer):
    def __init__(self, d_model=512, nhead=8, num_encoder_layers=6,
                 num_decoder_layers=6, dim_feedforward=2048, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None,
                 custom_encoder=None, custom_decoder=None):
        super().__init__()
        if custom_encoder is not None:
            self.encoder = custom_encoder
        else:
            enc_layer = TransformerEncoderLayer(
                d_model, nhead, dim_feedforward, dropout, activation,
                attn_dropout, act_dropout, normalize_before, weight_attr,
                bias_attr)
            norm = LayerNorm(d_model) if normalize_before else None
            self.encoder = TransformerEncoder(enc_layer, num_encoder_layers,
                                              norm)
        if custom_decoder is not None:
            self.decoder = custom_decoder
        else:
            dec_layer = TransformerDecoderLayer(
                d_model, nhead, dim_feedforward, dropout, activation,
                attn_dropout, act_dropout, normalize_before, weight_attr,
                bias_attr)
            norm = LayerNorm(d_model) if normalize_before else None
            self.decoder = TransformerDecoder(dec_layer, num_decoder_layers,
                                              norm)
        self.d_model = d_model
        self.nhead = nhead

    def forward(self, src, tgt, src_mask=None, tgt_mask=None,
                memory_mask=None):
        memory = self.encoder(src, src_mask)
        return self.decoder(tgt, memory, tgt_mask, memory_mask)

    @staticmethod
    def generate_square_subsequent_mask(length):
        from ...framework import Tensor
        m = jnp.where(
            jnp.tril(jnp.ones((length, length), bool)), 0.0, -jnp.inf)
        return Tensor(m)
