"""paddle_tpu.nn — layers, functionals, initializers.

Parity with python/paddle/nn (~90 Layer classes, SURVEY.md §2.6).
"""
from . import functional  # noqa: F401
from . import initializer  # noqa: F401
from . import extension  # noqa: F401
from . import vision  # noqa: F401
from . import weight_norm_hook  # noqa: F401
from .decode import BeamSearchDecoder, Decoder, dynamic_decode  # noqa: F401
from .layer import *  # noqa: F401,F403
from .layer import Layer  # noqa: F401
from .param_attr import ParamAttr  # noqa: F401
from .utils import weight_norm, remove_weight_norm, spectral_norm  # noqa: F401
from .clip import ClipGradByValue, ClipGradByNorm, ClipGradByGlobalNorm  # noqa: F401
