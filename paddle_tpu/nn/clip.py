"""Gradient clipping (python/paddle/fluid/clip.py:152-345 parity).

ClipGradBy{Value,Norm,GlobalNorm} operate on (param, grad) lists — wired
into Optimizer.step, same contract as the reference's optimizer grad_clip.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..framework import Tensor

__all__ = ["ClipGradByValue", "ClipGradByNorm", "ClipGradByGlobalNorm",
           "clip_grad_norm_", "clip_grad_value_"]


class ClipGradBase:
    def __call__(self, params_grads):
        raise NotImplementedError


class ClipGradByValue(ClipGradBase):
    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -self.max

    def __call__(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None:
                out.append((p, g))
                continue
            out.append((p, Tensor(jnp.clip(g._data, self.min, self.max))))
        return out


class ClipGradByNorm(ClipGradBase):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def __call__(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None:
                out.append((p, g))
                continue
            norm = jnp.sqrt(jnp.sum(jnp.square(g._data)))
            scale = jnp.minimum(self.clip_norm / jnp.maximum(norm, 1e-12),
                                1.0)
            out.append((p, Tensor(g._data * scale)))
        return out


class ClipGradByGlobalNorm(ClipGradBase):
    def __init__(self, clip_norm, group_name="default_group"):
        self.clip_norm = float(clip_norm)

    def __call__(self, params_grads):
        grads = [g._data for _, g in params_grads if g is not None]
        if not grads:
            return params_grads
        global_sq = sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
                        for g in grads)
        gnorm = jnp.sqrt(global_sq)
        scale = jnp.minimum(self.clip_norm / jnp.maximum(gnorm, 1e-12), 1.0)
        out = []
        for p, g in params_grads:
            if g is None or (hasattr(p, "need_clip") and not p.need_clip):
                out.append((p, g))
            else:
                out.append((p, Tensor((g._data * scale).astype(g.dtype))))
        return out


def clip_grad_norm_(parameters, max_norm, norm_type=2.0,
                    error_if_nonfinite=False):
    params = [parameters] if isinstance(parameters, Tensor) else \
        list(parameters)
    grads = [p._grad for p in params if p._grad is not None]
    if not grads:
        return Tensor(jnp.asarray(0.0))
    if norm_type == float("inf"):
        total = jnp.max(jnp.stack([jnp.max(jnp.abs(g)) for g in grads]))
    else:
        total = jnp.power(
            sum(jnp.sum(jnp.power(jnp.abs(g.astype(jnp.float32)),
                                  norm_type)) for g in grads),
            1.0 / norm_type)
    scale = jnp.minimum(max_norm / jnp.maximum(total, 1e-12), 1.0)
    for p in params:
        if p._grad is not None:
            p._grad = (p._grad * scale).astype(p._grad.dtype)
    return Tensor(total)


def clip_grad_value_(parameters, clip_value):
    params = [parameters] if isinstance(parameters, Tensor) else \
        list(parameters)
    for p in params:
        if p._grad is not None:
            p._grad = jnp.clip(p._grad, -clip_value, clip_value)
