"""nn.weight_norm_hook (reference python/paddle/nn/weight_norm_hook.py):
the weight-norm reparameterization hooks live in nn/utils.py here."""
from .utils import weight_norm, remove_weight_norm  # noqa: F401

__all__ = ["weight_norm", "remove_weight_norm"]
