"""nn.utils: weight_norm / spectral_norm wrappers
(python/paddle/nn/utils/ parity)."""
from __future__ import annotations

import jax.numpy as jnp

from ..framework import Parameter, Tensor
from .layer.layers import Layer

__all__ = ["weight_norm", "remove_weight_norm", "spectral_norm"]


def _norm_except(w, dim):
    axes = tuple(i for i in range(w.ndim) if i != dim)
    return jnp.sqrt(jnp.sum(jnp.square(w), axis=axes, keepdims=True))


def weight_norm(layer: Layer, name="weight", dim=0):
    """Reparameterize layer.<name> as g * v/||v|| via a forward-pre-hook."""
    w = getattr(layer, name)
    dim = dim if dim is not None else 0
    g = Parameter(_norm_except(w._data, dim).reshape(-1))
    v = Parameter(w._data)
    layer.add_parameter(name + "_g", g)
    layer.add_parameter(name + "_v", v)
    del layer._parameters[name]

    def hook(lyr, inputs):
        from ..ops.registry import run_op
        gv, vv = lyr._parameters[name + "_g"], lyr._parameters[name + "_v"]

        def impl(g_, v_):
            norm = _norm_except(v_, dim)
            shape = [1] * v_.ndim
            shape[dim] = -1
            return v_ / norm * g_.reshape(shape)
        w_eff = run_op("weight_norm", impl, (gv, vv), {})
        lyr._buffers[name] = w_eff  # found by __getattr__ during forward
        return None

    h = layer.register_forward_pre_hook(hook)
    layer.__dict__["_weight_norm_hook"] = h
    # materialize once so the attribute exists pre-forward
    hook(layer, ())
    return layer


def remove_weight_norm(layer: Layer, name="weight"):
    h = layer.__dict__.pop("_weight_norm_hook", None)
    if h is not None:
        h.remove()
    w_eff = layer._buffers.pop(name, None)
    layer._parameters.pop(name + "_g", None)
    layer._parameters.pop(name + "_v", None)
    if w_eff is not None:
        layer._parameters[name] = Parameter(w_eff._data)
    return layer


def spectral_norm(layer: Layer, name="weight", n_power_iterations=1,
                  eps=1e-12, dim=None):
    from .layer.norm import SpectralNorm
    w = getattr(layer, name)
    dim = dim if dim is not None else 0
    sn = SpectralNorm(list(w._data.shape), dim=dim,
                      power_iters=n_power_iterations, eps=eps)
    layer.add_sublayer(name + "_sn", sn)
    orig = Parameter(w._data)
    layer.add_parameter(name + "_orig", orig)
    del layer._parameters[name]

    def hook(lyr, inputs):
        w_eff = lyr._sub_layers[name + "_sn"](
            lyr._parameters[name + "_orig"])
        lyr._buffers[name] = w_eff
        return None

    layer.register_forward_pre_hook(hook)
    hook(layer, ())
    return layer
