"""nn.extension (reference python/paddle/nn/extension row)."""
from .functional.extension import diag_embed  # noqa: F401

__all__ = ["diag_embed"]
